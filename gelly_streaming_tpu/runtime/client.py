"""``gelly-client``: the remote side of the streaming RPC serving plane.

``GellyClient`` is the programmatic API (one socket, synchronous
request/reply frames — runtime/protocol.py); ``main`` is the console
script: submit / status / push-edges / results / drain / cancel against a
``gelly-serve --listen`` server.

Edges cross the socket in the framework's own wire encodings: the client
packs micro-batches with io/wire.py (fixed-width, or BDV delta/varint at
~2.7 B/edge when the server's submit reply advertises ``accept_bdv``), so
the link cost is the PR-6 compressed format, not 8-byte id pairs.
Emission records come back as their flattened array leaves (one ``.npz``
payload per ``results`` reply) — bit-identical to what an in-process
sink's ``jax.tree.leaves`` would see, which is exactly what the
equivalence tests compare.
"""

from __future__ import annotations

import argparse
import io as _io
import socket
import sys
import time
from typing import Iterator, List, Optional, Tuple

import numpy as np

from gelly_streaming_tpu.runtime import protocol


class ClientError(RuntimeError):
    """Transport-level failure (connection closed, bad frame)."""


class ServerRefused(RuntimeError):
    """The server answered with ``ok: false``; carries the typed code
    plus the full reply header as ``details`` — resync fields like the
    ``out-of-sync`` refusal's ``expected`` cursor and the ``rerouted``
    refusal's ``backend`` live there."""

    def __init__(self, code: str, message: str, details: Optional[dict] = None):
        super().__init__(message)
        self.code = code
        self.details = details or {}


class GellyClient:
    """One connection to a StreamServer.  Thread-compatible, not
    thread-safe: use one client per pushing thread (that is also what
    keeps per-connection backpressure per-client)."""

    def __init__(
        self,
        host: str,
        port: int,
        token: str = "",
        timeout: Optional[float] = 120.0,
    ):
        self.token = token
        self._host = host
        self._port = port
        self._timeout = timeout
        self._sock = socket.create_connection((host, port), timeout=timeout)
        try:
            # request/reply framing: Nagle + delayed ACK would add ~40 ms
            # to every small frame round trip
            self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass
        self._f = self._sock.makefile("rwb")

    def close(self) -> None:
        try:
            self._f.close()
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass

    def reconnect(self) -> None:
        """Drop the (possibly dead) socket and dial the same address
        again.  Behind a ``gelly-router`` this re-resolves placement: the
        router places every frame per-request, so after a failover the
        same address reaches the standby that took the jobs over."""
        self.close()
        self._sock = socket.create_connection(
            (self._host, self._port), timeout=self._timeout
        )
        try:
            self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass
        self._f = self._sock.makefile("rwb")

    def __enter__(self) -> "GellyClient":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- frame plumbing ------------------------------------------------------

    def call_raw(
        self, header: dict, payload: bytes = b""
    ) -> Tuple[dict, bytes]:
        """One request/reply round trip; raises ``ClientError`` on
        transport failure, returns the reply even when ``ok`` is false."""
        header = dict(header)
        header.setdefault("token", self.token)
        try:
            protocol.write_frame(self._f, header, payload)
            reply = protocol.read_frame(self._f)
        except (OSError, protocol.ProtocolError) as e:
            raise ClientError(f"transport failure: {e}") from e
        if reply is None:
            raise ClientError("server closed the connection")
        return reply

    def call(self, header: dict, payload: bytes = b"") -> Tuple[dict, bytes]:
        """``call_raw`` + refusal handling: ``ok: false`` raises
        ``ServerRefused(code)``."""
        head, pay = self.call_raw(header, payload)
        if not head.get("ok"):
            raise ServerRefused(
                head.get("code", "error"),
                head.get("error", "refused"),
                details=head,
            )
        return head, pay

    # -- verbs ---------------------------------------------------------------

    def ping(self) -> dict:
        return self.call({"verb": "ping"})[0]

    def submit(self, **spec) -> dict:
        """Submit a job spec; returns the reply (``resume_edges`` is the
        cursor to push from for checkpointed jobs)."""
        return self.call({"verb": "submit", "spec": spec})[0]

    def push_wire(self, job: str, buf, kind: str = "wire") -> dict:
        return self.call(
            {"verb": "push", "job": job, "kind": kind},
            np.ascontiguousarray(buf, np.uint8).tobytes(),
        )[0]

    def push_tail(
        self, job: str, src, dst, offset: Optional[int] = None
    ) -> dict:
        src = np.ascontiguousarray(src, "<i4")
        dst = np.ascontiguousarray(dst, "<i4")
        header = {"verb": "push", "job": job, "kind": "tail", "count": len(src)}
        if offset is not None:
            header["offset"] = int(offset)
        return self.call(header, src.tobytes() + dst.tobytes())[0]

    def eos(self, job: str) -> dict:
        return self.call({"verb": "eos", "job": job})[0]

    def push_edges(
        self,
        job: str,
        src,
        dst,
        batch: int,
        capacity: int,
        bdv: bool = False,
        start: int = 0,
        close: bool = True,
        window: int = 32,
        position: Optional[int] = None,
        declare_position: bool = True,
    ) -> int:
        """Pack ``src/dst[start:]`` into full wire batches (+ raw tail) and
        push them, optionally closing the stream.  Returns edges pushed.

        ``start`` is the resume cursor from ``submit`` — on reconnect the
        client ships only the suffix the server's checkpoint doesn't cover.

        ``position`` is the GLOBAL stream offset of ``src[start]`` when it
        differs from ``start`` itself — the incremental pattern, where each
        call pushes a fresh chunk (``start=0``) of a stream whose earlier
        edges went in previous calls: pass the count pushed so far.
        ``declare_position=False`` drops the offset stamps entirely (the
        server's legacy unchecked behavior) for callers that cannot know
        their position.

        Push frames are PIPELINED: up to ``window`` frames are written
        before their replies are read (replies come back in order — the
        server handles one connection's frames sequentially), so the
        socket round trip is paid once per window, not once per batch,
        while the bounded reply window still surfaces refusals promptly
        and keeps the server's per-connection backpressure effective.

        Every frame is stamped with its global edge ``offset`` (``start +
        batches pushed so far``), which the server verifies against the
        source's exact positional accounting — so a frame still in flight
        when a live rescale/drain swaps the job's source is refused
        ``out-of-sync`` instead of silently landing past the new resume
        cursor.  On a ``quiesced``/``out-of-sync`` refusal, re-push from
        the advertised cursor.
        """
        from gelly_streaming_tpu.io import wire as wire_mod

        src = np.ascontiguousarray(src, np.int32)[start:]
        dst = np.ascontiguousarray(dst, np.int32)[start:]
        base = int(position) if position is not None else start
        width = wire_mod.width_for_capacity(capacity)
        n_full = len(src) // batch
        outstanding = 0
        # a refusal mid-pipeline must not desync the connection: every
        # outstanding reply is still read (in order) before the first
        # refusal is raised, so the next verb on this socket reads ITS
        # reply, not a stale push ack
        refusal: Optional[ServerRefused] = None

        def read_reply():
            nonlocal refusal
            reply = protocol.read_frame(self._f)
            if reply is None:
                raise ClientError("server closed the connection")
            head, _pay = reply
            if not head.get("ok") and refusal is None:
                refusal = ServerRefused(
                    head.get("code", "error"),
                    head.get("error", "refused"),
                    details=head,
                )

        try:
            for i in range(n_full):
                s_b = src[i * batch : (i + 1) * batch]
                d_b = dst[i * batch : (i + 1) * batch]
                if bdv:
                    head = {"verb": "push", "job": job, "kind": "bdv"}
                    buf = wire_mod.pack_edges_bdv(s_b, d_b, capacity)
                else:
                    head = {"verb": "push", "job": job, "kind": "wire"}
                    buf = wire_mod.pack_edges(s_b, d_b, width)
                if declare_position:
                    head["offset"] = base + i * batch
                head["token"] = self.token
                protocol.write_frame(self._f, head, np.ascontiguousarray(buf))
                outstanding += 1
                if outstanding >= max(1, window):
                    read_reply()
                    outstanding -= 1
                if refusal is not None:
                    break  # stop producing; drain what's in flight below
            while outstanding:
                read_reply()
                outstanding -= 1
        except (OSError, protocol.ProtocolError) as e:
            raise ClientError(f"transport failure: {e}") from e
        if refusal is not None:
            raise refusal
        if len(src) % batch:
            self.push_tail(
                job,
                src[n_full * batch :],
                dst[n_full * batch :],
                offset=base + n_full * batch if declare_position else None,
            )
        if close:
            self.eos(job)
        return len(src)

    # refusal codes that mean "the stream will come back: retry through
    # the same address" — rerouted (fleet failover in progress), quiesced
    # (live rescale/drain swapping the source), unavailable
    _RETRY_CODES = frozenset({"rerouted", "quiesced", "unavailable"})

    def push_edges_resilient(
        self,
        job: str,
        src,
        dst,
        batch: int,
        capacity: int,
        bdv: bool = False,
        start: int = 0,
        close: bool = True,
        window: int = 32,
        deadline_s: float = 120.0,
        backoff_s: float = 0.2,
    ) -> int:
        """``push_edges`` with automatic reconnect-with-resync: survives
        connection loss and typed ``rerouted`` refusals (fleet failover
        behind a ``gelly-router``) by re-dialing the same address and
        re-declaring the push position.

        The resync protocol NEVER silently re-pushes acked edges.  The
        client's cursor only moves when the server tells it to: every
        frame is offset-stamped, so a frame the server already counted is
        REFUSED ``out-of-sync`` with the advertised ``expected`` cursor
        (never folded twice), and the cursor jumps there.  The one case
        where edges are re-sent is ``expected`` BELOW the cursor — a
        failover landed the job on a standby whose checkpoint trails the
        acked stream — and that overlap is server-directed: exactly the
        suffix past the resume cursor, the same at-least-once/overlap-
        only contract every restart path in the repo pins.

        Raises the refusal unchanged for non-retryable codes (auth,
        unknown-job, bad-spec: retrying cannot fix those) and
        ``ClientError`` when ``deadline_s`` expires first.
        """
        total = len(src)
        pos = int(start)
        deadline = time.monotonic() + deadline_s
        last_err: Optional[Exception] = None

        def _wait(transport: bool) -> None:
            if time.monotonic() > deadline:
                raise ClientError(
                    f"resilient push of {job!r} did not finish within "
                    f"{deadline_s}s (cursor {pos}/{total}): {last_err}"
                ) from last_err
            time.sleep(backoff_s)
            if transport:
                try:
                    self.reconnect()
                except OSError as e:  # router itself briefly down
                    nonlocal_err(e)

        def nonlocal_err(e: Exception) -> None:
            nonlocal last_err
            last_err = e

        while pos < total:
            try:
                self.push_edges(
                    job,
                    src,
                    dst,
                    batch=batch,
                    capacity=capacity,
                    bdv=bdv,
                    start=pos,
                    close=False,
                    window=window,
                )
                pos = total
            except ClientError as e:
                # connection loss mid-window: frames past the last ack
                # may or may not have landed.  Reconnect and retry from
                # the stale cursor — counted frames are refused
                # out-of-sync (not folded) and the refusal's expected
                # cursor moves us forward.
                nonlocal_err(e)
                _wait(transport=True)
            except ServerRefused as e:
                expected = e.details.get("expected")
                if e.code == "out-of-sync" and isinstance(expected, int):
                    # the server's cursor IS the resync point — jump
                    # there immediately, no backoff (this is the common
                    # post-reconnect/post-failover step, not an error)
                    moved = min(max(expected, 0), total)
                    nonlocal_err(e)
                    if moved == pos:
                        # no progress: something upstream is still
                        # settling (e.g. a resume filler in flight) —
                        # don't spin on refusals
                        _wait(transport=False)
                    pos = moved
                elif e.code in self._RETRY_CODES:
                    nonlocal_err(e)
                    _wait(transport=False)
                else:
                    raise
        if close:
            while True:
                try:
                    self.eos(job)
                    break
                except ClientError as e:
                    nonlocal_err(e)
                    _wait(transport=True)
                except ServerRefused as e:
                    if e.code not in self._RETRY_CODES:
                        raise
                    nonlocal_err(e)
                    _wait(transport=False)
        return total - int(start)

    def results(
        self, job: str, max_records: int = 256, timeout_ms: int = 1000
    ) -> Tuple[List[List[np.ndarray]], str, bool]:
        """Fetch buffered emission records: (records, job state, eos).
        Each record is the list of its flattened host array leaves."""
        head, payload = self.call(
            {
                "verb": "results",
                "job": job,
                "max": max_records,
                "timeout_ms": timeout_ms,
            }
        )
        records: List[List[np.ndarray]] = []
        if head["count"]:
            # raw leaf framing: dtype/shape metadata in the header, the
            # payload is the leaves' bytes concatenated in order (the
            # server's _h_results twin — same leaves the npz container
            # used to carry, without the per-record zipfile cost)
            off = 0
            for meta in head["leafmeta"]:
                leaves = []
                for dtype_str, shape in meta:
                    dt = np.dtype(dtype_str)
                    count = int(np.prod(shape, dtype=np.int64)) if shape else 1
                    nb = dt.itemsize * count
                    arr = np.frombuffer(
                        payload, dt, count=count, offset=off
                    ).reshape(shape)
                    leaves.append(arr)
                    off += nb
                records.append(leaves)
        return records, head["state"], bool(head["eos"])

    def iter_results(
        self, job: str, poll_timeout_ms: int = 1000, deadline_s: float = 300.0
    ) -> Iterator[List[np.ndarray]]:
        """Yield records until end-of-stream (or ``deadline_s``, then
        ``ClientError`` — a remote hang must fail loudly, not forever)."""
        deadline = time.monotonic() + deadline_s
        while True:
            records, state, eos = self.results(
                job, timeout_ms=poll_timeout_ms
            )
            for rec in records:
                yield rec
            if eos:
                return
            if time.monotonic() > deadline:
                raise ClientError(
                    f"job {job!r} produced no end-of-stream within "
                    f"{deadline_s}s (state {state})"
                )

    def status(self) -> dict:
        return self.call({"verb": "status"})[0]

    def metrics(self) -> dict:
        """The full observability registry (tenant-scoped job/tenant rows
        + process-plane counters + histograms + span stage aggregates)."""
        return self.call({"verb": "metrics"})[0]["metrics"]

    def metrics_prometheus(self) -> str:
        """The same registry rendered in the Prometheus text exposition
        format (ships as the frame payload)."""
        _head, payload = self.call(
            {"verb": "metrics", "format": "prometheus"}
        )
        return payload.decode("utf-8")

    def health(self) -> dict:
        """The health plane's keep-up verdicts: per-job gauges (watermark
        lag, backlog depth/age, arrival/drain EWMA rates, keep-up ratio,
        time-to-queue-full), visible alert rows, configured SLO specs, and
        the monitor's liveness stats."""
        return self.call({"verb": "health"})[0]["health"]

    def alerts(self) -> list:
        """Just the visible SLO alert rows (state, burn rates, since)."""
        return self.call({"verb": "alerts"})[0]["alerts"]

    def events(self, n: int = 64, kind: "Optional[str]" = None) -> list:
        """Tail the structured event journal (job transitions, admission
        rejections, drain/restart cursors, alert firings/clears)."""
        header: dict = {"verb": "events", "n": n}
        if kind is not None:
            header["kind"] = kind
        return self.call(header)[0]["events"]

    def trace(self, n: int = 32) -> dict:
        """The flight recorder's last ``n`` window spans plus the span
        stage aggregates: ``{"spans": [...], "tracing_active": bool,
        "stats": {...}}``.  Empty spans until some run enables
        ``trace_sample`` / ``GELLY_TRACE_SAMPLE``."""
        return self.call({"verb": "trace", "n": n})[0]

    def pause(self, job: str) -> dict:
        return self.call({"verb": "pause", "job": job})[0]

    def resume(self, job: str) -> dict:
        return self.call({"verb": "resume", "job": job})[0]

    def cancel(self, job: str) -> dict:
        return self.call({"verb": "cancel", "job": job})[0]

    def drain(
        self, jobs: Optional[List[str]] = None, shutdown: bool = False
    ) -> dict:
        """Graceful drain; the reply's ``cursors`` map job -> resume
        cursor (``resume_edges``) for checkpointed push jobs."""
        header = {"verb": "drain", "shutdown": bool(shutdown)}
        if jobs is not None:
            header["jobs"] = list(jobs)
        return self.call(header)[0]

    def shutdown_server(self) -> dict:
        return self.call({"verb": "shutdown"})[0]


# ---------------------------------------------------------------------------
# console script
# ---------------------------------------------------------------------------


def _parse_addr(addr: str) -> Tuple[str, int]:
    host, _, port = addr.rpartition(":")
    if not host or not port.isdigit():
        raise SystemExit(f"--connect needs host:port, got {addr!r}")
    return host, int(port)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="gelly-client",
        description="remote console for a gelly-serve --listen server",
    )
    parser.add_argument(
        "--connect", required=True, help="server address, host:port"
    )
    parser.add_argument("--token", default="", help="tenant auth token")
    sub = parser.add_subparsers(dest="cmd", required=True)

    sub.add_parser("status", help="server + per-job status lines")

    p_submit = sub.add_parser("submit", help="submit a push-source job")
    p_submit.add_argument("--name", required=True)
    p_submit.add_argument(
        "--query", default="cc", choices=("cc", "degree", "edges")
    )
    p_submit.add_argument(
        "--summary",
        default=None,
        choices=("sketch_triangles", "hll_degree", "cm_heavy_hitters"),
        help="swap the job's summary for a fixed-tiny-state sketch "
        "(overrides --query; see --eps/--delta)",
    )
    p_submit.add_argument(
        "--eps",
        type=float,
        default=None,
        help="sketch relative-error target (sketch summaries only)",
    )
    p_submit.add_argument(
        "--delta",
        type=float,
        default=None,
        help="sketch failure probability of the eps bound",
    )
    p_submit.add_argument("--capacity", type=int, default=1 << 16)
    p_submit.add_argument("--window-edges", type=int, default=1 << 13)
    p_submit.add_argument("--batch", type=int, default=1 << 12)
    p_submit.add_argument("--weight", type=int, default=1)
    p_submit.add_argument("--checkpoint", action="store_true")

    p_push = sub.add_parser(
        "push-edges",
        help="push a seeded synthetic edge stream into a submitted job "
        "(geometry flags must match the submit)",
    )
    p_push.add_argument("--job", required=True)
    p_push.add_argument("--edges", type=int, default=100_000)
    p_push.add_argument("--seed", type=int, default=0)
    p_push.add_argument("--capacity", type=int, default=1 << 16)
    p_push.add_argument("--batch", type=int, default=1 << 12)
    p_push.add_argument("--bdv", action="store_true")
    p_push.add_argument(
        "--start", type=int, default=0, help="resume cursor (edges to skip)"
    )
    p_push.add_argument(
        "--no-results",
        action="store_true",
        help="push + eos only; don't consume emissions",
    )

    p_results = sub.add_parser("results", help="stream a job's emissions")
    p_results.add_argument("--job", required=True)

    p_metrics = sub.add_parser(
        "metrics",
        help="dump the server's observability registry (counters, "
        "histogram quantiles, span stage aggregates)",
    )
    p_metrics.add_argument(
        "--prometheus",
        action="store_true",
        help="print the Prometheus text exposition format instead of JSON",
    )

    p_trace = sub.add_parser(
        "trace", help="dump the flight recorder's last N window spans"
    )
    p_trace.add_argument("--last", type=int, default=32)

    sub.add_parser(
        "health",
        help="per-job keep-up gauges (lag, backlog age, keep-up ratio) "
        "and SLO alert states",
    )

    p_events = sub.add_parser(
        "events",
        help="tail the structured event journal (lifecycle transitions, "
        "admission rejections, cursors, alert firings/clears)",
    )
    p_events.add_argument("--last", type=int, default=64)
    p_events.add_argument("--kind", default=None)

    p_cancel = sub.add_parser("cancel", help="cancel a job")
    p_cancel.add_argument("--job", required=True)

    p_drain = sub.add_parser(
        "drain", help="drain this tenant's jobs; print resume cursors"
    )
    p_drain.add_argument("--shutdown", action="store_true")

    args = parser.parse_args(argv)
    host, port = _parse_addr(args.connect)
    with GellyClient(host, port, token=args.token) as client:
        try:
            return _run_cmd(client, args)
        except ServerRefused as e:
            print(f"refused [{e.code}]: {e}", file=sys.stderr)
            return 2


def _run_cmd(client: GellyClient, args) -> int:
    if args.cmd == "status":
        reply = client.status()
        for line in reply["lines"]:
            print(line)
        srv = reply["server"]
        print(
            f"server: {srv['connections']} connection(s), "
            f"{srv['served_jobs']} served job(s)"
        )
        return 0
    if args.cmd == "submit":
        spec = dict(
            name=args.name,
            query=args.query,
            capacity=args.capacity,
            window_edges=args.window_edges,
            batch=args.batch,
            weight=args.weight,
            checkpoint=args.checkpoint,
        )
        # sketch knobs travel only when given: the server validates them
        # at admission and refuses loudly on a bad contract
        if args.summary is not None:
            spec["summary"] = args.summary
        if args.eps is not None:
            spec["eps"] = args.eps
        if args.delta is not None:
            spec["delta"] = args.delta
        reply = client.submit(**spec)
        line = (
            f"submitted {reply['job']}: batch={reply['batch']} "
            f"window={reply['window_edges']} resume_edges="
            f"{reply['resume_edges']} accept_bdv={reply['accept_bdv']}"
        )
        contract = reply.get("error_contract")
        if contract:
            line += (
                f" sketch={contract['kind']} eps={contract['eps']} "
                f"delta={contract['delta']}"
            )
        print(line)
        return 0
    if args.cmd == "push-edges":
        rng = np.random.default_rng(args.seed)
        src = rng.integers(0, args.capacity, args.edges).astype(np.int32)
        dst = rng.integers(0, args.capacity, args.edges).astype(np.int32)
        t0 = time.perf_counter()
        pushed = client.push_edges(
            args.job,
            src,
            dst,
            batch=args.batch,
            capacity=args.capacity,
            bdv=args.bdv,
            start=args.start,
        )
        dt = time.perf_counter() - t0
        print(
            f"pushed {pushed} edges in {dt:.2f}s "
            f"({pushed / max(dt, 1e-9):.0f} eps over the socket)"
        )
        if not args.no_results:
            n = 0
            for _rec in client.iter_results(args.job):
                n += 1
            print(f"{n} record(s), end of stream")
        return 0
    if args.cmd == "results":
        n = 0
        for rec in client.iter_results(args.job):
            n += 1
            shapes = ", ".join(str(leaf.shape) for leaf in rec)
            print(f"record {n}: {len(rec)} leaves [{shapes}]")
        print(f"{n} record(s), end of stream")
        return 0
    if args.cmd == "metrics":
        if args.prometheus:
            sys.stdout.write(client.metrics_prometheus())
            return 0
        import json as _json

        print(_json.dumps(client.metrics(), indent=2, sort_keys=True))
        return 0
    if args.cmd == "trace":
        reply = client.trace(args.last)
        if not reply["tracing_active"]:
            print(
                "tracing is off (enable with trace_sample / "
                "GELLY_TRACE_SAMPLE on the server)"
            )
        for span in reply["spans"]:
            stages = " ".join(
                f"{s['stage']}={s['ms']:.2f}" for s in span["stages"]
            )
            print(
                f"#{span['trace_id']} {span['plane']} w={span['window']} "
                f"total={span['total_ms']:.2f}ms  {stages}"
            )
        return 0
    if args.cmd == "health":
        health = client.health()
        for job_id, row in sorted(health["jobs"].items()):
            gauges = " ".join(
                f"{k}={v}" for k, v in sorted(row.items())
            )
            print(f"{job_id}: {gauges}")
        for a in health["alerts"]:
            print(
                f"alert [{a['state']}] {a['scope']}:{a['id']} {a['slo']} "
                f"burn_fast={a['burn_fast']} burn_slow={a['burn_slow']}"
            )
        mon = health.get("monitor")
        print(
            f"monitor: {mon}" if mon else "monitor: off (no SLOs configured)"
        )
        return 0
    if args.cmd == "events":
        import json as _json

        for ev in client.events(args.last, kind=args.kind):
            print(_json.dumps(ev, sort_keys=True))
        return 0
    if args.cmd == "cancel":
        reply = client.cancel(args.job)
        print(f"cancel {args.job}: state={reply['state']}")
        return 0
    if args.cmd == "drain":
        reply = client.drain(shutdown=args.shutdown)
        for name, cur in sorted(reply["cursors"].items()):
            print(
                f"{name}: state={cur['state']} resume_edges="
                f"{cur['resume_edges']} pending={cur['records_pending']}"
            )
        return 0
    raise SystemExit(f"unknown command {args.cmd!r}")


if __name__ == "__main__":
    sys.exit(main())

"""JobManager: many concurrent streaming queries over one device pipeline.

The scheduling model is COOPERATIVE: one scheduler thread round-robins the
runnable jobs in weighted-fair rounds, pulling each job's record iterator
``weight * fair_quantum`` times per round.  One pull advances that job's
query by one emission — which, under the hood, dispatches its next
window(s) through the existing pack/transfer/dispatch/drain pipeline
(core/async_exec.py when the job's ``StreamConfig.async_windows`` > 0, the
synchronous loops otherwise).  Nothing about the per-query execution
changes: the same merge loops, the same checkpoints, and — decisively —
the same process-global ``compile_cache``, so N same-shape jobs share one
set of compiled executables and co-scheduling costs scheduling, not N
compilations (the GraphBLAST kernel-reuse observation applied to tenancy).

Isolation boundaries:

* **Admission** (``submit``): bounded concurrent jobs and bounded
  aggregate summary-state bytes.  Over-capacity submits raise
  ``AdmissionError`` immediately — never a hang.
* **Per-job bounded emission queues**: the scheduler only ever
  ``put_nowait``s; a job whose sink lags until its queue fills is simply
  skipped for the round (``job_queue_full_skips`` counts it) while other
  jobs keep dispatching.  A slow sink slows ITS job, nothing else.
* **Per-job checkpoints**: each job snapshots its own position/summary
  through the unchanged ``utils/checkpoint.py`` machinery, so jobs
  crash-resume independently.

Failure is per-job too: an exception from one job's iterator marks that
job FAILED (the cause lands on ``job.error``) and the round continues with
the rest.
"""

from __future__ import annotations

import itertools
import queue
import threading
import time
from typing import Callable, Dict, Iterator, List, Optional

# The sanctioned global lock order (pinned by graftcheck pass #7): the
# manager lock is the runtime's root, and the journal/metrics registries
# are LEAF locks — emitting or counting under the manager lock is the
# documented-safe direction, and nothing called under a leaf lock may
# re-enter the manager.
# lock-order: manager._lock < events._JOURNAL_LOCK < events.EventJournal._lock
# lock-order: manager._lock < metrics._JOB_LOCK
# lock-order: manager._lock < metrics._HEALTH_LOCK
# lock-order: manager._lock < metrics._ALERT_LOCK
# lock-order: manager._lock < metrics._SCALE_LOCK
# lock-order: manager._lock < metrics._HIST_LOCK

from gelly_streaming_tpu.core.config import RuntimeConfig
from gelly_streaming_tpu.core.windows import FoldRequest, stack_fold_rows
from gelly_streaming_tpu.runtime.job import (
    _SENTINEL,
    AdmissionError,
    Job,
    JobState,
)
from gelly_streaming_tpu.utils import events, metrics, tracing

# distinguishes "initiate a fresh pull" from "resume a parked FoldRequest
# with this fused partial" (which may legitimately be None — the solo
# fallback) in the scheduler's pull loop
_FRESH = object()


class _Quantum:
    """One job's in-flight weighted-fair round, parkable mid-pull.

    The fused-dispatch continuation: when a job's iterator yields a
    ``FoldRequest`` instead of a record, its quantum parks here — credits
    spent so far, the rolling dispatch clock, and the parked request — so
    the scheduler can collect same-key requests from OTHER jobs' quanta
    into one cohort before resuming each with its row of the mega-fold.
    Touched by the one scheduler thread only; lives for one round.
    """

    __slots__ = ("job", "credits", "pulled", "t_round", "t_prev", "request")

    def __init__(self, job: Job, credits: int, t_round: float):
        self.job = job
        self.credits = credits
        self.pulled = 0
        self.t_round = t_round
        # rolling dispatch clock (one perf_counter read per record, not
        # two: each record's dispatch_s spans from the previous read)
        self.t_prev = t_round
        self.request: Optional[FoldRequest] = None


class JobManager:
    """Submit / pause / resume / cancel / status over a shared scheduler.

    Use as a context manager in tests and drivers: ``__exit__`` cancels
    whatever is still live and joins the scheduler thread.
    """

    def __init__(self, cfg: Optional[RuntimeConfig] = None):
        self.cfg = cfg or RuntimeConfig()
        self._lock = threading.RLock()
        self._jobs: Dict[str, Job] = {}  # guarded-by: _lock
        self._admitted_bytes = 0  # guarded-by: _lock
        # state bytes held OUT of the open pool by in-flight rescale swaps
        # (begin_rescale moves a draining job's budget here, priced at the
        # NEW geometry; submit(reserved_bytes=...) consumes it) — counted
        # against max_state_bytes by every admission check, so a
        # concurrent tenant can never steal a swap's budget mid-drain
        self._reserved_bytes = 0  # guarded-by: _lock
        # job SLOTS held the same way: mid-swap the draining job reads
        # terminal, so without this a concurrent submit could fill
        # max_jobs during the drain and strand the resubmit
        self._reserved_jobs = 0  # guarded-by: _lock
        self._seq = itertools.count()
        self._stop = False  # guarded-by: _lock
        self._thread: Optional[threading.Thread] = None  # guarded-by: _lock
        # scheduler parks on this when no job can make progress; submits,
        # resumes, cancels, and consumer gets wake it
        self._wake = threading.Event()
        # health-plane sampling (ISSUE 10): the scheduler loop samples each
        # live job's keep-up gauges every health_sample_s seconds — all
        # state below is touched by the scheduler thread only
        self._health_every = float(self.cfg.health_sample_s or 0.0)
        self._next_health = 0.0  # single-thread: scheduler
        self._keepup: Dict[str, metrics.KeepUpTracker] = {}  # single-thread: scheduler
        # SLO burn-rate monitor (runtime/slo.py): started with the
        # scheduler when cfg.slos is non-empty, stopped at shutdown
        self._slo_monitor = None  # guarded-by: _lock
        # elastic control plane (runtime/autoscale.py): started with the
        # scheduler when cfg.autoscale / GELLY_AUTOSCALE resolves on
        self._autoscaler = None  # guarded-by: _lock

    # -- submission ----------------------------------------------------------

    def submit(
        self,
        build: Callable[[], Iterator[tuple]],
        *,
        name: Optional[str] = None,
        sink: Optional[Callable] = None,
        weight: int = 1,
        checkpoint_path: Optional[str] = None,
        state_bytes: int = 0,
        edges_per_record: int = 0,
        edges_hint: Optional[int] = None,
        ready: Optional[Callable[[], bool]] = None,
        progress: Optional[Callable[[], dict]] = None,
        reserved_bytes: Optional[int] = None,
    ) -> Job:
        """Admit a query whose ``build()`` returns a fresh records iterator
        (the ``OutputStream`` contract: ``iter(stream.aggregate(...))``).

        ``state_bytes`` is the job's summary-state footprint charged
        against ``RuntimeConfig.max_state_bytes`` (descriptors compute it
        via ``SummaryAggregation.state_nbytes``; ``submit_aggregation``
        fills it in).  Raises ``AdmissionError`` when either cap would be
        exceeded — the job is NOT enqueued.

        ``ready`` (externally-fed sources, e.g. the network ingest plane's
        ``NetworkEdgeSource.ready``): a thread-safe, non-blocking callable
        the scheduler consults before pulling; False skips the job for the
        round (counted as ``job_source_wait_skips``) so a starved source
        idles its own job, never the scheduler.  Producers should ``poke()``
        the manager after feeding the source.

        ``progress`` (optional, same thread-safety contract as ``ready``):
        a probe returning the source's progress dict (see
        ``NetworkEdgeSource.progress``) for the health plane's keep-up
        gauges; jobs without one still get sink-side gauges.

        ``reserved_bytes`` (None = a normal submit): this is a rescale
        RESUBMIT — consume that many bytes of an in-flight swap
        reservation (``begin_rescale``) plus the job slot it holds,
        instead of fresh budget.  Must not exceed the outstanding
        reservation.
        """
        state_bytes = int(state_bytes)
        swap_submit = reserved_bytes is not None
        reserved_bytes = int(reserved_bytes or 0)
        with self._lock:
            if self._stop:
                raise RuntimeError("JobManager is shut down")
            if reserved_bytes < 0 or reserved_bytes > self._reserved_bytes:
                raise ValueError(
                    f"reserved_bytes ({reserved_bytes}) exceeds the "
                    f"outstanding swap reservation ({self._reserved_bytes})"
                )
            if swap_submit and self._reserved_jobs < 1:
                raise ValueError(
                    "reserved_bytes passed without an outstanding rescale "
                    "slot (begin_rescale reserves one per swap)"
                )
            active = [
                j
                for j in self._jobs.values()
                if not j._state_in(*JobState.TERMINAL)
            ]
            # in-flight swaps hold their job slots; a swap's own resubmit
            # consumes (exactly) the slot its begin_rescale reserved
            slots_held = len(active) + self._reserved_jobs - (
                1 if swap_submit else 0
            )
            if slots_held >= self.cfg.max_jobs:
                self._reject(
                    name,
                    f"job cap reached: {len(active)} active + "
                    f"{self._reserved_jobs} rescaling jobs >= "
                    f"max_jobs={self.cfg.max_jobs}",
                )
            # swap reservations count as committed: the open pool is
            # admitted + reserved, and a rescale submit's own reservation
            # covers (exactly) that much of its price
            committed = self._admitted_bytes + self._reserved_bytes
            if (
                self.cfg.max_state_bytes
                and committed + state_bytes - reserved_bytes
                > self.cfg.max_state_bytes
            ):
                self._reject(
                    name,
                    f"state-byte cap reached: {self._admitted_bytes} admitted"
                    f" + {self._reserved_bytes} reserved"
                    f" + {state_bytes} requested > "
                    f"max_state_bytes={self.cfg.max_state_bytes}",
                )
            if checkpoint_path is not None and any(
                j.checkpoint_path == checkpoint_path
                for j in active
            ):
                # two live jobs interleaving saves into ONE snapshot file
                # would corrupt both resumes; derive per-job files from a
                # shared prefix with utils.checkpoint.per_job_file instead
                self._reject(
                    name,
                    f"checkpoint path {checkpoint_path!r} is already in use "
                    "by an active job (use checkpoint.per_job_file to key a "
                    "shared prefix per job)",
                )
            job_id = name or f"job-{next(self._seq)}"
            if job_id in self._jobs and not self._jobs[job_id]._state_in(
                *JobState.TERMINAL
            ):
                self._reject(job_id, f"job name {job_id!r} is already active")
            self._evict_old_terminal()
            job = Job(
                job_id,
                build,
                manager_lock=self._lock,
                sink=sink,
                weight=weight,
                checkpoint_path=checkpoint_path,
                state_bytes=state_bytes,
                edges_per_record=edges_per_record,
                edges_hint=edges_hint,
                queue_depth=self.cfg.job_queue_depth,
                ready=ready,
                progress=progress,
            )
            job._manager = self
            self._jobs[job_id] = job
            self._admitted_bytes += state_bytes
            self._reserved_bytes -= reserved_bytes
            if swap_submit:
                self._reserved_jobs -= 1
            # journal the submit BEFORE the scheduler can run the job: the
            # scheduler's PENDING->RUNNING transition must get a later seq
            # than job_submitted or replay's lifecycle chain breaks (the
            # journal lock is a leaf lock — emitting under the manager
            # lock is the documented-safe order)
            events.journal().emit(
                "job_submitted",
                job=job_id,
                weight=int(weight),
                state_bytes=state_bytes,
                checkpoint=bool(checkpoint_path),
            )
            self._ensure_scheduler()
        if sink is not None:
            self._start_sink_thread(job)
        self._wake.set()
        return job

    @staticmethod
    def _reject(name: Optional[str], msg: str) -> None:
        """Journal + raise one admission refusal (the journal records WHY
        a submit bounced, not just that a counter moved)."""
        events.journal().emit(
            "admission_reject", job=name or "?", reason=msg
        )
        raise AdmissionError(msg)

    def submit_aggregation(
        self,
        stream,
        descriptor,
        *,
        name: Optional[str] = None,
        sink: Optional[Callable] = None,
        weight: int = 1,
        checkpoint_path: Optional[str] = None,
        ready: Optional[Callable[[], bool]] = None,
    ) -> Job:
        """Submit ``descriptor.run(stream)`` as a job — the entry point that
        turns the aggregation runtime's loops into schedulable work.

        ``ready`` passes through to :meth:`submit` (the source-readiness
        gate); a shared gate also coordinates starts — submit N jobs with
        ``ready=event.is_set`` and flip the event once, and the cohort
        enters the scheduler in the same round with no submission-order
        head start (how the fairness bench isolates scheduling from
        submission stagger).

        State bytes come from ``descriptor.admission_nbytes(stream.cfg)``
        — the persistent summary PLUS the declared emission-time scratch
        (``emission_scratch``: a sketch's top-k heap, gathered register
        view, wedge strips).  Pricing the summary alone would let a
        thousand KB-state sketch jobs OOM on the unpriced residue;
        per-record edge accounting from the stream's ingestion-pane size
        when the source pins one (each emission covers one closed pane);
        the total-edge progress hint from ``stream.num_edges_hint()``.

        With fused dispatch resolved on (``cfg.fused_dispatch`` /
        GELLY_FUSED_DISPATCH) and the job on the plain windowed plane,
        the build produces the descriptor's cohort-member generator
        (``run_fused``) so this job's windows can stack into cross-tenant
        mega-folds; every other plane — and fused-off — keeps the exact
        ``descriptor.run`` path, which stays the equivalence oracle.
        """
        from gelly_streaming_tpu.core import aggregation

        cfg = stream.cfg
        state_bytes = descriptor.admission_nbytes(cfg)
        edges_per_record = cfg.ingest_window_edges or 0
        eligible = getattr(descriptor, "fused_eligible", None)
        if (
            aggregation.resolve_fused_dispatch(cfg)
            and eligible is not None
            and eligible(stream)
        ):
            build = lambda: descriptor.run_fused(
                stream, checkpoint_path=checkpoint_path
            )
        else:
            build = lambda: iter(
                descriptor.run(stream, checkpoint_path=checkpoint_path)
            )
        return self.submit(
            build,
            name=name,
            sink=sink,
            weight=weight,
            checkpoint_path=checkpoint_path,
            state_bytes=state_bytes,
            edges_per_record=edges_per_record,
            edges_hint=stream.num_edges_hint(),
            ready=ready,
        )

    # -- rescale budget swap (the elastic control plane, ISSUE 11) -----------

    def begin_rescale(self, job: Job, new_state_bytes: int) -> int:
        """Atomically move a live job's admitted budget into a swap
        reservation priced at its NEW geometry — step one of a live
        re-shard's re-pricing (runtime/autoscale.py).

        Under the ONE admission lock: the job's held bytes leave the
        admitted pool (its later terminal release returns nothing — the
        budget moved, it was not freed) and ``new_state_bytes`` enter the
        reservation, which every admission check counts as committed.  So
        across the whole drain -> resubmit window there is no instant
        where the old and new footprints are both charged (no 2x
        double-book) and no instant where a concurrent tenant can grab
        the freed budget (no steal).  Growth beyond the held bytes is
        admission-checked here; rejection raises ``AdmissionError`` and
        leaves the job exactly as it was.

        Returns the reserved byte count — pass it to
        ``submit(reserved_bytes=...)`` to consume, or to
        ``abort_rescale`` to return it to the pool if the swap dies.
        """
        new_state_bytes = int(new_state_bytes)
        if new_state_bytes < 0:
            raise ValueError("new_state_bytes must be >= 0")
        with self._lock:
            held = job.state_bytes
            grow = new_state_bytes - held
            if (
                self.cfg.max_state_bytes
                and grow > 0
                and self._admitted_bytes + self._reserved_bytes + grow
                > self.cfg.max_state_bytes
            ):
                self._reject(
                    job.job_id,
                    f"rescale re-pricing needs {grow} more state bytes: "
                    f"{self._admitted_bytes} admitted + "
                    f"{self._reserved_bytes} reserved + {grow} > "
                    f"max_state_bytes={self.cfg.max_state_bytes}",
                )
            self._admitted_bytes -= held
            job.state_bytes = 0  # its release now returns nothing
            self._reserved_bytes += new_state_bytes
            # the job SLOT is reserved too: the drain makes this job
            # terminal mid-swap, and a concurrent submit filling max_jobs
            # during it would strand the resubmit
            self._reserved_jobs += 1
        return new_state_bytes

    def abort_rescale(
        self,
        reserved_bytes: int,
        job: Optional[Job] = None,
        restore_state_bytes: int = 0,
    ) -> None:
        """Return an unconsumed swap reservation (bytes + job slot) to the
        open pool — the drain or resubmit failed and budget must not leak
        out of circulation.

        ``job``/``restore_state_bytes``: when the DRAIN itself failed (the
        cancel timed out and the job is still live), re-charge the job's
        original bytes out of the freed reservation — a running job whose
        ``state_bytes`` stayed zeroed would let admission stack a second
        full job on top of its live summary state.  A job that did reach a
        terminal state restores nothing (its budget is correctly free).
        """
        with self._lock:
            self._reserved_bytes = max(
                0, self._reserved_bytes - int(reserved_bytes)
            )
            self._reserved_jobs = max(0, self._reserved_jobs - 1)
            if (
                job is not None
                and restore_state_bytes
                and not job._state_in(*JobState.TERMINAL)
            ):
                job.state_bytes = int(restore_state_bytes)
                self._admitted_bytes += int(restore_state_bytes)
        self._wake.set()

    @property
    def autoscaler(self):
        """The elastic control plane's policy thread, or None when
        ``RuntimeConfig.autoscale`` / ``GELLY_AUTOSCALE`` left it off (or
        no job has started the scheduler yet).  The serving plane
        registers its rescale handles here."""
        with self._lock:
            return self._autoscaler

    # holds-lock: _lock
    def _evict_old_terminal(self) -> None:
        """Bound the terminal-job history to ``keep_terminal_jobs`` (oldest
        first; dict order is submission order).  Caller holds _lock.  The
        evicted jobs' per-job metrics rows are dropped too — the module
        totals keep their contribution, so a long-lived serving process's
        footprint is bounded while its aggregates stay exact."""
        with self._lock:
            terminal = [
                job_id
                for job_id, j in self._jobs.items()
                if j._state_in(*JobState.TERMINAL)
            ]
            excess = len(terminal) - self.cfg.keep_terminal_jobs
            for job_id in terminal[: max(0, excess)]:
                del self._jobs[job_id]
                metrics.drop_job_stats(job_id)

    # -- lifecycle commands --------------------------------------------------

    def pause(self, job: Job) -> bool:
        """Stop scheduling ``job`` after its in-progress pull completes.

        The iterator stays suspended in place and the job's checkpoint
        keeps its last saved position; ``resume`` continues exactly where
        pulling stopped, so pause/resume is emission-exact in process and
        checkpoint-exact across one (crash-resume replays from the
        snapshot, the merge loops' existing contract).

        Best-effort by design: the scheduler may finish or fail the job
        concurrently with this call, so an un-pausable state (DRAINING /
        terminal) returns False rather than racing the caller into an
        exception — the check and the transition are one atomic step under
        the manager lock.
        """
        with self._lock:
            if not job._state_in(JobState.PENDING, JobState.RUNNING):
                return False
            job._transition(JobState.PAUSED)
            return True

    def resume(self, job: Job) -> bool:
        """PAUSED -> RUNNING; False if the job is not paused (same
        best-effort contract as ``pause``)."""
        with self._lock:
            if not job._state_in(JobState.PAUSED):
                return False
            job._transition(JobState.RUNNING)
        self._wake.set()
        return True

    def cancel(
        self, job: Job, wait: bool = True, timeout: Optional[float] = 30.0
    ) -> bool:
        """Request cancellation; the SCHEDULER performs it (closing the
        job's iterator mid-``next()`` from another thread is illegal), so
        the cancel rides the same thread that owns the generator: close ->
        the merge loop's GeneratorExit drain recycles in-flight arenas ->
        CANCELLED, with already-queued emissions left deliverable (dropping
        them would gap the at-least-once emission contract).  With ``wait``
        (default) blocks until terminal; returns whether the job IS
        terminal on return."""
        with self._lock:
            if job._state_in(*JobState.TERMINAL):
                return True
            job._cancel_requested = True
        self._wake.set()
        if wait:
            return job.wait(timeout)
        return job._state_in(*JobState.TERMINAL)

    def status(self) -> dict:
        """Per-job status snapshot + module totals.

        ``jobs`` maps job id -> {state, weight, queue_depth, checkpoint,
        error, and the per-job counters from utils.metrics.job_stats:
        records, dispatches, edges, dispatch seconds, stall/skip counts,
        queue-depth high-water}.  ``totals`` preserves the module
        aggregates as sums (max for high-water marks).
        """
        with self._lock:
            jobs = dict(self._jobs)
            admitted = self._admitted_bytes
            reserved = self._reserved_bytes
            dumps = {
                job_id: job._trace_dump for job_id, job in jobs.items()
            }
            fused = {
                job_id: job._fused_windows for job_id, job in jobs.items()
            }
        out = {}
        for job_id, job in jobs.items():
            row = {
                "state": job.state,
                "weight": job.weight,
                "queue_depth": job.queue_depth,
                "fused_windows": fused[job_id],
                "state_bytes": job.state_bytes,
                "edges_hint": job.edges_hint,
                "checkpoint_path": job.checkpoint_path,
                "error": repr(job.error) if job.error is not None else None,
                **metrics.job_stats(job_id),
            }
            latency = metrics.job_latency_snapshot(job_id)
            if latency:
                row["latency_ms"] = latency
            health = metrics.job_health(job_id)
            if health:
                row["health"] = health
            scale = metrics.job_scale(job_id)
            if scale:
                row["scale"] = scale
            alerts = metrics.alerts_for("job", job_id)
            if alerts:
                row["alerts"] = alerts
            if dumps[job_id] is not None:
                # the FAILED post-mortem: the flight recorder's last spans
                # at the moment the job died (see _fail)
                row["trace"] = dumps[job_id]
            out[job_id] = row
        return {
            "jobs": out,
            "admitted_state_bytes": admitted,
            "reserved_state_bytes": reserved,
            "totals": metrics.job_totals(),
        }

    def poke(self) -> None:
        """Wake the scheduler to re-check job readiness — producers feeding
        an externally-driven source (``submit(ready=...)``) call this after
        queueing data so the next round starts now rather than at the
        parked loop's 50 ms re-check."""
        self._wake.set()

    def wait_all(self, timeout: Optional[float] = None) -> bool:
        """Block until every submitted job is terminal (True) or the
        timeout elapses (False)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            jobs = list(self._jobs.values())
        for job in jobs:
            left = None if deadline is None else deadline - time.monotonic()
            if left is not None and left <= 0:
                return False
            if not job.wait(left):
                return False
        return True

    def shutdown(self, cancel: bool = True, timeout: float = 60.0) -> None:
        """Stop the scheduler.  ``cancel`` (default) cancels live jobs
        first — their in-flight windows drain through the completion-queue
        path; ``cancel=False`` waits for them to finish instead."""
        with self._lock:
            jobs = list(self._jobs.values())
        if cancel:
            for job in jobs:
                self.cancel(job, wait=False)
        self.wait_all(timeout)
        with self._lock:
            self._stop = True
            thread = self._thread
            monitor = self._slo_monitor
            self._slo_monitor = None
            autoscaler = self._autoscaler
            self._autoscaler = None
        self._wake.set()
        if monitor is not None:
            monitor.stop()
        if autoscaler is not None:
            autoscaler.stop()
        if thread is not None:
            thread.join(timeout)

    def __enter__(self) -> "JobManager":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown(cancel=True)

    # -- scheduler internals -------------------------------------------------

    # holds-lock: _lock
    def _ensure_scheduler(self) -> None:
        """Start the scheduler thread on first submit; caller holds _lock.
        The SLO monitor (when objectives are configured) starts and stops
        with it — a manager that never runs a job never pays a thread."""
        with self._lock:
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._loop, name="gelly-job-scheduler", daemon=True
                )
                self._thread.start()
            if self.cfg.slos and self._slo_monitor is None:
                from gelly_streaming_tpu.runtime.slo import SLOMonitor

                self._slo_monitor = SLOMonitor(
                    self.cfg.slos, interval_s=self.cfg.slo_interval_s
                )
                self._slo_monitor.start()
            if self._autoscaler is None:
                from gelly_streaming_tpu.runtime.autoscale import (
                    Autoscaler,
                    resolve_autoscale,
                )

                if resolve_autoscale(self.cfg):
                    self._autoscaler = Autoscaler(self.cfg.autoscale_policy)
                    self._autoscaler.start()

    def _start_sink_thread(self, job: Job) -> None:
        """Per-job sink pump: drains the bounded queue into the sink on its
        own thread, so sink latency lands on this job alone."""

        def pump():  # single-thread: per-job sink pump
            while True:
                rec = job._out.get()
                if rec is _SENTINEL:
                    break
                t0 = time.perf_counter()
                try:
                    job.sink(rec)
                except BaseException as e:
                    self._fail(job, e)
                    break
                metrics.job_add(
                    job.job_id,
                    "job_sink_stall_s",
                    time.perf_counter() - t0,
                )
                self._wake.set()  # queue space freed: the job is runnable
            self._mark_drained(job)

        job._sink_thread = threading.Thread(
            target=pump, name=f"gelly-sink-{job.job_id}", daemon=True
        )
        job._sink_thread.start()

    def _mark_drained(self, job: Job) -> None:
        """DRAINING -> DONE once the job's sentinel was consumed (sink pump
        or ``results``); no-op for FAILED/CANCELLED drains."""
        with self._lock:
            if job._state_in(JobState.DRAINING):
                job._transition(JobState.DONE)
                self._release(job)

    # holds-lock: _lock
    def _release(self, job: Job) -> None:
        """Return a terminal job's admitted bytes and drop its source
        closure (which may capture the whole input dataset) so a retained
        terminal job costs bookkeeping, not data; caller holds _lock.
        The job's health gauge row goes too — a DONE job's last backlog
        reading must not keep an SLO alert burning (the metrics locks are
        leaf locks, safe under the manager lock)."""
        with self._lock:
            self._admitted_bytes -= job.state_bytes
            job.state_bytes = 0  # idempotent: released exactly once
            job._build = None
        metrics.drop_job_health(job.job_id)

    def _fail(self, job: Job, err: BaseException) -> None:
        """Mark FAILED from ANY thread (scheduler pull errors, sink pump
        errors).  Sentinel delivery is DEFERRED to the scheduler — only the
        scheduler thread ever puts into a job's queue, which is what makes
        its full()-check-then-put_nowait in ``_run_quantum`` race-free.

        The FAILED transition snapshots the flight recorder into the job
        (``status()`` surfaces it as ``trace``): the last N window spans
        at the moment of death are the post-mortem — where each recent
        window's time went — captured before later jobs overwrite the
        ring.  Empty when tracing never ran; the recorder's lock nests
        inside the manager lock here and never the other way around.
        """
        dump = (
            tracing.flight_recorder().last(32) if tracing.active() else []
        )
        with self._lock:
            if job._state_in(*JobState.TERMINAL):
                return
            job._error = err
            job._trace_dump = dump
            job._transition(JobState.FAILED)
            self._release(job)
            job._sentinel_pending = True
        self._wake.set()

    def _enqueue_sentinel(self, job: Job) -> None:  # single-thread: scheduler
        """Best-effort sentinel enqueue; a full queue defers it to the next
        scheduler round (``_sentinel_pending``) rather than blocking."""
        try:
            job._out.put_nowait(_SENTINEL)
            delivered = True
        except queue.Full:
            delivered = False
        with self._lock:
            job._sentinel_pending = not delivered
        if not delivered:
            self._wake.set()

    # The scheduler loop and everything below it runs on the ONE scheduler
    # thread; job lifecycle state is still read/written under the manager
    # lock because API threads mutate it concurrently.

    def _loop(self) -> None:  # single-thread: scheduler
        while True:
            with self._lock:
                if self._stop:
                    return
                jobs = list(self._jobs.values())
            progressed = False
            # quanta parked at a FoldRequest this round, awaiting a cohort
            pending: List[_Quantum] = []  # single-thread: scheduler
            for job in jobs:
                try:
                    progressed |= self._run_quantum(job, pending)
                except BaseException as e:  # defensive: never kill the loop
                    self._fail(job, e)
            # cross-tenant fused dispatch: every parked window collected
            # above now folds — same-key cohorts in ONE vmapped dispatch,
            # loners solo — and each quantum resumes its remaining credits
            # (which may park again, so cycles repeat until no job is
            # parked; per-round work stays bounded by the credit budget)
            progressed |= self._dispatch_cohorts(pending)
            if self._health_every:
                # the health plane's sampling point: BETWEEN rounds on the
                # one scheduler thread, reading host-side Python counters
                # only — the dispatch hot path above gains a clock check
                # per round and zero device syncs
                now = time.monotonic()
                if now >= self._next_health:
                    self._next_health = now + self._health_every
                    try:
                        self._sample_health(jobs, now)
                    except Exception:
                        # same invariant as the quantum loop: a malformed
                        # progress dict (user-supplied probe) must degrade
                        # a gauge sweep, never kill the ONE scheduler
                        pass
            if not progressed:
                # nothing runnable: park until a submit/resume/cancel or a
                # consumer freeing queue space wakes us (short cap so a
                # missed wake degrades to polling, never to a wedge)
                self._wake.wait(0.05)
                self._wake.clear()

    def _run_quantum(
        self, job: Job, collect: "List[_Quantum]"
    ) -> bool:  # single-thread: scheduler
        """One weighted-fair round for one job; True if it made progress.

        A job whose iterator parks at a ``FoldRequest`` lands its quantum
        in ``collect`` for the round's cohort dispatch (``_dispatch_cohorts``)
        instead of completing here — the quantum's credits carry over to
        the resume, so fairness accounting is identical either way (one
        emission = one credit, fused or solo).
        """
        with self._lock:
            cancel_now = job._cancel_requested and not job._state_in(
                *JobState.TERMINAL
            )
            sentinel_owed = job._sentinel_pending
            if not cancel_now:
                if job._state_in(JobState.PENDING):
                    job._transition(JobState.RUNNING)
                elif not job._state_in(JobState.RUNNING):
                    # PAUSED / DRAINING / terminal: only a deferred
                    # sentinel still needs delivering
                    if sentinel_owed:
                        self._enqueue_sentinel(job)
                    return False
        if cancel_now:
            self._cancel_now(job)
            return True
        ready = job._ready
        if ready is not None:
            # the network-source gate: a pull would block the ONE scheduler
            # thread on that job's producer, so an un-ready source skips the
            # round instead (cancel above still wins: a dead client's job
            # stays cancellable forever)
            try:
                if not ready():
                    metrics.job_add(job.job_id, "job_source_wait_skips", 1)
                    return False
            except BaseException as e:
                self._fail(job, e)
                return True
        t_round = time.perf_counter()
        q = _Quantum(job, job.weight * self.cfg.fair_quantum, t_round)
        return self._pull_loop(q, collect, _FRESH)

    def _pull_loop(
        self, q: "_Quantum", collect: "List[_Quantum]", send
    ) -> bool:  # single-thread: scheduler
        """Run (or resume) one quantum's pull loop.

        ``send`` is ``_FRESH`` to initiate new pulls, or the fused partial
        (possibly None — the solo-fallback signal) to resume a parked
        ``FoldRequest`` first.  The per-pull gates (RUNNING state, cancel,
        queue fullness, source readiness) apply only when INITIATING a
        fresh pull: a parked fold always resumes, because its window's
        device work happened in the cohort dispatch and dropping the
        resume would strand the emission.  The queue-full guarantee still
        holds — fullness was checked before the pull that parked, and
        this thread is the queue's sole producer.

        Clocking (profiled: two ``perf_counter`` reads per pull were the
        scheduler's second-hottest line behind the fold itself): ONE read
        per record, rolled through ``q.t_prev``, spans gate overhead into
        ``job_dispatch_s`` — nanoseconds against a device fold — and the
        round-level health/SLO clock stays in ``_loop``, once per round,
        never per pull.
        """
        job = q.job
        ready = job._ready
        # tag this thread with the job id for the duration of its pulls:
        # histograms recorded deep inside the merge loops / network source
        # (close-to-emission, push-to-fold) land in this job's rows too
        prev_scope = metrics.set_hist_job(job.job_id)
        try:
            while True:
                if send is _FRESH:
                    if q.pulled >= q.credits:
                        break
                    if not job._state_in(JobState.RUNNING):
                        break
                    if job._cancel_pending():
                        break
                    if job._out.full():
                        metrics.job_add(job.job_id, "job_queue_full_skips", 1)
                        break
                    if q.pulled and ready is not None and not ready():
                        # re-check between pulls: each pull drains a
                        # window's worth from the source, so readiness
                        # established for the FIRST pull says nothing
                        # about the rest of the quantum — a pull past the
                        # queued data would block the scheduler thread on
                        # that job's producer (the wedge the gate exists
                        # to prevent)
                        break
                    if job._it is None:
                        build = job._build
                        if build is None:
                            break  # raced a concurrent terminal transition
                        # lazy build: first schedule pays the query's setup
                        # (including any cold compile) on the scheduler
                        # thread — cooperative by design, amortized by the
                        # shared cache
                        job._it = iter(build())
                        q.t_prev = time.perf_counter()
                    try:
                        rec = next(job._it)
                    except StopIteration:
                        with self._lock:
                            job._transition(JobState.DRAINING)
                        self._enqueue_sentinel(job)
                        q.pulled += 1
                        break
                    except BaseException as e:
                        self._fail(job, e)
                        q.pulled += 1
                        break
                else:
                    partial, send = send, _FRESH
                    q.t_prev = time.perf_counter()
                    try:
                        rec = job._it.send(partial)
                    except StopIteration:
                        with self._lock:
                            job._transition(JobState.DRAINING)
                        self._enqueue_sentinel(job)
                        q.pulled += 1
                        break
                    except BaseException as e:
                        self._fail(job, e)
                        q.pulled += 1
                        break
                if type(rec) is FoldRequest:
                    # park: the window's fold is offered to this round's
                    # cohort; the quantum resumes from _dispatch_cohorts
                    q.request = rec
                    collect.append(q)
                    return bool(q.pulled)
                t_rec = time.perf_counter()
                metrics.job_add(job.job_id, "job_dispatch_s", t_rec - q.t_prev)
                q.t_prev = t_rec
                metrics.job_add(job.job_id, "job_dispatches", 1)
                metrics.job_add(job.job_id, "job_records", 1)
                if not job._first_emitted:
                    job._first_emitted = True
                    metrics.hist_record(
                        "submit_to_first_emission_ms",
                        (t_rec - job._submit_t) * 1e3,
                        job=job.job_id,
                    )
                if job.edges_per_record:
                    metrics.job_add(
                        job.job_id, "job_edges", job.edges_per_record
                    )
                # sole producer is this thread and fullness was checked
                # above, so put_nowait cannot raise
                job._out.put_nowait(rec)
                metrics.job_high_water(
                    job.job_id, "job_queue_depth_hwm", job._out.qsize()
                )
                q.pulled += 1
        finally:
            metrics.set_hist_job(prev_scope)
        if q.pulled:
            # scheduler queue wait: the gap from this job's previous
            # PRODUCTIVE quantum to this one's start — what a closed
            # window waits before the shared scheduler gets back to its
            # job.  Recorded only on productive quanta: unproductive
            # visits (full queue, unready source) neither advance the
            # anchor nor record, so consumer backpressure never
            # masquerades as ramping scheduler wait.
            if job._last_quantum_end is not None:
                metrics.hist_record(
                    "sched_queue_wait_ms",
                    (q.t_round - job._last_quantum_end) * 1e3,
                    job=job.job_id,
                )
            metrics.job_add(job.job_id, "job_sched_rounds", 1)
            job._last_quantum_end = time.perf_counter()
        return bool(q.pulled)

    def _dispatch_cohorts(
        self, pending: "List[_Quantum]"
    ) -> bool:  # single-thread: scheduler
        """Drain the round's parked quanta through cohort dispatch cycles.

        Each cycle groups parked ``FoldRequest``s by key — (descriptor
        cache token, frozen config, has-val, pow2 pane bucket) — so only
        windows that would compile and trace IDENTICALLY may share a
        dispatch; each cohort folds once and every member resumes with
        its own row.  Resumed quanta may park again at their next window,
        feeding the next cycle; total pulls per round stay bounded by the
        per-job credit budgets, so the cycles terminate.
        """
        progressed = False
        while pending:
            quanta, pending = pending, []
            cohorts: Dict[tuple, List[_Quantum]] = {}
            for q in quanta:
                cohorts.setdefault(q.request.key, []).append(q)
            for qs in cohorts.values():
                try:
                    partials = self._fused_fold(qs)
                except BaseException as e:
                    # a cohort-level dispatch failure fails its MEMBERS
                    # (their windows were in that dispatch), not the round
                    for q in qs:
                        self._fail(q.job, e)
                    continue
                for q, partial in zip(qs, partials):
                    q.request = None
                    if q.job._it is None:
                        continue  # raced a terminal transition mid-round
                    try:
                        progressed |= self._pull_loop(q, pending, partial)
                    except BaseException as e:
                        self._fail(q.job, e)
        return progressed

    def _fused_fold(self, qs: "List[_Quantum]"):  # single-thread: scheduler
        """One cohort's device work: N parked same-key windows stacked into
        the superbatch row layout and folded by ONE call to the shared
        vmapped executable.  Returns one per-row partial per member, in
        member order; a singleton cohort returns ``[None]`` — the member
        solo-folds in its own generator, keeping the oracle path exercised
        even under fused mode.

        The row axis is pow2-bucketed by ``stack_fold_rows``, so tenancy
        varying 1..16 jobs revisits at most log2 bucket shapes and the
        process-wide recompile guard stays at zero.  No host sync happens
        here: the fold and the compiled per-row drain both dispatch
        asynchronously and each member's partial stays a device pytree,
        materialized only where the plain plane would have synced anyway
        (transform at emission).
        """
        if len(qs) == 1:
            metrics.fused_add("fused_solo_fallbacks", 1)
            return [None]
        import jax
        import jax.numpy as jnp

        reqs = [q.request for q in qs]
        src, dst, val, msk, pad_rows = stack_fold_rows(reqs)
        t0 = time.perf_counter()
        states = reqs[0].fold(
            jnp.asarray(src),
            jnp.asarray(dst),
            None if val is None else jax.tree.map(jnp.asarray, val),
            jnp.asarray(msk),
        )
        # drain in ONE dispatch too: the compiled split slices the stacked
        # result into per-row partials (eager per-row a[i] slices cost one
        # device call per job — measured ~2x the fused fold itself at 16
        # rows — and would undo the amortization the mega-fold just bought)
        rows = len(qs) + pad_rows
        parts = reqs[0].split(rows)(states)
        # the one dispatch's wall time attributes evenly: each tenant row
        # cost ~1/N of the fused call, and the per-job histograms/benches
        # read job_dispatch_s exactly as they do for solo dispatch
        share = (time.perf_counter() - t0) / len(qs)
        metrics.fused_add("fused_dispatches", 1)
        metrics.fused_add("fused_jobs_total", len(qs))
        metrics.fused_add("fused_pad_rows_total", pad_rows)
        metrics.fused_high_water("fused_jobs_per_dispatch_hwm", len(qs))
        with self._lock:
            for q in qs:
                q.job._fused_windows += 1
        partials = []
        for i, q in enumerate(qs):
            metrics.job_add(q.job.job_id, "job_dispatch_s", share)
            partials.append(parts[i])
        return partials

    def _sample_health(self, jobs, now: float) -> None:  # single-thread: scheduler
        """One keep-up gauge sweep over the live jobs (ISSUE 10).

        For jobs with a ``progress`` probe (network-fed sources) the full
        vocabulary: watermark lag from the probe's positional accounting,
        backlog depth/age from its queue snapshot, EWMA arrival vs drain
        rates, the keep-up ratio, and a time-to-queue-full estimate.
        Other jobs get sink-side gauges (drain rate, emission-queue
        depth).  Terminal jobs lose their gauge rows — a DONE job's stale
        backlog must not keep an SLO alert burning.

        Each job's sample is individually fault-isolated (a malformed
        user-supplied probe dict degrades THAT job's gauges for the
        sweep, never the rest), and a probe that stops producing REPLACES
        the row with sink-side figures — no frozen backlog/lag values
        driving SLO verdicts after the source is gone.
        """
        for job in jobs:
            try:
                self._sample_job_health(job, now)
            except Exception:
                continue  # one bad probe must not abort the sweep
        # trackers for jobs evicted between sweeps (terminal + evicted
        # before a tick saw them) would otherwise accumulate forever in a
        # long-lived churny server
        live = {job.job_id for job in jobs}
        for job_id in [j for j in self._keepup if j not in live]:
            del self._keepup[job_id]

    def _sample_job_health(self, job: Job, now: float) -> None:  # single-thread: scheduler
        job_id = job.job_id
        if job._state_in(*JobState.TERMINAL):
            if self._keepup.pop(job_id, None) is not None:
                metrics.drop_job_health(job_id)
            return
        gauges = {"out_queue_depth": job._out.qsize()}
        prog = None
        probe = job._progress
        if probe is not None:
            try:
                prog = probe()
            except BaseException:
                prog = None  # a broken probe degrades, never fails a job
        tracker = self._keepup.get(job_id)
        if tracker is None:
            tracker = self._keepup[job_id] = metrics.KeepUpTracker()
        if prog is not None:
            arrival, drain = tracker.sample(
                now, prog["edges_in"], prog["edges_out"]
            )
            lag = max(
                0, prog["closable_windows"] - prog["delivered_windows"]
            )
            backlog_edges = prog["backlog_edges"]
            gauges.update(
                watermark_lag_windows=lag,
                backlog_batches=prog["backlog_batches"],
                backlog_edges=backlog_edges,
                backlog_age_s=round(prog["backlog_age_s"], 4),
                arrival_eps=round(arrival, 2),
                drain_eps=round(drain, 2),
                keepup_ratio=(
                    round(min(drain / arrival, 999.0), 4)
                    if arrival > 1e-9
                    else 1.0
                ),
            )
            net = arrival - drain
            headroom = prog["queue_capacity_edges"] - backlog_edges
            # -1 = not filling (the JSON/Prometheus-safe "infinity")
            gauges["time_to_queue_full_s"] = (
                round(max(headroom, 0) / net, 2) if net > 1e-9 else -1.0
            )
        else:
            # sink-side drain only: the job's attributed edge counter
            # is the best cumulative drain figure available
            edges = metrics.job_stats(job_id)["job_edges"]
            _arrival, drain = tracker.sample(now, edges, edges)
            gauges["drain_eps"] = round(drain, 2)
        metrics.job_health_set(job_id, gauges)

    def _cancel_now(self, job: Job) -> None:  # single-thread: scheduler
        """Perform a requested cancel on the scheduler thread.

        Closing the iterator propagates GeneratorExit into the merge loop,
        whose drain path waits on each in-flight fold and recycles its
        transfer arenas (``# arena-live-until: drain`` — see
        core/async_exec.py); then the job is marked CANCELLED and the
        sentinel appended.  Emissions already in the queue stay DELIVERABLE:
        they were emitted past their windows' checkpoint saves, so dropping
        them would turn a cancel + resubmit-from-checkpoint into an
        at-most-once gap (the runtime keeps the framework's state
        exactly-once / emission at-least-once contract).
        """
        it = job._it
        job._it = None
        if it is not None and hasattr(it, "close"):
            try:
                it.close()
            except BaseException as e:
                # a close-time error must not mask the cancel; record it
                with self._lock:
                    if job._error is None:
                        job._error = e
        with self._lock:
            if not job._state_in(*JobState.TERMINAL):
                job._transition(JobState.CANCELLED)
                self._release(job)
        self._enqueue_sentinel(job)

"""Multi-tenant job runtime: concurrent streaming queries, one device
pipeline (ISSUE 5).

Public surface::

    from gelly_streaming_tpu.runtime import JobManager, RuntimeConfig

    with JobManager(RuntimeConfig(max_jobs=4)) as jm:
        job = jm.submit_aggregation(stream, ConnectedComponents())
        for record in job.results():
            ...

See runtime/job.py for the lifecycle state machine and runtime/manager.py
for the weighted-fair cooperative scheduler + admission control;
``gelly-serve`` (runtime/serve.py) is the console driver.

The network layer on top (ISSUE 8)::

    from gelly_streaming_tpu.runtime import StreamServer
    from gelly_streaming_tpu.runtime.client import GellyClient

    with JobManager() as jm, StreamServer(jm, ServerConfig()) as server:
        with GellyClient("127.0.0.1", server.port) as client:
            client.submit(name="cc", query="cc", window_edges=1 << 13)
            client.push_edges("cc", src, dst, batch=1 << 12,
                              capacity=1 << 16)
            for record in client.iter_results("cc"):
                ...

``gelly-serve --listen host:port`` runs the long-lived server;
``gelly-client`` is the remote console (runtime/client.py).

The fleet tier on top of THAT (ISSUE 20)::

    from gelly_streaming_tpu.runtime import Fleet, GLYRouter
    from gelly_streaming_tpu.runtime.fleet import BackendSpec, FleetConfig

    fleet = Fleet(FleetConfig(backends=(
        BackendSpec("b1", "127.0.0.1", 7421),
        BackendSpec("b2", "127.0.0.1", 7422),
        BackendSpec("sb", "127.0.0.1", 7429, standby=True),
    ), replica_dir="/var/lib/gelly/replica"))
    with GLYRouter(fleet) as router:
        ...  # GellyClient("127.0.0.1", router.port) — same protocol

``gelly-router --config fleet.json`` is the console form: N
``gelly-serve`` backends, rendezvous placement per tenant/job,
journal-replicated warm-standby failover (runtime/fleet.py), and
verb fan-out aggregation (runtime/router.py).
"""

from gelly_streaming_tpu.core.config import (
    RuntimeConfig,
    ServerConfig,
    TenantConfig,
)
from gelly_streaming_tpu.runtime.job import (
    AdmissionError,
    Job,
    JobError,
    JobState,
)
from gelly_streaming_tpu.runtime.manager import JobManager


def __getattr__(name):
    # StreamServer drags in the full server module (sockets, selectors);
    # keep `from gelly_streaming_tpu.runtime import JobManager` light
    if name == "StreamServer":
        from gelly_streaming_tpu.runtime.server import StreamServer

        return StreamServer
    if name == "Fleet":
        from gelly_streaming_tpu.runtime.fleet import Fleet

        return Fleet
    if name == "GLYRouter":
        from gelly_streaming_tpu.runtime.router import GLYRouter

        return GLYRouter
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__all__ = [
    "AdmissionError",
    "Fleet",
    "GLYRouter",
    "Job",
    "JobError",
    "JobManager",
    "JobState",
    "RuntimeConfig",
    "ServerConfig",
    "StreamServer",
    "TenantConfig",
]

"""Multi-tenant job runtime: concurrent streaming queries, one device
pipeline (ISSUE 5).

Public surface::

    from gelly_streaming_tpu.runtime import JobManager, RuntimeConfig

    with JobManager(RuntimeConfig(max_jobs=4)) as jm:
        job = jm.submit_aggregation(stream, ConnectedComponents())
        for record in job.results():
            ...

See runtime/job.py for the lifecycle state machine and runtime/manager.py
for the weighted-fair cooperative scheduler + admission control;
``gelly-serve`` (runtime/serve.py) is the console driver.
"""

from gelly_streaming_tpu.core.config import RuntimeConfig
from gelly_streaming_tpu.runtime.job import (
    AdmissionError,
    Job,
    JobError,
    JobState,
)
from gelly_streaming_tpu.runtime.manager import JobManager

__all__ = [
    "AdmissionError",
    "Job",
    "JobError",
    "JobManager",
    "JobState",
    "RuntimeConfig",
]

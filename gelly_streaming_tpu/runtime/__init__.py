"""Multi-tenant job runtime: concurrent streaming queries, one device
pipeline (ISSUE 5).

Public surface::

    from gelly_streaming_tpu.runtime import JobManager, RuntimeConfig

    with JobManager(RuntimeConfig(max_jobs=4)) as jm:
        job = jm.submit_aggregation(stream, ConnectedComponents())
        for record in job.results():
            ...

See runtime/job.py for the lifecycle state machine and runtime/manager.py
for the weighted-fair cooperative scheduler + admission control;
``gelly-serve`` (runtime/serve.py) is the console driver.

The network layer on top (ISSUE 8)::

    from gelly_streaming_tpu.runtime import StreamServer
    from gelly_streaming_tpu.runtime.client import GellyClient

    with JobManager() as jm, StreamServer(jm, ServerConfig()) as server:
        with GellyClient("127.0.0.1", server.port) as client:
            client.submit(name="cc", query="cc", window_edges=1 << 13)
            client.push_edges("cc", src, dst, batch=1 << 12,
                              capacity=1 << 16)
            for record in client.iter_results("cc"):
                ...

``gelly-serve --listen host:port`` runs the long-lived server;
``gelly-client`` is the remote console (runtime/client.py).
"""

from gelly_streaming_tpu.core.config import (
    RuntimeConfig,
    ServerConfig,
    TenantConfig,
)
from gelly_streaming_tpu.runtime.job import (
    AdmissionError,
    Job,
    JobError,
    JobState,
)
from gelly_streaming_tpu.runtime.manager import JobManager


def __getattr__(name):
    # StreamServer drags in the full server module (sockets, selectors);
    # keep `from gelly_streaming_tpu.runtime import JobManager` light
    if name == "StreamServer":
        from gelly_streaming_tpu.runtime.server import StreamServer

        return StreamServer
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


__all__ = [
    "AdmissionError",
    "Job",
    "JobError",
    "JobManager",
    "JobState",
    "RuntimeConfig",
    "ServerConfig",
    "StreamServer",
    "TenantConfig",
]

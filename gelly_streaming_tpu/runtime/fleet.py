"""Fleet tier state: backend registry, liveness probing, warm-standby
replication, journal-replay failover, and tenant rebalancing (ISSUE 20).

One ``gelly-serve`` process is one failure domain bounded by one host's
cores.  The fleet tier scales past that WITHOUT inventing new machinery:

* :class:`BackendRegistry` — which backends exist and which answer GLY1
  ``ping`` frames right now (a typed refusal counts as alive: the probe
  proves the event loop, not the credentials).
* :class:`Fleet` — consistent placement (rendezvous-hashed on
  ``tenant/job``, overridden by rebalance pins and failover takeovers),
  plus warm-standby replication: each backend's JSONL event journal and
  positional checkpoints (the exact ``per_job_file`` derivation the
  server already writes) are shipped to the standby's paths, so a
  SIGKILL'd backend's jobs are resubmittable from journal replay alone —
  the ``job_spec`` records carry the verbatim client specs, and the
  replicated checkpoints supply the resume cursors.
* :class:`FleetRebalancer` — the Autoscaler's policy-thread pattern
  (streaks, cooldown, deterministic ``evaluate_once`` with an injectable
  clock, actuation OUTSIDE the lock) generalized from shard geometry to
  tenant PLACEMENT: sustained PAGE burn on one backend drains the
  tenant's jobs there (cursors), ships their checkpoints, and resubmits
  them on a cold backend — the same drain→cursor→resubmit actuation path
  the elastic control plane (runtime/autoscale.py) already pins.

Everything here is control plane: the data plane (frame relay, offset
guard, pipelining) lives in runtime/router.py, and the recovery contract
is the EXISTING one — clients resync through ``out-of-sync``/``expected``
offsets, at-least-once with overlap-only emissions.
"""

from __future__ import annotations

import glob
import os
import shutil
import socket
import threading
import time
from dataclasses import dataclass, field
from hashlib import md5
from typing import Callable, Dict, List, Mapping, Optional, Tuple

from gelly_streaming_tpu.runtime import protocol
from gelly_streaming_tpu.runtime.job import JobState
from gelly_streaming_tpu.utils import events
from gelly_streaming_tpu.utils.checkpoint import per_job_file


@dataclass(frozen=True)
class BackendSpec:
    """One ``gelly-serve --listen`` process the fleet routes to.

    ``journal_path`` / ``checkpoint_prefix`` name the backend's ON-DISK
    durable state (its ``events_path`` journal and per-job snapshot
    prefix) as seen from the router's host — replication reads them, so
    they must be reachable paths (same host or a shared filesystem).
    ``standby=True`` marks the warm standby: it takes no placements until
    a failover redirects a dead backend's keys onto it.
    """

    name: str
    host: str
    port: int
    journal_path: Optional[str] = None
    checkpoint_prefix: Optional[str] = None
    standby: bool = False


@dataclass(frozen=True)
class FleetConfig:
    """Knobs for the fleet control plane.

    Attributes:
      backends: every process in the fleet, standby included.
      replica_dir: where backend journal replicas land (one
        ``journal-<name>.jsonl`` per backend); None disables journal
        replication (failover then has no specs to replay).
      tenant_tokens: ``{tenant: token}`` — the control plane's
        credentials for drain/resubmit during failover and rebalance
        (open-mode fleets leave it empty and everything runs as the
        implicit ``default`` tenant).
      probe_interval_s / probe_timeout_s / fail_threshold: liveness
        probing cadence; ``fail_threshold`` CONSECUTIVE probe failures
        transition a backend to down and trigger failover.
      replicate_interval_s: cadence of the journal/checkpoint shipping
        loop.
    """

    backends: Tuple[BackendSpec, ...] = ()
    replica_dir: Optional[str] = None
    tenant_tokens: Mapping[str, str] = field(default_factory=dict)
    probe_interval_s: float = 0.3
    probe_timeout_s: float = 2.0
    fail_threshold: int = 2
    replicate_interval_s: float = 0.5


def _probe_backend(spec: BackendSpec, timeout_s: float) -> float:
    """One liveness probe: connect, ping, read ANY reply -> RTT ms.

    A typed refusal (e.g. ``auth`` on a token-mode backend) still proves
    the process accepts connections and serves frames — liveness, not
    authorization, is what the registry tracks.
    """
    t0 = time.perf_counter()
    with socket.create_connection(
        (spec.host, spec.port), timeout=timeout_s
    ) as sock:
        sock.settimeout(timeout_s)
        f = sock.makefile("rwb")
        protocol.write_frame(f, {"verb": "ping", "token": ""})
        if protocol.read_frame(f) is None:
            raise OSError("backend closed the probe connection")
    return (time.perf_counter() - t0) * 1e3


class BackendRegistry:
    """Live/down state for every backend, maintained by a probe thread.

    ``report_failure`` lets the data plane (a relay whose upstream write
    failed) feed the same counter the probes use, so a dead backend is
    detected at frame latency, not probe latency; the down transition —
    and its ``on_down`` callback — still fires exactly once.
    """

    def __init__(
        self,
        backends: Tuple[BackendSpec, ...],
        probe_interval_s: float = 0.3,
        probe_timeout_s: float = 2.0,
        fail_threshold: int = 2,
        on_down: Optional[Callable[[BackendSpec], None]] = None,
    ):
        self.backends = tuple(backends)
        self._by_name = {b.name: b for b in self.backends}
        self.probe_interval_s = probe_interval_s
        self.probe_timeout_s = probe_timeout_s
        self.fail_threshold = max(1, int(fail_threshold))
        self._on_down = on_down
        self._lock = threading.Lock()
        self._alive = {b.name: True for b in self.backends}  # guarded-by: _lock
        self._fails = {b.name: 0 for b in self.backends}  # guarded-by: _lock
        self._rtt_ms = {b.name: None for b in self.backends}  # guarded-by: _lock
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def backend(self, name: str) -> Optional[BackendSpec]:
        return self._by_name.get(name)

    def is_alive(self, name: str) -> bool:
        with self._lock:
            return bool(self._alive.get(name))

    def mark_up(self, name: str) -> None:
        with self._lock:
            self._alive[name] = True
            self._fails[name] = 0

    def report_failure(self, name: str) -> None:
        """One observed failure against ``name`` (probe or data plane);
        the ``fail_threshold``-th consecutive one transitions it down."""
        if name not in self._by_name:
            return
        newly_down = False
        with self._lock:
            self._fails[name] = self._fails.get(name, 0) + 1
            if self._alive.get(name) and (
                self._fails[name] >= self.fail_threshold
            ):
                self._alive[name] = False
                newly_down = True
        # the callback does real work (failover submits) — never under
        # the registry lock, and never twice for one down transition
        if newly_down and self._on_down is not None:
            self._on_down(self._by_name[name])

    def probe_once(self) -> Dict[str, bool]:
        """Probe every backend once; returns ``{name: alive}``."""
        for spec in self.backends:
            try:
                rtt = _probe_backend(spec, self.probe_timeout_s)
            except (OSError, protocol.ProtocolError):
                self.report_failure(spec.name)
                continue
            with self._lock:
                self._alive[spec.name] = True
                self._fails[spec.name] = 0
                self._rtt_ms[spec.name] = round(rtt, 3)
        with self._lock:
            return dict(self._alive)

    def snapshot(self) -> Dict[str, dict]:
        """Per-backend registry rows for the router's ``fleet`` verb."""
        with self._lock:
            alive = dict(self._alive)
            fails = dict(self._fails)
            rtt = dict(self._rtt_ms)
        return {
            b.name: {
                "host": b.host,
                "port": b.port,
                "standby": b.standby,
                "alive": bool(alive.get(b.name)),
                "fails": fails.get(b.name, 0),
                "rtt_ms": rtt.get(b.name),
            }
            for b in self.backends
        }

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="fleet-probe", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
        self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.probe_interval_s):
            try:
                self.probe_once()
            except Exception:  # a probe bug must never kill the thread
                continue


class Fleet:
    """Placement + replication + failover for one router's backends.

    Placement resolves in three layers, most specific first: rebalance
    PINS (``tenant/job`` moved explicitly), failover TAKEOVERS (every key
    of a dead backend redirected to the standby), then rendezvous hashing
    over the serving (non-standby) backends — deterministic, so N
    stateless routers over the same config agree without coordination.
    """

    def __init__(self, cfg: FleetConfig):
        self.cfg = cfg
        self.serving = tuple(b for b in cfg.backends if not b.standby)
        standbys = [b for b in cfg.backends if b.standby]
        self.standby = standbys[0] if standbys else None
        if not self.serving:
            raise ValueError("fleet needs at least one serving backend")
        self.registry = BackendRegistry(
            cfg.backends,
            probe_interval_s=cfg.probe_interval_s,
            probe_timeout_s=cfg.probe_timeout_s,
            fail_threshold=cfg.fail_threshold,
            on_down=self._backend_down,
        )
        # token -> tenant (placement is keyed on the TENANT, and the
        # token is its wire proxy); unknown tokens hash as themselves so
        # placement stays consistent even without a configured table
        self._tenant_of = {t: name for name, t in cfg.tenant_tokens.items()}
        self._lock = threading.Lock()
        self._pins: Dict[str, str] = {}  # guarded-by: _lock
        self._takeover: Dict[str, str] = {}  # guarded-by: _lock
        self._failed_over: set = set()  # guarded-by: _lock
        self._repl_stats = {"files": 0, "bytes": 0, "syncs": 0}  # guarded-by: _lock
        self._stop = threading.Event()
        self._repl_thread: Optional[threading.Thread] = None

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> None:
        self.registry.start()
        if self.cfg.replica_dir and self._repl_thread is None:
            os.makedirs(self.cfg.replica_dir, exist_ok=True)
            self._stop.clear()
            self._repl_thread = threading.Thread(
                target=self._replicate_run, name="fleet-replicate", daemon=True
            )
            self._repl_thread.start()

    def stop(self) -> None:
        self._stop.set()
        self.registry.stop()
        t = self._repl_thread
        if t is not None:
            t.join(timeout=5.0)
        self._repl_thread = None

    def __enter__(self) -> "Fleet":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- placement -----------------------------------------------------------

    def tenant_for_token(self, token: str) -> str:
        return self._tenant_of.get(token) or (token or "default")

    def _rendezvous(self, key: str) -> str:
        """Highest-random-weight choice over the serving backends: each
        key independently lands on the backend whose ``md5(name|key)``
        wins, so placement is uniform, deterministic, and stable under
        the FIXED backend set (liveness changes reroute via takeovers,
        never by re-hashing every key)."""
        return max(
            self.serving,
            key=lambda b: md5(f"{b.name}|{key}".encode()).digest(),
        ).name

    def place(self, tenant: str, job: str) -> BackendSpec:
        """Resolve ``tenant/job`` to its backend: pin, then takeover
        redirect, then rendezvous."""
        key = f"{tenant}/{job}"
        with self._lock:
            name = self._pins.get(key)
            takeover = dict(self._takeover)
        if name is None:
            name = self._rendezvous(key)
        name = takeover.get(name, name)
        return self.registry.backend(name) or self.serving[0]

    def pin(self, tenant: str, job: str, backend: str) -> None:
        with self._lock:
            self._pins[f"{tenant}/{job}"] = backend

    def pin_counts(self) -> Dict[str, int]:
        counts = {b.name: 0 for b in self.serving}
        with self._lock:
            pins = dict(self._pins)
        for name in pins.values():
            counts[name] = counts.get(name, 0) + 1
        return counts

    def takeover_map(self) -> Dict[str, str]:
        with self._lock:
            return dict(self._takeover)

    def snapshot(self) -> dict:
        """The ``fleet`` verb's payload: registry rows + routing state."""
        with self._lock:
            pins = dict(self._pins)
            takeover = dict(self._takeover)
            repl = dict(self._repl_stats)
        return {
            "backends": self.registry.snapshot(),
            "standby": self.standby.name if self.standby else None,
            "takeover": takeover,
            "pins": pins,
            "replication": repl,
        }

    # -- warm-standby replication --------------------------------------------

    def replica_journal_path(self, name: str) -> Optional[str]:
        if not self.cfg.replica_dir:
            return None
        return os.path.join(self.cfg.replica_dir, f"journal-{name}.jsonl")

    @staticmethod
    def _copy_if_changed(src: str, dst: str) -> int:
        """tmp+rename copy (the destination is always a COMPLETE older
        snapshot, never a torn one); skipped when size+mtime already
        match.  Returns bytes shipped."""
        try:
            st = os.stat(src)
        except OSError:
            return 0
        try:
            dt = os.stat(dst)
            if (dt.st_size, dt.st_mtime_ns) == (st.st_size, st.st_mtime_ns):
                return 0
        except OSError:
            pass
        os.makedirs(os.path.dirname(os.path.abspath(dst)), exist_ok=True)
        tmp = dst + ".tmp"
        shutil.copy2(src, tmp)
        os.replace(tmp, dst)
        return st.st_size

    def sync_backend(
        self,
        spec: BackendSpec,
        ckpt_dst_prefix: Optional[str] = None,
        jobs: Optional[List[str]] = None,
    ) -> Dict[str, int]:
        """Ship one backend's durable state: its event journal to the
        replica dir, and its positional checkpoints to the standby's
        checkpoint prefix (or ``ckpt_dst_prefix`` — the rebalance target).

        ``jobs`` restricts the checkpoint copy to those job ids (the
        server's ``tenant.name`` keying) — rebalance moves ONE tenant's
        files, not the whole backend's.
        """
        stats = {"files": 0, "bytes": 0}
        replica = self.replica_journal_path(spec.name)
        if spec.journal_path and replica:
            n = self._copy_if_changed(spec.journal_path, replica)
            if n:
                stats["files"] += 1
                stats["bytes"] += n
        dst_prefix = ckpt_dst_prefix
        if dst_prefix is None and self.standby is not None:
            dst_prefix = self.standby.checkpoint_prefix
        src_prefix = spec.checkpoint_prefix
        if src_prefix and dst_prefix and dst_prefix != src_prefix:
            if jobs is not None:
                paths = [per_job_file(src_prefix, j) for j in jobs]
            else:
                base = (
                    src_prefix[: -len(".npz")]
                    if src_prefix.endswith(".npz")
                    else src_prefix
                )
                paths = glob.glob(glob.escape(base) + ".job_*.npz")
            dst_base = (
                dst_prefix[: -len(".npz")]
                if dst_prefix.endswith(".npz")
                else dst_prefix
            )
            src_base = (
                src_prefix[: -len(".npz")]
                if src_prefix.endswith(".npz")
                else src_prefix
            )
            for path in paths:
                n = self._copy_if_changed(
                    path, dst_base + path[len(src_base):]
                )
                if n:
                    stats["files"] += 1
                    stats["bytes"] += n
        with self._lock:
            self._repl_stats["files"] += stats["files"]
            self._repl_stats["bytes"] += stats["bytes"]
            self._repl_stats["syncs"] += 1
        return stats

    def replicate_once(self) -> Dict[str, int]:
        total = {"files": 0, "bytes": 0}
        for spec in self.serving:
            try:
                stats = self.sync_backend(spec)
            except OSError:
                continue  # a torn source retries next tick
            total["files"] += stats["files"]
            total["bytes"] += stats["bytes"]
        return total

    def _replicate_run(self) -> None:
        while not self._stop.wait(self.cfg.replicate_interval_s):
            try:
                self.replicate_once()
            except Exception:  # replication must never kill its thread
                continue

    # -- failover ------------------------------------------------------------

    def _backend_down(self, spec: BackendSpec) -> None:
        """Registry down-transition hook.  Failover does network work
        (resubmits against the standby), so it runs on its own thread —
        the caller may be a relay's reader mid-frame."""
        events.journal().emit(
            "fleet_backend_down", backend=spec.name, standby=spec.standby
        )
        if spec.standby or self.standby is None:
            return
        threading.Thread(
            target=self.failover,
            args=(spec.name,),
            name=f"fleet-failover-{spec.name}",
            daemon=True,
        ).start()

    def failover(self, name: str) -> dict:
        """Reattach a dead backend's live jobs on the warm standby.

        Replays the backend's journal REPLICA (a final sync first — the
        dead process's files are still on disk), resubmits every
        non-terminal ``job_spec`` verbatim against the standby (whose
        replicated checkpoints supply the resume cursors), then installs
        the takeover redirect so placement — and every reconnecting
        client — lands on the standby.  Runs at most once per backend.
        """
        spec = self.registry.backend(name)
        if spec is None or self.standby is None:
            return {"backend": name, "resubmitted": [], "failed": []}
        with self._lock:
            # check-and-claim under ONE lock hold: two down-reports race
            # here, exactly one runs the failover
            if name in self._failed_over:
                return {"backend": name, "resubmitted": [], "failed": []}
            self._failed_over.add(name)
        try:
            self.sync_backend(spec)
        except OSError:
            pass  # the periodic replica (if any) is the fallback
        replica = self.replica_journal_path(name)
        evs: List[dict] = []
        if replica and os.path.exists(replica):
            evs = events.replay(replica)
        specs: Dict[str, dict] = {}
        for ev in evs:
            if ev.get("kind") == "job_spec":
                specs[ev["job"]] = ev
        from gelly_streaming_tpu.runtime.client import (
            ClientError,
            GellyClient,
            ServerRefused,
        )

        resubmitted, failed = [], []
        for job_key, ev in sorted(specs.items()):
            try:
                hist = events.job_history(evs, job_key)
            except ValueError:
                hist = []  # a gapped chain still resubmits: liveness wins
            if hist and hist[-1] and hist[-1][-1] in JobState.TERMINAL:
                continue  # completed before the crash: nothing to reattach
            tenant = ev.get("tenant", "default")
            token = self.cfg.tenant_tokens.get(tenant, "")
            try:
                with GellyClient(
                    self.standby.host, self.standby.port, token=token
                ) as client:
                    reply = client.submit(**ev.get("spec", {}))
                resubmitted.append(
                    {
                        "job": job_key,
                        "resume_edges": reply.get("resume_edges", 0),
                    }
                )
            except (OSError, ClientError, ServerRefused) as e:
                failed.append({"job": job_key, "error": str(e)})
        with self._lock:
            self._takeover[name] = self.standby.name
        events.journal().emit(
            "fleet_failover",
            backend=name,
            standby=self.standby.name,
            jobs=[r["job"] for r in resubmitted],
            failed=[f["job"] for f in failed],
        )
        return {
            "backend": name,
            "standby": self.standby.name,
            "resubmitted": resubmitted,
            "failed": failed,
        }

    # -- rebalance -----------------------------------------------------------

    def rebalance(self, tenant: str, src_name: str, dst_name: str) -> dict:
        """Move one tenant's jobs from ``src`` to ``dst``: drain (resume
        cursors), ship their checkpoints + the journal, resubmit the
        journaled specs on ``dst``, pin the keys there.  The jobs'
        clients ride the EXISTING recovery contract the whole way:
        ``quiesced`` refusals during the drain, then ``out-of-sync`` with
        the advertised cursor once the pins route them to ``dst``.
        """
        src = self.registry.backend(src_name)
        dst = self.registry.backend(dst_name)
        if src is None or dst is None:
            raise ValueError(f"unknown backend {src_name!r}/{dst_name!r}")
        token = self.cfg.tenant_tokens.get(tenant, "")
        from gelly_streaming_tpu.runtime.client import GellyClient

        with GellyClient(src.host, src.port, token=token) as client:
            cursors = client.drain().get("cursors", {})
        if not cursors:
            return {"tenant": tenant, "moved": [], "failed": []}
        self.sync_backend(
            src,
            ckpt_dst_prefix=dst.checkpoint_prefix,
            jobs=[f"{tenant}.{n}" for n in cursors],
        )
        replica = self.replica_journal_path(src_name)
        evs = (
            events.replay(replica)
            if replica and os.path.exists(replica)
            else []
        )
        specs = {
            ev["job"]: ev for ev in evs if ev.get("kind") == "job_spec"
        }
        moved, failed = [], []
        for jname, cur in sorted(cursors.items()):
            job_key = f"{tenant}/{jname}"
            ev = specs.get(job_key)
            if ev is None:
                failed.append({"job": job_key, "error": "no journaled spec"})
                continue
            try:
                with GellyClient(dst.host, dst.port, token=token) as client:
                    reply = client.submit(**ev.get("spec", {}))
            except Exception as e:
                failed.append({"job": job_key, "error": str(e)})
                continue
            self.pin(tenant, jname, dst_name)
            moved.append(
                {
                    "job": job_key,
                    "cursor": cur.get("resume_edges"),
                    "resume_edges": reply.get("resume_edges", 0),
                }
            )
        events.journal().emit(
            "fleet_rebalance",
            tenant=tenant,
            source=src_name,
            target=dst_name,
            jobs=[m["job"] for m in moved],
            failed=[f["job"] for f in failed],
        )
        return {"tenant": tenant, "moved": moved, "failed": failed}


@dataclass(frozen=True)
class RebalancePolicy:
    """Knobs for the fleet rebalancer's policy loop.

    ``page_streak`` CONSECUTIVE evaluations observing PAGE-level burn for
    one (backend, tenant) trigger a move; ``cooldown_s`` then holds that
    pair — rebalancing is a big hammer, and flapping placement under a
    sustained overload would multiply the pain, not divide it.
    """

    interval_s: float = 2.0
    page_streak: int = 3
    cooldown_s: float = 60.0
    probe_timeout_s: float = 5.0


class FleetRebalancer:
    """Fleet-aware elasticity: sustained PAGE burn on one backend moves
    the burning tenant's jobs to a cold one.

    The Autoscaler's shape exactly (runtime/autoscale.py): a policy
    thread with an injectable clock, a deterministic ``evaluate_once``
    that tests drive directly, streak/cooldown state under one lock, and
    actuation OUTSIDE the lock.  ``burn_probe(spec) -> {tenant: bool}``
    is injectable too — the default reads each backend's ``alerts`` verb
    and reports tenants with a PAGE-state row.
    """

    def __init__(
        self,
        fleet: Fleet,
        policy: Optional[RebalancePolicy] = None,
        burn_probe: Optional[
            Callable[[BackendSpec], Mapping[str, bool]]
        ] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.fleet = fleet
        self.policy = policy or RebalancePolicy()
        self._burn_probe = burn_probe or self._probe_alerts
        self._clock = clock
        self._lock = threading.Lock()
        self._streaks: Dict[Tuple[str, str], int] = {}  # guarded-by: _lock
        self._last_move: Dict[Tuple[str, str], float] = {}  # guarded-by: _lock
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def _probe_alerts(self, spec: BackendSpec) -> Mapping[str, bool]:
        """Default burn probe: one ``alerts`` call per configured tenant;
        a PAGE-state row attributes to the row's job scope's tenant."""
        from gelly_streaming_tpu.runtime.client import GellyClient

        tokens = dict(self.fleet.cfg.tenant_tokens) or {"default": ""}
        out: Dict[str, bool] = {}
        for tenant, token in tokens.items():
            try:
                with GellyClient(
                    spec.host,
                    spec.port,
                    token=token,
                    timeout=self.policy.probe_timeout_s,
                ) as client:
                    rows = client.alerts()
            except Exception:
                continue  # an unreachable backend is the registry's call
            for row in rows:
                if row.get("state") != "PAGE":
                    continue
                scope = str(row.get("id", row.get("scope", "")))
                owner = scope.split("/", 1)[0] if "/" in scope else tenant
                out[owner] = True
        return out

    def evaluate_once(self, now: float) -> List[dict]:
        """One deterministic policy evaluation at time ``now``; returns
        the rebalance outcomes it actuated (possibly empty)."""
        observations = []
        for spec in self.fleet.serving:
            if not self.fleet.registry.is_alive(spec.name):
                continue
            burn = self._burn_probe(spec)  # network I/O: outside the lock
            observations.append((spec, dict(burn)))
        decisions: List[Tuple[str, str]] = []
        with self._lock:
            for spec, burn in observations:
                burning_now = {t for t, b in burn.items() if b}
                # a tenant ABSENT from this probe is not burning: its
                # streak resets (the default probe only reports PAGE
                # rows, so absence is the all-clear signal — a stale
                # streak must not combine with one later PAGE into an
                # instant move)
                for key in list(self._streaks):
                    if key[0] == spec.name and key[1] not in burning_now:
                        self._streaks[key] = 0
                for tenant in sorted(burning_now):
                    key = (spec.name, tenant)
                    self._streaks[key] = self._streaks.get(key, 0) + 1
                    last = self._last_move.get(key)
                    cooled = (
                        last is None
                        or now - last >= self.policy.cooldown_s
                    )
                    if self._streaks[key] >= self.policy.page_streak and (
                        cooled
                    ):
                        decisions.append(key)
                        self._streaks[key] = 0
                        self._last_move[key] = now
        results = []
        for src_name, tenant in decisions:  # actuation: outside the lock
            dst_name = self._pick_target(src_name)
            if dst_name is None:
                events.journal().emit(
                    "rebalance_failed",
                    tenant=tenant,
                    source=src_name,
                    error="no live target backend",
                )
                continue
            events.journal().emit(
                "rebalance_decision",
                tenant=tenant,
                source=src_name,
                target=dst_name,
            )
            try:
                outcome = self.fleet.rebalance(tenant, src_name, dst_name)
            except Exception as e:
                events.journal().emit(
                    "rebalance_failed",
                    tenant=tenant,
                    source=src_name,
                    target=dst_name,
                    error=str(e),
                )
                continue
            events.journal().emit(
                "rebalance_done",
                tenant=tenant,
                source=src_name,
                target=dst_name,
                jobs=[m["job"] for m in outcome["moved"]],
            )
            results.append(outcome)
        return results

    def _pick_target(self, src_name: str) -> Optional[str]:
        """The coldest live serving backend that isn't the source: fewest
        pinned keys, name as the deterministic tiebreak."""
        takeover = self.fleet.takeover_map()
        counts = self.fleet.pin_counts()
        candidates = [
            b.name
            for b in self.fleet.serving
            if b.name != src_name
            and b.name not in takeover
            and self.fleet.registry.is_alive(b.name)
        ]
        if not candidates:
            return None
        return min(candidates, key=lambda n: (counts.get(n, 0), n))

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="fleet-rebalance", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5.0)
        self._thread = None

    def _run(self) -> None:
        while not self._stop.wait(self.policy.interval_s):
            try:
                self.evaluate_once(self._clock())
            except Exception:  # policy bugs must never kill the thread
                continue

"""GIL-free decode pool for the serving data plane (ISSUE 14).

The serving bench pinned the frontend at ~0.4x the in-process rate: GLY1
frame parsing, wire decode-validation, and repack all ran as Python/numpy
on connection threads, timesharing the GIL with the scheduler and the
fold drain.  This pool moves the per-push decode work off the
interpreter: worker threads run the native ``decode_wire_into`` entry
point (one C call per buffer — size bounds, id decode, BOTH ends of the
id-range check, optional (dst, src) binning — with the GIL released for
the duration) and land the decoded rows directly into recycled
``ArenaPool`` transfer arenas, so ``NetworkEdgeSource`` receives
ready-to-queue int32 rows instead of freshly allocated intermediate
batches.

Equivalence oracle: ``GELLY_DECODE_WORKERS=0`` (or
``ServerConfig.decode_workers=0``) disables the pool and the server runs
today's pure-Python path (``NetworkEdgeSource.push_wire`` over
``validate_wire_buffer``).  The pool's refusals are the ORACLE'S: the
native code only detects, and any refused buffer is re-run through the
numpy twin (``io/wire.decode_wire_np``) to raise the canonical typed
``ValueError`` — so the two paths are byte-identical in both accepted
batches and refusal messages (pinned by tests/test_decode_pool.py).

Threading/locking (the serving plane's lock hierarchy, pass #7/#8): the
pool's completion lock is a LEAF — workers and waiters take it bare and
call nothing under it; the submission queue's own mutex and the arena
free-list lock (core/async_exec.ArenaPool._lock) are only ever taken in
SEQUENCE with it, never nested.  Workers never touch the device: decode
is host-side by construction (numpy + ctypes, no jax import in this
module), so a decode worker can never introduce a device sync into the
scheduler's dispatch overlap.
"""
# lock-order: server.StreamServer._admission < decode_pool.DecodePool._lock

from __future__ import annotations

import os
import queue
import threading
from typing import Tuple

import numpy as np

from gelly_streaming_tpu.core.async_exec import ArenaPool

# default pool size when neither config nor env decides: two workers keeps
# decode off the scheduler's core on this image's 2-core hosts without
# oversubscribing it
DEFAULT_DECODE_WORKERS = 2


def resolve_decode_workers(requested: int = -1) -> int:
    """Effective decode-pool size: explicit config (>= 0) wins, then the
    ``GELLY_DECODE_WORKERS`` env var, then ``DEFAULT_DECODE_WORKERS``.

    0 means "no pool": pushes take the pure-Python decode path — the
    equivalence oracle.  An unparseable env spelling refuses loudly (the
    same contract as the other data-plane switches in utils/envswitch.py)
    rather than silently flipping the hot path.
    """
    if requested is not None and requested >= 0:
        return int(requested)
    env = os.environ.get("GELLY_DECODE_WORKERS")
    if env is not None:
        try:
            return max(0, int(env))
        except ValueError:
            raise ValueError(
                f"GELLY_DECODE_WORKERS={env!r} is not an integer "
                "(0 disables the decode pool)"
            )
    return DEFAULT_DECODE_WORKERS


class DecodePoolClosed(RuntimeError):
    """Decode refused because the pool is shutting down (server stop): the
    connection gets a typed refusal instead of a wedged wait."""


class DecodePool:
    """N worker threads running native wire decode into transfer arenas.

    ``decode()`` is called from connection handler threads: it enqueues
    one request and blocks until a worker finishes it, returning
    ``(src, dst, release)`` where ``src``/``dst`` are int32[batch] rows of
    a pooled arena and ``release`` returns the arena to the free-list.
    Ownership: the CALLER owns the arena from return until it either
    hands it to the ingest queue (``NetworkEdgeSource.push_decoded``
    passes ``release`` along; the stream factory fires it after copying
    the rows out — the donation fence) or fails, in which case it must
    fire ``release`` itself.

    Results cross threads through a completion map under one leaf lock
    (see the module docstring's hierarchy note); per-request condition
    wakeups keep a slow client's wait from costing other connections
    anything.
    """

    def __init__(self, workers: int, arena_per_shape: int = 16):
        if workers <= 0:
            raise ValueError("DecodePool needs workers >= 1 (0 = no pool)")
        self.workers = int(workers)
        # recycled (2, batch) int32 landing arenas; free-list guarded
        # inside ArenaPool (async_exec.ArenaPool._free # guarded-by: _lock)
        self._arenas = ArenaPool(per_shape=arena_per_shape)
        # submission queue: bounded so a flood of pushing connections
        # backpressures at submit, not in an unbounded request pile
        self._subq: "queue.Queue" = queue.Queue(maxsize=4 * self.workers)
        # the pool's ONE leaf lock: a Condition so completion wakeups and
        # the guarded state share a single acquisition
        self._lock = threading.Condition()
        # completion queue: request id -> decoded rows or the refusal to
        # re-raise; workers write, the submitting connection thread reaps
        self._done: dict = {}  # guarded-by: _lock
        self._next_id = 0  # guarded-by: _lock
        # native-vs-twin served counts (the bench/status introspection)
        self._stats = {"native": 0, "fallback": 0}  # guarded-by: _lock
        self._stop = threading.Event()
        self._threads = [
            threading.Thread(
                target=self._run, name=f"gelly-decode-{i}", daemon=True
            )
            for i in range(self.workers)
        ]
        for t in self._threads:
            t.start()

    # -- submit side (connection handler threads) ----------------------------

    def decode(
        self, buf, width, batch: int, capacity: int, sort: bool = False
    ) -> Tuple[np.ndarray, np.ndarray, "callable"]:
        """Validate + decode one full wire buffer on the pool.

        Blocks until a worker completes it.  Raises the numpy oracle's
        typed ``ValueError`` for a refused buffer (byte-identical to the
        Python path's), ``DecodePoolClosed`` when the pool is stopping.
        """
        if self._stop.is_set():
            raise DecodePoolClosed("decode pool is stopping")
        with self._lock:
            rid = self._next_id
            self._next_id += 1
        self._subq.put((rid, buf, width, batch, capacity, sort))
        with self._lock:
            while rid not in self._done:
                if self._stop.is_set():
                    raise DecodePoolClosed("decode pool is stopping")
                self._lock.wait(0.1)
            out = self._done.pop(rid)
        if isinstance(out, BaseException):
            raise out
        return out

    def stats(self) -> dict:
        with self._lock:
            return dict(self._stats)

    # -- worker side ---------------------------------------------------------

    def _decode_one(self, buf, width, batch, capacity, sort):
        from gelly_streaming_tpu.core.stream import validate_wire_width
        from gelly_streaming_tpu.io import wire

        # the same guard order as NetworkEdgeSource.push_wire: width
        # first, then the buffer (refusal precedence is part of the
        # oracle contract)
        validate_wire_width(width, capacity)
        arena = self._arenas.acquire((2, batch), np.int32)
        try:
            out_src, out_dst = arena[0], arena[1]
            # GIL released inside the ctypes call: frame bytes -> arena
            # rows without the interpreter on the critical path
            native = wire.decode_wire_into(
                buf, batch, width, capacity, out_src, out_dst, sort=sort
            )
            if not native:
                s, d = wire.decode_wire_np(
                    buf, batch, width, capacity, sort=sort
                )
                out_src[:] = s
                out_dst[:] = d
            with self._lock:
                self._stats["native" if native else "fallback"] += 1
        except BaseException:
            self._arenas.release(arena)
            raise
        release = _ArenaRelease(self._arenas, arena)
        return out_src, out_dst, release

    def _run(self) -> None:
        while not self._stop.is_set():
            try:
                req = self._subq.get(timeout=0.1)
            except queue.Empty:
                continue
            rid, buf, width, batch, capacity, sort = req
            try:
                out = self._decode_one(buf, width, batch, capacity, sort)
            except BaseException as e:
                out = e
            with self._lock:
                self._done[rid] = out
                self._lock.notify_all()

    # -- lifecycle -----------------------------------------------------------

    def close(self) -> None:
        """Stop the workers and fail any still-blocked ``decode`` calls
        (their waits see the stop flag within one poll slice).  Idempotent;
        arenas still held by queued batches drain through their own
        ``release`` callbacks (or the GC, if their job died with them)."""
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5.0)
        while True:
            try:
                self._subq.get_nowait()
            except queue.Empty:
                break
        with self._lock:
            # unreaped results (their waiter already gave up): return
            # their arenas to the free-list before dropping them
            for out in self._done.values():
                if isinstance(out, tuple):
                    out[2]()
            self._done.clear()
            self._lock.notify_all()

    def __enter__(self) -> "DecodePool":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class _ArenaRelease:
    """One-shot arena return: safe to fire from whichever thread ends up
    owning the decoded rows (the stream factory's copy fence, or the
    server's error path), and inert on double-fire."""

    __slots__ = ("_pool", "_arena")

    def __init__(self, pool: ArenaPool, arena: np.ndarray):
        self._pool = pool
        self._arena = arena

    def __call__(self) -> None:
        arena, self._arena = self._arena, None
        if arena is not None:
            self._pool.release(arena)

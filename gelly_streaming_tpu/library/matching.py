"""Greedy 1/6-approximation streaming weighted matching (centralized).

Reference: example/CentralizedWeightedMatching.java:68-108 — a parallelism-1
stateful flatMap: for each edge, collect the matched edges colliding on either
endpoint; if the new weight exceeds twice their weight sum, evict them (REMOVE
events) and admit the edge (ADD event).  The reference anchors this on a single
subtask (:59); here it is a single-shard ``lax.scan`` whose state is a pair of
dense arrays (partner[C], weight-by-endpoint) — a matching stores at most one
edge per vertex, so collisions are two O(1) lookups instead of a set walk.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from gelly_streaming_tpu.core import compile_cache
from gelly_streaming_tpu.core.config import StreamConfig
from gelly_streaming_tpu.core.output import OutputStream
from gelly_streaming_tpu.utils.value_types import MatchingEvent


class MatchingState(NamedTuple):
    partner: jax.Array  # int32[C]; -1 = unmatched
    weight: jax.Array  # float32[C]; weight of the matched edge at this vertex


def init_matching(cfg: StreamConfig) -> MatchingState:
    return MatchingState(
        partner=jnp.full((cfg.vertex_capacity,), -1, jnp.int32),
        weight=jnp.zeros((cfg.vertex_capacity,), jnp.float32),
    )


def matching_update(state: MatchingState, src, dst, val, mask):
    """Returns (state, events[B, 3, 4], event_mask[B, 3]).

    Event slots per edge: [REMOVE collision@src, REMOVE collision@dst, ADD].
    Each event row is (type, src, dst, weight) with type 0=REMOVE, 1=ADD.
    """

    def step(carry, inp):
        partner, weight = carry
        u, v, w, ok = inp
        w = w.astype(jnp.float32)
        pu, pv = partner[u], partner[v]
        wu = jnp.where(pu >= 0, weight[u], 0.0)
        # Avoid double-counting when u and v are matched to each other.
        same_edge = (pu == v) & (pv == u) & (pu >= 0)
        wv = jnp.where((pv >= 0) & ~same_edge, weight[v], 0.0)
        admit = ok & (w > 2.0 * (wu + wv)) & (u != v)

        def evict(partner, weight, a, do):
            b = partner[a]
            ww = weight[a]
            do = do & (b >= 0)
            pa = jnp.where(do, -1, partner[a])
            pb = jnp.where(do, -1, partner[jnp.maximum(b, 0)])
            partner = partner.at[a].set(pa)
            partner = partner.at[jnp.maximum(b, 0)].set(pb)
            weight = weight.at[a].set(jnp.where(do, 0.0, weight[a]))
            weight = weight.at[jnp.maximum(b, 0)].set(
                jnp.where(do, 0.0, weight[jnp.maximum(b, 0)])
            )
            # Evicted edges are emitted in canonical (min, max) orientation
            # (the array state does not retain the original arrival orientation).
            lo = jnp.minimum(a, jnp.maximum(b, 0))
            hi = jnp.maximum(a, b)
            ev = jnp.stack(
                [jnp.float32(0), lo.astype(jnp.float32), hi.astype(jnp.float32), ww]
            )
            return partner, weight, ev, do

        partner, weight, ev_u, m_u = evict(partner, weight, u, admit)
        partner, weight, ev_v, m_v = evict(partner, weight, v, admit)
        partner = partner.at[u].set(jnp.where(admit, v, partner[u]))
        partner = partner.at[v].set(jnp.where(admit, u, partner[v]))
        weight = weight.at[u].set(jnp.where(admit, w, weight[u]))
        weight = weight.at[v].set(jnp.where(admit, w, weight[v]))
        ev_add = jnp.stack(
            [jnp.float32(1), u.astype(jnp.float32), v.astype(jnp.float32), w]
        )
        events = jnp.stack([ev_u, ev_v, ev_add])
        emask = jnp.stack([m_u, m_v, admit])
        return (partner, weight), (events, emask)

    if val is None:
        val = jnp.ones(src.shape, jnp.float32)
    (partner, weight), (events, emask) = jax.lax.scan(
        step, (state.partner, state.weight), (src, dst, val, mask)
    )
    return MatchingState(partner, weight), events, emask


class CentralizedWeightedMatching:
    """Continuous MatchingEvent stream (ADD/REMOVE), single-shard stateful op."""

    def __init__(self):
        # graftcheck RAWJIT fix: per-instance jax.jit retraced this kernel
        # for every fresh matcher; the process-global cache compiles it once
        self._kernel = compile_cache.cached_jit(
            ("matching_update",), lambda: matching_update
        )

    def run(self, stream) -> OutputStream:
        def records():
            state = init_matching(stream.cfg)
            for batch in stream.batches():
                state, events, emask = self._kernel(
                    state, batch.src, batch.dst, batch.val, batch.mask
                )
                e_h = np.asarray(events)
                m_h = np.asarray(emask)
                for i in range(e_h.shape[0]):
                    for slot in range(3):
                        if m_h[i, slot]:
                            t, s, d, w = e_h[i, slot]
                            yield MatchingEvent(
                                "ADD" if t > 0.5 else "REMOVE",
                                int(s),
                                int(d),
                                float(w),
                            ).as_tuple()
            self.final_state = state

        return OutputStream(records)

    def matched_edges(self, state: MatchingState):
        """Current matching as canonical (u, v, w) host tuples."""
        partner = np.asarray(state.partner)
        weight = np.asarray(state.weight)
        out = []
        for u in np.nonzero(partner >= 0)[0]:
            v = partner[u]
            if u < v:
                out.append((int(u), int(v), float(weight[u])))
        return out

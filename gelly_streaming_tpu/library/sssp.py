"""Windowed single-source shortest paths over sliced edge streams.

Not present in the reference library (SURVEY.md §2.1); with windowed
PageRank this completes the classic snapshot-analytics pair.  Per closed
window the pane's subgraph relaxes as a dense scatter-min Bellman–Ford:

    dist = min(dist, scatter_min(dst, dist[src] + w))

under ``lax.while_loop`` until a fixed point (or the V-1 iteration bound) —
fixed shapes, no per-vertex Python, one compiled step reused across panes.
Edge values are the weights (valueless streams relax hop counts); negative
weights are rejected (min-plus relaxation's usual contract on streams).
``slide_ms`` composes through the shared pane dispatch
(core/windows.windowed_panes).
"""

from __future__ import annotations

from functools import partial
from typing import Iterator, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from gelly_streaming_tpu.core.output import OutputStream, RecordBlock
from gelly_streaming_tpu.core.windows import pad_pane_edges, windowed_panes

_INF = jnp.float32(jnp.inf)


@partial(jax.jit, static_argnames=("capacity",))
def _pane_sssp(src, dst, w, mask, source, capacity, max_iters):
    """Distances [C] from ``source`` over one pane's (padded) edge list."""
    dist0 = jnp.full((capacity,), _INF).at[source].set(0.0)
    big = jnp.float32(3.4e38)  # inf-safe stand-in inside the scatter

    def body(state):
        dist, _, it = state
        cand = jnp.where(mask, jnp.where(jnp.isinf(dist[src]), big, dist[src]) + w, big)
        relaxed = jnp.full((capacity,), big).at[dst].min(cand)
        new = jnp.minimum(dist, jnp.where(relaxed >= big, _INF, relaxed))
        return new, jnp.any(new < dist), it + 1

    def cond(state):
        _, changed, it = state
        return changed & (it < max_iters)

    dist, _, iters = jax.lax.while_loop(
        cond, body, (dist0, jnp.bool_(True), 0)
    )
    return dist, iters


def sssp_windows(
    stream,
    source: int,
    window_ms: int,
    slide_ms: Optional[int] = None,
    max_iters: Optional[int] = None,
) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    """(vertex ids [V], distances [V]) per window, reached vertices only."""
    cfg = stream.cfg
    if not 0 <= source < cfg.vertex_capacity:
        # an out-of-range source would be silently dropped by the jit
        # scatter and read as "nothing reachable"
        raise ValueError(
            f"source {source} outside [0, {cfg.vertex_capacity})"
        )
    for pane in windowed_panes(stream, window_ms, slide_ms):
        e = pane.num_edges
        if e == 0:
            continue
        src, dst, msk = pad_pane_edges(pane)
        e_pad = len(src)
        if pane.val is not None:
            leaves = jax.tree.leaves(pane.val)
            wts = np.asarray(leaves[0], np.float32)
            if (wts < 0).any():
                raise ValueError("sssp requires non-negative edge weights")
            w = np.zeros((e_pad,), np.float32)
            w[:e] = wts
        else:
            w = np.ones((e_pad,), np.float32)  # hop counts
        iters = max_iters if max_iters is not None else cfg.vertex_capacity - 1
        dist, _ = _pane_sssp(
            jnp.asarray(src),
            jnp.asarray(dst),
            jnp.asarray(w),
            jnp.asarray(msk),
            jnp.int32(source),
            cfg.vertex_capacity,
            jnp.int32(iters),
        )
        d = np.asarray(dist)
        vids = np.nonzero(np.isfinite(d))[0]
        yield vids, d[vids]


def windowed_sssp(
    stream,
    source: int,
    window_ms: int,
    slide_ms: Optional[int] = None,
    max_iters: Optional[int] = None,
) -> OutputStream:
    """(vertex, distance) records per closed window (tumbling or sliding).

    Directionality is as-given (relaxation follows src -> dst); pre-apply
    ``stream.undirected()`` for symmetric distances.  Unreached vertices
    emit nothing.
    """

    def blocks() -> Iterator[RecordBlock]:
        for vids, dists in sssp_windows(
            stream, source, window_ms, slide_ms, max_iters
        ):
            yield RecordBlock((vids.astype(np.int64), dists))

    return OutputStream(blocks_fn=blocks)

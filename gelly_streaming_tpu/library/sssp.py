"""Windowed single-source shortest paths over sliced edge streams.

Not present in the reference library (SURVEY.md §2.1); with windowed
PageRank this completes the classic snapshot-analytics pair.  Per closed
window the pane relaxes on the kernel core's min-plus semiring
(ops/spmv.py): ``dist = min(dist, A^T dist)`` under direction-optimized
push/pull fixpoint iteration — sparse frontiers expand through bucketed
SpMSpV, dense phases take the flat segment-reduce SpMV, and the emitted
distances are bit-identical in every direction mode (tests/test_spmv.py).
Edge values are the weights (valueless streams relax hop counts); negative
weights are rejected (min-plus relaxation's usual contract on streams).
``slide_ms`` composes through the shared pane dispatch
(core/windows.windowed_panes).
"""

from __future__ import annotations

from typing import Iterator, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from gelly_streaming_tpu.core.output import OutputStream, RecordBlock
from gelly_streaming_tpu.core.windows import pad_pane_edges, windowed_panes
from gelly_streaming_tpu.ops import spmv

_BIG = np.float32(spmv.MIN_PLUS.identity)  # unreached sentinel


def sssp_windows(
    stream,
    source: int,
    window_ms: int,
    slide_ms: Optional[int] = None,
    max_iters: Optional[int] = None,
) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    """(vertex ids [V], distances [V]) per window, reached vertices only.

    ``max_iters`` bounds the relaxation rounds: the default (capacity - 1)
    always converges to exact shortest paths; a smaller value computes
    BOUNDED-HOP distances — shortest paths using at most ``max_iters``
    relaxation rounds, with farther vertices reported unreached (the same
    bounded semantics as the spanner's boundedBFS)."""
    cfg = stream.cfg
    if not 0 <= source < cfg.vertex_capacity:
        # an out-of-range source would be silently dropped by the jit
        # scatter and read as "nothing reachable"
        raise ValueError(
            f"source {source} outside [0, {cfg.vertex_capacity})"
        )
    direction = spmv.resolve_direction(cfg)
    threshold = spmv.resolve_threshold(cfg)
    for pane in windowed_panes(stream, window_ms, slide_ms):
        e = pane.num_edges
        if e == 0:
            continue
        src, dst, msk = pad_pane_edges(pane)
        e_pad = len(src)
        if pane.val is not None:
            leaves = jax.tree.leaves(pane.val)
            if len(leaves) != 1 or np.ndim(leaves[0]) != 1:
                # a multi-leaf value has no unambiguous weight — refuse
                # loudly (same contract as distinct's _value_bits)
                raise ValueError(
                    "sssp needs a single scalar edge value as the weight; "
                    f"got a {len(leaves)}-leaf value pytree"
                )
            wts = np.asarray(leaves[0], np.float32)
            if (wts < 0).any():
                raise ValueError("sssp requires non-negative edge weights")
            w = np.zeros((e_pad,), np.float32)
            w[:e] = wts
        else:
            w = None  # hop counts (unit weights)
        iters = max_iters if max_iters is not None else cfg.vertex_capacity - 1
        op = spmv.prepare_pane(src, dst, w, msk, cfg.vertex_capacity)
        dist0 = jnp.full(
            (cfg.vertex_capacity,), _BIG, jnp.float32
        ).at[source].set(0.0)
        res = spmv.fixpoint(
            spmv.MIN_PLUS,
            op,
            dist0,
            max_iters=iters,
            direction=direction,
            threshold=threshold,
        )
        d = np.asarray(res.x)
        vids = np.nonzero(d < 1e30)[0]
        yield vids, d[vids]


def windowed_sssp(
    stream,
    source: int,
    window_ms: int,
    slide_ms: Optional[int] = None,
    max_iters: Optional[int] = None,
) -> OutputStream:
    """(vertex, distance) records per closed window (tumbling or sliding).

    Directionality is as-given (relaxation follows src -> dst); pre-apply
    ``stream.undirected()`` for symmetric distances.  Unreached vertices
    emit nothing; with a user ``max_iters`` below the window's path depth
    that includes vertices farther than the bound (bounded-hop semantics,
    see sssp_windows).
    """

    def blocks() -> Iterator[RecordBlock]:
        for vids, dists in sssp_windows(
            stream, source, window_ms, slide_ms, max_iters
        ):
            yield RecordBlock((vids.astype(np.int64), dists))

    return OutputStream(blocks_fn=blocks)

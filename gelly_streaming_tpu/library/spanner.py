"""Continuous k-spanner (library/Spanner.java:40-118).

Reference semantics: per edge, run a k-bounded BFS between the endpoints on the
current spanner; admit the edge only if the distance exceeds k (:71-77).  The
combine re-inserts the smaller spanner's edges into the larger under the same
test (:92-116).

TPU-native admission is TWO-PHASE (VERDICT r2 weak #3 replaced the per-edge
scan whose body ran a dense [C, D] BFS per edge):

1. **Vectorized pre-filter.**  Distances only shrink as edges are admitted,
   so any edge already within k of the PRE-batch spanner is rejected no
   matter what the batch admits before it.  The whole batch is tested at
   once via meet-in-the-middle neighborhood balls (radius ceil(k/2) from u,
   k - ceil(k/2) from v, truncated at a cap): balls intersect <=> dist <= k.
   Truncation can only miss a rejection (sound) — never falsely reject.
2. **Sequential resolution over survivors only.**  Candidates compact to the
   front (arrival order preserved) and a ``lax.while_loop`` with a DYNAMIC
   trip count runs the exact dense BFS + insert per candidate — after
   warm-up almost every edge dies in phase 1, so the sequential tail is
   typically a tiny fraction of the batch.

The final spanner is IDENTICAL to the fully sequential fold: phase 1 only
removes edges whose sequential outcome was already determined.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from gelly_streaming_tpu.core.aggregation import SummaryBulkAggregation
from gelly_streaming_tpu.core.config import StreamConfig
from gelly_streaming_tpu.summaries import adjacency
from gelly_streaming_tpu.summaries.adjacency import AdjacencyListGraph


class SpannerState(NamedTuple):
    nbrs: jax.Array  # int32[C, D]
    deg: jax.Array  # int32[C]


# ball expansion is shared with the exact distance tests:
# summaries/adjacency.expand_balls (one implementation, cannot drift)
_balls = adjacency.expand_balls


def auto_body(capacity: int, max_degree: int, k: int) -> str:
    """The per-candidate distance body ``body="auto"`` runs for (k, C, D):
    "within_two" (k=2 O(D^2) row intersection), "balls" (exact
    meet-in-the-middle, cost independent of C), or "bfs" (dense k*C*D
    sweep).  Single source of truth for the crossover — ``_admit_batch``
    executes it and ``measurements spanner`` calibrates it."""
    if k == 2:
        return "within_two"
    if adjacency.ball_cost(max_degree, k) < k * capacity * max_degree:
        return "balls"
    return "bfs"


def _within_k_prefilter(nbrs, src, dst, k: int, cap: int, chunk: int = 256):
    """bool[B]: True only where dist(src, dst) <= k on ``nbrs`` for sure."""
    b = src.shape[0]
    w = min(chunk, b)
    pad = (-b) % w
    if pad:
        src = jnp.concatenate([src, jnp.zeros((pad,), src.dtype)])
        dst = jnp.concatenate([dst, jnp.zeros((pad,), dst.dtype)])
    a = (k + 1) // 2

    def one_chunk(uv):
        u, v = uv
        ball_u = _balls(nbrs, u, a, cap)
        ball_v = _balls(nbrs, v, k - a, cap)
        hit = (
            (ball_u[:, :, None] == ball_v[:, None, :])
            & (ball_u >= 0)[:, :, None]
            & (ball_v >= 0)[:, None, :]
        )
        return jnp.any(hit, axis=(1, 2))

    within = jax.lax.map(
        one_chunk, (src.reshape(-1, w), dst.reshape(-1, w))
    ).reshape(-1)
    return within[:b]


def _admit_batch(nbrs, deg, src, dst, mask, k: int, cap: int,
                 body_kind: str = "auto"):
    """Two-phase spanner admission; returns the updated (nbrs, deg).

    ``body_kind`` selects the per-candidate exact distance test: "auto"
    picks by the analytical ``ball_cost`` crossover; "balls"/"bfs" force one
    body (every body is exact, so the admitted spanner is identical — the
    forced modes exist for the calibration measurement,
    ``measurements spanner --body both``).
    """
    b = src.shape[0]
    within_pre = _within_k_prefilter(nbrs, src, dst, k, cap)
    cand = mask & ~within_pre
    m = jnp.sum(cand.astype(jnp.int32))
    # stable compaction: candidates first, arrival order preserved
    idx = jnp.arange(b, dtype=jnp.int32)
    order = jnp.argsort(jnp.where(cand, idx, b + idx))
    cu = jnp.maximum(src[order], 0)
    cv = jnp.maximum(dst[order], 0)

    def cond(carry):
        return carry[0] < m

    # per-candidate distance test: pick the cheapest EXACT form for this
    # (k, C, D) via the shared ``auto_body`` crossover.  k=2 gets the O(D^2)
    # row intersection; k>=3 uses exact meet-in-the-middle balls (cost
    # independent of C) when their sort-based intersection beats the dense
    # BFS's k*C*D sweep — the capacity-independence that lets the admission
    # tail scale to reference-size graphs (VERDICT r3 weak #5)
    capacity, max_degree = nbrs.shape
    picked = (
        auto_body(capacity, max_degree, k)
        if body_kind == "auto"
        else body_kind
    )

    def body(carry):
        i, nbrs, deg = carry
        u, v = cu[i], cv[i]
        if picked == "within_two":
            within = adjacency.within_two(nbrs, u, v)
        elif picked == "balls":
            within = adjacency.within_k_balls(nbrs, u, v, k)
        else:
            within = adjacency.bounded_bfs(nbrs, u, v, k)
        nbrs, deg = adjacency.add_undirected_edge(
            nbrs, deg, u, v, enabled=~within
        )
        return i + 1, nbrs, deg

    _, nbrs, deg = jax.lax.while_loop(
        cond, body, (jnp.zeros((), jnp.int32), nbrs, deg)
    )
    return nbrs, deg


class Spanner(SummaryBulkAggregation):
    """aggregate(Spanner(window_ms, k)) -> stream of AdjacencyListGraph views.

    ``filter_cap`` bounds the phase-1 ball width; caps at least
    ``max_degree + 1`` keep the k=2 filter exact (a ball of radius 1 is the
    vertex plus its full neighbor row).
    """

    def __init__(self, window_ms: int, k: int, filter_cap: int = 128,
                 body: str = "auto"):
        super().__init__(window_ms)
        if body not in ("auto", "balls", "bfs"):
            raise ValueError(f"body must be auto/balls/bfs, got {body!r}")
        self.k = k
        self.filter_cap = filter_cap
        self.body = body

    def initial_state(self, cfg: StreamConfig) -> SpannerState:
        nbrs, deg = adjacency.init_table(cfg.vertex_capacity, cfg.max_degree)
        return SpannerState(nbrs, deg)

    def update(self, state: SpannerState, src, dst, val, mask) -> SpannerState:
        nbrs, deg = _admit_batch(
            state.nbrs, state.deg, src, dst, mask, self.k, self.filter_cap,
            self.body,
        )
        return SpannerState(nbrs, deg)

    def combine(self, a: SpannerState, b: SpannerState) -> SpannerState:
        """Re-insert the smaller spanner's edges into the larger
        (CombineSpanners, Spanner.java:92-116).  Edges of the smaller are
        enumerated as canonical (v, nbr) slot pairs of its table and admitted
        through the same two-phase batch path as the fold."""
        k, cap = self.k, self.filter_cap
        size_a = jnp.sum((a.deg > 0).astype(jnp.int32))
        size_b = jnp.sum((b.deg > 0).astype(jnp.int32))

        def merge(big: SpannerState, small: SpannerState) -> SpannerState:
            capacity, max_degree = small.nbrs.shape
            vs = jnp.repeat(jnp.arange(capacity, dtype=jnp.int32), max_degree)
            ns = small.nbrs.reshape(-1)
            slot_ok = (ns >= 0) & (vs < ns)  # canonical: insert each edge once
            nbrs, deg = _admit_batch(
                big.nbrs, big.deg, vs, jnp.maximum(ns, 0), slot_ok, k, cap,
                self.body,
            )
            return SpannerState(nbrs, deg)

        return jax.lax.cond(
            size_a >= size_b, lambda: merge(a, b), lambda: merge(b, a)
        )

    def transform(self, state: SpannerState) -> AdjacencyListGraph:
        return AdjacencyListGraph.from_state(state.nbrs, state.deg)

"""Single-pass streaming graph algorithms (reference library/ + example/ algorithms)."""

from gelly_streaming_tpu.library.bipartiteness import BipartitenessCheck
from gelly_streaming_tpu.library.connected_components import (
    BlockShardedCC,
    ConnectedComponents,
    ConnectedComponentsTree,
    block_sharded_cc_fixpoint,
    sharded_cc_fixpoint,
    sharded_cc_round,
    unshard_labels,
)
from gelly_streaming_tpu.library.degree_distribution import DegreeDistribution
from gelly_streaming_tpu.library.graphsage import (
    GraphSAGEWindows,
    SageParams,
    SageTrainState,
    sage_init_train,
    sage_train_step,
    sage_train_step_mesh,
    sample_pairs,
)
from gelly_streaming_tpu.library.iterative_cc import IterativeConnectedComponents
from gelly_streaming_tpu.library.matching import CentralizedWeightedMatching
from gelly_streaming_tpu.library.kcore import core_numbers_windows, windowed_kcore
from gelly_streaming_tpu.library.pagerank import pagerank_windows, windowed_pagerank
from gelly_streaming_tpu.library.sssp import sssp_windows, windowed_sssp
from gelly_streaming_tpu.library.incidence_sampling import (
    IncidenceRouter,
    MeshSampledTriangleCount,
)
from gelly_streaming_tpu.library.sampled_triangles import (
    BroadcastTriangleCount,
    IncidenceSamplingTriangleCount,
)
from gelly_streaming_tpu.library.spanner import Spanner
from gelly_streaming_tpu.library.triangles import (
    ExactTriangleCount,
    pipelined_pane_counts,
    window_triangles,
)

__all__ = [
    "BipartitenessCheck",
    "BlockShardedCC",
    "ConnectedComponents",
    "ConnectedComponentsTree",
    "block_sharded_cc_fixpoint",
    "unshard_labels",
    "sharded_cc_fixpoint",
    "sharded_cc_round",
    "DegreeDistribution",
    "GraphSAGEWindows",
    "SageParams",
    "SageTrainState",
    "sage_init_train",
    "sage_train_step",
    "sage_train_step_mesh",
    "sample_pairs",
    "IterativeConnectedComponents",
    "CentralizedWeightedMatching",
    "core_numbers_windows",
    "windowed_kcore",
    "pagerank_windows",
    "windowed_pagerank",
    "sssp_windows",
    "windowed_sssp",
    "BroadcastTriangleCount",
    "IncidenceSamplingTriangleCount",
    "IncidenceRouter",
    "MeshSampledTriangleCount",
    "Spanner",
    "ExactTriangleCount",
    "pipelined_pane_counts",
    "window_triangles",
]

"""Streaming Connected Components (bulk, tree, and sharded-mesh variants).

Reference: library/ConnectedComponents.java:41-124 — a
``SummaryBulkAggregation<K, EV, DisjointSet, DisjointSet>`` whose per-edge fold
is ``DisjointSet.union(src, trg)`` (:83-86) and whose combine merges the smaller
set into the larger (:116-124); library/ConnectedComponentsTree.java:26-36 is
the same over SummaryTreeReduce.  Here the summary is the dense
(parent, seen) array pair and both fold and combine are the batched union-find
kernel (ops/unionfind.py) — order-free, so bulk and tree strategies share it.

``sharded_cc_step`` is the multi-chip data plane: labels are replicated per
shard, edges are sharded, and rounds of {local batched union, pmin label
exchange over ICI, compress} run to a global fixed point — the TPU-native
replacement for the keyBy-fold + timeWindowAll-reduce pipeline
(SummaryBulkAggregation.java:76-83).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from gelly_streaming_tpu.core.aggregation import (
    SummaryBulkAggregation,
    SummaryTreeAggregation,
)
from gelly_streaming_tpu.core.config import StreamConfig
from gelly_streaming_tpu.core.output import OutputStream
from gelly_streaming_tpu.core.sharded_state import ShardedStateSpec
from gelly_streaming_tpu.ops import unionfind as uf
from gelly_streaming_tpu.parallel.mesh import SHARD_AXIS
from gelly_streaming_tpu.summaries.disjoint_set import DisjointSet


class CCState(NamedTuple):
    parent: jax.Array  # int32[C]
    seen: jax.Array  # bool[C]


class _CCMixin:
    """Shared descriptor hooks for both combine strategies."""

    # the union fold reaches the same partition whatever the edge order, so
    # CC may ride the sorted EF40 multiset wire encoding
    order_free = True

    @property
    def cache_token(self):
        # update/combine/initial_state are pure functions of (class, cfg):
        # re-created descriptors (one per stream/window/bench chunk) share
        # compiled executables instead of retracing
        return type(self)

    def initial_state(self, cfg: StreamConfig) -> CCState:
        return CCState(
            parent=uf.init_parent(cfg.vertex_capacity),
            seen=jnp.zeros((cfg.vertex_capacity,), bool),
        )

    def update(self, state: CCState, src, dst, val, mask) -> CCState:
        # UpdateCC.foldEdges == ds.union(src, trg) (ConnectedComponents.java:83-86)
        parent, seen = uf.union_edges_with_seen(
            state.parent, state.seen, src, dst, mask
        )
        return CCState(parent, seen)

    def combine(self, a: CCState, b: CCState) -> CCState:
        # CombineCC.reduce == DisjointSet.merge (ConnectedComponents.java:116-124)
        return CCState(
            parent=uf.merge_parents(a.parent, b.parent),
            seen=a.seen | b.seen,
        )

    def transform(self, state: CCState) -> DisjointSet:
        return DisjointSet(
            capacity=int(state.parent.shape[0]),
            parent=state.parent,
            seen=state.seen,
        )

    def mesh_combine_states(self, cfg: StreamConfig, axis_name: str):
        """Collective cross-shard combine: pmin-round fixpoint, not
        gather-and-merge — see ``collective_parent_seen_combine``."""

        def combine(state: CCState, has_data) -> CCState:
            return CCState(
                *collective_parent_seen_combine(
                    state.parent, state.seen, axis_name
                )
            )

        return combine

    def sharded_state_spec(self, cfg: StreamConfig):
        """Owner-sharded summary state (ISSUE 4): O(C/S) label/seen blocks
        per shard, root-delta exchanges, lazy emission gather."""
        return CCShardedState(self)


class ConnectedComponents(_CCMixin, SummaryBulkAggregation):
    """Flat-combine streaming CC (library/ConnectedComponents.java:41-56)."""


class ConnectedComponentsTree(_CCMixin, SummaryTreeAggregation):
    """Tree-combine streaming CC (library/ConnectedComponentsTree.java:26-36)."""


# ---------------------------------------------------------------------------
# Owner-sharded summary state (core/sharded_state.py protocol)
# ---------------------------------------------------------------------------


class CCBlocks(NamedTuple):
    """One shard's owner block of the CC summary: O(C/S) rows."""

    label: jax.Array  # int32[C/S] — parent-pointer forest rows this shard owns
    seen: jax.Array  # bool[C/S]


class CCShardedState(ShardedStateSpec):
    """Block-sharded streaming CC state with root-delta reconciliation.

    Persistent state per shard is vertex g's forest row at (g % S, g // S) —
    the quadrant-B BlockShardedCC ownership, generalized behind the
    SummaryAggregation protocol.  Edges fold locally (arrival placement,
    ring-free and skew-immune — no keyBy shuffle); reconciliation exchanges
    ONLY the remapped OLD-ROOT rows since the last exchange:

      per round: gather the block forest (the one sanctioned full-view
      collective per round), compress, apply the local partial's constraints
      as union edges, and ship (old root -> new min) pairs to their owners
      through fixed-capacity pow2-bucketed delta buffers
      (routing.exchange_slab_deltas) — min-folded into the owner rows.

    Hooking OLD ROOT ROWS ONLY keeps every non-root pointer chain intact
    (the Shiloach-Vishkin discipline ``block_sharded_cc_round`` documents),
    so the delta is bounded by merges-since-last-exchange, not component
    sizes, and capacity spills simply re-derive next round (the spilled row
    still reads as a remapped root against the re-gathered forest).  The
    loop ends when no old root remaps anywhere (pmax) — at that point every
    local constraint is intra-component in the block forest, the same fixed
    point as the replicated combine, so the gathered emission is
    bit-identical to the oracle's min labels.
    """

    route_key = None  # edges stay where they arrive: labels travel, not edges

    # -- host-side hooks ------------------------------------------------------

    def initial_shard_state(self, cfg, num_shards: int):
        from gelly_streaming_tpu.parallel.mesh import block_rows

        return CCBlocks(
            label=init_label_blocks(cfg.vertex_capacity, num_shards),
            seen=np.zeros(
                (num_shards, block_rows(cfg.vertex_capacity, num_shards)), bool
            ),
        )

    def shard_summary(self, summary, cfg, num_shards: int):
        """CCState([C], [C]) -> [S, C/S] blocks (restore seeding)."""
        parent = np.asarray(summary["parent"] if isinstance(summary, dict) else summary.parent)
        seen = np.asarray(summary["seen"] if isinstance(summary, dict) else summary.seen)
        return CCBlocks(
            label=np.ascontiguousarray(parent.reshape(-1, num_shards).T),
            seen=np.ascontiguousarray(seen.reshape(-1, num_shards).T),
        )

    def delta_bound(self, cfg, n_edges: int) -> int:
        # one merge (one remapped root) per union; both endpoints' seen rows
        return 2 * max(int(n_edges), 1)

    @staticmethod
    def _dense(cfg, ctx) -> bool:
        """True when the exchange interval can touch most of the state.

        The delta buffers only compress when the changed set is genuinely
        smaller than the state: once ``delta_capacity`` clamps at the
        structural C/S maximum, packed (row, value) buffers cost MORE than
        shipping whole slabs, and the root-delta formulation converges in
        more rounds than full-slab min propagation — so the saturated
        regime exchanges dense slabs (still O(C) per shard per round, 1/S
        of the replicated plane's O(C*S)), and the incremental regime
        (windowed panes, frequent snapshots) rides the delta buffers.
        """
        return ctx.delta_cap >= cfg.vertex_capacity // ctx.num_shards

    def comm_profile(self, cfg, ctx) -> dict:
        from gelly_streaming_tpu.parallel import routing

        c = cfg.vertex_capacity
        if self._dense(cfg, ctx):
            # per round: label-block gather + full-slab proposal swap;
            # emission adds the label + seen reassembly + one seen slab swap
            return {
                "round_nbytes": 2 * routing.gather_blocks_nbytes(c, 4),
                "gather_nbytes": routing.gather_blocks_nbytes(c, 4)
                + 2 * routing.gather_blocks_nbytes(c, 1),
            }
        return {
            # per exchange round: label-block gather + one delta buffer swap
            "round_nbytes": routing.gather_blocks_nbytes(c, 4)
            + routing.delta_exchange_nbytes(ctx.num_shards, ctx.delta_cap, 4),
            # per emission/snapshot: label + seen full-view reassembly, plus
            # the one-shot seen delta swap
            "gather_nbytes": routing.gather_blocks_nbytes(c, 4)
            + routing.gather_blocks_nbytes(c, 1)
            + routing.delta_exchange_nbytes(ctx.num_shards, ctx.delta_cap, 4),
        }

    # -- traced hooks (inside shard_map) --------------------------------------

    def _exchange_dense(self, local_state, blocks, ctx):
        """Saturated-regime exchange: full-slab min propagation.

        Per round every shard merges its local constraints into the
        gathered forest and proposes whole per-owner slabs; owners keep the
        elementwise min.  Each proposal array is a total compressed closure,
        so one shard's proposal can never fragment a component, and
        cross-shard disagreements reconverge through the next round's
        re-derived closures (the validated proto of the ISSUE-4 plane) —
        fewer rounds than root-deltas when nearly every row changed.
        """
        from gelly_streaming_tpu.core.sharded_state import ExchangeStats
        from gelly_streaming_tpu.parallel import routing

        n, axis = ctx.num_shards, ctx.axis_name
        v = jnp.arange(local_state.parent.shape[0], dtype=jnp.int32)
        local_p = local_state.parent
        zero = jnp.zeros((), jnp.int32)

        def cond(c):
            return c[1]

        def body(c):
            blk, _, rounds, hwm = c
            full = routing.gather_blocks(blk, n, axis)  # gather-ok: exchange reconciliation round (emit/snapshot boundary)
            p2 = uf.union_edges(full, v, local_p)
            occ = jnp.max(
                jnp.sum((p2 != full).reshape(-1, n).astype(jnp.int32), axis=0)
            )
            recv = routing.slab_exchange(p2, n, axis)
            blk2 = jnp.minimum(blk, jnp.min(recv, axis=0))
            again = jax.lax.pmax(jnp.any(blk2 != blk), axis)
            return blk2, again, rounds + 1, jnp.maximum(hwm, occ)

        label, _, rounds, hwm = jax.lax.while_loop(
            cond, body, (blocks.label, jnp.asarray(True), zero, zero)
        )
        seen_recv = routing.slab_exchange(
            local_state.seen.astype(jnp.int32), n, axis
        )
        seen_blk = blocks.seen | jnp.any(seen_recv.astype(bool), axis=0)
        return CCBlocks(label=label, seen=seen_blk), ExchangeStats(
            rounds=rounds, delta_hwm=hwm, spilled=zero
        )

    def exchange(self, local_state, blocks, ctx):
        from gelly_streaming_tpu.core.sharded_state import ExchangeStats
        from gelly_streaming_tpu.parallel import routing

        if self._dense(ctx.cfg, ctx):
            return self._exchange_dense(local_state, blocks, ctx)
        n, axis, cap = ctx.num_shards, ctx.axis_name, ctx.delta_cap
        v = jnp.arange(local_state.parent.shape[0], dtype=jnp.int32)
        local_p = local_state.parent

        def cond(c):
            return c[1]

        def body(c):
            blk, _, rounds, hwm, spills = c
            full = routing.gather_blocks(blk, n, axis)  # gather-ok: exchange reconciliation round (emit/snapshot boundary)
            base = uf.compress(full)
            p2 = uf.union_edges(base, v, local_p)
            # the delta: OLD ROOT rows that remapped — bounded by merges
            # since the last exchange, never by component sizes
            changed = (base == v) & (p2 != v)
            recv_rows, recv_vals, _, occ, sp = routing.exchange_slab_deltas(
                changed, p2, n, cap, axis, fill=jnp.iinfo(jnp.int32).max
            )
            blk2 = routing.apply_block_deltas(
                blk, recv_rows, recv_vals, "min", jnp.iinfo(jnp.int32).max
            )
            again = jax.lax.pmax(jnp.any(changed), axis)
            return (
                blk2,
                again,
                rounds + 1,
                jnp.maximum(hwm, occ),
                spills + sp,
            )

        zero = jnp.zeros((), jnp.int32)
        label, _, rounds, hwm, spills = jax.lax.while_loop(
            cond, body, (blocks.label, jnp.asarray(True), zero, zero, zero)
        )

        # seen: one retried delta pass (op=max == or); rows are distinct, so
        # per-owner demand <= min(C/S, touched) and spills only defer
        seen_full = routing.gather_blocks(blocks.seen, n, axis)  # gather-ok: exchange reconciliation round (emit/snapshot boundary)

        def seen_cond(c):
            return jax.lax.pmax(jnp.any(c[1]), axis)

        def seen_body(c):
            sb, pending, rounds2, hwm2 = c
            recv_rows, recv_vals, sent, occ, _ = routing.exchange_slab_deltas(
                pending, pending.astype(jnp.int32), n, cap, axis, fill=0
            )
            sb2 = routing.apply_block_deltas(
                sb.astype(jnp.int32), recv_rows, recv_vals, "max", 0
            ).astype(bool)
            return sb2, pending & ~sent, rounds2 + 1, jnp.maximum(hwm2, occ)

        seen_blk, _, _seen_rounds, seen_hwm = jax.lax.while_loop(
            seen_cond,
            seen_body,
            (blocks.seen, local_state.seen & ~seen_full, zero, zero),
        )
        # rounds meters LABEL rounds only: comm accounting multiplies it by
        # round_nbytes (gather + delta swap), which seen passes don't pay —
        # their single expected swap is in gather_nbytes, and spill retries
        # beyond it are rare enough that bytes stay a tight lower bound
        stats = ExchangeStats(
            rounds=rounds,
            delta_hwm=jnp.maximum(hwm, seen_hwm),
            spilled=spills,
        )
        return CCBlocks(label=label, seen=seen_blk), stats

    def gather_state(self, blocks, ctx):
        from gelly_streaming_tpu.parallel import routing

        full = routing.gather_blocks(blocks.label, ctx.num_shards, ctx.axis_name)  # gather-ok: emit — lazy replicated view at emission/snapshot boundaries
        seen = routing.gather_blocks(blocks.seen, ctx.num_shards, ctx.axis_name)  # gather-ok: emit — lazy replicated view at emission/snapshot boundaries
        # fully compress so the emitted labels are the oracle's min labels
        return CCState(parent=uf.compress(full), seen=seen)


# ---------------------------------------------------------------------------
# Sharded mesh data plane
# ---------------------------------------------------------------------------


def collective_parent_seen_combine(parent, seen, axis_name: str):
    """Combine per-shard (parent, seen) union-find partials with mesh
    collectives: the shared recipe behind CC's and bipartiteness'
    ``mesh_combine_states``.

    Each shard's partial parent array encodes its local equivalences as
    pointer constraints (v ~ parent[v]).  Iterating {apply own constraints,
    pmin labels over the mesh axis, compress} converges to the transitive
    closure of the union of all shards' relations — the same fixed point as
    folding the S partials through DisjointSet.merge-style combines
    (ConnectedComponents.java:116-124), but with log-depth ICI collectives
    instead of an all_gather plus S-1 sequential pointer-doubling merges
    (VERDICT r3 weak #2).  ``seen`` is a plain elementwise union -> one pmax.
    Both callers' initial states are combine identities (identity parent,
    all-False seen), so empty shards need no masking.
    """
    v = jnp.arange(parent.shape[0], dtype=jnp.int32)
    combined = sharded_cc_fixpoint(parent, v, parent, None, axis_name)
    seen_all = jax.lax.pmax(seen.astype(jnp.int32), axis_name).astype(bool)
    return combined, seen_all


def block_sharded_cc_round(
    label_local, src, dst, mask, num_shards: int, axis_name: str = SHARD_AXIS
):
    """One round on BLOCK-DISTRIBUTED labels (O(C/S) state per shard).

    ``label_local``: [C/S] this shard's label rows (vertex g on shard g % S
    at row g // S; labels are global vertex ids, label[g] <= g).  Edges stay
    WHEREVER THEY ARRIVED — no keyBy shuffle, no orientation doubling, no
    skew sensitivity: both endpoints' labels arrive via a ring lookup, each
    edge relaxes both endpoints toward the min, and the updates fold into
    their owner blocks through ``ring_scatter_min`` as the blocks loop the
    mesh.  No shard ever holds the full [C] table (the fix for VERDICT r2
    missing #4; Flink's keyed state is likewise partitioned per subtask,
    never replicated, SimpleEdgeStream.java:119).

    The round: lookup both endpoint labels (ring pass 1), HOOK — scatter
    each edge's smaller root into its larger root's row,
    ``label[max(ru, rv)] <- min(ru, rv)`` (ring pass 2) — then
    pointer-halve every local row (label <- label[label], ring pass 3),
    the lazy compression that propagates merges to vertices no new edge
    touches.

    Hooking ROOT rows only (never the endpoints) is load-bearing for
    MULTI-PANE streams: writing a new minimum straight into an endpoint's
    row would sever that endpoint's pointer to its previous root — e.g.
    with label[1002]=222 from an earlier pane, edges (1002,128) and
    (222,50) folding in one round would drop 1002 to 128 and 222 to 50,
    losing the 1002->222 witness that ties {128,1002} to 50's component.
    The Shiloach-Vishkin-style root hook keeps 1002->222 intact (only
    row 222, then row 128 via later rounds, takes new minima), and halving
    re-compresses endpoints afterwards.  Labels are non-increasing and
    every written value is a label from the same component, so the
    fixpoint loop below stays sound and terminating; at a halving-stable
    fixpoint every label is a self-fixed root, so an unmergeable hook
    (l[max] <= min with l[max] = max) forces equal endpoint roots.
    """
    from gelly_streaming_tpu.parallel.ring import ring_lookup, ring_scatter_min

    big = jnp.iinfo(jnp.int32).max
    e = src.shape[0]
    q = jnp.concatenate([src, dst])
    m2 = jnp.concatenate([mask, mask])
    labels = ring_lookup(label_local, jnp.where(m2, q, 0), num_shards, axis_name)
    ru, rv = labels[:e], labels[e:]
    lo = jnp.minimum(ru, rv)
    hi = jnp.maximum(ru, rv)
    label_local = ring_scatter_min(
        label_local,
        jnp.where(mask, hi, 0),
        jnp.where(mask, lo, big),
        num_shards,
        axis_name,
    )
    # pointer halving: label values are global ids, so their current labels
    # live on their owners — one more ring pass compresses every local row
    return ring_lookup(label_local, label_local, num_shards, axis_name)


def block_sharded_cc_fixpoint(
    label_local, src, dst, mask, num_shards: int, axis_name: str = SHARD_AXIS
):
    """Iterate block-sharded rounds until no label changes on any shard.

    Labels are non-increasing and integer-bounded, so the loop terminates;
    each round relaxes BOTH endpoints of every edge toward the pair minimum,
    so at the fixed point every edge has equal endpoint labels and halving
    has fully compressed the pointer forest — every vertex carries its
    component's minimum id, directly comparable to a host union-find's
    min-root labels.  Edges may live on any shard in any orientation.
    """

    def cond(carry):
        return carry[1]

    def body(carry):
        l, _ = carry
        l2 = block_sharded_cc_round(l, src, dst, mask, num_shards, axis_name)
        changed = jax.lax.pmax(jnp.any(l2 != l), axis_name)
        return l2, changed

    l, _ = jax.lax.while_loop(cond, body, (label_local, jnp.asarray(True)))
    return l


def sharded_cc_round(parent, src, dst, mask, axis_name: str = SHARD_AXIS):
    """One mesh round: local batched union, label exchange, compress.

    Call inside shard_map with ``parent`` replicated per shard ([C] each) and
    (src, dst, mask) holding this shard's edges.  Iterate to fixed point via
    ``sharded_cc_fixpoint`` or a caller-managed loop.
    """
    p = uf.union_edges(parent, src, dst, mask)
    p = jax.lax.pmin(p, axis_name)
    return uf.compress(p)


def init_label_blocks(capacity: int, num_shards: int) -> np.ndarray:
    """[S, C/S] modulo-ownership label blocks, each vertex labeled itself."""
    if capacity % num_shards:
        raise ValueError(
            f"vertex capacity {capacity} must divide over {num_shards} shards"
        )
    return np.arange(capacity, dtype=np.int32).reshape(-1, num_shards).T.copy()


def unshard_labels(blocks) -> np.ndarray:
    """[S, C/S] modulo blocks -> [C] labels (labels[v] = blocks[v%S, v//S])."""
    return np.asarray(blocks).T.reshape(-1)


class BlockShardedCC:
    """Streaming CC whose label state is BLOCK-DISTRIBUTED over the mesh.

    The replicated ``sharded_cc_fixpoint`` holds the full [C] parent table on
    every device — per-chip memory O(C), which caps the vertex scale a mesh
    can hold (VERDICT r2 missing #4).  Here shard s holds only its [C/S]
    block (vertex g at (g % S, g // S)); edges split EVENLY over the shards
    with no keyBy shuffle at all (the ring passes inside
    ``block_sharded_cc_fixpoint`` move labels to the edges instead of edges
    to their keys' owners — skew-immune by construction, SURVEY §7's
    hot-shard hard part).  O(C/S + E/S) memory per shard.  The reference's
    analog: Flink keyed state is partitioned per subtask and scales out the
    same way (SimpleEdgeStream.java:119, SummaryBulkAggregation.java:78).

    ``run(stream)`` yields the device-resident [S, C/S] label blocks per
    closed pane (no host gather on the hot path — ``unshard_labels`` converts
    when a host view is wanted).  Labels are component minima, so they match
    a host union-find's min-root labels exactly.
    """

    def __init__(self, window_ms: Optional[int] = None, mesh=None):
        from gelly_streaming_tpu.parallel import mesh as mesh_mod

        self.mesh = mesh if mesh is not None else mesh_mod.make_mesh()
        self.window_ms = window_ms
        self._step_cache = {}

    @property
    def num_shards(self) -> int:
        return self.mesh.devices.size

    def _step(self, cap: int):
        if cap in self._step_cache:
            return self._step_cache[cap]
        from jax.sharding import PartitionSpec as P

        from gelly_streaming_tpu.parallel.mesh import shard_map

        n = self.num_shards

        def step(label_blocks, src, dst, mask):
            lab = block_sharded_cc_fixpoint(
                label_blocks[0], src[0], dst[0], mask[0], n
            )
            return lab[None]

        spec = P(SHARD_AXIS)
        fn = jax.jit(  # graft: disable=RAWJIT — keyed per (mesh, cap) in self._step_cache; a Mesh is not a stable process-global cache key
            shard_map(
                step,
                mesh=self.mesh,
                in_specs=(spec, spec, spec, spec),
                out_specs=spec,
            )
        )
        self._step_cache[cap] = fn
        return fn

    def _split_pane(self, src: np.ndarray, dst: np.ndarray):
        """Even round-robin split to [S, cap] — no keyBy, any orientation.

        Element i lands at [i % S, i // S], which is one pad + reshape."""
        n = self.num_shards
        total = len(src)
        per = -(-max(total, 1) // n)
        cap = max(1, 1 << (per - 1).bit_length())

        def split(a):
            return np.pad(a, (0, n * cap - total)).reshape(cap, n).T

        m = (np.arange(n * cap) < total).reshape(cap, n).T
        return split(src), split(dst), np.ascontiguousarray(m)

    def _checkpoint_like(self, cfg):
        return {
            "labels": init_label_blocks(cfg.vertex_capacity, self.num_shards),
            "last_window": np.full((), -1, np.int64),
            "global_done": np.zeros((), bool),
        }

    def run(
        self,
        stream,
        panes=None,
        checkpoint_path: Optional[str] = None,
        restore: bool = True,
    ) -> OutputStream:
        """One [S, C/S] label-block record per closed pane.

        ``panes``: optional zero-arg callable returning a WindowPane iterator
        (e.g. multi-host gated windows via
        ``parallel.multihost.merge_pane_shares``), overriding the stream's
        own tumbling assignment — same contract as
        ``MeshAggregationRunner.run``.

        With ``checkpoint_path`` the label blocks + stream position snapshot
        after every pane (the Merger's positional-checkpoint semantics —
        the same skip-by-window-id / emit-before-snapshot protocol as
        ``SummaryAggregation._merge_loop``, which remains the reference
        implementation of these semantics): on restore the source replays
        from the start, already-folded panes are skipped by window id, state
        is exactly-once and emissions at-least-once — labels only ever
        decrease, so a replayed fold is also idempotent by construction.

        Snapshot layout scales with the mesh topology: a single-process
        mesh downloads the full [S, C/S] table (int32: 4 bytes/vertex per
        pane close); a MULTI-PROCESS mesh saves per process — each host
        writes only its ADDRESSABLE shard rows to
        ``{checkpoint_path}.proc{K}`` (the orbax-style per-host shard save
        the reference's repartitioning TODO never built,
        SummaryAggregation.java:121-135), so no host ever materializes
        another host's blocks.  Restore requires the same process-to-shard
        topology; every process must hold a consistent snapshot (same
        position) or all start fresh together (agreement via one
        process_allgather round).
        """
        from gelly_streaming_tpu.core.windows import stream_panes

        cfg = stream.cfg
        if checkpoint_path and cfg.ingest_window_ms:
            raise ValueError(
                "wall-clock ingestion panes (ingest_window_ms) are not "
                "replay-deterministic; use ingest_window_edges for "
                "checkpointed runs"
            )
        n = self.num_shards
        window_ms = self.window_ms or cfg.window_ms

        def records():
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P

            sharding = NamedSharding(self.mesh, P(SHARD_AXIS))
            multi = jax.process_count() > 1
            start_after = -1
            global_done = False
            label = None
            if checkpoint_path and restore:
                if multi:
                    label, start_after, global_done = self._restore_per_process(
                        cfg, checkpoint_path, sharding
                    )
                else:
                    from gelly_streaming_tpu.utils.checkpoint import (
                        checkpoint_exists,
                        load_state,
                    )

                    if checkpoint_exists(checkpoint_path):
                        try:
                            snap = load_state(
                                checkpoint_path, self._checkpoint_like(cfg)
                            )
                        except ValueError:
                            snap = None  # mismatched/legacy: start fresh
                        if snap is not None:
                            label = jax.device_put(
                                np.asarray(snap["labels"]), sharding
                            )
                            start_after = int(snap["last_window"])
                            global_done = bool(snap["global_done"])
            # block-distributed from the first byte: the [S, C/S] table goes
            # straight to its owners (committing it to one device first would
            # reintroduce the O(C)-per-chip footprint this class removes)
            if label is None:
                label = jax.device_put(
                    init_label_blocks(cfg.vertex_capacity, n), sharding
                )
            pane_iter = (
                panes() if panes is not None else stream_panes(stream, window_ms)
            )
            for pane in pane_iter:
                already = (0 <= pane.window_id <= start_after) or (
                    pane.window_id == -1 and global_done
                )
                if already or len(pane.src) == 0:
                    continue
                s, d, m = self._split_pane(
                    pane.src.astype(np.int32), pane.dst.astype(np.int32)
                )
                step = self._step(s.shape[1])
                label = step(
                    label, jnp.asarray(s), jnp.asarray(d), jnp.asarray(m)
                )
                # emit BEFORE snapshotting: a crash between the two re-emits
                # this pane on recovery instead of dropping it
                yield (label,)
                start_after = max(pane.window_id, start_after)
                global_done = global_done or pane.window_id == -1
                if checkpoint_path:
                    if multi:
                        self._save_per_process(
                            checkpoint_path, label, start_after, global_done
                        )
                    else:
                        from gelly_streaming_tpu.utils.checkpoint import (
                            save_state,
                        )

                        save_state(
                            checkpoint_path,
                            {
                                "labels": np.asarray(label),
                                "last_window": np.full(
                                    (), start_after, np.int64
                                ),
                                "global_done": np.full((), global_done, bool),
                            },
                        )

        return OutputStream(records)

    @staticmethod
    def _proc_file(checkpoint_path: str) -> str:
        from gelly_streaming_tpu.utils.checkpoint import per_process_file

        return per_process_file(checkpoint_path)

    def _save_per_process(
        self, checkpoint_path: str, label, start_after: int, global_done: bool
    ) -> None:
        """Each process saves ONLY its addressable shard rows (+ position)."""
        from gelly_streaming_tpu.utils.checkpoint import save_state

        shards = sorted(label.addressable_shards, key=lambda s: s.index[0].start)
        rows = np.array([s.index[0].start for s in shards], np.int64)
        blocks = np.stack([np.asarray(s.data)[0] for s in shards])
        save_state(
            self._proc_file(checkpoint_path),
            {
                "rows": rows,
                "blocks": blocks,
                "last_window": np.full((), start_after, np.int64),
                "global_done": np.full((), global_done, bool),
            },
        )

    def _restore_per_process(self, cfg, checkpoint_path: str, sharding):
        """Rebuild the sharded label table from per-process snapshots.

        Every process loads only its own file; validity (file present,
        layout ok, rows matching this process's addressable shards) and the
        stream position must AGREE across processes — one
        ``process_allgather`` round decides; any inconsistency means all
        start fresh together (a split restore would deadlock the lockstep
        fold).  Returns (label | None, start_after, global_done).
        """
        from jax.experimental import multihost_utils

        from gelly_streaming_tpu.utils.checkpoint import (
            checkpoint_exists,
            load_state,
        )

        n = self.num_shards
        block = cfg.vertex_capacity // n
        snap = None
        path = self._proc_file(checkpoint_path)
        # this process's addressable shard count fixes the snapshot shapes
        # (load_state validates layout exactly)
        k = sum(
            1
            for d in self.mesh.devices.flat
            if d.process_index == jax.process_index()
        )
        if checkpoint_exists(path):
            try:
                like = {
                    "rows": np.zeros((k,), np.int64),
                    "blocks": np.zeros((k, block), np.int32),
                    "last_window": np.zeros((), np.int64),
                    "global_done": np.zeros((), bool),
                }
                snap = load_state(path, like)
            except ValueError:
                snap = None
        # rows this process NOW owns under the mesh (row r lives on device r)
        own_rows = {
            r
            for r, d in enumerate(self.mesh.devices.flat)
            if d.process_index == jax.process_index()
        }
        ok = snap is not None and set(
            int(r) for r in snap["rows"]
        ) == own_rows
        pos = int(snap["last_window"]) if ok else -1
        done = bool(snap["global_done"]) if ok else False
        # rows_match participates in the agreement: a topology change must
        # fail on EVERY process (a split restore — one process raising while
        # the others enter the pane fold — would deadlock the first ring
        # collective)
        agree = multihost_utils.process_allgather(
            np.array([int(ok), pos, int(done)], np.int64)
        )
        if not (
            agree[:, 0].all()
            and (agree[:, 1] == agree[0, 1]).all()
            and (agree[:, 2] == agree[0, 2]).all()
        ):
            return None, -1, False
        row_to_block = {
            int(r): snap["blocks"][i] for i, r in enumerate(snap["rows"])
        }

        def cb(index):
            row = index[0].start or 0
            blk = row_to_block.get(int(row))
            if blk is None:
                raise ValueError(
                    f"per-process snapshot {path} holds rows "
                    f"{sorted(row_to_block)} but this process now owns row "
                    f"{row}: restore requires the same process-to-shard "
                    "topology the snapshot was written under"
                )
            return blk[None]

        label = jax.make_array_from_callback((n, block), sharding, cb)
        return label, int(agree[0, 1]), bool(agree[0, 2])


def sharded_cc_fixpoint(parent, src, dst, mask, axis_name: str = SHARD_AXIS):
    """Iterate sharded rounds until no label changes on any shard.

    Correctness: at the fixed point every shard's labels satisfy its local edge
    constraints and are pmin-stable across shards, so labels are globally
    consistent with the union of all shards' edges — the same fixed point the
    reference reaches via fold + timeWindowAll reduce (order-free min labels).
    """

    def cond(carry):
        _, changed = carry
        return changed

    def body(carry):
        p, _ = carry
        p2 = sharded_cc_round(p, src, dst, mask, axis_name)
        local_changed = jnp.any(p2 != p)
        changed = jax.lax.pmax(local_changed, axis_name)
        return p2, changed

    p, _ = jax.lax.while_loop(cond, body, (parent, jnp.asarray(True)))
    return p

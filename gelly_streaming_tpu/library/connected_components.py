"""Streaming Connected Components (bulk, tree, and sharded-mesh variants).

Reference: library/ConnectedComponents.java:41-124 — a
``SummaryBulkAggregation<K, EV, DisjointSet, DisjointSet>`` whose per-edge fold
is ``DisjointSet.union(src, trg)`` (:83-86) and whose combine merges the smaller
set into the larger (:116-124); library/ConnectedComponentsTree.java:26-36 is
the same over SummaryTreeReduce.  Here the summary is the dense
(parent, seen) array pair and both fold and combine are the batched union-find
kernel (ops/unionfind.py) — order-free, so bulk and tree strategies share it.

``sharded_cc_step`` is the multi-chip data plane: labels are replicated per
shard, edges are sharded, and rounds of {local batched union, pmin label
exchange over ICI, compress} run to a global fixed point — the TPU-native
replacement for the keyBy-fold + timeWindowAll-reduce pipeline
(SummaryBulkAggregation.java:76-83).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp

from gelly_streaming_tpu.core.aggregation import (
    SummaryBulkAggregation,
    SummaryTreeAggregation,
)
from gelly_streaming_tpu.core.config import StreamConfig
from gelly_streaming_tpu.ops import unionfind as uf
from gelly_streaming_tpu.parallel.mesh import SHARD_AXIS
from gelly_streaming_tpu.summaries.disjoint_set import DisjointSet


class CCState(NamedTuple):
    parent: jax.Array  # int32[C]
    seen: jax.Array  # bool[C]


class _CCMixin:
    """Shared descriptor hooks for both combine strategies."""

    # the union fold reaches the same partition whatever the edge order, so
    # CC may ride the sorted EF40 multiset wire encoding
    order_free = True

    def initial_state(self, cfg: StreamConfig) -> CCState:
        return CCState(
            parent=uf.init_parent(cfg.vertex_capacity),
            seen=jnp.zeros((cfg.vertex_capacity,), bool),
        )

    def update(self, state: CCState, src, dst, val, mask) -> CCState:
        # UpdateCC.foldEdges == ds.union(src, trg) (ConnectedComponents.java:83-86)
        parent, seen = uf.union_edges_with_seen(
            state.parent, state.seen, src, dst, mask
        )
        return CCState(parent, seen)

    def combine(self, a: CCState, b: CCState) -> CCState:
        # CombineCC.reduce == DisjointSet.merge (ConnectedComponents.java:116-124)
        return CCState(
            parent=uf.merge_parents(a.parent, b.parent),
            seen=a.seen | b.seen,
        )

    def transform(self, state: CCState) -> DisjointSet:
        return DisjointSet(
            capacity=int(state.parent.shape[0]),
            parent=state.parent,
            seen=state.seen,
        )


class ConnectedComponents(_CCMixin, SummaryBulkAggregation):
    """Flat-combine streaming CC (library/ConnectedComponents.java:41-56)."""


class ConnectedComponentsTree(_CCMixin, SummaryTreeAggregation):
    """Tree-combine streaming CC (library/ConnectedComponentsTree.java:26-36)."""


# ---------------------------------------------------------------------------
# Sharded mesh data plane
# ---------------------------------------------------------------------------


def sharded_cc_round(parent, src, dst, mask, axis_name: str = SHARD_AXIS):
    """One mesh round: local batched union, label exchange, compress.

    Call inside shard_map with ``parent`` replicated per shard ([C] each) and
    (src, dst, mask) holding this shard's edges.  Iterate to fixed point via
    ``sharded_cc_fixpoint`` or a caller-managed loop.
    """
    p = uf.union_edges(parent, src, dst, mask)
    p = jax.lax.pmin(p, axis_name)
    return uf.compress(p)


def sharded_cc_fixpoint(parent, src, dst, mask, axis_name: str = SHARD_AXIS):
    """Iterate sharded rounds until no label changes on any shard.

    Correctness: at the fixed point every shard's labels satisfy its local edge
    constraints and are pmin-stable across shards, so labels are globally
    consistent with the union of all shards' edges — the same fixed point the
    reference reaches via fold + timeWindowAll reduce (order-free min labels).
    """

    def cond(carry):
        _, changed = carry
        return changed

    def body(carry):
        p, _ = carry
        p2 = sharded_cc_round(p, src, dst, mask, axis_name)
        local_changed = jnp.any(p2 != p)
        changed = jax.lax.pmax(local_changed, axis_name)
        return p2, changed

    p, _ = jax.lax.while_loop(cond, body, (parent, jnp.asarray(True)))
    return p

"""Windowed PageRank over sliced edge streams.

Not present in the reference (its library stops at CC / bipartiteness /
spanner / triangles / matching — `library/` in SURVEY.md §2.1); PageRank is
the canonical "snapshot analytics over a windowed graph stream" workload.
Each closed pane's subgraph becomes dense [C]-indexed arrays and the damped
power iteration runs on the kernel core's plus-times semiring
(ops/spmv.pagerank_fixpoint): the mass spread is a masked SpMV whose
direction — arrival-order scatter-add (push) or dst-stable-sorted segment
sum (pull) — is a traced ``lax.cond`` flag, bit-identical either way
(tests/test_spmv.py pins it).

Semantics per window (the standard damped random surfer restricted to the
pane's subgraph): vertices = endpoints present in the window; uniform
teleport over those vertices; dangling mass (window vertices with no
out-edge) redistributes uniformly; iterate until the L1 delta drops below
``tol`` or ``max_iters`` — both static shapes, so XLA compiles once per
(padded pane size, capacity).

``slide_ms`` composes: ``windowed_pagerank(stream, window_ms, slide_ms=...)``
ranks every sliding window via the shared pane-sharing dispatch
(core/windows.windowed_panes).
"""

from __future__ import annotations

from typing import Iterator, Optional, Tuple

import numpy as np

from gelly_streaming_tpu.core.output import OutputStream, RecordBlock
from gelly_streaming_tpu.core.windows import pad_pane_edges, windowed_panes
from gelly_streaming_tpu.ops import spmv


def windowed_pagerank(
    stream,
    window_ms: int,
    slide_ms: Optional[int] = None,
    damping: float = 0.85,
    tol: float = 1e-6,
    max_iters: int = 100,
) -> OutputStream:
    """(vertex, rank) records per closed window (tumbling or sliding).

    Ranks sum to ~1 within each window.  Direction is as-given (each edge
    src -> dst contributes out-mass from src); pre-apply
    ``stream.undirected()`` for symmetric ranking.
    """
    def blocks() -> Iterator[RecordBlock]:
        for vids, ranks in pagerank_windows(
            stream, window_ms, slide_ms,
            damping=damping, tol=tol, max_iters=max_iters,
        ):
            yield RecordBlock((vids.astype(np.int64), ranks))

    return OutputStream(blocks_fn=blocks)


def pagerank_windows(
    stream,
    window_ms: int,
    slide_ms: Optional[int] = None,
    damping: float = 0.85,
    tol: float = 1e-6,
    max_iters: int = 100,
) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    """(vertex ids [V], ranks [V]) arrays per window — the array-level view
    of ``windowed_pagerank`` for callers composing further device work."""
    cfg = stream.cfg
    # every iteration spreads all mass (no frontier), so direction is a
    # whole-run choice; auto keeps the arrival-order push scatter (the
    # historical bit-exact path — pull measures within noise here)
    use_pull = spmv.resolve_direction(cfg) == "pull"
    for pane in windowed_panes(stream, window_ms, slide_ms):
        if pane.num_edges == 0:
            continue
        src, dst, msk = pad_pane_edges(pane)
        op = spmv.prepare_pane(src, dst, None, msk, cfg.vertex_capacity)
        r, in_w, _ = spmv.pagerank_fixpoint(
            op, damping=damping, tol=tol, max_iters=max_iters,
            use_pull=use_pull,
        )
        r_h, in_h = np.asarray(r), np.asarray(in_w)
        vids = np.nonzero(in_h)[0]
        yield vids, r_h[vids]

"""Windowed PageRank over sliced edge streams.

Not present in the reference (its library stops at CC / bipartiteness /
spanner / triangles / matching — `library/` in SURVEY.md §2.1); PageRank is
the canonical "snapshot analytics over a windowed graph stream" workload and
maps cleanly onto the TPU design: each closed pane's subgraph becomes dense
[C]-indexed arrays and the power iteration is a fixed-shape
``segment_sum``-style scatter-add under ``lax.while_loop`` — no per-vertex
Python, no dynamic shapes, one compiled step reused across panes.

Semantics per window (the standard damped random surfer restricted to the
pane's subgraph): vertices = endpoints present in the window; uniform
teleport over those vertices; dangling mass (window vertices with no
out-edge) redistributes uniformly; iterate until the L1 delta drops below
``tol`` or ``max_iters`` — both static shapes, so XLA compiles once per
(padded pane size, capacity).

``slide_ms`` composes: ``windowed_pagerank(stream, window_ms, slide_ms=...)``
ranks every sliding window via the shared pane-sharing dispatch
(core/windows.windowed_panes).
"""

from __future__ import annotations

from functools import partial
from typing import Iterator, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from gelly_streaming_tpu.core.output import OutputStream, RecordBlock
from gelly_streaming_tpu.core.windows import pad_pane_edges, windowed_panes


@partial(jax.jit, static_argnames=("capacity",))
def _pane_pagerank(src, dst, mask, capacity, damping, tol, max_iters):
    """Ranks [C] for one pane's (padded) edge list; zeros off-window.

    src/dst: int32 [E_pad] (padding ignored via ``mask``).  The window's
    vertex set, out-degrees, dangling set, and the scatter-add transition
    are all dense [C] arrays — the same capacity-bounded layout every other
    summary in the framework uses.
    """
    zeros = jnp.zeros((capacity,), jnp.float32)
    ones = jnp.ones_like(zeros)
    m = mask.astype(jnp.float32)
    # window membership + out-degree (src side carries the out-edges)
    in_window = (
        zeros.at[src].max(m).at[dst].max(m) > 0
    )
    out_deg = zeros.at[src].add(m)
    n = jnp.maximum(jnp.sum(in_window.astype(jnp.float32)), 1.0)
    dangling = in_window & (out_deg == 0)
    base = jnp.where(in_window, (1.0 - damping) / n, 0.0)
    safe_deg = jnp.maximum(out_deg, 1.0)

    def body(state):
        r, _, it = state
        contrib = jnp.where(mask, r[src] / safe_deg[src], 0.0)
        spread = zeros.at[dst].add(contrib)
        dangling_mass = jnp.sum(jnp.where(dangling, r, 0.0)) / n
        r_new = base + damping * (
            spread + jnp.where(in_window, dangling_mass, 0.0)
        )
        delta = jnp.sum(jnp.abs(r_new - r))
        return r_new, delta, it + 1

    def cond(state):
        _, delta, it = state
        return (delta > tol) & (it < max_iters)

    r0 = jnp.where(in_window, ones / n, 0.0)
    r, _, iters = jax.lax.while_loop(cond, body, (r0, jnp.inf, 0))
    return r, in_window, iters


def windowed_pagerank(
    stream,
    window_ms: int,
    slide_ms: Optional[int] = None,
    damping: float = 0.85,
    tol: float = 1e-6,
    max_iters: int = 100,
) -> OutputStream:
    """(vertex, rank) records per closed window (tumbling or sliding).

    Ranks sum to ~1 within each window.  Direction is as-given (each edge
    src -> dst contributes out-mass from src); pre-apply
    ``stream.undirected()`` for symmetric ranking.
    """
    def blocks() -> Iterator[RecordBlock]:
        for vids, ranks in pagerank_windows(
            stream, window_ms, slide_ms,
            damping=damping, tol=tol, max_iters=max_iters,
        ):
            yield RecordBlock((vids.astype(np.int64), ranks))

    return OutputStream(blocks_fn=blocks)


def pagerank_windows(
    stream,
    window_ms: int,
    slide_ms: Optional[int] = None,
    damping: float = 0.85,
    tol: float = 1e-6,
    max_iters: int = 100,
) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    """(vertex ids [V], ranks [V]) arrays per window — the array-level view
    of ``windowed_pagerank`` for callers composing further device work."""
    cfg = stream.cfg
    for pane in windowed_panes(stream, window_ms, slide_ms):
        if pane.num_edges == 0:
            continue
        src, dst, msk = pad_pane_edges(pane)
        r, in_w, _ = _pane_pagerank(
            jnp.asarray(src),
            jnp.asarray(dst),
            jnp.asarray(msk),
            cfg.vertex_capacity,
            jnp.float32(damping),
            jnp.float32(tol),
            jnp.int32(max_iters),
        )
        r_h, in_h = np.asarray(r), np.asarray(in_w)
        vids = np.nonzero(in_h)[0]
        yield vids, r_h[vids]

"""Fully-dynamic degree distribution over add/delete edge events.

Reference: example/DegreeDistribution.java:54-132 — the repo's single
fully-dynamic algorithm, a 3-stage keyed pipeline: per edge emit a +/-1 change
for each endpoint (:70-79); a per-vertex stage tracks degrees and emits
(newDegree, +1) / (oldDegree, -1) deltas, removing vertices at degree 0
(:84-111); a per-degree stage keeps the histogram and emits (degree, count)
updates (:116-132).

TPU-native state: dense ``deg[C]`` and ``hist[C]`` arrays.  Each edge event
produces up to four (degree, count) records; a ``lax.scan`` preserves the
reference's per-event emission order (deletions of absent vertices are no-ops,
and transitions to degree 0 emit only the old-degree decrement).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from gelly_streaming_tpu.core import compile_cache
from gelly_streaming_tpu.core.aggregation import SummaryBulkAggregation
from gelly_streaming_tpu.core.config import StreamConfig
from gelly_streaming_tpu.core.output import OutputStream, RecordBlock
from gelly_streaming_tpu.core.sharded_state import ShardedStateSpec


class DegreeDistState(NamedTuple):
    deg: jax.Array  # int32[C]
    hist: jax.Array  # int32[C]  (#vertices with each nonzero degree)


def init_state(cfg: StreamConfig) -> DegreeDistState:
    return DegreeDistState(
        deg=jnp.zeros((cfg.vertex_capacity,), jnp.int32),
        hist=jnp.zeros((cfg.vertex_capacity,), jnp.int32),
    )


def degree_dist_update(state: DegreeDistState, src, dst, sign, mask):
    """Returns (state, records[B, 4, 2], rec_mask[B, 4]).

    Per event, slots are: [src new-degree update, src old-degree update,
    dst new-degree update, dst old-degree update] — each a (degree, count)
    histogram record, masked off when not emitted.
    """

    def vertex_change(deg, hist, v, delta, ok):
        old = deg[v]
        # deleting an absent vertex is a no-op (VertexDegreeCounts removes at 0)
        ok = ok & ~((delta < 0) & (old <= 0))
        new = jnp.maximum(old + delta, 0)
        deg = deg.at[v].set(jnp.where(ok, new, old))
        emit_new = ok & (new > 0)
        emit_old = ok & (old > 0)
        hist = hist.at[new].add(jnp.where(emit_new, 1, 0))
        rec_new = jnp.stack([new, hist[new]])
        hist = hist.at[old].add(jnp.where(emit_old, -1, 0))
        rec_old = jnp.stack([old, hist[old]])
        return deg, hist, rec_new, rec_old, emit_new, emit_old

    def step(carry, inp):
        deg, hist = carry
        u, v, sg, ok = inp
        delta = sg.astype(jnp.int32)
        deg, hist, ru_new, ru_old, mu_new, mu_old = vertex_change(
            deg, hist, u, delta, ok
        )
        deg, hist, rv_new, rv_old, mv_new, mv_old = vertex_change(
            deg, hist, v, delta, ok
        )
        recs = jnp.stack([ru_new, ru_old, rv_new, rv_old])
        rmask = jnp.stack([mu_new, mu_old, mv_new, mv_old])
        return (deg, hist), (recs, rmask)

    if sign is None:
        sign = jnp.ones(src.shape, jnp.int8)
    (deg, hist), (recs, rmask) = jax.lax.scan(
        step, (state.deg, state.hist), (src, dst, sign, mask)
    )
    return DegreeDistState(deg, hist), recs, rmask


# ---------------------------------------------------------------------------
# Windowed degree summary (SummaryAggregation form) — the second descriptor
# on the owner-sharded mesh plane (ISSUE 4).
#
# The event-sequenced DegreeDistribution below preserves the reference's
# per-record (degree, count) emission order and is inherently sequential; the
# summary form here is its windowed fold analog: state is the dense per-vertex
# degree vector deg[C], updateFun adds one per endpoint, combine is
# elementwise + (both associative AND satisfying the sharded-state contract
# combine(a, update(init, e)) == update(a, e)), transform emits the degree
# vector (``degree_histogram`` derives the (degree, count) view).


class DegreeSummaryState(NamedTuple):
    deg: jax.Array  # int32[C]


def degree_histogram(deg) -> dict:
    """{degree: vertex count} over vertices with nonzero degree."""
    d = np.asarray(deg)
    d = d[d > 0]
    vals, counts = np.unique(d, return_counts=True)
    return {int(v): int(c) for v, c in zip(vals, counts)}


class DegreeShardedState(ShardedStateSpec):
    """Owner-sharded degree state: O(C/S) deg blocks, additive delta exchange.

    The local fold accumulates degree DELTAS since the last exchange in a
    transient dense scratch; reconciliation ships only the nonzero rows —
    distinct block rows, so per-owner demand is structurally <= C/S and the
    pow2-bucketed buffers (routing.exchange_slab_deltas) spill only under
    extreme skew, where the retry loop drains them losslessly (sent rows are
    zeroed from the pending set; addition is order-free).  No gather is
    needed to reconcile — addition has no cross-row transitivity — so an
    exchange is exactly one delta swap per retry round: comms O(delta), the
    GraphBLAST frontier ideal.
    """

    route_key = "src"  # host keyBy on the pack thread localizes src updates

    def initial_shard_state(self, cfg, num_shards: int):
        from gelly_streaming_tpu.parallel.mesh import block_rows

        return DegreeBlocks(
            deg=np.zeros(
                (num_shards, block_rows(cfg.vertex_capacity, num_shards)),
                np.int32,
            )
        )

    def shard_summary(self, summary, cfg, num_shards: int):
        deg = np.asarray(summary["deg"] if isinstance(summary, dict) else summary.deg)
        return DegreeBlocks(deg=np.ascontiguousarray(deg.reshape(-1, num_shards).T))

    def delta_bound(self, cfg, n_edges: int) -> int:
        return 2 * max(int(n_edges), 1)

    @staticmethod
    def _dense(cfg, ctx) -> bool:
        """Once the delta capacity clamps at C/S, packed (row, value) pairs
        cost more than shipping whole slabs — exchange dense slabs there
        (one summed all_to_all, no retry loop)."""
        return ctx.delta_cap >= cfg.vertex_capacity // ctx.num_shards

    def comm_profile(self, cfg, ctx) -> dict:
        from gelly_streaming_tpu.parallel import routing

        if self._dense(cfg, ctx):
            return {
                "round_nbytes": routing.slab_exchange_nbytes(
                    cfg.vertex_capacity, 4
                ),
                "gather_nbytes": routing.gather_blocks_nbytes(
                    cfg.vertex_capacity, 4
                ),
            }
        return {
            "round_nbytes": routing.delta_exchange_nbytes(
                ctx.num_shards, ctx.delta_cap, 4
            ),
            "gather_nbytes": routing.gather_blocks_nbytes(
                cfg.vertex_capacity, 4
            ),
        }

    def exchange(self, local_state, blocks, ctx):
        from gelly_streaming_tpu.core.sharded_state import ExchangeStats
        from gelly_streaming_tpu.parallel import routing

        n, axis, cap = ctx.num_shards, ctx.axis_name, ctx.delta_cap
        local = local_state.deg
        if self._dense(ctx.cfg, ctx):
            recv = routing.slab_exchange(local, n, axis)
            occ = jnp.max(
                jnp.sum((local != 0).reshape(-1, n).astype(jnp.int32), axis=0)
            )
            one = jnp.ones((), jnp.int32)
            return DegreeBlocks(
                deg=blocks.deg + jnp.sum(recv, axis=0)
            ), ExchangeStats(rounds=one, delta_hwm=occ, spilled=one * 0)

        def cond(c):
            return jax.lax.pmax(jnp.any(c[1]), axis)

        def body(c):
            blk, pending, rounds, hwm, spills = c
            recv_rows, recv_vals, sent, occ, sp = routing.exchange_slab_deltas(
                pending, local, n, cap, axis, fill=0
            )
            blk2 = routing.apply_block_deltas(blk, recv_rows, recv_vals, "add", 0)
            return (
                blk2,
                pending & ~sent,
                rounds + 1,
                jnp.maximum(hwm, occ),
                spills + sp,
            )

        zero = jnp.zeros((), jnp.int32)
        blk, _, rounds, hwm, spills = jax.lax.while_loop(
            cond, body, (blocks.deg, local != 0, zero, zero, zero)
        )
        return DegreeBlocks(deg=blk), ExchangeStats(rounds, hwm, spills)

    def gather_state(self, blocks, ctx):
        from gelly_streaming_tpu.parallel import routing

        deg = routing.gather_blocks(blocks.deg, ctx.num_shards, ctx.axis_name)  # gather-ok: emit — lazy replicated view at emission/snapshot boundaries
        return DegreeSummaryState(deg=deg)


class DegreeBlocks(NamedTuple):
    deg: jax.Array  # int32[C/S] — this shard's owned degree rows


class DegreeDistributionSummary(SummaryBulkAggregation):
    """Dense per-vertex degree fold (the windowed summary form).

    updateFun adds 1 to each endpoint's degree; combine is elementwise +;
    transform emits the deg vector (see ``degree_histogram``).  Deletions
    (sign < 0 events) belong to the event-sequenced ``DegreeDistribution``
    below, which preserves per-record emission order — this summary is the
    add-only windowed analog the mesh plane aggregates.
    """

    # addition commutes: legal on the sorted EF40 multiset wire encoding
    order_free = True

    @property
    def cache_token(self):
        # pure function of (class, cfg): re-created descriptors share
        # compiled executables instead of retracing
        return type(self)

    def initial_state(self, cfg: StreamConfig) -> DegreeSummaryState:
        return DegreeSummaryState(
            deg=jnp.zeros((cfg.vertex_capacity,), jnp.int32)
        )

    def update(self, state, src, dst, val, mask) -> DegreeSummaryState:
        ones = jnp.where(mask, 1, 0).astype(jnp.int32)
        deg = state.deg.at[jnp.where(mask, src, 0)].add(ones)
        deg = deg.at[jnp.where(mask, dst, 0)].add(ones)
        return DegreeSummaryState(deg=deg)

    def combine(self, a, b) -> DegreeSummaryState:
        return DegreeSummaryState(deg=a.deg + b.deg)

    def transform(self, state):
        # emit the bare deg vector: a NamedTuple state would be splatted by
        # the tuple-emission convention (records yield ``out`` verbatim when
        # it is a tuple), so records are (deg,) either way — make it explicit
        return state.deg

    def sharded_state_spec(self, cfg: StreamConfig):
        return DegreeShardedState(self)


class DegreeDistribution:
    """Continuous (degree, count) histogram-update stream."""

    def __init__(self):
        # graftcheck RAWJIT fix: per-instance jax.jit retraced this kernel
        # for every fresh DegreeDistribution; the process-global cache
        # compiles it once and meters retraces
        self._kernel = compile_cache.cached_jit(
            ("degree_dist_update",), lambda: degree_dist_update
        )

    def run(self, stream) -> OutputStream:
        def blocks():
            state = init_state(stream.cfg)
            for batch in stream.batches():
                state, recs, rmask = self._kernel(
                    state, batch.src, batch.dst, batch.sign, batch.mask
                )
                # [B, 4, 2] per-edge record slots -> one compacted block per
                # micro-batch, flattened in the reference's emission order
                # (per edge: u-new, u-old, v-new, v-old)
                r_h = np.asarray(recs).reshape(-1, 2)
                m_h = np.asarray(rmask).reshape(-1)
                idx = np.nonzero(m_h)[0]
                if len(idx):
                    yield RecordBlock(
                        (r_h[idx, 0].astype(np.int64), r_h[idx, 1].astype(np.int64))
                    )
            self.final_state = state

        return OutputStream(blocks_fn=blocks)

"""Fully-dynamic degree distribution over add/delete edge events.

Reference: example/DegreeDistribution.java:54-132 — the repo's single
fully-dynamic algorithm, a 3-stage keyed pipeline: per edge emit a +/-1 change
for each endpoint (:70-79); a per-vertex stage tracks degrees and emits
(newDegree, +1) / (oldDegree, -1) deltas, removing vertices at degree 0
(:84-111); a per-degree stage keeps the histogram and emits (degree, count)
updates (:116-132).

TPU-native state: dense ``deg[C]`` and ``hist[C]`` arrays.  Each edge event
produces up to four (degree, count) records; a ``lax.scan`` preserves the
reference's per-event emission order (deletions of absent vertices are no-ops,
and transitions to degree 0 emit only the old-degree decrement).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from gelly_streaming_tpu.core import compile_cache
from gelly_streaming_tpu.core.config import StreamConfig
from gelly_streaming_tpu.core.output import OutputStream, RecordBlock


class DegreeDistState(NamedTuple):
    deg: jax.Array  # int32[C]
    hist: jax.Array  # int32[C]  (#vertices with each nonzero degree)


def init_state(cfg: StreamConfig) -> DegreeDistState:
    return DegreeDistState(
        deg=jnp.zeros((cfg.vertex_capacity,), jnp.int32),
        hist=jnp.zeros((cfg.vertex_capacity,), jnp.int32),
    )


def degree_dist_update(state: DegreeDistState, src, dst, sign, mask):
    """Returns (state, records[B, 4, 2], rec_mask[B, 4]).

    Per event, slots are: [src new-degree update, src old-degree update,
    dst new-degree update, dst old-degree update] — each a (degree, count)
    histogram record, masked off when not emitted.
    """

    def vertex_change(deg, hist, v, delta, ok):
        old = deg[v]
        # deleting an absent vertex is a no-op (VertexDegreeCounts removes at 0)
        ok = ok & ~((delta < 0) & (old <= 0))
        new = jnp.maximum(old + delta, 0)
        deg = deg.at[v].set(jnp.where(ok, new, old))
        emit_new = ok & (new > 0)
        emit_old = ok & (old > 0)
        hist = hist.at[new].add(jnp.where(emit_new, 1, 0))
        rec_new = jnp.stack([new, hist[new]])
        hist = hist.at[old].add(jnp.where(emit_old, -1, 0))
        rec_old = jnp.stack([old, hist[old]])
        return deg, hist, rec_new, rec_old, emit_new, emit_old

    def step(carry, inp):
        deg, hist = carry
        u, v, sg, ok = inp
        delta = sg.astype(jnp.int32)
        deg, hist, ru_new, ru_old, mu_new, mu_old = vertex_change(
            deg, hist, u, delta, ok
        )
        deg, hist, rv_new, rv_old, mv_new, mv_old = vertex_change(
            deg, hist, v, delta, ok
        )
        recs = jnp.stack([ru_new, ru_old, rv_new, rv_old])
        rmask = jnp.stack([mu_new, mu_old, mv_new, mv_old])
        return (deg, hist), (recs, rmask)

    if sign is None:
        sign = jnp.ones(src.shape, jnp.int8)
    (deg, hist), (recs, rmask) = jax.lax.scan(
        step, (state.deg, state.hist), (src, dst, sign, mask)
    )
    return DegreeDistState(deg, hist), recs, rmask


class DegreeDistribution:
    """Continuous (degree, count) histogram-update stream."""

    def __init__(self):
        # graftcheck RAWJIT fix: per-instance jax.jit retraced this kernel
        # for every fresh DegreeDistribution; the process-global cache
        # compiles it once and meters retraces
        self._kernel = compile_cache.cached_jit(
            ("degree_dist_update",), lambda: degree_dist_update
        )

    def run(self, stream) -> OutputStream:
        def blocks():
            state = init_state(stream.cfg)
            for batch in stream.batches():
                state, recs, rmask = self._kernel(
                    state, batch.src, batch.dst, batch.sign, batch.mask
                )
                # [B, 4, 2] per-edge record slots -> one compacted block per
                # micro-batch, flattened in the reference's emission order
                # (per edge: u-new, u-old, v-new, v-old)
                r_h = np.asarray(recs).reshape(-1, 2)
                m_h = np.asarray(rmask).reshape(-1)
                idx = np.nonzero(m_h)[0]
                if len(idx):
                    yield RecordBlock(
                        (r_h[idx, 0].astype(np.int64), r_h[idx, 1].astype(np.int64))
                    )
            self.final_state = state

        return OutputStream(blocks_fn=blocks)

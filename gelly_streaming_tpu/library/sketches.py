"""Sketch summary descriptors: (eps, delta)-bounded state in KB, not O(C) MB.

Three approximate summaries built on the order-free monoid kernels in
summaries/sketches.py, each an ORDINARY ``SummaryAggregation`` — they ride
every existing plane (windowed folds, wire streaming, the mesh runner, the
owner-sharded state plane, positional checkpoints, cross-tenant fused
dispatch) with zero new machinery:

  * ``SketchTriangleCount`` — streaming triangle estimate from an R-row
    min-hash edge sample + distinct-edge HLL (the order-free form of
    neighborhood sampling, arXiv:1308.2166).  Degrades to EXACT when the
    sample covers every distinct edge.
  * ``HLLDegreeSummary`` — distinct-vertex / distinct-edge cardinalities
    from two HLL register banks (max-merge).
  * ``CountMinHeavyHitters`` — top-k degree heavy hitters from a d x w
    count-min grid (add-merge), the heap materialized only at emission.

Every descriptor declares its ``(eps, delta)`` contract
(``error_contract()``: surfaced in server ``status`` and the metrics sketch
registry) and prices BOTH its persistent registers (``state_nbytes``) and
its transient emission-time scratch (``emission_scratch`` — top-k heap,
gathered register view, wedge matrices) so ``admission_nbytes`` is what a
thousand admitted sketch jobs actually cost.

All register shapes are pure functions of (eps, delta) through pow2 clamps,
so ``cache_token`` — (class, shape params) — makes same-contract tenants
share compiled executables and form perfect same-shape fused-dispatch
cohorts: 0 recompiles across sketch-width and tenancy drift.

Sharding: ``SketchShardedState`` block-shards every 1-D register leaf
modulo-S (the same ``reshape(-1, S).T`` owner layout as the vertex-keyed
specs) and reconciles with ONE dense slab all_to_all + the descriptor's own
commutative combine — registers are KB, so dense slabs beat packed deltas
at any realistic S, and merge commutativity makes sharded-vs-solo folds
bit-identical.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from gelly_streaming_tpu.core.aggregation import SummaryBulkAggregation
from gelly_streaming_tpu.core.config import StreamConfig
from gelly_streaming_tpu.core.sharded_state import (
    ExchangeStats,
    ShardedStateSpec,
)
from gelly_streaming_tpu.summaries import sketches as sk

#: the serving-plane catalog of sketch summary kinds
SKETCH_KINDS = ("sketch_triangles", "hll_degree", "cm_heavy_hitters")


class SketchParamError(ValueError):
    """Invalid (eps, delta) contract — raised at descriptor CONSTRUCTION so
    admission (gelly-serve / gelly-client submit / JobManager) refuses
    loudly with a typed error instead of folding garbage or hanging."""


def _check_eps_delta(eps: float, delta: float) -> tuple:
    try:
        eps = float(eps)
        delta = float(delta)
    except (TypeError, ValueError):
        raise SketchParamError(
            f"eps/delta must be numbers, got eps={eps!r} delta={delta!r}"
        )
    if not (0.0 < eps < 1.0):
        raise SketchParamError(f"eps must be in (0, 1), got {eps}")
    if not (0.0 < delta < 1.0):
        raise SketchParamError(f"delta must be in (0, 1), got {delta}")
    return eps, delta


class SketchShardedState(ShardedStateSpec):
    """Generic owner-sharded plane for 1-D pow2 register pytrees.

    Every sketch state leaf is 1-D with pow2 length, so each leaf
    block-shards modulo-S exactly like the vertex-keyed summaries (row g of
    a leaf lives on shard g % S at block row g // S) — which keeps
    row-coupled leaves (the min-hash sample's (hash, lo, hi) columns)
    co-resident, and keeps ``reshard_summary(..., rows="auto")`` a pure
    host reindex.  Reconciliation is ONE dense slab all_to_all per leaf
    plus an S-way fold of the descriptor's own commutative ``combine``:
    registers are KB-sized, so a dense slab costs less than the packed
    (row, value) delta machinery at any realistic S, and there is nothing
    to spill or retry — the exchange is exactly one round, always.
    """

    route_key = None  # registers are hash-addressed: no owner to route by

    def _leaf_sizes(self, cfg) -> list:
        return [
            (int(np.prod(leaf.shape)), np.dtype(leaf.dtype).itemsize)
            for leaf in jax.tree.leaves(
                jax.eval_shape(lambda: self.agg.initial_state(cfg))
            )
        ]

    def initial_shard_state(self, cfg, num_shards: int):
        return self.shard_summary(
            jax.tree.map(np.asarray, self.agg.initial_state(cfg)),
            cfg,
            num_shards,
        )

    def shard_summary(self, summary, cfg, num_shards: int):
        def block(a):
            a = np.asarray(a)
            if a.size % num_shards:
                raise ValueError(
                    f"sketch leaf of {a.size} rows cannot shard evenly "
                    f"over {num_shards} shards"
                )
            return np.ascontiguousarray(a.reshape(-1, num_shards).T)

        return jax.tree.map(block, summary)

    def delta_bound(self, cfg, n_edges: int) -> int:
        return 1  # dense slabs only: the delta buffers are never used

    def comm_profile(self, cfg, ctx) -> dict:
        from gelly_streaming_tpu.parallel import routing

        round_nbytes = sum(
            routing.slab_exchange_nbytes(size, itemsize)
            for size, itemsize in self._leaf_sizes(cfg)
        )
        gather_nbytes = sum(
            routing.gather_blocks_nbytes(size, itemsize)
            for size, itemsize in self._leaf_sizes(cfg)
        )
        return {"round_nbytes": round_nbytes, "gather_nbytes": gather_nbytes}

    def exchange(self, local_state, blocks, ctx):
        from gelly_streaming_tpu.parallel import routing

        n, axis = ctx.num_shards, ctx.axis_name
        # recv[leaf][s] = what peer s folded for the rows THIS shard owns
        recv = jax.tree.map(
            lambda a: routing.slab_exchange(a, n, axis), local_state
        )
        merged = blocks
        for s in range(n):
            merged = self.agg.combine(
                merged, jax.tree.map(lambda a: a[s], recv)
            )
        rows = max(size // n for size, _ in self._leaf_sizes(ctx.cfg))
        one = jnp.ones((), jnp.int32)
        return merged, ExchangeStats(
            rounds=one,
            delta_hwm=jnp.full((), rows, jnp.int32),
            spilled=one * 0,
        )

    def gather_state(self, blocks, ctx):
        from gelly_streaming_tpu.parallel import routing

        return jax.tree.map(
            lambda a: routing.gather_blocks(a, ctx.num_shards, ctx.axis_name),  # gather-ok: emit — registers reassemble lazily at emission/snapshot boundaries
            blocks,
        )


class _SketchSummary(SummaryBulkAggregation):
    """Shared sketch-descriptor surface: contract, pricing, sharding."""

    #: serving-plane kind string (SKETCH_KINDS); subclasses set it
    kind: str = ""
    # register folds commute: legal on the sorted EF40 multiset wire
    # encoding, and the precondition for the sharded/fused planes
    order_free = True

    def __init__(self, eps: float, delta: float, window_ms=None):
        super().__init__(window_ms)
        self.eps, self.delta = _check_eps_delta(eps, delta)

    def error_contract(self) -> dict:
        """The declared (eps, delta) bound, as surfaced in server status
        lines and the utils.metrics sketch registry."""
        return {"kind": self.kind, "eps": self.eps, "delta": self.delta}

    def sharded_state_spec(self, cfg: StreamConfig):
        return SketchShardedState(self)


class TriangleSketchState(NamedTuple):
    eh: jax.Array  # uint32[R]  per-bucket min sample-hash (EMPTY_HASH = none)
    elo: jax.Array  # int32[R]  sampled edge lo endpoint (-1 = none)
    ehi: jax.Array  # int32[R]  sampled edge hi endpoint (-1 = none)
    regs: jax.Array  # int32[M]  distinct-edge HLL registers


class SketchTriangleCount(_SketchSummary):
    """Streaming triangle estimate from R min-hash-sampled edges.

    Emits ``(estimate, sampled_rows, distinct_edges)`` per window.  The
    estimate scales the closed wedges found WITHIN the sample by the cube
    of the per-edge inclusion probability (occupied rows / distinct edges,
    the latter from the composed HLL bank) — see
    ``summaries.sketches.tri_estimate``.  When the stream's distinct edges
    fit the sample (p = 1) the estimate IS the exact count; the declared
    (eps, delta) otherwise assumes enough triangle mass for concentration
    (the regime the seeded zipf equivalence tests pin).
    """

    kind = "sketch_triangles"

    def __init__(self, eps=0.1, delta=0.05, window_ms=None):
        super().__init__(eps, delta, window_ms)
        self.rows = sk.tri_rows(self.eps, self.delta)
        self.hll_m = sk.hll_num_registers(max(self.eps / 2.0, 0.01))

    @property
    def cache_token(self):
        # pure function of (class, register shapes): same-contract tenants
        # share executables and fuse into one same-shape cohort
        return (type(self), self.rows, self.hll_m)

    def initial_state(self, cfg: StreamConfig) -> TriangleSketchState:
        eh, elo, ehi = sk.tri_init(self.rows)
        return TriangleSketchState(
            eh=eh, elo=elo, ehi=ehi, regs=sk.hll_init(self.hll_m)
        )

    def update(self, state, src, dst, val, mask) -> TriangleSketchState:
        eh, elo, ehi = sk.tri_fold(
            (state.eh, state.elo, state.ehi), src, dst, mask
        )
        lo, hi = sk.canonical_edge(src, dst)
        regs = sk.hll_fold(
            state.regs,
            sk.hash_pair_u32(lo, hi, sk.SALT_EDGE_HLL),
            mask & (lo != hi),
        )
        return TriangleSketchState(eh=eh, elo=elo, ehi=ehi, regs=regs)

    def combine(self, a, b) -> TriangleSketchState:
        eh, elo, ehi = sk.tri_merge(
            (a.eh, a.elo, a.ehi), (b.eh, b.elo, b.ehi)
        )
        return TriangleSketchState(
            eh=eh, elo=elo, ehi=ehi, regs=sk.hll_merge(a.regs, b.regs)
        )

    def transform(self, state):
        return sk.tri_estimate(
            (state.eh, state.elo, state.ehi), state.regs
        )

    def emission_scratch(self, cfg: StreamConfig):
        # the closure check's peak live set: one [BLOCK, R] wedge strip
        # (closing endpoints + membership keys, ~4 int32-equivalents live
        # at once) plus the sorted membership keys
        r = self.rows
        b = min(sk.TRI_CLOSURE_BLOCK, r)
        return (
            jax.ShapeDtypeStruct((b, r), jnp.int32),
            jax.ShapeDtypeStruct((b, r), jnp.int32),
            jax.ShapeDtypeStruct((b, r), jnp.int32),
            jax.ShapeDtypeStruct((b, r), jnp.uint32),
            jax.ShapeDtypeStruct((r,), jnp.uint32),
        )


class HLLDegreeState(NamedTuple):
    verts: jax.Array  # int32[M] distinct-vertex registers
    edges: jax.Array  # int32[M] distinct-edge registers


class HLLDegreeSummary(_SketchSummary):
    """Distinct-vertex / distinct-edge cardinalities (max-merge registers).

    Emits ``(distinct_vertices, distinct_edges)`` float32 estimates per
    window — the degree-cardinality view (how many vertices are live, how
    many distinct undirected edges touched them) at 2m registers instead of
    the exact summaries' O(C) rows.
    """

    kind = "hll_degree"

    def __init__(self, eps=0.05, delta=0.05, window_ms=None):
        super().__init__(eps, delta, window_ms)
        self.hll_m = sk.hll_num_registers(self.eps)

    @property
    def cache_token(self):
        return (type(self), self.hll_m)

    def initial_state(self, cfg: StreamConfig) -> HLLDegreeState:
        return HLLDegreeState(
            verts=sk.hll_init(self.hll_m), edges=sk.hll_init(self.hll_m)
        )

    def update(self, state, src, dst, val, mask) -> HLLDegreeState:
        verts = sk.hll_fold(
            state.verts, sk.hash_u32(src, sk.SALT_VERTEX_HLL), mask
        )
        verts = sk.hll_fold(
            verts, sk.hash_u32(dst, sk.SALT_VERTEX_HLL), mask
        )
        lo, hi = sk.canonical_edge(src, dst)
        edges = sk.hll_fold(
            state.edges, sk.hash_pair_u32(lo, hi, sk.SALT_EDGE_HLL), mask
        )
        return HLLDegreeState(verts=verts, edges=edges)

    def combine(self, a, b) -> HLLDegreeState:
        return HLLDegreeState(
            verts=sk.hll_merge(a.verts, b.verts),
            edges=sk.hll_merge(a.edges, b.edges),
        )

    def transform(self, state):
        return sk.hll_estimate(state.verts), sk.hll_estimate(state.edges)

    def emission_scratch(self, cfg: StreamConfig):
        # the sharded plane's gathered register view (transient full-[m]
        # reassembly of both banks at emission)
        m = self.hll_m
        return (
            jax.ShapeDtypeStruct((m,), jnp.int32),
            jax.ShapeDtypeStruct((m,), jnp.int32),
        )


class CountMinState(NamedTuple):
    grid: jax.Array  # int32[d * w] counter grid, stored flat


class CountMinHeavyHitters(_SketchSummary):
    """Top-k degree heavy hitters from a count-min grid (add-merge).

    Each edge increments both endpoints' degree counters in all d rows;
    ``transform`` materializes the per-vertex estimate view (min over rows,
    for every vertex id < capacity) and takes the top-k — the "heap" lives
    ONLY at emission time, which is exactly why ``emission_scratch`` must
    price the O(C) gathered view: the persistent grid is KB, the residue is
    not.  Emits ``(vertex_ids[k], degree_estimates[k])``.
    """

    kind = "cm_heavy_hitters"

    def __init__(self, eps=0.01, delta=0.02, top_k=16, window_ms=None):
        super().__init__(eps, delta, window_ms)
        self.top_k = int(top_k)
        if self.top_k <= 0:
            raise SketchParamError(
                f"top_k must be positive, got {self.top_k}"
            )
        self.depth, self.width = sk.cm_dims(self.eps, self.delta)
        # transform needs the candidate-id range; bound at initial_state
        # (always called before any fold/transform on every plane)
        self._capacity = None

    @property
    def cache_token(self):
        return (type(self), self.depth, self.width, self.top_k)

    def error_contract(self) -> dict:
        out = super().error_contract()
        out["top_k"] = self.top_k
        return out

    def initial_state(self, cfg: StreamConfig) -> CountMinState:
        self._capacity = cfg.vertex_capacity
        return CountMinState(grid=sk.cm_init(self.depth, self.width))

    def update(self, state, src, dst, val, mask) -> CountMinState:
        ones = jnp.ones(src.shape, jnp.int32)
        grid = sk.cm_fold(
            state.grid, self.depth, self.width, src, ones, mask
        )
        grid = sk.cm_fold(grid, self.depth, self.width, dst, ones, mask)
        return CountMinState(grid=grid)

    def combine(self, a, b) -> CountMinState:
        return CountMinState(grid=sk.cm_merge(a.grid, b.grid))

    def transform(self, state):
        if self._capacity is None:
            raise RuntimeError(
                "CountMinHeavyHitters.transform before initial_state: "
                "the candidate-id range is bound per StreamConfig"
            )
        ids = jnp.arange(self._capacity, dtype=jnp.int32)
        est = sk.cm_query(state.grid, self.depth, self.width, ids)
        vals, idx = jax.lax.top_k(est, min(self.top_k, self._capacity))
        return idx.astype(jnp.int32), vals

    def emission_scratch(self, cfg: StreamConfig):
        # the O(C) gathered estimate view the top-k scans — THE residue
        # that dwarfs the persistent grid and must be admission-priced
        return (
            jax.ShapeDtypeStruct((cfg.vertex_capacity,), jnp.int32),
            jax.ShapeDtypeStruct((self.top_k,), jnp.int32),
            jax.ShapeDtypeStruct((self.top_k,), jnp.int32),
        )


def make_sketch(kind: str, eps=None, delta=None, top_k=None, window_ms=None):
    """Serving-plane factory: a sketch descriptor from its catalog kind.

    Unknown kinds and malformed knobs raise ``SketchParamError`` — the
    typed refusal gelly-serve/gelly-client admission converts to a loud
    ``bad-spec`` error (never a hang, never a silently-exact fallback).
    """
    if kind not in SKETCH_KINDS:
        raise SketchParamError(
            f"unknown sketch kind {kind!r} (expected one of "
            f"{'/'.join(SKETCH_KINDS)})"
        )
    kwargs = {"window_ms": window_ms}
    if eps is not None:
        kwargs["eps"] = eps
    if delta is not None:
        kwargs["delta"] = delta
    if kind == "sketch_triangles":
        return SketchTriangleCount(**kwargs)
    if kind == "hll_degree":
        return HLLDegreeSummary(**kwargs)
    if top_k is not None:
        kwargs["top_k"] = top_k
    return CountMinHeavyHitters(**kwargs)

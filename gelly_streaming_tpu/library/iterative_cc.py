"""Iterative connected components: on-device label propagation.

Reference: example/IterativeConnectedComponents.java:45-167 — a Flink streaming
*feedback iteration*: emitted (vertex, component) records re-enter the keyed
flatMap (``edges.iterate()``/``closeWith`` :56-58), whose per-record state is a
linear-scanned ``HashMap<compId, HashSet<vertex>>`` (:79-114).

The feedback edge exists because a JVM dataflow can only propagate labels by
sending records around the loop.  On a TPU the loop collapses into the batched
union-find fixed point (``lax.while_loop`` + scatter-min — ops/unionfind.py),
run per micro-batch against persistent labels: strictly less communication and
the same converged labels (min component id).  This module emits the reference's
observable output — a continuous (vertex, componentId) stream re-emitting
affected vertices as merges happen.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from gelly_streaming_tpu.core.output import OutputStream, RecordBlock
from gelly_streaming_tpu.ops import spmv
from gelly_streaming_tpu.ops import unionfind as uf


class IterativeConnectedComponents:
    """Continuous (vertex, component) stream with on-device label propagation."""

    def __init__(self):
        # the min-min semiring fixpoint on the kernel core: hooking is a
        # masked scatter-min of labels, compression is pointer doubling —
        # one shared process-global executable (ops/spmv.cc_fixpoint),
        # array-identical to unionfind.union_edges_with_seen
        self._kernel = spmv.cc_fixpoint

    def run(self, stream) -> OutputStream:
        cfg = stream.cfg

        def blocks():
            parent = uf.init_parent(cfg.vertex_capacity)
            seen = jnp.zeros((cfg.vertex_capacity,), bool)
            prev = np.asarray(parent).copy()
            prev_seen = np.zeros((cfg.vertex_capacity,), bool)
            for batch in stream.batches():
                parent, seen = self._kernel(
                    parent, seen, batch.src, batch.dst, batch.mask
                )
                p_h, s_h = np.asarray(parent), np.asarray(seen)
                # Re-emit every vertex whose label or membership changed — the
                # observable effect of the reference's feedback re-emissions
                # (IterativeConnectedComponents.java:116-167) — as one
                # vectorized block per micro-batch.
                changed = (s_h & ~prev_seen) | (s_h & (p_h != prev))
                idx = np.nonzero(changed)[0]
                if len(idx):
                    yield RecordBlock((idx.astype(np.int64), p_h[idx].astype(np.int64)))
                prev, prev_seen = p_h, s_h
            self.final_labels = np.asarray(parent)

        return OutputStream(blocks_fn=blocks)

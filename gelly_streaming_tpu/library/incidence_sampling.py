"""Incidence-routed sampling triangle estimator on the device mesh.

Reference: example/IncidenceSamplingTriangleCount.java:39-242.  A
parallelism-1 ``EdgeSampleMapper`` (:61-122) tracks every sampler instance's
reservoir decisions with a seeded RNG (0xDEADBEEF :61) and routes each edge
ONLY to the (subtask, instance) samplers that care — because the instance
resamples it, or because it is incident to the instance's sampled wedge — as
``SampledEdge`` envelopes, keyed by subtask; ``TriangleSampleMapper``
(:125-203) applies them and a parallelism-1 ``TriangleSummer`` (:206-242)
recombines the estimate.  The routing is the point: the broadcast variant
ships every edge to every subtask, incidence ships a vanishing fraction.

TPU-native form:
  * the router is a host stage (the ingest plane owns the stream anyway);
    its per-edge randomness is derived from the edge's global index, so its
    decisions are reproducible and order-stable;
  * sampler lanes are SHARDED over the mesh (lane block per shard); a batch's
    envelopes are bucketed by owning shard on the host and applied on device
    in one ``shard_map`` step — vectorized segment ops, no per-envelope scan:
    a lane's flags reset at its last in-batch resample and set on any
    later hit;
  * broadcast mode uses the SAME router emitting an envelope for every
    (edge, lane) pair, so broadcast and incidence produce *identical*
    estimates by construction while shipping very different volumes — the
    mesh test asserts both, and ``comm_envelopes`` exposes the measured
    difference (the reference offers no such counter).

Envelopes are the reference's wire type: ``utils.value_types.SampledEdge``
(subtask, instance, edge, edgeCount, resample).
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from gelly_streaming_tpu.core.config import StreamConfig
from gelly_streaming_tpu.core.output import OutputStream
from gelly_streaming_tpu.parallel.mesh import SHARD_AXIS, make_mesh, shard_map
from gelly_streaming_tpu.utils.value_types import SampledEdge


_M64 = np.uint64(0xFFFFFFFFFFFFFFFF)


def _splitmix64(x: np.ndarray) -> np.ndarray:
    """Counter-based 64-bit mix (splitmix64 finalizer) over uint64 arrays."""
    with np.errstate(over="ignore"):  # uint64 wraparound is the algorithm
        x = (x + np.uint64(0x9E3779B97F4A7C15)) & _M64
        x = ((x ^ (x >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)) & _M64
        x = ((x ^ (x >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)) & _M64
        return x ^ (x >> np.uint64(31))


def _hashed_bits(seed: int, counters: np.ndarray, stream: int) -> np.ndarray:
    """Deterministic uint64 word per counter: hash(seed, stream, counter).

    Counter-based (no per-edge Generator construction) so a whole batch of
    (edge, lane) draws is one vectorized pass — the reference's seeded
    sequential RNG (IncidenceSamplingTriangleCount.java:61) made routing
    decisions reproducible; hashing the global edge index keeps that property
    while decoupling the draws from arrival batching.
    """
    base = _splitmix64(np.uint64(seed & 0xFFFFFFFFFFFFFFFF) + np.uint64(stream))
    return _splitmix64(base + counters.astype(np.uint64))


class IncidenceRouter:
    """Host central router: one envelope per (edge, interested lane).

    Mirrors EdgeSampleMapper (IncidenceSamplingTriangleCount.java:61-122):
    keeps every lane's (sampled edge, watched third vertex), flips the 1/i
    reservoir coin per lane per edge, and emits envelopes for lanes that
    resample the edge or whose watched wedge it closes.  ``broadcast=True``
    emits an envelope for every valid lane instead (the BroadcastTriangleCount
    topology) — same decisions, maximal shipping.

    The whole micro-batch routes in one vectorized pass: coin/third draws are
    counter-hashed per (edge, lane), and each lane's state at edge j is
    reconstructed from its last resample strictly before j (a prefix max),
    so no per-edge Python loop or per-edge RNG construction remains.
    """

    def __init__(
        self,
        num_samplers: int,
        capacity: int,
        seed: int = 0xDEADBEEF,
        broadcast: bool = False,
    ):
        self.num_samplers = num_samplers
        self.capacity = capacity
        self.seed = seed
        self.broadcast = broadcast
        # cap on m * num_samplers elements per vectorized routing pass; route
        # splits bigger batches into sequential chunks (tunable, and tests
        # shrink it to exercise the chunked path)
        self.chunk_elems = 1 << 21
        self.edge_tab = np.full((num_samplers, 2), -1, np.int64)
        self.third = np.full((num_samplers,), -1, np.int64)
        self.edges_seen = 0
        self.seen = np.zeros((capacity,), bool)

    def route(
        self, src: np.ndarray, dst: np.ndarray, mask: Optional[np.ndarray] = None
    ) -> dict:
        """Route one micro-batch; returns envelope columns (numpy arrays).

        Columns: lane, idx (global 1-based edge index), resample, third (new
        watched vertex for resamples, -1 otherwise), hit_a, hit_b (whether
        the edge closes the lane's (edgeEndpoint, third) wedge sides).

        Large batches process in bounded chunks: the vectorized pass builds
        [m, num_samplers] intermediates, so m is capped (``chunk_elems``) to
        bound the working set.  (The OUTPUT still scales with the number of
        interested envelopes — in broadcast mode that is m * num_samplers
        rows no matter how the routing is chunked.)
        """
        chunk = max(1, self.chunk_elems // max(self.num_samplers, 1))
        if len(src) > chunk:
            outs = [
                self.route(
                    src[i : i + chunk],
                    dst[i : i + chunk],
                    None if mask is None else mask[i : i + chunk],
                )
                for i in range(0, len(src), chunk)
            ]
            return {
                k: np.concatenate([o[k] for o in outs]) for k in outs[0]
            }
        s = self.num_samplers
        src = np.asarray(src, np.int64)
        dst = np.asarray(dst, np.int64)
        if mask is not None:
            sel = np.asarray(mask, bool)
            src, dst = src[sel], dst[sel]
        m = len(src)
        if m == 0:
            # dtypes must match the non-empty path's columns exactly, or a
            # chunked concatenate would promote the bool columns to int64
            return {
                "lane": np.zeros((0,), np.int64),
                "idx": np.zeros((0,), np.int64),
                "resample": np.zeros((0,), bool),
                "third": np.zeros((0,), np.int64),
                "hit_a": np.zeros((0,), bool),
                "hit_b": np.zeros((0,), bool),
            }
        self.seen[src] = True
        self.seen[dst] = True
        idx = self.edges_seen + 1 + np.arange(m, dtype=np.int64)  # 1-based
        self.edges_seen += m

        # vectorized draws: one counter-hashed word per (edge, lane)
        counters = (idx[:, None] * np.int64(s) + np.arange(s, dtype=np.int64))
        u01 = (_hashed_bits(self.seed, counters, 0) >> np.uint64(11)).astype(
            np.float64
        ) * (1.0 / (1 << 53))
        coins = u01 < (1.0 / idx)[:, None]  # [m, s] 1/i reservoir coin
        thirds = (
            _hashed_bits(self.seed, counters, 1) % np.uint64(self.capacity)
        ).astype(np.int64)

        # each lane's state at edge row j = its last resample strictly
        # before j this batch, else the carried state (-1 sentinel)
        rows = np.arange(m, dtype=np.int64)
        fired = np.where(coins, rows[:, None], np.int64(-1))
        last_fired = np.maximum.accumulate(fired, axis=0)  # [m, s]
        state_at = np.empty((m, s), np.int64)
        state_at[0] = -1
        state_at[1:] = last_fired[:-1]
        in_batch = state_at >= 0
        row_clip = np.clip(state_at, 0, None)
        e0_at = np.where(in_batch, src[row_clip], self.edge_tab[None, :, 0])
        e1_at = np.where(in_batch, dst[row_clip], self.edge_tab[None, :, 1])
        t_at = np.where(
            in_batch, np.take_along_axis(thirds, row_clip, axis=0), self.third
        )

        # incidence vs the CURRENT samples (before applying resamples): the
        # edge closes side a/b of a lane's wedge if it equals
        # {edge_endpoint, third} as an unordered pair
        lo = np.minimum(src, dst)[:, None]
        hi = np.maximum(src, dst)[:, None]
        hit_a = (np.minimum(e0_at, t_at) == lo) & (np.maximum(e0_at, t_at) == hi)
        hit_b = (np.minimum(e1_at, t_at) == lo) & (np.maximum(e1_at, t_at) == hi)
        interested = (
            np.ones((m, s), bool) if self.broadcast else (coins | hit_a | hit_b)
        )
        erow, lane = np.nonzero(interested)  # row-major: edge-major, lane asc

        out = {
            "lane": lane.astype(np.int64),
            "idx": idx[erow],
            "resample": coins[erow, lane],
            "third": np.where(coins[erow, lane], thirds[erow, lane], -1),
            # a resampling lane's hits refer to the OLD wedge it just dropped
            "hit_a": hit_a[erow, lane] & ~coins[erow, lane],
            "hit_b": hit_b[erow, lane] & ~coins[erow, lane],
        }
        # apply the batch's net resamples to the router's mirror of lane state
        final = last_fired[-1]
        changed = final >= 0
        frow = np.clip(final, 0, None)
        self.edge_tab[changed, 0] = src[frow][changed]
        self.edge_tab[changed, 1] = dst[frow][changed]
        self.third[changed] = np.take_along_axis(
            thirds, frow[None, :], axis=0
        )[0][changed]
        return out

    def envelopes(
        self, env: dict, src_of_idx: dict, lanes_per_shard: int
    ) -> List[SampledEdge]:
        """Render routed columns as the reference's SampledEdge wire records
        (subtask = owning shard, instance = lane, edgeCount = global index)."""
        return [
            SampledEdge(
                subtask=int(l) // lanes_per_shard,
                instance=int(l),
                src=src_of_idx[int(i)][0],
                dst=src_of_idx[int(i)][1],
                edge_count=int(i),
                resample=bool(r),
            )
            for l, i, r in zip(env["lane"], env["idx"], env["resample"])
        ]


def _apply_envelopes(closed_a, closed_b, lane, idx, resample, hit_a, hit_b, mask):
    """Vectorized per-shard envelope application (TriangleSampleMapper analog).

    Lane flags reset at the lane's LAST in-batch resample; any hit at a
    strictly later index sets the corresponding side.  Hits of lanes that
    never resample this batch accumulate onto the carried flags.  Pure
    function over this shard's [L] flag arrays and [cap] envelope columns.
    """
    num_lanes = closed_a.shape[0]
    lane = jnp.where(mask, lane, 0)
    res = resample & mask
    # segment max of resample indices per lane (0 = none; idx is 1-based)
    last_res = jnp.zeros((num_lanes,), idx.dtype).at[lane].max(
        jnp.where(res, idx, 0)
    )
    has_res = last_res > 0
    after = idx > last_res[lane]
    new_a = jnp.zeros((num_lanes,), bool).at[lane].max(hit_a & mask & after)
    new_b = jnp.zeros((num_lanes,), bool).at[lane].max(hit_b & mask & after)
    closed_a = jnp.where(has_res, new_a, closed_a | new_a)
    closed_b = jnp.where(has_res, new_b, closed_b | new_b)
    return closed_a, closed_b


class MeshSampledTriangleCount:
    """Sampler lanes sharded over the mesh, fed by the incidence router.

    ``mode="incidence"`` ships only interested-lane envelopes;
    ``mode="broadcast"`` ships every (edge, lane) envelope through the same
    path.  Estimates are identical by construction (a lane untouched by an
    edge cannot change state); ``comm_envelopes`` records shipped volume per
    batch for the comparison the reference never measures.
    """

    def __init__(
        self,
        num_samplers: int,
        mesh=None,
        mode: str = "incidence",
        seed: int = 0xDEADBEEF,
    ):
        if mode not in ("incidence", "broadcast"):
            raise ValueError(f"unknown mode {mode!r}")
        self.mesh = mesh if mesh is not None else make_mesh()
        self.n_shards = self.mesh.devices.size
        if num_samplers % self.n_shards:
            raise ValueError("num_samplers must divide evenly over shards")
        self.num_samplers = num_samplers
        self.lanes_per_shard = num_samplers // self.n_shards
        self.mode = mode
        self.seed = seed
        self.comm_envelopes: List[int] = []
        self._step = None

    def _apply_step(self):
        if self._step is not None:
            return self._step
        from jax.sharding import PartitionSpec as P

        lanes_per = self.lanes_per_shard

        def step(closed_a, closed_b, lane, idx, resample, hit_a, hit_b, mask):
            # [1, cap] envelope block for this shard; lanes local to shard
            a, b = _apply_envelopes(
                closed_a,
                closed_b,
                lane[0],
                idx[0],
                resample[0],
                hit_a[0],
                hit_b[0],
                mask[0],
            )
            beta_local = jnp.sum((a & b).astype(jnp.int32))
            beta = jax.lax.psum(beta_local, SHARD_AXIS)
            return a, b, beta

        spec = P(SHARD_AXIS)
        self._step = jax.jit(  # graft: disable=RAWJIT — per-mesh sharded step memoized on the instance; a Mesh is not a stable process-global cache key
            shard_map(
                step,
                mesh=self.mesh,
                in_specs=(spec,) * 8,
                out_specs=(spec, spec, P()),
            )
        )
        return self._step

    def _bucket(self, env: dict) -> Tuple[dict, np.ndarray]:
        """Host pack: envelope columns -> [n_shards, cap] arrays by owner."""
        owner = env["lane"] // self.lanes_per_shard
        counts = np.bincount(owner, minlength=self.n_shards)
        cap = max(1, 1 << (int(counts.max()) - 1).bit_length()) if counts.max() else 1
        packed = {
            k: np.zeros((self.n_shards, cap), np.int32)
            for k in ("lane", "idx")
        }
        for k in ("resample", "hit_a", "hit_b"):
            packed[k] = np.zeros((self.n_shards, cap), bool)
        mask = np.zeros((self.n_shards, cap), bool)
        for shard in range(self.n_shards):
            sel = owner == shard
            n = int(sel.sum())
            packed["lane"][shard, :n] = env["lane"][sel] % self.lanes_per_shard
            packed["idx"][shard, :n] = env["idx"][sel]
            packed["resample"][shard, :n] = env["resample"][sel]
            packed["hit_a"][shard, :n] = env["hit_a"][sel]
            packed["hit_b"][shard, :n] = env["hit_b"][sel]
            mask[shard, :n] = True
        return packed, mask

    def run(self, stream) -> OutputStream:
        """One (estimate,) record per micro-batch, like the in-core variants."""
        cfg: StreamConfig = stream.cfg

        def records() -> Iterator[tuple]:
            router = IncidenceRouter(
                self.num_samplers,
                cfg.vertex_capacity,
                self.seed,
                broadcast=self.mode == "broadcast",
            )
            self.router = router
            self.comm_envelopes = []
            step = self._apply_step()
            closed_a = jnp.zeros((self.num_samplers,), bool)
            closed_b = jnp.zeros((self.num_samplers,), bool)
            for batch in stream.batches():
                env = router.route(
                    np.asarray(batch.src),
                    np.asarray(batch.dst),
                    np.asarray(batch.mask),
                )
                self.comm_envelopes.append(len(env["lane"]))
                packed, mask = self._bucket(env)
                closed_a, closed_b, beta = step(
                    closed_a,
                    closed_b,
                    jnp.asarray(packed["lane"]),
                    jnp.asarray(packed["idx"]),
                    jnp.asarray(packed["resample"]),
                    jnp.asarray(packed["hit_a"]),
                    jnp.asarray(packed["hit_b"]),
                    jnp.asarray(mask),
                )
                e = float(router.edges_seen)
                v = float(router.seen.sum())
                yield (
                    float(beta) / self.num_samplers * e * max(v - 2.0, 0.0),
                )

        return OutputStream(records)

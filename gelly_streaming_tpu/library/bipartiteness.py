"""Streaming bipartiteness (2-colorability) check.

Reference: library/BipartitenessCheck.java:39-130 — a
``SummaryBulkAggregation<..., Candidates, Candidates>`` whose fold assigns
sign(+) to the min endpoint and sign(-) to the max (:52-59), merges per-edge
candidates (:93-95), and combines partitions with sign-flip reconciliation
(:128-130); any conflict yields the fail sentinel.

TPU-native re-derivation (not a port): the parity union-find on the doubled
vertex space (ops/unionfind.py) reaches the same verdict — an odd cycle is
exactly a vertex whose two side-nodes share a component — and the Candidates
host view (summaries/candidates.py) reproduces the reference's output format,
including the min-endpoint-positive sign convention.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from gelly_streaming_tpu.core.aggregation import SummaryBulkAggregation
from gelly_streaming_tpu.core.config import StreamConfig
from gelly_streaming_tpu.ops import unionfind as uf
from gelly_streaming_tpu.summaries.candidates import Candidates


class BPState(NamedTuple):
    parent2: jax.Array  # int32[2C] doubled-space union-find
    seen: jax.Array  # bool[C]


class BipartitenessCheck(SummaryBulkAggregation):
    """aggregate(BipartitenessCheck(window_ms)) -> stream of Candidates."""

    # parity union-find reaches the same (components, conflict) fixed point
    # in any edge order -> eligible for the EF40 multiset wire encoding
    order_free = True

    @property
    def cache_token(self):
        # kernels are pure functions of (class, cfg): share executables
        # across re-created descriptors
        return type(self)

    def initial_state(self, cfg: StreamConfig) -> BPState:
        return BPState(
            parent2=uf.init_parity_parent(cfg.vertex_capacity),
            seen=jnp.zeros((cfg.vertex_capacity,), bool),
        )

    def update(self, state: BPState, src, dst, val, mask) -> BPState:
        parent2 = uf.parity_union_edges(state.parent2, src, dst, mask)
        seen = state.seen.at[jnp.where(mask, src, 0)].max(mask)
        seen = seen.at[jnp.where(mask, dst, 0)].max(mask)
        return BPState(parent2, seen)

    def combine(self, a: BPState, b: BPState) -> BPState:
        return BPState(
            parent2=uf.merge_parents(a.parent2, b.parent2),
            seen=a.seen | b.seen,
        )

    def transform(self, state: BPState) -> Candidates:
        return Candidates(state.parent2, state.seen)

    def mesh_combine_states(self, cfg: StreamConfig, axis_name: str):
        """Collective cross-shard combine on the doubled space: the same
        pmin-round fixpoint as CC (each shard's parent2 pointers are its
        local parity constraints) — the TPU-native form of Candidates'
        partition merge (BipartitenessCheck.java:128-130)."""
        from gelly_streaming_tpu.library.connected_components import (
            collective_parent_seen_combine,
        )

        def combine(state: BPState, has_data) -> BPState:
            return BPState(
                *collective_parent_seen_combine(
                    state.parent2, state.seen, axis_name
                )
            )

        return combine

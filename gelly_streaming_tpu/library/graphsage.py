"""1-layer GraphSAGE-style message passing over sliced windows.

Not present in the reference (BASELINE.json lists it as a new TPU workload:
"1-layer GraphSAGE message-passing as applyOnNeighbors over sliced windows").
It exercises the framework's MXU path: per closed window, each keyed vertex
aggregates its neighbors' feature vectors (masked mean over the padded [K, D]
neighborhood tensor) and projects through two dense bfloat16 matmuls:

    h_v = relu(x_v @ W_self + mean_{u in N(v)}(x_u) @ W_nbr + b)

Feature gathers and the [K, D, F] -> [K, F] mean are VPU work; the projections
are MXU matmuls — large, batched, bfloat16, exactly what the systolic array
wants (SURVEY.md design stance).
"""

from __future__ import annotations

from typing import Iterator, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from gelly_streaming_tpu.core import compile_cache
from gelly_streaming_tpu.core.output import OutputStream, RecordBlock
from gelly_streaming_tpu.core.snapshot import SnapshotStream
from gelly_streaming_tpu.core.types import EdgeDirection


class SageParams(NamedTuple):
    w_self: jax.Array  # [F_in, F_out] bf16
    w_nbr: jax.Array  # [F_in, F_out] bf16
    bias: jax.Array  # [F_out] bf16


def init_params(
    key: jax.Array, in_features: int, out_features: int
) -> SageParams:
    k1, k2 = jax.random.split(key)
    scale = 1.0 / np.sqrt(in_features)
    return SageParams(
        w_self=(jax.random.normal(k1, (in_features, out_features)) * scale).astype(
            jnp.bfloat16
        ),
        w_nbr=(jax.random.normal(k2, (in_features, out_features)) * scale).astype(
            jnp.bfloat16
        ),
        bias=jnp.zeros((out_features,), jnp.bfloat16),
    )


def sage_kernel(params: SageParams, features, keys, nbrs, valid):
    """[K] keys + [K, D] padded neighborhoods -> [K, F_out] embeddings."""
    x_self = features[keys].astype(jnp.bfloat16)  # [K, F]
    x_nbr = features[nbrs].astype(jnp.bfloat16)  # [K, D, F]
    w = valid.astype(jnp.bfloat16)[:, :, None]
    denom = jnp.maximum(jnp.sum(w, axis=1), 1.0)
    mean_nbr = jnp.sum(x_nbr * w, axis=1) / denom  # [K, F]
    h = x_self @ params.w_self + mean_nbr @ params.w_nbr + params.bias
    return jax.nn.relu(h)


# graftcheck RAWJIT fix: route the module-level executable through the
# process-global cache so its compiles are metered by the retrace guard
sage_kernel_jit = compile_cache.cached_jit(("sage_kernel",), lambda: sage_kernel)


def sage_kernel_ring(params: SageParams, block, keys, nbrs, valid, num_shards):
    """Sharded-feature GraphSAGE layer (call inside shard_map).

    The feature matrix is modulo-sharded into per-device blocks; the ring
    exchange (parallel/ring.py) streams every block past every shard so the
    masked neighbor mean and self rows assemble without replicating X — the
    framework's ring-attention-style schedule.  The projections stay local
    bf16 MXU matmuls on each shard's [K, F] slice.
    """
    from gelly_streaming_tpu.parallel.ring import ring_neighbor_features

    x_self, mean_nbr, _ = ring_neighbor_features(
        block, keys, nbrs, valid, num_shards
    )
    h = (
        x_self.astype(jnp.bfloat16) @ params.w_self
        + mean_nbr.astype(jnp.bfloat16) @ params.w_nbr
        + params.bias
    )
    return jax.nn.relu(h)


class GraphSAGEWindows:
    """Per-window vertex embeddings over a sliced edge stream."""

    def __init__(self, params, features):
        # a single SageParams (1 layer) or a sequence (stacked layers: layer
        # l+1 aggregates layer l's window embeddings — beyond the reference).
        # NB SageParams is itself a (Named)tuple — test for it FIRST.
        self.layers = (
            [params] if isinstance(params, SageParams) else list(params)
        )
        if not self.layers or not all(
            isinstance(p, SageParams) for p in self.layers
        ):
            raise TypeError(
                "params must be a SageParams or a non-empty sequence of them"
            )
        self.params = self.layers[0]  # layer-1 view (back-compat)
        self.features = jnp.asarray(features)

    def _layer_over_buckets(self, params, feats, hoods):
        """One sage layer over a window's materialized buckets: returns
        (keys [K], emb [K, F_out]) host arrays for the window's real rows."""
        ks, es = [], []
        for hood in hoods:
            emb = sage_kernel_jit(
                params,
                feats,
                jnp.asarray(hood.keys),
                jnp.asarray(hood.nbrs),
                jnp.asarray(hood.valid),
            )
            n = hood.num_keys
            ks.append(np.asarray(hood.keys)[:n])
            es.append(np.asarray(emb.astype(jnp.float32))[:n])
        return np.concatenate(ks), np.concatenate(es)

    def _stack_layers(self, hoods, first=None):
        """Run the layer stack over one window's buckets.

        ``first`` optionally supplies layer 1's output (e.g. from the
        sharded plane).  Hidden layers see a per-window [C, F_l] buffer:
        rows for the window's keyed vertices, zeros elsewhere — the window
        defines the graph, so vertices outside it have no layer-l state.
        With slice(ALL) every window vertex is a key, so every neighbor row
        is populated.
        """
        c = self.features.shape[0]
        keys = emb = None
        for li, p in enumerate(self.layers):
            if li == 0 and first is not None:
                keys, emb = first
                continue
            feats = self.features
            if li > 0:
                h = np.zeros((c, emb.shape[1]), np.float32)
                h[keys] = emb
                feats = jnp.asarray(h)
            keys, emb = self._layer_over_buckets(p, feats, hoods)
        return keys, emb

    def run(self, snapshot: SnapshotStream) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        """Yields (keys [K], embeddings [K, F_out]) per closed window.

        Panes arrive as degree buckets (core/snapshot.py); the kernel runs per
        bucket — smaller, tighter [K_b, D_b] tensors — and one record per
        window concatenates the buckets' rows.  With ``cfg.num_shards > 1``
        the window runs on the sharded plane: features live as modulo blocks
        (one per device) and ``sage_kernel_ring`` assembles self/neighbor
        rows via the ring exchange instead of replicating X — the sharded
        kernel finally drives the product path (VERDICT r2 missing #6).

        Stacked layers (a params sequence): layer 1 reads the raw feature
        table — the potentially huge gather, ring-sharded on the mesh path —
        and each deeper layer aggregates the previous layer's window
        embeddings, a per-window [C, F_l] buffer that is orders smaller and
        runs on one device.
        """
        self._check_direction(snapshot)
        if snapshot._use_mesh():
            yield from self._run_sharded(snapshot)
            return
        import itertools

        grouped = itertools.groupby(
            snapshot._neighborhood_panes(), key=lambda h: h.pane.window_id
        )
        if len(self.layers) == 1:
            # stream bucket-by-bucket: no need to pin a window's tensors
            for _, hoods in grouped:
                yield self._layer_over_buckets(self.layers[0], self.features, hoods)
            return
        for _, hoods in grouped:
            yield self._stack_layers(list(hoods))

    def _check_direction(self, snapshot: SnapshotStream) -> None:
        """Stacked layers need every in-window vertex keyed so hidden rows
        exist for every neighbor — only slice(ALL) guarantees that (under
        OUT/IN a sink/source-only vertex would contribute a zero hidden row
        and silently dilute layer-2 means)."""
        if len(self.layers) > 1 and snapshot.direction != EdgeDirection.ALL:
            raise ValueError(
                "stacked GraphSAGE layers require slice(..., EdgeDirection.ALL)"
            )

    def _sharded_state(self, s_n: int):
        """(kernel, blocks) built once per shard count: the kernel object is
        the snapshot layer's compile-cache key, so re-running the
        OutputStream (or a new window pass) must present the SAME closure —
        and the block placement should happen once, not per run."""
        cached = getattr(self, "_sharded_cache", None)
        if cached is not None and cached[0] == s_n:
            return cached[1], cached[2]
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        from gelly_streaming_tpu.parallel.mesh import SHARD_AXIS, make_mesh
        from gelly_streaming_tpu.parallel.ring import shard_features

        # place each block on its shard up front: the table must never sit
        # whole on one device (that replication is what the ring avoids)
        blocks = jax.device_put(
            shard_features(np.asarray(self.features), s_n),
            NamedSharding(make_mesh(s_n), P(SHARD_AXIS)),
        )
        params = self.params

        def kernel(keys, nbrs, vals, valid, block):
            return sage_kernel_ring(params, block, keys, nbrs, valid, s_n)

        self._sharded_cache = (s_n, kernel, blocks)
        return kernel, blocks

    def _sharded_layer1_windows(self, snapshot: SnapshotStream):
        """Layer 1 on the sharded plane, one (window_id, keys, emb) triple
        per window (the id lets stacked-layer zipping verify pairing)."""
        kernel, blocks = self._sharded_state(snapshot._stream.cfg.num_shards)

        cur_wid = None
        ks, es = [], []
        for wid, keys_h, out, _ in snapshot._kernel_chunks(
            kernel, False, extra=blocks
        ):
            if cur_wid is not None and wid != cur_wid and ks:
                yield cur_wid, np.concatenate(ks), np.concatenate(es)
                ks, es = [], []
            cur_wid = wid
            ks.append(keys_h)
            es.append(np.asarray(out).astype(np.float32))
        if ks:
            yield cur_wid, np.concatenate(ks), np.concatenate(es)

    def _run_sharded(self, snapshot: SnapshotStream):
        """Ring-sharded window pass: feature blocks [S, C/S, F] stay on their
        shards; each shard's buckets gather remote rows via ppermute hops.
        Stacked layers: layer 1 (the raw-feature gather) runs sharded; deeper
        layers aggregate the window's [C, F_l] hidden buffer single-device
        over a second, bucket-building pass of the same re-runnable stream,
        zipped window-by-window with layer 1's output."""
        if len(self.layers) == 1:
            for _wid, keys, emb in self._sharded_layer1_windows(snapshot):
                yield keys, emb
            return
        import copy
        import itertools

        if snapshot._stream.cfg.ingest_window_ms:
            # wall-clock panes are not replay-deterministic (core/windows.py
            # documents the same refusal for checkpointed runs): the second
            # pane-building pass would cut different windows and the zip
            # below would silently pair layer-1 output with foreign buckets
            raise ValueError(
                "stacked sharded GraphSAGE needs replay-deterministic panes; "
                "use ingest_window_edges or event-time windows, not "
                "ingest_window_ms"
            )
        # pass 2 rebuilds the window buckets on a sink-less stream clone:
        # the layer-1 pass already delivered each late record to the user's
        # on_late sink once; the second assignment must not re-fire it
        s2 = copy.copy(snapshot._stream)
        s2._late_holder = {"sink": None}
        snap2 = SnapshotStream(
            s2, snapshot.window_ms, snapshot.direction, snapshot.slide_ms
        )
        hood_groups = itertools.groupby(
            snap2._neighborhood_panes(), key=lambda h: h.pane.window_id
        )
        # STRICT zip: the two passes re-run the same source, so their window
        # sequences must match 1:1.  A one-shot or nondeterministic user
        # source factory would otherwise exhaust one side early (plain zip
        # silently truncates) or cut different windows (silently pairing
        # layer-1 output with a FOREIGN window's buckets) — raise instead.
        _END = object()
        for l1, grp in itertools.zip_longest(
            self._sharded_layer1_windows(snapshot), hood_groups, fillvalue=_END
        ):
            if l1 is _END or grp is _END:
                raise RuntimeError(
                    "stacked sharded GraphSAGE: the two window passes "
                    "disagree on window count — the stream source must be "
                    "re-runnable and deterministic (pass "
                    f"{'1' if l1 is _END else '2'} exhausted early)"
                )
            wid1, keys, emb = l1
            wid2, hoods = grp
            if wid1 != wid2:
                raise RuntimeError(
                    "stacked sharded GraphSAGE: window ids diverged between "
                    f"the two passes ({wid1} vs {wid2}) — the stream source "
                    "must be re-runnable and deterministic"
                )
            yield self._stack_layers(list(hoods), first=(keys, emb))

    def output(self, snapshot: SnapshotStream) -> OutputStream:
        """(vertex, embedding-norm) records — a compact observable stream."""
        def blocks():
            for keys, emb in self.run(snapshot):
                yield RecordBlock(
                    (keys.astype(np.int64), np.linalg.norm(emb, axis=1))
                )

        return OutputStream(blocks_fn=blocks)


# ---------------------------------------------------------------------------
# Training (beyond the reference, which has no learned models at all): a full
# unsupervised GraphSAGE training step — single-device and as a mesh step
# whose forward rides the ring feature exchange (features stay block-sharded;
# parameter gradients flow back through the ppermute hops and are psum'd).
#
# Objective: skip-gram with negative sampling over the window graph (the
# GraphSAGE paper's unsupervised loss, eq. 1): the sage embedding z_u of each
# keyed vertex is scored against a *context* projection c(v) = relu(X[v] @
# w_self + bias) of one sampled neighbor (positive) and one uniform random
# vertex (negative); loss = mean softplus(-z.c_pos) + mean softplus(z.c_neg).
# Pair sampling is host-side and explicit (sample_pairs) so the mesh step is
# bit-comparable to the single-device step on the same pairs.


class SageTrainState(NamedTuple):
    params: SageParams  # float32 masters (optimizer precision)
    opt_state: object  # optax state pytree


def _as_bf16(params: SageParams) -> SageParams:
    return SageParams(*(p.astype(jnp.bfloat16) for p in params))


def sample_pairs(rng, nbrs, valid, capacity: int):
    """One (positive neighbor, negative vertex) pair per keyed row.

    Returns device arrays (pos_ids [K], has_pos [K], neg_ids [K]): pos is a
    uniformly sampled VALID neighbor (gumbel-argmax over the mask; rows with
    empty neighborhoods get has_pos=False and contribute no positive term),
    neg a uniform vertex id in [0, capacity).
    """
    k_pos, k_neg = jax.random.split(rng)
    scores = jnp.where(valid, jax.random.uniform(k_pos, valid.shape), -1.0)
    pos_idx = jnp.argmax(scores, axis=1)
    pos_ids = jnp.take_along_axis(nbrs, pos_idx[:, None], axis=1)[:, 0]
    has_pos = valid.any(axis=1)
    neg_ids = jax.random.randint(k_neg, (nbrs.shape[0],), 0, capacity)
    return pos_ids, has_pos, neg_ids


def _context(params_b: SageParams, x):
    return jax.nn.relu(
        x.astype(jnp.bfloat16) @ params_b.w_self + params_b.bias
    ).astype(jnp.float32)


def _pair_terms(z, c_pos, c_neg, has_pos):
    """(pos_loss_sum, pos_n, neg_loss_sum, neg_n) float32 scalars."""
    pos_s = jnp.sum(z * c_pos, axis=-1)
    neg_s = jnp.sum(z * c_neg, axis=-1)
    w = has_pos.astype(jnp.float32)
    return (
        jnp.sum(jax.nn.softplus(-pos_s) * w),
        jnp.sum(w),
        jnp.sum(jax.nn.softplus(neg_s)),
        jnp.asarray(z.shape[0], jnp.float32),
    )


def sage_loss(params, features, keys, nbrs, valid, pos_ids, has_pos, neg_ids):
    """Scalar unsupervised loss on one neighborhood bucket (f32 params in,
    bf16 MXU compute inside)."""
    p = _as_bf16(params)
    z = sage_kernel(p, features, keys, nbrs, valid).astype(jnp.float32)
    t = _pair_terms(
        z, _context(p, features[pos_ids]), _context(p, features[neg_ids]), has_pos
    )
    return t[0] / jnp.maximum(t[1], 1.0) + t[2] / jnp.maximum(t[3], 1.0)


def sage_init_train(key, in_features: int, out_features: int, tx) -> SageTrainState:
    """Float32 master params + optimizer state for the given optax ``tx``."""
    p = init_params(key, in_features, out_features)
    p32 = SageParams(*(x.astype(jnp.float32) for x in p))
    return SageTrainState(p32, tx.init(p32))


def sage_train_step(tx, state: SageTrainState, features, keys, nbrs, valid,
                    pos_ids, has_pos, neg_ids):
    """One optimizer step; returns (new_state, loss).  Jit-friendly with
    ``tx`` static (functools.partial / closure)."""
    loss, grads = jax.value_and_grad(sage_loss)(
        state.params, features, keys, nbrs, valid, pos_ids, has_pos, neg_ids
    )
    updates, opt_state = tx.update(grads, state.opt_state, state.params)
    return SageTrainState(optax.apply_updates(state.params, updates), opt_state), loss


def sage_loss_mesh(params, blocks, keys, nbrs, valid, pos_ids, has_pos,
                   neg_ids, num_shards: int):
    """The same scalar loss with rows sharded [S, K_s, ...] and features
    block-sharded [S, C/S, F]: the forward assembles self/neighbor rows via
    the ring exchange and the pos/neg context rows via ring lookups, the
    four loss terms psum across shards, and the replicated scalar matches
    sage_loss on the concatenated rows (same pairs, same masks) within bf16
    tolerance — the single-device kernel averages neighbors in bf16, the
    ring path in float32.
    Differentiating through this (shard_map + ppermute transpose) yields the
    total parameter gradient — the mesh training step's forward/backward.
    """
    from jax.sharding import PartitionSpec as P

    from gelly_streaming_tpu.parallel.mesh import SHARD_AXIS, make_mesh, shard_map
    from gelly_streaming_tpu.parallel.ring import ring_lookup

    mesh = make_mesh(num_shards)
    p = _as_bf16(params)

    def shard_fn(pb, block, keys, nbrs, valid, pos_ids, has_pos, neg_ids):
        block, keys, nbrs = block[0], keys[0], nbrs[0]
        valid, pos_ids, has_pos, neg_ids = (
            valid[0], pos_ids[0], has_pos[0], neg_ids[0]
        )
        z = sage_kernel_ring(
            pb, block, keys, nbrs, valid, num_shards
        ).astype(jnp.float32)
        c_pos = _context(pb, ring_lookup(block, pos_ids, num_shards))
        c_neg = _context(pb, ring_lookup(block, neg_ids, num_shards))
        t = _pair_terms(z, c_pos, c_neg, has_pos)
        t = jax.lax.psum(jnp.stack(t), SHARD_AXIS)
        return t[0] / jnp.maximum(t[1], 1.0) + t[2] / jnp.maximum(t[3], 1.0)

    S = P(SHARD_AXIS)
    return shard_map(
        shard_fn,
        mesh,
        in_specs=(P(), S, S, S, S, S, S, S),
        out_specs=P(),
    )(p, blocks, keys, nbrs, valid, pos_ids, has_pos, neg_ids)


def sage_train_step_mesh(tx, state: SageTrainState, blocks, keys, nbrs, valid,
                         pos_ids, has_pos, neg_ids, num_shards: int):
    """One mesh optimizer step (params replicated, grads via the ring
    backward); returns (new_state, loss)."""
    loss, grads = jax.value_and_grad(sage_loss_mesh)(
        state.params, blocks, keys, nbrs, valid, pos_ids, has_pos, neg_ids,
        num_shards,
    )
    updates, opt_state = tx.update(grads, state.opt_state, state.params)
    return SageTrainState(optax.apply_updates(state.params, updates), opt_state), loss

"""1-layer GraphSAGE-style message passing over sliced windows.

Not present in the reference (BASELINE.json lists it as a new TPU workload:
"1-layer GraphSAGE message-passing as applyOnNeighbors over sliced windows").
It exercises the framework's MXU path: per closed window, each keyed vertex
aggregates its neighbors' feature vectors (masked mean over the padded [K, D]
neighborhood tensor) and projects through two dense bfloat16 matmuls:

    h_v = relu(x_v @ W_self + mean_{u in N(v)}(x_u) @ W_nbr + b)

Feature gathers and the [K, D, F] -> [K, F] mean are VPU work; the projections
are MXU matmuls — large, batched, bfloat16, exactly what the systolic array
wants (SURVEY.md design stance).
"""

from __future__ import annotations

from typing import Iterator, NamedTuple, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from gelly_streaming_tpu.core.output import OutputStream, RecordBlock
from gelly_streaming_tpu.core.snapshot import SnapshotStream
from gelly_streaming_tpu.core.types import EdgeDirection


class SageParams(NamedTuple):
    w_self: jax.Array  # [F_in, F_out] bf16
    w_nbr: jax.Array  # [F_in, F_out] bf16
    bias: jax.Array  # [F_out] bf16


def init_params(
    key: jax.Array, in_features: int, out_features: int
) -> SageParams:
    k1, k2 = jax.random.split(key)
    scale = 1.0 / np.sqrt(in_features)
    return SageParams(
        w_self=(jax.random.normal(k1, (in_features, out_features)) * scale).astype(
            jnp.bfloat16
        ),
        w_nbr=(jax.random.normal(k2, (in_features, out_features)) * scale).astype(
            jnp.bfloat16
        ),
        bias=jnp.zeros((out_features,), jnp.bfloat16),
    )


def sage_kernel(params: SageParams, features, keys, nbrs, valid):
    """[K] keys + [K, D] padded neighborhoods -> [K, F_out] embeddings."""
    x_self = features[keys].astype(jnp.bfloat16)  # [K, F]
    x_nbr = features[nbrs].astype(jnp.bfloat16)  # [K, D, F]
    w = valid.astype(jnp.bfloat16)[:, :, None]
    denom = jnp.maximum(jnp.sum(w, axis=1), 1.0)
    mean_nbr = jnp.sum(x_nbr * w, axis=1) / denom  # [K, F]
    h = x_self @ params.w_self + mean_nbr @ params.w_nbr + params.bias
    return jax.nn.relu(h)


sage_kernel_jit = jax.jit(sage_kernel)


def sage_kernel_ring(params: SageParams, block, keys, nbrs, valid, num_shards):
    """Sharded-feature GraphSAGE layer (call inside shard_map).

    The feature matrix is modulo-sharded into per-device blocks; the ring
    exchange (parallel/ring.py) streams every block past every shard so the
    masked neighbor mean and self rows assemble without replicating X — the
    framework's ring-attention-style schedule.  The projections stay local
    bf16 MXU matmuls on each shard's [K, F] slice.
    """
    from gelly_streaming_tpu.parallel.ring import ring_neighbor_features

    x_self, mean_nbr, _ = ring_neighbor_features(
        block, keys, nbrs, valid, num_shards
    )
    h = (
        x_self.astype(jnp.bfloat16) @ params.w_self
        + mean_nbr.astype(jnp.bfloat16) @ params.w_nbr
        + params.bias
    )
    return jax.nn.relu(h)


class GraphSAGEWindows:
    """Per-window vertex embeddings over a sliced edge stream."""

    def __init__(self, params: SageParams, features):
        self.params = params
        self.features = jnp.asarray(features)

    def run(self, snapshot: SnapshotStream) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
        """Yields (keys [K], embeddings [K, F_out]) per closed window.

        Panes arrive as degree buckets (core/snapshot.py); the kernel runs per
        bucket — smaller, tighter [K_b, D_b] tensors — and one record per
        window concatenates the buckets' rows.  With ``cfg.num_shards > 1``
        the window runs on the sharded plane: features live as modulo blocks
        (one per device) and ``sage_kernel_ring`` assembles self/neighbor
        rows via the ring exchange instead of replicating X — the sharded
        kernel finally drives the product path (VERDICT r2 missing #6)."""
        if snapshot._use_mesh():
            yield from self._run_sharded(snapshot)
            return
        import itertools

        for _, hoods in itertools.groupby(
            snapshot._neighborhood_panes(), key=lambda h: h.pane.window_id
        ):
            ks, es = [], []
            for hood in hoods:
                emb = sage_kernel_jit(
                    self.params,
                    self.features,
                    jnp.asarray(hood.keys),
                    jnp.asarray(hood.nbrs),
                    jnp.asarray(hood.valid),
                )
                n = hood.num_keys
                ks.append(np.asarray(hood.keys)[:n])
                es.append(np.asarray(emb.astype(jnp.float32))[:n])
            yield np.concatenate(ks), np.concatenate(es)

    def _sharded_state(self, s_n: int):
        """(kernel, blocks) built once per shard count: the kernel object is
        the snapshot layer's compile-cache key, so re-running the
        OutputStream (or a new window pass) must present the SAME closure —
        and the block placement should happen once, not per run."""
        cached = getattr(self, "_sharded_cache", None)
        if cached is not None and cached[0] == s_n:
            return cached[1], cached[2]
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        from gelly_streaming_tpu.parallel.mesh import SHARD_AXIS, make_mesh
        from gelly_streaming_tpu.parallel.ring import shard_features

        # place each block on its shard up front: the table must never sit
        # whole on one device (that replication is what the ring avoids)
        blocks = jax.device_put(
            shard_features(np.asarray(self.features), s_n),
            NamedSharding(make_mesh(s_n), P(SHARD_AXIS)),
        )
        params = self.params

        def kernel(keys, nbrs, vals, valid, block):
            return sage_kernel_ring(params, block, keys, nbrs, valid, s_n)

        self._sharded_cache = (s_n, kernel, blocks)
        return kernel, blocks

    def _run_sharded(self, snapshot: SnapshotStream):
        """Ring-sharded window pass: feature blocks [S, C/S, F] stay on their
        shards; each shard's buckets gather remote rows via ppermute hops."""
        kernel, blocks = self._sharded_state(snapshot._stream.cfg.num_shards)

        cur_wid = None
        ks, es = [], []
        for wid, keys_h, out, _ in snapshot._kernel_chunks(
            kernel, False, extra=blocks
        ):
            if cur_wid is not None and wid != cur_wid and ks:
                yield np.concatenate(ks), np.concatenate(es)
                ks, es = [], []
            cur_wid = wid
            ks.append(keys_h)
            es.append(np.asarray(out).astype(np.float32))
        if ks:
            yield np.concatenate(ks), np.concatenate(es)

    def output(self, snapshot: SnapshotStream) -> OutputStream:
        """(vertex, embedding-norm) records — a compact observable stream."""
        def blocks():
            for keys, emb in self.run(snapshot):
                yield RecordBlock(
                    (keys.astype(np.int64), np.linalg.norm(emb, axis=1))
                )

        return OutputStream(blocks_fn=blocks)

"""Sampling-based triangle-count estimators (broadcast + incidence routing).

Reference: example/BroadcastTriangleCount.java:41-174 broadcasts every edge to
all subtasks, each running ``samples/parallelism`` reservoir triangle samplers
(TriangleSampler :62-135: replace the sampled edge with probability 1/i
:200-207, pick a random third vertex, watch for the two closing edges), with a
parallelism-1 TriangleSummer recombining per-subtask estimates into
``(1/samples) * sum(beta) * |E| * (|V|-2)`` (:138-174).
example/IncidenceSamplingTriangleCount.java:39-242 computes the same estimator
but routes each edge only to the samplers whose sampled edge it is incident to.

TPU-native form: ALL samplers live in one vectorized state (arrays of shape
[S]); an arriving edge updates every sampler with masked lane arithmetic — the
broadcast is a vector op, and incidence routing is exactly the masking the math
already does, so both reference programs collapse to the same kernel with
different parallelism mappings (replicate batch vs. shard samplers).
Randomness is ``jax.random`` with an explicit threaded key (the reference seeds
a JVM Random with 0xDEADBEEF, IncidenceSamplingTriangleCount.java:61).
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from gelly_streaming_tpu.core import compile_cache
from gelly_streaming_tpu.core.config import StreamConfig
from gelly_streaming_tpu.core.output import OutputStream


class SamplerState(NamedTuple):
    key: jax.Array  # PRNG key
    edge: jax.Array  # int32[S, 2] sampled edge per sampler (-1 = none)
    third: jax.Array  # int32[S] watched third vertex
    closed_a: jax.Array  # bool[S] saw (u, third)
    closed_b: jax.Array  # bool[S] saw (v, third)
    edges_seen: jax.Array  # int32[] |E| so far
    seen: jax.Array  # bool[C] vertex presence (|V| tracking)


def init_samplers(cfg: StreamConfig, num_samplers: int, seed: int = 0xDEADBEEF) -> SamplerState:
    return SamplerState(
        key=jax.random.PRNGKey(seed),
        edge=jnp.full((num_samplers, 2), -1, jnp.int32),
        third=jnp.full((num_samplers,), -1, jnp.int32),
        closed_a=jnp.zeros((num_samplers,), bool),
        closed_b=jnp.zeros((num_samplers,), bool),
        edges_seen=jnp.zeros((), jnp.int32),
        seen=jnp.zeros((cfg.vertex_capacity,), bool),
    )


def sampler_update(state: SamplerState, src, dst, mask) -> SamplerState:
    """Feed an edge micro-batch through every sampler (scan keeps the 1/i
    reservoir probabilities sequential, as in TriangleSampler.sampleEdge,
    BroadcastTriangleCount.java:200-207)."""
    num_samplers = state.edge.shape[0]
    capacity = state.seen.shape[0]

    def step(carry, inp):
        st = carry
        u, v, ok = inp
        seen = st.seen.at[jnp.where(ok, u, 0)].max(ok)
        seen = seen.at[jnp.where(ok, v, 0)].max(ok)
        i = st.edges_seen + jnp.where(ok, 1, 0)
        key, k_coin, k_third = jax.random.split(st.key, 3)
        coin = jax.random.uniform(k_coin, (num_samplers,)) < (
            1.0 / jnp.maximum(i, 1).astype(jnp.float32)
        )
        resample = coin & ok
        # random third vertex per resampled lane (uniform over the id space;
        # lanes hitting an endpoint or an unseen id simply never close)
        rnd = jax.random.randint(k_third, (num_samplers,), 0, capacity)
        edge = jnp.where(resample[:, None], jnp.stack([u, v])[None, :], st.edge)
        third = jnp.where(resample, rnd, st.third)
        closed_a = jnp.where(resample, False, st.closed_a)
        closed_b = jnp.where(resample, False, st.closed_b)
        # closing-edge watch (TriangleSampler.sampleVertex/beta logic)
        eu, ev = edge[:, 0], edge[:, 1]
        hits_a = ok & (
            ((eu == u) & (third == v)) | ((eu == v) & (third == u))
        )
        hits_b = ok & (
            ((ev == u) & (third == v)) | ((ev == v) & (third == u))
        )
        closed_a = closed_a | hits_a
        closed_b = closed_b | hits_b
        return (
            SamplerState(key, edge, third, closed_a, closed_b, i, seen),
            None,
        )

    state, _ = jax.lax.scan(step, state, (src, dst, mask))
    return state


def estimate(state: SamplerState) -> float:
    """(1/S) * sum(beta) * |E| * (|V| - 2)  (TriangleSummer,
    BroadcastTriangleCount.java:160-171)."""
    betas = (state.closed_a & state.closed_b).astype(jnp.float32)
    s = state.edge.shape[0]
    e = state.edges_seen.astype(jnp.float32)
    v = jnp.sum(state.seen.astype(jnp.float32))
    return float(jnp.sum(betas) / s * e * jnp.maximum(v - 2.0, 0.0))


class _SampledTriangleCount:
    def __init__(self, num_samplers: int, seed: int = 0xDEADBEEF):
        self.num_samplers = num_samplers
        self.seed = seed
        # graftcheck RAWJIT fix: per-instance jax.jit retraced this kernel
        # for every fresh estimator; the process-global cache compiles once
        self._kernel = compile_cache.cached_jit(
            ("sampler_update",), lambda: sampler_update
        )

    def run(self, stream) -> OutputStream:
        """Continuous estimates: one record (estimate,) after each micro-batch."""

        def records():
            state = init_samplers(stream.cfg, self.num_samplers, self.seed)
            for batch in stream.batches():
                state = self._kernel(state, batch.src, batch.dst, batch.mask)
                yield (estimate(state),)
            self.final_state = state

        return OutputStream(records)


class BroadcastTriangleCount(_SampledTriangleCount):
    """Every edge reaches every sampler (BroadcastTriangleCount.java:41-45);
    on the mesh this is a replicated micro-batch with sampler lanes sharded."""


class IncidenceSamplingTriangleCount(_SampledTriangleCount):
    """Same estimator; the reference routes edges only to incident samplers
    (IncidenceSamplingTriangleCount.java:61-122) — a comm-topology optimization
    that the vectorized kernel's lane masking already embodies on a mesh."""

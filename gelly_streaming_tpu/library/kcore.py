"""Windowed k-core decomposition over sliced edge streams.

Not present in the reference library (SURVEY.md §2.1).  Core numbers per
closed window via the **iterative h-index** fixed point: initialize each
vertex's estimate to its degree, then repeatedly set it to the H-index of
its neighbors' estimates — the sequence is non-increasing and converges to
the core number (Lü et al., "The H-index of a network node", 2016).  This
is the TPU-shaped formulation: no sequential peeling, just vmapped sorted
row reductions over the window's degree-bucketed [K, D] neighborhoods
(ops/neighborhoods.build_buckets — the same tensors slice() aggregations
use), iterated to a fixed point with one jitted step per bucket shape.

The window graph is treated as simple and undirected: edges are
canonicalized and deduplicated per pane, self-loops dropped (the standard
k-core contract).  ``slide_ms`` composes through the shared pane dispatch.
"""

from __future__ import annotations

from typing import Iterator, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from gelly_streaming_tpu.core import compile_cache
from gelly_streaming_tpu.core.output import OutputStream, RecordBlock
from gelly_streaming_tpu.core.windows import windowed_panes
from gelly_streaming_tpu.ops import neighborhoods as nbh_ops
from gelly_streaming_tpu.ops import spmv


def _h_index_rows(vals, valid):
    """Row-wise H-index of the valid entries of [K, D] ``vals``: the largest
    h such that at least h entries are >= h (invalid entries count 0)."""
    masked = jnp.where(valid, vals, 0)
    s = jnp.sort(masked, axis=1)[:, ::-1]  # descending
    ranks = jnp.arange(1, s.shape[1] + 1)[None, :]
    return jnp.max(jnp.where(s >= ranks, ranks, 0), axis=1).astype(jnp.int32)


def _build_bucket_round():
    def kernel(c, keys, nbrs, valid, num_keys):
        # One h-index update for one bucket: gather neighbor estimates,
        # take row H-indices, scatter-min back at the bucket's keys (the
        # kernel core's min-combine scatter).  Rows beyond ``num_keys`` are
        # padding whose key ids alias real vertices — they scatter the
        # min-min identity (INT32_MAX) so the min never touches anyone's
        # estimate.
        h = _h_index_rows(c[nbrs], valid)
        real = jnp.arange(keys.shape[0]) < num_keys
        ident = jnp.asarray(spmv.MIN_MIN.identity, h.dtype)
        return spmv.MIN_MIN.scatter(c, keys, jnp.where(real, h, ident))

    return kernel


# shared process-global executable (one per bucket shape) instead of a raw
# module-level jax.jit outside the compile-cache retrace guard
_bucket_round = compile_cache.cached_jit(
    ("kcore_bucket_round",), _build_bucket_round, label="spmv"
)

_build_buckets_j = nbh_ops.build_buckets_jit


def core_numbers_windows(
    stream,
    window_ms: int,
    slide_ms: Optional[int] = None,
    max_rounds: Optional[int] = None,
) -> Iterator[Tuple[np.ndarray, np.ndarray]]:
    """(vertex ids [V], core numbers [V]) per closed window.

    The default iterates to the exact fixed point (bounded by the window's
    vertex count — corrections can propagate one hop per round, e.g. along
    a long path).  A user ``max_rounds`` that exhausts before convergence
    raises rather than yielding silently over-estimated cores."""
    cfg = stream.cfg
    capacity = cfg.vertex_capacity
    for pane in windowed_panes(stream, window_ms, slide_ms):
        if pane.num_edges == 0:
            continue
        # simple undirected window graph: canonical dedupe, drop self-loops
        a = np.minimum(pane.src, pane.dst).astype(np.int64)
        b = np.maximum(pane.src, pane.dst).astype(np.int64)
        keep = a != b
        uniq = np.unique(a[keep] * capacity + b[keep])
        us, ud = (uniq // capacity).astype(np.int32), (uniq % capacity).astype(np.int32)
        # both directions -> per-vertex neighborhoods
        e2 = 2 * len(us)
        if e2 == 0:
            continue
        e_pad = max(1, 1 << (e2 - 1).bit_length())
        src = np.zeros((e_pad,), np.int32)
        dst = np.zeros((e_pad,), np.int32)
        msk = np.zeros((e_pad,), bool)
        src[: len(us)], src[len(us) : e2] = us, ud
        dst[: len(us)], dst[len(us) : e2] = ud, us
        msk[:e2] = True
        buckets = _build_buckets_j(
            jnp.asarray(src), jnp.asarray(dst), None, jnp.asarray(msk)
        )
        buckets = [bkt for bkt in buckets if int(bkt.num_keys) > 0]

        # estimates start at degree (the h-index sequence is non-increasing
        # from any upper bound); off-window vertices stay 0.  Counting
        # incidence is the kernel core's plus-one scatter.
        c = spmv.scatter_into(
            spmv.PLUS_ONE, capacity, src, np.ones((e_pad,), np.int32), msk
        )
        bound = max_rounds if max_rounds is not None else e2 + 1
        converged = False
        for _ in range(bound):
            prev = c
            for bkt in buckets:
                c = _bucket_round(c, bkt.keys, bkt.nbrs, bkt.valid, bkt.num_keys)
            if bool(jnp.array_equal(c, prev)):
                converged = True
                break
        if not converged:
            raise RuntimeError(
                f"k-core h-index did not converge within {bound} rounds; "
                "raise max_rounds (default iterates to the fixed point)"
            )
        c_h = np.asarray(c)
        vids = np.nonzero(c_h > 0)[0]
        yield vids, c_h[vids]


def windowed_kcore(
    stream,
    window_ms: int,
    slide_ms: Optional[int] = None,
) -> OutputStream:
    """(vertex, core number) records per closed window."""

    def blocks() -> Iterator[RecordBlock]:
        for vids, cores in core_numbers_windows(stream, window_ms, slide_ms):
            yield RecordBlock((vids.astype(np.int64), cores.astype(np.int64)))

    return OutputStream(blocks_fn=blocks)

"""Triangle counting: windowed exact and insertion-only streaming exact.

Window variant — reference example/WindowTriangles.java:50-65: slice(ALL) ->
per-vertex candidate wedges (O(d^2), :82-115) -> keyBy(candidate edge) window
join against real edges (:118-139) -> all-window sum.  The TPU-native
re-design skips the wedge materialization entirely: per closed pane it builds
the deduped undirected CSR and counts, for every canonical edge (u, v), the
common neighbors |N(u) & N(v)| with one [E, D, D] masked equality reduction —
each triangle is counted once per its three edges, so count = sum / 3.  Same
result, no candidate shuffle.

Streaming variant — reference example/ExactTriangleCount.java:43-56
(KDD'16-style single pass): buildNeighborhood + canonical edges + stateful
neighborhood intersection emitting per-vertex and global counter updates
(:74-134).  Here the state is the device NeighborTable plus dense counter
arrays; each edge's intersection is a masked row comparison, applied in batch
arrival order via lax.scan (intersections must see the adjacency as of the
edge's arrival).
"""

from __future__ import annotations

from typing import Iterator, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from gelly_streaming_tpu.core.config import StreamConfig
from gelly_streaming_tpu.core.output import OutputStream
from gelly_streaming_tpu.core.types import EdgeDirection
from gelly_streaming_tpu.core.windows import (
    stream_panes,
    validate_slide,
    windowed_panes,
)
from gelly_streaming_tpu.ops import neighbors as nbr_ops
from gelly_streaming_tpu.ops import pallas_triangles


# ---------------------------------------------------------------------------
# Windowed exact count
# ---------------------------------------------------------------------------


# Panes whose compacted vertex count fits this bound use the dense MXU kernel
# (ops/pallas_triangles.py): 16x faster than the CSR equality reduction at
# K=4096 on a v5e chip, and the dense [K, K] bf16 adjacency stays modest
# (<=128 MB).  Larger panes fall back to the padded-CSR path.  Off-TPU the
# kernel runs in the Pallas interpreter (slow), so the dense path is kept only
# small enough to stay test-friendly.
DENSE_PANE_MAX_VERTICES = 8192
DENSE_PANE_MAX_VERTICES_INTERPRET = 512


def _dense_pane_bound() -> int:
    return (
        DENSE_PANE_MAX_VERTICES
        if jax.default_backend() == "tpu"
        else DENSE_PANE_MAX_VERTICES_INTERPRET
    )


def _pane_prepare(pane):
    """Host side of a pane submission: classify + pack, NO device calls.

    Returns ``(meta, host_arrays)`` fit for the prefetching pipeline
    (io/wire.py Prefetcher): the transfer thread device_puts
    ``host_arrays`` and ``_pane_dispatch`` turns the pair into an async
    count handle.  Dense-eligible panes ship the 4 B/edge packed wire form
    (ops/pallas_triangles.pack_pane); sparse id spaces are compacted here
    (the host work overlaps the previous pane's transfer/compute)."""
    src, dst = pane
    if len(src) == 0:
        return ("const", 0), None
    max_id = int(max(src.max(), dst.max()))
    if max_id < _dense_pane_bound():
        # Ids already fit the dense kernel: ship packed words and let the
        # device scatter canonicalize/dedup (no host unique).
        w, n = pallas_triangles.pack_pane(
            src.astype(np.int32), dst.astype(np.int32)
        )
        return ("packed", max_id + 1), (w, n)
    # Sparse id space: compact vertices on the host first.
    lo = np.minimum(src, dst)
    hi = np.maximum(src, dst)
    keep = lo != hi
    pairs = np.unique(np.stack([lo[keep], hi[keep]], axis=1), axis=0)
    if len(pairs) == 0:
        return ("const", 0), None
    u, v = pairs[:, 0].astype(np.int32), pairs[:, 1].astype(np.int32)
    verts, inv = np.unique(np.concatenate([u, v]), return_inverse=True)
    cu, cv = inv[: len(u)].astype(np.int32), inv[len(u) :].astype(np.int32)
    k_n = len(verts)
    if k_n <= _dense_pane_bound():
        w, n = pallas_triangles.pack_pane(cu, cv)
        return ("packed", k_n), (w, n)
    deg = np.bincount(np.concatenate([cu, cv]), minlength=k_n)
    d_max = int(deg.max())
    return ("csr", k_n, d_max), (cu, cv)


def _pane_dispatch(meta, arrays):
    """Device side: dispatch a prepared pane, returning an async handle."""
    if meta[0] == "const":
        return ("const", meta[1])
    if meta[0] == "packed":
        w, n = arrays
        return (
            "halves",
            pallas_triangles.pane_triangles_submit_packed(w, n, meta[1]),
        )
    _, k_n, d_max = meta
    cu, cv = arrays
    return ("scalar", _count_kernel(jnp.asarray(cu), jnp.asarray(cv), k_n, d_max))


def _pane_triangle_submit(src: np.ndarray, dst: np.ndarray):
    """Upload + dispatch a pane's triangle count without waiting.

    Returns an opaque handle for ``_pane_triangle_finish``; splitting the two
    lets consecutive panes pipeline (the next pane's transfer and compute run
    while this one's scalar rides the readback RTT home).
    """
    meta, arrays = _pane_prepare((src, dst))
    return _pane_dispatch(meta, arrays)


def _pane_triangle_finish(handle) -> int:
    """Blocking fetch of a submitted pane count."""
    kind, payload = handle
    if kind == "const":
        return payload
    if kind == "halves":
        return pallas_triangles.triangles_from_halves(payload)
    return int(payload)


def _pane_triangle_count(src: np.ndarray, dst: np.ndarray) -> int:
    """Exact triangles among a pane's edges (host orchestration, device count)."""
    return _pane_triangle_finish(_pane_triangle_submit(src, dst))


def pipelined_pane_counts(
    panes, recorder=None, warmup: int = 0, depth: int = 2, device_recorder=None
):
    """Triangle counts for a sequence of panes with submit/readback overlap.

    The sequential loop pays (upload + compute + readback-RTT) per pane; on a
    tunneled device the RTT dominates (VERDICT r2 weak #2).  Here up to
    ``depth`` panes are in flight: pane k's scalar rides the readback link
    home while pane k+1 transfers and computes, so steady-state per-pane
    latency approaches max(upload + compute, RTT) instead of their sum.

    ``panes``: iterable of (src, dst) numpy id arrays.  ``recorder``: optional
    WindowLatencyRecorder; per pane, close = submission time, emit = host
    fetch completion (panes with index < ``warmup`` are not recorded —
    compile/first-touch).  Returns the list of counts in pane order.

    Latency accounting is per *window*: with pipelining a pane's measured
    close->result interval includes the next pane's submission — that is the
    steady-state cost a continuously sliced stream actually observes
    (WindowTriangles.java:60-65 panes close back-to-back the same way).

    The host pack/compaction and the device upload run on the Prefetcher's
    two background threads (io/wire.py), so a pane's 4 B/edge wire transfer
    hides under the previous pane's kernel: the measured latency is
    dispatch + MXU compute + readback, not the upload.

    ``device_recorder`` (optional WindowLatencyRecorder) captures the
    close -> DEVICE-completion interval separately from ``recorder``'s
    close -> host-visible-result interval.  The two differ by the device->
    host result delivery: ~tens of microseconds on a PCIe host, but ~40-65 ms
    through the session tunnel (BASELINE.md) — an environmental floor on the
    host-visible number that no pipelining removes, while pane *throughput*
    still pipelines (the async readback of pane k rides under panes k+1..).
    """
    import time as _time

    import jax as _jax

    from gelly_streaming_tpu.io.wire import Prefetcher

    counts = []
    pending = []  # (index, t_close, handle)
    # A pane "closes" when it ENTERS the Prefetcher — so the recorded
    # latency covers host pack/compaction + upload + dispatch + compute (+
    # readback for ``recorder``), not just the post-upload tail.  (Round-3
    # numbers stamped t_close after the upload and are not comparable —
    # advisor finding, BASELINE.md round-4 note.)  Caveat: with panes
    # arriving back-to-back (as in the bench) the pack thread pulls ahead,
    # so a pane's measured interval also includes its residence in the
    # depth-bounded prefetch queues — the number is the SATURATED-pipeline
    # latency and scales with ``depth``; a stream whose windows close slower
    # than the pipeline drains sees no queueing and a smaller number.
    enter_t = {}

    def stamped():
        for k, p in enumerate(panes):
            enter_t[k] = _time.perf_counter()
            yield p

    def drain_one():
        k, t_close, handle = pending.pop(0)
        if device_recorder is not None and handle[0] != "const":
            _jax.block_until_ready(handle[1])
            if k >= warmup:
                device_recorder.record(
                    (_time.perf_counter() - t_close) * 1e3
                )
        counts.append(_pane_triangle_finish(handle))
        if recorder is not None and k >= warmup:
            recorder.record((_time.perf_counter() - t_close) * 1e3)

    with Prefetcher(stamped(), _pane_prepare, depth=max(depth, 2)) as pf:
        for k, (meta, dev) in enumerate(pf):
            t_close = enter_t.pop(k)
            pending.append((k, t_close, _pane_dispatch(meta, dev)))
            if len(pending) >= depth:
                drain_one()
    while pending:
        drain_one()
    return counts


from gelly_streaming_tpu.core import compile_cache


def _superpane_count_fn(k: int, e_pad: int, num_vertices: int, max_deg: int):
    """Compiled K-pane triangle counter: one vmapped masked-CSR dispatch
    over ``k`` panes' canonical edges (padded to shared static shapes) —
    the superbatch form of the per-pane ``_count_kernel`` dispatch.  Exact:
    per pane it is the same |N(u) & N(v)| equality reduction, with padding
    rows masked out of both the insert and the reduction."""
    from gelly_streaming_tpu.core import compile_cache

    def make():
        def one(u, v, ok):
            table = nbr_ops.init_table(num_vertices, max_deg)
            both_src = jnp.concatenate([u, v])
            both_dst = jnp.concatenate([v, u])
            table = nbr_ops.insert_batch(
                table, both_src, both_dst, jnp.concatenate([ok, ok])
            )
            rows_u, valid_u = nbr_ops.gather_rows(table, u)
            rows_v, valid_v = nbr_ops.gather_rows(table, v)
            eq = (
                (rows_u[:, :, None] == rows_v[:, None, :])
                & valid_u[:, :, None]
                & valid_v[:, None, :]
                & ok[:, None, None]
            )
            return jnp.sum(eq.astype(jnp.int32)) // 3

        return jax.vmap(one)

    return compile_cache.cached_jit(
        ("superpane_triangles", k, e_pad, num_vertices, max_deg), make
    )


def _superpane_canonical(pane_edges):
    """Canonicalize one pane's edges for the masked-CSR counter: dedup'd
    undirected (lo, hi) pairs, self-loops dropped, ids COMPACTED to the
    pane's vertex set (the same host prep as _pane_prepare's CSR path)."""
    src, dst = pane_edges
    if len(src) == 0:
        return None
    lo = np.minimum(src, dst)
    hi = np.maximum(src, dst)
    keep = lo != hi
    pairs = np.unique(np.stack([lo[keep], hi[keep]], axis=1), axis=0)
    if len(pairs) == 0:
        return None
    u, v = pairs[:, 0].astype(np.int32), pairs[:, 1].astype(np.int32)
    verts, inv = np.unique(np.concatenate([u, v]), return_inverse=True)
    cu = inv[: len(u)].astype(np.int32)
    cv = inv[len(u) :].astype(np.int32)
    deg = np.bincount(np.concatenate([cu, cv]), minlength=len(verts))
    return cu, cv, len(verts), int(deg.max())


def _superbatched_window_counts(panes, k: int):
    """(count, max_timestamp) per pane, up to ``k`` panes per dispatch.

    Pane boundaries live in the stacked leading axis; shapes are shared
    per group (bucketed powers of two), so successive groups of similar
    panes reuse executables via the compile cache.
    """
    from gelly_streaming_tpu.core.windows import group_panes

    # keep_empty: this consumer emits (0, max_timestamp) even for panes
    # with no edges, exactly as the per-pane dispatch path does
    for group in group_panes(iter(panes), k, keep_empty=True):
        prepped = [_superpane_canonical((p.src, p.dst)) for p in group]
        live = [i for i, pr in enumerate(prepped) if pr is not None]
        counts = [0] * len(group)
        if live:
            e_pad = max(1, 1 << (max(len(prepped[i][0]) for i in live) - 1).bit_length())
            n_v = max(1, 1 << (max(prepped[i][2] for i in live) - 1).bit_length())
            d_max = max(1, 1 << (max(prepped[i][3] for i in live) - 1).bit_length())
            # pow2 row bucket (matching the docstring + the aggregation
            # path): varying group occupancy must not mint new compiled
            # variants per count — extra rows are fully masked, count 0
            kk = max(1, 1 << (len(live) - 1).bit_length())
            u = np.zeros((kk, e_pad), np.int32)
            v = np.zeros((kk, e_pad), np.int32)
            ok = np.zeros((kk, e_pad), bool)
            for row, i in enumerate(live):
                cu, cv, _, _ = prepped[i]
                u[row, : len(cu)] = cu
                v[row, : len(cv)] = cv
                ok[row, : len(cu)] = True
            fn = _superpane_count_fn(kk, e_pad, n_v, d_max)
            out = np.asarray(fn(jnp.asarray(u), jnp.asarray(v), jnp.asarray(ok)))
            for row, i in enumerate(live):
                counts[i] = int(out[row])
        for i, pane in enumerate(group):
            yield counts[i], pane.max_timestamp


def _count_kernel_impl(u: jax.Array, v: jax.Array, num_vertices: int, max_deg: int):
    """sum over edges |N(u) & N(v)| / 3 with a padded-CSR equality reduction."""
    e = u.shape[0]
    table = nbr_ops.init_table(num_vertices, max_deg)
    both_src = jnp.concatenate([u, v])
    both_dst = jnp.concatenate([v, u])
    table = nbr_ops.insert_batch(
        table, both_src, both_dst, jnp.ones((2 * e,), bool)
    )
    rows_u, valid_u = nbr_ops.gather_rows(table, u)  # [E, D]
    rows_v, valid_v = nbr_ops.gather_rows(table, v)
    eq = (
        (rows_u[:, :, None] == rows_v[:, None, :])
        & valid_u[:, :, None]
        & valid_v[:, None, :]
    )
    return jnp.sum(eq.astype(jnp.int32)) // 3


# shared executable for the per-pane count: (num_vertices, max_deg) are
# pow2-bucketed by the caller, so each bucket compiles once process-wide
_count_kernel = compile_cache.cached_jit(
    ("tri_count_kernel",), lambda: _count_kernel_impl, static_argnums=(2, 3)
)


def window_triangles(
    stream, window_ms: int, slide_ms: "int | None" = None
) -> OutputStream:
    """(triangle_count, window_max_timestamp) per closed pane
    (output shape of WindowTriangles.java:60-65's final sum).

    Panes pipeline one deep: pane k+1's upload/compute is submitted before
    pane k's count is fetched, hiding the readback RTT behind device work.
    ``slide_ms`` (must divide ``window_ms``) counts sliding windows via
    pane-sharing (core/windows.sliding_panes) — beyond the tumbling-only
    reference.
    """
    validate_slide(window_ms, slide_ms)
    from gelly_streaming_tpu.core import async_exec

    depth = async_exec.resolve_depth(stream.cfg)
    if depth > 0 and stream.cfg.superbatch <= 1:
        # asynchronous window pipeline (core/async_exec.py): pane
        # pack/compaction on the pack thread, uploads on the transfer
        # thread, counts dispatched without waiting and fetched through the
        # completion queue in window order — the deep generalization of the
        # one-deep submit/finish overlap below, with cfg.async_windows
        # panes in flight.  Counts are identical to the sequential path
        # (pinned by tests/test_async_windows.py).
        def records_async() -> Iterator[tuple]:
            def prepare(pane):
                meta, arrays = _pane_prepare((pane.src, pane.dst))
                return (pane.max_timestamp, meta), arrays

            def dispatch(meta, dev):
                return _pane_dispatch(meta[1], dev)

            def finish(meta, handle):
                return (_pane_triangle_finish(handle), meta[0])

            yield from async_exec.pipelined(
                windowed_panes(stream, window_ms, slide_ms),
                prepare,
                dispatch,
                finish,
                depth,
                prefetch_depth=max(2, depth),
            )

        return OutputStream(records_async)

    if stream.cfg.superbatch > 1:
        # superbatch dispatch coalescing: up to K panes count in ONE
        # vmapped masked-CSR dispatch (exact same counts — pinned by
        # tests/test_superbatch.py against the per-pane path)
        def records_sb() -> Iterator[tuple]:
            yield from _superbatched_window_counts(
                windowed_panes(stream, window_ms, slide_ms),
                stream.cfg.superbatch,
            )

        return OutputStream(records_sb)

    def records() -> Iterator[tuple]:
        pending = None  # (handle, timestamp) of the previous pane
        for pane in windowed_panes(stream, window_ms, slide_ms):
            try:
                handle = _pane_triangle_submit(pane.src, pane.dst)
            except BaseException:
                # pane k's count is already computed — deliver it before
                # propagating pane k+1's failure (the sequential version
                # emitted it first)
                if pending is not None:
                    yield (_pane_triangle_finish(pending[0]), pending[1])
                    pending = None
                raise
            if pending is not None:
                yield (_pane_triangle_finish(pending[0]), pending[1])
            pending = (handle, pane.max_timestamp)
        if pending is not None:
            yield (_pane_triangle_finish(pending[0]), pending[1])

    return OutputStream(records)


# ---------------------------------------------------------------------------
# Streaming exact count (insertion-only)
# ---------------------------------------------------------------------------

GLOBAL_KEY = -1  # reference routes the global counter under key -1
# (ExactTriangleCount.java:108-110)


class TriangleCountState(NamedTuple):
    table: nbr_ops.NeighborTable  # undirected adjacency over the whole stream
    local: jax.Array  # int32[C] per-vertex triangle counts
    global_count: jax.Array  # int32[]


def init_triangle_state(cfg: StreamConfig) -> TriangleCountState:
    return TriangleCountState(
        table=nbr_ops.init_table(cfg.vertex_capacity, cfg.max_degree),
        local=jnp.zeros((cfg.vertex_capacity,), jnp.int32),
        global_count=jnp.zeros((), jnp.int32),
    )


def triangle_update(
    state: TriangleCountState, src, dst, mask
) -> Tuple[TriangleCountState, jax.Array, jax.Array]:
    """Fold an edge batch; returns (state, local_after[B,2], global_after[B]).

    Per edge (in arrival order): count common neighbors c of the canonical
    endpoints in the adjacency-so-far, bump local[u], local[v] by c, local[w]
    by 1 for each common w, and the global count by c — then insert the edge
    (IntersectNeighborhoods + SumAndEmitCounters semantics,
    ExactTriangleCount.java:74-134, with duplicate edges ignored).
    """
    capacity, max_degree = state.table.nbrs.shape

    def step(carry, inp):
        table, local, glob = carry
        u, v, ok = inp
        lo = jnp.minimum(u, v)
        hi = jnp.maximum(u, v)
        dup = nbr_ops.contains_batch(table, lo[None], hi[None])[0] | (lo == hi)
        ok = ok & ~dup
        row_u = table.nbrs[lo]
        row_v = table.nbrs[hi]
        valid_u = jnp.arange(max_degree) < table.deg[lo]
        valid_v = jnp.arange(max_degree) < table.deg[hi]
        eq = (
            (row_u[:, None] == row_v[None, :])
            & valid_u[:, None]
            & valid_v[None, :]
        )
        c = jnp.where(ok, jnp.sum(eq.astype(jnp.int32)), 0)
        common_mask = jnp.any(eq, axis=1) & ok  # [D] over row_u slots
        local = local.at[jnp.where(common_mask, row_u, 0)].add(
            common_mask.astype(jnp.int32)
        )
        local = local.at[lo].add(c)
        local = local.at[hi].add(c)
        glob = glob + c
        table = nbr_ops.insert_batch(
            table,
            jnp.stack([lo, hi]),
            jnp.stack([hi, lo]),
            jnp.stack([ok, ok]),
        )
        return (table, local, glob), (
            jnp.stack([local[lo], local[hi]]),
            glob,
        )

    (table, local, glob), (local_trace, global_trace) = jax.lax.scan(
        step, (state.table, state.local, state.global_count), (src, dst, mask)
    )
    return TriangleCountState(table, local, glob), local_trace, global_trace


def triangle_update_block(
    state: TriangleCountState, src, dst, mask, chunk: int = 64
) -> TriangleCountState:
    """Batch-vectorized exact triangle fold — same final state as
    ``triangle_update``, without the per-edge trace (VERDICT r1 item 7).

    The per-edge scan pays a [D] gather + [D, D] comparison per edge,
    sequentially.  Here the batch folds in chunks of ``chunk`` edges; per
    chunk ONE set of dense tensor ops handles all three ways a chunk edge
    (u, v) can close a wedge u–w–v (attribution to the LAST arriving edge of
    each triangle, as in the single-pass algorithm,
    ExactTriangleCount.java:74-116):

      old-old:  both wedge edges pre-chunk — a [r, D, D] masked equality
                reduction over the endpoints' adjacency rows;
      old-new:  one wedge edge earlier in the chunk, the other pre-chunk —
                a [r, r, D] membership test against the gathered rows;
      new-new:  both wedge edges earlier in the chunk — a [r, r, r]
                elementwise condition tensor (no lookups at all).

    Cross-chunk dependencies need nothing special: chunks fold sequentially
    and earlier chunks are already in the table ("old").  Duplicate edges are
    ignored exactly as in the scan path (table membership + first-occurrence
    within the chunk).
    """
    capacity, max_degree = state.table.nbrs.shape
    from gelly_streaming_tpu.ops import segments

    b = src.shape[0]
    r = min(chunk, b)
    pad = (-b) % r
    if pad:
        src = jnp.concatenate([src, jnp.zeros((pad,), src.dtype)])
        dst = jnp.concatenate([dst, jnp.zeros((pad,), dst.dtype)])
        mask = jnp.concatenate([mask, jnp.zeros((pad,), bool)])
    n_chunks = (b + pad) // r
    lo = jnp.minimum(src, dst).reshape(n_chunks, r)
    hi = jnp.maximum(src, dst).reshape(n_chunks, r)
    ok0 = (mask & (jnp.minimum(src, dst) != jnp.maximum(src, dst))).reshape(
        n_chunks, r
    )

    lower = jnp.tril(jnp.ones((r, r), bool), -1)  # [j, i]: i < j

    def step(carry, inp):
        table, local, glob = carry
        lo, hi, ok = inp
        ok = (
            ok
            & ~nbr_ops.contains_batch(table, lo, hi)
            & segments.first_occurrence_mask_pairs(lo, hi, ok)
        )
        row_lo, valid_lo = nbr_ops.gather_rows(table, lo)  # [r, D]
        row_hi, valid_hi = nbr_ops.gather_rows(table, hi)

        # -- old-old: [r, D, D]
        eq = (
            (row_lo[:, :, None] == row_hi[:, None, :])
            & valid_lo[:, :, None]
            & valid_hi[:, None, :]
        )
        c1 = jnp.where(ok, jnp.sum(eq, axis=(1, 2)), 0)
        common1 = eq.any(axis=2) & ok[:, None]  # marks on row_lo slots

        # pair geometry among chunk edges: does e_i touch e_j's endpoints?
        pair_ok = lower & ok[:, None] & ok[None, :]  # [j, i]
        i_lo, i_hi = lo[None, :], hi[None, :]  # e_i endpoints, broadcast on j
        shares_lo = (i_lo == lo[:, None]) | (i_hi == lo[:, None])  # e_i ∋ lo_j
        shares_hi = (i_lo == hi[:, None]) | (i_hi == hi[:, None])  # e_i ∋ hi_j
        w_lo = jnp.where(i_lo == lo[:, None], i_hi, i_lo)  # other end of e_i
        w_hi = jnp.where(i_lo == hi[:, None], i_hi, i_lo)

        # -- old-new: wedge edge e_i in chunk (earlier), mate edge pre-chunk.
        # (lo_j, w)=e_i and (hi_j, w) old  <=>  w in row_hi[j]; and symmetric.
        def member(rows, valid, w):  # [j, D] rows vs [j, i] queries
            return jnp.any(
                (rows[:, None, :] == w[:, :, None]) & valid[:, None, :], axis=2
            )

        c2a = pair_ok & shares_lo & member(row_hi, valid_hi, w_lo)
        c2b = pair_ok & shares_hi & member(row_lo, valid_lo, w_hi)
        c2 = jnp.sum(c2a, axis=1) + jnp.sum(c2b, axis=1)

        # -- new-new: wedge edges e_i (∋ lo_j) and e_k (∋ hi_j), both earlier,
        # meeting at the same w: [j, i, k]
        a3 = pair_ok & shares_lo  # [j, i]
        b3 = pair_ok & shares_hi  # [j, k]
        cond3 = (
            a3[:, :, None]
            & b3[:, None, :]
            & (w_lo[:, :, None] == w_hi[:, None, :])
        )
        c3 = jnp.sum(cond3, axis=(1, 2))
        w3_weight = jnp.sum(cond3, axis=2)  # per (j, i): marks on w_lo[j, i]

        c = c1 + c2 + c3
        # counter updates (SumAndEmitCounters semantics): endpoints get c,
        # each common w gets +1, the global key accumulates everything
        local = local.at[jnp.where(common1, row_lo, 0)].add(
            common1.astype(jnp.int32)
        )
        local = local.at[jnp.where(c2a, w_lo, 0)].add(c2a.astype(jnp.int32))
        local = local.at[jnp.where(c2b, w_hi, 0)].add(c2b.astype(jnp.int32))
        local = local.at[jnp.where(w3_weight > 0, w_lo, 0)].add(w3_weight)
        local = local.at[jnp.where(ok, lo, 0)].add(jnp.where(ok, c, 0))
        local = local.at[jnp.where(ok, hi, 0)].add(jnp.where(ok, c, 0))
        glob = glob + jnp.sum(c)
        table = nbr_ops.insert_batch(
            table,
            jnp.concatenate([lo, hi]),
            jnp.concatenate([hi, lo]),
            jnp.concatenate([ok, ok]),
        )
        return (table, local, glob), None

    (table, local, glob), _ = jax.lax.scan(
        step, (state.table, state.local, state.global_count), (lo, hi, ok0)
    )
    return TriangleCountState(table, local, glob)


class ExactTriangleCount:
    """Host-facing runner: continuous (key, count) updates, key -1 = global.

    ``mode="block"`` (default) rides the chunk-vectorized fold
    (triangle_update_block) and emits one block of running (key, count)
    records per micro-batch — the endpoints it touched plus the global key —
    the per-batch relaxation SURVEY §7 anticipates for batched execution.
    ``mode="trace"`` opts into the reference's exact per-edge running trace
    via the sequential scan kernel (golden parity; ~B times more device
    round-trips and per-record Python, so not the production default —
    VERDICT r2 weak #5).
    """

    def __init__(self, cfg: Optional[StreamConfig] = None, mode: str = "block"):
        if mode not in ("trace", "block"):
            raise ValueError(f"unknown mode {mode!r}")
        from gelly_streaming_tpu.core import compile_cache

        self.mode = mode
        # module-level kernels: every runner instance shares the executables
        self._kernel = compile_cache.cached_jit(
            ("triangle_update",), lambda: triangle_update
        )
        self._block_kernel = compile_cache.cached_jit(
            ("triangle_update_block",), lambda: triangle_update_block
        )

    def run(self, stream) -> OutputStream:
        if self.mode == "block":
            return self._run_blocks(stream)

        def records():
            state = init_triangle_state(stream.cfg)
            for batch in stream.batches():
                state, local_trace, global_trace = self._kernel(
                    state, batch.src, batch.dst, batch.mask
                )
                l_h = np.asarray(local_trace)
                g_h = np.asarray(global_trace)
                m_h = np.asarray(batch.mask)
                s_h = np.asarray(batch.src)
                d_h = np.asarray(batch.dst)
                for i in np.nonzero(m_h)[0]:
                    u, v = int(min(s_h[i], d_h[i])), int(max(s_h[i], d_h[i]))
                    yield (u, int(l_h[i, 0]))
                    yield (v, int(l_h[i, 1]))
                    yield (GLOBAL_KEY, int(g_h[i]))
            self.final_state = state

        return OutputStream(records)

    def _run_blocks(self, stream) -> OutputStream:
        from gelly_streaming_tpu.core.output import RecordBlock

        def blocks():
            state = init_triangle_state(stream.cfg)
            prev_local = np.asarray(state.local)
            for batch in stream.batches():
                state = self._block_kernel(
                    state, batch.src, batch.dst, batch.mask
                )
                m_h = np.asarray(batch.mask)
                local_h = np.asarray(state.local)
                # endpoints of the batch plus every vertex whose counter moved
                # (common neighbors w also get updates in the reference,
                # ExactTriangleCount.java:95-104)
                touched = np.unique(
                    np.concatenate(
                        [
                            np.asarray(batch.src)[m_h],
                            np.asarray(batch.dst)[m_h],
                            np.nonzero(local_h != prev_local)[0],
                        ]
                    )
                )
                prev_local = local_h
                keys = np.concatenate([touched, [GLOBAL_KEY]]).astype(np.int64)
                counts = np.concatenate(
                    [local_h[touched], [int(state.global_count)]]
                )
                yield RecordBlock((keys, counts))
            self.final_state = state

        return OutputStream(blocks_fn=blocks)

"""Batched capacity-bounded neighbor tables (device adjacency state).

The reference keeps per-key adjacency as JVM collections inside stateful
operators: per-key ``HashSet<Edge>`` for distinct (SimpleEdgeStream.java:309-323)
and per-vertex ``TreeSet`` for buildNeighborhood (SimpleEdgeStream.java:540-560).
The TPU-native state is a dense table ``nbrs: int32[C, D]`` (-1 = empty slot)
plus ``deg: int32[C]``, updated for a whole micro-batch in one vectorized pass:
sort rows by source, rank within group, scatter to ``deg[src] + rank``.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from gelly_streaming_tpu.ops import segments


class NeighborTable(NamedTuple):
    """Pytree state: padded adjacency rows + row occupancy + overflow counter."""

    nbrs: jax.Array  # int32[C, D], -1 = empty
    deg: jax.Array  # int32[C]
    dropped: jax.Array  # int32[] — rows lost to capacity overflow (observability)


def init_table(capacity: int, max_degree: int) -> NeighborTable:
    return NeighborTable(
        nbrs=jnp.full((capacity, max_degree), -1, dtype=jnp.int32),
        deg=jnp.zeros((capacity,), dtype=jnp.int32),
        dropped=jnp.zeros((), dtype=jnp.int32),
    )


def contains_batch(
    table: NeighborTable, src: jax.Array, dst: jax.Array
) -> jax.Array:
    """For each row i: is dst[i] already in N(src[i])?  Vectorized [B, D] compare."""
    rows = table.nbrs[src]  # [B, D]
    return jnp.any(rows == dst[:, None], axis=1)


def insert_batch(
    table: NeighborTable,
    src: jax.Array,
    dst: jax.Array,
    mask: jax.Array,
) -> NeighborTable:
    """Append dst[i] to N(src[i]) for every masked row, in one vectorized pass.

    Caller is responsible for dedup (contains_batch + in-batch first-occurrence);
    this routine appends unconditionally.  Rows that would exceed a row's
    capacity D are dropped and counted in ``dropped``.
    """
    capacity, max_degree = table.nbrs.shape
    rank = segments.occurrence_rank(src, mask)
    pos = table.deg[src] + rank
    ok = mask & (pos < max_degree)
    # Flat scatter: row-major slot index; masked/overflow rows write to a
    # sacrificial slot past the end (dropped by the scatter's OOB semantics).
    flat_idx = jnp.where(ok, src * max_degree + pos, capacity * max_degree)
    nbrs = (
        table.nbrs.reshape(-1)
        .at[flat_idx]
        .set(jnp.where(ok, dst, -1), mode="drop")
        .reshape(capacity, max_degree)
    )
    deg = table.deg.at[jnp.where(ok, src, 0)].add(ok.astype(jnp.int32))
    dropped = table.dropped + jnp.sum((mask & ~ok).astype(jnp.int32))
    return NeighborTable(nbrs=nbrs, deg=deg, dropped=dropped)


def insert_unique_batch(
    table: NeighborTable,
    src: jax.Array,
    dst: jax.Array,
    mask: Optional[jax.Array] = None,
) -> Tuple[NeighborTable, jax.Array]:
    """Insert only rows not already present (in table or earlier in the batch).

    Returns (new_table, is_new) where is_new marks rows actually inserted — the
    device analog of the reference's ``HashSet.add`` returning true
    (SimpleEdgeStream.java:313-320).
    """
    if mask is None:
        mask = jnp.ones(src.shape, bool)
    present = contains_batch(table, src, dst)
    first = segments.first_occurrence_mask_pairs(src, dst, mask)
    is_new = mask & ~present & first
    return insert_batch(table, src, dst, is_new), is_new


def gather_rows(table: NeighborTable, vertices: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """(neighbors [B, D], valid [B, D]) for a batch of vertices."""
    rows = table.nbrs[vertices]
    valid = jnp.arange(table.nbrs.shape[1])[None, :] < table.deg[vertices][:, None]
    return rows, valid


def insert_unique_valued_batch(
    table: NeighborTable,
    vtable: NeighborTable,
    src: jax.Array,
    dst: jax.Array,
    val_bits: jax.Array,
    mask: Optional[jax.Array] = None,
) -> Tuple[NeighborTable, NeighborTable, jax.Array]:
    """Whole-EDGE distinct: a row is new iff its (src, dst, value) triple is.

    Two slot-aligned neighbor tables carry the per-src entries — ``table``
    stores the dst ids, ``vtable`` the int32-bitcast edge values.  Both are
    driven by the same insert mask, so their degrees and slot layouts stay
    identical by construction and presence is a same-slot conjunction.
    This is the dense-array form of the reference's per-key HashSet over
    whole Edges (SimpleEdgeStream.java:309-323).
    """
    if mask is None:
        mask = jnp.ones(src.shape, bool)
    rows_d, valid = gather_rows(table, src)
    rows_v = vtable.nbrs[src]
    present = jnp.any(
        (rows_d == dst[:, None]) & (rows_v == val_bits[:, None]) & valid,
        axis=1,
    )
    first = segments.first_occurrence_mask_triples(src, dst, val_bits, mask)
    is_new = mask & ~present & first
    return (
        insert_batch(table, src, dst, is_new),
        insert_batch(vtable, src, val_bits, is_new),
        is_new,
    )

"""GraphBLAST-style masked semiring SpMV kernel core with push/pull
direction optimization.

The iterative vertex programs in library/ (pagerank, sssp, k-core,
iterative CC) each used to carry a private jitted kernel around the same
two device idioms: "combine a candidate per masked edge into a dense [C]
summary" (scatter-reduce) and "iterate that under ``lax.while_loop`` to a
fixed point".  This module is the shared home for that linear-algebra
core, in the masked-semiring formulation of GraphBLAST (Yang et al.,
arXiv:1908.01407): a graph pane is a sparse matrix, one propagation round
is y = A^T x over an (add, mul) semiring restricted by an edge mask, and
an algorithm is a semiring + an initial vector + a fixpoint policy.

Two lowerings serve every product:

* **pull (SpMV, dense mask)** — one gather over the pane's dst-STABLE-
  sorted edge copy plus a sorted segment reduction.  Cost is O(e_pad) with
  segment-local writes; the right regime when many vertices are active.
* **push (SpMSpV, sparse frontier)** — expand the active rows of the
  src-sorted CSR into a pow2-bucketed candidate buffer (masked-degree
  cumsum + searchsorted), then scatter-reduce the candidates.  Cost is
  O(f_cap): a frontier touching few edges pays the small bucket, not the
  whole pane.

Direction optimization (Beamer-style, via GraphBLAST's mask-density rule):
inside one cached while_loop executable the per-iteration direction is a
branchless ``lax.cond`` on frontier density vs a TRACED threshold — one
executable serves push, pull, and auto (force modes fold into the
threshold scalar: 2.0 is never exceeded -> always push; -1.0 always is ->
always pull), so flipping GELLY_SPMV_DIRECTION never recompiles.  Real
shape savings come from the host driver escalating through pow2 frontier
capacity buckets (``frontier_caps``): sparse phases run the small-f_cap
executable, dense phases the flat pull — every bucket cached through
core/compile_cache, zero recompiles across panes and direction changes
(pinned by tests/test_spmv.py).

Bit-exactness contract: for idempotent semirings every lowering produces
per-iteration-identical states (a dominated candidate stays dominated,
so relaxing only frontier rows equals relaxing all rows); for plus-times
the pull lowering's dst-STABLE sort preserves each destination's addend
arrival order, so the sorted segment sum accumulates the same sequence
the arrival-order scatter-add does.  The rebuilt library algorithms emit
byte-identical records in every direction mode (tests/test_spmv.py).
"""

from __future__ import annotations

import os
from typing import Callable, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from gelly_streaming_tpu.core import compile_cache
from gelly_streaming_tpu.ops import unionfind as uf
from gelly_streaming_tpu.utils import metrics
from gelly_streaming_tpu.utils.envswitch import resolve_choice

# Frontier density (|frontier| / |active vertices|) above which "auto"
# switches from the sparse push to the dense pull lowering.  Tuned on the
# skewed-community bench graph (bench.py _spmv_bench): push's expansion
# machinery beats the flat pull only while the frontier touches a small
# fraction of the pane.
DEFAULT_DIRECTION_THRESHOLD = 0.05

DIRECTIONS = ("auto", "push", "pull")

_HIST_BINS = metrics.SPMV_DENSITY_BINS


# ---------------------------------------------------------------------------
# semiring descriptors


def _segment_min(vals, seg, num_segments):
    return jax.ops.segment_min(
        vals, seg, num_segments=num_segments, indices_are_sorted=True
    )


def _segment_sum(vals, seg, num_segments):
    return jax.ops.segment_sum(
        vals, seg, num_segments=num_segments, indices_are_sorted=True
    )


def _scatter_min(target, idx, vals):
    return target.at[idx].min(vals, mode="drop")


def _scatter_add(target, idx, vals):
    return target.at[idx].add(vals, mode="drop")


class Semiring(NamedTuple):
    """An (add, mul) pair with the three reduction lowerings it admits.

    ``identity`` is add's neutral element (the empty-row value);
    ``idempotent`` marks add(a, a) == a — the property that makes
    frontier-restricted (push) iteration state-identical to full
    relaxation, and hence which semirings ``fixpoint`` accepts.
    ``scatter`` combines candidates into an existing [C] target at given
    rows (out-of-range rows drop — the padding sentinel); ``segment``
    reduces a dst-sorted candidate vector segment-wise.
    """

    name: str
    identity: float
    idempotent: bool
    mul: Callable
    combine: Callable
    scatter: Callable
    segment: Callable


#: min-plus: shortest-path relaxation (sssp).
MIN_PLUS = Semiring(
    "min_plus", 1e30, True,
    lambda x, w: x + w, jnp.minimum, _scatter_min, _segment_min,
)
#: plus-times: mass spreading (pagerank's damped transition).
PLUS_TIMES = Semiring(
    "plus_times", 0.0, False,
    lambda x, w: x * w, lambda a, b: a + b, _scatter_add, _segment_sum,
)
#: min-min: label propagation (iterative CC's hooking step).
MIN_MIN = Semiring(
    "min_min", 2**31 - 1, True,
    lambda x, w: jnp.minimum(x, w.astype(x.dtype)),
    jnp.minimum, _scatter_min, _segment_min,
)
#: plus-one: degree / incidence counting (k-core's estimate init).
PLUS_ONE = Semiring(
    "plus_one", 0, False,
    lambda x, w: jnp.ones_like(x), lambda a, b: a + b,
    _scatter_add, _segment_sum,
)


# ---------------------------------------------------------------------------
# pane operator: one pane's edges in the layouts the lowerings need


class PaneOperator(NamedTuple):
    """One pane's (padded) edge list as a masked sparse matrix, in the
    three layouts the lowerings need: arrival order (bit-exact plus-times
    scatter), src-sorted CSR (push expansion), and dst-STABLE-sorted
    (pull segment reduce).  ``n_active`` counts the vertices incident to
    any masked edge — the density denominator."""

    capacity: int
    e_pad: int
    src: jax.Array
    dst: jax.Array
    w: jax.Array
    msk: jax.Array
    s_dst: jax.Array
    s_w: jax.Array
    s_msk: jax.Array
    off: jax.Array
    d_src: jax.Array
    d_dst: jax.Array
    d_w: jax.Array
    d_msk: jax.Array
    n_active: jax.Array


def prepare_pane(src, dst, w, msk, capacity: int) -> PaneOperator:
    """Sort one padded pane into a :class:`PaneOperator` (on device, one
    cached executable per (capacity, e_pad); ``w=None`` means unit
    weights).  Masked-out rows sort past every real key so the CSR offsets
    and segment ids never see them."""
    src = jnp.asarray(src, jnp.int32)
    dst = jnp.asarray(dst, jnp.int32)
    msk = jnp.asarray(msk, bool)
    e_pad = int(src.shape[0])
    w = (
        jnp.ones((e_pad,), jnp.float32)
        if w is None
        else jnp.asarray(w, jnp.float32)
    )

    def build():
        def kernel(src, dst, w, msk):
            key_s = jnp.where(msk, src, capacity)
            o = jnp.argsort(key_s)  # stable
            off = jnp.searchsorted(
                key_s[o], jnp.arange(capacity + 1)
            ).astype(jnp.int32)
            key_d = jnp.where(msk, dst, capacity)
            o2 = jnp.argsort(key_d)  # stable: arrival order kept per dst
            act = jnp.zeros((capacity,), bool)
            act = act.at[jnp.where(msk, src, 0)].max(msk)
            act = act.at[jnp.where(msk, dst, 0)].max(msk)
            return (
                dst[o], w[o], msk[o], off,
                src[o2], dst[o2], w[o2], msk[o2],
                jnp.sum(act.astype(jnp.int32)),
            )

        return kernel

    fn = compile_cache.cached_jit(
        ("spmv_prep", capacity, e_pad), build, label="spmv"
    )
    return PaneOperator(capacity, e_pad, src, dst, w, msk, *fn(src, dst, w, msk))


def frontier_caps(e_pad: int) -> tuple:
    """The pow2 frontier-capacity buckets the driver escalates through."""
    return tuple(
        sorted({
            min(e_pad, max(256, e_pad >> 4)),
            min(e_pad, max(256, e_pad >> 2)),
            e_pad,
        })
    )


# ---------------------------------------------------------------------------
# the two lowerings (traced helpers shared by one-shots and fixpoint runs)


def _push_product(sem, capacity, f_cap, off, deg, s_dst, s_w, s_msk, x, fm):
    """SpMSpV: expand the frontier's CSR rows into f_cap candidate slots
    and scatter-reduce.  Slot j belongs to the j-th frontier edge (masked-
    degree exclusive cumsum + searchsorted); slots past the frontier's
    edge total target the out-of-range sentinel row and drop.  The caller
    guarantees the frontier's edge count fits f_cap."""
    ident = jnp.asarray(sem.identity, x.dtype)
    deg_f = jnp.where(fm, deg, 0)
    starts = jnp.cumsum(deg_f) - deg_f
    j = jnp.arange(f_cap)
    v = jnp.searchsorted(starts, j, side="right") - 1
    total = starts[-1] + deg_f[-1]
    ok = j < total
    e_idx = jnp.where(ok, off[v] + (j - starts[v]), 0)
    live = ok & s_msk[e_idx]
    rows = jnp.where(live, s_dst[e_idx], capacity)
    cand = jnp.where(live, sem.mul(x[v], s_w[e_idx]), ident)
    return sem.scatter(jnp.full((capacity,), ident, x.dtype), rows, cand)


def _pull_product(sem, capacity, d_src, d_w, d_msk, seg, x):
    """SpMV: gather over the dst-sorted edge copy, sorted segment reduce.
    Combining with an identity-filled vector normalizes empty segments to
    the semiring identity (segment_min's empty value is the dtype max)."""
    ident = jnp.asarray(sem.identity, x.dtype)
    cand = jnp.where(d_msk, sem.mul(x[d_src], d_w), ident)
    y = sem.segment(cand, seg, capacity + 1)[:capacity]
    return sem.combine(jnp.full((capacity,), ident, x.dtype), y)


def spmv_dense(sem: Semiring, op: PaneOperator, x) -> jax.Array:
    """One masked semiring SpMV (dense-mask pull lowering):
    ``y[d] = add over masked edges (s, d, w) of mul(x[s], w)``, identity
    where no edge lands."""
    capacity, e_pad = op.capacity, op.e_pad

    def build():
        def kernel(d_src, d_dst, d_w, d_msk, x):
            seg = jnp.where(d_msk, d_dst, capacity)
            return _pull_product(sem, capacity, d_src, d_w, d_msk, seg, x)

        return kernel

    fn = compile_cache.cached_jit(
        ("spmv_dense", sem.name, capacity, e_pad), build, label="spmv"
    )
    return fn(op.d_src, op.d_dst, op.d_w, op.d_msk, jnp.asarray(x))


def spmsv_frontier(
    sem: Semiring, op: PaneOperator, x, frontier, f_cap: Optional[int] = None
) -> jax.Array:
    """One masked semiring SpMSpV (sparse-frontier push lowering): the
    same product restricted to edges whose source is in ``frontier``.
    Refuses loudly when the frontier's edge count exceeds ``f_cap``
    (silent truncation would be a wrong answer, not a slow one)."""
    capacity, e_pad = op.capacity, op.e_pad
    if f_cap is None:
        f_cap = e_pad
    if not 1 <= f_cap <= e_pad:
        raise ValueError(f"f_cap {f_cap} outside [1, {e_pad}]")
    fm = jnp.asarray(frontier, bool)
    deg = op.off[1:] - op.off[:-1]
    fe = int(jnp.sum(jnp.where(fm, deg, 0)))
    if fe > f_cap:
        raise ValueError(
            f"frontier touches {fe} edges > f_cap {f_cap}; use a bigger "
            "bucket (frontier_caps) or the dense lowering"
        )

    def build():
        def kernel(off, s_dst, s_w, s_msk, x, fm):
            deg = off[1:] - off[:-1]
            return _push_product(
                sem, capacity, f_cap, off, deg, s_dst, s_w, s_msk, x, fm
            )

        return kernel

    fn = compile_cache.cached_jit(
        ("spmsv_frontier", sem.name, capacity, e_pad, f_cap),
        build,
        label="spmv",
    )
    return fn(op.off, op.s_dst, op.s_w, op.s_msk, jnp.asarray(x), fm)


def scatter_into(sem: Semiring, capacity: int, idx, vals, msk) -> jax.Array:
    """One-shot masked scatter-combine into an identity-filled [capacity]
    vector — the degenerate SpMV every degree/count init is (k-core seeds
    estimates with a PLUS_ONE scatter over the pane's src column)."""
    idx = jnp.asarray(idx, jnp.int32)
    vals = jnp.asarray(vals)
    msk = jnp.asarray(msk, bool)
    e_pad = int(idx.shape[0])

    def build():
        def kernel(idx, vals, msk):
            ident = jnp.asarray(sem.identity, vals.dtype)
            return sem.scatter(
                jnp.full((capacity,), ident, vals.dtype),
                jnp.where(msk, idx, capacity),
                jnp.where(msk, vals, ident),
            )

        return kernel

    fn = compile_cache.cached_jit(
        ("spmv_scatter", sem.name, capacity, e_pad, str(vals.dtype)),
        build,
        label="spmv",
    )
    return fn(idx, vals, msk)


# ---------------------------------------------------------------------------
# direction-optimized fixpoint


def _build_run(sem, capacity, e_pad, f_cap):
    """One while_loop executable that serves BOTH directions: each
    iteration picks push or pull by ``lax.cond`` on frontier density vs
    the traced threshold.  The loop exits early (for the host driver to
    escalate buckets) only when push is wanted but the frontier's edge
    count outgrew this bucket's f_cap."""

    def kernel(
        off, s_dst, s_w, s_msk, d_src, d_dst, d_w, d_msk, n_act,
        x, fm, thr, it, max_iters, last_dir,
        push_iters, pull_iters, switches, hist,
    ):
        deg = off[1:] - off[:-1]
        seg = jnp.where(d_msk, d_dst, capacity)
        denom = jnp.maximum(n_act, 1).astype(jnp.float32)

        def fstats(fm):
            fe = jnp.sum(jnp.where(fm, deg, 0))
            dens = jnp.sum(fm).astype(jnp.float32) / denom
            return fe, dens

        def push(args):
            x, fm = args
            return _push_product(
                sem, capacity, f_cap, off, deg, s_dst, s_w, s_msk, x, fm
            )

        def pull(args):
            x, fm = args
            return _pull_product(sem, capacity, d_src, d_w, d_msk, seg, x)

        def cond(c):
            x, fm, it = c[0], c[1], c[2]
            fe, dens = fstats(fm)
            return (
                jnp.any(fm)
                & (it < max_iters)
                & ((dens > thr) | (fe <= f_cap))
            )

        def body(c):
            (x, fm, it, last_dir, push_iters, pull_iters, switches, hist) = c
            _, dens = fstats(fm)
            use_pull = dens > thr
            y = jax.lax.cond(use_pull, pull, push, (x, fm))
            xn = sem.combine(x, y)
            d = use_pull.astype(jnp.int32)
            switched = ((last_dir >= 0) & (d != last_dir)).astype(jnp.int32)
            b = jnp.clip(
                (dens * _HIST_BINS).astype(jnp.int32), 0, _HIST_BINS - 1
            )
            return (
                xn, xn != x, it + 1, d,
                push_iters + (1 - d), pull_iters + d,
                switches + switched, hist.at[b].add(1),
            )

        c = jax.lax.while_loop(
            cond, body,
            (x, fm, it, last_dir, push_iters, pull_iters, switches, hist),
        )
        fe, _ = fstats(c[1])
        return c + (fe,)

    return kernel


class FixpointResult(NamedTuple):
    x: jax.Array
    frontier: jax.Array
    iters: int
    push_iters: int
    pull_iters: int
    switches: int


def _bucket_index(caps, fe: int) -> int:
    for i, cap in enumerate(caps):
        if fe <= cap:
            return i
    return len(caps) - 1


def fixpoint(
    sem: Semiring,
    op: PaneOperator,
    x0,
    *,
    max_iters: int,
    direction: str = "auto",
    threshold: Optional[float] = None,
    frontier=None,
) -> FixpointResult:
    """Iterate ``x = combine(x, A^T x)`` to a fixed point (or the
    iteration bound) with per-iteration push/pull direction optimization.

    Idempotent semirings only: frontier-restricted push relaxation equals
    full relaxation per iteration exactly when a dominated candidate stays
    dominated.  ``direction`` forces one lowering by folding into the
    traced threshold (no recompile); ``threshold`` is the auto-mode
    density cut, defaulting to :data:`DEFAULT_DIRECTION_THRESHOLD`.  The
    initial frontier defaults to the non-identity entries of ``x0``.
    """
    if not sem.idempotent:
        raise ValueError(
            f"fixpoint needs an idempotent semiring (frontier relaxation "
            f"must be dominance-stable); {sem.name} is not"
        )
    if direction not in DIRECTIONS:
        raise ValueError(
            f"direction {direction!r} is not one of {'/'.join(DIRECTIONS)}"
        )
    if threshold is None:
        threshold = DEFAULT_DIRECTION_THRESHOLD
    thr = {"push": 2.0, "pull": -1.0}.get(direction, float(threshold))
    x = jnp.asarray(x0)
    fm = (
        x != jnp.asarray(sem.identity, x.dtype)
        if frontier is None
        else jnp.asarray(frontier, bool)
    )
    caps = frontier_caps(op.e_pad)
    runs = [
        compile_cache.cached_jit(
            ("spmv_run", sem.name, op.capacity, op.e_pad, fc),
            lambda fc=fc: _build_run(sem, op.capacity, op.e_pad, fc),
            label="spmv",
        )
        for fc in caps
    ]
    it = jnp.int32(0)
    last_dir = jnp.int32(-1)
    push_i = pull_i = sw = jnp.int32(0)
    hist = jnp.zeros((_HIST_BINS,), jnp.int32)
    thr_j = jnp.float32(thr)
    mi = jnp.int32(max_iters)
    deg = op.off[1:] - op.off[:-1]
    k = _bucket_index(caps, int(jnp.sum(jnp.where(fm, deg, 0))))
    # every dispatch advances >= 1 iteration or strictly escalates the
    # bucket, so the dispatch count is bounded by the iteration budget
    for _ in range(int(max_iters) + len(caps) + 2):
        (x, fm, it, last_dir, push_i, pull_i, sw, hist, fe) = runs[k](
            op.off, op.s_dst, op.s_w, op.s_msk,
            op.d_src, op.d_dst, op.d_w, op.d_msk, op.n_active,
            x, fm, thr_j, it, mi, last_dir, push_i, pull_i, sw, hist,
        )
        if int(it) >= int(max_iters) or not bool(jnp.any(fm)):
            break
        # live frontier inside the budget: push is wanted (density under
        # threshold) but its edge count outgrew this bucket — escalate
        k = _bucket_index(caps, int(fe))
    else:
        raise RuntimeError("spmv fixpoint made no progress (driver bug)")
    metrics.spmv_add("spmv_fixpoints", 1)
    metrics.spmv_add("spmv_push_iters", int(push_i))
    metrics.spmv_add("spmv_pull_iters", int(pull_i))
    metrics.spmv_add("spmv_direction_switches", int(sw))
    h = np.asarray(hist)
    for b in range(_HIST_BINS):
        if int(h[b]):
            metrics.spmv_add(f"spmv_density_hist_{b}", int(h[b]))
    return FixpointResult(x, fm, int(it), int(push_i), int(pull_i), int(sw))


# ---------------------------------------------------------------------------
# algorithm kernels built on the core (hosted here so library/ modules
# keep only validation + emission)


def pagerank_fixpoint(
    op: PaneOperator, *, damping: float, tol: float, max_iters: int,
    use_pull: bool = False,
):
    """The damped power iteration over one pane (library/pagerank.py's
    kernel on the plus-times semiring).  There is no frontier — every
    iteration spreads all mass — so direction is a whole-run choice:
    push scatter-adds in arrival order (the bit-exact historical path,
    and the auto default: both lowerings measure within noise here),
    pull segment-sums the dst-STABLE-sorted copy — the same per-
    destination addend order, hence bit-identical (pinned by
    tests/test_spmv.py).  ``use_pull`` is traced: flipping it reuses the
    executable."""
    capacity, e_pad = op.capacity, op.e_pad

    def build():
        def kernel(src, dst, mask, d_src, d_dst, d_msk,
                   use_pull, damping, tol, max_iters):
            zeros = jnp.zeros((capacity,), jnp.float32)
            ones = jnp.ones_like(zeros)
            m = mask.astype(jnp.float32)
            in_window = zeros.at[src].max(m).at[dst].max(m) > 0
            out_deg = zeros.at[src].add(m)
            n = jnp.maximum(jnp.sum(in_window.astype(jnp.float32)), 1.0)
            dangling = in_window & (out_deg == 0)
            base = jnp.where(in_window, (1.0 - damping) / n, 0.0)
            safe_deg = jnp.maximum(out_deg, 1.0)
            seg = jnp.where(d_msk, d_dst, capacity)

            def spread_push(r):
                contrib = jnp.where(mask, r[src] / safe_deg[src], 0.0)
                return PLUS_TIMES.scatter(zeros, dst, contrib)

            def spread_pull(r):
                cand = jnp.where(d_msk, r[d_src] / safe_deg[d_src], 0.0)
                return PLUS_TIMES.segment(cand, seg, capacity + 1)[:capacity]

            def body(state):
                r, _, it = state
                spread = jax.lax.cond(use_pull, spread_pull, spread_push, r)
                dangling_mass = jnp.sum(jnp.where(dangling, r, 0.0)) / n
                r_new = base + damping * (
                    spread + jnp.where(in_window, dangling_mass, 0.0)
                )
                delta = jnp.sum(jnp.abs(r_new - r))
                return r_new, delta, it + 1

            def cond(state):
                _, delta, it = state
                return (delta > tol) & (it < max_iters)

            r0 = jnp.where(in_window, ones / n, 0.0)
            r, _, iters = jax.lax.while_loop(cond, body, (r0, jnp.inf, 0))
            return r, in_window, iters

        return kernel

    fn = compile_cache.cached_jit(
        ("spmv_pagerank", capacity, e_pad), build, label="spmv"
    )
    r, in_w, iters = fn(
        op.src, op.dst, op.msk, op.d_src, op.d_dst, op.d_msk,
        jnp.bool_(use_pull), jnp.float32(damping), jnp.float32(tol),
        jnp.int32(max_iters),
    )
    metrics.spmv_add("spmv_fixpoints", 1)
    metrics.spmv_add(
        "spmv_pull_iters" if use_pull else "spmv_push_iters", int(iters)
    )
    return r, in_w, iters


def _build_cc():
    def kernel(parent, seen, src, dst, mask):
        src_ = jnp.where(mask, src, 0)
        dst_ = jnp.where(mask, dst, 0)

        def cond(p):
            return jnp.any(p[src_] != p[dst_])

        def body(p):
            rs = p[src_]
            rd = p[dst_]
            lo = jnp.minimum(rs, rd)
            hi = jnp.maximum(rs, rd)
            return uf.compress(MIN_MIN.scatter(p, hi, lo))

        parent = jax.lax.while_loop(cond, body, uf.compress(parent))
        seen = seen.at[src_].max(mask).at[dst_].max(mask)
        return parent, seen

    return kernel


def cc_fixpoint(parent, seen, src, dst, mask):
    """Connected-components hooking on the min-min semiring: each round
    scatter-mins the lower endpoint label onto the higher (the kernel
    core's scatter primitive — candidates ARE labels), then pointer-
    doubles (ops/unionfind.compress) until every edge's endpoints agree.
    The identical array fixed point to unionfind.union_edges_with_seen —
    parent[v] = min vertex id of v's component, fully compressed — via
    one shared process-global executable."""
    fn = compile_cache.cached_jit(("spmv_cc_fixpoint",), _build_cc, label="spmv")
    return fn(parent, seen, src, dst, mask)


# ---------------------------------------------------------------------------
# config/env resolution (the shared tri-state contract, utils/envswitch.py)


def resolve_direction(cfg) -> str:
    """cfg.spmv_direction ("" defers) > GELLY_SPMV_DIRECTION > auto;
    unrecognized spellings refuse loudly."""
    return resolve_choice(
        cfg.spmv_direction, "GELLY_SPMV_DIRECTION", DIRECTIONS, "auto"
    )


def resolve_threshold(cfg) -> float:
    """cfg.direction_threshold (-1 defers) > GELLY_DIRECTION_THRESHOLD >
    :data:`DEFAULT_DIRECTION_THRESHOLD`; non-density env values refuse
    loudly."""
    if cfg.direction_threshold != -1.0:
        return float(cfg.direction_threshold)
    env = os.environ.get("GELLY_DIRECTION_THRESHOLD")
    if env is None:
        return DEFAULT_DIRECTION_THRESHOLD
    try:
        val = float(env.strip())
    except ValueError:
        raise ValueError(
            f"GELLY_DIRECTION_THRESHOLD={env!r} is not a float density"
        ) from None
    if not 0.0 <= val <= 1.0:
        raise ValueError(
            f"GELLY_DIRECTION_THRESHOLD={env!r} must be in [0, 1]"
        )
    return val

"""Batched key-grouping primitives: the TPU-native replacement for ``keyBy``.

Everywhere the reference routes records through Flink's hash shuffle and mutates
per-key operator state (e.g. SimpleEdgeStream.java:119,303,492;
SummaryBulkAggregation.java:78), this framework instead sorts/ranks keys inside a
padded micro-batch and applies vectorized segment reductions and scatters.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def _grouping_key(keys: jax.Array, mask: Optional[jax.Array]) -> jax.Array:
    """int32-safe composite key where padding rows never join a valid group.

    Valid keys map to even space (k*2), padding rows to odd space (k*2+1), so a
    padding row sorts next to — but never inside — a valid group.  Requires
    0 <= key < 2^30 (the framework caps vertex_capacity accordingly).
    """
    k = keys.astype(jnp.int32) * 2
    if mask is None:
        return k
    return k + jnp.where(mask, 0, 1)


def occurrence_rank(keys: jax.Array, mask: Optional[jax.Array] = None) -> jax.Array:
    """rank[i] = number of earlier valid rows j<i with keys[j] == keys[i].

    This is what turns per-key *sequential* state updates (the reference's
    per-record HashMap increments, SimpleEdgeStream.java:461-478) into one
    vectorized pass: the k-th occurrence of a key inside a batch can compute its
    running value as ``base[key] + rank``.
    """
    k = _grouping_key(keys, mask)
    order = jnp.argsort(k, stable=True)
    return _rank_from_grouping(order, segment_boundaries(k[order]))


def first_occurrence_mask(
    keys: jax.Array, mask: Optional[jax.Array] = None
) -> jax.Array:
    """True for the first valid occurrence of each key within the batch."""
    first = occurrence_rank(keys, mask) == 0
    if mask is not None:
        first = first & mask
    return first


def group_counts(
    keys: jax.Array, num_groups: int, mask: Optional[jax.Array] = None
) -> jax.Array:
    """Number of valid rows per key, as a dense [num_groups] array."""
    ones = jnp.ones(keys.shape, jnp.int32)
    if mask is not None:
        ones = jnp.where(mask, ones, 0)
        keys = jnp.where(mask, keys, 0)
        # masked rows contribute 0 to group 0
    return jax.ops.segment_sum(ones, keys, num_segments=num_groups)


def segment_sum(
    values: jax.Array,
    keys: jax.Array,
    num_groups: int,
    mask: Optional[jax.Array] = None,
) -> jax.Array:
    if mask is not None:
        values = jnp.where(mask, values, jnp.zeros_like(values))
        keys = jnp.where(mask, keys, 0)
    return jax.ops.segment_sum(values, keys, num_segments=num_groups)


def _rank_from_grouping(order: jax.Array, boundary: jax.Array) -> jax.Array:
    """Within-group rank (0-based, original order) from a stable grouping
    ``order`` and the group-start ``boundary`` mask over the sorted keys."""
    n = order.shape[0]
    pos = jnp.arange(n, dtype=jnp.int32)
    seg_start = jax.lax.cummax(jnp.where(boundary, pos, 0))
    rank_sorted = pos - seg_start
    return jnp.zeros((n,), jnp.int32).at[order].set(rank_sorted)


def _multi_order(
    src: jax.Array, cols: Tuple[jax.Array, ...], mask: Optional[jax.Array]
) -> Tuple[jax.Array, jax.Array]:
    """Stable order grouping equal (src, *cols) composites; returns
    (order, boundary).

    Uses lexsort on (position, cols reversed, grouping-src) so stability is
    explicit and no int64 composite key is needed; only ``src`` needs the
    padding-safe grouping key (one differing column suffices to split a
    group, and padding rows already split on src).
    """
    n = src.shape[0]
    ks = _grouping_key(src, mask)
    pos = jnp.arange(n, dtype=jnp.int32)
    cols32 = tuple(c.astype(jnp.int32) for c in cols)
    order = jnp.lexsort((pos,) + tuple(reversed(cols32)) + (ks,))
    boundary = segment_boundaries(ks[order])
    for c in cols32:
        boundary = boundary | segment_boundaries(c[order])
    return order, boundary


def _pair_order(
    src: jax.Array, dst: jax.Array, mask: Optional[jax.Array]
) -> Tuple[jax.Array, jax.Array]:
    """Stable order grouping equal (src, dst) pairs; returns (order, boundary)."""
    return _multi_order(src, (dst,), mask)


def occurrence_rank_pairs(
    src: jax.Array, dst: jax.Array, mask: Optional[jax.Array] = None
) -> jax.Array:
    """occurrence_rank over composite (src, dst) keys."""
    order, boundary = _pair_order(src, dst, mask)
    return _rank_from_grouping(order, boundary)


def first_occurrence_mask_pairs(
    src: jax.Array, dst: jax.Array, mask: Optional[jax.Array] = None
) -> jax.Array:
    """True for the first valid occurrence of each (src, dst) pair in the batch."""
    first = occurrence_rank_pairs(src, dst, mask) == 0
    if mask is not None:
        first = first & mask
    return first


def sort_by_key(
    keys: jax.Array, mask: Optional[jax.Array] = None
) -> Tuple[jax.Array, jax.Array]:
    """Stable grouping order; returns (order, sorted_grouping_keys).

    Valid rows are grouped by key with original order preserved within a group;
    padding rows sort adjacent to — but never inside — a valid group.
    """
    k = _grouping_key(keys, mask)
    order = jnp.argsort(k, stable=True)
    return order, k[order]


def segment_boundaries(sorted_keys: jax.Array) -> jax.Array:
    """Boundary mask over sorted grouping keys (True at each new group start)."""
    return jnp.concatenate(
        [jnp.ones((1,), bool), sorted_keys[1:] != sorted_keys[:-1]]
    )


def first_occurrence_mask_triples(
    src: jax.Array,
    dst: jax.Array,
    third: jax.Array,
    mask: Optional[jax.Array] = None,
) -> jax.Array:
    """True for the first valid occurrence of each (src, dst, third) triple.

    The whole-edge analog of ``first_occurrence_mask_pairs`` (reference
    dedup is over the Edge INCLUDING its value,
    SimpleEdgeStream.java:309-323): ``third`` is an arbitrary int32 column
    (e.g. bitcast edge values) lexsorted alongside the endpoints.
    """
    order, boundary = _multi_order(src, (dst, third), mask)
    first = _rank_from_grouping(order, boundary) == 0
    if mask is not None:
        first = first & mask
    return first

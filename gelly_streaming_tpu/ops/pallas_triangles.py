"""Pallas TPU kernel: dense-adjacency triangle counting on the MXU.

The reference's windowed triangle count shuffles O(d^2) candidate wedges per
vertex through the network and joins them against real edges
(example/WindowTriangles.java:82-139).  The TPU-first formulation is algebraic:
for a pane's undirected simple adjacency matrix A (zero diagonal),

    triangles = sum(A * (A @ A)) / 6

since (A @ A)[u, v] counts common neighbors of u and v, and each triangle is
seen once per ordered adjacent pair.  The FLOPs live in A @ A — exactly what
the MXU's systolic array is for — and the elementwise mask-and-reduce fuses on
top.  This kernel tiles the computation so A^2 is never materialized in HBM:
for each (i, j) output tile it accumulates A[i,:] @ A[:,j] in VMEM, masks by
the A[i,j] tile, and adds the tile's (exact, int32) partial count into an SMEM
scalar across the sequential grid.

Inputs are bfloat16 0/1 values: exact in the MXU with float32 accumulation
(products are 0/1, sums < 2^24), so the count is exact.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

TILE = 128  # MXU-native tile edge


_LO_BITS = 15  # running totals are split into low/high halves (see _kernel)


def _kernel(a_row_ref, a_col_ref, a_tile_ref, out_ref):
    i = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when((i == 0) & (j == 0))
    def _():
        out_ref[0, 0] = jnp.int32(0)
        out_ref[0, 1] = jnp.int32(0)

    # Common-neighbor counts for this output tile: [TILE, K] @ [K, TILE].
    acc = jnp.dot(
        a_row_ref[:], a_col_ref[:], preferred_element_type=jnp.float32
    )
    # Mask by adjacency and reduce exactly.  Each float32 entry is an integer
    # < K <= MAX_K, hence exact; the per-tile sum c is < TILE*TILE*K < 2^31,
    # so converting entries to int32 before the reduce keeps c exact too.  A
    # single running int32 total would wrap beyond ~3.6e8 triangles, and
    # per-tile outputs (the obvious fix) stall the Mosaic pipeline ~8x, so the
    # total is accumulated as a low/high pair: lo += c mod 2^15, hi += c >> 15,
    # recombined on the host in int64.  Both stay < 2^31 for K <= MAX_K.
    masked = acc * a_tile_ref[:].astype(jnp.float32)
    c = jnp.sum(masked.astype(jnp.int32))
    out_ref[0, 0] += c & ((1 << _LO_BITS) - 1)
    out_ref[0, 1] += c >> _LO_BITS


# lo <= ntiles * 2^15 and hi <= ntiles * (TILE*TILE*K >> 15) must stay < 2^31;
# K = 2^14 gives ntiles = 2^14, lo <= 2^29, hi <= 2^27 — comfortably exact.
MAX_K = 1 << 14


@functools.partial(jax.jit, static_argnames=("interpret",))  # graft: disable=RAWJIT — module-scope decorator: one process-global jit per import, no per-call closure to key a cache entry on
def _count_halves(adj: jax.Array, *, interpret: bool = False) -> jax.Array:
    k = adj.shape[0]
    a = adj.astype(jnp.bfloat16)
    grid = (k // TILE, k // TILE)
    return pl.pallas_call(
        _kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((TILE, k), lambda i, j: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((k, TILE), lambda i, j: (0, j), memory_space=pltpu.VMEM),
            pl.BlockSpec((TILE, TILE), lambda i, j: (i, j), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (1, 2), lambda i, j: (0, 0), memory_space=pltpu.SMEM
        ),
        out_shape=jax.ShapeDtypeStruct((1, 2), jnp.int32),
        interpret=interpret,
    )(a, a, a)


def _triangles_from_halves(halves) -> int:
    """Recombine the kernel's low/high running totals into the count."""
    halves = np.asarray(halves).astype(np.int64)
    return int((halves[0, 0] + (halves[0, 1] << _LO_BITS)) // 6)


def _check_k(k: int) -> None:
    if k > MAX_K:
        raise ValueError(f"K={k} exceeds the kernel's exactness bound {MAX_K}")


def triangle_count_dense(adj, *, interpret: bool = False) -> int:
    """Exact triangle count of a dense 0/1 adjacency matrix (zero diagonal).

    ``adj`` is [K, K] with K a multiple of TILE (pad with zeros — isolated
    padding vertices contribute nothing) and K <= MAX_K.
    """
    k = adj.shape[0]
    if adj.shape != (k, k) or k % TILE != 0:
        raise ValueError(f"adjacency must be square with K % {TILE} == 0, got {adj.shape}")
    _check_k(k)
    return _triangles_from_halves(_count_halves(adj, interpret=interpret))


def _use_interpret() -> bool:
    """Compiled Mosaic kernels need a real TPU; elsewhere run interpreted."""
    return jax.default_backend() != "tpu"


def _adjacency_count(u, v, ok, k: int, interpret: bool):
    """Scatter a (possibly duplicated, uncanonical) edge list into a dense
    [k, k] adjacency and run the MXU kernel; the scatter dedups for free."""
    uu = jnp.where(ok, u, 0)
    vv = jnp.where(ok, v, 0)
    adj = jnp.zeros((k, k), jnp.bool_)
    adj = adj.at[uu, vv].max(ok)
    adj = adj.at[vv, uu].max(ok)
    return _count_halves(adj, interpret=interpret)


_ID_BITS = 14  # MAX_K = 2^14, so a (u, v) pair packs into 28 bits of a uint32


@functools.partial(jax.jit, static_argnames=("k", "interpret"))  # graft: disable=RAWJIT — module-scope decorator: one process-global jit per import, no per-call closure to key a cache entry on
def _count_from_packed(w, n, k: int, interpret: bool):
    """Device-side pane count from the 4 B/edge packed pane wire format.

    ``w``: uint32[cap] edge words (u | v << _ID_BITS), ``n``: traced edge
    count (entries past n are padding — masked on device, so varying pane
    sizes share one compiled kernel per pow2 capacity).  Halving the pane's
    wire bytes matters because the transfer rides the same tunnel budget as
    the ingest plane (BASELINE.md round-3 environment model).
    """
    u = (w & ((1 << _ID_BITS) - 1)).astype(jnp.int32)
    v = (w >> _ID_BITS).astype(jnp.int32)
    ok = (jnp.arange(w.shape[0], dtype=jnp.int32) < n) & (u != v)
    return _adjacency_count(u, v, ok, k, interpret)


def pack_pane(u: np.ndarray, v: np.ndarray, mask=None):
    """Host-side pane pack: (u, v) -> (uint32[cap] edge words, n) at
    4 B/edge, capacity padded to the next power of two so varying pane sizes
    reuse a bounded set of compiled kernels.  Masked-out edges are dropped
    here (the wire ships only live edges)."""
    if mask is not None:
        u, v = np.asarray(u)[mask], np.asarray(v)[mask]
    n = len(u)
    if n:
        u = np.asarray(u)
        v = np.asarray(v)
        # u packs into the low _ID_BITS; a larger id would silently bleed
        # into v's bits (corrupted edges, no error) — current callers bound
        # ids by the dense-pane cap, but guard future callers loudly
        if int(min(u.min(), v.min())) < 0 or int(max(u.max(), v.max())) >= (
            1 << _ID_BITS
        ):
            raise ValueError(
                f"pack_pane ids must be in [0, 2^{_ID_BITS}); got "
                f"[{int(min(u.min(), v.min()))}, "
                f"{int(max(u.max(), v.max()))}]"
            )
    n_cap = max(1, 1 << (n - 1).bit_length()) if n else 1
    w = np.zeros((n_cap,), np.uint32)
    w[:n] = u.astype(np.uint32) | (v.astype(np.uint32) << _ID_BITS)
    return w, np.int32(n)


def pane_triangles_submit_packed(w, n, num_vertices: int):
    """Dispatch a packed pane (from ``pack_pane``; host OR device-resident
    arrays) without waiting.  Device-resident inputs let a prefetching
    caller overlap the pane upload with the previous pane's compute."""
    k = max(TILE, ((num_vertices + TILE - 1) // TILE) * TILE)
    _check_k(k)
    halves = _count_from_packed(w, n, k, _use_interpret())
    try:
        halves.copy_to_host_async()  # start the readback behind the compute
    except AttributeError:
        pass
    return halves


def pane_triangles_submit(u: np.ndarray, v: np.ndarray, num_vertices: int, mask=None):
    """Upload + dispatch the dense pane count WITHOUT waiting for the result.

    Returns the kernel's device-resident running-total halves (or None for an
    empty pane); recombine with ``triangles_from_halves`` when the value is
    needed.  Splitting submit from fetch lets a pipelined caller overlap the
    next pane's transfer/compute with this pane's readback RTT — on a
    tunneled device the readback latency otherwise lands on every window.

    ``u``/``v`` may contain duplicates and both orientations (the device
    scatter canonicalizes); self-loops are dropped.  ``num_vertices`` bounds
    the ids.  The pane ships in the packed 4 B/edge wire form (pack_pane).
    """
    if len(u) == 0:
        return None
    w, n = pack_pane(u, v, mask)
    return pane_triangles_submit_packed(w, n, num_vertices)


def triangles_from_halves(halves) -> int:
    """Blocking fetch: device halves (from pane_triangles_submit) -> count."""
    return 0 if halves is None else _triangles_from_halves(halves)


def pane_triangles_dense(
    u: np.ndarray, v: np.ndarray, num_vertices: int, mask=None
) -> int:
    """Synchronous pane count (submit + fetch in one call)."""
    return triangles_from_halves(pane_triangles_submit(u, v, num_vertices, mask))

"""Device-side, degree-bucketed neighborhood grouping for window panes.

Replaces the host numpy sort in the snapshot path (reference: the per-window
keyed grouping Flink performs inside ``WindowedStream.apply``,
SnapshotStream.java:129-181).  Two properties matter:

* **On device.**  The pane ships as its edge list (8 B/edge up) and the
  grouping — sort by key, dense key ids, within-key ranks, scatters — runs as
  one jitted program.  The host build it replaces uploaded the padded
  [K, D_max] tensors instead, which under skew is far larger than E.

* **Degree-bucketed.**  One hub vertex used to inflate the whole pane tensor
  to [K, max_degree] (SURVEY.md §7, ``applyOnNeighbors`` padding).  Here keys
  land in buckets by degree class: bucket b holds keys with degree in
  (2^(b-1), 2^b], padded to [K_b, 2^b] with K_b = min(E, 2E/2^b) — at most
  E/2^(b-1) keys can have degree > 2^(b-1), so the shapes are static in E and
  total padded area is O(E log E) instead of O(K * max_degree).

All shapes derive from the pow2-padded edge count, so successive panes of
similar size reuse compiled kernels.
"""

from __future__ import annotations

from typing import List, NamedTuple, Optional

import jax
import jax.numpy as jnp

from gelly_streaming_tpu.ops import segments


class NeighborhoodBucket(NamedTuple):
    """One degree class of a pane: padded [K_b, D_b] tensors (device)."""

    keys: jax.Array  # int32[K_b]
    nbrs: jax.Array  # int32[K_b, D_b]
    vals: Optional[object]  # pytree of [K_b, D_b] or None
    valid: jax.Array  # bool[K_b, D_b]
    num_keys: jax.Array  # int32[] — real keys in this bucket


def bucket_shapes(e_pad: int) -> List[tuple]:
    """Static (K_b, D_b) per degree bucket for a pow2 edge capacity."""
    shapes = []
    b = 0
    while (1 << b) <= e_pad:
        d = 1 << b
        k = max(1, min(e_pad, (2 * e_pad) // d))
        shapes.append((k, d))
        b += 1
    return shapes


def build_buckets(src, dst, val, mask) -> List[NeighborhoodBucket]:
    """Group a padded edge list by source key into degree buckets (traceable).

    ``src``/``dst``/``mask``: [E] with E a power of two; ``val``: optional
    pytree of [E] edge values.  Returns one NeighborhoodBucket per degree
    class (possibly with num_keys == 0); neighbor columns within a key are in
    arrival order (stable sort), matching the reference's per-window neighbor
    iteration order.
    """
    e = src.shape[0]
    order, sorted_gk = segments.sort_by_key(src, mask)
    ks = src[order]
    kd = dst[order]
    kmask = mask[order]
    kval = None if val is None else jax.tree.map(lambda a: a[order], val)
    boundary = segments.segment_boundaries(sorted_gk)
    key_id = jnp.cumsum(boundary.astype(jnp.int32)) - 1  # dense key rank [E]
    pos = jnp.arange(e, dtype=jnp.int32)
    seg_start = jax.lax.cummax(jnp.where(boundary, pos, 0))
    col = pos - seg_start  # within-key arrival rank

    # per-key tables over [E] slots (at most E distinct keys)
    deg = jnp.zeros((e,), jnp.int32).at[key_id].add(kmask.astype(jnp.int32))
    key_of = jnp.zeros((e,), jnp.int32).at[jnp.where(kmask, key_id, 0)].max(
        jnp.where(kmask, ks, 0)
    )
    key_valid = deg > 0
    # degree class: deg in (2^(b-1), 2^b] -> bucket b  (ceil log2).  Integer
    # clz, not float log2: float32 log2(2^k + 1) rounds to exactly k for
    # k >~ 22, which would mis-bucket huge-degree keys into a class with
    # D_b < degree and silently overwrite the last neighbor slot.
    ceil_log2 = jnp.where(
        deg <= 1, 0, 32 - jax.lax.clz(jnp.maximum(deg, 2) - 1)
    ).astype(jnp.int32)
    bucket_of = jnp.where(key_valid, ceil_log2, -1)

    out: List[NeighborhoodBucket] = []
    for b, (k_b, d_b) in enumerate(bucket_shapes(e)):
        in_b = key_valid & (bucket_of == b)  # per key slot [E]
        row_of = jnp.cumsum(in_b.astype(jnp.int32)) - 1  # dense row in bucket
        keys_b = (
            jnp.zeros((k_b,), jnp.int32)
            .at[jnp.where(in_b, jnp.minimum(row_of, k_b - 1), k_b)]
            .max(key_of, mode="drop")
        )
        # per edge: does my key live in this bucket?
        esel = kmask & in_b[key_id]
        erow = jnp.where(esel, row_of[key_id], k_b)
        ecol = jnp.minimum(col, d_b - 1)  # esel guarantees col < d_b
        nbrs_b = (
            jnp.zeros((k_b, d_b), jnp.int32)
            .at[erow, ecol]
            .set(jnp.where(esel, kd, 0), mode="drop")
        )
        valid_b = (
            jnp.zeros((k_b, d_b), bool).at[erow, ecol].max(esel, mode="drop")
        )
        vals_b = None
        if kval is not None:
            vals_b = jax.tree.map(
                lambda a: jnp.zeros((k_b, d_b) + a.shape[1:], a.dtype)
                .at[erow, ecol]
                .set(
                    jnp.where(
                        esel.reshape((-1,) + (1,) * (a.ndim - 1)), a, 0
                    ),
                    mode="drop",
                ),
                kval,
            )
        out.append(
            NeighborhoodBucket(
                keys_b, nbrs_b, vals_b, valid_b, jnp.sum(in_b.astype(jnp.int32))
            )
        )
    return out


# the shared jitted instance (one compile cache for every caller:
# core/snapshot.py pane builds, library/kcore.py, ...), routed through the
# process-global executable cache so its compiles are metered by the
# retrace guard
from gelly_streaming_tpu.core import compile_cache

build_buckets_jit = compile_cache.cached_jit(
    ("nbr_build_buckets",), lambda: build_buckets
)

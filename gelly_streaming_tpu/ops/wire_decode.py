"""Vectorized device-side decode of the BDV compressed wire format.

BDV (binned delta/group-varint, io/wire.py) ships a dst-sorted edge batch
as one interleaved value stream — per edge an unsigned dst delta, then a
zigzag GLOBAL src delta (src[-1] = 0), then for valued batches a zigzag
value.  The stream is GROUP varint: a control block of 2-bit byte lengths
(four values per control byte) at the buffer head, then the little-endian
value bytes.  Buffers bucket-pad with 0x00 for shape-stable transfers.

The decode is deliberately gather/scan-only — XLA's CPU backend lowers
scatters to a serial per-element loop that would eat the transfer saving,
and gathers/cumsums vectorize on every backend — and it fuses into the
consumer's fold kernel (dispatched through the process-global compile
cache), so decompression costs no extra HBM round trip and no dispatch:

  1. **Lengths** — value k's byte length is 2 bits of control byte k>>2:
     one gather over the (static-size) control block.
  2. **Offsets** — value starts are the control size plus an exclusive
     cumsum of the lengths.
  3. **Assembly** — four clipped gathers of ``data[start + j]``, masked by
     ``j < len`` and shifted ``8j``.
  4. **Stream reconstruction** — dst is a cumsum of the unsigned deltas;
     src a cumsum of the zigzag-decoded global deltas (the chain
     telescopes, so partial sums never leave the id range).

Ids are bounded at 2^28 (``BDV_MAX_ID_BITS``, enforced at pack time in
io/wire.py) so zigzag deltas fit the 4-byte group-varint ceiling.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# ids (and zigzag-encoded deltas) must fit this many bits so every encoded
# value fits the 4-byte group-varint ceiling (2^28 ids -> 2^29 zigzag); the
# single definition lives with the encoder so the two sides cannot drift
from gelly_streaming_tpu.io.wire import BDV_MAX_ID_BITS  # noqa: F401


def decode_varints(buf, count: int):
    """uint8[cap] group-varint stream -> uint32[count] values (count static).

    Bytes past the encoded payload (the bucket padding) are never asked
    for; an all-zero buffer decodes to zeros.
    """
    b = buf.astype(jnp.uint32)
    ctrl = (count + 3) // 4
    k = jnp.arange(count, dtype=jnp.int32)
    lens = ((b[k >> 2] >> (2 * (k & 3)).astype(jnp.uint32)) & 3) + 1
    starts = ctrl + jnp.cumsum(lens) - lens
    nb = b.shape[0]
    val = jnp.zeros((count,), jnp.uint32)
    for j in range(4):
        byte = b[jnp.minimum(starts + j, nb - 1)]
        val = val | jnp.where(lens > j, byte << jnp.uint32(8 * j), 0)
    return val


def _unzigzag(z):
    """uint32 zigzag -> signed int32."""
    return (z >> 1).astype(jnp.int32) ^ -(z & 1).astype(jnp.int32)


def decode_bdv(buf, n: int, valued: bool = False):
    """BDV wire buffer -> (src, dst[, val]) int32[n] in dst-sorted order.

    ``n`` is the static batch size; ``valued`` selects the 3-stream layout
    (dst delta, zigzag src delta, zigzag value per edge).  Both id columns
    are cumsums of their delta streams — src deltas are GLOBAL (signed,
    telescoping), so no segmented scan is needed.  Pure traced code —
    dispatch through the caller's cached executable
    (core/compile_cache.py) so the decode fuses into the downstream fold.
    """
    per = 3 if valued else 2
    vals = decode_varints(buf, per * n)
    d_delta = vals[0::per]
    s_delta = _unzigzag(vals[1::per])
    dst = jnp.cumsum(d_delta.astype(jnp.int32))
    src = jnp.cumsum(s_delta)
    if not valued:
        return src, dst
    return src, dst, _unzigzag(vals[2::per])

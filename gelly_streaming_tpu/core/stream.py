"""EdgeStream: the graph-stream API (reference: GraphStream.java + SimpleEdgeStream.java).

The reference models a graph as a Flink ``DataStream<Edge>`` with lazy
transformations and per-key stateful operators.  Here an ``EdgeStream`` is a
lazy pipeline of *stages* over padded COO micro-batches: each stage is a pure
``(state, batch) -> (state, batch)`` function; the whole pipeline is composed
and jitted once, and state (dense per-vertex arrays) threads functionally
through the run — the SPMD replacement for Flink's keyed operator state.

API parity map (reference file:line):
  map_edges            SimpleEdgeStream.java:217   (value transform per edge)
  filter_edges         SimpleEdgeStream.java:290
  filter_vertices      SimpleEdgeStream.java:257-281 (predicate on both endpoints)
  distinct             SimpleEdgeStream.java:301-323 (stateful seen-table)
  reverse              SimpleEdgeStream.java:328
  undirected           SimpleEdgeStream.java:350-361 (emit edge + reverse)
  union                SimpleEdgeStream.java:343
  get_vertices         SimpleEdgeStream.java:116-129 (first-occurrence emission)
  get_degrees/in/out   SimpleEdgeStream.java:413-478 (running degree trace)
  number_of_vertices   SimpleEdgeStream.java:366-383 (running distinct count)
  number_of_edges      SimpleEdgeStream.java:388-404 (running edge count)
  slice                SimpleEdgeStream.java:135-167 -> core/snapshot.py
  aggregate            SimpleEdgeStream.java:100-102 -> core/aggregation.py
"""

from __future__ import annotations

from typing import Callable, Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from gelly_streaming_tpu.core import compile_cache
from gelly_streaming_tpu.core.config import StreamConfig
from gelly_streaming_tpu.core.output import NULL, OutputStream, RecordBlock
from gelly_streaming_tpu.core.types import EdgeBatch, EdgeDirection
from gelly_streaming_tpu.ops import neighbors, segments


# ---------------------------------------------------------------------------
# Pipeline stages
# ---------------------------------------------------------------------------


class Stage:
    """A pure pipeline stage.  ``init`` builds the state pytree; ``apply`` is
    jit-traced as part of the composed pipeline step."""

    def init(self, cfg: StreamConfig):
        return ()

    def apply(self, state, batch: EdgeBatch):
        raise NotImplementedError


class _Stateless(Stage):
    def __init__(self, fn: Callable[[EdgeBatch], EdgeBatch]):
        self.fn = fn

    def apply(self, state, batch):
        return state, self.fn(batch)


def _value_bits(val) -> jax.Array:
    """Lossless int32 view of a per-edge scalar value for whole-edge dedup.

    Exact bit equality (the dense analog of the reference HashSet's
    value-based equals): <=32-bit leaves bitcast/cast without collision.
    Multi-leaf or >32-bit values have no sound dense form (hashing could
    collide and silently drop genuinely distinct edges) — refuse loudly.
    """
    leaves = jax.tree.leaves(val)
    if len(leaves) != 1 or leaves[0].ndim != 1:
        raise ValueError(
            "whole-edge distinct needs a single scalar value per edge; "
            "use distinct(by='endpoints') or map the values into one "
            "<=32-bit scalar first (map_edges)"
        )
    leaf = leaves[0]
    dt = jnp.dtype(leaf.dtype)
    if dt.itemsize > 4:
        raise ValueError(
            f"whole-edge distinct supports values of <= 32 bits (got {dt}); "
            "use distinct(by='endpoints') or narrow the values (map_edges)"
        )
    # issubdtype (not dtype.kind) so bfloat16/float8 — numpy kind 'V' — hit
    # the bitcast branch: astype would TRUNCATE them (1.5 and 1.0 both -> 1)
    # and silently merge genuinely distinct edges
    if jnp.issubdtype(dt, jnp.floating):
        width_int = {1: jnp.int8, 2: jnp.int16, 4: jnp.int32}[dt.itemsize]
        return jax.lax.bitcast_convert_type(leaf, width_int).astype(jnp.int32)
    if jnp.issubdtype(dt, jnp.integer) or dt.kind == "b":
        return leaf.astype(jnp.int32)
    raise ValueError(
        f"whole-edge distinct cannot form exact bits for dtype {dt}; "
        "use distinct(by='endpoints') or map the values (map_edges)"
    )


class _DistinctStage(Stage):
    """Stateful distinct mirroring DistinctEdgeMapper's per-key HashSet
    (SimpleEdgeStream.java:309-323) with device neighbor tables.

    The reference's set is over the whole Edge INCLUDING its value, so the
    default (``edge`` mode) dedupes (src, dst, value) triples via two
    slot-aligned tables (ops/neighbors.insert_unique_valued_batch) —
    value-less batches behave exactly like endpoint dedup there (their
    value bits are the constant 0).  Streams the source KNOWS are
    value-less resolve ``auto`` to the single-table ``endpoints`` mode
    instead (same semantics, half the state); callers can force
    ``endpoints`` on valued streams for first-value-wins endpoint-pair
    semantics.  Batches must be value-structure-homogeneous within one
    stream — a stream mixing value-less and valued batches is ill-typed
    (as in the reference: Edge<K, NullValue> and Edge<K, Double> streams
    cannot union), and in such a stream a value-less edge would collide
    with a 0-valued one.
    """

    def __init__(self, mode: str):
        assert mode in ("edge", "endpoints"), mode
        self.mode = mode

    def init(self, cfg):
        table = neighbors.init_table(cfg.vertex_capacity, cfg.max_degree)
        if self.mode == "endpoints":
            return table
        return (table, neighbors.init_table(cfg.vertex_capacity, cfg.max_degree))

    def apply(self, state, batch):
        if self.mode == "endpoints":
            table, is_new = neighbors.insert_unique_batch(
                state, batch.src, batch.dst, batch.mask
            )
            return table, batch.replace(mask=is_new)
        table, vtable = state
        bits = (
            jnp.zeros(batch.src.shape, jnp.int32)
            if batch.val is None
            else _value_bits(batch.val)
        )
        table, vtable, is_new = neighbors.insert_unique_valued_batch(
            table, vtable, batch.src, batch.dst, bits, batch.mask
        )
        return (table, vtable), batch.replace(mask=is_new)


class _FanoutLateHolder:
    """Late-sink holder for ``union()``: one logical sink spanning the
    unioned chain AND both input chains.

    ``on_late``'s contract is "one sink per transform chain"; a union joins
    two chains, so a sink attached anywhere — either input (before or after
    the union) or the unioned stream itself — must be seen by every pane
    assignment over any of the three chains.  Reads fall through to the
    parents; writes fan out to them (the unioned stream's consumers read
    through this holder, the inputs' consumers read their own holders).
    """

    def __init__(self, *parents):
        self._parents = parents
        self._own = {"sink": None}

    def __getitem__(self, key):
        if self._own[key] is not None:
            return self._own[key]
        for parent in self._parents:
            value = parent[key]
            if value is not None:
                return value
        return None

    def __setitem__(self, key, value):
        self._own[key] = value
        for parent in self._parents:
            parent[key] = value


def plan_superbatch_groups(n: int, k: int, boundaries=()) -> List[int]:
    """Split ``n`` sequential unit batches into superbatch dispatch groups.

    Group sizes are powers of two <= ``k`` — a small bucketed set of
    compiled shapes (at most log2(k)+1 distinct scan lengths) — and no
    group crosses a boundary: each entry of ``boundaries`` is a
    ``(modulus, offset)`` pair marking batch indices ``i`` where
    ``(i + offset) % modulus == 0`` must START a fresh group (emission and
    snapshot points, so coalescing never changes what a consumer observes).
    Returns group sizes summing to ``n``; ``k <= 1`` degenerates to
    per-batch dispatch.
    """
    if k <= 1 or n <= 0:
        return [1] * max(n, 0)
    groups: List[int] = []
    i = 0
    while i < n:
        limit = min(n - i, k)
        for mod, off in boundaries:
            if mod:
                limit = min(limit, mod - ((i + off) % mod))
        g = 1 << (max(limit, 1).bit_length() - 1)  # largest pow2 <= limit
        groups.append(g)
        i += g
    return groups


# ---------------------------------------------------------------------------
# wire-buffer validation (the from_wire guards, shared with the network
# ingest plane)
# ---------------------------------------------------------------------------


def validate_wire_width(width, capacity: int) -> None:
    """The ``from_wire`` width guards as a reusable check: the encoding must
    be a supported one, and a tuple width's claimed capacity must not exceed
    the stream's (decoded ids could reach or pass it and silently corrupt
    device state)."""
    from ..io import wire as _wire

    if width not in (2, 3, 4, _wire.PAIR40) and not (
        isinstance(width, tuple)
        and len(width) == 2
        and width[0] in (_wire.EF40, _wire.BDV)
    ):
        raise ValueError(f"unsupported wire width {width}")
    if isinstance(width, tuple) and width[1] > capacity:
        raise ValueError(
            f"{width[0].upper()} width capacity {width[1]} exceeds "
            f"cfg.vertex_capacity {capacity}: decoded ids could reach or "
            "pass it and silently corrupt device state; "
            "intern ids first (io.interning.VertexInterner)"
        )


def validate_wire_buffer(
    buf,
    batch_size: int,
    width,
    capacity: int,
    index: int = 0,
    decode_ids: bool = False,
):
    """One buffer's worth of the ``from_wire`` guards: dtype, size bounds
    (exact for fixed widths, [floor, worst-case] for the data-dependent BDV
    sizes), and — with ``decode_ids`` — a host decode with both ends of the
    id range checked (BDV's signed zigzag deltas can express NEGATIVE ids,
    whose scatters silently wrap to the summary tail).

    ``from_wire`` applies the decode check to buffer 0 only (replay
    producers are trusted — see its docstring); the network ingest plane
    (io/sources.NetworkEdgeSource) applies it to EVERY pushed buffer, since
    the socket is the trust boundary.  Returns the decoded ``(src, dst)``
    arrays when ``decode_ids`` (the caller was going to decode anyway),
    else None.
    """
    from ..io import wire as _wire

    b = np.asarray(buf)
    if b.dtype != np.uint8:
        # a same-nbytes buffer of another dtype would sign-extend /
        # mis-slice in the device decode — wire bytes are uint8
        raise ValueError(f"wire buffer {index} has dtype {b.dtype}, not uint8")
    expect = _wire.wire_nbytes(batch_size, width)
    is_bdv = isinstance(width, tuple) and width[0] == _wire.BDV
    if is_bdv:
        # BDV buffers are data-dependent sizes under the worst-case bound
        # (delta/varint payload + bucket padding); the floor is the control
        # block + one byte per varint — shorter buffers cannot hold
        # batch_size edges, and the device decoder's clipped gathers would
        # silently read garbage instead of raising (devices cannot)
        bdv_min = (2 * batch_size + 3) // 4 + 2 * batch_size
        if b.nbytes > expect:
            raise ValueError(
                f"BDV wire buffer {index} holds {b.nbytes} bytes; "
                f"batch_size={batch_size} caps at {expect}"
            )
        if b.nbytes < bdv_min:
            raise ValueError(
                f"BDV wire buffer {index} holds {b.nbytes} bytes, "
                f"truncated below the {bdv_min}-byte minimum for "
                f"batch_size={batch_size}"
            )
    elif b.nbytes != expect:
        raise ValueError(
            f"wire buffer {index} holds {b.nbytes} bytes; "
            f"batch_size={batch_size} at width {width} needs {expect}"
        )
    if not decode_ids:
        return None
    from ..io.wire import unpack_edges_host as _unpack

    s, d = _unpack(b, batch_size, width)
    if len(s) and (
        int(min(s.min(), d.min())) < 0
        or int(max(s.max(), d.max())) >= capacity
    ):
        raise ValueError(
            f"wire buffer {index} decodes vertex ids outside "
            f"[0, vertex_capacity {capacity}); intern ids first "
            "(io.interning.VertexInterner)"
        )
    return s, d


# ---------------------------------------------------------------------------
# EdgeStream
# ---------------------------------------------------------------------------


class EdgeStream:
    """A (possibly infinite) stream of graph edges over a dense vertex space.

    Construction:
      EdgeStream.from_collection(edges, cfg)      finite host collection
      EdgeStream.from_batches(factory, cfg)       any re-runnable batch source
    """

    def __init__(
        self,
        source_factory: Callable[[], Iterator[EdgeBatch]],
        cfg: StreamConfig,
        stages: Tuple[Stage, ...] = (),
        wire_arrays: Optional[Tuple[np.ndarray, np.ndarray, int]] = None,
        wire_packed: Optional[tuple] = None,
        valued: Optional[bool] = None,
    ):
        self._source_factory = source_factory
        self.cfg = cfg
        self._stages = stages
        # Does this stream carry edge values?  True / False when the source
        # knows (collections, arrays, files), None for opaque batch sources.
        # Consumers that must pick a state layout BEFORE seeing a batch
        # (distinct's whole-edge mode) read this; None means "assume it
        # might" (safe, costs an extra value table).
        self._valued = valued
        # (src, dst, batch_size) host arrays backing the packed-wire fast path
        # (core/aggregation.py): present only for value-less, untimed sources,
        # and preserved through stage-adding transforms (stages run in-jit
        # after the device-side unpack, so packing commutes with them).
        self._wire_arrays = wire_arrays
        # (bufs, batch_size, width, tail) for a replay source whose records
        # are ALREADY in wire format (from_wire): the fast path skips host
        # packing entirely and the timed cost is transfer + on-device unpack.
        self._wire_packed = wire_packed
        # shared holder for the late-record sink: derived streams (_with)
        # alias the SAME holder, so on_late() attached to any stream in a
        # transform chain is seen by every stream derived from it — before
        # or after the derivation
        self._late_holder = {"sink": None}

    @property
    def late_sink(self):
        """callable(src, dst, val, time) for later-than-bound records
        (None = drop); shared across a transform chain."""
        return self._late_holder["sink"]

    def on_late(self, sink) -> "EdgeStream":
        """Route later-than-bound event-time records to ``sink(src, dst,
        val, time)`` instead of dropping them (Flink's side-output-for-late
        analog; used with ``cfg.out_of_orderness_ms`` > 0)."""
        self._late_holder["sink"] = sink
        return self

    def num_edges_hint(self) -> Optional[int]:
        """Total edge count when the SOURCE knows it (array/wire-backed
        streams), else None.

        Used by the job runtime (``JobManager.submit_aggregation`` stores
        it on the job; ``status()`` reports it as ``edges_hint`` next to
        the measured ``job_edges``) — a hint only: stages that drop edges
        (filters, distinct) make the true consumed count smaller, and
        opaque batch sources simply report None.
        """
        if self._wire_arrays is not None:
            return len(self._wire_arrays[0])
        if self._wire_packed is not None:
            bufs, batch_size, _width, tail = self._wire_packed
            return len(bufs) * batch_size + (len(tail[0]) if tail else 0)
        return None

    # ---- construction -------------------------------------------------------

    @staticmethod
    def from_collection(
        edges: Sequence[tuple],
        cfg: StreamConfig = StreamConfig(),
        batch_size: Optional[int] = None,
        with_time: bool = False,
    ) -> "EdgeStream":
        """Finite in-memory stream (the tests' analog of env.fromCollection).

        ``with_time`` reads a 4th tuple element as the event timestamp,
        mirroring the event-time SimpleEdgeStream ctor
        (SimpleEdgeStream.java:86-90); otherwise arrival order is time
        (ingestion-time ctor, SimpleEdgeStream.java:69-73).
        """
        edges = list(edges)
        bs = batch_size or (len(edges) if edges else 1)
        has_val = bool(edges) and len(edges[0]) >= 3

        def factory():
            for i in range(0, max(len(edges), 1), bs):
                chunk = edges[i : i + bs]
                if not chunk:
                    return
                yield EdgeBatch.from_edges(chunk, pad_to=bs, with_time=with_time)

        return EdgeStream(factory, cfg, valued=has_val)

    @staticmethod
    def from_batches(
        factory: Callable[[], Iterator[EdgeBatch]], cfg: StreamConfig = StreamConfig()
    ) -> "EdgeStream":
        return EdgeStream(factory, cfg)

    @staticmethod
    def from_arrays(
        src: np.ndarray,
        dst: np.ndarray,
        cfg: StreamConfig = StreamConfig(),
        batch_size: Optional[int] = None,
    ) -> "EdgeStream":
        """Value-less, untimed stream over host id arrays.

        This is the framework's fast ingest source: the arrays double as the
        backing store for the packed-wire transfer path (io/wire.py), which
        ``aggregate()`` rides when no checkpointing or sharding is requested —
        the product-API equivalent of the reference's runtime-internal network
        ingest (SummaryBulkAggregation.java:76-83 runs *inside* Flink's stack).
        """
        src = np.asarray(src)
        dst = np.asarray(dst)
        if src.shape != dst.shape:
            raise ValueError("src/dst length mismatch")
        if len(src) and (
            min(src.min(), dst.min()) < 0
            or max(src.max(), dst.max()) >= cfg.vertex_capacity
        ):
            # Out-of-range ids would silently wrap on the packed wire (and
            # clamp in device scatters) — fail loudly BEFORE the int32 cast
            # (a cast-first check would let 64-bit ids wrap into range);
            # intern first (io/interning.py is the framework's bounds guard).
            raise ValueError(
                "vertex ids must be in [0, vertex_capacity); intern ids first "
                "(io.interning.VertexInterner)"
            )
        src = np.ascontiguousarray(src, dtype=np.int32)
        dst = np.ascontiguousarray(dst, dtype=np.int32)
        bs = batch_size or cfg.batch_size

        def factory():
            for i in range(0, max(len(src), 1), bs):
                chunk_s = src[i : i + bs]
                if len(chunk_s) == 0:
                    return
                yield EdgeBatch.from_arrays(chunk_s, dst[i : i + bs], pad_to=bs)

        return EdgeStream(factory, cfg, wire_arrays=(src, dst, bs), valued=False)

    @staticmethod
    def from_wire(
        bufs: Sequence[np.ndarray],
        batch_size: int,
        width,
        cfg: StreamConfig = StreamConfig(),
        tail: Optional[Tuple[np.ndarray, np.ndarray]] = None,
    ) -> "EdgeStream":
        """Replay source: records arrive ALREADY in the framework's wire format.

        This is the ingest contract the reference's hot operator actually
        lives under — Flink's SummaryBulkAggregation consumes tuples the
        upstream network stack serialized (SummaryBulkAggregation.java:76-83
        behind pom.xml:38-63's Netty shuffle); serialization is the
        producer's cost, not the fold's.  The TPU analog: ``bufs`` are
        per-batch uint8 wire buffers (``io.wire.pack_stream`` is the
        producer-side helper), each holding ``batch_size`` edges in
        ``width`` encoding, plus an optional raw ``(src, dst)`` remainder.
        ``aggregate()``'s fast path streams them transfer-only (no host
        pack in the loop); every other consumer sees ordinary EdgeBatches
        via the host decode (``io.wire.unpack_edges_host``).

        EF40 buffers carry a sorted multiset, so non-order-free
        aggregations refuse them (same rule as ``wire_encoding='ef40'``).

        Vertex-id bounds: ids must be interned (< cfg.vertex_capacity) —
        out-of-range ids would silently clamp/drop in device scatters (the
        corruption mode ``from_arrays`` guards with a loud ValueError).  An
        EF40 width whose capacity exceeds cfg.vertex_capacity is refused
        outright (it fully bounds decoded ids); fixed-width buffers whose
        encoding can express ids >= vertex_capacity get the FIRST buffer
        decoded and checked as a smoke guard — full validation of every
        buffer is the producer's contract (decoding the whole stream here
        would defeat the replay fast path).  Tail ids are always checked.
        """
        bufs = list(bufs)
        from ..io import wire as _wire

        validate_wire_width(width, cfg.vertex_capacity)
        cap = cfg.vertex_capacity
        is_bdv = isinstance(width, tuple) and width[0] == _wire.BDV
        for i, b in enumerate(bufs):
            validate_wire_buffer(b, batch_size, width, cap, index=i)
        if is_bdv and bufs:
            # varints can express ids past the claimed capacity (and BDV's
            # signed zigzag src deltas can even express NEGATIVE ids, whose
            # scatters silently wrap to the end of the summary arrays):
            # decode the FIRST buffer as a smoke guard checking both ends
            # (full validation of every buffer is the producer's contract,
            # as for fixed widths; the network ingest plane — where the
            # producer is untrusted — checks every pushed buffer instead)
            validate_wire_buffer(
                bufs[0], batch_size, width, cap, index=0, decode_ids=True
            )
        if not isinstance(width, tuple):
            # fixed-width encodings can express ids beyond vertex_capacity;
            # decode the FIRST buffer as a smoke guard (full validation is
            # the producer's contract — see docstring)
            id_bound = (1 << 20) if width == _wire.PAIR40 else (1 << (8 * width))
            if id_bound > cap and bufs:
                validate_wire_buffer(
                    bufs[0], batch_size, width, cap, index=0, decode_ids=True
                )
        if tail is not None:
            t_src0 = np.asarray(tail[0])
            t_dst0 = np.asarray(tail[1])
            # bounds BEFORE the int32 cast: a cast-first check would let
            # 64-bit ids wrap into range (same rule as from_arrays)
            if len(t_src0) and (
                min(t_src0.min(), t_dst0.min()) < 0
                or max(t_src0.max(), t_dst0.max()) >= cap
            ):
                raise ValueError(
                    f"tail vertex ids must be in [0, vertex_capacity={cap}); "
                    "intern ids first (io.interning.VertexInterner)"
                )
            t_src = np.ascontiguousarray(t_src0, dtype=np.int32)
            t_dst = np.ascontiguousarray(t_dst0, dtype=np.int32)
            if t_src.shape != t_dst.shape or len(t_src) >= batch_size:
                raise ValueError("tail must be a (src, dst) pair shorter than one batch")
            # an empty tail is no tail: the fast path would otherwise compile
            # and run a fully masked-out padded tail step
            tail = (t_src, t_dst) if len(t_src) else None

        def factory():
            for b in bufs:
                s, d = _wire.unpack_edges_host(b, batch_size, width)
                yield EdgeBatch.from_arrays(s, d, pad_to=batch_size)
            if tail is not None and len(tail[0]):
                yield EdgeBatch.from_arrays(tail[0], tail[1], pad_to=batch_size)

        return EdgeStream(
            factory,
            cfg,
            wire_packed=(bufs, batch_size, width, tail),
            valued=False,
        )

    def _with(self, stage: Stage, valued: Optional[bool] = None) -> "EdgeStream":
        out = EdgeStream(
            self._source_factory,
            self.cfg,
            self._stages + (stage,),
            wire_arrays=self._wire_arrays,
            wire_packed=self._wire_packed,
            valued=self._valued if valued is None else valued,
        )
        out._late_holder = self._late_holder  # alias: one sink per chain
        return out

    # ---- transformations (lazy) --------------------------------------------

    def map_edges(self, fn: Callable) -> "EdgeStream":
        """Transform each edge's value: fn(src, dst, val) -> new val (pytree ok).

        Reference: SimpleEdgeStream.java:217 (mapEdges maps the edge value;
        tuple-typed results mirror TestMapEdges' Tuple2 goldens).
        """

        def tx(batch: EdgeBatch) -> EdgeBatch:
            return batch.replace(val=fn(batch.src, batch.dst, batch.val))

        return self._with(_Stateless(tx), valued=True)

    def filter_edges(self, pred: Callable) -> "EdgeStream":
        """Keep edges where pred(src, dst, val) is True (SimpleEdgeStream.java:290)."""

        def tx(batch: EdgeBatch) -> EdgeBatch:
            keep = pred(batch.src, batch.dst, batch.val)
            return batch.replace(mask=batch.mask & keep)

        return self._with(_Stateless(tx))

    def filter_vertices(self, pred: Callable) -> "EdgeStream":
        """Keep edges whose BOTH endpoints satisfy pred(vertex_ids)
        (reference applies the vertex filter to source and target,
        SimpleEdgeStream.java:264-281)."""

        def tx(batch: EdgeBatch) -> EdgeBatch:
            keep = pred(batch.src) & pred(batch.dst)
            return batch.replace(mask=batch.mask & keep)

        return self._with(_Stateless(tx))

    def reverse(self) -> "EdgeStream":
        """Swap src/dst (SimpleEdgeStream.java:328)."""
        return self._with(_Stateless(lambda b: b.reversed()))

    def undirected(self) -> "EdgeStream":
        """Emit each edge in both directions (SimpleEdgeStream.java:350-361).
        Doubles the static batch size."""
        return self._with(_Stateless(lambda b: b.concat(b.reversed())))

    def distinct(self, by: str = "auto") -> "EdgeStream":
        """Drop duplicate edges (SimpleEdgeStream.java:301-323).

        Matches the reference's whole-Edge dedup (including the value) by
        default: ``by="auto"`` picks the two-table whole-edge mode unless
        the source is KNOWN value-less, where the single-table endpoint
        mode is identical semantics at half the state.  ``by="edge"``
        forces whole-edge; ``by="endpoints"`` forces endpoint-pair dedup
        (first occurrence's value wins — a deliberate semantic deviation
        for valued multigraphs, explicit by construction).
        """
        if by not in ("auto", "edge", "endpoints"):
            raise ValueError(f"unknown distinct mode {by!r}")
        if by == "auto":
            by = "endpoints" if self._valued is False else "edge"
        return self._with(_DistinctStage(by))

    def union(self, other: "EdgeStream") -> "EdgeStream":
        """Merge two edge streams (SimpleEdgeStream.java:343).  Batches from
        both (fully transformed) streams interleave round-robin."""
        if other.cfg.vertex_capacity != self.cfg.vertex_capacity:
            raise ValueError("union requires matching vertex_capacity")
        left, right = self, other

        def factory():
            its = [left.batches(), right.batches()]
            for batch in _round_robin(its):
                yield batch

        if left._valued is None or right._valued is None:
            merged_valued = True if (left._valued or right._valued) else None
        else:
            merged_valued = left._valued or right._valued
        out = EdgeStream(factory, self.cfg, valued=merged_valued)
        # one logical late sink across the union AND both input chains: an
        # on_late attached to either input (before or after this call) is
        # seen downstream of the union, and a sink attached to the union
        # fans out to both input chains (on_late's shared-chain contract)
        out._late_holder = _FanoutLateHolder(left._late_holder, right._late_holder)
        return out

    # ---- execution ----------------------------------------------------------

    def _compiled_step(self):
        stages = self._stages

        def build():
            def step(states, batch):
                out_states = []
                for stage, st in zip(stages, states):
                    st, batch = stage.apply(st, batch)
                    out_states.append(st)
                return tuple(out_states), batch

            return step

        # keyed by the stages tuple: every stream over the same stage chain
        # (including stage-less re-created sources) shares the executable
        return compile_cache.cached_jit(("pipeline_step", stages), build)

    def batches(self) -> Iterator[EdgeBatch]:
        """Run the pipeline, yielding transformed micro-batches."""
        states = tuple(stage.init(self.cfg) for stage in self._stages)
        step = self._compiled_step()
        for batch in self._source_factory():
            states, out = step(states, batch)
            yield out

    def _kernel_stream(self, init_fn, kernel, kernel_key=None) -> Iterator:
        """Run a terminal op's kernel fused with the pipeline stages.

        ``kernel(op_state, EdgeBatch) -> (op_state, outs)`` with ``outs`` a
        pytree of per-batch output arrays; ``init_fn(cfg)`` builds the op
        state.  Yields ``outs`` as HOST (numpy) pytrees per micro-batch,
        with the device->host downloads pipelined ahead of the consumer
        (io/wire.prefetch_to_host — async copies overlap later batches'
        compute, so the emission plane is bounded by the downlink rate, not
        per-batch round trips; VERDICT r3 weak #7).  When the source is
        wire-backed the whole step — device-side unpack, stages, kernel —
        is ONE jitted function fed by prefetched packed transfers with the
        carry donated (the property-stream analog of the aggregate fast
        path); otherwise it runs over the EdgeBatch source.
        """
        from gelly_streaming_tpu.io import wire as _wire_mod

        yield from _wire_mod.prefetch_to_host(
            self._kernel_stream_device(init_fn, kernel, kernel_key),
            depth=self.cfg.prefetch_depth,
        )

    def _kernel_stream_device(self, init_fn, kernel, kernel_key=None) -> Iterator:
        """`_kernel_stream`'s device plane: yields per-batch DEVICE outs."""
        cfg = self.cfg
        stages = self._stages
        step_j, wire_j = self._kernel_step_jits(kernel, kernel_key)

        # Committed placement: without it the first call (uncommitted fresh
        # arrays) and later calls (committed step outputs) hit different jit
        # cache entries — paying the compile twice.
        carry = jax.device_put(
            (tuple(stage.init(cfg) for stage in stages), init_fn(cfg)),
            jax.devices()[0],
        )

        if self._wire_arrays is None:
            for batch in self._source_factory():
                carry, outs = step_j(carry, batch)
                yield outs
            return

        from gelly_streaming_tpu.io import wire

        src, dst, batch_size = self._wire_arrays
        bs = min(batch_size, max(len(src), 1))
        n_full = len(src) // bs

        def full_batches():
            for i in range(n_full):
                yield src[i * bs : (i + 1) * bs], dst[i * bs : (i + 1) * bs]

        width = wire.width_for_capacity(cfg.vertex_capacity)
        with wire.WirePrefetcher(
            full_batches(), width, depth=cfg.prefetch_depth
        ) as pf:
            # hot-loop: fused kernel-stream dispatch (downloads ride
            # prefetch_to_host's async-copy queue, never this loop)
            for buf, _ in pf:
                carry, outs = wire_j(carry, buf, bs, width)
                yield outs
            # hot-loop-end
        rem = len(src) - n_full * bs
        if rem:
            tail = EdgeBatch.from_arrays(
                src[n_full * bs :], dst[n_full * bs :], pad_to=bs
            )
            carry, outs = step_j(carry, tail)
            yield outs

    def _kernel_step_jits(self, kernel, kernel_key=None):
        """Jitted (plain, wire) step functions for a terminal-op kernel.

        Executables live in the process-global compile cache
        (core/compile_cache.py): the key is ``kernel_key`` when the caller
        supplies a stable kernel identity (the built-in property streams do
        — re-created streams over equal stage chains then NEVER retrace),
        falling back to the kernel object itself (per-OutputStream reuse,
        the historical behavior).
        """
        from gelly_streaming_tpu.io import wire

        stages = self._stages
        identity = kernel_key if kernel_key is not None else kernel

        def make_step():
            def step(carry, batch):
                states, op_state = carry
                out_states = []
                for stage, st in zip(stages, states):
                    st, batch = stage.apply(st, batch)
                    out_states.append(st)
                op_state, outs = kernel(op_state, batch)
                return (tuple(out_states), op_state), outs

            return step

        def make_wire_step():
            step = make_step()

            def wire_step(carry, buf, bs, width):
                s, d = wire.unpack_edges(buf, bs, width)
                # keep the byte-unpack expression out of downstream
                # gather/scatter fusions (see _interleave_endpoints: ~7x TPU
                # compile blowup)
                s, d = jax.lax.optimization_barrier((s, d))
                return step(
                    carry, EdgeBatch(src=s, dst=d, mask=jnp.ones((bs,), bool))
                )

            return wire_step

        return (
            compile_cache.cached_jit(
                ("kernel_step", stages, identity), make_step
            ),
            compile_cache.cached_jit(
                ("kernel_wire_step", stages, identity),
                make_wire_step,
                static_argnums=(2, 3),
                donate_argnums=0,
            ),
        )

    def collect_edges(self) -> List[tuple]:
        out: List[tuple] = []
        for b in self.batches():
            out.extend(b.to_tuples())
        return out

    def edges_csv_lines(self) -> List[str]:
        return OutputStream(lambda: iter(self.collect_edges())).lines()

    # ---- continuous property streams ---------------------------------------

    def get_vertices(self) -> OutputStream:
        """(vertex, NullValue) on each vertex's first appearance
        (SimpleEdgeStream.java:116-129: EmitSrcAndTarget + FilterDistinctVertices)."""

        def init(cfg):
            return jnp.zeros((cfg.vertex_capacity,), bool)

        def kernel(seen, batch):
            v, m = _interleave_endpoints(batch)
            new = segments.first_occurrence_mask(v, m) & ~seen[v] & m
            seen = seen.at[jnp.where(m, v, 0)].max(m)
            return seen, (v, new)

        def blocks():
            for v, new in self._kernel_stream(init, kernel, ("vertices",)):
                idx = np.nonzero(new)[0]
                yield RecordBlock((v[idx], NULL))

        return OutputStream(blocks_fn=blocks)

    def get_degrees(self) -> OutputStream:
        """Running (vertex, degree) trace over both endpoints
        (SimpleEdgeStream.java:413-415, DegreeTypeSeparator both flags true)."""
        return self._degree_stream(EdgeDirection.ALL)

    def get_in_degrees(self) -> OutputStream:
        return self._degree_stream(EdgeDirection.IN)

    def get_out_degrees(self) -> OutputStream:
        return self._degree_stream(EdgeDirection.OUT)

    def _degree_stream(self, direction: EdgeDirection) -> OutputStream:
        """The continuous degree property stream.

        Batched trace-exact form of DegreeMapFunction's per-record HashMap
        update (SimpleEdgeStream.java:461-478): the k-th in-batch occurrence of
        vertex v emits ``base[v] + k + 1`` and a segment add bumps the base.

        When vertex ids fit 20 bits (vertex_capacity <= 2^20), records leave
        the device PACKED — 48 bits per (vertex, degree) plus one mask bit,
        built in-kernel (io/wire.py pack_records48) — instead of raw int32
        columns + a bool mask (9 B/slot): the trace download is the emission
        plane's bottleneck on a narrow device link, and this is its wire
        format (the mirror of the ingest pack, VERDICT r2 missing #7).
        Degrees cap at 2^28 in the packed form; wider vertex spaces ship raw
        columns (correct at any capacity).
        """
        from gelly_streaming_tpu.io import wire as wire_mod

        packed_ok = self.cfg.vertex_capacity <= 1 << 20

        def init(cfg):
            return jnp.zeros((cfg.vertex_capacity,), jnp.int32)

        def kernel(counts, batch):
            if direction == EdgeDirection.ALL:
                v, m = _interleave_endpoints(batch)
            elif direction == EdgeDirection.OUT:
                v, m = batch.src, batch.mask
            else:
                v, m = batch.dst, batch.mask
            rank = segments.occurrence_rank(v, m)
            emitted = counts[v] + rank + 1
            counts = counts.at[jnp.where(m, v, 0)].add(m.astype(jnp.int32))
            if not packed_ok:
                return counts, (v, emitted, m)
            return counts, (
                wire_mod.pack_records48(v, emitted),
                wire_mod.pack_mask_bits(m),
            )

        def blocks():
            # _kernel_stream pipelines the downloads (async copies overlap
            # later batches' compute); outs arrive as numpy
            for outs in self._kernel_stream(
                init, kernel, ("degrees", direction, packed_ok)
            ):
                if packed_ok:
                    packed, maskbits = outs
                    ids, vals, m = wire_mod.unpack_records48(
                        packed, maskbits, len(packed) // 6
                    )
                else:
                    ids, vals, m = outs
                idx = np.nonzero(m)[0]
                yield RecordBlock((ids[idx], vals[idx]))

        return OutputStream(blocks_fn=blocks)

    def number_of_vertices(self) -> OutputStream:
        """Running distinct-vertex count, emitted on change
        (SimpleEdgeStream.java:366-383 via globalAggregate's change-dedup
        GlobalAggregateMapper :562-576)."""

        def init(cfg):
            return jnp.zeros((cfg.vertex_capacity,), bool)

        def kernel(seen, batch):
            v, m = _interleave_endpoints(batch)
            new = segments.first_occurrence_mask(v, m) & ~seen[v] & m
            base = jnp.sum(seen.astype(jnp.int32))
            running = base + jnp.cumsum(new.astype(jnp.int32))
            seen = seen.at[jnp.where(m, v, 0)].max(m)
            return seen, (running, new)

        def blocks():
            for running, new in self._kernel_stream(init, kernel, ("nvertices",)):
                idx = np.nonzero(new)[0]
                yield RecordBlock((running[idx],))

        return OutputStream(blocks_fn=blocks)

    def number_of_edges(self) -> OutputStream:
        """Running edge count, one record per arriving edge
        (parallelism-1 counter, SimpleEdgeStream.java:388-404)."""

        def init(cfg):
            return jnp.zeros((), jnp.int32)

        def kernel(total, batch):
            running = total + jnp.cumsum(batch.mask.astype(jnp.int32))
            return total + batch.num_valid(), (running, batch.mask)

        def blocks():
            for running, m in self._kernel_stream(init, kernel, ("nedges",)):
                idx = np.nonzero(m)[0]
                yield RecordBlock((running[idx],))

        return OutputStream(blocks_fn=blocks)

    def get_edges(self) -> OutputStream:
        """The edge stream itself as records (GraphStream.getEdges)."""

        def records():
            for batch in self.batches():
                for t in batch.to_tuples():
                    yield t

        return OutputStream(records)

    def keyed_aggregate(
        self,
        edge_expand: Callable,
        state_init: Callable,
        vertex_update: Callable,
    ) -> OutputStream:
        """Generic keyed aggregation — the reference's
        ``aggregate(edgeMapper, vertexMapper)`` (SimpleEdgeStream.java:489-494:
        flatMap -> keyBy(0) -> stateful map), array-form:

          edge_expand(src, dst, val) -> (keys [M, B], vals pytree of [M, B])
              vectorized flatMap emitting M records per edge (static M);
          state_init(cfg) -> dense per-key state pytree (arrays over [0, C));
          vertex_update(state, keys [N], vals [N], mask [N])
              -> (state, out pytree of [N], out_mask [N])
              batched keyed update; use ops.segments.occurrence_rank for
              running per-key semantics within a batch.

        Returns the (key, out...) record stream.  Records emit as vectorized
        blocks (one RecordBlock of compacted columns per micro-batch — no
        per-record Python on the hot path, VERDICT r2 weak #5); the per-tuple
        view derives from the block columns, so golden traces are unchanged.
        """
        cfg = self.cfg

        def kernel(state, batch):
            keys, vals = edge_expand(batch.src, batch.dst, batch.val)
            m = keys.shape[0]
            flat_keys = keys.reshape(-1)
            flat_vals = jax.tree.map(lambda a: a.reshape((-1,) + a.shape[2:]), vals)
            flat_mask = jnp.tile(batch.mask, (m, 1)).reshape(-1)
            state, out, out_mask = vertex_update(
                state, flat_keys, flat_vals, flat_mask
            )
            return state, flat_keys, out, out_mask

        # the kernel's traced behavior is fully determined by the two user
        # callables, so equal (expand, update) pairs share the executable
        # across re-created streams
        kernel = compile_cache.cached_jit(
            ("keyed_aggregate", edge_expand, vertex_update),
            lambda fn=kernel: fn,
        )

        def chunks():
            state = state_init(cfg)
            for batch in self.batches():
                state, keys, out, out_mask = kernel(state, batch)
                sel = np.nonzero(np.asarray(out_mask))[0]
                if len(sel) == 0:
                    continue
                k_h = np.asarray(keys)[sel]
                cols = tuple(np.asarray(x)[sel] for x in jax.tree.leaves(out))
                yield k_h, cols, jax.tree.structure(out)

        def is_flat(treedef) -> bool:
            """Flat tuple of leaves (or a single leaf): the block columns
            reproduce the record tuples exactly."""
            n = treedef.num_leaves
            return treedef == jax.tree.structure(tuple(range(n))) or (
                treedef == jax.tree.structure(0)
            )

        def blocks():
            for k_h, cols, treedef in chunks():
                if not is_flat(treedef):
                    # nested outputs (dicts etc.) keep their structure via
                    # the per-record view; pack them as an object column
                    recs = np.empty((len(k_h),), object)
                    for i in range(len(k_h)):
                        recs[i] = jax.tree.unflatten(
                            treedef, [c[i].item() for c in cols]
                        )
                    yield RecordBlock((k_h, recs))
                    continue
                yield RecordBlock((k_h,) + cols)

        return OutputStream(blocks_fn=blocks)

    def global_aggregate(
        self,
        update: Callable,
        initial_state: Callable,
        result: Callable,
        emit_on_change: bool = True,
    ) -> OutputStream:
        """Centralized (parallelism-1 analog) aggregation with change-dedup
        (SimpleEdgeStream.java:505-519 + GlobalAggregateMapper :562-576).

        update(state, batch) -> state (jitted once); result(state) -> host
        value; a record is emitted per batch only when the result changes
        (always, when emit_on_change=False).
        """
        cfg = self.cfg
        update_j = compile_cache.cached_jit(
            ("global_aggregate", update), lambda: update
        )

        def records():
            state = initial_state(cfg)
            prev = None
            for batch in self.batches():
                state = update_j(state, batch)
                res = result(state)
                if not emit_on_change or res != prev:
                    yield res if isinstance(res, tuple) else (res,)
                    prev = res

        return OutputStream(records)

    def build_neighborhood(
        self, directed: bool = False, mode: str = "block"
    ) -> OutputStream:
        """Continuous adjacency stream (SimpleEdgeStream.java:531-560): emits
        per arriving edge its source's adjacency, with state as of the end of
        the edge's micro-batch (the reference's per-key TreeSet trace is
        recovered exactly at batch_size=1).

        directed=False mirrors the reference default: the stream is made
        undirected first, so each edge contributes both directions.

        ``mode="block"`` (default) emits vectorized RecordBlocks whose
        neighbor column is the device-SORTED padded row ([D] int32, -1 past
        the degree) — no per-record Python or host sorting on the hot path
        (VERDICT r2 weak #5).  ``mode="trace"`` emits per-record
        (src, dst, sorted-neighbor-tuple) host tuples — the reference's
        BuildNeighborhoods record shape (:540-560) for golden parity.
        """
        if mode not in ("block", "trace"):
            raise ValueError(f"unknown mode {mode!r}")
        cfg = self.cfg
        base = self if directed else self.undirected()
        big = jnp.iinfo(jnp.int32).max

        def kernel(table, batch):
            table, _ = neighbors.insert_unique_batch(
                table, batch.src, batch.dst, batch.mask
            )
            rows, valid = neighbors.gather_rows(table, batch.src)
            # sort each row on device (invalid slots to the end as -1): the
            # reference's TreeSet iteration order without host work
            rows_sorted = jnp.sort(jnp.where(valid, rows, big), axis=1)
            deg = jnp.sum(valid, axis=1)
            rows_sorted = jnp.where(
                jnp.arange(rows.shape[1])[None, :] < deg[:, None], rows_sorted, -1
            )
            return table, rows_sorted, deg

        kernel = compile_cache.cached_jit(
            ("build_neighborhood",), lambda fn=kernel: fn
        )

        def blocks():
            table = neighbors.init_table(cfg.vertex_capacity, cfg.max_degree)
            for batch in base.batches():
                table, rows_sorted, deg = kernel(table, batch)
                sel = np.nonzero(np.asarray(batch.mask))[0]
                if len(sel) == 0:
                    continue
                yield RecordBlock(
                    (
                        np.asarray(batch.src)[sel],
                        np.asarray(batch.dst)[sel],
                        np.asarray(rows_sorted)[sel],
                        np.asarray(deg)[sel],
                    )
                )

        if mode == "block":
            return OutputStream(blocks_fn=blocks)

        def records():
            for blk in blocks():
                s_c, d_c, rows_c, deg_c = blk.columns
                for i in range(blk.num_records):
                    yield (
                        int(s_c[i]),
                        int(d_c[i]),
                        tuple(int(x) for x in rows_c[i][: deg_c[i]]),
                    )

        return OutputStream(records)

    # ---- windows & aggregations (defined in sibling modules) ----------------

    def slice(
        self,
        window_ms: Optional[int] = None,
        direction: EdgeDirection = EdgeDirection.OUT,
        slide_ms: Optional[int] = None,
    ):
        """Windowed snapshot stream (SimpleEdgeStream.java:135-167).

        Tumbling by default; pass ``slide_ms`` (must divide ``window_ms``)
        for sliding windows of size ``window_ms`` emitted every ``slide_ms``
        — beyond the tumbling-only reference, implemented by pane-sharing
        (core/windows.sliding_panes) so each edge is assembled once per
        slide, not once per window."""
        from gelly_streaming_tpu.core.snapshot import SnapshotStream

        return SnapshotStream(
            self, window_ms or self.cfg.window_ms, direction, slide_ms
        )

    def aggregate(
        self,
        summary_aggregation,
        checkpoint_path: Optional[str] = None,
        restore: bool = True,
    ) -> OutputStream:
        """Run a summary aggregation over this stream
        (GraphStream.java:139-140 -> SummaryAggregation.run).

        With ``checkpoint_path`` the running summary and stream position are
        snapshot as the stream folds and restored on start — on every
        execution path, including the packed-wire fast path (the reference
        checkpoints inside its full-speed pipeline the same way,
        SummaryAggregation.java:127-135)."""
        return summary_aggregation.run(
            self, checkpoint_path=checkpoint_path, restore=restore
        )


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


def _interleave_endpoints(batch: EdgeBatch) -> Tuple[jax.Array, jax.Array]:
    """Per-edge (src, dst) emission order, flattened to [2B]
    (mirrors EmitSrcAndTarget / DegreeTypeSeparator emission order,
    SimpleEdgeStream.java:181-188,450-458).

    The barrier stops XLA from inlining the stack/reshape expression into
    every downstream gather/scatter — without it the TPU compile of a
    sort+gather+scatter consumer at 2^21 rows blows up ~7x (173s vs 24s
    measured on v5e via remote compile)."""
    v = jnp.stack([batch.src, batch.dst], axis=1).reshape(-1)
    m = jnp.stack([batch.mask, batch.mask], axis=1).reshape(-1)
    return jax.lax.optimization_barrier(v), m


def _round_robin(iterators: List[Iterator]) -> Iterator:
    iterators = list(iterators)
    while iterators:
        nxt = []
        for it in iterators:
            try:
                yield next(it)
                nxt.append(it)
            except StopIteration:
                pass
        iterators = nxt

"""Owner-sharded summary state: the SummaryAggregation sharded-state protocol.

The mesh runner's historical data plane keeps every shard's partial summary
at FULL size and reconciles by all_gathering all S partials and re-combining
them replicated on every shard — comms and combine cost O(C * S) per
dispatch no matter how few labels a batch actually changed
(core/aggregation.py, MeshAggregationRunner).  This module defines the
protocol that replaces it as the default mesh streaming path (ISSUE 4):

  * **Owner blocks** — the persistent summary is modulo block-sharded:
    vertex g's row lives ONLY on shard g % S at block row g // S (same
    ownership as parallel/mesh.owner_of, ring.py, BlockShardedCC).  Per-shard
    persistent state — and checkpoint volume — is O(C/S).
  * **Local folds** — a dispatch folds edges into a per-shard TRANSIENT
    scratch with the descriptor's ordinary ``update`` (updateFun): no
    collectives on the per-batch hot path.
  * **Delta exchange** — cross-shard reconciliation ships fixed-capacity,
    pow2-bucketed buffers of (changed row, value) pairs since the last
    exchange (parallel/routing.pack_slab_deltas) via all_to_all —
    propagation blocking (arXiv:2011.08451) + GraphBLAST's frontier/delta
    formulation (arXiv:1908.01407): communicate only what changed, bucketed
    by owning partition.  Exchanges happen at emission/snapshot boundaries,
    so steady-state dispatches pay zero collective bytes.
  * **Lazy gather** — the replicated full view is reassembled
    (routing.gather_blocks) ONLY at emit/snapshot boundaries; the
    collective-discipline analyzer pass (COLLGATHER) pins that confinement.

Descriptors opt in by returning a ``ShardedStateSpec`` from
``sharded_state_spec(cfg)``; the ``all_gather``-replicated combine remains
the fallback — and the equivalence oracle — for descriptors that don't.

Protocol contract: the block-sharded initial state must be the fold/combine
identity (so empty shards and restores need no masking), and
``combine(a, update(initial, e)) == update(a, e)`` must hold (running folds
continue in place instead of re-merging per-pane partials) — true of the
union-find and additive summaries this plane serves.

Relation to CROSS-TENANT fused dispatch (``cfg.fused_dispatch``,
runtime/manager.py): the two batching axes are mutually exclusive by
construction.  This plane shards ONE job's summary state over S devices;
the fused plane stacks N single-partition jobs' per-window partials along
a batch axis of one device dispatch — each tenant's summary-state row
stays wholly its own (per-job combine/transform/checkpoint, no cross-job
state), which is why ``fused_eligible`` refuses sharded configs and a
``num_shards > 1`` job under a fused manager simply keeps this plane and
dispatches solo.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

from gelly_streaming_tpu.parallel.mesh import SHARD_AXIS
from gelly_streaming_tpu.utils.envswitch import resolve_switch


def reshard_summary(
    blocks, cfg, old_num_shards: int, new_num_shards: int, rows=None
):
    """Re-route owner-sharded summary blocks into a new shard geometry.

    ``blocks`` is a spec's block pytree — every array leaf laid out
    ``[old_S, C/old_S, ...]`` under the modulo ownership every
    ``shard_summary`` in the tree uses (vertex ``g`` at row
    ``(g % S, g // S)``) — and the result is the SAME pytree re-blocked
    ``[new_S, C/new_S, ...]``.  This is the elastic control plane's state
    re-route (runtime/autoscale.py): a drained job's persistent blocks
    move to the 2x (or half) geometry without a device in the loop.

    Bit-exact by construction: each leaf is unsharded through its
    replicated ``[C, ...]`` view (the ``shard_summary`` inverse — the same
    reindexing ``unshard_labels`` does for CC label blocks) and re-blocked
    with the identical ``reshape(-1, S).swapaxes`` rule ``shard_summary``
    itself applies, so for any spec
    ``reshard_summary(spec.shard_summary(x, cfg, a), cfg, a, b)
    == spec.shard_summary(x, cfg, b)`` holds leaf-for-leaf — pinned by
    tests/test_sharded_state.py's round-trip oracles.

    Pure host reindexing (no device, no collective): both geometries are
    modulo-sharded, so the move is two reshapes per leaf, O(C) bytes.

    ``rows`` selects the per-leaf row count the layout is validated
    against: ``None`` (default) requires every leaf to be vertex-keyed
    (``cfg.vertex_capacity`` rows — the owner-block summaries this plane
    grew up on); ``"auto"`` takes each leaf's own ``S * block_rows`` total
    — the register-keyed sketch blocks, whose leaves have DIFFERENT pow2
    row counts (sample rows vs HLL registers vs count-min cells) that all
    reblock by the same modulo rule.
    """
    import numpy as np

    old_s, new_s = int(old_num_shards), int(new_num_shards)
    cap = cfg.vertex_capacity
    for name, s in (("old", old_s), ("new", new_s)):
        if s <= 0:
            raise ValueError(f"{name} shard count must be positive, got {s}")
        if rows is None and cap % s:
            raise ValueError(
                f"vertex_capacity ({cap}) must be divisible by the {name} "
                f"shard count ({s}) for even re-sharding"
            )

    def leaf(a):
        a = np.asarray(a)
        if a.ndim < 2 or a.shape[0] != old_s:
            raise ValueError(
                f"block leaf shape {a.shape} does not match the "
                f"[{old_s}, rows/{old_s}, ...] owner-block layout"
            )
        total = a.shape[0] * a.shape[1]
        if rows is None and total != cap:
            raise ValueError(
                f"block leaf shape {a.shape} does not match the "
                f"[{old_s}, {cap // old_s}, ...] owner-block layout"
            )
        if total % new_s:
            raise ValueError(
                f"leaf row count ({total}) must be divisible by the new "
                f"shard count ({new_s}) for even re-sharding"
            )
        # shard_summary inverse: full[g] = blocks[g % S, g // S]
        full = np.ascontiguousarray(np.swapaxes(a, 0, 1)).reshape(
            (total,) + a.shape[2:]
        )
        # and shard_summary forward at the new geometry
        reblocked = np.swapaxes(
            full.reshape((total // new_s, new_s) + a.shape[2:]), 0, 1
        )
        return np.ascontiguousarray(reblocked)

    import jax

    return jax.tree.map(leaf, blocks)


def resolve_sharded_state(cfg) -> bool:
    """Effective sharded-state switch: config > env > on.

    ``cfg.sharded_state``: 1 forces on, 0 forces off, -1 (default) defers to
    the ``GELLY_SHARDED_STATE`` env var, defaulting ON — descriptors that
    supply a spec ride the owner-sharded path unless explicitly disabled.
    """
    return resolve_switch(
        getattr(cfg, "sharded_state", -1), "GELLY_SHARDED_STATE", default=True
    )


class ShardContext(NamedTuple):
    """Static per-step facts handed to the spec's traced hooks."""

    cfg: object
    num_shards: int
    axis_name: str = SHARD_AXIS
    #: pow2-bucketed per-(sender, receiver) delta-buffer capacity
    delta_cap: int = 1


class ExchangeStats(NamedTuple):
    """Device-side int32 counters an exchange returns (per shard).

    Fetched at the exchange boundary (emit/snapshot — already a host sync
    point) and folded into utils.metrics comms counters; never read on the
    per-dispatch hot path.
    """

    rounds: object  # exchange passes executed (dynamic: spills/chains retry)
    delta_hwm: object  # max per-owner changed-row demand seen (pre-capping)
    spilled: object  # rows deferred past a full buffer (retried, never lost)


class ShardedStateSpec:
    """Descriptor hooks for the owner-sharded summary plane.

    Subclasses implement the traced hooks against a single shard's view
    (call them only inside shard_map over ``ctx.axis_name``).  The LOCAL
    fold is deliberately NOT part of this spec: dispatches fold with the
    descriptor's ordinary ``initial_state``/``update`` into a transient
    full-[C] scratch, so the sharded and replicated planes share one
    updateFun and cannot drift.
    """

    #: optional host_route key ("src"/"dst") — when set, the mesh runner's
    #: pane prepare buckets edges by owner on the prefetcher's pack thread
    #: (keyBy moved off the dispatch thread); None keeps round-robin panes
    #: (skew-immune, e.g. CC's ring-free delta plane needs no edge routing)
    route_key: Optional[str] = None

    def __init__(self, agg):
        self.agg = agg

    # -- host-side hooks ------------------------------------------------------

    def initial_shard_state(self, cfg, num_shards: int):
        """[S, ...]-stacked host blocks (leading axis = shard) — MUST be the
        combine identity so restores and empty shards need no masking."""
        raise NotImplementedError

    def shard_summary(self, summary, cfg, num_shards: int):
        """Host: a replicated summary pytree -> [S, ...] owner blocks (the
        inverse of ``gather_state``; used to seed blocks from a restored
        positional checkpoint)."""
        raise NotImplementedError

    def delta_bound(self, cfg, n_edges: int) -> int:
        """Rows that can change per exchange interval from ``n_edges`` folded
        edges — sizes the pow2-bucketed delta buffers (routing.delta_capacity
        clamps to C/S, the structural maximum)."""
        return 2 * max(int(n_edges), 1)

    def comm_profile(self, cfg, ctx: ShardContext) -> dict:
        """Static per-shard byte costs: ``round_nbytes`` (one exchange pass)
        and ``gather_nbytes`` (one full-view reassembly) — multiplied by the
        dynamic round counts into utils.metrics comms counters."""
        raise NotImplementedError

    # -- traced hooks (inside shard_map) --------------------------------------

    def exchange(self, local_state, blocks, ctx: ShardContext):
        """Reconcile a local partial fold into the owner blocks.

        ``local_state``: this shard's transient full-[C] partial (the
        descriptor's ordinary summary pytree, folded since the LAST
        exchange).  Returns ``(blocks', ExchangeStats)``; the caller resets
        the local scratch to ``initial_state`` afterwards.  May loop
        (while_loop + pmax) until every delta is absorbed — spilled buffer
        rows re-derive next round rather than dropping.
        """
        raise NotImplementedError

    def gather_state(self, blocks, ctx: ShardContext):
        """Owner blocks -> the full replicated summary pytree (emit/snapshot
        boundaries ONLY — the lazy gather the COLLGATHER pass sanctions)."""
        raise NotImplementedError

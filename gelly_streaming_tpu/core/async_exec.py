"""Asynchronous window execution pipeline: overlapped pack -> transfer ->
fold -> fetch with non-blocking result delivery.

The windowed plane's synchronous loop pays one full host round trip per
closed window: the pane is padded and packed inline on the dispatch thread,
its fold dispatched, and the emission fetched before the next pane is even
packed — so per-window latency is floored by the host->device link RTT while
the device idles (ARCHITECTURE.md performance model; the classic
"preprocessing/communication is the bottleneck" regime of propagation
blocking).  This module keeps a bounded number of windows in flight end to
end instead:

* **pack** — pane padding/packing runs on the prefetcher's pack thread
  (io/wire.Prefetcher), writing into reusable transfer-layout arenas
  (``ArenaPool``) with double-buffered, donation-safe ownership: an arena is
  recycled only after the fold that consumed it completed (device_put may
  zero-copy host memory on the CPU backend, so "transfer started" is not
  "safe to overwrite").
* **transfer** — ``device_put`` on the prefetcher's second thread, so
  packing window k+1 overlaps transferring window k.
* **dispatch** — the consumer thread dispatches folds without waiting (JAX
  dispatch is asynchronous); window emissions go into a completion queue
  with their device->host copies started (``copy_to_host_async``).
* **drain** — completion-queue entries resolve in window order, so the
  record sequence is bit-identical to the synchronous path; checkpoint
  saves ride the queue too (emit-before-snapshot is preserved per window).

``cfg.async_windows`` (or the ``GELLY_ASYNC_WINDOWS`` env var when the
config leaves it at 0) sets the in-flight depth; 0 keeps the synchronous
lockstep — the default and the equivalence oracle for
tests/test_async_windows.py.  Occupancy counters (in-flight high-water
mark, per-stage stall seconds) land in utils/metrics.pipeline_stats.
"""

from __future__ import annotations

import collections
import os
import threading
import time
from typing import Callable, Iterable, Iterator, Optional

import numpy as np

from gelly_streaming_tpu.utils import metrics, tracing


def resolve_depth(cfg) -> int:
    """Effective async-window depth: explicit config > env var > 0 (sync).

    ``cfg.async_windows`` wins when set; a config left at the 0 default
    defers to ``GELLY_ASYNC_WINDOWS`` so a whole process can be switched
    without threading the knob through every call site.
    """
    n = getattr(cfg, "async_windows", 0)
    if n:
        return max(0, int(n))
    env = os.environ.get("GELLY_ASYNC_WINDOWS")
    if env:
        try:
            return max(0, int(env))
        except ValueError:
            pass
    return 0


def start_host_fetch(tree) -> None:
    """Kick off the device->host copy of every array leaf (non-blocking).

    The completion-queue contract: emissions enter the queue with their
    downloads already in flight, so the drain's materialization waits on a
    copy that has been overlapping later windows' compute, not a fresh RTT.
    Host-side leaves (numpy, python scalars) need no copy and are skipped.
    """
    import jax

    for leaf in jax.tree.leaves(tree):
        try:
            leaf.copy_to_host_async()
        except AttributeError:
            pass


def wait_ready(tree) -> None:
    """Block until every device leaf of ``tree`` is computed.

    This is the completion-queue drain's synchronization point — the ONE
    place the async pipeline is allowed to wait on the device (hot-loop
    lint allowlist).  Used before recycling an arena whose host memory the
    fold may still be reading through a zero-copy transfer.
    """
    import jax

    t0 = time.perf_counter()
    for leaf in jax.tree.leaves(tree):
        try:
            leaf.block_until_ready()  # hot-loop-ok: completion-queue drain
        except AttributeError:
            pass
    metrics.pipeline_add(
        "pipeline_drain_stall_s", time.perf_counter() - t0
    )


class ArenaPool:
    """Reusable host transfer arenas with donation-safe ownership.

    ``acquire(shape, dtype)`` hands out a zeroed numpy buffer — recycled
    when one is free, freshly allocated otherwise; ``release`` returns
    buffers for reuse, keeping at most ``per_shape`` per (shape, dtype)
    class.  The pool itself NEVER blocks: the number of panes holding
    arenas is already bounded by the prefetcher's queues plus the
    completion queue's depth (that is the pipeline's backpressure), so the
    pool only has to cap how much recycled memory it retains — a blocking
    pool here can deadlock the pack thread against the very drain that
    would release its arenas.

    Ownership rule (why release happens at the completion-queue drain, not
    after device_put): on the CPU backend ``jax.device_put`` may alias the
    numpy buffer zero-copy, so the fold reads the arena's memory until the
    dispatch that consumed it completes.  Callers release an arena only
    after something downstream of its fold is known complete (e.g. the
    window's emission materialized) — double-buffered by construction:
    while window k's arenas are owned by its in-flight fold, window k+1
    packs into different buffers.
    """

    def __init__(self, per_shape: int = 8):
        self._per_shape = max(1, per_shape)
        # (shape, dtype str) -> free arrays; touched by the pack thread
        # (acquire) and the drain (release) concurrently
        self._free: dict = {}  # guarded-by: _lock
        self._lock = threading.Lock()

    def acquire(self, shape, dtype) -> np.ndarray:
        key = (tuple(shape), np.dtype(dtype).str)
        with self._lock:
            free = self._free.get(key)
            buf = free.pop() if free else None
        if buf is None:
            return np.zeros(shape, dtype)
        buf.fill(0)
        return buf

    def release(self, *bufs) -> None:
        with self._lock:
            for buf in bufs:
                if buf is None:
                    continue
                key = (tuple(buf.shape), buf.dtype.str)
                free = self._free.setdefault(key, [])
                if len(free) < self._per_shape:
                    free.append(buf)


def pipelined(
    items: Iterable,
    prepare: Callable,
    dispatch: Callable,
    finish: Callable,
    depth: int,
    prefetch_depth: int = 4,
    device=None,
) -> Iterator:
    """Run windows through pack -> transfer -> dispatch -> drain with up to
    ``depth`` dispatched-but-undrained windows in flight.

    ``prepare(item) -> (meta, host_arrays)`` runs on the prefetcher's pack
    thread; the ``device_put`` of ``host_arrays`` on its transfer thread;
    ``dispatch(meta, device_arrays) -> handle`` on the caller's thread (an
    asynchronous JAX dispatch — it must not block); ``finish(meta, handle)
    -> result`` resolves a completed window at drain time.  Results yield
    strictly in item order, so consumers observe the synchronous sequence.

    On an upstream failure, windows already dispatched are drained (their
    results were computed and the synchronous path would have delivered
    them) before the failure propagates — mirroring the sequential loop's
    emission-then-raise order.
    """
    from gelly_streaming_tpu.io import wire

    depth = max(1, depth)
    metrics.pipeline_high_water("pipeline_prefetch_depth", prefetch_depth)
    pending: "collections.deque" = collections.deque()

    def drain_one():
        meta, handle = pending.popleft()
        t0 = time.perf_counter()
        out = finish(meta, handle)
        metrics.pipeline_add(
            "pipeline_drain_stall_s", time.perf_counter() - t0
        )
        metrics.pipeline_add("pipeline_windows_drained", 1)
        span = tracing.find_span(meta) if tracing.active() else None
        if span is not None:
            span.mark("drain", t0)
            tracing.flight_recorder().record(span)
        return out

    with wire.Prefetcher(
        items, prepare, device=device, depth=prefetch_depth
    ) as pf:
        it = iter(pf)
        try:
            # hot-loop: async window dispatch (no per-window host syncs)
            while True:
                t0 = time.perf_counter()
                try:
                    meta, dev = next(it)
                except StopIteration:
                    break
                metrics.pipeline_add(
                    "pipeline_dispatch_stall_s", time.perf_counter() - t0
                )
                span = tracing.find_span(meta) if tracing.active() else None
                t_disp = time.perf_counter() if span is not None else 0.0
                handle = dispatch(meta, dev)
                if span is not None:
                    span.mark("dispatch", t_disp)
                pending.append((meta, handle))
                metrics.pipeline_add("pipeline_windows_dispatched", 1)
                metrics.pipeline_high_water(
                    "pipeline_inflight_high_water", len(pending)
                )
                while len(pending) > depth:
                    yield drain_one()
            # hot-loop-end
        except GeneratorExit:
            # consumer closed (a cancelled job, an abandoned run): no
            # further yields are legal, but dispatched windows still own
            # transfer arenas whose host memory their folds may be reading
            # (zero-copy device_put).  Drain the completion queue WITHOUT
            # yielding — finish() waits on each fold and recycles its
            # arenas — so cancellation neither leaks arenas nor recycles
            # one a fold still reads.
            while pending:
                drain_one()
            raise
        except BaseException:
            # deliver windows whose results already exist, then propagate
            # (the sequential path emitted them before hitting the failure)
            while pending:
                yield drain_one()
            raise
    while pending:
        yield drain_one()


def async_merge_loop(
    agg,
    cfg,
    panes: Iterator,
    fold_pane: Callable,
    checkpoint_path: Optional[str],
    restore: bool,
    unwrap: bool = False,
    depth: int = 2,
    release: Optional[Callable] = None,
    fold_is_running: bool = False,
) -> Iterator[tuple]:
    """The Merger with a non-blocking completion queue
    (SummaryAggregation._merge_loop's async form — same restore, merge,
    emission-order, and at-least-once semantics, pinned by
    tests/test_async_windows.py).

    ``fold_is_running`` mirrors the synchronous loop: the owner-sharded
    plane's folds accumulate into persistent cross-window blocks and return
    the running summary directly, so no combine is dispatched here — the
    double-buffered route -> fold -> exchange schedule stays non-blocking
    (each pane's exchange+gather chains behind its fold in the device queue
    while the NEXT pane's routing/packing runs on the prefetcher's pack
    thread).

    Window folds dispatch without waiting; each window's emission record
    enters a completion queue with its device->host copies started, and
    records yield in window order with the positional checkpoint saved
    immediately after its window's record is consumed — exactly the
    synchronous loop's emit-before-snapshot interleaving, so a crash at any
    drain point leaves the same snapshot/emission frontier as the sync path.

    ``release(payload)`` (optional) recycles a window's transfer arenas at
    drain time; it is called only after the window's FOLD OUTPUT is known
    complete (``wait_ready``), which proves the arena's host memory is no
    longer read (donation-safe ownership, see ArenaPool).  The fold output
    — not the emission record — is the wait target because transforms may
    wrap state in non-pytree host objects whose leaves wait_ready cannot
    block on.
    """
    running = None
    start_after = -1
    global_done = False
    if checkpoint_path and restore:
        from gelly_streaming_tpu.utils.checkpoint import (
            checkpoint_exists,
            load_state,
        )

        if checkpoint_exists(checkpoint_path):
            try:
                snap = load_state(checkpoint_path, agg._checkpoint_like(cfg))
                if bool(snap["has_summary"]):
                    running = snap["summary"]
                start_after = int(snap["last_window"])
                global_done = bool(snap["global_done"])
            except ValueError:
                # legacy snapshot layout: a bare summary pytree with no
                # stream position (pre-position checkpoints)
                running = load_state(checkpoint_path, agg.initial_state(cfg))

    # completion queue: (window_id, record, ckpt summary, global_done after
    # this window, release payload) in dispatch (= window) order
    pending: "collections.deque" = collections.deque()

    def save(wid_through: int, gdone: bool, summary) -> None:
        from gelly_streaming_tpu.utils.checkpoint import save_state

        t0 = time.perf_counter()
        save_state(
            checkpoint_path,
            {
                "summary": summary,
                "has_summary": np.full((), not agg.transient_state, bool),
                "last_window": np.full((), wid_through, np.int64),
                "global_done": np.full((), gdone, bool),
            },
        )
        metrics.pipeline_add(
            "pipeline_drain_stall_s", time.perf_counter() - t0
        )

    drained_through = start_after
    drained_global = global_done

    def drain_one():
        nonlocal drained_through, drained_global
        wid, rec, summary, payload, span, t_item, fold_out = pending.popleft()
        metrics.pipeline_add("pipeline_windows_drained", 1)
        t_drain = time.perf_counter()
        if release is not None and payload is not None:
            # wait on the FOLD OUTPUT, not the transformed record: transforms
            # may return non-pytree host wrappers (e.g. CC's DisjointSet)
            # whose opaque leaves wait_ready silently skips, which would
            # recycle the arena under a still-pending zero-copy fold
            wait_ready(fold_out)
            release(payload)  # arena-live-until: drain — this IS the drain
        t_emit = time.perf_counter()
        # emission latency for EVERY window (bounded histogram, one lock
        # per window — same cost class as the pipeline counters above);
        # span recording only for the sampled ones
        metrics.hist_record(
            "window_close_to_emission_ms", (t_emit - t_item) * 1e3
        )
        if span is not None:
            span.mark("drain", t_drain, t_emit)
            span.mark("emit", t_emit)
            tracing.flight_recorder().record(span)
        return wid, rec, summary

    panes_it = iter(panes)
    try:
        # hot-loop: async Merger dispatch (no per-window host syncs)
        while True:
            t_pull = time.perf_counter()
            try:
                item = next(panes_it)
            except StopIteration:
                break
            metrics.pipeline_add(
                "pipeline_dispatch_stall_s", time.perf_counter() - t_pull
            )
            pane, payload = item if unwrap else (item, item)
            already_folded = (0 <= pane.window_id <= start_after) or (
                pane.window_id == -1 and global_done
            )
            if already_folded:
                continue  # folded before the snapshot: replay-safe
            # the span (if this window was sampled at the pack thread)
            # rides the payload meta; its dispatch stage covers the fold
            # dispatch + transform + host-fetch kickoff below
            span = tracing.find_span(payload) if tracing.active() else None
            t_item = time.perf_counter()
            pane_summary = fold_pane(payload)
            if pane_summary is None:
                continue
            if running is None or agg.transient_state or fold_is_running:
                running = pane_summary
            else:
                running = agg._combine_j(running, pane_summary)
            out = agg.transform(running)
            rec = out if isinstance(out, tuple) else (out,)
            start_host_fetch(rec)
            ck = running if checkpoint_path else None
            if ck is not None:
                start_host_fetch(ck)
            if span is not None:
                span.mark("dispatch", t_item)
            pending.append(
                (
                    pane.window_id,
                    rec,
                    ck,
                    payload if release is not None else None,
                    span,
                    t_item,
                    pane_summary if release is not None else None,
                )
            )
            metrics.pipeline_add("pipeline_windows_dispatched", 1)
            metrics.pipeline_high_water(
                "pipeline_inflight_high_water", len(pending)
            )
            start_after = max(pane.window_id, start_after)
            global_done = global_done or pane.window_id == -1
            if agg.transient_state:
                running = None
            while len(pending) > depth:
                wid, rec_d, summary = drain_one()
                yield rec_d
                drained_through = max(wid, drained_through)
                drained_global = drained_global or wid == -1
                if checkpoint_path:
                    save(drained_through, drained_global, summary)
        # hot-loop-end
    except GeneratorExit:
        # consumer closed (JobManager.cancel / Job.close / an abandoned
        # run): yielding is illegal here, but the completion queue still
        # holds in-flight windows whose arenas are owned by dispatched
        # folds.  Run them through the NORMAL drain path — drain_one waits
        # on each window's emission (proving its fold consumed the arena's
        # host memory) and releases the arenas — discarding the records, so
        # a mid-flight cancel recycles every arena without corrupting one a
        # zero-copy transfer still reads.
        while pending:
            drain_one()
        raise
    except BaseException:
        # deliver windows whose folds already dispatched (the sync loop
        # emitted them before reaching the failure), then propagate
        while pending:
            wid, rec_d, summary = drain_one()
            yield rec_d
            drained_through = max(wid, drained_through)
            drained_global = drained_global or wid == -1
            if checkpoint_path:
                save(drained_through, drained_global, summary)
        raise
    while pending:
        wid, rec_d, summary = drain_one()
        yield rec_d
        drained_through = max(wid, drained_through)
        drained_global = drained_global or wid == -1
        if checkpoint_path:
            save(drained_through, drained_global, summary)

"""Host-side window discretization: the time plane of the framework.

The reference delegates windowing to Flink (``timeWindow`` over event/ingestion
time, SimpleEdgeStream.java:135-167; every aggregation is windowed,
SummaryBulkAggregation.java:79-81).  In the TPU design the *host owns time*
(SURVEY.md §7): sources attach timestamps, this module assigns edges to tumbling
panes and flushes a pane when the (ascending) watermark passes its end — the
device only ever sees fixed-shape pane micro-batches.

Timestamps are assumed ascending, mirroring the reference's event-time ctor
with an ``AscendingTimestampExtractor`` (SimpleEdgeStream.java:86-90).  Streams
without timestamps form a single global pane flushed at end-of-stream (the
finite-test analog of "one ingestion-time window", e.g. TestSlice's 1s window
over a 7-edge collection).
"""

from __future__ import annotations

from typing import Iterator, NamedTuple, Optional, Tuple

import numpy as np

from gelly_streaming_tpu.core.types import EdgeBatch


class WindowPane(NamedTuple):
    """A closed tumbling window's edges, materialized as host arrays."""

    window_id: int
    max_timestamp: int  # inclusive window end (end_ms - 1); -1 for global pane
    src: np.ndarray
    dst: np.ndarray
    val: Optional[object]  # np array or pytree of np arrays, aligned with src
    time: Optional[np.ndarray]

    @property
    def num_edges(self) -> int:
        return int(self.src.shape[0])


def _batch_to_host(batch: EdgeBatch):
    mask = np.asarray(batch.mask)
    idx = np.nonzero(mask)[0]
    src = np.asarray(batch.src)[idx]
    dst = np.asarray(batch.dst)[idx]
    val = None
    if batch.val is not None:
        import jax

        val = jax.tree.map(lambda a: np.asarray(a)[idx], batch.val)
    time = None if batch.time is None else np.asarray(batch.time)[idx]
    return src, dst, val, time


class PaneAssembler:
    """Accumulates per-window edge parts and assembles closed panes.

    Shared by the single-host assigner below and the multi-host gated
    assigners (parallel/multihost.py) so pane assembly semantics cannot
    diverge between the paths.
    """

    def __init__(self, window_ms: int, val_proto=None, has_time: bool = False):
        """``val_proto``/``has_time`` declare the stream's record structure up
        front (a pytree of zero-length arrays).  Pass them in multi-host runs:
        with inference only, a host closing an empty share before its first
        val-carrying batch would return val=None while peers return zero-length
        pytrees, breaking positional share pairing."""
        self.window_ms = window_ms
        self._open = {}  # window_id -> list of (src, dst, val, time)
        # declared or inferred stream structure for shape-compatible empties
        self._val_proto = val_proto  # pytree of zero-length arrays, or None
        self._has_time = has_time

    def _remember_structure(self, val, time) -> None:
        if val is not None and self._val_proto is None:
            import jax

            self._val_proto = jax.tree.map(lambda a: a[:0], val)
        self._has_time = self._has_time or time is not None

    def add(self, src, dst, val, time, wids) -> None:
        import jax

        self._remember_structure(val, time)
        for wid in np.unique(wids):
            sel = wids == wid
            self._open.setdefault(int(wid), []).append(
                (
                    src[sel],
                    dst[sel],
                    None if val is None else jax.tree.map(lambda a: a[sel], val),
                    None if time is None else time[sel],
                )
            )

    def add_untimed(self, src, dst, val) -> None:
        """Single global pane (ingestion-time finite stream)."""
        self._remember_structure(val, None)
        self._open.setdefault(-1, []).append((src, dst, val, None))

    def open_ids(self):
        return sorted(self._open)

    def close(self, wid: int) -> WindowPane:
        """Assemble pane ``wid``; an id with no edges yields an empty share
        whose val/time carry the stream's structure (zero-length arrays), so
        cross-host positional pairing of shares never mixes None with pytrees.
        """
        max_ts = (wid + 1) * self.window_ms - 1 if wid >= 0 else -1
        parts = self._open.pop(wid, None)
        if parts is None:
            empty = np.empty((0,), np.int32)
            return WindowPane(
                wid,
                max_ts,
                empty,
                empty.copy(),
                self._val_proto,
                np.empty((0,), np.int64) if self._has_time else None,
            )
        src = np.concatenate([p[0] for p in parts])
        dst = np.concatenate([p[1] for p in parts])
        val = None
        if parts[0][2] is not None:
            import jax

            val = jax.tree.map(
                lambda *leaves: np.concatenate(leaves), *[p[2] for p in parts]
            )
        time = (
            None if parts[0][3] is None else np.concatenate([p[3] for p in parts])
        )
        return WindowPane(wid, max_ts, src, dst, val, time)


def assign_tumbling_windows(
    batches: Iterator[EdgeBatch],
    window_ms: int,
    out_of_orderness_ms: int = 0,
    late_sink=None,
) -> Iterator[WindowPane]:
    """Group a timed batch stream into closed tumbling panes.

    With the default ``out_of_orderness_ms=0`` timestamps are assumed
    ascending (the reference's AscendingTimestampExtractor contract,
    SimpleEdgeStream.java:86-90).  A positive bound is the
    BoundedOutOfOrderness watermark Flink offers one call below the
    reference: the watermark trails the max seen timestamp by the bound,
    window ``w`` closes only once the watermark passes its end, and records
    later than the bound — whose window already closed — go to
    ``late_sink(src, dst, val, time)`` (dropped when None) instead of
    corrupting closed panes.  Pane emission stays ascending either way,
    which downstream sliding_panes relies on.
    """
    panes = PaneAssembler(window_ms)
    watermark = None  # max event time seen - bound

    for batch in batches:
        src, dst, val, time = _batch_to_host(batch)
        if len(src) == 0:
            continue
        if time is None:
            panes.add_untimed(src, dst, val)
            continue
        wids = time // window_ms
        if watermark is not None:
            # a record is late iff its window already fired: watermark has
            # passed the window's maxTimestamp (end - 1), Flink's trigger
            # boundary — see the close condition below
            late = (wids + 1) * window_ms - 1 <= watermark
            if late.any():
                if late_sink is not None:
                    import jax

                    sel = np.nonzero(late)[0]
                    late_sink(
                        src[sel],
                        dst[sel],
                        None
                        if val is None
                        else jax.tree.map(lambda a: a[sel], val),
                        time[sel],
                    )
                keep = ~late
                src, dst = src[keep], dst[keep]
                time, wids = time[keep], wids[keep]
                if val is not None:
                    import jax

                    val = jax.tree.map(lambda a: a[keep], val)
                if len(src) == 0:
                    continue
        panes.add(src, dst, val, time, wids)
        new_watermark = int(time.max()) - out_of_orderness_ms
        if watermark is None or new_watermark > watermark:
            watermark = new_watermark
            # fire at watermark >= maxTimestamp = end - 1 (Flink's
            # TumblingEventTimeWindows trigger boundary): a window whose
            # last possible record sits exactly at maxTimestamp closes the
            # tick the watermark reaches it, not one tick later
            for wid in [
                w
                for w in panes.open_ids()
                if 0 <= w and (w + 1) * window_ms - 1 <= watermark
            ]:
                yield panes.close(wid)

    for wid in panes.open_ids():
        yield panes.close(wid)


def assign_ingestion_windows(
    batches: Iterator[EdgeBatch],
    every_edges: int = 0,
    every_ms: int = 0,
    clock=None,
) -> Iterator[WindowPane]:
    """Tumbling panes for UNTIMED streams: the reference's default
    ingestion-time mode (SimpleEdgeStream.java:69-73; running emission per
    window, ConnectedComponentsExample.java:65-67).

    ``every_edges`` cuts a pane every N arrivals — deterministic, the right
    choice for tests and replayable streams.  ``every_ms`` cuts by
    wall-clock at BATCH boundaries (the host assigns each batch to the pane
    open at its arrival instant; a pane closes when a later batch arrives
    past its end — the un-timered approximation of Flink's processing-time
    triggers).  Any timestamps the batches carry are ignored: callers route
    timed streams to ``assign_tumbling_windows`` (event time precedes
    ingestion time, as in the reference's two ctors).

    Panes carry synthetic ascending window ids (0, 1, ...) and
    ``max_timestamp=-1`` (no event-time meaning), so the Merger's running
    merge works unchanged.  Positional checkpoints are sound only for
    ``every_edges`` (a replayed stream cuts the same panes); wall-clock
    panes are NOT replay-deterministic — a resume could skip edges the
    crashed run never folded — so checkpointed runs refuse ``every_ms``
    (enforced in SummaryAggregation.run / BlockShardedCC.run).
    """
    import time as _time

    if bool(every_edges) == bool(every_ms):
        raise ValueError("set exactly one of every_edges / every_ms")
    clock = clock or _time.monotonic
    panes = PaneAssembler(0)  # window_ms=0 -> max_timestamp=-1 on close
    count = 0
    t0 = None

    for batch in batches:
        src, dst, val, _time_ignored = _batch_to_host(batch)
        if len(src) == 0:
            continue
        if every_edges:
            wids = (count + np.arange(len(src), dtype=np.int64)) // every_edges
            count += len(src)
        else:
            now = clock()
            if t0 is None:
                t0 = now
            wid = int((now - t0) * 1000.0 // every_ms)
            wids = np.full((len(src),), wid, np.int64)
        panes.add(src, dst, val, None, wids)
        newest = int(wids.max())
        for wid in [w for w in panes.open_ids() if 0 <= w < newest]:
            yield panes.close(wid)

    for wid in panes.open_ids():
        yield panes.close(wid)


def sliding_panes(
    panes: Iterator[WindowPane], k: int, slide_ms: int
) -> Iterator[WindowPane]:
    """Sliding windows by pane-sharing: merge each run of ``k`` consecutive
    ``slide_ms``-wide tumbling panes into one emitted window.

    Beyond the reference (its ``slice`` is tumbling-only,
    SimpleEdgeStream.java:135-167), matching the sliding ``timeWindow(size,
    slide)`` Flink exposes one call below it: window ``w`` covers panes
    ``[w-k+1, w]`` and is emitted when pane ``w`` closes (the upstream
    assigner only yields final panes, so every pane <= w is final by then).
    Early windows covering the stream's first panes are partial, windows
    with no edges do not fire, and the trailing ``k-1`` windows after the
    last pane flush at end-of-stream — all as in Flink's sliding trigger.
    Each edge appears in up to ``k`` emitted windows; memory is bounded by
    the ``k`` cached panes.  An untimed stream's single global pane
    (``window_id=-1``) passes through unchanged.
    """
    if k <= 1:
        yield from panes
        return
    import jax

    cache = {}  # pane id -> WindowPane (the k most recent)
    last = None  # newest window id emitted

    def emit(wid: int) -> Optional[WindowPane]:
        parts = [cache[i] for i in range(wid - k + 1, wid + 1) if i in cache]
        if not parts or all(p.num_edges == 0 for p in parts):
            return None
        timed = any(p.max_timestamp >= 0 for p in parts)
        src = np.concatenate([p.src for p in parts])
        dst = np.concatenate([p.dst for p in parts])
        val = None
        if parts[0].val is not None:
            val = jax.tree.map(
                lambda *leaves: np.concatenate(leaves), *[p.val for p in parts]
            )
        time = (
            None
            if parts[0].time is None
            else np.concatenate([p.time for p in parts])
        )
        max_ts = (wid + 1) * slide_ms - 1 if timed else -1
        return WindowPane(wid, max_ts, src, dst, val, time)

    def evict(wid: int) -> None:
        for old in [i for i in cache if i <= wid + 1 - k]:
            del cache[old]

    for pane in panes:
        if pane.window_id < 0:  # untimed global pane: degenerate window
            yield pane
            continue
        w = pane.window_id
        cache[w] = pane
        # windows in (last+k-1, w) contain no cached pane (ids between last
        # and w never arrived), so a timestamp gap costs O(k) work, not
        # O(gap/slide) empty emit() calls
        if last is None:
            candidates = [w]
        else:
            candidates = [*range(last + 1, min(last + k, w)), w]
        for wid in candidates:
            out = emit(wid)
            if out is not None:
                yield out
            evict(wid)
        last = w

    if last is not None:
        for wid in range(last + 1, last + k):
            if not cache:
                break
            out = emit(wid)
            if out is not None:
                yield out
            evict(wid)


class SuperPane(NamedTuple):
    """Up to K consecutive closed panes coalesced for ONE device dispatch.

    Pane boundaries are preserved via PER-EDGE window ids (``wid``), not
    separate dispatches: a consumer folds the concatenated edge run once and
    recovers each window's contribution by masking ``wid == window_ids[k]``.
    Arrays are padded to a power-of-two bucket so successive superpanes hit
    a small set of compiled shapes (mask False marks padding; padded ``wid``
    rows carry -2, which is never a real window id — real ids are >= -1).
    """

    panes: Tuple[WindowPane, ...]  # constituents, ascending window order
    src: np.ndarray  # [E_pad] int32
    dst: np.ndarray  # [E_pad] int32
    val: Optional[object]  # pytree of [E_pad, ...] arrays, or None
    wid: np.ndarray  # [E_pad] int32 per-edge window id (-2 on padding)
    mask: np.ndarray  # [E_pad] bool
    window_ids: np.ndarray  # [k] int32, the panes' window ids


def _assemble_superpane(panes) -> SuperPane:
    import jax

    # window ids ride int32 device columns (the framework's time plane is
    # int32 ms end to end — EdgeBatch refuses epoch-scale timestamps, so
    # event-time ids always fit); fail loudly rather than wrap if a pathological
    # ingestion-time stream ever outruns the range
    if any(not (-2 < p.window_id <= np.iinfo(np.int32).max) for p in panes):
        raise ValueError(
            "superbatch window ids must fit int32 (rebase event timestamps "
            "to stream-relative ms, as EdgeBatch requires)"
        )
    e = sum(p.num_edges for p in panes)
    e_pad = max(1, 1 << (e - 1).bit_length()) if e else 1
    src = np.zeros((e_pad,), np.int32)
    dst = np.zeros((e_pad,), np.int32)
    wid = np.full((e_pad,), -2, np.int32)
    mask = np.zeros((e_pad,), bool)
    o = 0
    for p in panes:
        n = p.num_edges
        src[o : o + n] = p.src
        dst[o : o + n] = p.dst
        wid[o : o + n] = p.window_id
        mask[o : o + n] = True
        o += n
    val = None
    if any(p.val is not None for p in panes):

        def cat(*leaves):
            flat = np.concatenate(leaves)
            out = np.zeros((e_pad,) + flat.shape[1:], flat.dtype)
            out[: len(flat)] = flat
            return out

        val = jax.tree.map(cat, *[p.val for p in panes])
    return SuperPane(
        panes=tuple(panes),
        src=src,
        dst=dst,
        val=val,
        wid=wid,
        mask=mask,
        window_ids=np.array([p.window_id for p in panes], np.int32),
    )


def group_panes(panes: Iterator[WindowPane], k: int, keep_empty: bool = False):
    """Groups of up to ``k`` consecutive closed panes (as lists).

    The grouping primitive under superbatch dispatch: consumers that build
    their own device layout (the aggregation fold's [K, E] per-window rows,
    the triangles vmapped counter) iterate this directly and pay NO
    assembly copy; ``coalesce_panes`` below materializes the flat SuperPane
    view on top of it.  Panes with no edges are dropped by default (the
    per-pane aggregation consumers skip them the same way); consumers that
    emit a record per pane regardless (window triangles) pass
    ``keep_empty=True``.
    """
    k = max(1, k)
    buf = []
    for pane in panes:
        if pane.num_edges == 0 and not keep_empty:
            continue
        buf.append(pane)
        if len(buf) == k:
            yield buf
            buf = []
    if buf:
        yield buf


def coalesce_panes(panes: Iterator[WindowPane], k: int) -> Iterator[SuperPane]:
    """Group up to ``k`` consecutive non-empty closed panes into SuperPanes.

    The superbatch form of the time plane: per-dispatch overhead amortizes
    over ``k`` windows while window identity rides the per-edge ``wid``
    column (pane boundaries as data, not dispatches); ``k <= 1``
    degenerates to one pane per superpane.
    """
    for group in group_panes(panes, k):
        yield _assemble_superpane(group)


def pad_pane_edges(pane: WindowPane):
    """(src, dst, mask) int32/bool arrays padded to the next power of two —
    the shared pane->fixed-shape policy for per-pane device kernels
    (PageRank, SSSP), so successive similar panes reuse compiled steps."""
    e = pane.num_edges
    e_pad = max(1, 1 << (e - 1).bit_length())
    src = np.zeros((e_pad,), np.int32)
    dst = np.zeros((e_pad,), np.int32)
    msk = np.zeros((e_pad,), bool)
    src[:e], dst[:e], msk[:e] = pane.src, pane.dst, True
    return src, dst, msk


class FoldRequest(NamedTuple):
    """One job's parked window fold, offered to a cross-tenant cohort.

    The fused-dispatch handshake record (core/aggregation.py
    ``_fused_pane_records`` yields these; runtime/manager.py collects them):
    ``key`` identifies the shared executable + padded shape, so requests with
    equal keys from different jobs can stack into one vmapped mega-fold.
    The arrays are already pow2-padded host arrays of length ``e_pad`` —
    exactly the per-row layout of the superbatch plane — and ``fold`` is the
    process-global cached executable (one per key, not per job).  A consumer
    that does not understand the protocol simply ``next()``s past the yield,
    which the generator treats as "no fused partial" and solo-folds: the
    bit-exact fallback oracle.
    """

    key: tuple  # (cache_token, cfg, has_val, e_pad) — cohort compatibility
    fold: object  # the shared superpane fold executable (compile_cache entry)
    split: object  # rows -> the shared cohort-drain executable (one dispatch
    #   slices the stacked result into per-row partials; eager per-row
    #   slicing would cost one device call per job and undo the amortization)
    src: np.ndarray  # int32 [e_pad]
    dst: np.ndarray  # int32 [e_pad]
    val: Optional[object]  # pytree of [e_pad]-padded arrays, or None
    mask: np.ndarray  # bool [e_pad]; True on the first ``edges`` slots
    window_id: int
    edges: int  # true (unpadded) edge count


def stack_fold_rows(requests):
    """Stack N same-key FoldRequests into the [rows, e_pad] superpane layout.

    ``rows`` is pow2-bucketed over the cohort size so varying tenancy
    (1..16 jobs per dispatch) reuses one compiled executable; padding rows
    are all-masked-out zeros, which every SummaryAggregation update ignores
    by contract.  Returns ``(src, dst, val, mask, pad_rows)`` host arrays
    ready for the shared superpane fold.
    """
    n = len(requests)
    e_pad = requests[0].src.shape[0]
    rows = max(1, 1 << (n - 1).bit_length())
    src = np.zeros((rows, e_pad), np.int32)
    dst = np.zeros((rows, e_pad), np.int32)
    msk = np.zeros((rows, e_pad), bool)
    for i, req in enumerate(requests):
        src[i], dst[i], msk[i] = req.src, req.dst, req.mask
    val = None
    if requests[0].val is not None:
        import jax

        def _stack(*leaves):
            out = np.zeros((rows,) + leaves[0].shape, leaves[0].dtype)
            for i, leaf in enumerate(leaves):
                out[i] = leaf
            return out

        val = jax.tree.map(_stack, *[req.val for req in requests])
    return src, dst, val, msk, rows - n


def validate_slide(window_ms: int, slide_ms: Optional[int]) -> None:
    """Eager check of a sliding-window spec (shared by every slide entry
    point so the contract cannot diverge)."""
    if slide_ms is None:
        return
    if not 0 < slide_ms <= window_ms:
        raise ValueError(f"slide_ms must be in (0, window_ms]; got {slide_ms}")
    if window_ms % slide_ms:
        raise ValueError(
            "window_ms must be a multiple of slide_ms for pane-shared "
            f"sliding windows; got {window_ms} % {slide_ms}"
        )


def windowed_panes(
    stream, window_ms: int, slide_ms: Optional[int] = None
) -> Iterator[WindowPane]:
    """Validated window-pane source: tumbling panes, or pane-shared sliding
    windows when ``slide_ms`` (a divisor of ``window_ms``) is given.  The
    single dispatch point for slice() and window_triangles."""
    validate_slide(window_ms, slide_ms)
    if slide_ms and slide_ms != window_ms:
        cfg = stream.cfg
        if cfg.ingest_window_edges or cfg.ingest_window_ms:
            # ingestion-mode panes are cut by arrival count/wall clock, not
            # by slide_ms — a k derived from time knobs would be a lie
            raise ValueError(
                "sliding windows apply to event-time slices; this stream "
                "cuts ingestion-time panes (ingest_window_edges/_ms)"
            )
        return sliding_panes(
            stream_panes(stream, slide_ms), window_ms // slide_ms, slide_ms
        )
    return stream_panes(stream, window_ms)


def _array_backed_panes(
    src: np.ndarray, dst: np.ndarray, every_edges: int
) -> Iterator[WindowPane]:
    """Count-cut ingestion panes sliced straight off an array-backed
    stream's host arrays.

    Pane-content-identical to routing the stream's padded micro-batches
    through ``assign_ingestion_windows``: ``EdgeStream.from_arrays``
    chunks the SAME arrays contiguously (only the final chunk carries
    masked padding, which ``_batch_to_host`` drops), so count cuts land on
    the same edges in the same order — minus the per-batch device
    EdgeBatch construction and mask readback, which dominated the windowed
    plane's host time for array sources.  Array-backed streams are untimed
    and value-less by construction, so panes carry ``max_timestamp=-1``
    and ``val=time=None``.  Yields VIEWS of the caller's arrays — the same
    backing-store contract the packed-wire path already has."""
    n = len(src)
    for wid in range((n + every_edges - 1) // every_edges):
        lo = wid * every_edges
        yield WindowPane(
            wid,
            -1,
            src[lo : lo + every_edges],
            dst[lo : lo + every_edges],
            None,
            None,
        )


def stream_panes(stream, window_ms: int) -> Iterator[WindowPane]:
    """The pane source for an aggregation over ``stream``: ingestion-time
    panes when the config asks for them, else event-time tumbling windows
    (untimed streams degrade to the single global pane there).  Shared by
    the simulated runtime, the mesh runner, and BlockShardedCC so the time
    plane cannot diverge between execution paths."""
    cfg = stream.cfg
    if cfg.ingest_window_edges or cfg.ingest_window_ms:
        arrays = getattr(stream, "_wire_arrays", None)
        if (
            cfg.ingest_window_edges
            and arrays is not None
            and not getattr(stream, "_stages", ())
        ):
            # count-cut panes over an untransformed array-backed stream
            # slice straight off the backing host arrays: the micro-batch
            # route chunks those same arrays, round-trips each chunk
            # through a device EdgeBatch, and reads it back — identical
            # pane content, one device round trip per batch more expensive
            return _array_backed_panes(
                arrays[0], arrays[1], cfg.ingest_window_edges
            )
        return assign_ingestion_windows(
            stream.batches(),
            cfg.ingest_window_edges,
            cfg.ingest_window_ms,
        )
    return assign_tumbling_windows(
        stream.batches(),
        window_ms,
        out_of_orderness_ms=cfg.out_of_orderness_ms,
        late_sink=getattr(stream, "late_sink", None),
    )

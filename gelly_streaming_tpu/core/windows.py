"""Host-side window discretization: the time plane of the framework.

The reference delegates windowing to Flink (``timeWindow`` over event/ingestion
time, SimpleEdgeStream.java:135-167; every aggregation is windowed,
SummaryBulkAggregation.java:79-81).  In the TPU design the *host owns time*
(SURVEY.md §7): sources attach timestamps, this module assigns edges to tumbling
panes and flushes a pane when the (ascending) watermark passes its end — the
device only ever sees fixed-shape pane micro-batches.

Timestamps are assumed ascending, mirroring the reference's event-time ctor
with an ``AscendingTimestampExtractor`` (SimpleEdgeStream.java:86-90).  Streams
without timestamps form a single global pane flushed at end-of-stream (the
finite-test analog of "one ingestion-time window", e.g. TestSlice's 1s window
over a 7-edge collection).
"""

from __future__ import annotations

from typing import Iterator, List, NamedTuple, Optional

import numpy as np

from gelly_streaming_tpu.core.types import EdgeBatch


class WindowPane(NamedTuple):
    """A closed tumbling window's edges, materialized as host arrays."""

    window_id: int
    max_timestamp: int  # inclusive window end (end_ms - 1); -1 for global pane
    src: np.ndarray
    dst: np.ndarray
    val: Optional[object]  # np array or pytree of np arrays, aligned with src
    time: Optional[np.ndarray]

    @property
    def num_edges(self) -> int:
        return int(self.src.shape[0])


def _batch_to_host(batch: EdgeBatch):
    mask = np.asarray(batch.mask)
    idx = np.nonzero(mask)[0]
    src = np.asarray(batch.src)[idx]
    dst = np.asarray(batch.dst)[idx]
    val = None
    if batch.val is not None:
        import jax

        val = jax.tree.map(lambda a: np.asarray(a)[idx], batch.val)
    time = None if batch.time is None else np.asarray(batch.time)[idx]
    return src, dst, val, time


class PaneAssembler:
    """Accumulates per-window edge parts and assembles closed panes.

    Shared by the single-host assigner below and the multi-host gated
    assigners (parallel/multihost.py) so pane assembly semantics cannot
    diverge between the paths.
    """

    def __init__(self, window_ms: int, val_proto=None, has_time: bool = False):
        """``val_proto``/``has_time`` declare the stream's record structure up
        front (a pytree of zero-length arrays).  Pass them in multi-host runs:
        with inference only, a host closing an empty share before its first
        val-carrying batch would return val=None while peers return zero-length
        pytrees, breaking positional share pairing."""
        self.window_ms = window_ms
        self._open = {}  # window_id -> list of (src, dst, val, time)
        # declared or inferred stream structure for shape-compatible empties
        self._val_proto = val_proto  # pytree of zero-length arrays, or None
        self._has_time = has_time

    def _remember_structure(self, val, time) -> None:
        if val is not None and self._val_proto is None:
            import jax

            self._val_proto = jax.tree.map(lambda a: a[:0], val)
        self._has_time = self._has_time or time is not None

    def add(self, src, dst, val, time, wids) -> None:
        import jax

        self._remember_structure(val, time)
        for wid in np.unique(wids):
            sel = wids == wid
            self._open.setdefault(int(wid), []).append(
                (
                    src[sel],
                    dst[sel],
                    None if val is None else jax.tree.map(lambda a: a[sel], val),
                    None if time is None else time[sel],
                )
            )

    def add_untimed(self, src, dst, val) -> None:
        """Single global pane (ingestion-time finite stream)."""
        self._remember_structure(val, None)
        self._open.setdefault(-1, []).append((src, dst, val, None))

    def open_ids(self):
        return sorted(self._open)

    def close(self, wid: int) -> WindowPane:
        """Assemble pane ``wid``; an id with no edges yields an empty share
        whose val/time carry the stream's structure (zero-length arrays), so
        cross-host positional pairing of shares never mixes None with pytrees.
        """
        max_ts = (wid + 1) * self.window_ms - 1 if wid >= 0 else -1
        parts = self._open.pop(wid, None)
        if parts is None:
            empty = np.empty((0,), np.int32)
            return WindowPane(
                wid,
                max_ts,
                empty,
                empty.copy(),
                self._val_proto,
                np.empty((0,), np.int64) if self._has_time else None,
            )
        src = np.concatenate([p[0] for p in parts])
        dst = np.concatenate([p[1] for p in parts])
        val = None
        if parts[0][2] is not None:
            import jax

            val = jax.tree.map(
                lambda *leaves: np.concatenate(leaves), *[p[2] for p in parts]
            )
        time = (
            None if parts[0][3] is None else np.concatenate([p[3] for p in parts])
        )
        return WindowPane(wid, max_ts, src, dst, val, time)


def assign_tumbling_windows(
    batches: Iterator[EdgeBatch], window_ms: int
) -> Iterator[WindowPane]:
    """Group an (ascending-time) batch stream into closed tumbling panes."""
    panes = PaneAssembler(window_ms)
    watermark_id = -1

    for batch in batches:
        src, dst, val, time = _batch_to_host(batch)
        if len(src) == 0:
            continue
        if time is None:
            panes.add_untimed(src, dst, val)
            continue
        wids = time // window_ms
        panes.add(src, dst, val, time, wids)
        new_watermark = int(wids.max())
        if new_watermark > watermark_id:
            for wid in [w for w in panes.open_ids() if 0 <= w < new_watermark]:
                yield panes.close(wid)
            watermark_id = new_watermark

    for wid in panes.open_ids():
        yield panes.close(wid)


def assign_ingestion_windows(
    batches: Iterator[EdgeBatch],
    every_edges: int = 0,
    every_ms: int = 0,
    clock=None,
) -> Iterator[WindowPane]:
    """Tumbling panes for UNTIMED streams: the reference's default
    ingestion-time mode (SimpleEdgeStream.java:69-73; running emission per
    window, ConnectedComponentsExample.java:65-67).

    ``every_edges`` cuts a pane every N arrivals — deterministic, the right
    choice for tests and replayable streams.  ``every_ms`` cuts by
    wall-clock at BATCH boundaries (the host assigns each batch to the pane
    open at its arrival instant; a pane closes when a later batch arrives
    past its end — the un-timered approximation of Flink's processing-time
    triggers).  Any timestamps the batches carry are ignored: callers route
    timed streams to ``assign_tumbling_windows`` (event time precedes
    ingestion time, as in the reference's two ctors).

    Panes carry synthetic ascending window ids (0, 1, ...) and
    ``max_timestamp=-1`` (no event-time meaning), so the Merger's running
    merge works unchanged.  Positional checkpoints are sound only for
    ``every_edges`` (a replayed stream cuts the same panes); wall-clock
    panes are NOT replay-deterministic — a resume could skip edges the
    crashed run never folded — so checkpointed runs refuse ``every_ms``
    (enforced in SummaryAggregation.run / BlockShardedCC.run).
    """
    import time as _time

    if bool(every_edges) == bool(every_ms):
        raise ValueError("set exactly one of every_edges / every_ms")
    clock = clock or _time.monotonic
    panes = PaneAssembler(0)  # window_ms=0 -> max_timestamp=-1 on close
    count = 0
    t0 = None

    for batch in batches:
        src, dst, val, _time_ignored = _batch_to_host(batch)
        if len(src) == 0:
            continue
        if every_edges:
            wids = (count + np.arange(len(src), dtype=np.int64)) // every_edges
            count += len(src)
        else:
            now = clock()
            if t0 is None:
                t0 = now
            wid = int((now - t0) * 1000.0 // every_ms)
            wids = np.full((len(src),), wid, np.int64)
        panes.add(src, dst, val, None, wids)
        newest = int(wids.max())
        for wid in [w for w in panes.open_ids() if 0 <= w < newest]:
            yield panes.close(wid)

    for wid in panes.open_ids():
        yield panes.close(wid)


def stream_panes(stream, window_ms: int) -> Iterator[WindowPane]:
    """The pane source for an aggregation over ``stream``: ingestion-time
    panes when the config asks for them, else event-time tumbling windows
    (untimed streams degrade to the single global pane there).  Shared by
    the simulated runtime, the mesh runner, and BlockShardedCC so the time
    plane cannot diverge between execution paths."""
    cfg = stream.cfg
    if cfg.ingest_window_edges or cfg.ingest_window_ms:
        return assign_ingestion_windows(
            stream.batches(),
            cfg.ingest_window_edges,
            cfg.ingest_window_ms,
        )
    return assign_tumbling_windows(stream.batches(), window_ms)

"""The aggregation runtime: windowed partial-fold + combine + running merge.

Reference: SummaryAggregation.java (descriptor: updateFun :31, combineFun :36,
transform :41, initialValue :43, transientState :48; the singleton Merger
final-combiner :93-119 with ListCheckpointed state :127-135) and its two
execution strategies SummaryBulkAggregation.java:68-90 (per-partition windowed
fold -> flat all-window combine) and SummaryTreeReduce.java:95-123 (log-depth
pairwise combine tree).

TPU-native form: a "partition" is a shard of the window pane; the per-partition
fold is a batched state-update kernel; the flat combine is a left fold over
partials; the tree combine is pairwise rounds (halving, mirroring enhance()'s
``partition/2`` re-keying).  The running summary (Merger state) is a pytree of
arrays — checkpointable by construction, closing the reference's gap where most
operator state is not checkpointed (SURVEY.md §5.3-4).
"""

from __future__ import annotations

import os
from typing import Any, Callable, Iterator, Optional

import jax
import jax.numpy as jnp
import numpy as np

from gelly_streaming_tpu.core.config import StreamConfig
from gelly_streaming_tpu.core.output import OutputStream
from gelly_streaming_tpu.core.windows import WindowPane, assign_tumbling_windows


class SummaryAggregation:
    """Abstract aggregation descriptor (SummaryAggregation.java:22-48).

    Subclasses define:
      initial_state(cfg) -> S          (initialValue :43; pytree of arrays)
      update(state, src, dst, val, mask) -> S   (updateFun :31 — folds an edge
                                        micro-batch into the partial state)
      combine(a, b) -> S               (combineFun :36 — merge partials)
      transform(state) -> T            (transform :41 — S to emitted record)
    ``transient_state`` resets the running summary after each emission
    (SummaryAggregation.java:113-115).
    """

    transient_state: bool = False

    def __init__(self, window_ms: Optional[int] = None):
        self.window_ms = window_ms

    # -- descriptor hooks -----------------------------------------------------

    def initial_state(self, cfg: StreamConfig):
        raise NotImplementedError

    def update(self, state, src, dst, val, mask):
        raise NotImplementedError

    def combine(self, a, b):
        raise NotImplementedError

    def transform(self, state):
        return state

    # -- execution ------------------------------------------------------------

    def _num_partitions(self, cfg: StreamConfig) -> int:
        return cfg.num_shards

    def _combine_partials(self, partials):
        """Flat left-fold combine (timeWindowAll.reduce analog,
        SummaryBulkAggregation.java:81-83).  Overridden by the tree strategy."""
        acc = partials[0]
        for p in partials[1:]:
            acc = self._combine_j(acc, p)
        return acc

    @property
    def _update_j(self):
        if not hasattr(self, "_update_cache"):
            self._update_cache = jax.jit(self.update)
        return self._update_cache

    @property
    def _combine_j(self):
        if not hasattr(self, "_combine_cache"):
            self._combine_cache = jax.jit(self.combine)
        return self._combine_cache

    def run(
        self,
        stream,
        checkpoint_path: Optional[str] = None,
        restore: bool = True,
    ) -> OutputStream:
        """Execute over an EdgeStream (entered via GraphStream.aggregate,
        GraphStream.java:139-140 / SimpleEdgeStream.java:100-102).

        With ``checkpoint_path``, the running summary is snapshot after every
        window close and restored on start — the Merger's ListCheckpointed
        behavior (SummaryAggregation.java:127-135), generalized to the whole
        summary pytree (closing the reference's unsaved-state gap)."""
        cfg = stream.cfg
        window_ms = self.window_ms or cfg.window_ms
        n_parts = self._num_partitions(cfg)

        def records() -> Iterator[tuple]:
            running = None
            if checkpoint_path and restore:
                from gelly_streaming_tpu.utils.checkpoint import (
                    checkpoint_exists,
                    load_state,
                )

                if checkpoint_exists(checkpoint_path):
                    running = load_state(checkpoint_path, self.initial_state(cfg))
            for pane in assign_tumbling_windows(stream.batches(), window_ms):
                partials = []
                for part in range(n_parts):
                    # Round-robin partitioning of the pane stands in for the
                    # reference's source-subtask tagging (PartitionMapper,
                    # SummaryBulkAggregation.java:93-106).
                    sel = np.arange(len(pane.src)) % n_parts == part
                    if not sel.any():
                        continue
                    # Pad to the next power of two so varying pane sizes hit a
                    # small, bounded set of compiled kernel shapes.
                    n = int(sel.sum())
                    padded = max(1, 1 << (n - 1).bit_length())
                    mask = np.zeros((padded,), bool)
                    mask[:n] = True

                    def pad(a, fill=0):
                        out = np.full((padded,) + a.shape[1:], fill, a.dtype)
                        out[:n] = a[sel]
                        return out

                    state = self.initial_state(cfg)
                    state = self._update_j(
                        state,
                        jnp.asarray(pad(pane.src), jnp.int32),
                        jnp.asarray(pad(pane.dst), jnp.int32),
                        None
                        if pane.val is None
                        else jax.tree.map(lambda a: jnp.asarray(pad(a)), pane.val),
                        jnp.asarray(mask),
                    )
                    partials.append(state)
                if not partials:
                    continue
                pane_summary = self._combine_partials(partials)
                # Merger: non-blocking running merge, one emission per window
                # close (SummaryAggregation.java:107-119).
                if running is None or self.transient_state:
                    running = pane_summary
                else:
                    running = self._combine_j(running, pane_summary)
                out = self.transform(running)
                if checkpoint_path:
                    from gelly_streaming_tpu.utils.checkpoint import save_state

                    save_state(checkpoint_path, running)
                yield out if isinstance(out, tuple) else (out,)
                if self.transient_state:
                    running = None

        return OutputStream(records)


class SummaryBulkAggregation(SummaryAggregation):
    """Flat combine strategy (SummaryBulkAggregation.java:51-90)."""


class SummaryTreeAggregation(SummaryAggregation):
    """Log-depth pairwise combine (SummaryTreeReduce.java:47-123): partials are
    merged in halving rounds (key = partition/2) instead of one flat fold —
    same fixed point for associative combines, fewer sequential merge steps."""

    def _combine_partials(self, partials):
        level = list(partials)
        while len(level) > 1:
            nxt = []
            for i in range(0, len(level) - 1, 2):
                nxt.append(self._combine_j(level[i], level[i + 1]))
            if len(level) % 2:
                nxt.append(level[-1])
            level = nxt
        return level[0]



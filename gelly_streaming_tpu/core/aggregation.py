"""The aggregation runtime: windowed partial-fold + combine + running merge.

Reference: SummaryAggregation.java (descriptor: updateFun :31, combineFun :36,
transform :41, initialValue :43, transientState :48; the singleton Merger
final-combiner :93-119 with ListCheckpointed state :127-135) and its two
execution strategies SummaryBulkAggregation.java:68-90 (per-partition windowed
fold -> flat all-window combine) and SummaryTreeReduce.java:95-123 (log-depth
pairwise combine tree).

TPU-native form: a "partition" is a shard of the window pane; the per-partition
fold is a batched state-update kernel; the flat combine is a left fold over
partials; the tree combine is pairwise rounds (halving, mirroring enhance()'s
``partition/2`` re-keying).  The running summary (Merger state) is a pytree of
arrays — checkpointable by construction, closing the reference's gap where most
operator state is not checkpointed (SURVEY.md §5.3-4).
"""

from __future__ import annotations

import functools
import os
import time
from typing import Any, Callable, Iterator, Optional

import jax
import jax.numpy as jnp
import numpy as np

from gelly_streaming_tpu.core import compile_cache
from gelly_streaming_tpu.core.config import StreamConfig
from gelly_streaming_tpu.core.output import OutputStream
from gelly_streaming_tpu.core.windows import (
    FoldRequest,
    WindowPane,
    stream_panes,
)
from gelly_streaming_tpu.utils import metrics, tracing


def _tree_copy_impl(tree):
    """On-device clone of a pytree.

    Outputs of a jit call never alias its (non-donated) inputs, so the clone
    stays valid after the caller donates the source to the next fold step —
    the invariant async snapshots rely on.
    """
    return jax.tree.map(jnp.copy, tree)


# one executable per pytree structure, shared process-wide and metered by
# the retrace guard (the structure is part of jit's own signature; the key
# names the kernel family)
_tree_copy = compile_cache.cached_jit(("tree_copy",), lambda: _tree_copy_impl)


def resolve_fused_dispatch(cfg: StreamConfig) -> bool:
    """Cross-tenant fused dispatch on/off: ``cfg.fused_dispatch`` forces,
    -1 defers to GELLY_FUSED_DISPATCH (default OFF — solo dispatch is the
    equivalence oracle, and fusing adds one superpane-executable compile
    that cold single-tenant paths should not pay)."""
    from gelly_streaming_tpu.utils.envswitch import resolve_switch

    return resolve_switch(cfg.fused_dispatch, "GELLY_FUSED_DISPATCH", False)


class SummaryAggregation:
    """Abstract aggregation descriptor (SummaryAggregation.java:22-48).

    Subclasses define:
      initial_state(cfg) -> S          (initialValue :43; pytree of arrays)
      update(state, src, dst, val, mask) -> S   (updateFun :31 — folds an edge
                                        micro-batch into the partial state)
      combine(a, b) -> S               (combineFun :36 — merge partials)
      transform(state) -> T            (transform :41 — S to emitted record)
    ``transient_state`` resets the running summary after each emission
    (SummaryAggregation.java:113-115).
    """

    transient_state: bool = False
    # Executable-cache identity.  The streaming kernels (update / combine /
    # the fused wire steps) are traced from bound methods, so by default
    # each descriptor INSTANCE owns its executables (``cache_token`` is the
    # instance).  Descriptors whose update/combine/initial_state are pure
    # functions of (class, cfg) — most library descriptors — override this
    # to the class, so re-created descriptors (a fresh
    # ``ConnectedComponents()`` per stream, window, or bench chunk) share
    # compiled executables instead of retracing.
    @property
    def cache_token(self):
        return self
    # True when transform(fold(edges)) is invariant under reordering edges
    # within (and across) micro-batches — e.g. union-find CC, parity
    # union-find bipartiteness.  Order-free descriptors may legally ride the
    # sorted EF40 multiset wire encoding (io/wire.py), which ships ~2x fewer
    # bytes per edge than the plain arrival-order pack.
    order_free: bool = False

    def __init__(self, window_ms: Optional[int] = None):
        self.window_ms = window_ms

    # -- descriptor hooks -----------------------------------------------------

    def initial_state(self, cfg: StreamConfig):
        raise NotImplementedError

    def update(self, state, src, dst, val, mask):
        raise NotImplementedError

    def combine(self, a, b):
        raise NotImplementedError

    def transform(self, state):
        return state

    def mesh_combine_states(self, cfg: StreamConfig, axis_name: str):
        """Optional COLLECTIVE cross-shard combine for the mesh data plane.

        Return a function ``(state, has_data) -> state`` that runs INSIDE
        shard_map over the mesh axis and reduces every shard's partial into
        the same (replicated-identical) combined state using XLA collectives
        (pmin/pmax/psum/ppermute riding ICI), or None to use the generic
        all_gather + sequential-combine fold.  ``has_data`` is this shard's
        "my bucket was non-empty" flag; descriptors whose initial state is a
        combine identity may ignore it.

        This is the TPU-native replacement for the reference's all-to-one
        ``timeWindowAll.reduce`` (SummaryBulkAggregation.java:81-83): instead
        of funneling S partials to one task and merging S-1 times
        sequentially, the combine is a logarithmic-depth collective over the
        mesh — the asymptotic win the sharded plane exists for.
        """
        return None

    def sharded_state_spec(self, cfg: StreamConfig):
        """Optional owner-sharded summary state protocol (ISSUE 4).

        Return a ``core.sharded_state.ShardedStateSpec`` to make O(C/S)
        owner blocks + delta-exchange reconciliation the descriptor's mesh
        streaming plane (the default path when supported and enabled —
        ``cfg.sharded_state`` / GELLY_SHARDED_STATE); None keeps the
        replicated combine above, which remains the equivalence oracle.
        """
        return None

    def state_nbytes(self, cfg: StreamConfig) -> int:
        """Summary-state footprint of one instance of this query (bytes).

        The admission-accounting entry point for the job runtime
        (runtime/manager.py): ``JobManager`` sums this over admitted jobs
        against ``RuntimeConfig.max_state_bytes``.  Computed via
        ``jax.eval_shape`` — abstract shapes only, nothing is allocated, so
        admission control itself cannot blow the budget it polices.
        """
        shapes = jax.eval_shape(lambda: self.initial_state(cfg))
        return int(
            sum(
                int(np.prod(leaf.shape)) * np.dtype(leaf.dtype).itemsize
                for leaf in jax.tree.leaves(shapes)
            )
        )

    def emission_scratch(self, cfg: StreamConfig):
        """Pytree of ``jax.ShapeDtypeStruct`` leaves describing the transient
        device buffers ``transform`` materializes at emission time beyond the
        summary itself (e.g. a sketch's gathered register view, a top-k
        heap, wedge-closure matrices).  Purely declarative — nothing is
        allocated; the default (no scratch) is right for descriptors whose
        transform is a view or O(1) reduction of the state.
        """
        return ()

    def aux_emission_nbytes(self, cfg: StreamConfig) -> int:
        """Bytes of ``emission_scratch`` — the emission-time residue that
        ``state_nbytes`` alone does not see."""
        return int(
            sum(
                int(np.prod(leaf.shape)) * np.dtype(leaf.dtype).itemsize
                for leaf in jax.tree.leaves(self.emission_scratch(cfg))
            )
        )

    def admission_nbytes(self, cfg: StreamConfig) -> int:
        """What admission control must charge for one instance of this query:
        the persistent summary PLUS the peak transient emission-time scratch.

        ``state_nbytes`` assumed the summary IS the job's whole device state;
        for sketch descriptors the emission-time buffers (top-k heap,
        gathered register view, wedge matrices) can dominate the KB-scale
        registers, so a thousand admitted jobs priced by registers alone
        could OOM on the unpriced residue.  runtime/manager.py and
        runtime/server.py charge THIS value against ``max_state_bytes``.
        """
        return self.state_nbytes(cfg) + self.aux_emission_nbytes(cfg)

    # -- execution ------------------------------------------------------------

    def _num_partitions(self, cfg: StreamConfig) -> int:
        return cfg.num_shards

    def _fold_partials(self, items, combine2, fanin: int = 2):
        """Combine-strategy hook over opaque items: flat left fold
        (timeWindowAll.reduce analog, SummaryBulkAggregation.java:81-83).
        Overridden by the tree strategy (which consumes ``fanin``).  Shared by
        the simulated runtime and the mesh runner so the strategies cannot
        diverge."""
        acc = items[0]
        for it in items[1:]:
            acc = combine2(acc, it)
        return acc

    def _tree_fanin(self, cfg: StreamConfig) -> int:
        """Combine-tree fan-in (SummaryTreeReduce's ``degree``, :53-64)."""
        return max(2, cfg.tree_degree)

    def _combine_partials(self, partials, cfg: StreamConfig):
        return self._fold_partials(partials, self._combine_j, self._tree_fanin(cfg))

    @property
    def _update_j(self):
        return compile_cache.cached_jit(
            ("agg_update", self.cache_token), lambda: self.update
        )

    @property
    def _combine_j(self):
        return compile_cache.cached_jit(
            ("agg_combine", self.cache_token), lambda: self.combine
        )

    # -- packed-wire fast path ------------------------------------------------
    #
    # The reference's aggregation pipeline runs *inside* the Flink runtime —
    # serialization, shuffle and windowing are the framework's own data plane
    # (SummaryBulkAggregation.java:76-83 over pom.xml:38-63 services).  The
    # equivalent here: when the source exposes packed-wire arrays (value-less,
    # untimed — EdgeStream.from_arrays / file_stream), `run()` streams packed
    # buffers through WirePrefetcher into ONE jitted fused step per micro-batch
    # (device-side unpack -> the stream's stages -> updateFun) with the whole
    # carry donated.  Untimed streams form a single global pane, and updateFun
    # is a fold over edges, so folding batch-by-batch into one running state is
    # exactly the single-partition pane fold of the simulated path.

    def _mesh_wire_eligible(self, stream) -> bool:
        """Wire-backed stream + a real mesh: the sharded STREAMING fold
        (MeshAggregationRunner.wire_records) — per micro-batch, packed
        per-shard rows fold into donated per-shard carries; one collective
        merge at stream end (VERDICT r3 weak #3: no per-pane re-fold)."""
        cfg = stream.cfg
        return (
            (
                getattr(stream, "_wire_arrays", None) is not None
                or getattr(stream, "_wire_packed", None) is not None
            )
            and cfg.num_shards > 1
            and cfg.num_shards <= len(jax.devices())
            # ingestion-time panes demand per-window emission; the streaming
            # fold emits once at stream end, so route to the windowed paths
            and not (cfg.ingest_window_edges or cfg.ingest_window_ms)
        )

    def _wire_emit_every(self, cfg: StreamConfig, batch: int) -> int:
        """Full batches per running emission on the wire fast path (0 = emit
        only at stream end).

        ``ingest_window_edges`` that divides the batch boundary keeps the
        stream ON the fast path: the donated fold carry IS the running
        merged summary (Merger semantics for non-transient descriptors), so
        emitting ``transform(carry)`` every K/batch batches reproduces the
        windowed path's running emission at full wire speed.  Non-aligned
        or transient configurations fall back to the windowed runtime.
        """
        k = cfg.ingest_window_edges
        if not k:
            return 0
        if k % batch or self.transient_state:
            return -1  # not fast-path representable
        return k // batch

    def _wire_eligible(self, stream) -> bool:
        cfg = stream.cfg
        if (
            getattr(stream, "_wire_arrays", None) is None
            and getattr(stream, "_wire_packed", None) is None
        ) or self._num_partitions(cfg) != 1:
            return False
        if cfg.ingest_window_ms:
            return False  # wall-clock panes need the windowed time plane
        packed = getattr(stream, "_wire_packed", None)
        batch = (
            packed[1] if packed is not None else stream._wire_arrays[2]
        )
        return self._wire_emit_every(cfg, batch) >= 0

    def _make_wire_tail(self, stages):
        """The shared (carry, src, dst, mask) -> carry fold tail: stream
        stages then updateFun, traced identically by the per-batch fused
        step, the padded-tail step, and the superbatch scan body."""
        from gelly_streaming_tpu.core.types import EdgeBatch

        def tail(carry, src, dst, mask):
            states, summary = carry
            b = EdgeBatch(src=src, dst=dst, mask=mask)
            out_states = []
            for stage, st in zip(stages, states):
                st, b = stage.apply(st, b)
                out_states.append(st)
            summary = self.update(summary, b.src, b.dst, b.val, b.mask)
            return (tuple(out_states), summary)

        return tail

    def _wire_fused_step(self, stream, batch: int, width):
        """Jitted (stage-states, summary), wire-buffer -> carry step.

        Executables live in the process-global compile cache keyed on
        (descriptor cache token, stages, cfg, batch, width) — so repeated
        runs, re-created streams, AND re-created descriptors with a
        class-level ``cache_token`` all share one compiled kernel.  Keys use
        the stages tuple itself (strong ref), not id(): an id can be reused
        after GC, silently resurrecting a kernel compiled for a DIFFERENT
        stream's stages (e.g. another filter predicate).
        """
        from gelly_streaming_tpu.io import wire

        token = self.cache_token
        stages = stream._stages
        key_tail = (stream._stages, stream.cfg, batch, str(width))

        def make_fused():
            tail = self._make_wire_tail(stages)

            def fused(carry, buf):
                s, d = wire.unpack_edges(buf, batch, width)
                return tail(carry, s, d, jnp.ones((batch,), bool))

            return fused

        return (
            compile_cache.cached_jit(
                ("wire_fused", token) + key_tail, make_fused, donate_argnums=0
            ),
            compile_cache.cached_jit(
                ("wire_tail", token, stages),
                lambda: self._make_wire_tail(stages),
                donate_argnums=0,
            ),
        )

    def _wire_scan_step(self, stream, batch: int, width, group: int):
        """Superbatch step: fold ``group`` stacked wire buffers in ONE
        device call via ``lax.scan`` over the same per-batch tail the fused
        step traces — bit-identical to ``group`` sequential dispatches, at
        1/group of the dispatch overhead.  Compiled once per bucketed group
        size (power-of-two sizes only, see plan_superbatch_groups)."""
        from gelly_streaming_tpu.io import wire

        token = self.cache_token
        stages = stream._stages
        key = (
            "wire_scan",
            token,
            stages,
            stream.cfg,
            batch,
            str(width),
            group,
        )

        def make_scan():
            tail = self._make_wire_tail(stages)

            def scan_fused(carry, bufs):  # bufs: uint8[group, nbytes]
                def body(c, buf):
                    s, d = wire.unpack_edges(buf, batch, width)
                    return tail(c, s, d, jnp.ones((batch,), bool)), None

                carry, _ = jax.lax.scan(body, carry, bufs)
                return carry

            return scan_fused

        return compile_cache.cached_jit(key, make_scan, donate_argnums=0)

    def _wire_width(self, cfg: StreamConfig, batch: Optional[int] = None):
        """Resolve the wire encoding for this descriptor + config.

        "auto" picks EF40 (sorted multiset, ~2x fewer bytes) only when the
        descriptor is order-free, ids fit in 20 bits, the host has spare
        cores to sort on — on a single-core host the per-batch radix sort
        competes with the transfer path for the same CPU and measures slower
        than shipping the plain 40-bit pack (BASELINE.md round 3) — AND it
        actually ships fewer bytes at the EFFECTIVE batch size (``batch``,
        defaulting to cfg.batch_size): its per-batch unary bitvector
        dominates when capacity >> batch, e.g. a short stream whose single
        batch shrank to the stream length.
        """
        from gelly_streaming_tpu.io import wire

        enc = cfg.wire_encoding
        if enc == "auto":
            try:
                # the process's USABLE cores (cgroup/affinity-aware), not the
                # machine's physical count — a container pinned to one core
                # of a 64-core host is still a single-core host here
                cores = len(os.sched_getaffinity(0))
            except AttributeError:
                cores = os.cpu_count() or 1
            # one shared cost policy with the replay producer
            width = wire.replay_width(
                cfg.vertex_capacity,
                batch if batch is not None else cfg.batch_size,
                self.order_free,
            )
            enc = "ef40" if (cores >= 2 and isinstance(width, tuple)) else "plain"
        if enc == "ef40":
            if not self.order_free:
                raise ValueError(
                    "wire_encoding='ef40' ships a sorted multiset; this "
                    "aggregation is not order-free"
                )
            if cfg.vertex_capacity > 1 << 20:
                raise ValueError("ef40 wire encoding needs vertex_capacity <= 2^20")
            return (wire.EF40, cfg.vertex_capacity)
        return wire.width_for_capacity(cfg.vertex_capacity)

    def _binned_modes(self, cfg: StreamConfig):
        """Resolve the propagation-blocking ingest switches for this
        descriptor: ``(binned, compress)``.

        Binning/compression reorder each batch into a (dst, src)-sorted
        multiset, so they are legal only for ORDER-FREE folds: an explicit
        ``cfg.binned_ingest=1`` / ``cfg.wire_compress=1`` on an
        order-sensitive descriptor refuses loudly (the EF40 rule), while
        the ambient env switches quietly stay on the arrival-order oracle —
        a process-wide GELLY_WIRE_COMPRESS=1 must not break the one
        order-sensitive query in a mixed pipeline.  Compression further
        needs ids in 2^28 (BDV varint bound) and yields to an explicit
        ``wire_encoding='ef40'`` (two compressed encodings cannot both win).
        """
        from gelly_streaming_tpu.io import wire

        compress = wire.resolve_wire_compress(cfg)
        binned = wire.resolve_binned_ingest(cfg)
        if not (binned or compress):
            return False, False
        forced = cfg.binned_ingest == 1 or cfg.wire_compress == 1
        if not self.order_free:
            if forced:
                raise ValueError(
                    "binned/compressed ingest ships a (dst, src)-sorted "
                    "multiset; this aggregation is not order-free"
                )
            return False, False
        if compress and cfg.vertex_capacity > 1 << wire.BDV_MAX_ID_BITS:
            if cfg.wire_compress == 1:
                raise ValueError(
                    "wire_compress needs vertex_capacity <= 2^28 (BDV varints)"
                )
            compress = False
        if compress and cfg.wire_encoding == "ef40":
            if cfg.wire_compress == 1:
                raise ValueError(
                    "wire_compress and wire_encoding='ef40' are mutually "
                    "exclusive wire formats; pick one"
                )
            compress = False
        return binned, compress

    def _maybe_bin_pane(
        self, cfg: StreamConfig, pane: WindowPane, width=None
    ) -> WindowPane:
        """Destination-bin a closed pane when binned ingest resolves on.

        Returns the pane with its edges (dst, src)-sorted — the same
        multiset, so order-free folds emit identically while their scatters
        walk the summary arrays segment-locally (the cache half of
        propagation blocking).  Valued/timed panes pass through untouched
        (their payload alignment is not worth permuting on the pack
        thread), as do non-order-free descriptors (loudly when forced —
        see ``_binned_modes``).  Callers that pack the pane at a known wire
        ``width`` pass it: tuple encodings (EF40) regroup each row by src
        themselves, so the pre-sort would be pure wasted pack-thread work
        (the same skip the wire fast path and the mesh row packer apply).
        """
        if pane.val is not None or pane.time is not None or pane.num_edges <= 1:
            return pane
        if width is not None and isinstance(width, tuple):
            return pane
        binned, _compress = self._binned_modes(cfg)
        if not binned:
            return pane
        from gelly_streaming_tpu.io import wire

        s, d = wire.sort_edges_binned(
            pane.src, pane.dst, cfg.vertex_capacity, record_stats=True
        )
        return pane._replace(src=s, dst=d)

    def _wire_checkpoint_like(self, stream):
        """Wire-path snapshot layout: the FULL fold carry (stage states +
        summary — closing the reference's unsaved-operator-state gap,
        SURVEY.md §5.3) plus the stream position in full batches."""
        cfg = stream.cfg
        return {
            "summary": self.initial_state(cfg),
            "stages": tuple(stage.init(cfg) for stage in stream._stages),
            "next_batch": np.zeros((), np.int64),
            # position is in units of full batches, so a resume under a
            # different batch_size would skip/refold the wrong edges — the
            # stored size makes that a hard error instead of silent corruption
            "batch": np.zeros((), np.int64),
            "done": np.zeros((), bool),
        }

    def _wire_restore(self, stream, checkpoint_path: Optional[str], batch: int):
        """Resolve a wire-path snapshot into a resume plan.

        Returns ``(start_batch, carry_host, done_summary)``: the batch to
        resume folding from, the restored carry (or None for a fresh start),
        and — when the snapshot says the stream already finished — the
        summary to re-emit instead of folding.  Legacy layouts degrade
        gracefully: a windowed-loop snapshot whose global pane finished
        re-emits; any other legacy form (windowed not-done, or the oldest
        bare-summary pytree) re-folds from the start — window positions
        don't map to wire batch positions, and exactly-once state holds
        either way.
        """
        cfg = stream.cfg
        if not checkpoint_path:
            return 0, None, None
        from gelly_streaming_tpu.utils.checkpoint import (
            checkpoint_exists,
            load_state,
        )

        if not checkpoint_exists(checkpoint_path):
            return 0, None, None
        try:
            snap = load_state(checkpoint_path, self._wire_checkpoint_like(stream))
        except ValueError:  # a pre-wire-path (windowed-layout) snapshot
            try:
                legacy = load_state(checkpoint_path, self._checkpoint_like(cfg))
            except ValueError:
                return 0, None, None  # bare-summary snapshot: no position
            if bool(legacy["global_done"]) and bool(legacy["has_summary"]):
                return 0, None, legacy["summary"]
            return 0, None, None
        if int(snap["batch"]) != batch:
            raise ValueError(
                f"wire checkpoint was written with batch_size "
                f"{int(snap['batch'])}; resuming with {batch} would "
                "misalign the stream position"
            )
        if bool(snap["done"]):
            return 0, None, snap["summary"]
        return int(snap["next_batch"]), (snap["stages"], snap["summary"]), None

    def _wire_records(
        self,
        stream,
        checkpoint_path: Optional[str] = None,
        restore: bool = True,
    ) -> Iterator[tuple]:
        """The packed-wire fast path, with optional positional checkpoints.

        Unlike the reference — whose Merger checkpoints inside the full-speed
        pipeline (SummaryAggregation.java:127-135) but loses all other
        operator state — the snapshot here is the WHOLE fold carry plus the
        batch position, taken every ``cfg.wire_checkpoint_batches`` full
        batches and at stream end.  On restore the source replays from the
        start and already-folded batches are skipped by position (the same
        replay contract as the windowed `_merge_loop`); state is exactly-once,
        the final emission is at-least-once.  A snapshot downloads the carry
        (device->host), so the interval trades recovery granularity against
        sustained ingest rate — at the default every-64-batches the cost is
        amortized to well under a percent of stream time on a PCIe host.
        """
        from gelly_streaming_tpu.io import wire
        from gelly_streaming_tpu.utils import metrics

        cfg = stream.cfg
        packed = getattr(stream, "_wire_packed", None)
        if packed is not None:
            # replay source: buffers are already wire-format (the producer
            # chose the encoding, BDV included); the loop's only host cost
            # is the transfer itself
            bufs, batch, width, tail_pair = packed
            # (EF40/BDV x order-sensitive refusal happens in run(), which
            # guards every consumption path, not just this one)
            src = dst = None
            binned = compress = False
            n_full = len(bufs)
            total_edges = n_full * batch + (len(tail_pair[0]) if tail_pair else 0)
        else:
            src, dst, batch = stream._wire_arrays
            batch = min(batch, max(len(src), 1))
            binned, compress = self._binned_modes(cfg)
            if compress:
                # the compressed wire format: (dst, src)-binned batches ship
                # delta/varint-packed and decode on device inside the same
                # cached fold executable (ops/wire_decode.py)
                width = (wire.BDV, cfg.vertex_capacity)
            else:
                width = self._wire_width(cfg, batch)
                if binned and isinstance(width, tuple):
                    # EF40 regroups each batch by src itself — pre-sorting
                    # by dst would be re-shuffled away; skip the wasted pass
                    binned = False
            n_full = len(src) // batch
            rem = len(src) - n_full * batch
            tail_pair = (
                (src[n_full * batch :], dst[n_full * batch :]) if rem else None
            )
            total_edges = len(src)
        fused, tail = self._wire_fused_step(stream, batch, width)
        start_batch, carry_host, done_summary = self._wire_restore(
            stream, checkpoint_path if restore else None, batch
        )
        if done_summary is not None:
            # stream fully folded before the crash: re-emit (the
            # at-least-once contract) without re-folding
            out = self.transform(done_summary)
            yield out if isinstance(out, tuple) else (out,)
            return
        # committed placement so the first and later calls share one jit entry
        carry = jax.device_put(
            carry_host
            if carry_host is not None
            else (
                tuple(stage.init(cfg) for stage in stream._stages),
                self.initial_state(cfg),
            ),
            jax.devices()[0],
        )

        # -- asynchronous snapshots (the reference's Merger checkpoints are
        # also async: Flink's barrier snapshots copy state off the hot path).
        # A snapshot (a) clones the carry ON DEVICE (a jitted tree copy whose
        # output cannot alias the non-donated input, so the next fused call's
        # donation can't corrupt it), (b) starts the device->host copy in the
        # background, and (c) hands the clone to a writer thread that blocks
        # on the download and does the atomic save — the fold never waits on
        # the downlink.  maxsize=1 bounds in-flight clones (backpressure: a
        # slow disk delays the NEXT snapshot, not the stream).
        import queue as _queue
        import threading as _threading

        snap_q: Optional["_queue.Queue"] = None
        snap_writer: Optional["_threading.Thread"] = None
        snap_err: list = []

        def _write_snapshots():
            from gelly_streaming_tpu.utils.checkpoint import save_state

            while True:
                item = snap_q.get()
                if item is None:
                    return
                pos, done_flag, carry_dev = item
                try:
                    host = jax.tree.map(np.asarray, carry_dev)
                    save_state(
                        checkpoint_path,
                        {
                            "summary": host[1],
                            "stages": host[0],
                            "next_batch": np.full((), pos, np.int64),
                            "batch": np.full((), batch, np.int64),
                            "done": np.full((), done_flag, bool),
                        },
                    )
                except BaseException as e:  # surfaced on the fold thread
                    snap_err.append(e)
                    return

        def _put_snap(item) -> bool:
            """Bounded put that cannot deadlock against a crashed writer:
            re-checks the error slot between attempts (the writer may die
            while this thread is blocked on a full queue)."""
            while not snap_err:
                try:
                    snap_q.put(item, timeout=0.05)
                    return True
                except _queue.Full:
                    continue
            return False

        def snapshot(pos: int, done: bool, carry_now):
            nonlocal snap_q, snap_writer
            if snap_err:
                raise snap_err[0]
            copy = _tree_copy(carry_now)
            for leaf in jax.tree.leaves(copy):
                try:
                    leaf.copy_to_host_async()
                except AttributeError:
                    pass
            if snap_q is None:
                snap_q = _queue.Queue(maxsize=1)
                snap_writer = _threading.Thread(target=_write_snapshots, daemon=True)
                snap_writer.start()
            if not _put_snap((pos, done, copy)):
                raise snap_err[0]

        def finish_snapshots(raise_err: bool = True):
            if snap_q is not None:
                if _put_snap(None):
                    snap_writer.join()
                else:
                    # dead writer never drains the queue — drop the leftovers
                    while True:
                        try:
                            snap_q.get_nowait()
                        except _queue.Empty:
                            break
            if raise_err and snap_err:
                raise snap_err[0]

        every = cfg.wire_checkpoint_batches
        since_snap = 0
        # running emission at batch boundaries (ingestion-time panes that
        # stay on the fast path — see _wire_emit_every); recomputed against
        # the EFFECTIVE batch: a stream shorter than one batch collapses to
        # a single end-of-stream pane, which the final emission covers
        emit_every = max(0, self._wire_emit_every(cfg, batch))

        # superbatch coalescing: dispatch groups of consecutive full batches
        # in ONE device call each.  Group sizes are powers of two <= K and
        # never cross an emission or snapshot boundary, so the observable
        # record/recovery sequence is identical to per-batch dispatch.
        from gelly_streaming_tpu.core.stream import plan_superbatch_groups

        boundaries = []
        if emit_every:
            boundaries.append((emit_every, start_batch))
        if checkpoint_path and every:
            boundaries.append((every, 0))
        groups = plan_superbatch_groups(
            n_full - start_batch, max(1, cfg.superbatch), boundaries
        )

        def device_buffers():
            """(group size, device buffer) pairs: ``uint8[nbytes]`` for
            size-1 groups (the historical per-batch path), ``uint8[g,
            nbytes]`` stacked groups otherwise.  Packing/stacking runs on
            the Prefetcher's background thread; the transfer on its second
            — one transfer per GROUP, so superbatching also amortizes
            per-transfer overhead."""
            offsets = []
            o = 0
            for g in groups:
                offsets.append((o, g))
                o += g
            if packed is not None:

                def prep(item):
                    o, g = item
                    if g == 1:
                        buf = bufs[start_batch + o]
                        metrics.wire_record_batch(1, batch, buf.nbytes)
                        return 1, buf
                    group_bufs = bufs[start_batch + o : start_batch + o + g]
                    widest = max(b.nbytes for b in group_bufs)
                    if all(b.nbytes == widest for b in group_bufs):
                        arena = np.stack(group_bufs)
                    else:
                        # variable-size (BDV) replay buffers: pad to the
                        # group max — trailing zeros decode as dropped
                        # empty varint groups
                        arena = np.zeros((g, widest), np.uint8)
                        for j, b in enumerate(group_bufs):
                            arena[j, : b.nbytes] = b
                    metrics.wire_record_batch(g, g * batch, arena.nbytes)
                    return g, arena

            else:
                from gelly_streaming_tpu.io import ingest as ingest_mod

                workers = ingest_mod.resolve_workers(cfg.ingest_workers)
                nbytes = wire.wire_nbytes(batch, width) if not compress else 0

                def prep(item):
                    o, g = item
                    i0 = start_batch + o
                    if compress:
                        # bin + delta/varint pack (sort on this pack thread,
                        # group rows across the ingest pool); buffers bucket
                        # to stable shapes, so same-regime batches reuse one
                        # compiled decode+fold executable
                        if g == 1:
                            buf = wire.pack_edges_bdv(
                                src[i0 * batch : (i0 + 1) * batch],
                                dst[i0 * batch : (i0 + 1) * batch],
                                cfg.vertex_capacity,
                                record_stats=True,
                            )
                        else:
                            buf = ingest_mod.pack_bdv_group(
                                src,
                                dst,
                                i0,
                                g,
                                batch,
                                cfg.vertex_capacity,
                                workers,
                            )
                        metrics.wire_record_batch(g, g * batch, buf.nbytes)
                        return g, buf
                    if g == 1:
                        s_b = src[i0 * batch : (i0 + 1) * batch]
                        d_b = dst[i0 * batch : (i0 + 1) * batch]
                        if binned:
                            s_b, d_b = wire.sort_edges_binned(
                                s_b, d_b, cfg.vertex_capacity, record_stats=True
                            )
                        buf = wire.pack_edges(s_b, d_b, width)
                        metrics.wire_record_batch(1, batch, buf.nbytes)
                        return 1, buf
                    # pack straight into the group arena (the transfer
                    # layout): no re-copy between pack and device_put
                    arena = np.empty((g, nbytes), np.uint8)
                    if binned:
                        ingest_mod.pack_binned_rows_into(
                            src,
                            dst,
                            i0,
                            g,
                            batch,
                            width,
                            cfg.vertex_capacity,
                            arena,
                            workers,
                        )
                    else:
                        ingest_mod.pack_rows_into(
                            src, dst, i0, g, batch, width, arena, workers
                        )
                    metrics.wire_record_batch(g, g * batch, arena.nbytes)
                    return g, arena

            with wire.Prefetcher(offsets, prep, depth=cfg.prefetch_depth) as pf:
                yield from pf

        pending_final = True
        try:
            pos = start_batch
            # hot-loop: wire fast-path fold (no per-batch host syncs)
            for g, dev in device_buffers():
                if g == 1:
                    carry = fused(carry, dev)
                else:
                    carry = self._wire_scan_step(stream, batch, width, g)(
                        carry, dev
                    )
                pos += g
                if emit_every and pos % emit_every == 0:
                    # the donated carry IS the running merged summary
                    # (Merger semantics): emit the pane's running record
                    # without leaving the fast path.  CLONE first — the next
                    # fused call donates the carry's buffers, which would
                    # delete them out from under the emitted record
                    out = self.transform(_tree_copy(carry[1]))
                    yield out if isinstance(out, tuple) else (out,)
                    # a stream ending exactly on a pane boundary with no
                    # tail has nothing further to emit
                    pending_final = pos != n_full or tail_pair is not None
                since_snap += g
                if checkpoint_path and every and since_snap >= every:
                    # the snapshot clones the carry on device BEFORE the next
                    # fused call donates it away
                    snapshot(pos, False, carry)
                    since_snap = 0
            # hot-loop-end
            if tail_pair is not None:
                rem = len(tail_pair[0])
                mask = np.zeros((batch,), bool)
                mask[:rem] = True
                pad_s = np.zeros((batch,), np.int32)
                pad_d = np.zeros((batch,), np.int32)
                pad_s[:rem] = tail_pair[0]
                pad_d[:rem] = tail_pair[1]
                carry = tail(
                    carry,
                    jnp.asarray(pad_s),
                    jnp.asarray(pad_d),
                    jnp.asarray(mask),
                )
            if total_edges == 0:
                return
            if pending_final:
                out = self.transform(carry[1])
                # emit BEFORE the final snapshot: a crash between the two
                # re-emits on recovery (at-least-once) instead of dropping
                # the record
                yield out if isinstance(out, tuple) else (out,)
            if checkpoint_path:
                snapshot(n_full, True, carry)
        except BaseException:
            # includes GeneratorExit from an abandoning consumer: shut the
            # writer down without masking the in-flight exception
            finish_snapshots(raise_err=False)
            raise
        finish_snapshots()

    def _checkpoint_like(self, cfg):
        """Checkpoint structure: summary + presence flag + stream position.

        ``global_done`` marks the untimed single global pane as folded —
        it has no orderable id (-1), so it needs its own done flag for
        replay-safe skipping.
        """
        return {
            "summary": self.initial_state(cfg),
            "has_summary": np.zeros((), bool),
            "last_window": np.full((), -1, np.int64),
            "global_done": np.zeros((), bool),
        }

    def run(
        self,
        stream,
        checkpoint_path: Optional[str] = None,
        restore: bool = True,
    ) -> OutputStream:
        """Execute over an EdgeStream (entered via GraphStream.aggregate,
        GraphStream.java:139-140 / SimpleEdgeStream.java:100-102).

        With ``checkpoint_path``, the running summary AND the stream position
        (last closed window id) are snapshot after every window close and
        restored on start — the Merger's ListCheckpointed behavior
        (SummaryAggregation.java:127-135), generalized to the whole summary
        pytree plus position (closing the reference's unsaved-state gap).  On
        restore, panes already folded before the snapshot are skipped, so the
        source may simply replay from the beginning.  State is exactly-once;
        emissions after the last snapshot are re-emitted (at-least-once), as
        in the reference's Merger.  The untimed single global pane resumes
        only for an unchanged replay (it has no sub-pane position — a longer
        replayed stream's extra untimed edges would be skipped with it).

        Execution strategy by config (the reference picks its pipeline at
        graph-build time the same way): wire-backed single-shard streams ride
        the packed-wire fast path; ``cfg.num_shards > 1`` with enough devices
        runs the real sharded data plane (MeshAggregationRunner); otherwise
        partitions are simulated sequentially (the MiniCluster shape).  All
        paths share the Merger/checkpoint loop (`_merge_loop`)."""
        if checkpoint_path and stream.cfg.ingest_window_ms:
            raise ValueError(
                "wall-clock ingestion panes (ingest_window_ms) are not "
                "replay-deterministic: a resume would skip panes by id that "
                "cover different edges than the crashed run's; use "
                "ingest_window_edges for checkpointed runs"
            )
        packed = getattr(stream, "_wire_packed", None)
        if packed is not None and isinstance(packed[2], tuple) and not self.order_free:
            # EF40/BDV replay buffers carry per-batch sorted multisets;
            # EVERY consumption path (fast, mesh, simulated) would see
            # reordered edges, so refuse up front rather than only on the
            # fast path
            raise ValueError(
                f"{packed[2][0]} replay buffers carry a sorted multiset; "
                "this aggregation is not order-free"
            )
        if self._wire_eligible(stream):
            return OutputStream(
                lambda: self._wire_records(stream, checkpoint_path, restore)
            )
        if self._mesh_wire_eligible(stream):
            runner = self._mesh_runner(stream.cfg)
            return OutputStream(
                lambda: runner.wire_records(stream, checkpoint_path, restore)
            )
        cfg = stream.cfg
        if cfg.num_shards > 1 and cfg.num_shards <= len(jax.devices()):
            return self._mesh_runner(cfg).run(
                stream, checkpoint_path=checkpoint_path, restore=restore
            )
        window_ms = self.window_ms or cfg.window_ms
        n_parts = self._num_partitions(cfg)

        if cfg.superbatch > 1 and n_parts == 1:
            # superbatch the TIME plane: up to K closed panes
            # (core/windows.group_panes) fold in ONE vmapped device call
            # over a row-per-window layout; the shared Merger loop still
            # merges/emits/checkpoints per window, so the record sequence
            # and recovery semantics are identical to per-pane dispatch.
            def records_sb() -> Iterator[tuple]:
                skip_through, skip_global = self._restored_position(
                    cfg, checkpoint_path, restore
                )
                return self._merge_loop(
                    cfg,
                    self._superpane_folds(
                        stream, window_ms, skip_through, skip_global
                    ),
                    lambda summary: summary,
                    checkpoint_path,
                    restore,
                    unwrap=True,
                )

            return OutputStream(records_sb)

        from gelly_streaming_tpu.core import async_exec

        if async_exec.resolve_depth(cfg) > 0 and n_parts == 1:
            # asynchronous window pipeline: pane padding on the pack thread
            # into reusable arenas, transfers on the second thread, folds
            # dispatched without waiting, emissions drained in window order
            # (core/async_exec.py) — bit-identical record sequence to the
            # synchronous fold_pane path below
            return OutputStream(
                lambda: self._async_pane_records(
                    stream, window_ms, checkpoint_path, restore
                )
            )

        def fold_pane(pane: WindowPane):
            # destination-bin the pane first (order-free folds only; no-op
            # otherwise): the round-robin strided slices of a sorted pane
            # stay sorted, so each partition's scatter is segment-local
            pane = self._maybe_bin_pane(cfg, pane)
            partials = []
            for part in range(n_parts):
                # Round-robin partitioning of the pane stands in for the
                # reference's source-subtask tagging (PartitionMapper,
                # SummaryBulkAggregation.java:93-106).
                sel = np.arange(len(pane.src)) % n_parts == part
                if not sel.any():
                    continue
                # Pad to the next power of two so varying pane sizes hit a
                # small, bounded set of compiled kernel shapes.
                n = int(sel.sum())
                padded = max(1, 1 << (n - 1).bit_length())
                mask = np.zeros((padded,), bool)
                mask[:n] = True

                def pad(a, fill=0):
                    out = np.full((padded,) + a.shape[1:], fill, a.dtype)
                    out[:n] = a[sel]
                    return out

                state = self.initial_state(cfg)
                state = self._update_j(
                    state,
                    jnp.asarray(pad(pane.src), jnp.int32),
                    jnp.asarray(pad(pane.dst), jnp.int32),
                    None
                    if pane.val is None
                    else jax.tree.map(lambda a: jnp.asarray(pad(a)), pane.val),
                    jnp.asarray(mask),
                )
                partials.append(state)
            if not partials:
                return None
            return self._combine_partials(partials, cfg)

        def records() -> Iterator[tuple]:
            return self._merge_loop(
                cfg,
                stream_panes(stream, window_ms),
                fold_pane,
                checkpoint_path,
                restore,
            )

        return OutputStream(records)

    # -- cross-tenant fused dispatch (runtime/manager.py cohorts) -------------

    def fused_eligible(self, stream) -> bool:
        """True when this descriptor/stream pair would ride the plain
        single-partition synchronous windowed plane — the only plane the
        cross-tenant fused protocol replaces.  Wire, mesh-wire, sharded,
        superbatch, and async jobs keep their own (already-batched or
        already-pipelined) planes and simply dispatch solo under a fused
        manager."""
        cfg = stream.cfg
        if self._wire_eligible(stream) or self._mesh_wire_eligible(stream):
            return False
        if cfg.num_shards > 1 and cfg.num_shards <= len(jax.devices()):
            return False
        if self._num_partitions(cfg) != 1:
            return False
        if cfg.superbatch > 1:
            return False
        from gelly_streaming_tpu.core import async_exec

        return async_exec.resolve_depth(cfg) == 0

    def run_fused(
        self,
        stream,
        checkpoint_path: Optional[str] = None,
        restore: bool = True,
    ) -> Iterator[tuple]:
        """The windowed plane as a fused-dispatch COHORT MEMBER: a
        bidirectional generator that parks each window's padded fold at a
        ``FoldRequest`` yield instead of dispatching it.

        The consumer (the manager's scheduler) ``send()``s back either a
        fused per-row partial — its row of one vmapped mega-fold over N
        tenant jobs' same-key requests — or ``None``, which makes the
        generator fold the SAME padded arrays itself through the same
        executable chain as the plain plane (the bit-exact solo oracle).
        A consumer that does not understand the protocol resumes with
        plain ``next()`` — Python defines that as ``send(None)`` — so a
        dropped/parked quantum, a paused-then-resumed job, or a naive
        iterator consumer all degrade to correct solo dispatch rather
        than losing the window.  Everything downstream of the fold
        (running merge order, transform, at-least-once emission,
        positional checkpoints, transient resets) is ``_merge_loop``'s
        logic verbatim, so fused and solo record sequences are
        bit-identical (pinned by tests/test_fused_dispatch.py).
        """
        if checkpoint_path and stream.cfg.ingest_window_ms:
            raise ValueError(
                "wall-clock ingestion panes (ingest_window_ms) are not "
                "replay-deterministic: a resume would skip panes by id that "
                "cover different edges than the crashed run's; use "
                "ingest_window_edges for checkpointed runs"
            )
        return self._fused_pane_records(stream, checkpoint_path, restore)

    def _fused_pane_records(
        self,
        stream,
        checkpoint_path: Optional[str],
        restore: bool,
    ) -> Iterator[tuple]:
        """Merger loop with the per-pane fold handed to the cohort consumer
        (see ``run_fused``).  Mirrors ``_merge_loop`` + the sync
        ``fold_pane`` exactly; any drift here is a correctness bug, not a
        style one."""
        cfg = stream.cfg
        window_ms = self.window_ms or cfg.window_ms
        running = None
        start_after = -1
        global_done = False
        if checkpoint_path and restore:
            from gelly_streaming_tpu.utils.checkpoint import (
                checkpoint_exists,
                load_state,
            )

            if checkpoint_exists(checkpoint_path):
                try:
                    snap = load_state(checkpoint_path, self._checkpoint_like(cfg))
                    if bool(snap["has_summary"]):
                        running = snap["summary"]
                    start_after = int(snap["last_window"])
                    global_done = bool(snap["global_done"])
                except ValueError:
                    # legacy snapshot layout: a bare summary pytree with
                    # no stream position (pre-position checkpoints)
                    running = load_state(checkpoint_path, self.initial_state(cfg))
        span_sampler = tracing.sampler(cfg, "merge")
        token = self.cache_token
        split = functools.partial(self._superpane_split_fn, cfg)
        for pane in stream_panes(stream, window_ms):
            already_folded = (0 <= pane.window_id <= start_after) or (
                pane.window_id == -1 and global_done
            )
            if already_folded:
                continue  # folded before the snapshot: replay-safe
            span = (
                span_sampler.begin(pane.window_id)
                if span_sampler is not None
                else None
            )
            t_item = time.perf_counter()
            pane = self._maybe_bin_pane(cfg, pane)
            n = pane.num_edges
            if n == 0:
                continue  # empty pane: the sync fold returns None too
            # the sync plane's pow2 pad, materialized as the offered row
            e_pad = max(1, 1 << (n - 1).bit_length())
            src = np.zeros((e_pad,), np.int32)
            dst = np.zeros((e_pad,), np.int32)
            msk = np.zeros((e_pad,), bool)
            src[:n], dst[:n], msk[:n] = pane.src, pane.dst, True
            val = None
            if pane.val is not None:

                def pad(a):
                    out = np.zeros((e_pad,) + a.shape[1:], a.dtype)
                    out[:n] = a
                    return out

                val = jax.tree.map(pad, pane.val)
            has_val = val is not None
            partial = yield FoldRequest(
                key=(token, cfg, has_val, e_pad),
                fold=self._superpane_fold_fn(cfg, has_val),
                split=split,
                src=src,
                dst=dst,
                val=val,
                mask=msk,
                window_id=pane.window_id,
                edges=n,
            )
            if partial is None:
                # solo fallback: no same-key peers this round (or a
                # protocol-naive resume) — fold the identical padded
                # arrays through the plain plane's executable
                partial = self._update_j(
                    self.initial_state(cfg),
                    jnp.asarray(src),
                    jnp.asarray(dst),
                    None if val is None else jax.tree.map(jnp.asarray, val),
                    jnp.asarray(msk),
                )
            if running is None or self.transient_state:
                running = partial
            else:
                running = self._combine_j(running, partial)
            out = self.transform(running)
            t_emit = time.perf_counter()
            metrics.hist_record(
                "window_close_to_emission_ms", (t_emit - t_item) * 1e3
            )
            if span is not None:
                span.mark("dispatch", t_item, t_emit)
                span.mark("emit", t_emit)
                span_sampler.record(span, t_emit)
            # Emit BEFORE snapshotting: a crash between the two re-emits
            # this window on recovery (at-least-once emission) instead of
            # dropping it (at-most-once would lose sink data).
            yield out if isinstance(out, tuple) else (out,)
            start_after = max(pane.window_id, start_after)
            global_done = global_done or pane.window_id == -1
            if checkpoint_path:
                from gelly_streaming_tpu.utils.checkpoint import save_state

                # transient aggregations reset after emission, so a
                # restore must come back with no running summary
                save_state(
                    checkpoint_path,
                    {
                        "summary": running,
                        "has_summary": np.full((), not self.transient_state, bool),
                        "last_window": np.full((), start_after, np.int64),
                        "global_done": np.full((), global_done, bool),
                    },
                )
            if self.transient_state:
                running = None

    def _restored_position(self, cfg, checkpoint_path, restore):
        """(last folded window id, global pane done) from a windowed-layout
        snapshot — for gating pane prefetch/fold work ahead of the merge
        loop, which re-reads the position itself and remains the source of
        truth.  (-1, False) when there is nothing to restore."""
        if not (checkpoint_path and restore):
            return -1, False
        from gelly_streaming_tpu.utils.checkpoint import (
            checkpoint_exists,
            load_state,
        )

        if not checkpoint_exists(checkpoint_path):
            return -1, False
        try:
            snap = load_state(checkpoint_path, self._checkpoint_like(cfg))
        except ValueError:
            return -1, False  # legacy layout: merge loop sorts it out
        return int(snap["last_window"]), bool(snap["global_done"])

    def _restored_summary(self, cfg, checkpoint_path, restore):
        """The snapshot's running summary pytree, or None — for seeding
        persistent sharded blocks on resume (the merge loop re-reads the
        position itself and stays the source of truth)."""
        if not (checkpoint_path and restore):
            return None
        from gelly_streaming_tpu.utils.checkpoint import (
            checkpoint_exists,
            load_state,
        )

        if not checkpoint_exists(checkpoint_path):
            return None
        try:
            snap = load_state(checkpoint_path, self._checkpoint_like(cfg))
        except ValueError:
            return None
        if not bool(snap["has_summary"]):
            return None
        return snap["summary"]

    def _async_pane_records(
        self,
        stream,
        window_ms: int,
        checkpoint_path: Optional[str],
        restore: bool,
    ) -> Iterator[tuple]:
        """Single-partition windowed plane on the async pipeline.

        Per pane: padding to the pow2 fold bucket happens on the
        prefetcher's pack thread, writing into reusable transfer arenas
        (async_exec.ArenaPool — recycled only once the consuming fold is
        known complete, since a CPU device_put may alias the host buffer);
        the device_put rides the transfer thread; the fold dispatches here
        through the SAME cached ``_update_j`` executable the synchronous
        ``fold_pane`` traces, so the per-window partials — and therefore
        the merged emission sequence — are bit-identical to the sync path
        (pinned by tests/test_async_windows.py).  Emission/checkpoint
        ordering rides the async Merger (`_merge_loop` -> async_merge_loop).
        """
        from gelly_streaming_tpu.core import async_exec
        from gelly_streaming_tpu.io import wire as wire_mod

        cfg = stream.cfg
        depth = async_exec.resolve_depth(cfg)
        skip_through, skip_global = self._restored_position(
            cfg, checkpoint_path, restore
        )
        # retention cap sized to the pipeline's own in-flight bound (two
        # int32 arenas per pane x panes across the prefetch + completion
        # queues), so steady state recycles instead of reallocating
        pool = async_exec.ArenaPool(per_shape=2 * depth + 6)
        # window spans originate HERE, on the prefetcher's pack thread:
        # each sampled pane gets its trace id before packing and carries
        # the span through transfer/dispatch/drain in its meta tuple
        # (sampling off = one branch per pane, nothing else)
        span_sampler = tracing.sampler(cfg, "windowed")

        def prepare(pane: WindowPane):
            already = (0 <= pane.window_id <= skip_through) or (
                pane.window_id == -1 and skip_global
            )
            n = pane.num_edges
            if already or n == 0:
                return (pane, None, None), None
            span = (
                span_sampler.begin(pane.window_id)
                if span_sampler is not None
                else None
            )
            t_pack = time.perf_counter()
            # destination binning rides this pack thread too (order-free
            # folds only; no-op otherwise) — the dispatch loop never sorts
            pane = self._maybe_bin_pane(cfg, pane)
            padded = max(1, 1 << (n - 1).bit_length())
            src = pool.acquire((padded,), np.int32)
            dst = pool.acquire((padded,), np.int32)
            mask = pool.acquire((padded,), bool)
            src[:n] = pane.src
            dst[:n] = pane.dst
            mask[:n] = True
            val = None
            if pane.val is not None:

                def pad(a):
                    out = np.zeros((padded,) + a.shape[1:], a.dtype)
                    out[:n] = a
                    return out

                val = jax.tree.map(pad, pane.val)
            if span is not None:
                span.mark("pack", t_pack)
                span.annotate(edges=n)
            return (pane, (src, dst, mask), span), (src, dst, val, mask)

        def fold_prepared(item):
            (pane, arenas, _span), dev = item
            if arenas is None:
                return None
            src_d, dst_d, val_d, mask_d = dev
            return self._update_j(
                self.initial_state(cfg), src_d, dst_d, val_d, mask_d
            )

        def release(item):
            (pane, arenas, _span), _dev = item
            if arenas is not None:
                pool.release(*arenas)  # arena-live-until: drain

        with wire_mod.Prefetcher(
            stream_panes(stream, window_ms), prepare, depth=depth + 1
        ) as pf:
            yield from self._merge_loop(
                cfg,
                ((meta[0], (meta, dev)) for meta, dev in pf),
                fold_prepared,
                checkpoint_path,
                restore,
                unwrap=True,
                release=release,
            )

    def _superpane_fold_fn(self, cfg: StreamConfig, has_val: bool):
        """Compiled K-window fold: ONE dispatch produces every coalesced
        window's partial summary via a vmap over per-window edge rows.

        The row layout ([K, E_max]: one padded row per window) keeps the
        dispatch's total work at K * E_max ~= the sum of the pane sizes for
        balanced windows — NOT K times the concatenated run, which a
        mask-per-window fold over the flat [E_total] layout would cost."""
        token = self.cache_token

        def make():
            def fold(src_k, dst_k, val_k, mask_k):
                def one(s, d, v, m):
                    return self.update(self.initial_state(cfg), s, d, v, m)

                if val_k is None:
                    return jax.vmap(lambda s, d, m: one(s, d, None, m))(
                        src_k, dst_k, mask_k
                    )
                return jax.vmap(one)(src_k, dst_k, val_k, mask_k)

            return fold

        return compile_cache.cached_jit(
            ("superpane_fold", token, cfg, has_val), make
        )

    def _superpane_split_fn(self, cfg: StreamConfig, rows: int):
        """Compiled cohort drain: ONE dispatch slices a ``[rows, ...]``
        stacked mega-fold result into per-row partial pytrees (row i =
        job i's window partial, still on device).

        Draining with an eager per-row ``a[i]`` slice instead costs one
        device dispatch per job per cohort — measured ~2x the fused fold
        itself at 16 rows — which would hand back most of the dispatch
        amortization the mega-fold just bought.  Keyed per pow2 row
        bucket, so 1..16-job tenancy reuses at most four traces."""
        token = self.cache_token

        def make():
            def split(states):
                return tuple(
                    jax.tree.map(lambda a, i=i: a[i], states)
                    for i in range(rows)
                )

            return split

        return compile_cache.cached_jit(
            ("superpane_split", token, cfg, rows), make
        )

    def _superpane_folds(
        self, stream, window_ms: int, skip_through: int = -1, skip_global: bool = False
    ):
        """(pane, partial summary) pairs with up to ``cfg.superbatch``
        consecutive panes folded per device dispatch.

        The per-window partial equals the per-pane path's fold exactly: the
        update kernel sees that window's edges (arrival order preserved)
        with padding masked out.  Both row count and row length bucket to
        powers of two (at most log2(K)+1 x shape-bucket compiled variants);
        rows past the group's real panes are fully masked and their
        initial-state outputs discarded.

        ``skip_through``/``skip_global`` gate RESTORED positions: panes a
        checkpoint already folded are dropped here without any device work
        (the merge loop would discard them unfolded anyway — the per-pane
        path never folds them either, and recovery must not pay a full
        re-fold of the pre-crash stream).
        """
        from gelly_streaming_tpu.core import async_exec
        from gelly_streaming_tpu.core.windows import group_panes

        cfg = stream.cfg
        live = (
            self._maybe_bin_pane(cfg, p)
            for p in stream_panes(stream, window_ms)
            if not (
                (0 <= p.window_id <= skip_through)
                or (p.window_id == -1 and skip_global)
            )
        )
        groups = group_panes(live, cfg.superbatch)
        depth = async_exec.resolve_depth(cfg)
        if depth > 0:
            # async pipeline: row assembly on the prefetcher's pack thread
            # (ingest-pool parallel row fill), transfer on its second,
            # folds dispatched here without waiting — same executables and
            # per-window partials as the inline path below
            from gelly_streaming_tpu.io import wire as wire_mod

            def prep(panes):
                return tuple(panes), self._assemble_superpane_rows(panes)

            with wire_mod.Prefetcher(groups, prep, depth=depth + 1) as pf:
                # hot-loop: superpane dispatch (no per-group host syncs)
                for panes, dev in pf:
                    src_d, dst_d, val_d, mask_d = dev
                    fold = self._superpane_fold_fn(cfg, val_d is not None)
                    states = fold(src_d, dst_d, val_d, mask_d)
                    for i, pane in enumerate(panes):
                        yield pane, jax.tree.map(lambda a, i=i: a[i], states)
                # hot-loop-end
            return
        for panes in groups:
            src_k, dst_k, val_k, mask_k = self._assemble_superpane_rows(panes)
            fold = self._superpane_fold_fn(cfg, val_k is not None)
            states = fold(
                jnp.asarray(src_k),
                jnp.asarray(dst_k),
                None if val_k is None else jax.tree.map(jnp.asarray, val_k),
                jnp.asarray(mask_k),
            )
            for i, pane in enumerate(panes):
                yield pane, jax.tree.map(lambda a, i=i: a[i], states)

    def _assemble_superpane_rows(self, panes):
        """Host assembly of a pane group's [rows, E_pad] fold layout (the
        transfer layout `_superpane_fold_fn` consumes): numpy
        (src_k, dst_k, val_k | None, mask_k).  Row filling shards across the
        ingest worker pool (io/ingest.fill_pane_rows_into) — one row per
        pane, each worker writing its slice in place."""
        from gelly_streaming_tpu.io import ingest as ingest_mod

        k = len(panes)
        rows = max(1, 1 << (k - 1).bit_length())  # pow2 bucket, <= K
        e_max = max(p.num_edges for p in panes)
        e_pad = max(1, 1 << (e_max - 1).bit_length())
        src_k = np.zeros((rows, e_pad), np.int32)
        dst_k = np.zeros((rows, e_pad), np.int32)
        mask_k = np.zeros((rows, e_pad), bool)
        ingest_mod.fill_pane_rows_into(panes, src_k, dst_k, mask_k)
        val_k = None
        if any(p.val is not None for p in panes):
            proto = next(p.val for p in panes if p.val is not None)
            val_k = jax.tree.map(
                lambda a: np.zeros((rows, e_pad) + a.shape[1:], a.dtype),
                proto,
            )
            for i, pane in enumerate(panes):
                if pane.val is not None:

                    def fill(buf, a):
                        buf[i, : len(a)] = a
                        return buf

                    val_k = jax.tree.map(fill, val_k, pane.val)
        return src_k, dst_k, val_k, mask_k

    def _mesh_runner(self, cfg: StreamConfig) -> "MeshAggregationRunner":
        """Cached sharded runner for cfg.num_shards (compiled steps persist)."""
        runner = getattr(self, "_mesh_runner_cache", None)
        if runner is None or runner.num_shards != cfg.num_shards:
            from gelly_streaming_tpu.parallel.mesh import make_mesh

            runner = MeshAggregationRunner(self, mesh=make_mesh(cfg.num_shards))
            self._mesh_runner_cache = runner
        return runner

    def _merge_loop(
        self,
        cfg: StreamConfig,
        panes: Iterator,
        fold_pane: Callable,
        checkpoint_path: Optional[str],
        restore: bool,
        unwrap: bool = False,
        release: Optional[Callable] = None,
        fold_is_running: bool = False,
    ) -> Iterator[tuple]:
        """The Merger: running merge + emission + positional checkpointing
        (SummaryAggregation.java:93-135), shared by the simulated and mesh
        execution paths so their recovery semantics cannot diverge.

        ``fold_is_running`` (the owner-sharded plane): ``fold_pane`` folds
        into PERSISTENT cross-window state and returns the running summary
        itself, so the loop skips the combine step — emission order,
        transient resets, and checkpoint semantics are unchanged.

        ``fold_pane(pane) -> summary | None`` supplies the per-pane partial
        fold+combine; everything downstream (merge order, transient reset,
        at-least-once emission, snapshot layout) is common.  With ``unwrap``
        the iterator yields (pane, payload) pairs — position/window logic
        reads the pane, the payload goes to ``fold_pane`` (the mesh runner
        attaches prefetched device buffers this way).

        With ``cfg.async_windows`` > 0 the loop runs in its asynchronous
        form (core/async_exec.async_merge_loop): folds dispatch without
        waiting and emissions/checkpoints resolve through a completion
        queue in window order — same record sequence and recovery
        semantics, minus the per-window host round trip.  ``release``
        (async only) recycles a window's transfer arenas once its fold is
        known complete.
        """
        from gelly_streaming_tpu.core import async_exec

        depth = async_exec.resolve_depth(cfg)
        if depth > 0:
            yield from async_exec.async_merge_loop(
                self,
                cfg,
                panes,
                fold_pane,
                checkpoint_path,
                restore,
                unwrap=unwrap,
                depth=depth,
                release=release,
                fold_is_running=fold_is_running,
            )
            return
        running = None
        start_after = -1
        global_done = False
        if checkpoint_path and restore:
            from gelly_streaming_tpu.utils.checkpoint import (
                checkpoint_exists,
                load_state,
            )

            if checkpoint_exists(checkpoint_path):
                try:
                    snap = load_state(checkpoint_path, self._checkpoint_like(cfg))
                    if bool(snap["has_summary"]):
                        running = snap["summary"]
                    start_after = int(snap["last_window"])
                    global_done = bool(snap["global_done"])
                except ValueError:
                    # legacy snapshot layout: a bare summary pytree with
                    # no stream position (pre-position checkpoints)
                    running = load_state(checkpoint_path, self.initial_state(cfg))
        # span sampling resolved ONCE: when off (the default) the loop
        # below pays a single `is not None` branch per window
        span_sampler = tracing.sampler(cfg, "merge")
        for item in panes:
            pane, payload = item if unwrap else (item, item)
            already_folded = (0 <= pane.window_id <= start_after) or (
                pane.window_id == -1 and global_done
            )
            if already_folded:
                continue  # folded before the snapshot: replay-safe
            span = (
                span_sampler.begin(pane.window_id)
                if span_sampler is not None
                else None
            )
            t_item = time.perf_counter()
            pane_summary = fold_pane(payload)
            if pane_summary is None:
                continue
            # Merger: non-blocking running merge, one emission per window
            # close (SummaryAggregation.java:107-119).
            if running is None or self.transient_state or fold_is_running:
                running = pane_summary
            else:
                running = self._combine_j(running, pane_summary)
            out = self.transform(running)
            t_emit = time.perf_counter()
            metrics.hist_record(
                "window_close_to_emission_ms", (t_emit - t_item) * 1e3
            )
            if span is not None:
                span.mark("dispatch", t_item, t_emit)
                span.mark("emit", t_emit)
                span_sampler.record(span, t_emit)
            # Emit BEFORE snapshotting: a crash between the two re-emits
            # this window on recovery (at-least-once emission) instead of
            # dropping it (at-most-once would lose sink data).
            yield out if isinstance(out, tuple) else (out,)
            start_after = max(pane.window_id, start_after)
            global_done = global_done or pane.window_id == -1
            if checkpoint_path:
                from gelly_streaming_tpu.utils.checkpoint import save_state

                # transient aggregations reset after emission, so a
                # restore must come back with no running summary
                save_state(
                    checkpoint_path,
                    {
                        "summary": running,
                        "has_summary": np.full((), not self.transient_state, bool),
                        "last_window": np.full((), start_after, np.int64),
                        "global_done": np.full((), global_done, bool),
                    },
                )
            if self.transient_state:
                running = None


class SummaryBulkAggregation(SummaryAggregation):
    """Flat combine strategy (SummaryBulkAggregation.java:51-90)."""


class SummaryTreeAggregation(SummaryAggregation):
    """Log-depth combine tree (SummaryTreeReduce.java:47-123): partials merge
    in rounds of ``degree``-ary groups (the reference re-keys partitions by
    ``partition/2`` per level and exposes a configurable ``degree`` :53-64,
    defaulting to the stream parallelism :75) — same fixed point as the flat
    fold for associative combines, fewer sequential merge steps.

    ``degree`` here defaults to ``cfg.tree_degree``; pass it explicitly to
    mirror the reference's constructor knob.
    """

    def __init__(self, window_ms: Optional[int] = None, degree: Optional[int] = None):
        super().__init__(window_ms)
        self.degree = degree

    def _tree_fanin(self, cfg: StreamConfig) -> int:
        return max(2, self.degree or cfg.tree_degree)

    def _fold_partials(self, items, combine2, fanin: int = 2):
        level = list(items)
        while len(level) > 1:
            nxt = []
            for i in range(0, len(level), fanin):
                group = level[i : i + fanin]
                acc = group[0]
                for it in group[1:]:
                    acc = combine2(acc, it)
                nxt.append(acc)
            level = nxt
        return level[0]


class MeshAggregationRunner:
    """Execute a SummaryAggregation's window fold+combine over a device mesh.

    The single-device ``run`` above *simulates* partitions sequentially (the
    MiniCluster shape); this runner is the real multi-chip data plane: each
    window pane is bucketed round-robin across shards on the host, and ONE
    jitted ``shard_map`` step does the per-shard fold (updateFun over the
    shard's bucket), an ``all_gather`` of the partial summaries over the mesh
    axis (riding ICI), and the combine fold — replacing the reference's
    keyBy -> per-partition windowed fold -> timeWindowAll network pipeline
    (SummaryBulkAggregation.java:76-83) with collectives.

    The combine strategy (flat vs tree) comes from the descriptor class
    itself (``_fold_partials``), exactly as in the simulated runtime; with
    one all_gather the communication is identical either way (ICI collectives
    are already ring/tree structured), only the local combine order changes.
    Shards whose bucket is empty are excluded from the combine by masking —
    matching the simulated runtime, which skips empty partitions, so
    descriptors whose initial state is not a combine identity still agree.

    The running cross-window merge stays on device, replicated over the mesh.
    """

    def __init__(self, agg: SummaryAggregation, mesh=None):
        from gelly_streaming_tpu.parallel import mesh as mesh_mod

        self.agg = agg
        self.mesh = mesh if mesh is not None else mesh_mod.make_mesh()
        self._axis = mesh_mod.SHARD_AXIS
        self._step_cache = {}

    @property
    def num_shards(self) -> int:
        return self.mesh.devices.size

    def _combine_over_mesh(self, cfg: StreamConfig):
        """``(state, has_data) -> state``: reduce every shard's partial into
        the same replicated combined state, inside shard_map.

        Uses the descriptor's collective combine (``mesh_combine_states`` —
        log-depth XLA collectives over ICI) when it supplies one, else
        all_gather + the descriptor's combine strategy with empty shards
        masked out (descriptors whose initial state is not a combine
        identity must not see initial_state partials — the simulated runtime
        skips empty partitions the same way)."""
        agg, axis, n = self.agg, self._axis, self.num_shards
        collective = agg.mesh_combine_states(cfg, axis)
        if collective is not None:
            return collective

        def masked_combine(a, b):
            """Combine (state, valid) pairs, ignoring empty-shard partials."""
            sa, va = a
            sb, vb = b
            merged = agg.combine(sa, sb)
            both = va & vb
            state = jax.tree.map(
                lambda m, x, y: jnp.where(both, m, jnp.where(va, x, y)),
                merged,
                sa,
                sb,
            )
            return state, va | vb

        def gather_combine(state, has_data):
            gathered = jax.tree.map(
                lambda a: jax.lax.all_gather(a, axis), state  # gather-ok: replicated fallback combine — the equivalence oracle for the sharded plane
            )
            has = jax.lax.all_gather(has_data, axis)  # gather-ok: replicated fallback combine — the equivalence oracle for the sharded plane
            parts = [
                (jax.tree.map(lambda g: g[i], gathered), has[i])
                for i in range(n)
            ]
            acc, _ = agg._fold_partials(
                parts, masked_combine, agg._tree_fanin(cfg)
            )
            return acc

        return gather_combine

    def _shard_fold_combine(self, cfg: StreamConfig):
        """The shared in-shard_map tail: fold this shard's bucket with
        updateFun, then reduce the partials over the mesh axis."""
        agg = self.agg
        combine = self._combine_over_mesh(cfg)

        def fold_combine(src, dst, val, mask):
            state = agg.initial_state(cfg)
            state = agg.update(state, src, dst, val, mask)
            return combine(state, jnp.any(mask))

        return fold_combine

    def _pane_step(self, cfg: StreamConfig, cap: int, has_val: bool):
        """Compiled sharded fold+combine for panes bucketed at capacity cap
        (raw-array ingest: panes with edge values)."""
        # fan-in is baked into the compiled combine tree -> part of the key
        key = (cfg, cap, has_val, self.agg._tree_fanin(cfg))
        if key in self._step_cache:
            return self._step_cache[key]
        from jax.sharding import PartitionSpec as P

        from gelly_streaming_tpu.parallel.mesh import shard_map

        fold_combine = self._shard_fold_combine(cfg)

        def step(src, dst, val, mask):
            # [1, cap] per shard inside shard_map: fold this shard's bucket
            return fold_combine(
                src[0],
                dst[0],
                None if val is None else jax.tree.map(lambda a: a[0], val),
                mask[0],
            )

        spec = P(self._axis)
        val_spec = spec if has_val else None
        fn = jax.jit(  # graft: disable=RAWJIT — keyed per-mesh in self._step_cache; a Mesh is not a stable process-global cache key
            shard_map(
                step,
                mesh=self.mesh,
                in_specs=(spec, spec, val_spec, spec),
                out_specs=P(),
            )
        )
        self._step_cache[key] = fn
        return fn

    def _pane_step_wire(self, cfg: StreamConfig, cap: int, width):
        """Compiled sharded fold+combine consuming PACKED per-shard wire rows.

        The value-less fast form (VERDICT r2 missing #3): each shard receives
        its bucket as a wire-format byte row + a fill count, unpacks on
        device (the byte combines fuse into the fold), and runs the same
        gather+combine tail as the raw path — the sharded analog of the
        single-chip `_wire_fused_step`, so the mesh plane rides the same
        optimized ingest the single-device path does.
        """
        key = (cfg, cap, str(width), self.agg._tree_fanin(cfg), "wire")
        if key in self._step_cache:
            return self._step_cache[key]
        from jax.sharding import PartitionSpec as P

        from gelly_streaming_tpu.io import wire
        from gelly_streaming_tpu.parallel.mesh import shard_map

        fold_combine = self._shard_fold_combine(cfg)

        def step(rows, counts):
            src, dst = wire.unpack_edges(rows[0], cap, width)
            mask = jnp.arange(cap, dtype=jnp.int32) < counts[0]
            return fold_combine(src, dst, None, mask)

        spec = P(self._axis)
        fn = jax.jit(  # graft: disable=RAWJIT — keyed per-mesh in self._step_cache; a Mesh is not a stable process-global cache key
            shard_map(
                step,
                mesh=self.mesh,
                in_specs=(spec, spec),
                out_specs=P(),
            )
        )
        self._step_cache[key] = fn
        return fn

    # -- sharded streaming wire fold (the mesh form of the single-chip
    # packed-wire fast path, VERDICT r3 weak #3) ------------------------------

    def _wire_stream_fns(self, cfg: StreamConfig, stages, row_len: int, width):
        """Compiled (step, finish) pair for the sharded streaming fold.

        ``step``: donated per-shard carry (stage states, summary, touched) +
        one [S, nbytes] group of packed wire rows with [S] fill counts ->
        next carry.  Each shard unpacks ITS row on device and folds it into
        its local partial — no collectives per micro-batch.  ``finish``: one
        collective merge of the per-shard partials into the replicated
        combined state (the descriptor's mesh_combine_states when supplied,
        else all_gather + masked combine fold).  This is the sharded analog
        of `_wire_fused_step`: streaming donated-carry fold per micro-batch,
        cross-shard communication only at window close
        (SummaryBulkAggregation.java:76-83's per-partition fold, with the
        timeWindowAll funnel replaced by a collective).
        """
        key = (stages, cfg, row_len, str(width), "stream-wire")
        if key in self._step_cache:
            return self._step_cache[key]
        from jax.sharding import PartitionSpec as P

        from gelly_streaming_tpu.core.types import EdgeBatch
        from gelly_streaming_tpu.io import wire
        from gelly_streaming_tpu.parallel.mesh import shard_map

        agg = self.agg
        combine = self._combine_over_mesh(cfg)

        def strip(t):
            return jax.tree.map(lambda a: a[0], t)

        def lift(t):
            return jax.tree.map(lambda a: a[None], t)

        def step(carry, rows, counts):
            states, summary, touched = carry
            s, d = wire.unpack_edges(rows[0], row_len, width)
            mask = jnp.arange(row_len, dtype=jnp.int32) < counts[0]
            b = EdgeBatch(src=s, dst=d, mask=mask)
            out_states = []
            for stage, st in zip(stages, strip(states)):
                st, b = stage.apply(st, b)
                out_states.append(st)
            summary2 = agg.update(strip(summary), b.src, b.dst, b.val, b.mask)
            return (
                lift(tuple(out_states)),
                lift(summary2),
                touched | jnp.any(b.mask)[None],
            )

        def finish(carry):
            _, summary, touched = carry
            return combine(strip(summary), touched[0])

        spec = P(self._axis)
        entry = (
            jax.jit(  # graft: disable=RAWJIT — keyed per-mesh in self._step_cache; a Mesh is not a stable process-global cache key
                shard_map(
                    step,
                    mesh=self.mesh,
                    in_specs=(spec, spec, spec),
                    out_specs=spec,
                ),
                donate_argnums=0,
            ),
            jax.jit(  # graft: disable=RAWJIT — keyed per-mesh in self._step_cache; a Mesh is not a stable process-global cache key
                shard_map(
                    finish, mesh=self.mesh, in_specs=(spec,), out_specs=P()
                )
            ),
        )
        self._step_cache[key] = entry
        return entry

    @staticmethod
    def _pack_padded_row(s, d, row_len: int, width):
        """Pack a (possibly short) edge row to ``row_len``, returning
        (buffer, fill count).  Fixed-width pads keep position, so a count
        prefix selects the real edges; EF40 sorts, so pads carry the maximal
        id pair and sort to the END (same invariant as `_pack_pane_wire`)."""
        from gelly_streaming_tpu.io import wire

        k = len(s)
        if k == row_len:
            return wire.pack_edges(s, d, width), k
        pad_id = width[1] - 1 if isinstance(width, tuple) else 0
        ps = np.full((row_len,), pad_id, np.int32)
        pd = np.full((row_len,), pad_id, np.int32)
        ps[:k] = s
        pd[:k] = d
        return wire.pack_edges(ps, pd, width), k

    def _wire_mesh_plan(self, stream):
        """Resolve a wire-backed stream into (row(i), n_rows, row_len, width,
        total_edges): a linearized sequence of per-shard rows, grouped S at a
        time by the caller.  Replay buffers (already packed at the stream's
        batch) round-robin whole rows; raw arrays split contiguously at
        batch/S so a group folds one batch."""
        cfg = stream.cfg
        S = self.num_shards
        packed = getattr(stream, "_wire_packed", None)
        if packed is not None:
            bufs, batch, width, tail_pair = packed
            row_len = batch
            n_rows = len(bufs) + (1 if tail_pair else 0)
            total = len(bufs) * batch + (len(tail_pair[0]) if tail_pair else 0)

            def row(i):
                if i < len(bufs):
                    return bufs[i], batch
                return self._pack_padded_row(
                    np.ascontiguousarray(tail_pair[0], np.int32),
                    np.ascontiguousarray(tail_pair[1], np.int32),
                    row_len,
                    width,
                )

            return row, n_rows, row_len, width, total
        src, dst, batch = stream._wire_arrays
        total = len(src)
        row_len = max(1, min(batch, max(total, 1)) // S)
        width = self.agg._wire_width(cfg, row_len)
        n_rows = -(-total // row_len) if total else 0
        binned, _compress = self.agg._binned_modes(cfg)
        if binned and isinstance(width, tuple):
            binned = False  # EF40 regroups by src itself; skip the dst sort

        def row(i):
            s_b = src[i * row_len : (i + 1) * row_len]
            d_b = dst[i * row_len : (i + 1) * row_len]
            if binned:
                # destination-binned mesh rows: each shard's streaming fold
                # scatters a sorted segment (order-free folds only — the
                # multiset per row is unchanged, so the stream-end collective
                # merge is bit-identical)
                from gelly_streaming_tpu.io import wire as wire_mod

                s_b, d_b = wire_mod.sort_edges_binned(
                    s_b, d_b, cfg.vertex_capacity, record_stats=True
                )
            return self._pack_padded_row(s_b, d_b, row_len, width)

        return row, n_rows, row_len, width, total

    def _wire_mesh_checkpoint_like(
        self, stream, row_len: int, rows: Optional[int] = None
    ):
        """Snapshot layout; ``rows`` overrides the leading axis (the number
        of shard rows held: S for single-process saves, this process's
        addressable count for per-process saves)."""
        cfg = stream.cfg
        n = self.num_shards if rows is None else rows

        def stack(tree):
            return jax.tree.map(
                lambda a: np.broadcast_to(
                    np.asarray(a), (n,) + np.shape(np.asarray(a))
                ).copy(),
                tree,
            )

        like = {
            "summary": stack(self.agg.initial_state(cfg)),
            "stages": stack(tuple(st.init(cfg) for st in stream._stages)),
            "touched": np.zeros((n,), bool),
            "next_group": np.zeros((), np.int64),
            "row_len": np.zeros((), np.int64),
            "shards": np.zeros((), np.int64),
            "done": np.zeros((), bool),
        }
        if rows is not None:
            like["rows"] = np.zeros((n,), np.int64)
        return like

    def _local_rows(self):
        """Shard rows this process addresses (row r lives on device r)."""
        return sorted(
            r
            for r, d in enumerate(self.mesh.devices.flat)
            if d.process_index == jax.process_index()
        )

    def _wire_mesh_restore_per_process(
        self, stream, checkpoint_path: str, row_len: int, sharding
    ):
        """Per-process restore for multi-process meshes.

        Each process loads only its own file; validity, stream position, and
        row ownership must AGREE across processes (one process_allgather
        round) or all start fresh together — a split restore would deadlock
        the collective finish.  Returns (carry | None, start_group, done).
        """
        from jax.experimental import multihost_utils

        from gelly_streaming_tpu.utils.checkpoint import (
            checkpoint_exists,
            load_state,
            per_process_file,
        )

        rows = self._local_rows()
        k = len(rows)
        path = per_process_file(checkpoint_path)
        snap = None
        if checkpoint_exists(path):
            like = self._wire_mesh_checkpoint_like(stream, row_len, rows=k)
            try:
                snap = load_state(path, like)
            except ValueError:
                snap = None
        if snap is not None and (
            int(snap["row_len"]) != row_len
            or int(snap["shards"]) != self.num_shards
        ):
            # same loud failure as the single-process branch: a changed
            # batch/shard geometry would misalign the stream position, and
            # silently re-folding from group 0 would discard the
            # checkpointed progress with no signal.  Every process computes
            # this from its own file + static config, so all raise together.
            raise ValueError(
                f"mesh wire checkpoint was written at row_len "
                f"{int(snap['row_len'])} x {int(snap['shards'])} shards; "
                f"resuming with {row_len} x {self.num_shards} would "
                "misalign the stream position"
            )
        ok = (
            snap is not None
            and [int(r) for r in snap["rows"]] == rows
        )
        pos = int(snap["next_group"]) if ok else -1
        done = bool(snap["done"]) if ok else False
        agree = multihost_utils.process_allgather(
            np.array([int(ok), pos, int(done)], np.int64)
        )
        if not (
            agree[:, 0].all()
            and (agree[:, 1] == agree[0, 1]).all()
            and (agree[:, 2] == agree[0, 2]).all()
        ):
            return None, 0, False
        row_to_i = {r: i for i, r in enumerate(rows)}
        S = self.num_shards

        def build(local):
            def cb(index):
                r = int(index[0].start or 0)
                return local[row_to_i[r]][None]

            return jax.make_array_from_callback(
                (S,) + local.shape[1:], sharding, cb
            )

        carry = jax.tree.map(
            build, (snap["stages"], snap["summary"], snap["touched"])
        )
        return carry, int(agree[0, 1]), bool(agree[0, 2])

    def _wire_mesh_save_per_process(
        self, checkpoint_path: str, carry, pos: int, done: bool, row_len: int
    ) -> None:
        """Each process saves ONLY its addressable shard rows of the carry."""
        from gelly_streaming_tpu.utils.checkpoint import (
            per_process_file,
            save_state,
        )

        rows = self._local_rows()

        def local(leaf):
            shards = sorted(
                leaf.addressable_shards, key=lambda s: s.index[0].start
            )
            return np.concatenate([np.asarray(s.data) for s in shards], axis=0)

        host = jax.tree.map(local, carry)
        save_state(
            per_process_file(checkpoint_path),
            {
                "summary": host[1],
                "stages": host[0],
                "touched": host[2],
                "rows": np.array(rows, np.int64),
                "next_group": np.full((), pos, np.int64),
                "row_len": np.full((), row_len, np.int64),
                "shards": np.full((), self.num_shards, np.int64),
                "done": np.full((), done, bool),
            },
        )

    # -- owner-sharded summary plane (core/sharded_state.py, ISSUE 4) --------

    def _sharded_spec(self, cfg: StreamConfig):
        """The descriptor's ShardedStateSpec when the owner-sharded plane is
        enabled and usable here.  Multi-process meshes stay on the
        replicated plane (their per-process snapshot machinery predates the
        block layout), as does anything with ``cfg.sharded_state`` off."""
        from gelly_streaming_tpu.core.sharded_state import resolve_sharded_state

        if jax.process_count() > 1 or not resolve_sharded_state(cfg):
            return None
        return self.agg.sharded_state_spec(cfg)

    def _shard_ctx(self, cfg: StreamConfig, spec, interval_edges: int):
        """Static per-step context; the delta capacity pow2-buckets the
        spec's changed-row bound for one exchange interval."""
        from gelly_streaming_tpu.core.sharded_state import ShardContext
        from gelly_streaming_tpu.parallel import routing

        cap = routing.delta_capacity(
            cfg.vertex_capacity,
            self.num_shards,
            spec.delta_bound(cfg, interval_edges),
        )
        return ShardContext(
            cfg=cfg,
            num_shards=self.num_shards,
            axis_name=self._axis,
            delta_cap=cap,
        )

    def _sharded_key(self, spec, cfg: StreamConfig, *extra):
        """Process-stable executable-cache key for a sharded mesh kernel.

        Unlike the legacy ``_step_cache`` (per-runner, raw jax.jit — invisible
        to the retrace guard), sharded kernels live in the process-global
        compile cache: ``mesh_cache_key`` makes re-created runners over the
        same devices resolve to the same executables, and the bench's
        ``cache_recompiles`` attestation covers this plane too.
        """
        from gelly_streaming_tpu.parallel.mesh import mesh_cache_key

        return (
            type(spec),
            self.agg.cache_token,
            mesh_cache_key(self.mesh),
            cfg,
        ) + extra

    def _record_exchange_stats(self, profile: dict, stats_host) -> None:
        """Fold one exchange's [S, 3] device-counter download into the
        process comms metrics (called at exchange boundaries only)."""
        from gelly_streaming_tpu.utils import metrics

        stats = np.asarray(stats_host)
        rounds = int(stats[:, 0].max())
        metrics.comms_add("comms_exchange_rounds", rounds)
        metrics.comms_high_water(
            "comms_delta_occupancy_hwm", int(stats[:, 1].max())
        )
        metrics.comms_add("comms_delta_spilled", int(stats[:, 2].sum()))
        metrics.comms_add(
            "comms_bytes_exchange", rounds * profile["round_nbytes"]
        )

    def _sharded_blocks_sharding(self):
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        return NamedSharding(self.mesh, P(self._axis))

    def _initial_blocks(self, spec, cfg: StreamConfig):
        return jax.device_put(
            spec.initial_shard_state(cfg, self.num_shards),
            self._sharded_blocks_sharding(),
        )

    def _sharded_wire_fns(self, cfg: StreamConfig, spec, stages, row_len, width, ctx):
        """(exchange, gather) pair for the sharded wire plane.

        ``exchange``: donated (carry, blocks) -> (carry', blocks', stats) —
        folds the per-shard local partial into the owner blocks through the
        spec's delta exchange and resets the local scratch (the carry keeps
        streaming through the SAME per-dispatch step as the replicated
        plane, so the hot path pays zero extra collectives).  ``gather``:
        blocks -> the replicated summary, emit/snapshot boundaries only.
        """
        from jax.sharding import PartitionSpec as P

        from gelly_streaming_tpu.parallel.mesh import shard_map

        agg = self.agg
        spec_ = P(self._axis)

        def strip(t):
            return jax.tree.map(lambda a: a[0], t)

        def lift(t):
            return jax.tree.map(lambda a: a[None], t)

        def make_exchange():
            def ex(carry, blocks):
                states, summary, touched = carry
                blocks2, stats = spec.exchange(strip(summary), strip(blocks), ctx)
                fresh = agg.initial_state(cfg)
                stats_row = jnp.stack(
                    [stats.rounds, stats.delta_hwm, stats.spilled]
                ).astype(jnp.int32)
                return (
                    (states, lift(fresh), touched),
                    lift(blocks2),
                    stats_row[None],
                )

            return shard_map(
                ex,
                mesh=self.mesh,
                in_specs=(spec_, spec_),
                out_specs=(spec_, spec_, spec_),
            )

        def make_gather():
            def g(blocks):
                return spec.gather_state(strip(blocks), ctx)  # gather-ok: emit/snapshot boundary — the lazy replicated view

            return shard_map(
                g, mesh=self.mesh, in_specs=(spec_,), out_specs=P()
            )

        exchange = compile_cache.cached_jit(
            ("mesh_sharded_wire_exchange",)
            + self._sharded_key(spec, cfg, stages, row_len, str(width), ctx.delta_cap),
            make_exchange,
            donate_argnums=(0, 1),
        )
        gather = compile_cache.cached_jit(
            ("mesh_sharded_gather",)
            + self._sharded_key(spec, cfg, ctx.delta_cap),
            make_gather,
        )
        return exchange, gather

    def _wire_sharded_checkpoint_like(self, stream, spec, row_len: int):
        """Sharded wire snapshot layout: O(C/S) owner blocks per shard (the
        S-fold download shrink vs the replicated carry), stage states, and
        the group position — same geometry validation as the replicated
        layout."""
        cfg = stream.cfg
        like = self._wire_mesh_checkpoint_like(stream, row_len)
        del like["summary"], like["touched"]
        like["blocks"] = jax.tree.map(
            np.asarray, spec.initial_shard_state(cfg, self.num_shards)
        )
        return like

    def _wire_records_sharded(
        self,
        stream,
        spec,
        checkpoint_path: Optional[str],
        restore: bool,
    ) -> Iterator[tuple]:
        """Owner-sharded form of ``wire_records``.

        Per dispatch the stream rides the IDENTICAL donated-carry step as
        the replicated plane (local folds, no collectives); at snapshot
        boundaries and stream end the local partials delta-exchange into the
        O(C/S) owner blocks ("changed keys since last exchange"), and the
        replicated view is gathered lazily only to emit.  Snapshots download
        blocks — O(C) total across the mesh instead of O(C*S).
        """
        from gelly_streaming_tpu.io import wire as wire_mod
        from gelly_streaming_tpu.utils import metrics
        from gelly_streaming_tpu.utils.checkpoint import (
            checkpoint_exists,
            load_state,
            save_state,
        )

        cfg = stream.cfg
        agg = self.agg
        S = self.num_shards
        row, n_rows, row_len, width, total_edges = self._wire_mesh_plan(stream)
        n_groups = -(-n_rows // S) if n_rows else 0
        step, _ = self._wire_stream_fns(cfg, stream._stages, row_len, width)
        every_groups = (
            max(1, cfg.wire_checkpoint_batches // S)
            if cfg.wire_checkpoint_batches
            else 0
        )
        # mid-stream exchanges only happen at snapshot boundaries, so the
        # delta buffers must be sized for the WHOLE stream when there is no
        # checkpoint path — an interval-sized cap there would force spill
        # retries and miss the dense-slab switch
        interval_edges = (
            (every_groups if checkpoint_path and every_groups else max(n_groups, 1))
            * S
            * row_len
        )
        ctx = self._shard_ctx(cfg, spec, interval_edges)
        profile = spec.comm_profile(cfg, ctx)
        exchange, gather = self._sharded_wire_fns(
            cfg, spec, stream._stages, row_len, width, ctx
        )
        sharding = self._sharded_blocks_sharding()

        start_group = 0
        blocks = None
        done_blocks = None
        if checkpoint_path and restore and checkpoint_exists(checkpoint_path):
            like = self._wire_sharded_checkpoint_like(stream, spec, row_len)
            try:
                snap = load_state(checkpoint_path, like)
            except ValueError:
                snap = None  # legacy/replicated/mismatched layout: fresh
            if snap is not None:
                if int(snap["row_len"]) != row_len or int(snap["shards"]) != S:
                    raise ValueError(
                        f"mesh wire checkpoint was written at row_len "
                        f"{int(snap['row_len'])} x {int(snap['shards'])} "
                        f"shards; resuming with {row_len} x {S} would "
                        "misalign the stream position"
                    )
                if bool(snap["done"]):
                    done_blocks = snap["blocks"]
                else:
                    start_group = int(snap["next_group"])
                    blocks = jax.device_put(snap["blocks"], sharding)
                    carry_stages = snap["stages"]
        if done_blocks is not None:
            # stream fully folded before the crash: re-emit from the blocks
            # (at-least-once) without re-folding
            metrics.comms_add("comms_bytes_gather", profile["gather_nbytes"])
            out = agg.transform(gather(jax.device_put(done_blocks, sharding)))
            yield out if isinstance(out, tuple) else (out,)
            return
        if blocks is None:
            blocks = self._initial_blocks(spec, cfg)
            carry_stages = None
        like_carry = self._wire_mesh_checkpoint_like(stream, row_len)
        carry = jax.device_put(
            (
                carry_stages if carry_stages is not None else like_carry["stages"],
                like_carry["summary"],
                like_carry["touched"],
            ),
            sharding,
        )

        def save(pos: int, done: bool, blocks_now, carry_now) -> None:
            host_blocks = jax.tree.map(np.asarray, blocks_now)
            host_stages = jax.tree.map(np.asarray, carry_now[0])
            save_state(
                checkpoint_path,
                {
                    "blocks": host_blocks,
                    "stages": host_stages,
                    "next_group": np.full((), pos, np.int64),
                    "row_len": np.full((), row_len, np.int64),
                    "shards": np.full((), S, np.int64),
                    "done": np.full((), done, bool),
                },
            )

        def prepare(g: int):
            # zeros, not empty: BDV replay rows are variable-size payloads
            # padded into the max-width arena (trailing zeros decode as
            # dropped empty varint groups); fixed-width rows fill exactly
            rows = np.zeros((S, wire_mod.wire_nbytes(row_len, width)), np.uint8)
            counts = np.zeros((S,), np.int32)
            for s in range(S):
                i = g * S + s
                if i < n_rows:
                    buf, counts[s] = row(i)
                else:
                    buf, _ = self._pack_padded_row(
                        np.empty((0,), np.int32),
                        np.empty((0,), np.int32),
                        row_len,
                        width,
                    )
                rows[s, : buf.nbytes] = buf
            metrics.wire_record_batch(S, int(counts.sum()), rows.nbytes)
            return g, (rows, counts)

        since_snap = 0
        with wire_mod.Prefetcher(
            range(start_group, n_groups),
            prepare,
            device=sharding,
            depth=cfg.prefetch_depth,
        ) as pf:
            for g, dev in pf:
                rows_d, counts_d = dev
                carry = step(carry, rows_d, counts_d)
                metrics.comms_add("comms_dispatches", 1)
                since_snap += 1
                if checkpoint_path and every_groups and since_snap >= every_groups:
                    # exchange at the snapshot boundary: local partials fold
                    # into the owner blocks (delta buffers), scratch resets
                    carry, blocks, stats = exchange(carry, blocks)
                    self._record_exchange_stats(profile, stats)
                    save(g + 1, False, blocks, carry)
                    since_snap = 0
        if total_edges == 0:
            return
        carry, blocks, stats = exchange(carry, blocks)
        self._record_exchange_stats(profile, stats)
        metrics.comms_add("comms_bytes_gather", profile["gather_nbytes"])
        out = agg.transform(gather(blocks))
        # emit BEFORE the final snapshot (at-least-once emission)
        yield out if isinstance(out, tuple) else (out,)
        if checkpoint_path:
            save(n_groups, True, blocks, carry)

    def wire_records(
        self,
        stream,
        checkpoint_path: Optional[str] = None,
        restore: bool = True,
    ) -> Iterator[tuple]:
        """Sharded STREAMING fold over a wire-backed (untimed) stream.

        Per micro-batch group, S packed rows ship straight to their owner
        shards (row-sharded device_put on the prefetch thread) and fold into
        donated per-shard carries — the stream is folded ONCE, batch by
        batch, exactly like the single-chip wire fast path; the only
        cross-shard communication is the collective merge at stream end.
        Positional checkpoints snapshot the carry plus the group position
        every ``cfg.wire_checkpoint_batches`` rows (synchronously — the
        download is one carry per interval).  Single-process meshes save
        the whole [S, ...] carry to one file; MULTI-PROCESS meshes save per
        process — each host writes only its addressable shard rows
        (`utils.checkpoint.per_process_file`), and restore requires every
        host to agree on validity, position, and row ownership (one
        process_allgather round) or all start fresh together.
        """
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        from gelly_streaming_tpu.io import wire as wire_mod
        from gelly_streaming_tpu.utils import metrics
        from gelly_streaming_tpu.utils.checkpoint import (
            checkpoint_exists,
            load_state,
            save_state,
        )

        cfg = stream.cfg
        spec = self._sharded_spec(cfg)
        if spec is not None:
            # the default: owner-sharded O(C/S) summary blocks with
            # delta-compressed reconciliation at snapshot/stream-end
            # boundaries (core/sharded_state.py)
            yield from self._wire_records_sharded(
                stream, spec, checkpoint_path, restore
            )
            return
        agg = self.agg
        S = self.num_shards
        multi = jax.process_count() > 1
        row, n_rows, row_len, width, total_edges = self._wire_mesh_plan(stream)
        n_groups = -(-n_rows // S) if n_rows else 0
        step, finish = self._wire_stream_fns(
            cfg, stream._stages, row_len, width
        )

        sharding = NamedSharding(self.mesh, P(self._axis))
        start_group = 0
        carry = None
        carry_host = None
        like = None
        if checkpoint_path and restore and multi:
            restored, start_group, was_done = (
                self._wire_mesh_restore_per_process(
                    stream, checkpoint_path, row_len, sharding
                )
            )
            if was_done and restored is not None:
                # stream fully folded before the crash: re-run only the
                # collective finish and re-emit (at-least-once)
                out = agg.transform(finish(restored))
                yield out if isinstance(out, tuple) else (out,)
                return
            carry = restored
        elif checkpoint_path and restore and checkpoint_exists(checkpoint_path):
            like = self._wire_mesh_checkpoint_like(stream, row_len)
            try:
                snap = load_state(checkpoint_path, like)
            except ValueError:
                snap = None  # legacy/mismatched layout: start fresh
            if snap is not None:
                if int(snap["row_len"]) != row_len or int(snap["shards"]) != S:
                    raise ValueError(
                        f"mesh wire checkpoint was written at row_len "
                        f"{int(snap['row_len'])} x {int(snap['shards'])} "
                        f"shards; resuming with {row_len} x {S} would "
                        "misalign the stream position"
                    )
                if bool(snap["done"]):
                    # stream fully folded before the crash: re-run only the
                    # collective finish and re-emit (at-least-once)
                    out = agg.transform(self._finish_host(snap, finish))
                    yield out if isinstance(out, tuple) else (out,)
                    return
                start_group = int(snap["next_group"])
                carry_host = (snap["stages"], snap["summary"], snap["touched"])

        if carry is None:
            if carry_host is None:
                like = like or self._wire_mesh_checkpoint_like(stream, row_len)
                carry_host = (like["stages"], like["summary"], like["touched"])
            carry = jax.device_put(carry_host, sharding)

        every_groups = (
            max(1, cfg.wire_checkpoint_batches // S)
            if cfg.wire_checkpoint_batches
            else 0
        )

        def save(pos: int, done: bool, carry_now):
            if multi:
                self._wire_mesh_save_per_process(
                    checkpoint_path, carry_now, pos, done, row_len
                )
                return
            host = jax.tree.map(np.asarray, carry_now)
            save_state(
                checkpoint_path,
                {
                    "summary": host[1],
                    "stages": host[0],
                    "touched": host[2],
                    "next_group": np.full((), pos, np.int64),
                    "row_len": np.full((), row_len, np.int64),
                    "shards": np.full((), S, np.int64),
                    "done": np.full((), done, bool),
                },
            )

        def prepare(g: int):
            # zeros, not empty: BDV replay rows are variable-size payloads
            # padded into the max-width arena (trailing zeros decode as
            # dropped empty varint groups); fixed-width rows fill exactly
            rows = np.zeros((S, wire_mod.wire_nbytes(row_len, width)), np.uint8)
            counts = np.zeros((S,), np.int32)
            for s in range(S):
                i = g * S + s
                if i < n_rows:
                    buf, counts[s] = row(i)
                else:
                    buf, _ = self._pack_padded_row(
                        np.empty((0,), np.int32),
                        np.empty((0,), np.int32),
                        row_len,
                        width,
                    )
                rows[s, : buf.nbytes] = buf
            metrics.wire_record_batch(S, int(counts.sum()), rows.nbytes)
            return g, (rows, counts)

        since_snap = 0
        with wire_mod.Prefetcher(
            range(start_group, n_groups),
            prepare,
            device=sharding,
            depth=cfg.prefetch_depth,
        ) as pf:
            for g, dev in pf:
                rows_d, counts_d = dev
                carry = step(carry, rows_d, counts_d)
                since_snap += 1
                if checkpoint_path and every_groups and since_snap >= every_groups:
                    save(g + 1, False, carry)
                    since_snap = 0
        if total_edges == 0:
            return
        final = finish(carry)
        out = agg.transform(final)
        # emit BEFORE the final snapshot (at-least-once emission, as in the
        # single-chip wire path)
        yield out if isinstance(out, tuple) else (out,)
        if checkpoint_path:
            save(n_groups, True, carry)

    def _finish_host(self, snap, finish):
        """Re-run the collective finish over a restored done-carry (the
        at-least-once re-emission after a crash between emit and final
        snapshot)."""
        from jax.sharding import NamedSharding
        from jax.sharding import PartitionSpec as P

        carry = jax.device_put(
            (snap["stages"], snap["summary"], snap["touched"]),
            NamedSharding(self.mesh, P(self._axis)),
        )
        return finish(carry)

    def _restored_position(self, cfg, checkpoint_path, restore):
        """(last folded window id, global pane done) — shared reader on the
        descriptor (SummaryAggregation._restored_position); the merge loop
        remains the source of truth for folding position."""
        return self.agg._restored_position(cfg, checkpoint_path, restore)

    def _pane_cap(self, total: int) -> int:
        from gelly_streaming_tpu.parallel.routing import pow2_bucket

        per = -(-max(total, 1) // self.num_shards)  # ceil, >= 1
        return pow2_bucket(per)  # the shared shape-bucketing rule

    def _bucket_pane(self, pane: WindowPane):
        """Round-robin the pane's edges into [n_shards, cap] host arrays."""
        n = self.num_shards
        total = len(pane.src)
        cap = self._pane_cap(total)
        src = np.zeros((n, cap), np.int32)
        dst = np.zeros((n, cap), np.int32)
        mask = np.zeros((n, cap), bool)
        val = None
        if pane.val is not None:
            val = jax.tree.map(
                lambda a: np.zeros((n, cap) + a.shape[1:], a.dtype), pane.val
            )
        for shard in range(n):
            idx = np.arange(shard, total, n)
            k = len(idx)
            src[shard, :k] = pane.src[idx]
            dst[shard, :k] = pane.dst[idx]
            mask[shard, :k] = True
            if val is not None:

                def fill(buf, a):
                    buf[shard, :k] = a[idx]
                    return buf

                val = jax.tree.map(fill, val, pane.val)
        return src, dst, val, mask

    def _pack_pane_wire(self, pane: WindowPane, width):
        """Round-robin + pack the pane into per-shard wire rows.

        Returns ([S, nbytes] uint8 rows, [S] int32 fill counts, cap).  The
        pad region packs as zero-id edges; the device step masks them out by
        count, so the transfer volume is the packed wire size — the same
        bytes-per-edge economy as the single-chip fast path, replacing the
        raw [S, cap] int32 uploads (VERDICT r2 missing #3).
        """
        from gelly_streaming_tpu.io import wire

        n = self.num_shards
        total = len(pane.src)
        cap = self._pane_cap(total)
        rows = np.zeros((n, wire.wire_nbytes(cap, width)), np.uint8)
        counts = np.zeros((n,), np.int32)
        s = np.zeros((cap,), np.int32)
        d = np.zeros((cap,), np.int32)
        # EF40 SORTS the bucket, so the pad edges must sort to the END for
        # the count-prefix mask to select exactly the real edges: pad with
        # the maximal id pair (ties with a real max-pair edge are identical
        # pairs, so any count-prefix is the same multiset).  Fixed-width
        # encodings preserve order; zero padding is fine there.
        pad_id = width[1] - 1 if isinstance(width, tuple) else 0
        for shard in range(n):
            idx = np.arange(shard, total, n)
            k = len(idx)
            s[:k] = pane.src[idx]
            d[:k] = pane.dst[idx]
            s[k:] = pad_id
            d[k:] = pad_id
            rows[shard] = wire.pack_edges(s, d, width)
            counts[shard] = k
        return rows, counts, cap

    def _pane_step_sharded(self, cfg: StreamConfig, spec, cap: int, kind, ctx):
        """Compiled sharded pane fold: route -> fold -> exchange -> gather in
        ONE dispatch against the persistent owner blocks.

        ``kind`` is ("wire", width) for packed value-less rows or
        ("raw", has_val) for bucket arrays.  The local fold runs the
        descriptor's ordinary updateFun on a transient scratch; the spec's
        delta exchange reconciles it into the O(C/S) blocks; the replicated
        summary comes out of the emit-boundary gather — there is no
        all_gather of full per-shard partials anywhere on this plane.
        """
        from jax.sharding import PartitionSpec as P

        from gelly_streaming_tpu.io import wire
        from gelly_streaming_tpu.parallel.mesh import shard_map

        agg = self.agg
        spec_p = P(self._axis)

        def strip(t):
            return jax.tree.map(lambda a: a[0], t)

        def lift(t):
            return jax.tree.map(lambda a: a[None], t)

        def tail(blocks, src, dst, val, mask):
            local = agg.update(agg.initial_state(cfg), src, dst, val, mask)
            blocks2, stats = spec.exchange(local, strip(blocks), ctx)
            summary = spec.gather_state(blocks2, ctx)  # gather-ok: emit — every pane close is an emission boundary on the windowed plane
            stats_row = jnp.stack(
                [stats.rounds, stats.delta_hwm, stats.spilled]
            ).astype(jnp.int32)
            return lift(blocks2), summary, stats_row[None]

        if kind[0] == "wire":
            width = kind[1]

            def make():
                def step(blocks, rows, counts):
                    src, dst = wire.unpack_edges(rows[0], cap, width)
                    mask = jnp.arange(cap, dtype=jnp.int32) < counts[0]
                    return tail(blocks, src, dst, None, mask)

                return shard_map(
                    step,
                    mesh=self.mesh,
                    in_specs=(spec_p, spec_p, spec_p),
                    out_specs=(spec_p, P(), spec_p),
                )

            key_tail = (cap, str(width), ctx.delta_cap, "wire")
        else:
            has_val = kind[1]

            def make():
                def step(blocks, src, dst, val, mask):
                    return tail(
                        blocks,
                        src[0],
                        dst[0],
                        None if val is None else jax.tree.map(lambda a: a[0], val),
                        mask[0],
                    )

                val_spec = spec_p if has_val else None

                return shard_map(
                    step,
                    mesh=self.mesh,
                    in_specs=(spec_p, spec_p, spec_p, val_spec, spec_p),
                    out_specs=(spec_p, P(), spec_p),
                )

            key_tail = (cap, has_val, ctx.delta_cap, "raw")

        return compile_cache.cached_jit(
            ("mesh_sharded_pane",) + self._sharded_key(spec, cfg, *key_tail),
            make,
        )

    def _run_sharded(
        self,
        stream,
        spec,
        window_ms: int,
        checkpoint_path: Optional[str],
        restore: bool,
        panes: Optional[Callable],
    ) -> OutputStream:
        """Windowed mesh plane over owner-sharded summary state.

        The persistent cross-window state is the O(C/S) block set; each
        closed pane is routed on the prefetcher's pack thread (host keyBy
        when the spec asks for it — ``spec.route_key`` — else the skew-free
        round-robin), folded + delta-exchanged + lazily gathered in one
        dispatch, and the gathered running summary rides the shared Merger
        loop (``fold_is_running``) so emission order, at-least-once
        semantics, and positional checkpoints are identical to the
        replicated plane — which stays available as the equivalence oracle
        (cfg.sharded_state=0).
        """
        from gelly_streaming_tpu.io import wire as wire_mod
        from gelly_streaming_tpu.parallel.routing import host_route
        from gelly_streaming_tpu.utils import metrics

        cfg = stream.cfg
        agg = self.agg
        S = self.num_shards
        width = agg._wire_width(cfg)
        skip_through, skip_global = self._restored_position(
            cfg, checkpoint_path, restore
        )

        binned_on, _ = agg._binned_modes(cfg)

        def prepare(pane: WindowPane):
            """Pack-thread routing + packing (keyBy off the dispatch thread):
            value-less panes become packed per-shard wire rows — owner
            buckets under ``spec.route_key``, round-robin otherwise — and
            valued panes ship raw bucket arrays.  With binned ingest on,
            the pane is destination-sorted first (order-free folds see the
            same multiset; per-shard scatters turn segment-local) and the
            keyBy bucketing itself runs on the parallel ingest pool — the
            host_route work moved into the parse/pack pass."""
            already = (0 <= pane.window_id <= skip_through) or (
                pane.window_id == -1 and skip_global
            )
            if already or len(pane.src) == 0:
                return (pane, None, None), None
            pane = agg._maybe_bin_pane(cfg, pane, width)
            if pane.val is None:
                if spec.route_key:
                    if binned_on:
                        from gelly_streaming_tpu.io import ingest as ingest_mod

                        routed = ingest_mod.parallel_host_route(
                            pane.src.astype(np.int32),
                            pane.dst.astype(np.int32),
                            S,
                            key=spec.route_key,
                            workers=cfg.ingest_workers,
                        )
                    else:
                        routed = host_route(
                            pane.src.astype(np.int32),
                            pane.dst.astype(np.int32),
                            S,
                            key=spec.route_key,
                        )
                    counts = routed.mask.sum(axis=1).astype(np.int32)
                    rows = wire_mod.pack_bucket_rows(
                        routed.src, routed.dst, counts, width
                    )
                    return (pane, ("wire", width), routed.src.shape[1]), (
                        rows,
                        counts,
                    )
                rows, counts, cap = self._pack_pane_wire(pane, width)
                return (pane, ("wire", width), cap), (rows, counts)
            if spec.route_key:
                routed = host_route(
                    pane.src.astype(np.int32),
                    pane.dst.astype(np.int32),
                    S,
                    key=spec.route_key,
                    val=pane.val,
                )
                return (pane, ("raw", True), routed.src.shape[1]), (
                    routed.src,
                    routed.dst,
                    routed.val,
                    routed.mask,
                )
            src, dst, val, mask = self._bucket_pane(pane)
            return (pane, ("raw", val is not None), src.shape[1]), (
                src,
                dst,
                val,
                mask,
            )

        def records() -> Iterator[tuple]:
            import collections as _collections

            sharding = self._sharded_blocks_sharding()
            restored = agg._restored_summary(cfg, checkpoint_path, restore)
            if restored is not None:
                blocks = jax.device_put(
                    spec.shard_summary(restored, cfg, S), sharding
                )
            else:
                blocks = self._initial_blocks(spec, cfg)
            initial = blocks if agg.transient_state else None
            pending_stats = _collections.deque()
            profiles = {}

            def drain_stats(limit: int) -> None:
                while len(pending_stats) > limit:
                    stats, profile = pending_stats.popleft()
                    self._record_exchange_stats(profile, stats)

            def fold_prepared(item):
                nonlocal blocks
                (pane, kind, cap), dev = item
                if kind is None:
                    return None
                ctx = self._shard_ctx(cfg, spec, S * cap)
                profile = profiles.get(ctx.delta_cap)
                if profile is None:
                    profile = profiles[ctx.delta_cap] = spec.comm_profile(cfg, ctx)
                if initial is not None:
                    blocks = initial  # transient descriptors reset per window
                step = self._pane_step_sharded(cfg, spec, cap, kind, ctx)
                blocks, summary, stats = step(blocks, *dev)
                metrics.comms_add("comms_dispatches", 1)
                metrics.comms_add(
                    "comms_bytes_gather", profile["gather_nbytes"]
                )
                # stats drain lags the pipeline depth so the async plane
                # never blocks on a per-pane download
                pending_stats.append((stats, profile))
                drain_stats(max(2, cfg.prefetch_depth))
                return summary

            from gelly_streaming_tpu.core.windows import stream_panes as _sp

            pane_iter = panes() if panes is not None else _sp(stream, window_ms)
            try:
                with wire_mod.Prefetcher(
                    pane_iter, prepare, device=sharding, depth=cfg.prefetch_depth
                ) as pf:
                    yield from agg._merge_loop(
                        cfg,
                        ((meta[0], (meta, dev)) for meta, dev in pf),
                        fold_prepared,
                        checkpoint_path,
                        restore,
                        unwrap=True,
                        fold_is_running=True,
                    )
            finally:
                drain_stats(0)

        return OutputStream(records)

    def run(
        self,
        stream,
        window_ms: Optional[int] = None,
        checkpoint_path: Optional[str] = None,
        restore: bool = True,
        panes: Optional[Callable] = None,
    ) -> OutputStream:
        """(transform(running_summary),) per closed window, like run().

        Shares the Merger/checkpoint loop with the simulated runtime
        (`SummaryAggregation._merge_loop`), so positional checkpoints and
        kill-and-resume work identically on the sharded data plane — the
        distributed analog of the reference's ListCheckpointed Merger
        (SummaryAggregation.java:127-135).

        ``panes`` overrides the time plane: a zero-arg callable returning a
        WindowPane iterator (zero-arg so the OutputStream stays re-runnable)
        — e.g. multi-host gated windows merged across ingest hosts
        (`parallel.multihost.merge_pane_shares`).  Without it, panes come
        from the stream's own tumbling assignment.
        """
        cfg = stream.cfg
        window_ms = window_ms or self.agg.window_ms or cfg.window_ms
        agg = self.agg
        spec = self._sharded_spec(cfg)
        if spec is not None:
            # the default windowed mesh plane: owner-sharded blocks +
            # delta exchange + lazy emission gather (core/sharded_state.py);
            # cfg.sharded_state=0 keeps the replicated oracle below
            return self._run_sharded(
                stream, spec, window_ms, checkpoint_path, restore, panes
            )
        from gelly_streaming_tpu.io import wire as wire_mod

        # value-less panes honor the configured wire encoding exactly as the
        # single-shard fast path does (incl. the order-free EF40 gate)
        width = agg._wire_width(cfg)
        skip_through, skip_global = self._restored_position(
            cfg, checkpoint_path, restore
        )

        def prepare(pane: WindowPane):
            """Background-thread pack: value-less panes become packed wire
            rows; valued panes ship raw bucket arrays.  Either way the
            device_put happens on the prefetch thread, so the transfer of
            pane k+1 overlaps pane k's sharded fold (the same
            pack/transfer/compute overlap as the single-chip fast path).
            Panes a restored checkpoint already folded skip packing — the
            merge loop would drop them unfolded anyway."""
            already = (0 <= pane.window_id <= skip_through) or (
                pane.window_id == -1 and skip_global
            )
            if already or len(pane.src) == 0:
                return (pane, None, None), None
            # destination binning (order-free folds; no-op otherwise): the
            # round-robin strided slices of a sorted pane stay sorted, so
            # each shard's fold scatter is segment-local
            pane = agg._maybe_bin_pane(cfg, pane, width)
            if pane.val is None:
                rows, counts, cap = self._pack_pane_wire(pane, width)
                return (pane, "wire", cap), (rows, counts)
            src, dst, val, mask = self._bucket_pane(pane)
            return (pane, "raw", src.shape[1]), (src, dst, val, mask)

        def fold_prepared(item):
            (pane, kind, cap), dev = item
            if kind is None:
                return None
            if kind == "wire":
                rows, counts = dev
                return self._pane_step_wire(cfg, cap, width)(rows, counts)
            src, dst, val, mask = dev
            return self._pane_step(cfg, cap, val is not None)(src, dst, val, mask)

        def records() -> Iterator[tuple]:
            from jax.sharding import NamedSharding
            from jax.sharding import PartitionSpec as P

            # every prepared buffer is [S, ...] with the shard axis leading,
            # so one row-sharded placement covers rows/counts/raw buckets —
            # each shard's bytes transfer straight to their owner device
            sharding = NamedSharding(self.mesh, P(self._axis))
            pane_iter = (
                panes() if panes is not None else stream_panes(stream, window_ms)
            )
            with wire_mod.Prefetcher(
                pane_iter, prepare, device=sharding, depth=cfg.prefetch_depth
            ) as pf:
                yield from agg._merge_loop(
                    cfg,
                    ((meta[0], (meta, dev)) for meta, dev in pf),
                    fold_prepared,
                    checkpoint_path,
                    restore,
                    unwrap=True,
                )

        return OutputStream(records)



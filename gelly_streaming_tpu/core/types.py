"""Core value types: padded COO edge micro-batches and enums.

The reference's wire type is Flink's ``Edge<K, EV>`` tuple flowing record-by-record
through a JVM dataflow (SimpleEdgeStream.java:55).  The TPU-native unit of work is
instead a *padded COO micro-batch*: fixed-shape int32 src/dst arrays plus a
validity mask, so every downstream kernel is a statically-shaped XLA program.
``EventType`` mirrors EventType.java:24-27 (additions/deletions) as a sign array.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import ClassVar, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np


class EventType(enum.Enum):
    """Edge event kind (reference: EventType.java:24-27)."""

    EDGE_ADDITION = 1
    EDGE_DELETION = -1


class EdgeDirection(enum.Enum):
    """Neighborhood direction for slice()/degree ops (Flink's EdgeDirection)."""

    IN = "in"
    OUT = "out"
    ALL = "all"


def _as_i32(x) -> jax.Array:
    return jnp.asarray(x, dtype=jnp.int32)


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class EdgeBatch:
    """A padded COO micro-batch of edge events.

    Fields are equal-length 1-D arrays of static size B:
      src, dst: interned (dense) vertex ids, int32.
      mask:     validity — False rows are padding and must be ignored.
      val:      optional edge values (any dtype) — ``None`` for NullValue graphs.
      time:     optional event-time timestamps (relative ms, int32; host owns time).
      sign:     optional +1/-1 event sign (EventType); ``None`` means all additions.
    """

    src: jax.Array
    dst: jax.Array
    mask: jax.Array
    val: Optional[jax.Array] = None
    time: Optional[jax.Array] = None
    sign: Optional[jax.Array] = None

    # ---- construction -------------------------------------------------------

    @staticmethod
    def from_arrays(
        src,
        dst,
        val=None,
        time=None,
        sign=None,
        mask=None,
        pad_to: Optional[int] = None,
    ) -> "EdgeBatch":
        """Build a batch from host/device arrays, optionally padding to a capacity."""
        src = _as_i32(src)
        dst = _as_i32(dst)
        n = src.shape[0]
        if mask is None:
            mask = jnp.ones((n,), dtype=bool)
        else:
            mask = jnp.asarray(mask, dtype=bool)
        if val is not None:
            val = jax.tree.map(jnp.asarray, val)
        if time is not None:
            # Relative stream time in ms (int32): windows are assigned on the
            # host, so device timestamps only need to order events within a
            # run.  Epoch-scale timestamps (~1.7e12 ms) would silently WRAP
            # in the cast — fail loudly instead (same philosophy as the
            # vertex-id bounds check in EdgeStream.from_arrays): rebase to
            # stream-relative ms first.
            # Traced construction (inside a jitted step) stays legal.  A
            # concrete device jax.Array is judged by DTYPE alone — no
            # np.asarray, which would force a device->host sync per batch
            # (~40-65 ms through the session tunnel) on timed hot paths: a
            # signed integer dtype of <= 32 bits cannot wrap in the int32
            # cast, anything wider (or float/uint32+) could hold
            # epoch-scale values and is refused without materializing.
            # Host inputs (lists, numpy) keep the exact value check.
            if isinstance(time, jax.core.Tracer):
                pass
            elif isinstance(time, jax.Array):
                dt = np.dtype(time.dtype)
                safe = (dt.kind == "i" and dt.itemsize <= 4) or (
                    dt.kind == "u" and dt.itemsize <= 2
                )
                if not safe:
                    raise ValueError(
                        f"device timestamp arrays must use a signed integer "
                        f"dtype of <= 32 bits (got {dt}): wider or "
                        "non-integer values could wrap in the int32 cast; "
                        "rebase to stream-relative ms on host first"
                    )
            else:
                t_host = np.asarray(time)
                if t_host.size and (
                    t_host.max() > np.iinfo(np.int32).max
                    or t_host.min() < np.iinfo(np.int32).min
                ):
                    raise ValueError(
                        "event timestamps must be stream-relative ms fitting "
                        "int32; rebase epoch timestamps (subtract the stream "
                        "start) before ingest — host owns time"
                    )
            time = jnp.asarray(time, dtype=jnp.int32)
        if sign is not None:
            sign = jnp.asarray(sign, dtype=jnp.int8)
        batch = EdgeBatch(src=src, dst=dst, mask=mask, val=val, time=time, sign=sign)
        if pad_to is not None and pad_to != n:
            batch = batch.pad_to(pad_to)
        return batch

    # shared all-ones host masks by size, read-only so every batch may
    # alias one safely (the pane cutter np.asarray's it without writing)
    _HOST_MASKS: ClassVar[dict] = {}

    @staticmethod
    def from_host_arrays(src, dst, pad_to: Optional[int] = None) -> "EdgeBatch":
        """Host-plane batch: contiguous NUMPY int32 leaves, no device
        conversion, the all-ones mask shared (read-only) across batches.

        For value-less/untimed sources whose consumer is the HOST pane
        cutter (core/windows.py ``np.asarray``'s every field before any
        device work): ``from_arrays`` would round-trip each batch through
        three eager jnp conversions (~ms-scale per batch — the measured
        ceiling of the serving ingest path, ISSUE 14) only for the cutter
        to convert straight back.  Numpy leaves are ordinary pytree
        leaves, so consumers that DO dispatch a batch still work — they
        pay the transfer exactly once, at the device boundary.
        """
        src = np.ascontiguousarray(src, dtype=np.int32)
        dst = np.ascontiguousarray(dst, dtype=np.int32)
        n = src.shape[0]
        if dst.shape[0] != n:
            raise ValueError("src/dst length mismatch")
        size = n if pad_to is None else int(pad_to)
        if size < n:
            raise ValueError(f"cannot pad batch of size {n} down to {size}")
        if size != n:
            pad = size - n
            src = np.concatenate([src, np.zeros(pad, np.int32)])
            dst = np.concatenate([dst, np.zeros(pad, np.int32)])
            mask = np.zeros(size, bool)
            mask[:n] = True
        else:
            mask = EdgeBatch._HOST_MASKS.get(size)
            if mask is None:
                mask = np.ones(size, bool)
                mask.flags.writeable = False
                EdgeBatch._HOST_MASKS[size] = mask
        return EdgeBatch(src=src, dst=dst, mask=mask)

    @staticmethod
    def from_edges(
        edges: Sequence[tuple], pad_to: Optional[int] = None, with_time: bool = False
    ) -> "EdgeBatch":
        """Build from a list of (src, dst[, val[, time]]) tuples (host-side helper)."""
        if not edges:
            size = pad_to or 0
            return EdgeBatch(
                src=jnp.zeros((size,), jnp.int32),
                dst=jnp.zeros((size,), jnp.int32),
                mask=jnp.zeros((size,), bool),
            )
        src = np.array([e[0] for e in edges], dtype=np.int32)
        dst = np.array([e[1] for e in edges], dtype=np.int32)
        val = None
        time = None
        if len(edges[0]) > 2:
            first = edges[0][2]
            if isinstance(first, tuple):
                # tuple-valued edges become a tuple-of-columns pytree
                val = tuple(
                    np.array([e[2][k] for e in edges]) for k in range(len(first))
                )
            else:
                val = np.array([e[2] for e in edges])
        if with_time and len(edges[0]) > 3:
            # int64 here so from_arrays' epoch-overflow guard sees the raw
            # values (an int32 build would wrap or raise before it runs)
            time = np.array([e[3] for e in edges], dtype=np.int64)
        return EdgeBatch.from_arrays(src, dst, val=val, time=time, pad_to=pad_to)

    # ---- shape/padding ------------------------------------------------------

    @property
    def size(self) -> int:
        """Static batch capacity B (including padding)."""
        return int(self.src.shape[0])

    def num_valid(self) -> jax.Array:
        return jnp.sum(self.mask.astype(jnp.int32))

    def pad_to(self, capacity: int) -> "EdgeBatch":
        n = self.size
        if capacity < n:
            raise ValueError(f"cannot pad batch of size {n} down to {capacity}")
        if capacity == n:
            return self
        pad = capacity - n

        def _pad1(x, fill=0):
            return jnp.concatenate(
                [x, jnp.full((pad,) + x.shape[1:], fill, dtype=x.dtype)]
            )

        def _pad(x, fill=0):
            if x is None:
                return None
            return jax.tree.map(lambda leaf: _pad1(leaf, fill), x)

        return EdgeBatch(
            src=_pad1(self.src),
            dst=_pad1(self.dst),
            mask=jnp.concatenate([self.mask, jnp.zeros((pad,), bool)]),
            val=_pad(self.val),
            time=_pad(self.time),
            sign=_pad(self.sign, fill=1),
        )

    # ---- transforms used by the stream API ---------------------------------

    def reversed(self) -> "EdgeBatch":
        """Swap src/dst (reference: SimpleEdgeStream.java:328)."""
        return dataclasses.replace(self, src=self.dst, dst=self.src)

    def replace(self, **kw) -> "EdgeBatch":
        return dataclasses.replace(self, **kw)

    def concat(self, other: "EdgeBatch") -> "EdgeBatch":
        def _cat(a, b, field, fill=None):
            if a is None and b is None:
                return None
            # One-sided optional field: synthesize the field's *semantic
            # default* for the side missing it (sign=None means "all
            # additions" -> fill +1; val -> zeros).  Event time cannot be
            # invented, so a one-sided time is an error.
            if (a is None) != (b is None):
                if fill is None:
                    raise ValueError(
                        f"cannot concat batches where only one side has {field!r}"
                    )
                length = (self.src if a is None else other.src).shape[0]

                def synth(leaf):
                    return jnp.full((length,) + leaf.shape[1:], fill, leaf.dtype)

                if a is None:
                    a = jax.tree.map(synth, b)
                else:
                    b = jax.tree.map(synth, a)
            return jax.tree.map(lambda x, y: jnp.concatenate([x, y]), a, b)

        return EdgeBatch(
            src=jnp.concatenate([self.src, other.src]),
            dst=jnp.concatenate([self.dst, other.dst]),
            mask=jnp.concatenate([self.mask, other.mask]),
            val=_cat(self.val, other.val, "val", fill=0),
            time=_cat(self.time, other.time, "time"),
            sign=_cat(self.sign, other.sign, "sign", fill=1),
        )

    # ---- host-side inspection ----------------------------------------------

    def to_tuples(self) -> list:
        """Materialize valid edges as host tuples (testing/sinks only).

        A pytree-valued ``val`` (e.g. a tuple of arrays from mapEdges-to-tuple)
        renders as a nested tuple per row, matching Flink's Tuple CSV rendering.
        """
        src = np.asarray(self.src)
        dst = np.asarray(self.dst)
        mask = np.asarray(self.mask)
        val = (
            None
            if self.val is None
            else jax.tree.map(np.asarray, self.val)
        )
        val_leaves, val_def = (
            (None, None) if val is None else jax.tree.flatten(val)
        )
        out = []
        for i in range(len(src)):
            if not mask[i]:
                continue
            if val is None:
                out.append((int(src[i]), int(dst[i])))
            else:
                leaves_i = [leaf[i].item() for leaf in val_leaves]
                v = jax.tree.unflatten(val_def, leaves_i)
                out.append((int(src[i]), int(dst[i]), v))
        return out



"""SnapshotStream: per-vertex-keyed windowed neighborhood views.

Reference: SnapshotStream.java (produced by ``slice()``,
SimpleEdgeStream.java:135-167) with three neighborhood aggregations:
``foldNeighbors`` (:61-86), ``reduceOnEdges`` (:100-120), ``applyOnNeighbors``
(:129-181).  The Flink version keys the window by vertex and iterates each
vertex's neighbors lazily per window.  The TPU-native version materializes each
closed pane as a *padded per-vertex neighborhood tensor* ``[K, D]`` (K distinct
keys, D the pane's max degree) and runs the user function as a vmapped/scanned
kernel over it — neighborhood iteration becomes a dense array sweep.

Direction semantics match slice() exactly: OUT keys by source; IN keys by
target (the reversed stream); ALL keys both endpoints of each edge
(undirected, SimpleEdgeStream.java:149-163).
"""

from __future__ import annotations

from typing import Callable, Iterator, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from gelly_streaming_tpu.core.output import OutputStream
from gelly_streaming_tpu.core.types import EdgeBatch, EdgeDirection
from gelly_streaming_tpu.core.windows import WindowPane, assign_tumbling_windows
from gelly_streaming_tpu.ops import neighborhoods as nbh_ops


class Neighborhoods:
    """One degree bucket of a closed pane: padded [K, D] tensors.

    Shapes are powers of two derived from the pane's padded edge count
    (ops/neighborhoods.py), so successive panes of similar size reuse the same
    compiled kernels.  Rows beyond ``num_keys`` are padding with an all-False
    valid mask; emission honors ``num_keys``.
    """

    def __init__(self, pane: WindowPane, keys, nbrs, vals, valid, num_keys):
        self.pane = pane
        self.keys = keys  # [K_padded]
        self.nbrs = nbrs  # [K_padded, D_padded]
        self.vals = vals  # None or pytree of [K_padded, D_padded]
        self.valid = valid  # [K_padded, D_padded] bool
        self.num_keys = num_keys  # real key count (rows beyond are padding)


_build_buckets_j = jax.jit(nbh_ops.build_buckets)


class SnapshotStream:
    """Windowed graph-snapshot stream (reference: SnapshotStream.java:46)."""

    def __init__(self, edge_stream, window_ms: int, direction: EdgeDirection):
        self._stream = edge_stream
        self.window_ms = window_ms
        self.direction = direction

    def _neighborhood_panes(self) -> Iterator[Neighborhoods]:
        """Device-built, degree-bucketed neighborhoods per closed pane.

        The pane ships as its edge list; grouping runs on device
        (ops/neighborhoods.py), and each degree class yields its own
        Neighborhoods so one hub vertex no longer inflates every row to the
        pane's max degree (VERDICT r1 item 6; ref SnapshotStream.java:143-172).
        """
        panes = assign_tumbling_windows(self._stream.batches(), self.window_ms)
        for pane in panes:
            src, dst, val = pane.src, pane.dst, pane.val
            if self.direction == EdgeDirection.IN:
                src, dst = dst, src
            elif self.direction == EdgeDirection.ALL:
                src, dst = (
                    np.concatenate([src, dst]),
                    np.concatenate([dst, src]),
                )
                if val is not None:
                    val = jax.tree.map(lambda a: np.concatenate([a, a]), val)
            n = len(src)
            if n == 0:
                continue
            e_pad = max(1, 1 << (n - 1).bit_length())
            mask = np.zeros((e_pad,), bool)
            mask[:n] = True

            def pad(a):
                out = np.zeros((e_pad,) + a.shape[1:], a.dtype)
                out[:n] = a
                return out

            buckets = _build_buckets_j(
                jnp.asarray(pad(src.astype(np.int32))),
                jnp.asarray(pad(dst.astype(np.int32))),
                None if val is None else jax.tree.map(lambda a: jnp.asarray(pad(a)), val),
                jnp.asarray(mask),
            )
            for bkt in buckets:
                nk = int(bkt.num_keys)
                if nk == 0:
                    continue
                yield Neighborhoods(
                    pane, bkt.keys, bkt.nbrs, bkt.vals, bkt.valid, nk
                )

    # ---- aggregations -------------------------------------------------------

    def fold_neighbors(self, init_accum, fold_fn: Callable) -> OutputStream:
        """Per key, fold neighbors in arrival order:
        fold_fn(accum, vid, nbr_id, edge_value) -> accum
        (reference EdgesFoldFunction, SnapshotStream.java:61-86).  Emits the
        final accumulator per (vertex, window)."""

        def kernel(keys, nbrs, vals, valid):
            def per_key(key, nbr_row, val_row, valid_row):
                def step(accum, inp):
                    nbr, val, ok = inp
                    new = fold_fn(accum, key, nbr, val)
                    return jax.tree.map(
                        lambda n, a: jnp.where(ok, n, a), new, accum
                    ), None

                accum, _ = jax.lax.scan(
                    step, init_accum, (nbr_row, val_row, valid_row)
                )
                return accum

            return jax.vmap(per_key)(keys, nbrs, vals, valid)

        kernel = jax.jit(kernel)

        def records():
            for hood in self._neighborhood_panes():
                accums = kernel(
                    jnp.asarray(hood.keys),
                    jnp.asarray(hood.nbrs),
                    jax.tree.map(jnp.asarray, hood.vals),
                    jnp.asarray(hood.valid),
                )
                leaves = [np.asarray(x) for x in jax.tree.leaves(accums)]
                treedef = jax.tree.structure(accums)
                for i in range(hood.num_keys):
                    rec = jax.tree.unflatten(
                        treedef, [leaf[i].item() for leaf in leaves]
                    )
                    yield rec if isinstance(rec, tuple) else (rec,)

        return OutputStream(records)

    def reduce_on_edges(self, reduce_fn: Callable) -> OutputStream:
        """Per key, reduce edge values pairwise; emits (vertex, reduced)
        (reference EdgesReduceFunction + project(0,2), SnapshotStream.java:100-120).
        Edge values may be any pytree; valueless (NullValue) streams have
        nothing to reduce and are rejected."""

        def kernel(keys, nbrs, vals, valid):
            def per_key(key, val_row, valid_row):
                def step(carry, inp):
                    accum, started = carry
                    val, ok = inp
                    reduced = reduce_fn(accum, val)
                    nxt = jax.tree.map(
                        lambda r, v, a: jnp.where(
                            ok & started, r, jnp.where(ok, v, a)
                        ),
                        reduced,
                        val,
                        accum,
                    )
                    return (nxt, started | ok), None

                init = jax.tree.map(lambda leaf: jnp.zeros_like(leaf[0]), val_row)
                (accum, _), _ = jax.lax.scan(
                    step, (init, jnp.asarray(False)), (val_row, valid_row)
                )
                return accum

            return jax.vmap(per_key)(keys, vals, valid)

        kernel = jax.jit(kernel)

        def records():
            for hood in self._neighborhood_panes():
                if hood.vals is None:
                    raise ValueError(
                        "reduce_on_edges requires edge values; this stream has none"
                    )
                out = kernel(
                    jnp.asarray(hood.keys),
                    jnp.asarray(hood.nbrs),
                    jax.tree.map(jnp.asarray, hood.vals),
                    jnp.asarray(hood.valid),
                )
                leaves = [np.asarray(x) for x in jax.tree.leaves(out)]
                treedef = jax.tree.structure(out)
                keys_h = np.asarray(hood.keys)
                for i in range(hood.num_keys):
                    rec = jax.tree.unflatten(
                        treedef, [leaf[i].item() for leaf in leaves]
                    )
                    yield (int(keys_h[i]), rec)

        return OutputStream(records)

    def apply_on_neighbors(
        self, apply_fn: Callable, post: Optional[Callable] = None
    ) -> OutputStream:
        """Per key, run a whole-neighborhood kernel:
        apply_fn(vid, nbr_ids [D], vals [D], valid [D]) -> record pytree
        (reference SnapshotFunction wrapping EdgesApply, SnapshotStream.java:129-181;
        the lazy neighbor Iterable becomes the padded row).  ``post`` maps the
        host record before emission (e.g. jax bool -> "big"/"small" strings)."""

        def kernel(keys, nbrs, vals, valid):
            return jax.vmap(apply_fn)(keys, nbrs, vals, valid)

        kernel = jax.jit(kernel)

        def records():
            for hood in self._neighborhood_panes():
                out = kernel(
                    jnp.asarray(hood.keys),
                    jnp.asarray(hood.nbrs),
                    jax.tree.map(jnp.asarray, hood.vals),
                    jnp.asarray(hood.valid),
                )
                leaves = [np.asarray(x) for x in jax.tree.leaves(out)]
                treedef = jax.tree.structure(out)
                for i in range(hood.num_keys):
                    rec = jax.tree.unflatten(
                        treedef, [leaf[i].item() for leaf in leaves]
                    )
                    if post is not None:
                        rec = post(rec)
                    yield rec if isinstance(rec, tuple) else (rec,)

        return OutputStream(records)

"""SnapshotStream: per-vertex-keyed windowed neighborhood views.

Reference: SnapshotStream.java (produced by ``slice()``,
SimpleEdgeStream.java:135-167) with three neighborhood aggregations:
``foldNeighbors`` (:61-86), ``reduceOnEdges`` (:100-120), ``applyOnNeighbors``
(:129-181).  The Flink version keys the window by vertex and iterates each
vertex's neighbors lazily per window.  The TPU-native version materializes each
closed pane as a *padded per-vertex neighborhood tensor* ``[K, D]`` (K distinct
keys, D the pane's max degree) and runs the user function as a vmapped/scanned
kernel over it — neighborhood iteration becomes a dense array sweep.

Direction semantics match slice() exactly: OUT keys by source; IN keys by
target (the reversed stream); ALL keys both endpoints of each edge
(undirected, SimpleEdgeStream.java:149-163).
"""

from __future__ import annotations

import time as _time
from typing import Callable, Iterator, Optional

import jax
import jax.numpy as jnp
import numpy as np

from gelly_streaming_tpu.core.output import OutputStream
from gelly_streaming_tpu.core.types import EdgeDirection
from gelly_streaming_tpu.core.windows import (
    WindowPane,
    validate_slide,
    windowed_panes,
)
from gelly_streaming_tpu.ops import neighborhoods as nbh_ops


_NEEDS_VALUES_MSG = "this aggregation requires edge values; the stream has none"


class Neighborhoods:
    """One degree bucket of a closed pane: padded [K, D] tensors.

    Shapes are powers of two derived from the pane's padded edge count
    (ops/neighborhoods.py), so successive panes of similar size reuse the same
    compiled kernels.  Rows beyond ``num_keys`` are padding with an all-False
    valid mask; emission honors ``num_keys``.
    """

    def __init__(self, pane: WindowPane, keys, nbrs, vals, valid, num_keys):
        self.pane = pane
        self.keys = keys  # [K_padded]
        self.nbrs = nbrs  # [K_padded, D_padded]
        self.vals = vals  # None or pytree of [K_padded, D_padded]
        self.valid = valid  # [K_padded, D_padded] bool
        self.num_keys = num_keys  # real key count (rows beyond are padding)


_build_buckets_j = nbh_ops.build_buckets_jit


class SnapshotStream:
    """Windowed graph-snapshot stream (reference: SnapshotStream.java:46).

    With ``cfg.num_shards > 1`` (and enough devices) the aggregations run on
    the sharded data plane: each pane's edges route to their key's owner
    shard (the keyBy shuffle — slice() is a *distributed* keyed window,
    SimpleEdgeStream.java:149-163), every shard builds its own degree-
    bucketed neighborhoods on device and runs the user kernel over them
    inside ONE shard_map step — keys are partitioned, so no collective is
    needed past the route, exactly like the reference's keyed window
    operator.
    """

    def __init__(
        self,
        edge_stream,
        window_ms: int,
        direction: EdgeDirection,
        slide_ms: Optional[int] = None,
    ):
        self._stream = edge_stream
        self.window_ms = window_ms
        self.direction = direction
        validate_slide(window_ms, slide_ms)
        self.slide_ms = slide_ms

    def _panes(self):
        """Closed window panes: tumbling, or pane-shared sliding windows when
        ``slide_ms`` divides the window (windows.sliding_panes; beyond the
        tumbling-only reference slice, SimpleEdgeStream.java:135-167)."""
        return windowed_panes(self._stream, self.window_ms, self.slide_ms)

    def _directed_edges(self, pane: WindowPane):
        """(src, dst, val) with slice()'s direction semantics applied."""
        src, dst, val = pane.src, pane.dst, pane.val
        if self.direction == EdgeDirection.IN:
            src, dst = dst, src
        elif self.direction == EdgeDirection.ALL:
            src, dst = (
                np.concatenate([src, dst]),
                np.concatenate([dst, src]),
            )
            if val is not None:
                val = jax.tree.map(lambda a: np.concatenate([a, a]), val)
        return src, dst, val

    def _padded_pane_edges(self, pane: WindowPane):
        """Direction semantics + the shared pow2 pad of one pane's edges.

        Returns numpy ``(src, dst, val | None, mask)`` in the exact layout
        `_build_buckets_j` consumes, or None for an edge-less pane.  ONE
        implementation feeds both the synchronous `_neighborhood_panes`
        and the async `_kernel_chunks_async` prepare stage, so the two
        paths' pad policy (and therefore their compiled shapes and chunk
        sequences) cannot diverge.
        """
        src, dst, val = self._directed_edges(pane)
        n = len(src)
        if n == 0:
            return None
        e_pad = max(1, 1 << (n - 1).bit_length())
        mask = np.zeros((e_pad,), bool)
        mask[:n] = True

        def pad(a):
            out = np.zeros((e_pad,) + a.shape[1:], a.dtype)
            out[:n] = a
            return out

        return (
            pad(src.astype(np.int32)),
            pad(dst.astype(np.int32)),
            None if val is None else jax.tree.map(pad, val),
            mask,
        )

    def _neighborhood_panes(self) -> Iterator[Neighborhoods]:
        """Device-built, degree-bucketed neighborhoods per closed pane.

        The pane ships as its edge list; grouping runs on device
        (ops/neighborhoods.py), and each degree class yields its own
        Neighborhoods so one hub vertex no longer inflates every row to the
        pane's max degree (VERDICT r1 item 6; ref SnapshotStream.java:143-172).
        """
        panes = self._panes()
        for pane in panes:
            padded = self._padded_pane_edges(pane)
            if padded is None:
                continue
            src_p, dst_p, val_p, mask = padded
            buckets = _build_buckets_j(
                jnp.asarray(src_p),
                jnp.asarray(dst_p),
                None if val_p is None else jax.tree.map(jnp.asarray, val_p),
                jnp.asarray(mask),
            )
            for bkt in buckets:
                nk = int(bkt.num_keys)
                if nk == 0:
                    continue
                yield Neighborhoods(
                    pane, bkt.keys, bkt.nbrs, bkt.vals, bkt.valid, nk
                )

    # ---- kernel execution (single-device and sharded) -----------------------

    def _use_mesh(self) -> bool:
        cfg = self._stream.cfg
        return cfg.num_shards > 1 and cfg.num_shards <= len(jax.devices())

    def _kernel_cache(self, bucket_kernel) -> dict:
        """Per-kernel compiled-fn cache, surviving OutputStream re-runs.

        Keyed on the kernel closure (one per aggregation call, shared by
        every re-run of that call's OutputStream), holding the jitted
        single-device fn and the per-(cap, has_val) mesh steps — so
        re-running a stream never recompiles.  Bounded with oldest-first
        eviction (compiled fns are heavy; same policy as the aggregate
        path's `_wire_fused_step` cache).  A kernel is always paired with
        the same ``extra`` operand by its creator, so extra need not key
        the cache.
        """
        if not hasattr(self, "_kernel_caches"):
            self._kernel_caches = {}
        entry = self._kernel_caches.get(bucket_kernel)
        if entry is None:
            while len(self._kernel_caches) >= 8:
                self._kernel_caches.pop(next(iter(self._kernel_caches)))
            entry = {}
            self._kernel_caches[bucket_kernel] = entry
        return entry

    def _jit_kernel(self, bucket_kernel, extra=None):
        """The cached single-device jitted bucket kernel (per-kernel cache,
        surviving OutputStream re-runs — see `_kernel_cache`)."""
        cache = self._kernel_cache(bucket_kernel)
        kernel = cache.get("jit")
        if kernel is None:
            if extra is None:
                kernel = jax.jit(bucket_kernel)  # graft: disable=RAWJIT — bounded per-kernel cache in self._kernel_caches
            else:
                x0 = jax.tree.map(lambda a: a[0], extra)
                kernel = jax.jit(  # graft: disable=RAWJIT — closes over the unhashable per-shard `extra` operand; cached per kernel in self._kernel_caches
                    lambda k, nb, v, vd: bucket_kernel(k, nb, v, vd, x0)
                )
            cache["jit"] = kernel
        return kernel

    def _kernel_chunks(self, bucket_kernel, needs_vals: bool, extra=None):
        """Run ``bucket_kernel(keys, nbrs, vals, valid[, extra])`` over every
        neighborhood bucket; yield host chunks
        ``(window_id, keys [n], out pytree of [n, ...], n)`` of real rows.

        ``extra`` is an optional per-shard operand pytree with leading shard
        axis ([S, ...] — e.g. ring feature blocks); on the single-device path
        its [0] slice is passed.

        With ``cfg.async_windows`` > 0 the single-device path runs on the
        asynchronous window pipeline (core/async_exec.py): pane padding on
        the pack thread, transfers overlapped, kernel dispatches
        non-blocking, and the per-pane host materialization rides the
        completion queue — same chunk sequence, no per-window RTT.
        """
        if self._use_mesh():
            yield from self._kernel_chunks_mesh(bucket_kernel, needs_vals, extra)
            return
        from gelly_streaming_tpu.core import async_exec

        depth = async_exec.resolve_depth(self._stream.cfg)
        if depth > 0:
            yield from self._kernel_chunks_async(
                bucket_kernel, needs_vals, extra, depth
            )
            return
        kernel = self._jit_kernel(bucket_kernel, extra)
        for hood in self._neighborhood_panes():
            if needs_vals and hood.vals is None:
                raise ValueError(_NEEDS_VALUES_MSG)
            out = kernel(
                jnp.asarray(hood.keys),
                jnp.asarray(hood.nbrs),
                jax.tree.map(jnp.asarray, hood.vals),
                jnp.asarray(hood.valid),
            )
            n = hood.num_keys
            yield (
                hood.pane.window_id,
                np.asarray(hood.keys)[:n],
                jax.tree.map(lambda a: np.asarray(a)[:n], out),
                n,
            )

    def _kernel_chunks_async(
        self, bucket_kernel, needs_vals: bool, extra, depth: int
    ):
        """`_kernel_chunks` on the async window pipeline (single device).

        Per pane: direction handling + pow2 padding on the pack thread,
        device transfer on the second thread, bucket build + kernel
        dispatched without waiting (with the result downloads started), and
        the host-side slicing deferred to the completion-queue drain.  The
        chunk sequence — window order, bucket order, real-row slicing — is
        identical to the synchronous path.
        """
        from gelly_streaming_tpu.core import async_exec
        from gelly_streaming_tpu.utils import tracing

        kernel = self._jit_kernel(bucket_kernel, extra)
        # spans originate on the prefetcher's pack thread (trace id +
        # pack timing); transfer/dispatch/drain marks ride the generic
        # pipeline (io/wire.Prefetcher + async_exec.pipelined)
        span_sampler = tracing.sampler(self._stream.cfg, "snapshot")

        def prepare(pane: WindowPane):
            t_pack = _time.perf_counter()
            padded = self._padded_pane_edges(pane)
            if padded is None:
                # edge-less pane: no span — it must not consume a stride
                # slot (sampling stays every-Nth FOLDED window) nor leak
                # a trace id that never reaches the recorder
                return (pane.window_id, 0, None), None
            span = (
                span_sampler.begin(pane.window_id)
                if span_sampler is not None
                else None
            )
            if span is not None:
                # the span's clock starts where the pack work did
                span.t0 = t_pack
                span.mark("pack", t_pack)
            return (pane.window_id, 1, span), padded

        def dispatch(meta, dev):
            if dev is None:
                return None
            src_d, dst_d, val_d, mask_d = dev
            if needs_vals and val_d is None:
                raise ValueError(_NEEDS_VALUES_MSG)
            handles = []
            for bkt in _build_buckets_j(src_d, dst_d, val_d, mask_d):
                out = kernel(bkt.keys, bkt.nbrs, bkt.vals, bkt.valid)
                async_exec.start_host_fetch((bkt.keys, bkt.num_keys, out))
                handles.append((bkt.keys, bkt.num_keys, out))
            return handles

        def finish(meta, handles):
            if handles is None:
                return []
            wid = meta[0]
            chunks = []
            for keys, num_keys, out in handles:
                nk = int(np.asarray(num_keys))
                if nk == 0:
                    continue
                chunks.append(
                    (
                        wid,
                        np.asarray(keys)[:nk],
                        jax.tree.map(lambda a: np.asarray(a)[:nk], out),
                        nk,
                    )
                )
            return chunks

        for chunks in async_exec.pipelined(
            self._panes(), prepare, dispatch, finish, depth
        ):
            yield from chunks

    def _mesh_step(self, cache, bucket_kernel, cap, has_val, extra_proto):
        key = (cap, has_val)
        if key in cache:
            return cache[key]
        from jax.sharding import PartitionSpec as P

        from gelly_streaming_tpu.parallel.mesh import make_mesh, shard_map

        cfg = self._stream.cfg
        mesh = make_mesh(cfg.num_shards)

        def step(src, dst, val, mask, extra):
            b_val = None if val is None else jax.tree.map(lambda a: a[0], val)
            x = None if extra is None else jax.tree.map(lambda a: a[0], extra)
            buckets = nbh_ops.build_buckets(src[0], dst[0], b_val, mask[0])
            outs = []
            for b in buckets:
                out = (
                    bucket_kernel(b.keys, b.nbrs, b.vals, b.valid)
                    if x is None
                    else bucket_kernel(b.keys, b.nbrs, b.vals, b.valid, x)
                )
                outs.append((b.keys, out, b.num_keys.reshape(1)))
            return tuple(outs)

        spec = P("shards")
        fn = jax.jit(  # graft: disable=RAWJIT — keyed per-mesh in the snapshot shard cache; a Mesh is not a stable process-global cache key
            shard_map(
                step,
                mesh=mesh,
                in_specs=(
                    spec,
                    spec,
                    spec if has_val else None,
                    spec,
                    None if extra_proto is None else spec,
                ),
                out_specs=spec,
            )
        )
        cache[key] = fn
        return fn

    def _kernel_chunks_mesh(self, bucket_kernel, needs_vals: bool, extra=None):
        """The sharded plane: host keyBy route -> per-shard device bucket
        build + kernel inside shard_map -> host chunks per (bucket, shard)."""
        from gelly_streaming_tpu.parallel.routing import host_route

        cfg = self._stream.cfg
        s_n = cfg.num_shards
        cache = self._kernel_cache(bucket_kernel)
        panes = self._panes()
        for pane in panes:
            src, dst, val = self._directed_edges(pane)
            if len(src) == 0:
                continue
            if needs_vals and val is None:
                raise ValueError(_NEEDS_VALUES_MSG)
            counts = np.bincount(src % s_n, minlength=s_n)
            cap = max(1, 1 << (int(counts.max()) - 1).bit_length())
            routed = host_route(
                src.astype(np.int32),
                dst.astype(np.int32),
                s_n,
                key="src",
                capacity=cap,
                val=val,
            )
            step = self._mesh_step(
                cache, bucket_kernel, cap, routed.val is not None, extra
            )
            outs = step(
                jnp.asarray(routed.src),
                jnp.asarray(routed.dst),
                None
                if routed.val is None
                else jax.tree.map(jnp.asarray, routed.val),
                jnp.asarray(routed.mask),
                extra,
            )
            for (keys_g, out_g, num_g), (k_b, _) in zip(
                outs, nbh_ops.bucket_shapes(cap)
            ):
                num_h = np.asarray(num_g)
                if not num_h.any():
                    continue
                keys_h = np.asarray(keys_g)
                out_h = jax.tree.map(np.asarray, out_g)
                for s in range(s_n):
                    n = int(num_h[s])
                    if n == 0:
                        continue
                    sl = slice(s * k_b, s * k_b + n)
                    yield (
                        pane.window_id,
                        keys_h[sl],
                        jax.tree.map(lambda a: a[sl], out_h),
                        n,
                    )

    # ---- aggregations -------------------------------------------------------

    def fold_neighbors(
        self, init_accum, fold_fn: Callable, mode: str = "device"
    ) -> OutputStream:
        """Per key, fold neighbors in arrival order:
        fold_fn(accum, vid, nbr_id, edge_value) -> accum
        (reference EdgesFoldFunction, SnapshotStream.java:61-86).  Emits the
        final accumulator per (vertex, window).

        ``mode="host"`` runs ``fold_fn`` as plain Python per neighbor (the
        EdgesFold escape hatch for non-traceable accumulators, e.g. string
        building — same contract as ``apply_on_neighbors(mode="host")``);
        ``init_accum`` may then be any Python value.
        """
        if mode not in ("device", "host"):
            raise ValueError(f"unknown fold_neighbors mode {mode!r}")
        if mode == "host":
            import copy as _copy

            def host_apply(vid, neighbors):
                accum = _copy.deepcopy(init_accum)
                for nbr, val in neighbors:
                    accum = fold_fn(accum, vid, nbr, val)
                # match the device path's record shape: tuple accumulators
                # splat into multi-field records; anything else (including a
                # LIST, which would otherwise hit the host-apply collector
                # convention and emit each element separately) is one field
                return accum if isinstance(accum, tuple) else (accum,)

            return self._apply_on_neighbors_host(host_apply, None)

        def kernel(keys, nbrs, vals, valid):
            def per_key(key, nbr_row, val_row, valid_row):
                def step(accum, inp):
                    nbr, val, ok = inp
                    new = fold_fn(accum, key, nbr, val)
                    return jax.tree.map(
                        lambda n, a: jnp.where(ok, n, a), new, accum
                    ), None

                accum, _ = jax.lax.scan(
                    step, init_accum, (nbr_row, val_row, valid_row)
                )
                return accum

            return jax.vmap(per_key)(keys, nbrs, vals, valid)

        def records():
            for _, keys_h, out, n in self._kernel_chunks(kernel, False):
                leaves = jax.tree.leaves(out)
                treedef = jax.tree.structure(out)
                for i in range(n):
                    rec = jax.tree.unflatten(
                        treedef, [leaf[i].item() for leaf in leaves]
                    )
                    yield rec if isinstance(rec, tuple) else (rec,)

        return OutputStream(records)

    def reduce_on_edges(
        self, reduce_fn: Callable, mode: str = "device"
    ) -> OutputStream:
        """Per key, reduce edge values pairwise; emits (vertex, reduced)
        (reference EdgesReduceFunction + project(0,2), SnapshotStream.java:100-120).
        Edge values may be any pytree; valueless (NullValue) streams have
        nothing to reduce and are rejected.

        ``mode="host"`` runs ``reduce_fn`` as plain Python (the EdgesReduce
        escape hatch for non-traceable reducers), emitting the same
        (vertex, reduced) records.
        """
        if mode not in ("device", "host"):
            raise ValueError(f"unknown reduce_on_edges mode {mode!r}")
        if mode == "host":

            def host_apply(vid, neighbors):
                if not neighbors:
                    return None
                if neighbors[0][1] is None:
                    raise ValueError(_NEEDS_VALUES_MSG)
                acc = neighbors[0][1]
                for _, val in neighbors[1:]:
                    acc = reduce_fn(acc, val)
                return (vid, acc)

            return self._apply_on_neighbors_host(host_apply, None)

        def kernel(keys, nbrs, vals, valid):
            def per_key(key, val_row, valid_row):
                def step(carry, inp):
                    accum, started = carry
                    val, ok = inp
                    reduced = reduce_fn(accum, val)
                    nxt = jax.tree.map(
                        lambda r, v, a: jnp.where(
                            ok & started, r, jnp.where(ok, v, a)
                        ),
                        reduced,
                        val,
                        accum,
                    )
                    return (nxt, started | ok), None

                init = jax.tree.map(lambda leaf: jnp.zeros_like(leaf[0]), val_row)
                (accum, _), _ = jax.lax.scan(
                    step, (init, jnp.asarray(False)), (val_row, valid_row)
                )
                return accum

            return jax.vmap(per_key)(keys, vals, valid)

        def records():
            for _, keys_h, out, n in self._kernel_chunks(kernel, True):
                leaves = jax.tree.leaves(out)
                treedef = jax.tree.structure(out)
                for i in range(n):
                    rec = jax.tree.unflatten(
                        treedef, [leaf[i].item() for leaf in leaves]
                    )
                    yield (int(keys_h[i]), rec)

        return OutputStream(records)

    def apply_on_neighbors(
        self,
        apply_fn: Callable,
        post: Optional[Callable] = None,
        mode: str = "device",
    ) -> OutputStream:
        """Per key, run a whole-neighborhood function
        (reference SnapshotFunction wrapping EdgesApply,
        SnapshotStream.java:129-181).

        ``mode="device"`` (default): ``apply_fn(vid, nbr_ids [D], vals [D],
        valid [D]) -> record pytree`` is a jax-traceable kernel vmapped over
        the degree-bucketed padded rows — the lazy neighbor Iterable becomes
        the padded row.  ``post`` maps the host record before emission (e.g.
        jax bool -> "big"/"small" strings).

        ``mode="host"`` is the escape hatch for truly irregular,
        NON-traceable UDFs (SURVEY §7; the reference's EdgesApply accepts
        arbitrary Java code over a lazy iterator, EdgesApply.java:47):
        ``apply_fn(vid, neighbors)`` runs as plain Python per vertex, where
        ``neighbors`` is a list of ``(nbr_id, val)`` tuples (``val`` None on
        value-less streams) in neighborhood order — the direct analog of
        the reference's ``Iterable<Tuple2<nbrId, edgeVal>>``.  It may
        return one record or a list of records (the collector analog:
        emit 0..n).  Neighborhood grouping still runs on device; only the
        UDF itself runs on host, so throughput is Python-bound — keep hot
        aggregations on the device path.
        """
        if mode not in ("device", "host"):
            raise ValueError(f"unknown apply_on_neighbors mode {mode!r}")
        if mode == "host":
            return self._apply_on_neighbors_host(apply_fn, post)

        def kernel(keys, nbrs, vals, valid):
            return jax.vmap(apply_fn)(keys, nbrs, vals, valid)

        def records():
            for _, keys_h, out, n in self._kernel_chunks(kernel, False):
                leaves = jax.tree.leaves(out)
                treedef = jax.tree.structure(out)
                for i in range(n):
                    rec = jax.tree.unflatten(
                        treedef, [leaf[i].item() for leaf in leaves]
                    )
                    if post is not None:
                        rec = post(rec)
                    yield rec if isinstance(rec, tuple) else (rec,)

        return OutputStream(records)

    def _apply_on_neighbors_host(
        self, apply_fn: Callable, post: Optional[Callable]
    ) -> OutputStream:
        """Host-mode neighborhood apply: arbitrary Python per vertex."""

        def records():
            for hood in self._neighborhood_panes():
                keys = np.asarray(hood.keys)
                nbrs = np.asarray(hood.nbrs)
                valid = np.asarray(hood.valid)
                vals = (
                    None
                    if hood.vals is None
                    else jax.tree.map(np.asarray, hood.vals)
                )
                leaves = None if vals is None else jax.tree.leaves(vals)
                treedef = None if vals is None else jax.tree.structure(vals)
                for i in range(hood.num_keys):
                    sel = valid[i]
                    row = nbrs[i][sel]
                    if vals is None:
                        neighbors = [(int(nb), None) for nb in row]
                    else:
                        # mask each leaf ONCE per vertex (not per neighbor:
                        # that would be O(D^2) on hub vertices)
                        masked = [leaf[i][sel] for leaf in leaves]
                        neighbors = [
                            (
                                int(nb),
                                jax.tree.unflatten(
                                    treedef, [m[j].item() for m in masked]
                                ),
                            )
                            for j, nb in enumerate(row)
                        ]
                    out = apply_fn(int(keys[i]), neighbors)
                    if out is None:
                        continue
                    outs = out if isinstance(out, list) else [out]
                    for rec in outs:
                        if post is not None:
                            rec = post(rec)
                        yield rec if isinstance(rec, tuple) else (rec,)

        return OutputStream(records)

"""Process-global AOT executable cache for the streaming hot loops.

Every dispatch plane in the framework used to call ``jax.jit`` at its own
call site, holding the compiled executable in whatever object happened to
own the closure (an EdgeStream, an OutputStream, a SummaryAggregation
instance).  Re-creating any of those — a new stream over the same arrays, a
fresh descriptor per window, the bench's chunk loop — silently retraced and
recompiled the identical kernel: seconds per compile on a TPU, repeated for
every (kernel, shape) the stream runtime produces.

This module is the single home for those executables.  A cache entry is
keyed by a caller-supplied *kernel identity* (a hashable tuple naming the
kernel and everything its traced behavior depends on: stage tuples, configs,
batch shapes, wire widths); the entry owns ONE ``jax.jit`` callable, so every
stream/descriptor/window that resolves to the same key shares the compiled
executables for all argument shapes.  The cache also meters itself:

  * ``key_hits`` / ``key_misses`` — entry-level reuse (a miss builds and
    jits a new callable; a hit reuses executables across streams).
  * ``compiles`` / ``compile_time_s`` — actual XLA trace+compile events,
    detected via the jitted callable's own signature cache growth, with the
    wall time of the compiling call attributed to compilation.
  * ``recompiles()`` — the retrace guard: number of compile events beyond
    the first for the same (kernel identity, abstract-signature) pair.  A
    healthy streaming run compiles each bucketed shape ONCE per kernel;
    anything above zero means the same kernel+shape was traced again —
    eviction churn of a hot entry, or a jit-internal retrace.  (Unstable
    kernel identities — fresh closures per call — surface as ``key_misses``
    growth instead: distinct keys are distinct kernels by definition.)

Counters are exposed through ``stats()`` here and re-exported by
``utils/metrics.py`` next to the throughput meters.

Key discipline for MESH kernels (the owner-sharded summary plane): a
``jax.sharding.Mesh`` object is not a guaranteed-stable identity across
re-created runners, so sharded shard_map steps key on
``parallel.mesh.mesh_cache_key(mesh)`` — device (platform, id) pairs plus
axis names — alongside the descriptor's ``cache_token``, the frozen config,
and every pow2-bucketed capacity the trace bakes in (pane cap, delta-buffer
cap, wire width).  That puts the whole mesh plane under this cache's
retrace guard: rebuilding a MeshAggregationRunner over the same devices
resolves to the same executables, and ``recompiles()`` stays 0 across
same-bucket panes (tests/test_sharded_state.py pins it).
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Optional, Tuple

# A LEAF of the runtime's lock order: builds and jit-traces run OUTSIDE
# the lock by design (they may import/trace arbitrarily), so nothing
# here may take a runtime lock; the scheduler, holding the manager lock,
# may reach the cache counters but never the reverse.
# lock-order: manager._lock < compile_cache._LOCK
_LOCK = threading.RLock()
# Shared across every dispatch thread (sync loops, the async pipeline's
# dispatch + drain, the mesh runners): the ``# guarded-by: _LOCK``
# annotations below are enforced by the lock-discipline analyzer pass
# (gelly_streaming_tpu/analysis/locks.py).
_ENTRIES: "Dict[Any, _CachedFn]" = {}  # guarded-by: _LOCK
_CAPACITY = 128

_KEY_HITS = 0  # guarded-by: _LOCK
_KEY_MISSES = 0  # guarded-by: _LOCK
# (kernel cache key, abstract signature) -> number of XLA compiles observed;
# >1 for any pair means the SAME kernel+shape was traced more than once (an
# eviction rebuild or a jit-internal retrace) — distinct kernels sharing
# shapes never collide here.  Bounded (oldest-first eviction) so per-call
# closure keys from long-running processes cannot pin memory forever.
_COMPILE_LOG: Dict[Tuple[Any, Any], int] = {}  # guarded-by: _LOCK
_COMPILE_LOG_CAP = 4096
_COMPILES = 0  # guarded-by: _LOCK
_COMPILE_TIME_S = 0.0  # guarded-by: _LOCK
_DISPATCH_HITS = 0  # guarded-by: _LOCK


def _abstract_sig(args, kwargs):
    """Shape/dtype signature of a call's array leaves (hashable).

    Computed ONLY on compile events (cache growth), so the cost never lands
    on the steady-state dispatch path.
    """
    import jax

    def leaf_sig(x):
        shape = getattr(x, "shape", None)
        dtype = getattr(x, "dtype", None)
        if shape is None or dtype is None:
            return repr(type(x))
        return (tuple(shape), str(dtype))

    leaves = jax.tree.leaves((args, kwargs))
    return tuple(leaf_sig(leaf) for leaf in leaves)


class _CachedFn:
    """A jitted callable that meters its own trace/compile events.

    ``jax.jit`` already caches one executable per abstract signature; what
    it cannot see is the same LOGICAL kernel being re-jitted under a fresh
    closure.  The entry detects real compiles by watching the jit signature
    cache grow across a call and logs them under the entry's label, which is
    what makes ``recompiles()`` a process-wide retrace guard.
    """

    __slots__ = (
        "_jit",
        "label",
        "log_key",
        "compiles",
        "compile_time_s",
        "calls",
        "_sig_fallback",
        "_seen_sigs",
    )

    def __init__(self, fn: Callable, label: Any, jit_kwargs: dict, log_key: Any = None):
        import jax

        self._jit = jax.jit(fn, **jit_kwargs)
        self.label = label
        self.log_key = log_key if log_key is not None else label
        self.compiles = 0
        self.compile_time_s = 0.0
        self.calls = 0
        # _cache_size is a private jax hook; when a build lacks it, fall
        # back to tracking abstract signatures ourselves (slower per call,
        # but the counters keep MEASURING instead of silently reporting 0
        # compiles — the bench's zero-recompile guard must never pass
        # vacuously)
        self._sig_fallback = not callable(getattr(self._jit, "_cache_size", None))
        self._seen_sigs = set() if self._sig_fallback else None

    def _cache_size(self) -> int:
        try:
            return self._jit._cache_size()
        except Exception:
            return -1

    def _record_compile(self, n: int, dt: float, sig) -> None:
        global _COMPILES, _COMPILE_TIME_S
        with _LOCK:
            self.compiles += n
            self.compile_time_s += dt
            _COMPILES += n
            _COMPILE_TIME_S += dt
            _COMPILE_LOG[(self.log_key, sig)] = (
                _COMPILE_LOG.get((self.log_key, sig), 0) + 1
            )
            while len(_COMPILE_LOG) > _COMPILE_LOG_CAP:
                _COMPILE_LOG.pop(next(iter(_COMPILE_LOG)))

    def __call__(self, *args, **kwargs):
        global _DISPATCH_HITS
        self.calls += 1
        if self._sig_fallback:
            sig = _abstract_sig(args, kwargs)
            fresh = sig not in self._seen_sigs
            t0 = time.perf_counter()
            out = self._jit(*args, **kwargs)
            if fresh:
                self._seen_sigs.add(sig)
                self._record_compile(1, time.perf_counter() - t0, sig)
            else:
                with _LOCK:
                    _DISPATCH_HITS += 1
            return out
        before = self._cache_size()
        t0 = time.perf_counter()
        out = self._jit(*args, **kwargs)
        after = self._cache_size()
        if after > before:
            self._record_compile(
                after - before,
                time.perf_counter() - t0,
                _abstract_sig(args, kwargs),
            )
        else:
            with _LOCK:
                _DISPATCH_HITS += 1
        return out

    def lower(self, *args, **kwargs):
        """Expose AOT lowering for callers that want to pre-compile."""
        return self._jit.lower(*args, **kwargs)


def cached_jit(
    key: Any,
    build: Callable[[], Callable],
    *,
    static_argnums=None,
    donate_argnums=None,
    label: Optional[str] = None,
) -> _CachedFn:
    """The process-global executable for kernel identity ``key``.

    ``build()`` produces the python callable to jit — invoked only on a key
    miss, so hot paths can pass cheap closure factories.  ``key`` must be
    hashable and must determine the traced behavior completely (include
    stage tuples, configs, static shapes, widths — anything the closure
    reads).  ``label`` names the kernel family for the retrace guard;
    defaults to the first element of a tuple key.

    Lifetime note: entries hold STRONG references to their key components
    (user callables, stage objects) and executables, bounded by the cache
    capacity with LRU eviction — callers whose keys are per-call closures
    (never re-hit) simply churn the cold end of the cache; stable keys (the
    streaming hot loops) stay resident.
    """
    global _KEY_HITS, _KEY_MISSES
    with _LOCK:
        entry = _ENTRIES.get(key)
        if entry is not None:
            _KEY_HITS += 1
            # LRU: hot kernels move to the back so capacity pressure from
            # one-shot keys (per-call closures) evicts cold entries, not the
            # streaming hot loop (an evicted+rebuilt kernel is a REAL
            # recompile and would rightly trip the retrace guard)
            _ENTRIES[key] = _ENTRIES.pop(key)
            return entry
        _KEY_MISSES += 1
    # Build + jit outside the lock: builds may import/trace arbitrarily.
    jit_kwargs = {}
    if static_argnums is not None:
        jit_kwargs["static_argnums"] = static_argnums
    if donate_argnums is not None:
        jit_kwargs["donate_argnums"] = donate_argnums
    if label is None:
        label = key[0] if isinstance(key, tuple) and key else repr(key)
    fresh = _CachedFn(build(), label, jit_kwargs, log_key=key)
    with _LOCK:
        entry = _ENTRIES.get(key)
        if entry is not None:  # lost a benign race; keep the first
            return entry
        while len(_ENTRIES) >= _CAPACITY:
            _ENTRIES.pop(next(iter(_ENTRIES)))
        _ENTRIES[key] = fresh
    return fresh


def recompiles() -> int:
    """Compile events beyond the first per (kernel identity, signature):
    the retrace count a healthy streaming process keeps at zero."""
    with _LOCK:
        return sum(c - 1 for c in _COMPILE_LOG.values() if c > 1)


def stats() -> dict:
    """Process-wide cache counters (see module docstring)."""
    with _LOCK:
        return {
            "entries": len(_ENTRIES),
            "key_hits": _KEY_HITS,
            "key_misses": _KEY_MISSES,
            "compiles": _COMPILES,
            "compile_time_s": round(_COMPILE_TIME_S, 4),
            "dispatch_hits": _DISPATCH_HITS,
            "recompiles": recompiles(),
        }


def label_stats(label: Any) -> dict:
    """Per-kernel-family counters: every live entry whose ``label`` matches,
    summed.  The fused-dispatch plane uses this to report the shared
    superpane executable's call/compile economy separately from the
    process-wide totals (one cohort dispatch = one ``calls`` tick here,
    however many tenant rows it folded)."""
    with _LOCK:
        entries = [e for e in _ENTRIES.values() if e.label == label]
        return {
            "entries": len(entries),
            "calls": sum(e.calls for e in entries),
            "compiles": sum(e.compiles for e in entries),
            "compile_time_s": round(
                sum(e.compile_time_s for e in entries), 4
            ),
        }


def reset_stats() -> None:
    """Zero the counters (entries and their executables stay cached)."""
    global _KEY_HITS, _KEY_MISSES, _COMPILES, _COMPILE_TIME_S, _DISPATCH_HITS
    with _LOCK:
        _KEY_HITS = _KEY_MISSES = _COMPILES = _DISPATCH_HITS = 0
        _COMPILE_TIME_S = 0.0
        _COMPILE_LOG.clear()
        for e in _ENTRIES.values():
            e.compiles = 0
            e.compile_time_s = 0.0
            e.calls = 0


def clear() -> None:
    """Drop every cached executable AND the counters (tests only: compiled
    kernels are expensive to rebuild)."""
    with _LOCK:
        _ENTRIES.clear()
    reset_stats()

"""Record output streams and sinks.

The reference's property streams are Flink ``DataStream``s written with
``writeAsCsv`` or collected in test sinks (e.g. TestGetDegrees.java:54-56,
ConnectedComponentsTest.java:84-94).  Here a terminal op yields per-batch record
blocks (dict of equal-length host arrays + validity mask); ``OutputStream``
wraps that iterator with collect/CSV sinks using the same rendering the golden
files assert (Flink Tuple CSV: ``1,2,12``; NullValue -> ``(null)``; nested
tuples -> ``(12,13)``).
"""

from __future__ import annotations

from typing import Callable, Iterable, Iterator, List, Optional, Tuple

import numpy as np


class NullValue:
    """Singleton mirroring Flink's NullValue; renders as ``(null)`` in CSV."""

    _inst = None

    def __new__(cls):
        if cls._inst is None:
            cls._inst = super().__new__(cls)
        return cls._inst

    def __repr__(self):
        return "(null)"


NULL = NullValue()


def _render(x) -> str:
    if isinstance(x, NullValue):
        return "(null)"
    if isinstance(x, tuple):
        return "(" + ",".join(_render(v) for v in x) + ")"
    if isinstance(x, (bool, np.bool_)):
        return "true" if x else "false"
    if isinstance(x, (float, np.floating)):
        return repr(float(x))
    if isinstance(x, (int, np.integer)):
        return str(int(x))
    return str(x)


class RecordBlock:
    """A vectorized block of records: one column per record field.

    Columns are equal-length host numpy arrays, or plain Python constants
    (e.g. ``NULL``) broadcast to every row — so a terminal op can emit a whole
    micro-batch's results as arrays without a per-record Python loop.
    """

    __slots__ = ("columns", "num_records")

    def __init__(self, columns: tuple):
        self.columns = columns
        self.num_records = next(
            (len(c) for c in columns if isinstance(c, np.ndarray)), 0
        )

    def tuples(self) -> Iterator[tuple]:
        """Per-record view (the goldens' trace mode)."""
        cols = [
            c if isinstance(c, np.ndarray) else None for c in self.columns
        ]

        def host(x):
            return x.item() if isinstance(x, np.generic) else x

        for i in range(self.num_records):
            yield tuple(
                host(c[i]) if c is not None else const
                for c, const in zip(cols, self.columns)
            )


class OutputStream:
    """A continuous stream of records produced by a terminal operation.

    ``records_fn`` is a zero-arg callable returning an iterator of host tuples
    (so the stream can be re-run, mirroring a dataflow's lazy execution).
    Block-native ops pass ``blocks_fn`` instead — an iterator of RecordBlocks —
    and per-record iteration becomes a derived view: ``blocks()`` is then the
    production sink path (no per-record Python loop), while golden-trace tests
    keep consuming tuples.
    """

    def __init__(
        self,
        records_fn: Optional[Callable[[], Iterator[tuple]]] = None,
        blocks_fn: Optional[Callable[[], Iterator[RecordBlock]]] = None,
    ):
        if (records_fn is None) == (blocks_fn is None):
            raise ValueError("pass exactly one of records_fn / blocks_fn")
        self._records_fn = records_fn
        self._blocks_fn = blocks_fn

    def blocks(self) -> Iterator[RecordBlock]:
        """Vectorized record blocks (production sinks).

        Record-based ops are adapted by chunking tuples into object columns —
        correct but not faster; block-native ops yield their arrays directly.
        """
        if self._blocks_fn is not None:
            return self._blocks_fn()

        def adapt():
            chunk: List[tuple] = []
            for rec in self._records_fn():
                chunk.append(rec)
                if len(chunk) >= 4096:
                    yield RecordBlock(
                        tuple(np.array(c, object) for c in zip(*chunk))
                    )
                    chunk = []
            if chunk:
                yield RecordBlock(
                    tuple(np.array(c, object) for c in zip(*chunk))
                )

        return adapt()

    def __iter__(self) -> Iterator[tuple]:
        if self._records_fn is not None:
            return self._records_fn()

        def derive():
            for blk in self._blocks_fn():
                yield from blk.tuples()

        return derive()

    def collect(self) -> List[tuple]:
        return list(iter(self))

    def collect_last(self) -> Optional[tuple]:
        last = None
        for r in self:
            last = r
        return last

    def lines(self) -> List[str]:
        """CSV lines in the reference's writeAsCsv rendering."""
        return [",".join(_render(f) for f in rec) for rec in self]

    def write_csv(self, path: str) -> None:
        """CSV sink in the reference's writeAsCsv rendering.

        Flat integer/bool column blocks render vectorized (numpy string
        ops — no per-record Python, matching the block emission design of
        the heavy property traces); floats, objects, and constants fall back
        to the per-record renderer, whose formatting is the golden contract.
        """
        with open(path, "w") as f:
            for blk in self.blocks():
                cols = blk.columns
                fast = blk.num_records > 0 and all(
                    isinstance(c, np.ndarray)
                    and c.ndim == 1
                    and (c.dtype == bool or np.issubdtype(c.dtype, np.integer))
                    for c in cols
                )
                if fast:
                    parts = [
                        np.where(c, "true", "false")
                        if c.dtype == bool
                        else c.astype(str)
                        for c in cols
                    ]
                    lines = parts[0]
                    for p in parts[1:]:
                        lines = np.char.add(np.char.add(lines, ","), p)
                    f.write("\n".join(lines.tolist()) + "\n")
                else:
                    for rec in blk.tuples():
                        f.write(",".join(_render(fld) for fld in rec) + "\n")

    def print(self) -> None:
        for rec in self:
            print(",".join(_render(f) for f in rec))

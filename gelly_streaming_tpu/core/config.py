"""Typed configuration for the streaming runtime.

The reference has no config framework — every example hand-parses argv and
library knobs are constructor params (SURVEY.md §5.6; e.g.
example/ConnectedComponentsExample.java:81-102).  Here a single typed config
carries the capacity/mesh/window knobs that static XLA shapes require.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class StreamConfig:
    """Static-shape and distribution knobs for a stream pipeline.

    Attributes:
      vertex_capacity: dense vertex-id space size C.  Vertex ids are interned to
        [0, C); all per-vertex state is a dense array of length C (the TPU answer
        to the reference's unbounded per-key HashMaps,
        SimpleEdgeStream.java:461-478).
      max_degree: per-vertex neighbor-table capacity D for stateful adjacency
        (distinct / buildNeighborhood analogs, SimpleEdgeStream.java:301-323,531-560).
      batch_size: edges per micro-batch (padded; the unit of device dispatch).
      num_shards: number of mesh shards the vertex space is partitioned over.
      window_ms: default tumbling-window length in milliseconds (the reference's
        per-aggregation mergeWindowTime, SummaryBulkAggregation.java:79).
      tree_degree: fan-in of the tree combine (SummaryTreeReduce.java:53-64 analog).
      prefetch_depth: packed-wire transfers kept in flight ahead of the device
        consumer on the fast ingest path (io/wire.py WirePrefetcher).
    """

    vertex_capacity: int = 1 << 16
    max_degree: int = 64
    batch_size: int = 1 << 10
    num_shards: int = 1
    window_ms: int = 1000
    tree_degree: int = 2
    prefetch_depth: int = 8

    def __post_init__(self):
        if self.vertex_capacity <= 0:
            raise ValueError("vertex_capacity must be positive")
        if self.num_shards <= 0:
            raise ValueError("num_shards must be positive")
        if self.vertex_capacity % self.num_shards != 0:
            raise ValueError(
                f"vertex_capacity ({self.vertex_capacity}) must be divisible by "
                f"num_shards ({self.num_shards}) for even sharding"
            )

    @property
    def shard_capacity(self) -> int:
        return self.vertex_capacity // self.num_shards


DEFAULT_CONFIG = StreamConfig()

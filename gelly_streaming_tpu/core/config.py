"""Typed configuration for the streaming runtime.

The reference has no config framework — every example hand-parses argv and
library knobs are constructor params (SURVEY.md §5.6; e.g.
example/ConnectedComponentsExample.java:81-102).  Here a single typed config
carries the capacity/mesh/window knobs that static XLA shapes require.
"""

from __future__ import annotations

import dataclasses
import re


@dataclasses.dataclass(frozen=True)
class StreamConfig:
    """Static-shape and distribution knobs for a stream pipeline.

    Attributes:
      vertex_capacity: dense vertex-id space size C.  Vertex ids are interned to
        [0, C); all per-vertex state is a dense array of length C (the TPU answer
        to the reference's unbounded per-key HashMaps,
        SimpleEdgeStream.java:461-478).
      max_degree: per-vertex neighbor-table capacity D for stateful adjacency
        (distinct / buildNeighborhood analogs, SimpleEdgeStream.java:301-323,531-560).
      batch_size: edges per micro-batch (padded; the unit of device dispatch).
      num_shards: number of mesh shards the vertex space is partitioned over.
      window_ms: default tumbling-window length in milliseconds (the reference's
        per-aggregation mergeWindowTime, SummaryBulkAggregation.java:79).
      tree_degree: fan-in of the tree combine (SummaryTreeReduce.java:53-64 analog).
      prefetch_depth: packed-wire transfers kept in flight ahead of the device
        consumer on the fast ingest path (io/wire.py WirePrefetcher).
      wire_encoding: ingest wire format on the packed fast path.  "plain"
        ships each batch in arrival order at the narrowest fixed width
        (io/wire.py width_for_capacity).  "ef40" sorts each micro-batch and
        ships the Elias-Fano multiset (~2.6-2.9 B/edge vs 5) — legal only for
        order-free aggregations (SummaryAggregation.order_free) with
        vertex_capacity <= 2^20.  "auto" picks per host: ef40 when the
        descriptor is order-free, ids fit, and the host has spare cores to
        sort on (>= 2); plain otherwise (on a single-core host the radix sort
        competes with the transfer for the same CPU and loses).
    """

    vertex_capacity: int = 1 << 16
    max_degree: int = 64
    batch_size: int = 1 << 10
    num_shards: int = 1
    window_ms: int = 1000
    tree_degree: int = 2
    prefetch_depth: int = 8
    wire_encoding: str = "auto"
    # full batches between positional snapshots on the wire fast path (0 =
    # snapshot only at stream end); each snapshot downloads the fold carry,
    # so the interval trades recovery granularity against ingest rate
    wire_checkpoint_batches: int = 64
    # Ingestion-time pane cut (the reference's DEFAULT mode: wall-clock
    # tumbling windows with running emission, SimpleEdgeStream.java:69-73).
    # Without either knob an untimed stream forms one global pane flushed at
    # end-of-stream — correct for finite tests, but an infinite untimed
    # source would never emit.  Set ingest_window_edges (deterministic:
    # close a pane every N arrivals) or ingest_window_ms (wall-clock; panes
    # cut at batch boundaries) to get per-window running summaries.  When
    # set, aggregation panes are cut by ARRIVAL — any event timestamps the
    # stream carries are ignored (pick one time characteristic per
    # pipeline, as the reference's two ctors do).
    ingest_window_edges: int = 0
    ingest_window_ms: int = 0
    # Superbatch dispatch coalescing: fold up to this many prefetched
    # micro-batches (wire fast path) or closed panes (windowed paths) into
    # ONE device call, amortizing the per-dispatch Python/runtime overhead
    # that dominates once the device is ~100x faster than the host feeding
    # it.  Groups are cut to power-of-two bucket sizes and never cross an
    # emission or snapshot boundary, so results and recovery semantics are
    # bit-identical to per-batch dispatch (pinned by tests/test_superbatch).
    # 0/1 = off (per-batch dispatch, the historical behavior).
    superbatch: int = 0
    # Host ingest worker count for parallel parsing/packing (io/ingest.py).
    # 0 = auto: the GELLY_INGEST_WORKERS env var when set, else the
    # process's usable core count.  1 = single-threaded.
    ingest_workers: int = 0
    # Asynchronous window pipeline (core/async_exec.py): keep up to this
    # many closed windows in flight end to end — pane packing on the
    # prefetcher's pack thread (ingest-pool assisted), transfers on its
    # second thread, device folds dispatched without waiting, and window
    # emissions resolved through a completion queue drained in window-id
    # order, so the record sequence (and checkpoint semantics) is
    # bit-identical to the synchronous path (pinned by
    # tests/test_async_windows.py).  0 = synchronous lockstep (the
    # historical behavior and the equivalence oracle); when left at 0 the
    # GELLY_ASYNC_WINDOWS env var may switch it on process-wide.
    async_windows: int = 0
    # Owner-sharded summary state on the mesh data plane
    # (core/sharded_state.py): persistent per-shard summary state is an
    # O(C/S) modulo block; cross-shard reconciliation exchanges pow2-bucketed
    # delta buffers at emission/snapshot boundaries; the replicated view is
    # gathered lazily only there.  1 = on, 0 = off (the all_gather-replicated
    # combine, which remains the equivalence oracle), -1 = auto: the
    # GELLY_SHARDED_STATE env var when set, else ON for descriptors that
    # supply a ShardedStateSpec.  Descriptors without a spec always use the
    # replicated combine regardless of this knob.
    sharded_state: int = -1
    # Propagation-blocking ingest (io/wire.py BDV, ops/wire_decode.py).
    # binned_ingest: bin/sort each value-less micro-batch or pane by
    # (dst, src) before packing, so device folds scatter segment-locally
    # (cache-resident summary rows instead of random [C] misses) and the
    # sharded pane plane's host keyBy runs on the parallel ingest pool.
    # Legal only for ORDER-FREE aggregations (the fold sees the same
    # multiset); order-sensitive consumers refuse a forced 1.  1 = on,
    # 0 = off (the arrival-order oracle), -1 = defer to the
    # GELLY_BINNED_INGEST env var (default off).
    binned_ingest: int = -1
    # wire_compress: ship binned batches delta/varint-compressed (BDV:
    # sorted dst deltas + run-relative src, decoded on device inside the
    # same cached fold executable).  Implies binned_ingest; needs
    # vertex_capacity <= 2^28.  1 = on, 0 = off (the plain fixed-width
    # oracle), -1 = defer to GELLY_WIRE_COMPRESS (default off).
    wire_compress: int = -1
    # Cross-tenant fused dispatch (runtime/manager.py): under a JobManager,
    # same-shape ready windows from N tenant jobs stack into ONE vmapped
    # mega-fold through the shared superpane executable
    # (core/aggregation.py `_superpane_fold_fn`) instead of N solo
    # dispatches — the superbatch row-per-window layout generalized across
    # jobs.  Applies to the single-partition windowed pane plane only;
    # wire/async/superbatch/sharded jobs keep their own planes.  1 = on,
    # 0 = off (per-job solo dispatch, the bit-exact equivalence oracle),
    # -1 = defer to the GELLY_FUSED_DISPATCH env var (default off).
    # Emission order, fairness accounting, checkpoints, and record bytes
    # are identical either way (pinned by tests/test_fused_dispatch.py).
    fused_dispatch: int = -1
    # Per-window span tracing (utils/tracing.py): sample rate in (0, 1]
    # for the flight-recorder spans that time each window across
    # pack -> transfer -> dispatch -> drain -> emit.  0 = off (the
    # default): planes resolve their sampler once outside the loop, so
    # the hot path pays one branch and nothing else — no clock reads, no
    # locks, emissions bit-identical with tracing on or off (pinned by
    # tests/test_tracing.py).  When left at 0 the GELLY_TRACE_SAMPLE env
    # var may switch it on process-wide (the async_windows pattern).
    # Sampling is a deterministic stride (every round(1/rate)-th window).
    trace_sample: float = 0.0
    # Push/pull direction optimization for the masked-SpMV kernel core
    # (ops/spmv.py): iterative vertex programs (sssp, pagerank, ...) pick
    # per-regime between a sparse push lowering (SpMSpV: expand the active
    # frontier's CSR rows into a pow2-bucketed candidate buffer and
    # scatter-reduce) and a dense pull lowering (SpMV: one gather over
    # dst-sorted edges + a sorted segment reduce).  spmv_direction:
    # "push"/"pull" force one lowering for every iteration; "auto" switches
    # on frontier density; "" (default) defers to the GELLY_SPMV_DIRECTION
    # env var (default auto).  Results are bit-identical in every mode
    # (pinned by tests/test_spmv.py) — this is a performance knob only.
    spmv_direction: str = ""
    # Frontier-density threshold for "auto": iterate push while
    # |frontier| / |active vertices| <= threshold, pull above it.
    # -1.0 (default) defers to GELLY_DIRECTION_THRESHOLD, then the kernel
    # default (ops/spmv.DEFAULT_DIRECTION_THRESHOLD).
    direction_threshold: float = -1.0
    # Bounded event-time out-of-orderness (ms): 0 keeps the reference's
    # ascending-timestamp contract (SimpleEdgeStream.java:86-90); positive
    # values trail the watermark behind max seen time by the bound, holding
    # windows open for stragglers and routing later-than-bound records to
    # the late sink (core/windows.assign_tumbling_windows).
    # Applies to the single-host event-time assigner only: the multi-host
    # gated assigners (parallel/multihost.py) close panes on GLOBAL
    # watermark agreement with their own on_late callback and do not use
    # this bound.
    out_of_orderness_ms: int = 0

    def __post_init__(self):
        if self.wire_encoding not in ("auto", "plain", "ef40"):
            raise ValueError(f"unknown wire_encoding {self.wire_encoding!r}")
        if self.out_of_orderness_ms < 0:
            raise ValueError("out_of_orderness_ms must be >= 0")
        if self.out_of_orderness_ms and (
            self.ingest_window_edges or self.ingest_window_ms
        ):
            raise ValueError(
                "out_of_orderness_ms applies to event-time windows only; "
                "ingestion-time panes window by arrival order"
            )
        if self.ingest_window_edges < 0 or self.ingest_window_ms < 0:
            raise ValueError("ingest window knobs must be >= 0")
        if self.ingest_window_edges and self.ingest_window_ms:
            raise ValueError(
                "set only one of ingest_window_edges / ingest_window_ms"
            )
        if self.wire_checkpoint_batches < 0:
            raise ValueError("wire_checkpoint_batches must be >= 0")
        if self.superbatch < 0:
            raise ValueError("superbatch must be >= 0")
        if self.ingest_workers < 0:
            raise ValueError("ingest_workers must be >= 0")
        if self.async_windows < 0:
            raise ValueError("async_windows must be >= 0")
        if not (0.0 <= self.trace_sample <= 1.0):
            raise ValueError("trace_sample must be in [0, 1]")
        if self.sharded_state not in (-1, 0, 1):
            raise ValueError("sharded_state must be -1 (auto), 0, or 1")
        if self.binned_ingest not in (-1, 0, 1):
            raise ValueError("binned_ingest must be -1 (auto), 0, or 1")
        if self.wire_compress not in (-1, 0, 1):
            raise ValueError("wire_compress must be -1 (auto), 0, or 1")
        if self.fused_dispatch not in (-1, 0, 1):
            raise ValueError("fused_dispatch must be -1 (auto), 0, or 1")
        if self.spmv_direction not in ("", "auto", "push", "pull"):
            raise ValueError(
                "spmv_direction must be ''/auto/push/pull "
                "('' defers to GELLY_SPMV_DIRECTION)"
            )
        if self.direction_threshold != -1.0 and not (
            0.0 <= self.direction_threshold <= 1.0
        ):
            raise ValueError(
                "direction_threshold must be -1 (defer) or a density in [0, 1]"
            )
        if self.wire_compress == 1 and self.binned_ingest == 0:
            raise ValueError(
                "wire_compress=1 needs binned batches (delta encoding rides "
                "the sorted bins); don't force binned_ingest=0 with it"
            )
        if self.wire_compress == 1:
            from gelly_streaming_tpu.io.wire import BDV_MAX_ID_BITS

            if self.vertex_capacity > 1 << BDV_MAX_ID_BITS:
                raise ValueError(
                    f"wire_compress needs vertex_capacity <= "
                    f"2^{BDV_MAX_ID_BITS} (BDV varints)"
                )
        if self.vertex_capacity <= 0:
            raise ValueError("vertex_capacity must be positive")
        if self.num_shards <= 0:
            raise ValueError("num_shards must be positive")
        if self.vertex_capacity % self.num_shards != 0:
            raise ValueError(
                f"vertex_capacity ({self.vertex_capacity}) must be divisible by "
                f"num_shards ({self.num_shards}) for even sharding"
            )

    @property
    def shard_capacity(self) -> int:
        return self.vertex_capacity // self.num_shards


DEFAULT_CONFIG = StreamConfig()


# SLO gauge-metric vocabulary: spec metric name -> (health gauge key,
# violation direction).  "gt" = a sample above the threshold is bad (lag,
# backlog); "lt" = below is bad (keep-up ratio).  Gauge metrics read the
# per-job health rows (utils.metrics.all_job_health), so they are job-scope
# only; histogram metrics (``p99_window_close_to_emission_ms`` style) work
# at job, tenant, and global scope.
SLO_GAUGE_METRICS = {
    "max_backlog_age_s": ("backlog_age_s", "gt"),
    "max_backlog_batches": ("backlog_batches", "gt"),
    "max_watermark_lag_windows": ("watermark_lag_windows", "gt"),
    "min_keepup_ratio": ("keepup_ratio", "lt"),
}

# pNN_<histogram name>: the quantile prefix both names the intent and
# fixes the error budget (p99 <= T  ==  at most 1% of samples over T)
_SLO_HIST_RE = re.compile(r"^p(\d{1,2}(?:\.\d+)?)_([a-z0-9_]+_ms)$")


@dataclasses.dataclass(frozen=True)
class SLOSpec:
    """One declarative service-level objective (evaluated by
    runtime/slo.py's monitor against the existing histograms/gauges).

    The metric grammar:

    * ``p99_window_close_to_emission_ms`` (any ``pNN_<histogram>_ms``) —
      "NN% of samples of that latency histogram stay under ``threshold``
      ms".  The quantile prefix derives the error budget (p99 -> 1% of
      samples may exceed), unless ``error_budget`` overrides it.
    * a :data:`SLO_GAUGE_METRICS` name (``max_backlog_age_s``,
      ``min_keepup_ratio``, ...) — "the job's gauge stays on the right
      side of ``threshold``".  Each monitor tick samples the gauge; the
      budget is the tolerated fraction of violating ticks (default 0.1).

    Alerting follows the SRE multiwindow burn-rate pattern: the bad-sample
    fraction over a FAST and a SLOW window, each divided by the budget, is
    the burn rate; WARN needs both windows at ``warn_burn``+, PAGE both at
    ``page_burn``+ (the fast window makes alerts responsive, the slow one
    keeps a brief blip from paging).  De-escalation is hysteretic: one
    level down per ``clear_hold`` consecutive below-warn evaluations, so a
    flapping metric cannot oscillate OK<->PAGE at tick rate.

    ``scope`` picks the registry ("job"/"tenant"/"global") and ``target``
    is an fnmatch pattern over instance ids (server jobs are
    ``tenant/name``), so one spec fans out over every matching live job.
    """

    metric: str
    threshold: float
    scope: str = "job"
    target: str = "*"
    name: str = ""
    fast_window_s: float = 60.0
    slow_window_s: float = 600.0
    warn_burn: float = 1.0
    page_burn: float = 4.0
    error_budget: float = 0.0  # 0 = derive (pNN prefix, or 0.1 for gauges)
    clear_hold: int = 3

    def __post_init__(self):
        if self.scope not in ("job", "tenant", "global"):
            raise ValueError("SLO scope must be job/tenant/global")
        if self.threshold <= 0:
            raise ValueError("SLO threshold must be positive")
        if not (0 < self.fast_window_s < self.slow_window_s):
            raise ValueError(
                "SLO windows need 0 < fast_window_s < slow_window_s"
            )
        if not (0 < self.warn_burn <= self.page_burn):
            raise ValueError("SLO burns need 0 < warn_burn <= page_burn")
        if not (0.0 <= self.error_budget < 1.0):
            raise ValueError("error_budget must be in [0, 1)")
        if self.clear_hold < 1:
            raise ValueError("clear_hold must be >= 1 evaluation")
        if self.metric in SLO_GAUGE_METRICS:
            if self.scope != "job":
                raise ValueError(
                    f"gauge SLO metric {self.metric!r} is job-scope only "
                    "(gauges live in the per-job health rows)"
                )
        elif not _SLO_HIST_RE.match(self.metric):
            raise ValueError(
                f"unknown SLO metric {self.metric!r}: expected a "
                f"pNN_<histogram>_ms quantile objective or one of "
                f"{sorted(SLO_GAUGE_METRICS)}"
            )

    def kind(self) -> tuple:
        """("hist", histogram name, quantile) or ("gauge", key, cmp)."""
        gauge = SLO_GAUGE_METRICS.get(self.metric)
        if gauge is not None:
            return ("gauge",) + gauge
        m = _SLO_HIST_RE.match(self.metric)
        return ("hist", m.group(2), float(m.group(1)))

    def budget(self) -> float:
        """Effective error budget (explicit wins; else pNN-derived for
        histogram objectives, 0.1 of ticks for gauge objectives)."""
        if self.error_budget > 0:
            return self.error_budget
        kind = self.kind()
        if kind[0] == "hist":
            return max(1.0 - kind[2] / 100.0, 1e-4)
        return 0.1

    def alert_name(self) -> str:
        return self.name or self.metric


@dataclasses.dataclass(frozen=True)
class AutoscalePolicy:
    """Policy knobs for the elastic control plane (runtime/autoscale.py).

    The autoscaler turns the health plane's verdicts into geometry
    decisions: a job whose SLO alert has sat at PAGE for ``page_hold``
    consecutive policy evaluations is drained and resubmitted at
    ``factor``x its shard count (up to ``max_shards``); a job that has
    been over-provisioned-idle (keep-up ratio at/above ``idle_keepup``
    with an empty backlog and no burning alert) for ``idle_hold``
    evaluations shrinks by the same factor (down to ``min_shards``),
    returning ``max_state_bytes`` budget headroom to admission.

    Hysteresis comes in two layers: the burn-rate state machine
    (OK -> WARN -> PAGE with clear-hold, runtime/slo.py) gates what counts
    as "burning" at all, and the streak/hold counters here demand the
    verdict be SUSTAINED across evaluations — a single paged tick never
    moves a shard.  ``cooldown_s`` then keeps a freshly rescaled job from
    flapping: its streaks restart and no new decision fires until the
    quiet period elapses.

    Attributes:
      factor: geometric step per decision (2 = double / halve).
      min_shards: floor for scale-down decisions.
      max_shards: ceiling for scale-up decisions; 0 defers entirely to the
        actuator's own eligibility check (device count, capacity
        divisibility), which always applies.
      page_hold: consecutive policy evaluations a job-scope alert must sit
        at PAGE before a scale-up fires.
      idle_hold: consecutive idle evaluations before a scale-down fires.
      idle_keepup: keep-up ratio at/above which a backlog-free job counts
        as over-provisioned (drain rate >= this multiple of arrivals).
      cooldown_s: per-job quiet period after a rescale (or a failed one —
        a failing actuator must not be retried at tick rate).
      interval_s: seconds between policy evaluations.
    """

    factor: int = 2
    min_shards: int = 1
    max_shards: int = 0
    page_hold: int = 3
    idle_hold: int = 10
    idle_keepup: float = 4.0
    cooldown_s: float = 30.0
    interval_s: float = 1.0

    def __post_init__(self):
        if self.factor < 2:
            raise ValueError("autoscale factor must be >= 2")
        if self.min_shards < 1:
            raise ValueError("min_shards must be >= 1")
        if self.max_shards < 0:
            raise ValueError("max_shards must be >= 0 (0 = actuator-bound)")
        if self.max_shards and self.max_shards < self.min_shards:
            raise ValueError("max_shards must be >= min_shards")
        if self.page_hold < 1 or self.idle_hold < 1:
            raise ValueError("page_hold/idle_hold must be >= 1 evaluation")
        if self.idle_keepup <= 1.0:
            raise ValueError(
                "idle_keepup must be > 1.0 (a job merely keeping up is "
                "not over-provisioned)"
            )
        if self.cooldown_s < 0:
            raise ValueError("cooldown_s must be >= 0")
        if self.interval_s <= 0:
            raise ValueError("autoscale interval_s must be positive")


@dataclasses.dataclass(frozen=True)
class RuntimeConfig:
    """Knobs for the multi-tenant job runtime (runtime/manager.py).

    ``StreamConfig`` shapes ONE query's pipeline; this shapes the process
    that runs many of them over one device.  Admission limits are hard caps
    enforced at ``JobManager.submit`` — rejection is an explicit
    ``AdmissionError``, never a queue that silently grows or a submit that
    hangs.

    Attributes:
      max_jobs: concurrent non-terminal jobs admitted (the reference's
        cluster-slot analog: a Flink job needs a free task slot or the
        submission is rejected up front).
      max_state_bytes: aggregate summary-state footprint across admitted
        jobs (descriptor ``state_nbytes`` at admission; 0 = unbounded).
        Bounds device/arena memory, which job count alone does not: one
        2^24-capacity job outweighs dozens of 2^16 ones.
      job_queue_depth: per-job bounded emission queue length — the
        isolation boundary between the shared dispatch loop and each job's
        sink.  A full queue makes that ONE job unrunnable for the round;
        it never blocks the scheduler thread.
      fair_quantum: iterator pulls per unit of job weight per scheduling
        round.  A weight-2 job gets twice the pulls of a weight-1 job per
        round — weighted fairness in dispatch opportunities, which for
        same-shape windows is weighted fairness in device time.
      keep_terminal_jobs: finished/failed/cancelled jobs retained for
        ``status()`` history.  Older terminal jobs are evicted at the next
        submit (their source closures were already dropped at the terminal
        transition), bounding a long-lived serving process's footprint.
      health_sample_s: interval at which the scheduler loop samples each
        live job's keep-up gauges (watermark lag, backlog depth/age, EWMA
        arrival vs drain rates) into utils.metrics' health registry.  The
        sampler reads host-side Python counters only — never a device
        sync — so the default-on 1 Hz costs one clock check per scheduler
        round.  0 disables sampling entirely.
      slos: declarative :class:`SLOSpec` objectives.  Non-empty starts the
        burn-rate monitor thread (runtime/slo.py) alongside the scheduler;
        the empty default costs nothing — no thread, no branch in the
        data planes.
      slo_interval_s: seconds between SLO monitor evaluations (each one
        reads histogram/gauge registries and updates the alert rows).
      autoscale: the elastic control plane switch (runtime/autoscale.py).
        1 starts the scaling-policy thread alongside the scheduler, 0
        forces it off, -1 (default) defers to the ``GELLY_AUTOSCALE`` env
        var, defaulting OFF — the passive health plane stays exactly what
        it was unless an operator closes the loop explicitly.
      autoscale_policy: the :class:`AutoscalePolicy` thresholds the policy
        thread evaluates (holds, factor, cooldown, interval).
    """

    max_jobs: int = 8
    max_state_bytes: int = 0
    job_queue_depth: int = 64
    fair_quantum: int = 4
    keep_terminal_jobs: int = 64
    health_sample_s: float = 1.0
    slos: tuple = ()
    slo_interval_s: float = 0.5
    autoscale: int = -1
    autoscale_policy: AutoscalePolicy = AutoscalePolicy()

    def __post_init__(self):
        if self.max_jobs <= 0:
            raise ValueError("max_jobs must be positive")
        if self.max_state_bytes < 0:
            raise ValueError("max_state_bytes must be >= 0 (0 = unbounded)")
        if self.job_queue_depth <= 0:
            raise ValueError("job_queue_depth must be positive")
        if self.fair_quantum <= 0:
            raise ValueError("fair_quantum must be positive")
        if self.keep_terminal_jobs < 0:
            raise ValueError("keep_terminal_jobs must be >= 0")
        if self.health_sample_s < 0:
            raise ValueError("health_sample_s must be >= 0 (0 = off)")
        if self.slo_interval_s <= 0:
            raise ValueError("slo_interval_s must be positive")
        if not all(isinstance(s, SLOSpec) for s in self.slos):
            raise ValueError("slos must be a tuple of SLOSpec")
        if self.autoscale not in (-1, 0, 1):
            raise ValueError("autoscale must be -1 (auto), 0, or 1")
        if not isinstance(self.autoscale_policy, AutoscalePolicy):
            raise ValueError("autoscale_policy must be an AutoscalePolicy")


@dataclasses.dataclass(frozen=True)
class TenantConfig:
    """One tenant of the streaming RPC serving plane (runtime/server.py).

    ``RuntimeConfig`` caps the PROCESS; a tenant is a slice of it.  The
    serving plane authenticates every request by token, then enforces the
    tenant's own admission caps on top of the manager's global ones and
    multiplies the tenant's ``weight`` into every job weight it submits —
    priorities ride the existing weighted-fair scheduler rather than a
    second queueing tier.

    Attributes:
      tenant: tenant id (job names are scoped per tenant).
      token: shared-secret auth token carried on every request frame.  An
        empty token is only legal in OPEN mode (a server configured with
        zero tenants runs a single implicit open tenant).
      max_jobs: concurrent non-terminal jobs this tenant may hold
        (0 = no per-tenant cap; the global ``RuntimeConfig.max_jobs``
        still applies).
      max_state_bytes: aggregate admitted summary-state bytes across this
        tenant's live jobs (0 = uncapped below the global cap).
      max_ingest_bps: wire bytes/second of network edge ingest this tenant
        may push (token bucket; 0 = unlimited).  Enforced by throttling the
        pushing CONNECTION — the backpressure lands on that tenant's
        socket, never on the scheduler or other tenants.
      weight: scheduler priority multiplier: an admitted job runs at
        ``tenant.weight * job.weight`` in the weighted-fair rounds.
    """

    tenant: str = "default"
    token: str = ""
    max_jobs: int = 0
    max_state_bytes: int = 0
    max_ingest_bps: int = 0
    weight: int = 1

    def __post_init__(self):
        if not self.tenant:
            raise ValueError("tenant id must be non-empty")
        if self.max_jobs < 0:
            raise ValueError("tenant max_jobs must be >= 0 (0 = uncapped)")
        if self.max_state_bytes < 0:
            raise ValueError("tenant max_state_bytes must be >= 0")
        if self.max_ingest_bps < 0:
            raise ValueError("tenant max_ingest_bps must be >= 0")
        if self.weight <= 0:
            raise ValueError("tenant weight must be positive")


@dataclasses.dataclass(frozen=True)
class ServerConfig:
    """Knobs for the streaming RPC server (runtime/server.py).

    The serving plane's load is connections and request frames, not a local
    file — these caps bound what one listener process will accept.  Frame
    and queue limits are refusal boundaries (a too-large frame gets a clean
    error frame, an over-quota submit an admission error), never silent
    growth.

    Attributes:
      host/port: listen address; port 0 binds an ephemeral port (read it
        back from ``StreamServer.port``).
      tenants: authenticated tenants.  Empty = OPEN mode: one implicit
        ``default`` tenant with no token and no per-tenant caps (tests,
        loopback benches).  Tokens and tenant ids must be unique.
      max_frame_bytes: hard cap on one frame's binary payload; oversized
        frames are refused with an error frame and the connection closed
        (the stream cannot be resynced past an unread giant payload).
      max_connections: concurrent client connections; excess accepts are
        refused with an error frame.
      ingest_queue_batches: per-source bounded queue of decoded wire
        batches between a pushing connection and its job — the network
        isolation boundary: a full queue blocks THAT connection's reader
        (TCP backpressure to that client), never the scheduler.
      result_buffer_records: per-job buffered emissions awaiting a
        ``results`` fetch; a full buffer blocks that job's sink pump (its
        bounded emission queue then skips only that job's rounds).
      checkpoint_prefix: when set, jobs submitted with ``checkpoint: true``
        get per-(tenant, job) snapshot files derived from this prefix
        (utils.checkpoint.per_job_file) — the durable state that makes
        drain/restart resume bit-exactly.
      decode_workers: size of the GIL-free native decode pool
        (runtime/decode_pool.py) that validates + decodes pushed wire
        buffers into transfer arenas off the interpreter.  -1 (default)
        defers to the ``GELLY_DECODE_WORKERS`` env var (then the pool's
        own default); 0 disables the pool — pushes take the pure-Python
        decode path, the bit-identical equivalence oracle.
    """

    host: str = "127.0.0.1"
    port: int = 0
    tenants: tuple = ()
    max_frame_bytes: int = 1 << 26
    max_connections: int = 64
    ingest_queue_batches: int = 64
    result_buffer_records: int = 1024
    checkpoint_prefix: "str | None" = None
    decode_workers: int = -1

    def __post_init__(self):
        if not (0 <= self.port <= 65535):
            raise ValueError("port must be in [0, 65535]")
        if self.max_frame_bytes < (1 << 12):
            raise ValueError("max_frame_bytes must be >= 4096")
        if self.max_connections <= 0:
            raise ValueError("max_connections must be positive")
        if self.ingest_queue_batches <= 0:
            raise ValueError("ingest_queue_batches must be positive")
        if self.result_buffer_records <= 0:
            raise ValueError("result_buffer_records must be positive")
        if self.decode_workers < -1:
            raise ValueError(
                "decode_workers must be >= -1 (-1 defers to "
                "GELLY_DECODE_WORKERS, 0 disables the decode pool)"
            )
        ids = [t.tenant for t in self.tenants]
        if len(set(ids)) != len(ids):
            raise ValueError(f"duplicate tenant ids: {sorted(ids)}")
        tokens = [t.token for t in self.tenants]
        if len(set(tokens)) != len(tokens):
            raise ValueError("tenant tokens must be unique")
        if self.tenants and any(not t.token for t in self.tenants):
            raise ValueError(
                "configured tenants need non-empty tokens (an empty token "
                "is only legal in open mode: tenants=())"
            )

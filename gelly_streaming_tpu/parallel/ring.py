"""Ring-parallel vertex-feature exchange over the mesh (ICI ppermute rounds).

The framework's analog of ring attention / context parallelism for long
sequences: the vertex *feature matrix* ``X: [C, F]`` is the large sharded
operand (the K/V analog), and neighborhood aggregation over a window's padded
neighborhoods is the contraction that needs remote rows.  Replicating X per
shard (``all_gather``) costs C*F memory per device; the ring instead rotates
feature *blocks* around the mesh — S-1 ``ppermute`` hops — while every shard
accumulates the rows it needs from the visiting block.  Peak memory per shard
stays at one block (C/S rows), and the per-hop transfer overlaps with the
gather+accumulate compute, exactly the ring-attention schedule.

Ownership is modulo (vertex v lives in block ``v % S`` at row ``v // S``),
matching parallel/mesh.owner_of.  All functions are called inside shard_map
over the ``shards`` axis.

Used by library/graphsage.py's sharded path; any windowed neighborhood
aggregation over sharded per-vertex payloads can reuse it.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from gelly_streaming_tpu.parallel.mesh import SHARD_AXIS


def ring_neighbor_features(
    block: jax.Array,
    keys: jax.Array,
    nbrs: jax.Array,
    valid: jax.Array,
    num_shards: int,
    axis_name: str = SHARD_AXIS,
):
    """Gather self features and masked neighbor means via a feature ring.

    Args (per shard, inside shard_map):
      block: [C/S, F] this shard's feature block (modulo ownership).
      keys:  [K] global vertex ids whose neighborhoods this shard processes.
      nbrs:  [K, D] padded global neighbor ids.
      valid: [K, D] neighbor validity mask.

    Returns (x_self [K, F], mean_nbr [K, F], count [K]) in float32:
    ``x_self[i] = X[keys[i]]``, ``mean_nbr[i]`` the mean of the valid
    neighbors' features (zeros when none), ``count[i]`` their number.
    """
    rows = block.shape[0]
    k = keys.shape[0]
    f = block.shape[1]
    perm = [(i, (i + 1) % num_shards) for i in range(num_shards)]
    me = jax.lax.axis_index(axis_name)

    blk = block
    x_self = jnp.zeros((k, f), jnp.float32)
    acc = jnp.zeros((k, f), jnp.float32)
    cnt = jnp.zeros((k,), jnp.int32)
    # Unrolled ring schedule (num_shards is static and small): S accumulate
    # steps, S-1 rotations — the final rotation would only restore the
    # starting layout, so it is skipped.
    for t in range(num_shards):
        owner = jnp.mod(me - t, num_shards)  # whose block is visiting now
        # neighbor rows served by the visiting block
        sel = valid & (nbrs % num_shards == owner)
        feats = blk[jnp.clip(nbrs // num_shards, 0, rows - 1)]  # [K, D, F]
        w = sel[:, :, None].astype(jnp.float32)
        acc = acc + jnp.sum(feats.astype(jnp.float32) * w, axis=1)
        cnt = cnt + jnp.sum(sel, axis=1)
        # self rows served by the visiting block
        ksel = (keys % num_shards == owner)[:, None].astype(jnp.float32)
        kfeat = blk[jnp.clip(keys // num_shards, 0, rows - 1)]
        x_self = x_self + kfeat.astype(jnp.float32) * ksel
        if t < num_shards - 1:
            # rotate: my block moves to the next shard, the previous shard's
            # block arrives here (overlaps with the next step's compute)
            blk = jax.lax.ppermute(blk, axis_name, perm)

    mean = acc / jnp.maximum(cnt, 1).astype(jnp.float32)[:, None]
    return x_self, mean, cnt


def ring_lookup(
    block: jax.Array,
    queries: jax.Array,
    num_shards: int,
    axis_name: str = SHARD_AXIS,
):
    """Answer arbitrary global-id lookups against a modulo-sharded table.

    ``block``: [C/S, ...] this shard's rows of the table (vertex/slot g lives
    on shard ``g % S`` at row ``g // S``).  ``queries``: [Q] global ids, any
    owner.  Returns ``table[queries]`` with the table never materialized on
    one device: the S blocks rotate around the ring (S-1 ``ppermute`` hops)
    and each visiting block answers the queries it owns.

    This is the capacity-safe alternative to bucketing queries by owner into
    an ``all_to_all``: a skewed query set (all ids on one shard) would force
    the bucket capacity to Q per (sender, receiver) pair, an S-fold comm
    blowup or a drop policy — the ring's cost is a flat C ints per lookup
    round regardless of the query distribution, and every query is answered.
    """
    rows = block.shape[0]
    perm = [(i, (i + 1) % num_shards) for i in range(num_shards)]
    me = jax.lax.axis_index(axis_name)
    blk = block
    ans = jnp.zeros(queries.shape[:1] + block.shape[1:], block.dtype)
    for t in range(num_shards):
        owner = jnp.mod(me - t, num_shards)  # whose block is visiting now
        sel = (queries % num_shards) == owner
        vals = blk[jnp.clip(queries // num_shards, 0, rows - 1)]
        ans = jnp.where(
            sel.reshape(sel.shape + (1,) * (vals.ndim - 1)), vals, ans
        )
        if t < num_shards - 1:
            blk = jax.lax.ppermute(blk, axis_name, perm)
    return ans


def ring_scatter_min(
    block: jax.Array,
    idx: jax.Array,
    val: jax.Array,
    num_shards: int,
    axis_name: str = SHARD_AXIS,
):
    """Fold arbitrary (global id, value) scatter-min updates into a
    modulo-sharded table — the WRITE counterpart of ``ring_lookup``.

    The blocks make one full loop around the ring (S ``ppermute`` hops); at
    each hop every shard scatter-mins the updates it holds for the currently
    visiting block, so after the loop each block is back home having
    absorbed every shard's updates.  Like the lookup, the cost is a flat C
    values per pass regardless of how the update ids are distributed — no
    per-(sender, receiver) capacities, no drops, no skew sensitivity.

    Masked updates should carry the dtype's max as ``val`` (a no-op min).
    """
    rows = block.shape[0]
    perm = [(i, (i + 1) % num_shards) for i in range(num_shards)]
    me = jax.lax.axis_index(axis_name)
    blk = block
    big = (
        jnp.finfo(block.dtype).max
        if jnp.issubdtype(block.dtype, jnp.inexact)
        else jnp.iinfo(block.dtype).max
    )
    for t in range(num_shards):
        owner = jnp.mod(me - t, num_shards)  # whose block is visiting now
        sel = (idx % num_shards) == owner
        r = jnp.clip(idx // num_shards, 0, rows - 1)
        blk = blk.at[jnp.where(sel, r, 0)].min(jnp.where(sel, val, big))
        # rotate even on the last step: S hops bring every block home
        blk = jax.lax.ppermute(blk, axis_name, perm)
    return blk


def shard_features(features, num_shards: int):
    """[C, F] host features -> [S, C/S, F] modulo-ownership blocks."""
    import numpy as np

    c = features.shape[0]
    if c % num_shards:
        raise ValueError(
            f"feature rows ({c}) must divide evenly into {num_shards} blocks"
        )
    return np.stack([features[s::num_shards] for s in range(num_shards)])

"""Multi-host ingest plane: watermark agreement and window-close barriers.

The reference inherits cross-worker time agreement from Flink: sources emit
watermarks, the runtime broadcasts them along dataflow edges, and a window
fires only when the *minimum* watermark across all input channels passes its
end (that is what makes `timeWindowAll` correct with parallel sources).  In
the TPU framework the analogous boundary is between *ingest hosts* feeding a
multi-host mesh over DCN: every host parses + timestamps its partition of the
edge stream locally, and a tumbling pane may close only once **every** host's
watermark has passed the pane end — otherwise a straggler host could still
hold edges for it.

Two transports, matching the two deployment shapes:

* ``ProcessWatermarkBoard`` + ``multihost_tumbling_windows`` — asynchronous
  agreement through a shared in-process board (condition variable).  This is
  the N-ingest-threads-on-one-host shape and the test/simulation transport
  (the MiniCluster analog).
* ``lockstep_tumbling_windows`` over an ``allgather`` callable — synchronous
  agreement for real multi-process runs: every host contributes one watermark
  per round via a collective (``JaxWatermarkBoard.allgather`` =
  ``multihost_utils.process_allgather`` over DCN), hosts that exhaust their
  stream keep participating with an END sentinel until all are done.  The
  collective doubles as the window-close barrier.

Both yield the same contract: every host emits a share (possibly empty) of
exactly the same pane-id sequence in the same order, so downstream cross-host
combines (psum over the mesh, or host gathers) can pair shares positionally.

Late edges — edges for a pane that already closed globally — are dropped with
a warning (Flink's default beyond allowed lateness), via an overridable
``on_late`` hook.  Device-side collectives (the data plane) are unchanged:
they ride ICI inside shard_map; this module aligns only the *time* plane.
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, Iterator, NamedTuple, Optional

import numpy as np

from gelly_streaming_tpu.core.types import EdgeBatch
from gelly_streaming_tpu.core.windows import PaneAssembler, WindowPane, _batch_to_host

logger = logging.getLogger(__name__)

END = int(np.iinfo(np.int64).max)  # "this host is finished" watermark sentinel


class HostEnv(NamedTuple):
    """This process's coordinates in the multi-host job."""

    host_id: int
    num_hosts: int


def distributed_env(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> HostEnv:
    """Resolve (and if needed initialize) the multi-host environment.

    Single-process runs return ``HostEnv(0, 1)`` without touching
    jax.distributed.  Multi-host runs pass coordinator parameters once, first
    thing in the program (before device use), exactly like any jax multi-host
    job; subsequent calls just read process_index/count.
    """
    import jax

    if coordinator_address is not None:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    return HostEnv(jax.process_index(), jax.process_count())


# ---------------------------------------------------------------------------
# Watermark agreement transports
# ---------------------------------------------------------------------------


class ProcessWatermarkBoard:
    """Thread-safe minimum-watermark board for N ingest workers in one process.

    Watermarks are window ids (time // window_ms), monotonically nondecreasing
    per host.  ``finish`` marks a host done (it no longer constrains the
    minimum — Flink's Long.MAX_VALUE watermark on source close) while its last
    real pane id stays visible through ``global_max_pane``.
    """

    END = END

    def __init__(self, num_hosts: int):
        self._marks = [-1] * num_hosts
        self._max_pane = -1  # highest real (non-END) pane id any host reported
        self._cond = threading.Condition()

    def report(self, host_id: int, watermark: int) -> None:
        with self._cond:
            if watermark < self._marks[host_id]:
                raise ValueError(
                    f"watermark of host {host_id} went backwards: "
                    f"{watermark} < {self._marks[host_id]}"
                )
            self._marks[host_id] = watermark
            if watermark != END:
                self._max_pane = max(self._max_pane, watermark)
            self._cond.notify_all()

    def finish(self, host_id: int) -> None:
        self.report(host_id, END)

    def global_watermark(self) -> int:
        with self._cond:
            return min(self._marks)

    def global_max_pane(self) -> int:
        with self._cond:
            return self._max_pane

    def wait_global(self, watermark: int, timeout: Optional[float] = None) -> int:
        """Block until the global (min) watermark reaches ``watermark``."""
        with self._cond:
            ok = self._cond.wait_for(
                lambda: min(self._marks) >= watermark, timeout=timeout
            )
            if not ok:
                raise TimeoutError(
                    f"global watermark stuck at {min(self._marks)} "
                    f"< {watermark} (per-host: {self._marks})"
                )
            return min(self._marks)


class JaxWatermarkBoard:
    """Cross-process transport: one allgather over DCN per agreement round.

    ``allgather`` is a collective — every participating process must call it
    once per round (``lockstep_tumbling_windows`` guarantees that cadence,
    END-padding hosts whose streams end early).

    Watermarks cross the collective as int64 under a local ``enable_x64``
    scope: the framework runs with x64 DISABLED (all kernels are int32), so
    a bare process_allgather would silently canonicalize the int64 marks to
    int32 — truncating the END sentinel (int64 max) to -1, which makes the
    END-agreement test unreachable and spins every host in the shutdown
    phase forever.  Caught by the real two-process jax.distributed test
    (tests/test_multihost_distributed.py); the in-process transports never
    jit, so they cannot see it.
    """

    def allgather(self, local_watermark: int) -> np.ndarray:
        import jax
        from jax.experimental import multihost_utils

        # export location moved across jax versions (top-level >= 0.5,
        # jax.experimental before)
        enable_x64 = getattr(jax, "enable_x64", None)
        if enable_x64 is None:
            from jax.experimental import enable_x64
        with enable_x64(True):
            out = multihost_utils.process_allgather(
                np.asarray(local_watermark, np.int64)
            )
        out = np.atleast_1d(np.asarray(out))
        if out.dtype != np.int64:
            # a canonicalized (int32) result means END came back as -1 — a
            # value indistinguishable from the legitimate 'no data yet' mark,
            # so the ONLY reliable regression guard is the dtype itself
            raise RuntimeError(
                f"watermark transport canonicalized int64 marks to {out.dtype};"
                " END-agreement would never terminate"
            )
        return out


def _default_on_late(pane_id: int, count: int) -> None:
    logger.warning(
        "dropping %d late edge(s) for already-closed pane %d", count, pane_id
    )


# ---------------------------------------------------------------------------
# Watermark-gated window assignment
# ---------------------------------------------------------------------------


class _GatedEmitter:
    """Orders pane closes behind the agreed watermark.

    Single point for the close-and-advance step of both gated assigners, so
    close semantics (empty shares, bookkeeping) cannot diverge between the
    async-board and lockstep paths.  ``through`` is the highest pane id closed
    so far (the late-edge boundary).
    """

    def __init__(self, panes: PaneAssembler):
        self._panes = panes
        self.through = -1

    def drain_below(self, upto: int):
        """Close panes with ids in (through, upto), in order."""
        for wid in range(self.through + 1, upto):
            self.through = wid
            yield self._panes.close(wid)

    def drain_through(self, last: int):
        """Close panes with ids in (through, last], in order."""
        return self.drain_below(last + 1)


def _ingest_batch(panes, batch, window_ms, emitted_through, on_late):
    """Append one batch's edges to open panes; returns (local_mark, had_data).

    Edges for panes at or below ``emitted_through`` (already closed globally)
    are dropped through ``on_late`` — counting them would corrupt closed
    windows.
    """
    src, dst, val, time = _batch_to_host(batch)
    if len(src) == 0:
        return -1, False
    if time is None:
        raise ValueError(
            "multi-host windows need event timestamps (the single-pane "
            "ingestion-time path is single-host only)"
        )
    wids = time // window_ms
    late = wids <= emitted_through
    if late.any():
        for wid in np.unique(wids[late]):
            on_late(int(wid), int((wids == wid).sum()))
        keep = ~late
        src, dst, time, wids = src[keep], dst[keep], time[keep], wids[keep]
        if val is not None:
            import jax

            val = jax.tree.map(lambda a: a[keep], val)
        if len(src) == 0:
            return -1, False
    panes.add(src, dst, val, time, wids)
    return int(wids.max()), True


def multihost_tumbling_windows(
    batches: Iterator[EdgeBatch],
    window_ms: int,
    host_id: int,
    board: ProcessWatermarkBoard,
    timeout: Optional[float] = None,
    on_late: Callable[[int, int], None] = _default_on_late,
    val_proto=None,
) -> Iterator[WindowPane]:
    """This host's share of each tumbling pane, closed on *global* agreement.

    Same pane assembly as core/windows.py:assign_tumbling_windows, but a pane
    [w*window_ms, (w+1)*window_ms) is yielded only once every host's watermark
    has passed w — the straggler-safe close.  Cross-host stragglers are
    handled by that global agreement (plus the ``on_late`` callback for
    records behind this host's own mark); ``StreamConfig.out_of_orderness_ms``
    is a single-host-assigner knob and does not apply here.  All hosts yield shares (possibly
    empty) of the same pane ids in the same order.  For value-carrying
    streams pass ``val_proto`` (a pytree of zero-length arrays) so an empty
    share closed before this host's first val batch stays shape-compatible
    with peers' shares.
    """
    panes = PaneAssembler(window_ms, val_proto=val_proto, has_time=True)
    em = _GatedEmitter(panes)
    local_mark = -1  # this host's watermark: max pane id seen, never regressing

    try:
        for batch in batches:
            mark, had_data = _ingest_batch(
                panes, batch, window_ms, em.through, on_late
            )
            if not had_data:
                continue
            if mark > local_mark:
                local_mark = mark
                board.report(host_id, local_mark)
            # Close every pane the *global* watermark has passed: all hosts
            # have moved beyond it, so no host can still hold edges for it.  A
            # host checks lazily (at its next batch), which only delays
            # emission, never loses or double-emits a pane.  Empty shares keep
            # the sequence aligned across hosts.
            yield from em.drain_below(board.global_watermark())
    finally:
        # Always release the peers — a crashing source or an abandoned pane
        # consumer must not leave other hosts blocked in wait_global forever.
        board.finish(host_id)

    # End of this host's stream: wait for everyone, then every host flushes
    # the same tail — panes up to the globally highest reported pane id, with
    # empty shares where this host held nothing.
    board.wait_global(END, timeout=timeout)
    yield from em.drain_through(board.global_max_pane())


def merge_pane_shares(share_iters) -> Iterator[WindowPane]:
    """Zip multiple ingest hosts' aligned pane-share sequences into whole
    panes.

    Both gated assemblers guarantee every host emits a (possibly empty)
    share of exactly the same pane-id sequence in the same order, so shares
    pair positionally; this merges each position's shares into one pane —
    the glue between the multi-host time plane and a mesh data plane
    (e.g. ``MeshAggregationRunner.run(stream, panes=...)``), standing in for
    the reference's network shuffle out of parallel sources into the keyed
    window (SummaryBulkAggregation.java:78-79).
    """
    import itertools

    import jax

    for shares in itertools.zip_longest(*share_iters):
        if any(s is None for s in shares):
            raise ValueError(
                "pane share sequences diverged across hosts (unequal length)"
            )
        wid = shares[0].window_id
        if any(s.window_id != wid for s in shares):
            raise ValueError(
                f"pane share ids diverged: {[s.window_id for s in shares]}"
            )
        # a host that saw no data (and declared no val_proto) contributes a
        # None val on its empty shares — filter those out (they hold zero
        # edges) instead of feeding a None/pytree mix to tree.map
        vals = [s.val for s in shares if s.val is not None]
        if not vals:
            val = None
        elif len(vals) == 1:
            val = vals[0]
        else:
            val = jax.tree.map(lambda *parts: np.concatenate(parts), *vals)
        times = [s.time for s in shares]
        time = (
            None
            if all(t is None for t in times)
            else np.concatenate([t for t in times if t is not None])
        )
        yield WindowPane(
            wid,
            shares[0].max_timestamp,
            np.concatenate([s.src for s in shares]),
            np.concatenate([s.dst for s in shares]),
            val,
            time,
        )


class _DeadlineRunner:
    """Run (potentially hanging) collectives with a wall-clock deadline.

    A crashed peer leaves survivors blocked inside the allgather forever —
    the transport has no side channel.  Calls run on ONE long-lived DAEMON
    worker thread (no per-round thread churn on the ingest hot path, and —
    unlike a ThreadPoolExecutor, whose non-daemon workers are joined at
    interpreter shutdown — an abandoned stuck worker cannot hang a process
    that is trying to exit after the error).  Exceeding ``timeout`` raises
    TimeoutError on the caller so the survivor fails fast.  After a timeout
    the worker is considered poisoned (it may never return) and a fresh one
    is created for any subsequent call; the process is expected to tear down
    / restart its distributed context on this error.
    """

    def __init__(self):
        self._chan = None  # (request Queue, response Queue) of the live worker

    def run(self, fn: Callable, arg, timeout: Optional[float]):
        if timeout is None:
            return fn(arg)
        import queue as _queue

        if self._chan is None:
            req: "_queue.Queue" = _queue.Queue()
            resp: "_queue.Queue" = _queue.Queue()

            def loop():
                while True:
                    f, a = req.get()
                    try:
                        resp.put((True, f(a)))
                    except BaseException as e:
                        resp.put((False, e))

            threading.Thread(
                target=loop, daemon=True, name="watermark-deadline"
            ).start()
            self._chan = (req, resp)
        req, resp = self._chan
        req.put((fn, arg))
        try:
            ok, val = resp.get(timeout=timeout)
        except _queue.Empty:
            self._chan = None  # worker is stuck in the collective: abandon it
            raise TimeoutError(
                f"watermark collective exceeded {timeout}s — peer host "
                "crashed or wedged; tear down and restart the distributed "
                "context"
            ) from None
        if ok:
            return val
        raise val


def lockstep_tumbling_windows(
    batches: Iterator[EdgeBatch],
    window_ms: int,
    allgather: Callable[[int], np.ndarray],
    on_late: Callable[[int, int], None] = _default_on_late,
    timeout: Optional[float] = None,
    val_proto=None,
) -> Iterator[WindowPane]:
    """Collective-transport variant for real multi-process (DCN) runs.

    Protocol: one ``allgather(local_watermark)`` round per ingested batch.
    Panes below the round's global minimum close immediately (the collective
    is the barrier).  A host whose stream ends keeps joining rounds with the
    END sentinel until every host reports END, so the collective cadence
    always matches across processes even with unequal batch counts; the final
    flush then emits the same tail of pane ids on every host.

    Pass ``JaxWatermarkBoard().allgather`` in a jax.distributed job, or any
    callable with allgather semantics (tests use a thread barrier).  With a
    ``timeout``, a round blocked on a crashed peer raises TimeoutError
    instead of hanging the survivors (see _collective_with_deadline);
    ``val_proto`` declares the stream's value structure as in
    multihost_tumbling_windows.
    """
    panes = PaneAssembler(window_ms, val_proto=val_proto, has_time=True)
    em = _GatedEmitter(panes)
    local_mark = -1
    max_pane = -1  # running max of real pane ids seen anywhere
    deadline = _DeadlineRunner()

    def agree(mark: int):
        nonlocal max_pane
        marks = deadline.run(allgather, mark, timeout)
        real = marks[marks != END]
        if len(real):
            max_pane = max(max_pane, int(real.max()))
        return int(marks.min())

    for batch in batches:
        mark, had_data = _ingest_batch(
            panes, batch, window_ms, em.through, on_late
        )
        if had_data:
            local_mark = max(local_mark, mark)
        yield from em.drain_below(agree(local_mark))

    while True:
        # Stream done here, but other hosts may still be ingesting: keep
        # joining their rounds with the END sentinel, closing panes as the
        # global watermark advances, until everyone reports END.  (A raising
        # source cannot be papered over here — the collective has no side
        # channel — so peers' rounds will time out in their transport.)
        agreed = agree(END)
        if agreed == END:
            break
        yield from em.drain_below(agreed)
    yield from em.drain_through(max_pane)

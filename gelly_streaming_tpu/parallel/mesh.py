"""Device mesh and vertex-space partitioning.

This package is the TPU-native stand-in for the Flink runtime services the
reference consumes (network shuffle via keyBy, broadcast, all-window gather,
iteration feedback — SURVEY.md §2.3/§5.8, pom.xml:38-63): a 1-D
``jax.sharding.Mesh`` over a ``shards`` axis carries the data plane; vertex
ownership is ``vertex_id % num_shards`` over the dense interned id space
(the analog of Flink's key-group hashing).
"""

from __future__ import annotations

import time
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from gelly_streaming_tpu.utils import tracing

SHARD_AXIS = "shards"


def make_mesh(num_shards: Optional[int] = None, devices: Optional[Sequence] = None) -> Mesh:
    """1-D mesh over the first ``num_shards`` devices (default: all)."""
    t0 = time.perf_counter()
    devs = list(devices if devices is not None else jax.devices())
    n = num_shards or len(devs)
    if n > len(devs):
        raise ValueError(f"requested {n} shards but only {len(devs)} devices")
    mesh = Mesh(np.array(devs[:n]), (SHARD_AXIS,))
    # setup-time observability: when tracing is on, the topology a run
    # built (and what it cost) lands in the flight recorder next to the
    # window spans — the first thing a mesh-plane post-mortem checks
    tracing.record_event(
        "mesh",
        "build",
        t0,
        shards=n,
        platform=devs[0].platform if devs else "none",
    )
    return mesh


def owner_of(vertex_ids: np.ndarray, num_shards: int) -> np.ndarray:
    """Owning shard of each vertex (dense interned ids: modulo spreads load)."""
    return vertex_ids % num_shards


def mesh_cache_key(mesh: Mesh):
    """A process-stable hashable identity for a mesh, for executable-cache keys.

    A ``Mesh`` object itself hashes by identity semantics that are not
    guaranteed stable across re-created meshes on every jax version, so
    kernels compiled per mesh key on the raw object could silently retrace
    when a runner is rebuilt.  Device ids + platform + axis names ARE stable
    for the same topology within a process, so two ``make_mesh(n)`` calls
    resolve to the same executables (core/compile_cache.py keys the
    mesh-runner sharded steps on this).
    """
    return (
        tuple((d.platform, d.id) for d in mesh.devices.flat),
        tuple(mesh.axis_names),
    )


def block_rows(capacity: int, num_shards: int) -> int:
    """Rows of one owner block of a [capacity] modulo-sharded state."""
    if capacity % num_shards:
        raise ValueError(
            f"vertex capacity {capacity} must divide over {num_shards} shards"
        )
    return capacity // num_shards


try:  # jax >= 0.5 exports shard_map at top level; older builds under
    # jax.experimental (accessing the missing top-level name raises
    # AttributeError from jax's deprecation shim)
    _shard_map_impl = jax.shard_map
except AttributeError:
    from jax.experimental.shard_map import shard_map as _shard_map_impl


def shard_map(fn, mesh: Mesh, in_specs, out_specs):
    """jax.shard_map with the replication (vma) check disabled.

    The framework's kernels run data-dependent ``while_loop``s whose carries
    change mesh-variance mid-loop (invariant labels become shard-varying after
    hooking local edges, then invariant again after pmin) — valid SPMD that the
    static vma checker rejects.  Handles the check kwarg rename and the
    export location change across jax versions.
    """
    try:
        return _shard_map_impl(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=False
        )
    except TypeError:
        return _shard_map_impl(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=False
        )


def sharded(mesh: Mesh):
    """Sharding for arrays split on their leading axis."""
    return NamedSharding(mesh, P(SHARD_AXIS))


def replicated(mesh: Mesh):
    return NamedSharding(mesh, P())

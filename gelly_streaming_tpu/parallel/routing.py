"""Edge routing: the keyBy shuffle, TPU-style.

The reference's ``keyBy`` is a Netty network shuffle routing each record to the
subtask owning its key (SimpleEdgeStream.java:119,303,492;
SummaryBulkAggregation.java:78).  Here routing happens in two places:

  * host_route: the ingest plane — the host buckets a window pane's edges by
    owning shard and pads to a fixed per-shard capacity, producing the stacked
    [S, B] arrays a ``shard_map`` program consumes (the keyBy-from-source
    analog; SURVEY.md §5.8 "control/ingest plane").
  * device_route: the data plane — re-keying mid-pipeline without leaving the
    mesh, via in-shard bucketing + ``lax.all_to_all`` over ICI.
  * the delta-exchange plane (owner-sharded summary state, ISSUE 4): modulo
    block-sharded per-vertex state reconciles across shards by exchanging
    FIXED-CAPACITY buffers of (changed row, value) pairs — pow2-bucketed so
    shapes stay cache-stable — instead of all_gathering the full state
    (propagation blocking, arXiv:2011.08451; GraphBLAST's frontier/delta
    formulation, arXiv:1908.01407).  ``gather_blocks`` is the sanctioned
    full-view reassembly for emit/snapshot boundaries.

All capacities are pow2-bucketed (``pow2_bucket``): a pane whose occupancy
varies window to window still resolves to one of log2(C) compiled shapes, so
the executable cache never retraces on the sharded path.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from gelly_streaming_tpu.ops import segments
from gelly_streaming_tpu.parallel.mesh import SHARD_AXIS


def pow2_bucket(n: int) -> int:
    """Smallest power of two >= max(n, 1) — the shared shape-bucketing rule
    (same policy as stream.plan_superbatch_groups / the pane fold pads)."""
    return 1 << (max(int(n), 1) - 1).bit_length()


class RoutedEdges(NamedTuple):
    """Stacked per-shard edge arrays: leading axis = shard."""

    src: np.ndarray  # [S, B]
    dst: np.ndarray  # [S, B]
    mask: np.ndarray  # [S, B]
    val: Optional[object] = None  # pytree of [S, B, ...] or None


def host_route(
    src: np.ndarray,
    dst: np.ndarray,
    num_shards: int,
    key: str = "src",
    capacity: Optional[int] = None,
    val=None,
) -> RoutedEdges:
    """Bucket edges by owner shard on the host, padding each bucket to a common
    capacity.  ``key`` picks the routing key ("src" or "dst"); an optional
    ``val`` pytree of per-edge payloads routes alongside the ids.  Relative
    edge order is preserved within each shard, so per-key arrival-order
    semantics survive the shuffle.

    Value-less int32 batches scatter through the native single-pass router
    (native/edge_parser.cpp route_edges — the hash-partitioner analog of the
    reference runtime's shuffle feed); other inputs take the numpy path
    (one boolean-mask selection per shard)."""
    if (
        val is None
        and len(src)
        and src.dtype == np.int32
        and dst.dtype == np.int32
    ):
        from gelly_streaming_tpu.utils.native import load_ingest_lib

        lib = load_ingest_lib()
        if lib is not None and hasattr(lib, "route_edges"):
            cap = capacity or pow2_bucket(
                int(np.bincount(
                    (src if key == "src" else dst) % num_shards,
                    minlength=num_shards,
                ).max())
            )
            s = np.zeros((num_shards, cap), np.int32)
            d = np.zeros((num_shards, cap), np.int32)
            counts = np.zeros((num_shards,), np.int64)
            src_c = np.ascontiguousarray(src)
            dst_c = np.ascontiguousarray(dst)
            import ctypes

            wrote = lib.route_edges(
                src_c.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                dst_c.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                len(src),
                num_shards,
                1 if key == "src" else 0,
                cap,
                s.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                d.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                counts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            )
            if wrote == len(src):  # no overflow: buckets are complete
                m = np.arange(cap)[None, :] < counts[:, None]
                return RoutedEdges(s, d, m, None)
    owner = (src if key == "src" else dst) % num_shards
    counts = np.bincount(owner, minlength=num_shards)
    # auto capacity pow2-buckets (explicit capacities are honored as given):
    # varying pane occupancy across windows resolves to a handful of shapes,
    # so downstream compiled steps reuse cached executables (retrace guard)
    cap = capacity or (pow2_bucket(int(counts.max())) if len(src) else 1)
    s = np.zeros((num_shards, cap), np.int32)
    d = np.zeros((num_shards, cap), np.int32)
    m = np.zeros((num_shards, cap), bool)
    v = None
    if val is not None:
        v = jax.tree.map(
            lambda a: np.zeros((num_shards, cap) + a.shape[1:], a.dtype), val
        )
    for shard in range(num_shards):
        sel = owner == shard
        n = min(int(sel.sum()), cap)
        s[shard, :n] = src[sel][:n]
        d[shard, :n] = dst[sel][:n]
        m[shard, :n] = True
        if v is not None:

            def fill(buf, a):
                buf[shard, :n] = a[sel][:n]
                return buf

            v = jax.tree.map(fill, v, val)
    return RoutedEdges(s, d, m, v)


def owner_rank(owner: jax.Array, mask: jax.Array, num_shards: int) -> jax.Array:
    """Per-owner occurrence rank for owner ids in [0, num_shards).

    The generic ``segments.occurrence_rank`` argsorts the whole batch — an
    XLA sort per routing call, ~10x the cost of the scatter it feeds on the
    CPU backend.  Owners come from a tiny dense alphabet, so a one-hot
    cumsum computes the same rank in one O(n * S) elementwise pass.
    """
    oh = (owner[:, None] == jnp.arange(num_shards, dtype=owner.dtype)[None, :])
    oh = oh & mask[:, None]
    c = jnp.cumsum(oh.astype(jnp.int32), axis=0)
    return c[jnp.arange(owner.shape[0]), owner] - 1


def device_route(
    src: jax.Array,
    dst: jax.Array,
    mask: jax.Array,
    num_shards: int,
    capacity: int,
    key: str = "src",
    axis_name: str = SHARD_AXIS,
    val=None,
) -> "RoutedDeviceEdges":
    """Re-key this shard's edges to their owner shards (call inside shard_map).

    Buckets local edges into a [S, cap] send buffer (scatter by per-owner
    occurrence rank), then ``all_to_all`` swaps buffers so each shard receives
    the edges it owns.  ``capacity`` is pow2-bucketed (``pow2_bucket``) so
    varying occupancy reuses cached executables.  An optional ``val`` pytree
    of per-edge payloads routes alongside the ids (the keyed-record analog of
    host_route's val).  Overflow beyond the bucketed cap per
    (sender, receiver) pair is dropped and COUNTED: ``dropped`` is this
    shard's scalar dropped count — never silent.  Size cap for the worst
    expected skew, check the counter, or use ``device_route_salted`` for
    power-law keys (SURVEY.md §7).

    Returns RoutedDeviceEdges(src, dst, mask, dropped, val) with edges
    flattened to [S * bucketed_cap].
    """
    routing_key = src if key == "src" else dst
    owner = jnp.where(mask, routing_key % num_shards, num_shards - 1)
    return _exchange_by_owner(
        src, dst, mask, owner, num_shards, capacity, axis_name, val
    )


class RoutedDeviceEdges:
    """device_route result: flattened [S * cap] per-shard received edges.

    Deliberately NOT a pytree (destructure it inside the traced caller —
    returning it across a jit/shard_map boundary is an error): iterating
    yields the legacy 4-tuple ``(src, dst, mask, dropped)`` so pre-val call
    sites keep unpacking unchanged; ``.val`` carries the routed payload.
    """

    __slots__ = ("src", "dst", "mask", "dropped", "val")

    def __init__(self, src, dst, mask, dropped, val=None):
        self.src = src
        self.dst = dst
        self.mask = mask
        self.dropped = dropped  # scalar int32: rows this shard failed to send
        self.val = val  # routed payload pytree or None

    def __iter__(self):
        return iter((self.src, self.dst, self.mask, self.dropped))


def _exchange_by_owner(
    src, dst, mask, owner, num_shards, capacity, axis_name, val=None
):
    """Scatter rows into [S, cap] send buffers by ``owner`` and all_to_all."""
    capacity = pow2_bucket(capacity)
    rank = owner_rank(owner, mask, num_shards)
    ok = mask & (rank < capacity)
    dropped = jnp.sum((mask & ~ok).astype(jnp.int32))
    slot = jnp.where(ok, owner * capacity + rank, num_shards * capacity)

    def build(buf_fill, values):
        flat_fill = jnp.asarray(buf_fill, values.dtype)
        buf = jnp.full(
            (num_shards * capacity,) + values.shape[1:], flat_fill, values.dtype
        )
        return buf.at[slot].set(
            jnp.where(
                ok.reshape((-1,) + (1,) * (values.ndim - 1)), values, flat_fill
            ),
            mode="drop",
        ).reshape((num_shards, capacity) + values.shape[1:])

    def swap(sent):
        return jax.lax.all_to_all(sent, axis_name, 0, 0, tiled=False)

    recv_src = swap(build(0, src))
    recv_dst = swap(build(0, dst))
    recv_mask = swap(build(False, ok))
    recv_val = None
    if val is not None:
        recv_val = jax.tree.map(
            lambda leaf: swap(build(0, leaf)).reshape(
                (num_shards * capacity,) + leaf.shape[1:]
            ),
            val,
        )
    return RoutedDeviceEdges(
        recv_src.reshape(-1),
        recv_dst.reshape(-1),
        recv_mask.reshape(-1),
        dropped,
        recv_val,
    )


def device_route_salted(
    src: jax.Array,
    dst: jax.Array,
    mask: jax.Array,
    num_shards: int,
    capacity: int,
    key: str = "src",
    axis_name: str = SHARD_AXIS,
    val=None,
) -> RoutedDeviceEdges:
    """Skew-safe routing for *associative* keyed aggregation: hot keys spread.

    The reference's keyBy sends every record of a key to one subtask — a
    power-law hub key makes that subtask (here: one (sender, receiver) bucket)
    the bottleneck, and under a fixed cap the hub's overflow drops.  Salting
    fans each key's k-th local occurrence out to shard
    ``(owner + k // capacity_share) % S``-style rotation; here: salt =
    occurrence-rank of the key, so a key with r local occurrences lands on
    ``min(r, S)`` distinct shards, each receiving at most
    ``ceil(r / S) + (other keys)`` rows.  Receivers hold *partial* per-key
    state; the caller completes the aggregation with a second-stage combine
    (``psum`` of dense per-key partials, or a second exact ``device_route`` of
    the much-smaller partial summaries) — the classic two-stage/salted
    combine for skewed keys.

    Same return shape as ``device_route``; the dropped counter stays (a batch
    can still exceed S*cap total), but a uniform spread of any single hot key
    makes drops a function of total volume, not key skew.
    """
    routing_key = src if key == "src" else dst
    base_owner = jnp.where(mask, routing_key % num_shards, num_shards - 1)
    salt = segments.occurrence_rank(routing_key, mask)
    owner = (base_owner + salt) % num_shards
    return _exchange_by_owner(
        src, dst, mask, owner, num_shards, capacity, axis_name, val
    )


# ---------------------------------------------------------------------------
# Owner-sharded summary state: block exchange primitives (ISSUE 4).
#
# Per-vertex summary state lives modulo-block-sharded over the mesh: vertex g
# is owned by shard g % S at block row g // S (the same ownership as
# mesh.owner_of / ring.py / BlockShardedCC).  The primitives below move state
# between the full [C] per-shard view (transient fold scratch) and the
# persistent [C/S] owner blocks:
#
#   * slab_exchange    — dense: every shard sends owner o its [C/S] slab of a
#                        full-[C] value array (one all_to_all; per-shard
#                        volume C, vs the S*C of all_gathering S partials).
#   * pack_slab_deltas — sparse: compact only CHANGED rows into fixed
#                        [S, cap] (row, value) buffers; cap is pow2-bucketed
#                        so shapes stay cache-stable, and the true demand is
#                        returned as ``occupancy`` (the delta-occupancy
#                        high-water metric) with spill counts — spilled rows
#                        are simply retried by the caller's exchange loop,
#                        never silently lost.
#   * gather_blocks    — the sanctioned full-view reassembly for
#                        emit/snapshot boundaries only (COLLGATHER pass).


DELTA_PAD = -1  # pack_slab_deltas row sentinel for empty buffer slots


def slab_exchange(values: jax.Array, num_shards: int, axis_name: str = SHARD_AXIS):
    """Dense block route: full-[C] per-shard ``values`` -> [S, C/S] received.

    Row o of the send view holds this shard's values for owner o's block
    rows (``values[o + S*i]``); after the all_to_all, ``recv[s, i]`` is what
    shard s proposed for MY block row i.  Per-shard traffic is C values —
    1/S of the S*C an all_gather of S full partials ships.
    """
    slabs = values.reshape(-1, num_shards).T  # [S, C/S]
    return jax.lax.all_to_all(slabs, axis_name, 0, 0, tiled=False)


def slab_exchange_nbytes(capacity: int, itemsize: int = 4) -> int:
    """Per-shard wire volume of one slab_exchange over a [C] value array."""
    return capacity * itemsize


def delta_capacity(capacity: int, num_shards: int, delta_bound: int) -> int:
    """Pow2-bucketed per-(sender, receiver) capacity for a delta exchange.

    Keys in a slab-delta buffer are DISTINCT block rows, so per-owner demand
    is structurally <= C/S; ``delta_bound`` caps it further by how many rows
    can have changed since the last exchange (e.g. 2 edges' endpoints per
    fold).  The pow2 bucket keeps compiled shapes cache-stable while the
    buffer stays O(min(C/S, delta)) instead of O(C).
    """
    from gelly_streaming_tpu.parallel.mesh import block_rows

    return pow2_bucket(min(block_rows(capacity, num_shards), max(int(delta_bound), 1)))


def pack_slab_deltas(
    changed: jax.Array,
    values: jax.Array,
    num_shards: int,
    capacity: int,
    fill,
):
    """Compact changed rows of a full-[C] view into per-owner delta buffers.

    ``changed``/``values`` are [C] by global id.  Returns
    ``(rows [S, cap] int32, vals [S, cap], sent [C] bool, occupancy,
    spilled)``: ``rows`` holds block-row indices (``g // S``; DELTA_PAD marks
    empty slots), ``vals`` the proposed values (``fill`` on padding),
    ``sent`` which changed rows made it into a buffer (retry loops clear
    those and re-pack the rest), ``occupancy`` the max per-owner demand
    BEFORE capping (the delta high-water mark — if it tops the capacity,
    ``spilled`` counts the overflow rows, which the caller's exchange loop
    re-derives next round).  Rank is a per-slab cumsum (the rows are already
    owner-structured), so no sort is paid.
    """
    c2 = changed.reshape(-1, num_shards)  # [C/S, S]: column o = owner o rows
    v2 = values.reshape(-1, num_shards)
    rank = jnp.cumsum(c2.astype(jnp.int32), axis=0) - 1
    counts = jnp.sum(c2, axis=0)
    ok = c2 & (rank < capacity)
    slot = jnp.where(
        ok,
        jnp.arange(num_shards, dtype=jnp.int32)[None, :] * capacity + rank,
        num_shards * capacity,
    )
    block_row = jnp.broadcast_to(
        jnp.arange(c2.shape[0], dtype=jnp.int32)[:, None], c2.shape
    )
    rows = (
        jnp.full((num_shards * capacity,), DELTA_PAD, jnp.int32)
        .at[slot.reshape(-1)]
        .set(jnp.where(ok, block_row, DELTA_PAD).reshape(-1), mode="drop")
        .reshape(num_shards, capacity)
    )
    fill = jnp.asarray(fill, v2.dtype)
    vals = (
        jnp.full((num_shards * capacity,), fill, v2.dtype)
        .at[slot.reshape(-1)]
        .set(jnp.where(ok, v2, fill).reshape(-1), mode="drop")
        .reshape(num_shards, capacity)
    )
    occupancy = jnp.max(counts)
    spilled = jnp.sum(jnp.maximum(counts - capacity, 0))
    return rows, vals, ok.reshape(-1), occupancy, spilled


def exchange_slab_deltas(
    changed: jax.Array,
    values: jax.Array,
    num_shards: int,
    capacity: int,
    axis_name: str = SHARD_AXIS,
    fill=0,
):
    """pack_slab_deltas + the all_to_all swap.

    Returns ``(recv_rows [S, cap], recv_vals [S, cap], sent [C] bool,
    occupancy, spilled)`` — ``recv_rows[s]`` are MY block rows shard s
    proposes values for (DELTA_PAD = empty slot).  Apply with
    ``apply_block_deltas``; retry loops clear ``sent`` rows and re-pack.
    """
    rows, vals, sent, occupancy, spilled = pack_slab_deltas(
        changed, values, num_shards, capacity, fill
    )
    recv_rows = jax.lax.all_to_all(rows, axis_name, 0, 0, tiled=False)
    recv_vals = jax.lax.all_to_all(vals, axis_name, 0, 0, tiled=False)
    return recv_rows, recv_vals, sent, occupancy, spilled


def delta_exchange_nbytes(num_shards: int, capacity: int, itemsize: int = 4) -> int:
    """Per-shard wire volume of one exchange_slab_deltas pass (rows + vals)."""
    return num_shards * capacity * (4 + itemsize)


def apply_block_deltas(block, recv_rows, recv_vals, op: str, fill):
    """Fold received delta buffers into this shard's [C/S] block.

    ``op``: "min" / "max" / "add" — the only reconciliation folds the
    owner-sharded descriptors need (CC hooks, seen marks, degree counts).
    Padding slots carry ``fill`` (the op identity) and a DELTA_PAD row, so
    they scatter out of range and drop.
    """
    rows = block.shape[0]
    ri = recv_rows.reshape(-1)
    rv = recv_vals.reshape(-1)
    ok = ri != DELTA_PAD
    idx = jnp.where(ok, ri, rows)
    vals = jnp.where(ok, rv, jnp.asarray(fill, rv.dtype))
    if op == "min":
        return block.at[idx].min(vals, mode="drop")
    if op == "max":
        return block.at[idx].max(vals, mode="drop")
    if op == "add":
        return block.at[idx].add(vals, mode="drop")
    raise ValueError(f"unknown block-delta op {op!r}")


def gather_blocks(block: jax.Array, num_shards: int, axis_name: str = SHARD_AXIS):
    """[C/S] owner blocks -> the full [C] replicated view (per shard).

    THE full-state collective: per-shard volume is C values, which is why the
    collective-discipline pass confines it to emit/snapshot boundaries (and
    the exchange internals below) — streaming-step kernels reconcile through
    the delta buffers above instead.
    """
    g = jax.lax.all_gather(block, axis_name)  # gather-ok: block reassembly primitive; call sites are COLLGATHER-gated
    return jnp.swapaxes(g, 0, 1).reshape((-1,) + g.shape[2:])


def gather_blocks_nbytes(capacity: int, itemsize: int = 4) -> int:
    """Per-shard wire volume of one gather_blocks over a [C]-row state."""
    return capacity * itemsize

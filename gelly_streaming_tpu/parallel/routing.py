"""Edge routing: the keyBy shuffle, TPU-style.

The reference's ``keyBy`` is a Netty network shuffle routing each record to the
subtask owning its key (SimpleEdgeStream.java:119,303,492;
SummaryBulkAggregation.java:78).  Here routing happens in two places:

  * host_route: the ingest plane — the host buckets a window pane's edges by
    owning shard and pads to a fixed per-shard capacity, producing the stacked
    [S, B] arrays a ``shard_map`` program consumes (the keyBy-from-source
    analog; SURVEY.md §5.8 "control/ingest plane").
  * device_route: the data plane — re-keying mid-pipeline without leaving the
    mesh, via in-shard bucketing + ``lax.all_to_all`` over ICI.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from gelly_streaming_tpu.ops import segments
from gelly_streaming_tpu.parallel.mesh import SHARD_AXIS


class RoutedEdges(NamedTuple):
    """Stacked per-shard edge arrays: leading axis = shard."""

    src: np.ndarray  # [S, B]
    dst: np.ndarray  # [S, B]
    mask: np.ndarray  # [S, B]
    val: Optional[object] = None  # pytree of [S, B, ...] or None


def host_route(
    src: np.ndarray,
    dst: np.ndarray,
    num_shards: int,
    key: str = "src",
    capacity: Optional[int] = None,
    val=None,
) -> RoutedEdges:
    """Bucket edges by owner shard on the host, padding each bucket to a common
    capacity.  ``key`` picks the routing key ("src" or "dst"); an optional
    ``val`` pytree of per-edge payloads routes alongside the ids.  Relative
    edge order is preserved within each shard, so per-key arrival-order
    semantics survive the shuffle.

    Value-less int32 batches scatter through the native single-pass router
    (native/edge_parser.cpp route_edges — the hash-partitioner analog of the
    reference runtime's shuffle feed); other inputs take the numpy path
    (one boolean-mask selection per shard)."""
    if (
        val is None
        and len(src)
        and src.dtype == np.int32
        and dst.dtype == np.int32
    ):
        from gelly_streaming_tpu.utils.native import load_ingest_lib

        lib = load_ingest_lib()
        if lib is not None and hasattr(lib, "route_edges"):
            cap = capacity or max(
                1, int(np.bincount(
                    (src if key == "src" else dst) % num_shards,
                    minlength=num_shards,
                ).max())
            )
            s = np.zeros((num_shards, cap), np.int32)
            d = np.zeros((num_shards, cap), np.int32)
            counts = np.zeros((num_shards,), np.int64)
            src_c = np.ascontiguousarray(src)
            dst_c = np.ascontiguousarray(dst)
            import ctypes

            wrote = lib.route_edges(
                src_c.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                dst_c.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                len(src),
                num_shards,
                1 if key == "src" else 0,
                cap,
                s.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                d.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
                counts.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            )
            if wrote == len(src):  # no overflow: buckets are complete
                m = np.arange(cap)[None, :] < counts[:, None]
                return RoutedEdges(s, d, m, None)
    owner = (src if key == "src" else dst) % num_shards
    counts = np.bincount(owner, minlength=num_shards)
    cap = capacity or (int(counts.max()) if len(src) else 1)
    s = np.zeros((num_shards, cap), np.int32)
    d = np.zeros((num_shards, cap), np.int32)
    m = np.zeros((num_shards, cap), bool)
    v = None
    if val is not None:
        v = jax.tree.map(
            lambda a: np.zeros((num_shards, cap) + a.shape[1:], a.dtype), val
        )
    for shard in range(num_shards):
        sel = owner == shard
        n = min(int(sel.sum()), cap)
        s[shard, :n] = src[sel][:n]
        d[shard, :n] = dst[sel][:n]
        m[shard, :n] = True
        if v is not None:

            def fill(buf, a):
                buf[shard, :n] = a[sel][:n]
                return buf

            v = jax.tree.map(fill, v, val)
    return RoutedEdges(s, d, m, v)


def device_route(
    src: jax.Array,
    dst: jax.Array,
    mask: jax.Array,
    num_shards: int,
    capacity: int,
    key: str = "src",
    axis_name: str = SHARD_AXIS,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Re-key this shard's edges to their owner shards (call inside shard_map).

    Buckets local edges into a [S, cap] send buffer (scatter by per-owner
    occurrence rank), then ``all_to_all`` swaps buffers so each shard receives
    the edges it owns.  Overflow beyond ``cap`` per (sender, receiver) pair is
    dropped and COUNTED: the last return value is this shard's scalar dropped
    count — never silent.  Size cap for the worst expected skew, check the
    counter, or use ``device_route_salted`` for power-law keys (SURVEY.md §7).

    Returns (src, dst, mask, dropped) with edges flattened to [S * cap].
    """
    routing_key = src if key == "src" else dst
    owner = jnp.where(mask, routing_key % num_shards, num_shards - 1)
    return _exchange_by_owner(
        src, dst, mask, owner, num_shards, capacity, axis_name
    )


def _exchange_by_owner(src, dst, mask, owner, num_shards, capacity, axis_name):
    """Scatter rows into [S, cap] send buffers by ``owner`` and all_to_all."""
    rank = segments.occurrence_rank(owner, mask)
    ok = mask & (rank < capacity)
    dropped = jnp.sum((mask & ~ok).astype(jnp.int32))
    slot = jnp.where(ok, owner * capacity + rank, num_shards * capacity)

    def build(buf_fill, values):
        buf = jnp.full((num_shards * capacity,), buf_fill, values.dtype)
        return buf.at[slot].set(jnp.where(ok, values, buf_fill), mode="drop").reshape(
            num_shards, capacity
        )

    send_src = build(0, src)
    send_dst = build(0, dst)
    send_mask = build(False, ok)
    recv_src = jax.lax.all_to_all(send_src, axis_name, 0, 0, tiled=False)
    recv_dst = jax.lax.all_to_all(send_dst, axis_name, 0, 0, tiled=False)
    recv_mask = jax.lax.all_to_all(send_mask, axis_name, 0, 0, tiled=False)
    return (
        recv_src.reshape(-1),
        recv_dst.reshape(-1),
        recv_mask.reshape(-1),
        dropped,
    )


def device_route_salted(
    src: jax.Array,
    dst: jax.Array,
    mask: jax.Array,
    num_shards: int,
    capacity: int,
    key: str = "src",
    axis_name: str = SHARD_AXIS,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Skew-safe routing for *associative* keyed aggregation: hot keys spread.

    The reference's keyBy sends every record of a key to one subtask — a
    power-law hub key makes that subtask (here: one (sender, receiver) bucket)
    the bottleneck, and under a fixed cap the hub's overflow drops.  Salting
    fans each key's k-th local occurrence out to shard
    ``(owner + k // capacity_share) % S``-style rotation; here: salt =
    occurrence-rank of the key, so a key with r local occurrences lands on
    ``min(r, S)`` distinct shards, each receiving at most
    ``ceil(r / S) + (other keys)`` rows.  Receivers hold *partial* per-key
    state; the caller completes the aggregation with a second-stage combine
    (``psum`` of dense per-key partials, or a second exact ``device_route`` of
    the much-smaller partial summaries) — the classic two-stage/salted
    combine for skewed keys.

    Same return shape as ``device_route``; the dropped counter stays (a batch
    can still exceed S*cap total), but a uniform spread of any single hot key
    makes drops a function of total volume, not key skew.
    """
    routing_key = src if key == "src" else dst
    base_owner = jnp.where(mask, routing_key % num_shards, num_shards - 1)
    salt = segments.occurrence_rank(routing_key, mask)
    owner = (base_owner + salt) % num_shards
    return _exchange_by_owner(
        src, dst, mask, owner, num_shards, capacity, axis_name
    )

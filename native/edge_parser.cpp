// Reference stub — the canonical native source is the PACKAGED copy at
// gelly_streaming_tpu/native_src/edge_parser.cpp (shipped as package data
// so pip installs keep the native host plane).  This file exists only so
// repo-layout tooling that expects native/edge_parser.cpp keeps building;
// it must carry no code of its own (tests/test_native_source_sync.py pins
// that, so the two layouts can never drift apart again).
#include "../gelly_streaming_tpu/native_src/edge_parser.cpp"

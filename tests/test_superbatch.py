"""Superbatch dispatch coalescing (cfg.superbatch): K micro-batches / K
closed panes per device call must be OBSERVABLY identical to per-batch
dispatch — same results, same running-emission sequence, same checkpoint
semantics — on every execution plane it touches (wire fast path, windowed
simulated path, windowed triangles).
"""

import numpy as np
import pytest

from gelly_streaming_tpu.core.config import StreamConfig
from gelly_streaming_tpu.core.stream import EdgeStream, plan_superbatch_groups
from gelly_streaming_tpu.library.bipartiteness import BipartitenessCheck
from gelly_streaming_tpu.library.connected_components import ConnectedComponents


def _edges(n=4000, c=64, seed=7):
    rng = np.random.default_rng(seed)
    return (
        rng.integers(0, c, n).astype(np.int32),
        rng.integers(0, c, n).astype(np.int32),
    )


# ---------------------------------------------------------------------------
# group planner
# ---------------------------------------------------------------------------


def test_plan_covers_exactly_with_pow2_buckets():
    for n in (0, 1, 5, 13, 64, 100):
        for k in (1, 2, 4, 8):
            groups = plan_superbatch_groups(n, k)
            assert sum(groups) == n
            assert all(g <= k and (g & (g - 1)) == 0 for g in groups)


def test_plan_never_crosses_boundaries():
    # emission every 6 batches starting at offset 2, snapshots every 4
    boundaries = [(6, 2), (4, 0)]
    groups = plan_superbatch_groups(40, 8, boundaries)
    assert sum(groups) == 40
    pos = 0
    for g in groups:
        for mod, off in boundaries:
            nxt = mod - ((pos + off) % mod)
            assert g <= nxt, (pos, g, nxt)
        pos += g


def test_plan_k1_is_per_batch():
    assert plan_superbatch_groups(7, 1) == [1] * 7


# ---------------------------------------------------------------------------
# wire fast path
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("agg_cls", [ConnectedComponents, BipartitenessCheck])
def test_wire_superbatch_matches_per_batch(agg_cls):
    src, dst = _edges()
    base = dict(vertex_capacity=64, batch_size=256)
    r1 = (
        EdgeStream.from_arrays(src, dst, StreamConfig(**base))
        .aggregate(agg_cls())
        .collect()
    )
    r4 = (
        EdgeStream.from_arrays(src, dst, StreamConfig(**base, superbatch=4))
        .aggregate(agg_cls())
        .collect()
    )
    assert len(r1) == len(r4) == 1
    if agg_cls is ConnectedComponents:
        assert r1[0][0].components() == r4[0][0].components()
    else:
        assert r1[0][0].is_bipartite() == r4[0][0].is_bipartite()


def test_wire_superbatch_running_emissions_identical():
    src, dst = _edges(n=4096)
    base = dict(vertex_capacity=64, batch_size=256, ingest_window_edges=512)
    runs = []
    for sb in (0, 4, 8):
        stream = EdgeStream.from_arrays(
            src, dst, StreamConfig(**base, superbatch=sb)
        )
        out = stream.aggregate(ConnectedComponents()).collect()
        runs.append([r[0].components() for r in out])
    assert runs[0] == runs[1] == runs[2]
    assert len(runs[0]) == 4096 // 512


def test_wire_superbatch_respects_checkpoint_cadence(tmp_path):
    """Snapshot positions under superbatching land exactly where per-batch
    dispatch put them, and a resumed run completes correctly."""
    src, dst = _edges(n=4096)
    cfg = StreamConfig(
        vertex_capacity=64,
        batch_size=256,
        superbatch=4,
        wire_checkpoint_batches=3,  # not a multiple of the superbatch K
    )
    ck = str(tmp_path / "ck")
    ref = (
        EdgeStream.from_arrays(src, dst, StreamConfig(vertex_capacity=64, batch_size=256))
        .aggregate(ConnectedComponents())
        .collect()
    )
    out = (
        EdgeStream.from_arrays(src, dst, cfg)
        .aggregate(ConnectedComponents(), checkpoint_path=ck)
        .collect()
    )
    assert out[-1][0].components() == ref[-1][0].components()
    # the final snapshot marks the stream done: a restore re-emits without
    # re-folding, proving position tracking survived the grouped dispatch
    again = (
        EdgeStream.from_arrays(src, dst, cfg)
        .aggregate(ConnectedComponents(), checkpoint_path=ck)
        .collect()
    )
    assert again[-1][0].components() == ref[-1][0].components()


# ---------------------------------------------------------------------------
# windowed (event-time) plane
# ---------------------------------------------------------------------------


def _timed_edges(n=600, c=48, seed=5, step=37):
    rng = np.random.default_rng(seed)
    return [
        (int(rng.integers(0, c)), int(rng.integers(0, c)), 0.0, step * i)
        for i in range(n)
    ]


def test_windowed_superbatch_matches_per_pane():
    edges = _timed_edges()
    runs = []
    for sb in (0, 4):
        cfg = StreamConfig(vertex_capacity=64, batch_size=64, superbatch=sb)
        stream = EdgeStream.from_collection(edges, cfg, 64, with_time=True)
        out = stream.aggregate(ConnectedComponents(window_ms=1000)).collect()
        runs.append([r[0].components() for r in out])
    assert runs[0] == runs[1]
    assert len(runs[0]) > 5  # actually windowed, not a single global pane


def test_windowed_superbatch_untimed_global_pane():
    src, dst = _edges(n=512)
    cfg = StreamConfig(vertex_capacity=64, batch_size=64, superbatch=4)
    # a collection source is NOT wire-backed -> the windowed path runs, and
    # the untimed stream's single global pane coalesces trivially
    stream = EdgeStream.from_collection(
        list(zip(src.tolist(), dst.tolist())), cfg, 64
    )
    out = stream.aggregate(ConnectedComponents()).collect()
    ref = (
        EdgeStream.from_collection(
            list(zip(src.tolist(), dst.tolist())),
            StreamConfig(vertex_capacity=64, batch_size=64),
            64,
        )
        .aggregate(ConnectedComponents())
        .collect()
    )
    assert out[-1][0].components() == ref[-1][0].components()


def test_window_triangles_superbatch_matches_per_pane():
    from gelly_streaming_tpu.library.triangles import window_triangles

    edges = _timed_edges(n=700, c=40)
    r1 = window_triangles(
        EdgeStream.from_collection(
            edges, StreamConfig(vertex_capacity=64, batch_size=64), 64, with_time=True
        ),
        1000,
    ).collect()
    r4 = window_triangles(
        EdgeStream.from_collection(
            edges,
            StreamConfig(vertex_capacity=64, batch_size=64, superbatch=4),
            64,
            with_time=True,
        ),
        1000,
    ).collect()
    assert r1 == r4
    assert any(c > 0 for c, _ in r1)  # the workload actually has triangles


def test_superpane_window_ids_preserve_boundaries():
    """coalesce_panes must keep each window's edges separable by wid."""
    from gelly_streaming_tpu.core.windows import (
        assign_tumbling_windows,
        coalesce_panes,
    )

    cfg = StreamConfig(vertex_capacity=64, batch_size=32)
    edges = _timed_edges(n=300, c=32)
    stream = EdgeStream.from_collection(edges, cfg, 32, with_time=True)
    panes = list(assign_tumbling_windows(stream.batches(), 500))
    supers = list(coalesce_panes(iter(panes), 4))
    rebuilt = []
    for sp in supers:
        assert len(sp.panes) <= 4
        for pane in sp.panes:
            sel = (sp.wid == pane.window_id) & sp.mask
            assert np.array_equal(sp.src[sel], pane.src)
            assert np.array_equal(sp.dst[sel], pane.dst)
            rebuilt.append(pane.window_id)
    assert rebuilt == [p.window_id for p in panes if p.num_edges]

"""ASan+UBSan fuzz gate for the untrusted native decode plane (ISSUE 15).

The static layer (analysis/nativecheck.py) lints the C++ byte path by
approximation; this module is the dynamic complement: the canonical
source is compiled with ``-fsanitize=address,undefined
-fno-sanitize-recover`` into a standalone harness executable
(tests/native_fuzz_harness.cpp — a shared library would need the ASan
runtime preloaded into the Python process, so the gate runs out of
process), then driven through

* the native self-checks (probe taxonomy, every push encoding's
  encode->decode round trip incl. the fused bin pass, sorter order/
  multiset, EF40 capacity discipline, router conservation),
* a deterministic structure-aware fuzz run (seeded PRNG mutations of
  valid fixed/PAIR40/BDV buffers and GLY1 frame prefixes — buffers are
  heap-allocated at EXACTLY the size the decoder is told, so any read
  past ``nbytes`` is an abort, not luck), and
* the persisted regression corpus (tests/fuzz_corpus/*.bin, format in
  that directory's README), byte-for-byte.

The corpus additionally replays in tier-1 WITHOUT sanitizers through the
regular native build and the numpy oracle with identical accept/refuse
verdicts — so verdict parity and memory safety are pinned by different
tests and a missing toolchain only skips the sanitizer half.

The sanitizer compile is cached per source hash (canonical .cpp + harness
+ flags) under the same user cache dir utils/native.py builds into, so
repeat runs do not recompile.  Skips cleanly when the image has no g++ or
its g++ lacks the sanitizer runtimes — exactly like test_native_build_gate.
"""

import ctypes
import hashlib
import os
import shutil
import struct
import subprocess
import sys

import numpy as np
import pytest

from gelly_streaming_tpu.io import wire
from gelly_streaming_tpu.utils import native as native_mod

pytestmark = pytest.mark.timeout_cap(420)

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CANONICAL = os.path.join(
    ROOT, "gelly_streaming_tpu", "native_src", "edge_parser.cpp"
)
HARNESS = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "native_fuzz_harness.cpp")
CORPUS_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "fuzz_corpus")

SAN_FLAGS = [
    "-O1", "-g", "-std=c++17", "-pthread",
    "-fsanitize=address,undefined", "-fno-sanitize-recover=all",
]
# leak checking stays ON: the decode plane's refusal paths must release
# their scratch allocations (the NATIVELEAK pass checks this statically;
# LeakSanitizer checks it for real)
SAN_ENV = {"ASAN_OPTIONS": "detect_leaks=1:abort_on_error=1"}


def _cache_dir() -> str:
    d = os.path.join(
        os.environ.get("XDG_CACHE_HOME", os.path.expanduser("~/.cache")),
        "gelly_streaming_tpu",
    )
    os.makedirs(d, exist_ok=True)
    return d


def _source_hash() -> str:
    h = hashlib.sha256()
    for path in (CANONICAL, HARNESS):
        with open(path, "rb") as f:
            h.update(f.read())
    h.update(" ".join(SAN_FLAGS).encode())
    return h.hexdigest()[:16]


def sanitizer_harness_path() -> str:
    """The cached-per-source-hash harness binary path (existing or not)."""
    return os.path.join(_cache_dir(), f"native_santest_{_source_hash()}")


def build_sanitizer_harness() -> str:
    """Compile (or reuse) the instrumented harness; pytest.skip without a
    capable toolchain, hard-fail when the canonical source itself breaks
    the sanitizer build."""
    if shutil.which("g++") is None:
        pytest.skip("no C++ toolchain in this image")
    out = sanitizer_harness_path()
    if os.path.exists(out):
        return out  # per-source-hash cache hit: no recompile
    # probe: does this g++ carry the ASan/UBSan runtimes at all?
    probe = subprocess.run(
        ["g++", *SAN_FLAGS, "-x", "c++", "-", "-o", os.devnull],
        input="int main(){return 0;}",
        capture_output=True,
        text=True,
        timeout=120,
    )
    if probe.returncode != 0:
        pytest.skip("g++ lacks ASan/UBSan runtimes: " + probe.stderr[:200])
    tmp = out + f".tmp{os.getpid()}"
    proc = subprocess.run(
        ["g++", *SAN_FLAGS, HARNESS, "-o", tmp],
        capture_output=True,
        text=True,
        timeout=300,
    )
    assert proc.returncode == 0, (
        "sanitizer build of the canonical native source failed:\n"
        + proc.stderr
    )
    os.replace(tmp, out)  # atomic publish for parallel test runs
    return out


@pytest.fixture(scope="module")
def san_bin():
    return build_sanitizer_harness()


def _run(san_bin, *args):
    env = dict(os.environ)
    env.update(SAN_ENV)
    return subprocess.run(
        [san_bin, *args], capture_output=True, text=True, env=env,
        timeout=300,
    )


def _corpus_files():
    return sorted(
        os.path.join(CORPUS_DIR, f)
        for f in os.listdir(CORPUS_DIR)
        if f.endswith(".bin")
    )


# ---------------------------------------------------------------------------
# sanitizer half (skips without a toolchain)


def test_sanitizer_selfcheck(san_bin):
    """Probe taxonomy, every encoding's round trip (n = 0 included), fused
    binning vs two-pass, sorter order/multiset, EF40/router/cc invariants —
    all under ASan+UBSan+LSan."""
    proc = _run(san_bin, "selfcheck")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "selfcheck ok" in proc.stdout


def test_sanitizer_fuzz_decode_plane(san_bin):
    """Deterministic structure-aware fuzz: seeded mutations of valid wire
    buffers and frame prefixes through decode/probe/encode/sort.  The seed
    is pinned so a failure reproduces; bump iterations locally to hunt."""
    proc = _run(san_bin, "fuzz", "20260804", "4000")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "fuzz ok" in proc.stdout


def test_sanitizer_replays_fuzz_corpus(san_bin):
    """Every persisted regression input replays byte-for-byte with zero
    sanitizer reports."""
    files = _corpus_files()
    assert files, "fuzz corpus is empty — the regression gate is vacuous"
    proc = _run(san_bin, "replay", *files)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert proc.stdout.count("replay ") == len(files)


def test_sanitizer_build_is_cached_per_source_hash(san_bin):
    """A second build call must reuse the hash-named binary (no recompile:
    the mtime is untouched), and the name must change when the source
    changes — the same contract as utils/native.py's mtime cache, keyed
    harder."""
    before = os.path.getmtime(san_bin)
    again = build_sanitizer_harness()
    assert again == san_bin
    assert os.path.getmtime(again) == before
    assert _source_hash() in os.path.basename(san_bin)


# ---------------------------------------------------------------------------
# tier-1 half: corpus verdict parity native-vs-oracle (no sanitizers, runs
# wherever the regular native build does)


def _read_case(path):
    with open(path, "rb") as f:
        data = f.read()
    assert data[:4] == b"GFZ1", path
    mode, code, sort = data[4], data[5], data[6]
    n, cap = struct.unpack_from("<II", data, 8)
    return mode, code, sort, n, cap, data[16:]


def _native_width(code):
    return {2: 2, 3: 3, 4: 4, 5: wire.PAIR40}.get(code)


def test_fuzz_corpus_files_exist_and_carry_magic():
    files = _corpus_files()
    assert len(files) >= 12
    for path in files:
        _read_case(path)  # asserts the magic and header shape


def test_fuzz_corpus_verdicts_match_numpy_oracle():
    """The contract the serving plane rides: whatever a corpus input does,
    the native decoder and the numpy oracle agree — same accept/refuse
    verdict, and identical arrays on accept.  This is what makes a native
    refusal safe to re-phrase through the oracle (io/wire.decode_wire_into
    falls back on refusal) without ever diverging from the pure-Python
    path."""
    lib = native_mod.load_ingest_lib()
    if lib is None or not hasattr(lib, "decode_wire_into"):
        pytest.skip("no native library in this environment")
    checked = 0
    for path in _corpus_files():
        mode, code, sort, n, cap, payload = _read_case(path)
        name = os.path.basename(path)
        if mode == 2:
            assert len(payload) >= 12, name
            hl = ctypes.c_int64(0)
            pl = ctypes.c_int64(0)
            rc = lib.gly1_probe_prefix(
                payload[:12], int(n), int(cap),
                ctypes.byref(hl), ctypes.byref(pl),
            )
            # pure-Python twin of the probe's refusal taxonomy
            h, p = struct.unpack(">II", payload[4:12])
            if payload[:4] != b"GLY1":
                expect = -1
            elif h > n:
                expect = -2
            elif p > cap:
                expect = -3
            else:
                expect = 0
            assert rc == expect, (name, rc, expect)
            assert (hl.value, pl.value) == (h, p), name
            checked += 1
            continue
        assert mode == 1, name
        width = _native_width(code) if code != 6 else (wire.BDV, int(cap))
        assert width is not None, name
        buf = np.frombuffer(payload, dtype=np.uint8)
        out_s = np.empty(n, np.int32)
        out_d = np.empty(n, np.int32)
        rc = lib.decode_wire_into(
            buf.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
            buf.nbytes, int(n), int(code), int(cap), int(sort),
            out_s.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            out_d.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        )
        try:
            oracle_s, oracle_d = wire.decode_wire_np(
                buf, int(n), width, int(cap), sort=bool(sort)
            )
            oracle_accepts = True
        except ValueError:
            oracle_accepts = False
        assert rc != -4, (name, "internal fallback on a corpus input")
        native_accepts = rc == n
        assert native_accepts == oracle_accepts, (name, rc)
        if native_accepts:
            assert np.array_equal(out_s, oracle_s), name
            assert np.array_equal(out_d, oracle_d), name
        checked += 1
    assert checked >= 12

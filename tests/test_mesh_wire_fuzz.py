"""Randomized differentials for the sharded streaming wire fold: arbitrary
(edge count, batch size, shard count, encoding, tail) configurations must
produce identical summaries to the single-shard wire fast path — the
mesh plane is an execution strategy, never a semantics change."""

import numpy as np
import pytest

from gelly_streaming_tpu.core.config import StreamConfig
from gelly_streaming_tpu.core.stream import EdgeStream
from gelly_streaming_tpu.io import wire
from gelly_streaming_tpu.library.bipartiteness import BipartitenessCheck
from gelly_streaming_tpu.library.connected_components import ConnectedComponents


@pytest.mark.parametrize("seed", range(6))
def test_mesh_streaming_fold_matches_single_shard_fuzz(seed):
    rng = np.random.default_rng(100 + seed)
    c = int(rng.choice([32, 64, 128]))
    n = int(rng.integers(1, 700))
    batch = int(rng.choice([8, 16, 64, 128]))
    shards = int(rng.choice([2, 4, 8]))
    enc = rng.choice(["plain", "ef40"])
    src = rng.integers(0, c, n).astype(np.int32)
    dst = rng.integers(0, c, n).astype(np.int32)

    single_cfg = StreamConfig(
        vertex_capacity=c, batch_size=batch, wire_encoding=str(enc)
    )
    mesh_cfg = StreamConfig(
        vertex_capacity=c,
        batch_size=batch,
        num_shards=shards,
        wire_encoding=str(enc),
    )
    single = (
        EdgeStream.from_arrays(src, dst, single_cfg)
        .aggregate(ConnectedComponents())
        .collect()
    )
    mesh = (
        EdgeStream.from_arrays(src, dst, mesh_cfg)
        .aggregate(ConnectedComponents())
        .collect()
    )
    assert mesh[-1][0].components() == single[-1][0].components(), (
        c, n, batch, shards, enc,
    )


@pytest.mark.parametrize("seed", range(3))
def test_mesh_streaming_fold_replay_with_tail_fuzz(seed):
    """from_wire replay (pre-packed buffers + raw tail) through the mesh."""
    rng = np.random.default_rng(200 + seed)
    c = 64
    batch = int(rng.choice([16, 32]))
    n = int(rng.integers(batch + 1, 500))
    src = rng.integers(0, c, n).astype(np.int32)
    dst = rng.integers(0, c, n).astype(np.int32)
    width = wire.replay_width(c, batch)
    bufs, tail = wire.pack_stream(src, dst, batch, width)

    single = (
        EdgeStream.from_wire(
            bufs, batch, width, StreamConfig(vertex_capacity=c, batch_size=batch),
            tail=tail,
        )
        .aggregate(ConnectedComponents())
        .collect()
    )
    mesh = (
        EdgeStream.from_wire(
            bufs, batch, width,
            StreamConfig(vertex_capacity=c, batch_size=batch, num_shards=8),
            tail=tail,
        )
        .aggregate(ConnectedComponents())
        .collect()
    )
    assert mesh[-1][0].components() == single[-1][0].components()


def test_mesh_streaming_fold_bipartiteness_matches():
    """The generic gather-combine is bypassed for BP too (collective
    parity fixpoint); verdicts and candidate renderings must agree."""
    rng = np.random.default_rng(7)
    for odd in (False, True):
        # random bipartite graph over two halves; optionally an odd chord
        u = rng.integers(0, 16, 300).astype(np.int32)
        v = (rng.integers(16, 32, 300)).astype(np.int32)
        src = u
        dst = v.copy()
        if odd:
            src = np.append(src, np.int32(3))
            dst = np.append(dst, np.int32(5))  # both in the same half
        single = (
            EdgeStream.from_arrays(
                src, dst, StreamConfig(vertex_capacity=32, batch_size=64)
            )
            .aggregate(BipartitenessCheck())
            .collect()
        )
        mesh = (
            EdgeStream.from_arrays(
                src,
                dst,
                StreamConfig(vertex_capacity=32, batch_size=64, num_shards=8),
            )
            .aggregate(BipartitenessCheck())
            .collect()
        )
        assert (
            mesh[-1][0].is_bipartite()
            == single[-1][0].is_bipartite()
            == (not odd)
        )
        assert str(mesh[-1][0]) == str(single[-1][0])


def test_whole_edge_distinct_fuzz_vs_python_set():
    """Whole-edge distinct vs a plain Python set over (src, dst, value)
    triples — arrival order, cross-batch memory, exact value equality."""
    rng = np.random.default_rng(17)
    for trial in range(4):
        n = int(rng.integers(10, 400))
        edges = [
            (
                int(rng.integers(0, 24)),
                int(rng.integers(0, 24)),
                float(rng.integers(0, 4)),  # few distinct values -> collisions
            )
            for _ in range(n)
        ]
        batch = int(rng.choice([4, 16, 64]))
        cfg = StreamConfig(vertex_capacity=32, batch_size=batch, max_degree=128)
        got = (
            EdgeStream.from_collection(edges, cfg, batch_size=batch)
            .distinct()
            .collect_edges()
        )
        seen = set()
        expect = []
        for e in edges:
            if e not in seen:
                seen.add(e)
                expect.append(e)
        assert got == expect, (trial, n, batch)


def test_mesh_streaming_fold_empty_stream_emits_nothing():
    """Zero-edge wire streams produce no emission on the mesh path, exactly
    like the single-shard fast path."""
    empty = np.empty((0,), np.int32)
    for shards in (1, 8):
        cfg = StreamConfig(vertex_capacity=32, batch_size=8, num_shards=shards)
        out = (
            EdgeStream.from_arrays(empty, empty, cfg)
            .aggregate(ConnectedComponents())
            .collect()
        )
        assert out == []
    width = wire.width_for_capacity(32)
    out = (
        EdgeStream.from_wire(
            [], 8, width, StreamConfig(vertex_capacity=32, batch_size=8, num_shards=8)
        )
        .aggregate(ConnectedComponents())
        .collect()
    )
    assert out == []


def test_mesh_streaming_fold_fewer_edges_than_shards():
    """A 3-edge stream over 8 shards pads empty rows and still folds."""
    src = np.array([1, 2, 5], np.int32)
    dst = np.array([2, 3, 6], np.int32)
    cfg = StreamConfig(vertex_capacity=32, batch_size=4, num_shards=8)
    out = (
        EdgeStream.from_arrays(src, dst, cfg)
        .aggregate(ConnectedComponents())
        .collect()
    )
    comps = out[-1][0].components()
    assert sorted(map(sorted, comps.values())) == [[1, 2, 3], [5, 6]]

"""Ring feature exchange: sharded aggregation equals the replicated kernel.

The ring (parallel/ring.py) rotates modulo-owned feature blocks over the mesh
axis with ppermute while shards accumulate the rows they need — so its
results must match a plain replicated gather exactly.  Runs on the 8-device
CPU mesh (the MiniCluster analog).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from gelly_streaming_tpu.library.graphsage import (
    SageParams,
    init_params,
    sage_kernel,
    sage_kernel_ring,
)
from gelly_streaming_tpu.parallel.mesh import SHARD_AXIS, make_mesh, shard_map
from gelly_streaming_tpu.parallel.ring import (
    ring_neighbor_features,
    shard_features,
)

S = 8  # mesh size (tests force an 8-device CPU backend)


def _case(seed, capacity=64, k_per_shard=5, max_deg=6, feat=16):
    rng = np.random.default_rng(seed)
    features = rng.standard_normal((capacity, feat)).astype(np.float32)
    keys = rng.integers(0, capacity, (S, k_per_shard)).astype(np.int32)
    nbrs = rng.integers(0, capacity, (S, k_per_shard, max_deg)).astype(np.int32)
    valid = rng.random((S, k_per_shard, max_deg)) < 0.7
    return features, keys, nbrs, valid


def _run_ring(features, keys, nbrs, valid, fn):
    mesh = make_mesh(S)
    blocks = jnp.asarray(shard_features(features, S))
    sharded = shard_map(
        fn,
        mesh=mesh,
        in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS)),
        out_specs=P(SHARD_AXIS),
    )
    return jax.jit(sharded)(
        blocks, jnp.asarray(keys), jnp.asarray(nbrs), jnp.asarray(valid)
    )


@pytest.mark.parametrize("seed", [0, 1])
def test_ring_gather_matches_replicated(seed):
    features, keys, nbrs, valid = _case(seed)

    def fn(block, keys, nbrs, valid):
        x_self, mean, cnt = ring_neighbor_features(
            block[0], keys[0], nbrs[0], valid[0], S
        )
        return x_self[None], mean[None], cnt[None]

    x_self, mean, cnt = _run_ring(features, keys, nbrs, valid, fn)

    for s in range(S):
        np.testing.assert_allclose(
            np.asarray(x_self)[s], features[keys[s]], rtol=1e-6
        )
        for i in range(keys.shape[1]):
            sel = valid[s, i]
            expect_cnt = int(sel.sum())
            assert int(np.asarray(cnt)[s, i]) == expect_cnt
            expect = (
                features[nbrs[s, i][sel]].mean(axis=0)
                if expect_cnt
                else np.zeros(features.shape[1])
            )
            np.testing.assert_allclose(
                np.asarray(mean)[s, i], expect, rtol=1e-5, atol=1e-6
            )


def test_sharded_sage_matches_replicated_kernel():
    features, keys, nbrs, valid = _case(7)
    params = init_params(jax.random.key(0), features.shape[1], 8)

    def fn(block, keys, nbrs, valid):
        return sage_kernel_ring(params, block[0], keys[0], nbrs[0], valid[0], S)[None]

    ring_out = np.asarray(_run_ring(features, keys, nbrs, valid, fn))
    for s in range(S):
        expect = np.asarray(
            sage_kernel(
                params,
                jnp.asarray(features),
                jnp.asarray(keys[s]),
                jnp.asarray(nbrs[s]),
                jnp.asarray(valid[s]),
            )
        )
        np.testing.assert_allclose(ring_out[s], expect, rtol=2e-2, atol=2e-2)


def test_shard_features_requires_even_split():
    with pytest.raises(ValueError):
        shard_features(np.zeros((10, 4), np.float32), 8)


def test_ring_scatter_min_folds_updates_from_all_shards():
    """ring_scatter_min: every shard's (global id, value) updates land in the
    owner block after one full loop, regardless of which shard held them."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from gelly_streaming_tpu.parallel.mesh import SHARD_AXIS, make_mesh, shard_map
    from gelly_streaming_tpu.parallel.ring import ring_scatter_min

    s_n = 8
    rows = 4  # table of 32 global slots, modulo-sharded
    mesh = make_mesh(s_n)
    big = np.iinfo(np.int32).max

    def step(blocks, idx, val):
        out = ring_scatter_min(blocks[0], idx[0], val[0], s_n)
        return out[None]

    fn = jax.jit(
        shard_map(
            step,
            mesh=mesh,
            in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS)),
            out_specs=P(SHARD_AXIS),
        )
    )
    table = jnp.full((s_n, rows), 100, jnp.int32)
    # every shard updates global slot 5 (owner 5 % 8) with a different value;
    # shard k also updates slot k with value k
    idx = jnp.stack([jnp.array([5, k], jnp.int32) for k in range(s_n)])
    val = jnp.stack([jnp.array([50 + k, k], jnp.int32) for k in range(s_n)])
    out = np.asarray(fn(table, idx, val))
    flat = out.T.reshape(-1)  # global view: slot g at blocks[g % S, g // S]
    assert flat[5] == 5  # slot 5: min(50..57, shard5's own "5") = 5
    for k in range(s_n):
        if k != 5:
            assert flat[k] == min(k, 100)

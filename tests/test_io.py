"""IO tests: native + fallback edge parsing, interning, checkpointing."""

import os

import numpy as np
import pytest

from gelly_streaming_tpu.core.config import StreamConfig
from gelly_streaming_tpu.io.interning import IdentityInterner, VertexInterner
from gelly_streaming_tpu.io.sources import (
    _parse_edge_file_numpy,
    file_stream,
    parse_edge_file,
)
from gelly_streaming_tpu.utils.checkpoint import load_state, save_state
from gelly_streaming_tpu.utils.native import load_ingest_lib

CFG = StreamConfig(vertex_capacity=64, max_degree=16, batch_size=4)


def _write(tmp_path, name, text):
    p = os.path.join(tmp_path, name)
    with open(p, "w") as f:
        f.write(text)
    return p


def test_native_lib_builds():
    # g++ is in the image; the native parser must actually build.
    assert load_ingest_lib() is not None


@pytest.mark.parametrize("parse", [parse_edge_file, _parse_edge_file_numpy])
def test_parse_plain_edges(parse, tmp_path):
    p = _write(str(tmp_path), "e.txt", "# comment\n1 2\n3\t4\n5,6\n\n")
    src, dst, val, tim, sign = parse(p)
    np.testing.assert_array_equal(src, [1, 3, 5])
    np.testing.assert_array_equal(dst, [2, 4, 6])
    assert val is None and tim is None and sign is None


@pytest.mark.parametrize("parse", [parse_edge_file, _parse_edge_file_numpy])
def test_parse_valued_and_timestamped(parse, tmp_path):
    p = _write(str(tmp_path), "e.txt", "1 2 12.5 100\n3 4 7 200\n")
    src, dst, val, tim, sign = parse(p)
    np.testing.assert_array_equal(src, [1, 3])
    np.testing.assert_allclose(val, [12.5, 7.0])
    np.testing.assert_array_equal(tim, [100, 200])
    assert sign is None


@pytest.mark.parametrize("parse", [parse_edge_file, _parse_edge_file_numpy])
def test_parse_signed_events(parse, tmp_path):
    p = _write(str(tmp_path), "e.txt", "1 2 +\n2 3 +\n1 2 -\n")
    src, dst, val, tim, sign = parse(p)
    np.testing.assert_array_equal(sign, [1, 1, -1])
    assert val is None


def test_native_matches_fallback(tmp_path):
    text = "".join(f"{i} {i+1} {i*10} {i*100}\n" for i in range(50))
    p = _write(str(tmp_path), "big.txt", text)
    a = parse_edge_file(p)
    b = _parse_edge_file_numpy(p)
    for x, y in zip(a, b):
        if x is None:
            assert y is None
        else:
            np.testing.assert_allclose(x, y)


def test_file_stream_end_to_end(tmp_path):
    p = _write(str(tmp_path), "e.txt", "1 2\n2 3\n3 1\n")
    stream, interner = file_stream(p, CFG)
    assert sorted(stream.collect_edges()) == [(1, 2), (2, 3), (3, 1)]


def test_file_stream_interns_large_ids(tmp_path):
    p = _write(str(tmp_path), "e.txt", "1000000 2000000\n2000000 3000000\n")
    stream, interner = file_stream(p, CFG)
    edges = stream.collect_edges()
    assert edges == [(0, 1), (1, 2)]
    assert interner.lookup(0) == 1000000


def test_interner_capacity_guard():
    it = VertexInterner(capacity=2)
    it.intern_ints(np.array([10, 20]))
    with pytest.raises(ValueError, match="capacity"):
        it.intern_ints(np.array([30]))
    ident = IdentityInterner(capacity=4)
    with pytest.raises(ValueError, match="out of range"):
        ident.intern_ints(np.array([7]))


def test_interner_roundtrip():
    it = VertexInterner(capacity=8)
    out = it.intern_ints(np.array([5, 9, 5, 7]))
    np.testing.assert_array_equal(out, [0, 1, 0, 2])
    assert it.lookup_many([0, 1, 2]) == [5, 9, 7]
    out2 = it.intern(["a", "b", "a"])
    np.testing.assert_array_equal(out2, [3, 4, 3])


def test_checkpoint_roundtrip(tmp_path):
    import jax.numpy as jnp

    from gelly_streaming_tpu.library.connected_components import ConnectedComponents

    cc = ConnectedComponents()
    state = cc.initial_state(CFG)
    state = cc.update(
        state,
        jnp.array([1, 2], jnp.int32),
        jnp.array([2, 3], jnp.int32),
        None,
        jnp.ones((2,), bool),
    )
    path = os.path.join(str(tmp_path), "ckpt", "cc.npz")
    save_state(path, state)
    restored = load_state(path, cc.initial_state(CFG))
    np.testing.assert_array_equal(np.asarray(restored.parent), np.asarray(state.parent))
    np.testing.assert_array_equal(np.asarray(restored.seen), np.asarray(state.seen))


def test_checkpoint_structure_mismatch(tmp_path):
    import jax.numpy as jnp

    path = os.path.join(str(tmp_path), "s.npz")
    save_state(path, {"a": jnp.zeros((4,))})
    with pytest.raises(ValueError, match="mismatch"):
        load_state(path, {"a": jnp.zeros((8,))})


def test_native_src_is_canonical_real_file():
    """The wheel ships gelly_streaming_tpu/native_src/edge_parser.cpp as a
    real file (not a symlink — symlinks break on checkouts without symlink
    support, silently degrading ingest to the numpy fallback).  It is the
    CANONICAL source (ISSUE 14 single-sourcing); the repo-layout
    native/edge_parser.cpp is a one-include reference stub, pinned in
    detail by tests/test_native_source_sync.py."""
    import pathlib

    pkg = pathlib.Path(__file__).resolve().parent.parent
    shipped = pkg / "gelly_streaming_tpu" / "native_src" / "edge_parser.cpp"
    assert not shipped.is_symlink()
    body = shipped.read_text()
    assert "extern \"C\"" in body  # the code-carrying copy
    from gelly_streaming_tpu.utils import native as native_mod

    assert native_mod.stub_is_reference_only(
        str(pkg / "native" / "edge_parser.cpp")
    )

"""Parallel host ingest (io/ingest.py): the worker-pool parse and pack
stages must be BIT-EXACT against their single-threaded counterparts — the
whole point of range/arena sharding is speed with zero semantic surface.
"""

import os

import numpy as np
import pytest

from gelly_streaming_tpu.io import ingest, sources, wire


def _write(tmp_path, name, lines, newline="\n", trailing=True):
    path = tmp_path / name
    body = newline.join(lines) + (newline if trailing else "")
    path.write_text(body)
    return str(path)


def _assert_same_parse(path):
    serial = sources.parse_edge_file(path, workers=1)
    parallel = ingest.parse_edge_file_parallel(path, workers=4)
    for a, b in zip(serial, parallel):
        if a is None:
            assert b is None
        else:
            assert np.array_equal(a, b)


def test_parallel_parse_matches_serial_all_column_shapes(tmp_path):
    rng = np.random.default_rng(0)
    n = 3000
    ids = rng.integers(0, 500, (n, 2))
    cases = {
        "plain.txt": [f"{s} {d}" for s, d in ids],
        "valued.txt": [f"{s},{d},{(s + d) / 7:.5f}" for s, d in ids],
        "timed.txt": [f"{s}\t{d}\t{s % 3}.5\t{i}" for i, (s, d) in enumerate(ids)],
        "signed.txt": [
            f"{s} {d} {'+' if i % 3 else '-'}" for i, (s, d) in enumerate(ids)
        ],
    }
    for name, lines in cases.items():
        # comments + blank lines interleaved, as real edge lists have
        salted = ["# header", ""]
        for i, ln in enumerate(lines):
            salted.append(ln)
            if i % 500 == 0:
                salted.append("% interleaved comment")
        _assert_same_parse(_write(tmp_path, name, salted))


def test_parallel_parse_edge_cases(tmp_path):
    # no trailing newline: the final line belongs to the last range
    _assert_same_parse(
        _write(tmp_path, "notrail.txt", ["1 2", "3 4", "5 6"], trailing=False)
    )
    # tiny file: collapses to one range (serial path), still correct
    _assert_same_parse(_write(tmp_path, "tiny.txt", ["7 8"]))
    # comments only: zero edges
    src, dst, val, tim, sign = ingest.parse_edge_file_parallel(
        _write(tmp_path, "comments.txt", ["# a", "% b"]), workers=4
    )
    assert len(src) == 0 and val is None and tim is None and sign is None


def test_parallel_parse_range_boundaries_partition_lines(tmp_path):
    """Force many ranges over a small file: every line parsed exactly once
    whatever the byte boundaries land on."""
    lines = [f"{i} {i + 1}" for i in range(997)]  # varying line lengths
    path = _write(tmp_path, "bounds.txt", lines)
    serial = sources.parse_edge_file(path, workers=1)
    old = ingest.MIN_RANGE_BYTES
    ingest.MIN_RANGE_BYTES = 64  # force ~dozens of ranges
    try:
        parallel = ingest.parse_edge_file_parallel(path, workers=16)
    finally:
        ingest.MIN_RANGE_BYTES = old
    assert np.array_equal(serial[0], parallel[0])
    assert np.array_equal(serial[1], parallel[1])


@pytest.mark.parametrize("width", [2, 3, 4, wire.PAIR40])
def test_pack_rows_into_bit_exact(width):
    rng = np.random.default_rng(1)
    batch, groups = 512, 5
    hi = 1 << 15 if width == 2 else 1 << 19  # ids must fit the encoding
    src = rng.integers(0, hi, batch * groups).astype(np.int32)
    dst = rng.integers(0, hi, batch * groups).astype(np.int32)
    nbytes = wire.wire_nbytes(batch, width)
    arena = np.empty((groups, nbytes), np.uint8)
    ingest.pack_rows_into(src, dst, 0, groups, batch, width, arena, workers=4)
    for j in range(groups):
        ref = wire.pack_edges(
            src[j * batch : (j + 1) * batch], dst[j * batch : (j + 1) * batch], width
        )
        assert np.array_equal(arena[j], ref)


def test_parallel_pack_stream_matches_serial_including_ef40():
    rng = np.random.default_rng(2)
    src = rng.integers(0, 4096, 10_000).astype(np.int32)
    dst = rng.integers(0, 4096, 10_000).astype(np.int32)
    for width in (3, (wire.EF40, 4096)):
        ref_bufs, ref_tail = wire.pack_stream(src, dst, 1024, width)
        par_bufs, par_tail = ingest.parallel_pack_stream(
            src, dst, 1024, width, workers=4
        )
        assert len(ref_bufs) == len(par_bufs)
        for a, b in zip(ref_bufs, par_bufs):
            assert np.array_equal(a, b)
        assert np.array_equal(ref_tail[0], par_tail[0])
        assert np.array_equal(ref_tail[1], par_tail[1])


def test_pack_edges_into_rejects_bad_buffer():
    src = np.arange(8, dtype=np.int32)
    with pytest.raises(ValueError):
        wire.pack_edges_into(src, src, 2, np.empty(3, np.uint8))


def test_resolve_workers_env(monkeypatch):
    assert ingest.resolve_workers(3) == 3
    monkeypatch.setenv("GELLY_INGEST_WORKERS", "5")
    assert ingest.resolve_workers(0) == 5
    monkeypatch.delenv("GELLY_INGEST_WORKERS")
    assert ingest.resolve_workers(0) >= 1


def test_file_stream_parses_in_parallel_by_default(tmp_path):
    """file_stream rides cfg.ingest_workers (0 = auto) and produces the same
    stream as a serial parse."""
    from gelly_streaming_tpu.core.config import StreamConfig
    from gelly_streaming_tpu.io.sources import file_stream

    lines = [f"{i % 50} {(i * 7) % 50}" for i in range(2000)]
    path = _write(tmp_path, "stream.txt", lines)
    cfg = StreamConfig(vertex_capacity=64, batch_size=256)
    stream, _ = file_stream(path, cfg)
    got = stream.collect_edges()
    want = [(i % 50, (i * 7) % 50) for i in range(2000)]
    assert got == want


def test_parallel_parse_long_lines_across_range_boundaries(tmp_path):
    """Lines longer than the native reader's 64KB buffer must parse
    identically in serial and parallel (fragment ownership: every fragment
    of a line belongs to the range its line STARTED in)."""
    long_pad = "# " + "x" * (70 << 10)  # one >64KB comment line
    lines = ["1 2", long_pad, "3 4", "5 6", long_pad, "7 8"]
    path = _write(tmp_path, "long.txt", lines)
    serial = sources.parse_edge_file(path, workers=1)
    old = ingest.MIN_RANGE_BYTES
    ingest.MIN_RANGE_BYTES = 1 << 12  # boundaries land inside the long lines
    try:
        parallel = ingest.parse_edge_file_parallel(path, workers=8)
    finally:
        ingest.MIN_RANGE_BYTES = old
    assert np.array_equal(serial[0], parallel[0])
    assert np.array_equal(serial[1], parallel[1])

"""Windowed k-core (beyond the reference library): h-index fixed point
matches host peeling on known and random graphs; dedupe/self-loop contract;
sliding windows compose."""

import numpy as np
import pytest

from gelly_streaming_tpu.core.config import StreamConfig
from gelly_streaming_tpu.core.stream import EdgeStream
from gelly_streaming_tpu.library.kcore import core_numbers_windows, windowed_kcore

CFG = StreamConfig(vertex_capacity=32, max_degree=16, batch_size=8)


def _host_cores(edges):
    """Classic peeling: repeatedly remove the min-degree vertex."""
    adj = {}
    for s, d in edges:
        if s == d:
            continue
        adj.setdefault(s, set()).add(d)
        adj.setdefault(d, set()).add(s)
    cores = {}
    deg = {v: len(ns) for v, ns in adj.items()}
    k = 0
    while deg:
        v = min(deg, key=deg.get)
        k = max(k, deg[v])
        cores[v] = k
        for u in adj[v]:
            if u in deg and u != v:
                deg[u] -= 1
        del deg[v]
        for u in adj[v]:
            adj.get(u, set()).discard(v)
    return cores


def _records(out):
    return {int(v): int(c) for v, c in out.collect()}


def test_clique_and_pendant():
    # 4-clique (core 3) with a pendant vertex (core 1)
    edges = [(0, 1), (0, 2), (0, 3), (1, 2), (1, 3), (2, 3), (3, 4)]
    got = _records(windowed_kcore(EdgeStream.from_collection(edges, CFG), 1000))
    assert got == {0: 3, 1: 3, 2: 3, 3: 3, 4: 1}


def test_cycle_is_two_core():
    edges = [(0, 1), (1, 2), (2, 3), (3, 0)]
    got = _records(windowed_kcore(EdgeStream.from_collection(edges, CFG), 1000))
    assert got == {0: 2, 1: 2, 2: 2, 3: 2}


def test_tree_is_one_core():
    edges = [(0, 1), (0, 2), (1, 3), (1, 4)]
    got = _records(windowed_kcore(EdgeStream.from_collection(edges, CFG), 1000))
    assert got == {v: 1 for v in range(5)}


def test_duplicates_and_self_loops_ignored():
    edges = [(0, 1), (1, 0), (0, 1), (2, 2), (1, 2), (2, 0)]
    got = _records(windowed_kcore(EdgeStream.from_collection(edges, CFG), 1000))
    # triangle 0-1-2 regardless of dupes/self-loop
    assert got == {0: 2, 1: 2, 2: 2}


@pytest.mark.parametrize("seed", range(4))
def test_random_graphs_match_host_peeling(seed):
    rng = np.random.default_rng(seed)
    edges = [
        (int(rng.integers(0, 24)), int(rng.integers(0, 24))) for _ in range(60)
    ]
    got = _records(windowed_kcore(EdgeStream.from_collection(edges, CFG), 1000))
    assert got == _host_cores(edges)


def test_sliding_windows_compose():
    timed = [
        (0, 1, 0, 100),
        (1, 2, 0, 200),
        (2, 0, 0, 300),   # triangle in pane 0
        (3, 4, 0, 1100),  # lone edge in pane 1
    ]
    stream = EdgeStream.from_collection(timed, CFG, batch_size=2, with_time=True)
    wins = [
        dict(zip(v.tolist(), c.tolist()))
        for v, c in core_numbers_windows(stream, 2000, slide_ms=1000)
    ]
    # windows: 0:{p0} 1:{p0,p1} 2:{p1}
    assert wins[0] == {0: 2, 1: 2, 2: 2}
    assert wins[1] == {0: 2, 1: 2, 2: 2, 3: 1, 4: 1}
    assert wins[2] == {3: 1, 4: 1}


def test_long_path_converges_exactly():
    """Corrections propagate one hop per round: a long path needs ~n/2
    rounds; the default must iterate to the exact fixed point (all cores 1)."""
    cfg = StreamConfig(vertex_capacity=1024, max_degree=8, batch_size=512)
    n = 600
    edges = [(i, i + 1) for i in range(n - 1)]
    got = _records(windowed_kcore(EdgeStream.from_collection(edges, cfg), 1000))
    assert got == {v: 1 for v in range(n)}


def test_exhausted_max_rounds_raises():
    cfg = StreamConfig(vertex_capacity=1024, max_degree=8, batch_size=512)
    edges = [(i, i + 1) for i in range(399)]
    stream = EdgeStream.from_collection(edges, cfg)
    with pytest.raises(RuntimeError, match="converge"):
        list(core_numbers_windows(stream, 1000, max_rounds=3))

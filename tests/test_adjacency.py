"""Adjacency summary tests mirroring util/AdjacencyListGraphTest.java."""

from gelly_streaming_tpu.summaries.adjacency import AdjacencyListGraph


def test_add_edge():
    # Mirrors AdjacencyListGraphTest.testAddEdge (:32-54)
    g = AdjacencyListGraph(capacity=32, max_degree=8)
    g.add_edge(1, 2)
    m = g.adjacency_map()
    assert len(m) == 2
    assert 2 in m[1] and 1 in m[2]
    assert len(m[1]) == 1 and len(m[2]) == 1

    g.add_edge(1, 3)
    m = g.adjacency_map()
    assert len(m) == 3
    assert 2 in m[1] and 3 in m[1] and 1 in m[3]

    g.add_edge(3, 1)  # duplicate in reverse: idempotent
    m = g.adjacency_map()
    assert len(m) == 3
    assert len(m[1]) == 2 and len(m[3]) == 1

    g.add_edge(1, 2)  # exact duplicate: idempotent
    m = g.adjacency_map()
    assert len(m) == 3
    assert len(m[1]) == 2 and len(m[2]) == 1


def test_bounded_bfs():
    # Mirrors AdjacencyListGraphTest.testBoundedBFS (:58-85): the spanner
    # admission sequence — boundedBFS(src, trg, k) == True means "within k hops"
    # (edge dropped); False means the edge must be added.
    g = AdjacencyListGraph(capacity=32, max_degree=8)
    g.add_edge(1, 4)
    g.add_edge(4, 5)
    g.add_edge(5, 6)
    g.add_edge(4, 7)
    g.add_edge(7, 8)

    assert g.bounded_bfs(2, 3, 3) is False
    g.add_edge(2, 3)

    assert g.bounded_bfs(3, 4, 3) is False
    g.add_edge(3, 4)

    assert g.bounded_bfs(3, 6, 3) is True  # 3-4-5-6: 3 hops -> dropped

    assert g.bounded_bfs(8, 9, 3) is False
    g.add_edge(8, 9)

    assert g.bounded_bfs(8, 6, 3) is False
    g.add_edge(8, 6)

    assert g.bounded_bfs(5, 9, 3) is True  # 5-6-8-9: 3 hops -> dropped

"""The masked-semiring SpMV kernel core (ISSUE 17): semiring lowerings
fuzzed against numpy oracles, the push/pull direction-optimized fixpoint
bit-identical to the pre-refactor per-algorithm kernels (embedded here as
oracles) in every direction mode, the retrace guard (zero recompiles
across frontier-density drift and force-push/force-pull/auto flips — the
traced threshold is the only thing that changes), the spmv_stats
registry, and the loud-refusal contracts on the direction knobs."""

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gelly_streaming_tpu.core import compile_cache
from gelly_streaming_tpu.core.config import StreamConfig
from gelly_streaming_tpu.core.stream import EdgeStream
from gelly_streaming_tpu.library.pagerank import windowed_pagerank
from gelly_streaming_tpu.library.sssp import windowed_sssp
from gelly_streaming_tpu.ops import spmv
from gelly_streaming_tpu.ops import unionfind as uf
from gelly_streaming_tpu.utils import metrics
from gelly_streaming_tpu.utils.envswitch import env_choice

C = 64
CFG = StreamConfig(vertex_capacity=32, max_degree=16, batch_size=8)


def _rand_pane(rng, e_pad, capacity=C, skew=False, self_loops=False,
               mask_frac=0.8):
    """One padded pane: (src, dst, w, msk) with the fuzz dimensions the
    kernel must survive — skewed hubs, masked padding, self-loops, and
    the max vertex id capacity-1."""
    if skew:
        src = ((rng.zipf(1.3, e_pad) - 1) % capacity).astype(np.int32)
    else:
        src = rng.integers(0, capacity, e_pad).astype(np.int32)
    dst = rng.integers(0, capacity, e_pad).astype(np.int32)
    if self_loops:
        src[: e_pad // 8] = dst[: e_pad // 8]
    src[0], dst[0] = capacity - 1, capacity - 1  # max-id edge always present
    w = (rng.integers(1, 8, e_pad)).astype(np.float32)  # int-valued: exact
    msk = rng.random(e_pad) < mask_frac
    return src, dst, w, msk


def _oracle_dense(sem, src, dst, w, msk, x, capacity):
    """Sequential per-edge reference for one masked semiring SpMV."""
    ident = sem.identity
    if sem.name == "min_plus":
        ident = float(np.float32(ident))  # the f32 the kernel really holds
    y = np.full((capacity,), ident, np.float64)
    for s, d, wt, m in zip(src, dst, w, msk):
        if not m:
            continue
        if sem.name == "min_plus":
            y[d] = min(y[d], float(x[s]) + float(wt))
        elif sem.name == "plus_times":
            y[d] += float(x[s]) * float(wt)
        elif sem.name == "min_min":
            y[d] = min(y[d], min(float(x[s]), float(wt)))
        elif sem.name == "plus_one":
            y[d] += 1
    return y


@pytest.mark.parametrize("case", ["uniform", "skew", "selfloop", "allmask",
                                  "nomask"])
@pytest.mark.parametrize(
    "sem", [spmv.MIN_PLUS, spmv.PLUS_TIMES, spmv.MIN_MIN, spmv.PLUS_ONE],
    ids=lambda s: s.name,
)
def test_spmv_dense_matches_numpy_oracle(sem, case):
    rng = np.random.default_rng(hash((sem.name, case)) % (1 << 31))
    src, dst, w, msk = _rand_pane(
        rng, 128,
        skew=case == "skew",
        self_loops=case == "selfloop",
        mask_frac={"allmask": 0.0, "nomask": 1.0}.get(case, 0.8),
    )
    op = spmv.prepare_pane(src, dst, w, msk, C)
    if sem.name in ("min_min", "plus_one"):
        x = rng.integers(0, 100, C).astype(np.int32)
    else:
        x = rng.integers(0, 10, C).astype(np.float32)
    got = np.asarray(spmv.spmv_dense(sem, op, jnp.asarray(x)))
    want = _oracle_dense(sem, src, dst, w, msk, x, C)
    if sem.name == "plus_times":
        np.testing.assert_allclose(got, want, rtol=1e-5)
    else:
        np.testing.assert_array_equal(got.astype(np.float64), want)


def test_spmsv_frontier_matches_dense_restricted():
    rng = np.random.default_rng(7)
    src, dst, w, msk = _rand_pane(rng, 128, skew=True)
    op = spmv.prepare_pane(src, dst, w, msk, C)
    x = rng.integers(0, 10, C).astype(np.float32)
    fm = rng.random(C) < 0.25
    got = np.asarray(
        spmv.spmsv_frontier(spmv.MIN_PLUS, op, jnp.asarray(x), jnp.asarray(fm))
    )
    # the push lowering only reads frontier rows: mask down to them
    want = _oracle_dense(
        spmv.MIN_PLUS, src, dst, w, msk & fm[src], x, C
    )
    np.testing.assert_array_equal(got.astype(np.float64), want)


def test_spmsv_frontier_overflow_refuses_loudly():
    rng = np.random.default_rng(8)
    src, dst, w, msk = _rand_pane(rng, 128, mask_frac=1.0)
    op = spmv.prepare_pane(src, dst, w, msk, C)
    x = np.zeros((C,), np.float32)
    with pytest.raises(ValueError, match="f_cap"):
        spmv.spmsv_frontier(
            spmv.MIN_PLUS, op, jnp.asarray(x),
            jnp.ones((C,), bool), f_cap=4,
        )


def test_scatter_into_counts_degrees():
    rng = np.random.default_rng(9)
    src, dst, w, msk = _rand_pane(rng, 128)
    got = np.asarray(
        spmv.scatter_into(
            spmv.PLUS_ONE, C, jnp.asarray(src),
            jnp.ones((128,), jnp.int32), jnp.asarray(msk),
        )
    )
    want = np.bincount(src[msk], minlength=C)
    np.testing.assert_array_equal(got, want)


# ---------------------------------------------------------------------------
# bit-identity vs the pre-refactor per-algorithm kernels (embedded oracles:
# these ARE the deleted library kernels, verbatim)

_BIG = jnp.float32(1e30)


@partial(jax.jit, static_argnames=("capacity",))
def _pane_sssp_oracle(src, dst, w, mask, source, capacity, max_iters):
    dist0 = jnp.full((capacity,), _BIG).at[source].set(0.0)

    def body(state):
        dist, _, it = state
        cand = jnp.where(mask, dist[src] + w, _BIG)
        relaxed = jnp.full((capacity,), _BIG).at[dst].min(cand)
        new = jnp.minimum(dist, relaxed)
        return new, jnp.any(new < dist), it + 1

    def cond(state):
        _, changed, it = state
        return changed & (it < max_iters)

    dist, _, iters = jax.lax.while_loop(
        cond, body, (dist0, jnp.bool_(True), 0)
    )
    return dist, iters


@partial(jax.jit, static_argnames=("capacity",))
def _pane_pagerank_oracle(src, dst, mask, capacity, damping, tol, max_iters):
    zeros = jnp.zeros((capacity,), jnp.float32)
    ones = jnp.ones_like(zeros)
    m = mask.astype(jnp.float32)
    in_window = zeros.at[src].max(m).at[dst].max(m) > 0
    out_deg = zeros.at[src].add(m)
    n = jnp.maximum(jnp.sum(in_window.astype(jnp.float32)), 1.0)
    dangling = in_window & (out_deg == 0)
    base = jnp.where(in_window, (1.0 - damping) / n, 0.0)
    safe_deg = jnp.maximum(out_deg, 1.0)

    def body(state):
        r, _, it = state
        contrib = jnp.where(mask, r[src] / safe_deg[src], 0.0)
        spread = zeros.at[dst].add(contrib)
        dangling_mass = jnp.sum(jnp.where(dangling, r, 0.0)) / n
        r_new = base + damping * (
            spread + jnp.where(in_window, dangling_mass, 0.0)
        )
        delta = jnp.sum(jnp.abs(r_new - r))
        return r_new, delta, it + 1

    def cond(state):
        _, delta, it = state
        return (delta > tol) & (it < max_iters)

    r0 = jnp.where(in_window, ones / n, 0.0)
    r, _, iters = jax.lax.while_loop(cond, body, (r0, jnp.inf, 0))
    return r, in_window, iters


@pytest.mark.timeout_cap(120)
@pytest.mark.parametrize("mode", ["auto", "push", "pull"])
def test_fixpoint_bit_identical_to_pre_refactor_sssp(mode):
    rng = np.random.default_rng(11)
    src, dst, w, msk = _rand_pane(rng, 256, skew=True)
    want, want_iters = _pane_sssp_oracle(
        jnp.asarray(src), jnp.asarray(dst), jnp.asarray(w),
        jnp.asarray(msk), jnp.int32(0), C, jnp.int32(C - 1),
    )
    op = spmv.prepare_pane(src, dst, w, msk, C)
    x0 = jnp.full((C,), spmv.MIN_PLUS.identity, jnp.float32).at[0].set(0.0)
    res = spmv.fixpoint(
        spmv.MIN_PLUS, op, x0, max_iters=C - 1, direction=mode
    )
    np.testing.assert_array_equal(np.asarray(res.x), np.asarray(want))
    assert res.iters == int(want_iters)
    if mode == "push":
        assert res.pull_iters == 0
    if mode == "pull":
        assert res.push_iters == 0


@pytest.mark.timeout_cap(120)
@pytest.mark.parametrize("threshold", [0.0, 0.03, 0.5, 1.0])
def test_fixpoint_threshold_sweep_keeps_answers(threshold):
    # the density cut changes WHICH lowering runs each iteration, never
    # what it computes
    rng = np.random.default_rng(12)
    src, dst, w, msk = _rand_pane(rng, 256, skew=True)
    want, _ = _pane_sssp_oracle(
        jnp.asarray(src), jnp.asarray(dst), jnp.asarray(w),
        jnp.asarray(msk), jnp.int32(3), C, jnp.int32(C - 1),
    )
    op = spmv.prepare_pane(src, dst, w, msk, C)
    x0 = jnp.full((C,), spmv.MIN_PLUS.identity, jnp.float32).at[3].set(0.0)
    res = spmv.fixpoint(
        spmv.MIN_PLUS, op, x0, max_iters=C - 1, threshold=threshold
    )
    np.testing.assert_array_equal(np.asarray(res.x), np.asarray(want))


@pytest.mark.timeout_cap(120)
def test_fixpoint_rejects_non_idempotent_semirings():
    rng = np.random.default_rng(13)
    src, dst, w, msk = _rand_pane(rng, 64)
    op = spmv.prepare_pane(src, dst, w, msk, C)
    with pytest.raises(ValueError, match="idempotent"):
        spmv.fixpoint(
            spmv.PLUS_TIMES, op, jnp.zeros((C,), jnp.float32), max_iters=4
        )
    with pytest.raises(ValueError, match="direction"):
        spmv.fixpoint(
            spmv.MIN_PLUS, op, jnp.zeros((C,), jnp.float32),
            max_iters=4, direction="sideways",
        )


@pytest.mark.timeout_cap(120)
def test_pagerank_fixpoint_push_pull_bit_identical():
    rng = np.random.default_rng(14)
    src, dst, _, msk = _rand_pane(rng, 256, skew=True)
    want_r, want_in, want_it = _pane_pagerank_oracle(
        jnp.asarray(src), jnp.asarray(dst), jnp.asarray(msk),
        C, jnp.float32(0.85), jnp.float32(1e-6), jnp.int32(100),
    )
    op = spmv.prepare_pane(src, dst, None, msk, C)
    for use_pull in (False, True):
        r, in_w, iters = spmv.pagerank_fixpoint(
            op, damping=0.85, tol=1e-6, max_iters=100, use_pull=use_pull
        )
        np.testing.assert_array_equal(np.asarray(r), np.asarray(want_r))
        np.testing.assert_array_equal(np.asarray(in_w), np.asarray(want_in))
        assert int(iters) == int(want_it)


@pytest.mark.timeout_cap(120)
def test_cc_fixpoint_matches_unionfind():
    rng = np.random.default_rng(15)
    for _ in range(5):
        src = rng.integers(0, C, 64).astype(np.int32)
        dst = rng.integers(0, C, 64).astype(np.int32)
        msk = rng.random(64) < 0.7
        p0, s0 = uf.init_parent(C), jnp.zeros((C,), bool)
        p_want, s_want = uf.union_edges_with_seen(
            p0, s0, jnp.asarray(src), jnp.asarray(dst), jnp.asarray(msk)
        )
        p_got, s_got = spmv.cc_fixpoint(
            p0, s0, jnp.asarray(src), jnp.asarray(dst), jnp.asarray(msk)
        )
        np.testing.assert_array_equal(np.asarray(p_got), np.asarray(p_want))
        np.testing.assert_array_equal(np.asarray(s_got), np.asarray(s_want))


# ---------------------------------------------------------------------------
# emission parity: the rebuilt library algorithms emit the same records in
# every direction mode

def _collect(out):
    return [(int(v), float(d)) for v, d in out.collect()]


@pytest.mark.timeout_cap(120)
def test_windowed_sssp_emissions_identical_across_modes():
    edges = [(0, 1, 4.0), (0, 2, 1.0), (2, 1, 2.0), (1, 3, 1.0), (2, 3, 5.0),
             (3, 4, 0.5), (4, 5, 0.5), (0, 5, 9.0)]
    base = _collect(
        windowed_sssp(EdgeStream.from_collection(edges, CFG), 0, 1000)
    )
    for mode in ("push", "pull", "auto"):
        cfg = dataclasses.replace(CFG, spmv_direction=mode)
        got = _collect(
            windowed_sssp(EdgeStream.from_collection(edges, cfg), 0, 1000)
        )
        assert got == base, mode
    # an explicit threshold changes scheduling, not answers
    cfg = dataclasses.replace(CFG, direction_threshold=0.5)
    got = _collect(
        windowed_sssp(EdgeStream.from_collection(edges, cfg), 0, 1000)
    )
    assert got == base


@pytest.mark.timeout_cap(120)
def test_windowed_pagerank_emissions_identical_across_modes():
    edges = [(0, 1), (1, 2), (2, 0), (2, 3), (3, 0), (1, 3)]
    base = _collect(
        windowed_pagerank(EdgeStream.from_collection(edges, CFG), 1000)
    )
    for mode in ("push", "pull", "auto"):
        cfg = dataclasses.replace(CFG, spmv_direction=mode)
        got = _collect(
            windowed_pagerank(EdgeStream.from_collection(edges, cfg), 1000)
        )
        assert got == base, mode


# ---------------------------------------------------------------------------
# retrace guard: one executable serves both directions — density drift,
# threshold changes, and force-mode flips land zero recompiles

@pytest.mark.timeout_cap(120)
def test_zero_recompiles_across_density_drift_and_mode_flips():
    rng = np.random.default_rng(16)
    src, dst, w, msk = _rand_pane(rng, 256, skew=True)
    op = spmv.prepare_pane(src, dst, w, msk, C)

    def run(source, mode, threshold=None):
        x0 = (
            jnp.full((C,), spmv.MIN_PLUS.identity, jnp.float32)
            .at[source].set(0.0)
        )
        return spmv.fixpoint(
            spmv.MIN_PLUS, op, x0, max_iters=C - 1,
            direction=mode, threshold=threshold,
        )

    run(0, "auto")  # warm the (single-bucket) executable
    compile_cache.reset_stats()
    for source, mode, thr in [
        (0, "push", None), (0, "pull", None), (0, "auto", 0.5),
        (1, "auto", None), (7, "push", None), (C - 1, "pull", None),
        (3, "auto", 0.01),
    ]:
        run(source, mode, thr)
    assert compile_cache.recompiles() == 0
    assert compile_cache.stats()["compiles"] == 0  # not even new buckets


@pytest.mark.timeout_cap(120)
def test_spmv_stats_registry_counts_direction_split():
    rng = np.random.default_rng(17)
    src, dst, w, msk = _rand_pane(rng, 256, skew=True)
    op = spmv.prepare_pane(src, dst, w, msk, C)
    x0 = jnp.full((C,), spmv.MIN_PLUS.identity, jnp.float32).at[0].set(0.0)
    metrics.reset_spmv_stats()
    res = spmv.fixpoint(spmv.MIN_PLUS, op, x0, max_iters=C - 1)
    stats = metrics.spmv_stats()
    assert stats["spmv_fixpoints"] == 1
    assert stats["spmv_push_iters"] == res.push_iters
    assert stats["spmv_pull_iters"] == res.pull_iters
    assert stats["spmv_direction_switches"] == res.switches
    assert stats["spmv_iters_total"] == res.iters
    hist = sum(
        stats[f"spmv_density_hist_{b}"]
        for b in range(metrics.SPMV_DENSITY_BINS)
    )
    assert hist == res.iters  # every iteration lands in exactly one bin
    metrics.reset_spmv_stats()
    assert metrics.spmv_stats()["spmv_fixpoints"] == 0
    # the registry rides into the shared snapshot beside the other planes
    assert "spmv" in metrics.metrics_snapshot()


# ---------------------------------------------------------------------------
# config/env knobs refuse loudly

def test_resolve_direction_env_knob(monkeypatch):
    assert spmv.resolve_direction(CFG) == "auto"
    monkeypatch.setenv("GELLY_SPMV_DIRECTION", "pull")
    assert spmv.resolve_direction(CFG) == "pull"
    monkeypatch.setenv("GELLY_SPMV_DIRECTION", " Push ")
    assert spmv.resolve_direction(CFG) == "push"
    cfg = dataclasses.replace(CFG, spmv_direction="auto")
    assert spmv.resolve_direction(cfg) == "auto"  # cfg beats env
    monkeypatch.setenv("GELLY_SPMV_DIRECTION", "sideways")
    with pytest.raises(ValueError, match="GELLY_SPMV_DIRECTION"):
        spmv.resolve_direction(CFG)


def test_resolve_threshold_env_knob(monkeypatch):
    assert spmv.resolve_threshold(CFG) == spmv.DEFAULT_DIRECTION_THRESHOLD
    monkeypatch.setenv("GELLY_DIRECTION_THRESHOLD", "0.25")
    assert spmv.resolve_threshold(CFG) == 0.25
    cfg = dataclasses.replace(CFG, direction_threshold=0.75)
    assert spmv.resolve_threshold(cfg) == 0.75  # cfg beats env
    for bad in ("lots", "1.5", "-0.1"):
        monkeypatch.setenv("GELLY_DIRECTION_THRESHOLD", bad)
        with pytest.raises(ValueError, match="GELLY_DIRECTION_THRESHOLD"):
            spmv.resolve_threshold(CFG)


def test_env_choice_refuses_unrecognized_spellings(monkeypatch):
    monkeypatch.delenv("GELLY_SPMV_DIRECTION", raising=False)
    assert env_choice("GELLY_SPMV_DIRECTION", spmv.DIRECTIONS, "auto") == "auto"
    monkeypatch.setenv("GELLY_SPMV_DIRECTION", "maybe")
    with pytest.raises(ValueError, match="auto/push/pull"):
        env_choice("GELLY_SPMV_DIRECTION", spmv.DIRECTIONS, "auto")


def test_config_rejects_bad_direction_fields():
    with pytest.raises(ValueError, match="spmv_direction"):
        StreamConfig(vertex_capacity=32, spmv_direction="sideways")
    with pytest.raises(ValueError, match="direction_threshold"):
        StreamConfig(vertex_capacity=32, direction_threshold=1.5)

"""Hot-path sync lint (tier-1): ``# hot-loop`` regions stay free of blocking
host syncs.

The async window pipeline's invariant (core/async_exec.py) is that dispatch
loops never wait on the device; a single ``np.asarray`` / ``.item()`` /
``block_until_ready`` re-introduced into one of those loops silently
restores the one-RTT-per-window lockstep.  This test pins the invariant over
the marked regions in ``core/``, ``io/``, and ``library/`` — plus unit-tests
the checker itself so a broken linter cannot pass vacuously.
"""

import textwrap

from gelly_streaming_tpu.utils import hot_loop_lint


def _lint(src: str):
    return hot_loop_lint.check_source(textwrap.dedent(src), "probe.py")


def test_package_hot_loops_are_sync_free():
    problems = hot_loop_lint.check_paths(
        hot_loop_lint.package_hot_loop_paths()
    )
    assert problems == [], "\n".join(problems)


def test_package_has_marked_regions():
    """The invariant is only pinned if regions are actually marked: count
    the ``# hot-loop`` openers across the scanned planes."""
    import os

    count = 0
    for root in hot_loop_lint.package_hot_loop_paths():
        for dirpath, _dirs, files in os.walk(root):
            for name in files:
                if not name.endswith(".py"):
                    continue
                with open(os.path.join(dirpath, name)) as f:
                    regions, errors = hot_loop_lint._regions(
                        f.read().splitlines()
                    )
                assert errors == []
                count += len(regions)
    assert count >= 5, "expected the async/wire dispatch loops to be marked"


def test_detects_np_asarray_in_region():
    problems = _lint(
        """
        import numpy as np

        def f(xs):
            out = []
            # hot-loop: probe region
            for x in xs:
                out.append(np.asarray(x))
            # hot-loop-end
            return out
        """
    )
    assert len(problems) == 1 and "np.asarray()" in problems[0]


def test_detects_item_and_block_until_ready():
    problems = _lint(
        """
        import jax

        def f(xs):
            # hot-loop
            for x in xs:
                x.block_until_ready()
                jax.block_until_ready(x)
                y = x.item()
            # hot-loop-end
        """
    )
    assert len(problems) == 3


def test_outside_region_and_jnp_asarray_are_clean():
    problems = _lint(
        """
        import numpy as np
        import jax.numpy as jnp

        def f(xs):
            host = np.asarray(xs)  # outside any region: fine
            # hot-loop
            dev = [jnp.asarray(x) for x in xs]  # transfer, not a sync
            # hot-loop-end
            return host, dev
        """
    )
    assert problems == []


def test_hot_loop_ok_allowlists_single_line():
    problems = _lint(
        """
        import numpy as np

        def f(xs):
            # hot-loop
            for x in xs:
                a = np.asarray(x)  # hot-loop-ok: completion-queue drain
                b = np.asarray(x)
            # hot-loop-end
            return a, b
        """
    )
    assert len(problems) == 1


def test_hot_loop_ok_honored_on_multiline_call_closing_line():
    """Regression: the allowlist marker must be honored on ANY physical
    line of the flagged call — a black-formatted multi-line call hangs its
    trailing comment on the closing paren line, which the original
    single-line scan (node.lineno only) missed."""
    problems = _lint(
        """
        import numpy as np

        def f(xs):
            out = []
            # hot-loop
            for x in xs:
                out.append(
                    np.asarray(
                        x
                    )  # hot-loop-ok: completion-queue drain
                )
            # hot-loop-end
            return out
        """
    )
    assert problems == []


def test_multiline_call_without_marker_still_flagged():
    problems = _lint(
        """
        import numpy as np

        def f(xs):
            # hot-loop
            ys = [
                np.asarray(
                    x
                )
                for x in xs
            ]
            # hot-loop-end
            return ys
        """
    )
    assert len(problems) == 1 and "np.asarray()" in problems[0]


def test_unclosed_region_is_an_error():
    problems = _lint(
        """
        def f():
            # hot-loop
            return 1
        """
    )
    assert len(problems) == 1 and "never closed" in problems[0]

"""Fleet tier control plane (ISSUE 20): registry, replication, failover,
rebalancing — and the kill-a-backend chaos test.

The contracts under test:

* PLACEMENT — rendezvous hashing is deterministic, spreads keys, and is
  overridden by pins and takeovers (never by re-hashing on liveness
  flaps).
* LIVENESS — ``report_failure`` transitions a backend down exactly once
  at the threshold, from probe OR data-plane reports.
* REPLICATION — journals and ``per_job_file`` checkpoints ship atomically
  to the standby's paths; unchanged files are skipped.
* FAILOVER — journal replay resubmits exactly the non-terminal
  ``job_spec`` records on the standby and installs the takeover.
* REBALANCE — the Autoscaler's streak/cooldown policy shape, evaluated
  deterministically with an injected clock and burn probe.
* CHAOS — SIGKILL a backend mid-stream under 2 tenants x 2 jobs behind a
  live router: the standby reattaches the dead backend's jobs at their
  resume cursors, resilient clients finish with exact non-idempotent
  counts and overlap-only emissions, and ``job_history`` replayed across
  the replica + standby journals spans both incarnations.

Every test carries ``timeout_cap`` (threads/sockets/subprocess).
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from gelly_streaming_tpu.runtime.client import GellyClient
from gelly_streaming_tpu.runtime.fleet import (
    BackendSpec,
    Fleet,
    FleetConfig,
    FleetRebalancer,
    RebalancePolicy,
)
from gelly_streaming_tpu.utils import events
from gelly_streaming_tpu.utils.checkpoint import per_job_file

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.timeout_cap(600)

CAP = 1 << 10
W = 1 << 8
B = 1 << 7
N = 8 * W


def _specs(n, standby=False, **kw):
    specs = [
        BackendSpec(f"b{i + 1}", "127.0.0.1", 7400 + i, **kw)
        for i in range(n)
    ]
    if standby:
        specs.append(BackendSpec("sb", "127.0.0.1", 7499, standby=True, **kw))
    return tuple(specs)


# ---------------------------------------------------------------------------
# placement
# ---------------------------------------------------------------------------


def test_rendezvous_placement_deterministic_spread_and_overrides():
    fleet = Fleet(FleetConfig(backends=_specs(3, standby=True)))
    keys = [("t1", f"job-{i}") for i in range(48)]
    first = {k: fleet.place(*k).name for k in keys}
    # deterministic: a second resolution (or a second router) agrees
    assert {k: fleet.place(*k).name for k in keys} == first
    # spread: every serving backend owns some keys, the standby none
    assert set(first.values()) == {"b1", "b2", "b3"}
    # pins override rendezvous for exactly their key
    tenant, job = keys[0]
    other = "b1" if first[keys[0]] != "b1" else "b2"
    fleet.pin(tenant, job, other)
    assert fleet.place(tenant, job).name == other
    assert fleet.place(*keys[1]).name == first[keys[1]]
    # a takeover redirects EVERY key of the dead backend to the standby
    with fleet._lock:
        fleet._takeover["b2"] = "sb"
    for k, name in first.items():
        want = "sb" if name == "b2" and k != keys[0] else first[k]
        if k == keys[0]:
            want = "sb" if other == "b2" else other
        assert fleet.place(*k).name == want


def test_tenant_for_token_inverts_configured_tokens():
    fleet = Fleet(
        FleetConfig(
            backends=_specs(2),
            tenant_tokens={"t1": "tok1", "t2": "tok2"},
        )
    )
    assert fleet.tenant_for_token("tok1") == "t1"
    assert fleet.tenant_for_token("tok2") == "t2"
    assert fleet.tenant_for_token("") == "default"
    # unknown tokens hash as themselves: placement stays consistent
    assert fleet.tenant_for_token("mystery") == "mystery"


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------


def test_registry_down_transition_fires_exactly_once():
    from gelly_streaming_tpu.runtime.fleet import BackendRegistry

    downs = []
    reg = BackendRegistry(
        _specs(2), fail_threshold=2, on_down=lambda s: downs.append(s.name)
    )
    reg.report_failure("b1")
    assert reg.is_alive("b1") and not downs
    reg.report_failure("b1")
    assert not reg.is_alive("b1") and downs == ["b1"]
    # further failures don't re-fire the transition
    reg.report_failure("b1")
    assert downs == ["b1"]
    # recovery re-arms it
    reg.mark_up("b1")
    reg.report_failure("b1")
    reg.report_failure("b1")
    assert downs == ["b1", "b1"]
    # unknown names are ignored, not crashed on
    reg.report_failure("nope")


def test_probe_once_reports_unreachable_backends():
    import socket as socket_mod

    from gelly_streaming_tpu.runtime.fleet import BackendRegistry

    probe = socket_mod.socket()
    probe.bind(("127.0.0.1", 0))
    dead_port = probe.getsockname()[1]
    probe.close()
    reg = BackendRegistry(
        (BackendSpec("gone", "127.0.0.1", dead_port),),
        probe_timeout_s=1.0,
        fail_threshold=2,
    )
    assert reg.probe_once() == {"gone": True}  # first strike
    assert reg.probe_once() == {"gone": False}  # threshold
    snap = reg.snapshot()
    assert snap["gone"]["alive"] is False
    assert snap["gone"]["fails"] >= 2


# ---------------------------------------------------------------------------
# replication
# ---------------------------------------------------------------------------


def test_sync_backend_ships_journal_and_checkpoints_atomically(tmp_path):
    b1_ck = str(tmp_path / "b1" / "ck")
    sb_ck = str(tmp_path / "sb" / "ck")
    journal = tmp_path / "b1" / "journal.jsonl"
    journal.parent.mkdir(parents=True)
    journal.write_text('{"kind": "job_spec", "job": "t1/j"}\n')
    np.savez(per_job_file(b1_ck, "t1.j"), cursor=np.int64(512))
    b1 = BackendSpec(
        "b1", "127.0.0.1", 7400,
        journal_path=str(journal), checkpoint_prefix=b1_ck,
    )
    sb = BackendSpec(
        "sb", "127.0.0.1", 7499, checkpoint_prefix=sb_ck, standby=True,
    )
    fleet = Fleet(
        FleetConfig(backends=(b1, sb), replica_dir=str(tmp_path / "replica"))
    )
    stats = fleet.sync_backend(b1)
    assert stats["files"] == 2 and stats["bytes"] > 0
    assert os.path.exists(fleet.replica_journal_path("b1"))
    shipped = per_job_file(sb_ck, "t1.j")
    assert os.path.exists(shipped)
    assert int(np.load(shipped)["cursor"]) == 512
    # unchanged sources are skipped (size+mtime match)
    assert fleet.sync_backend(b1) == {"files": 0, "bytes": 0}
    # a changed journal ships again
    with open(journal, "a") as f:
        f.write('{"kind": "job_spec", "job": "t1/k"}\n')
    assert fleet.sync_backend(b1)["files"] == 1
    assert fleet.snapshot()["replication"]["syncs"] == 3


# ---------------------------------------------------------------------------
# failover from journal replay (standby = in-process server)
# ---------------------------------------------------------------------------


def test_failover_resubmits_only_live_jobs_and_installs_takeover(tmp_path):
    from gelly_streaming_tpu.core.config import ServerConfig
    from gelly_streaming_tpu.runtime import JobManager, StreamServer

    replica_dir = tmp_path / "replica"
    replica_dir.mkdir()
    spec = {
        "name": "live", "query": "edges", "capacity": CAP,
        "window_edges": W, "batch": B,
    }
    done_spec = dict(spec, name="done")
    rows = [
        {"kind": "job_spec", "job": "default/live", "tenant": "default",
         "spec": spec},
        {"kind": "job_submitted", "job": "default/live"},
        {"kind": "job_spec", "job": "default/done", "tenant": "default",
         "spec": done_spec},
        {"kind": "job_submitted", "job": "default/done"},
        {"kind": "job_transition", "job": "default/done",
         "from": "PENDING", "to": "RUNNING"},
        {"kind": "job_transition", "job": "default/done",
         "from": "RUNNING", "to": "DONE"},
    ]
    (replica_dir / "journal-b1.jsonl").write_text(
        "".join(json.dumps(r) + "\n" for r in rows)
    )
    with JobManager() as jm, StreamServer(jm, ServerConfig()) as standby:
        fleet = Fleet(
            FleetConfig(
                backends=(
                    BackendSpec("b1", "127.0.0.1", 1),  # dead by construction
                    BackendSpec(
                        "sb", "127.0.0.1", standby.port, standby=True
                    ),
                ),
                replica_dir=str(replica_dir),
            )
        )
        outcome = fleet.failover("b1")
        assert [r["job"] for r in outcome["resubmitted"]] == ["default/live"]
        assert outcome["failed"] == []
        assert fleet.takeover_map() == {"b1": "sb"}
        assert fleet.place("default", "live").name == "sb"
        # the standby actually serves the job now
        with GellyClient("127.0.0.1", standby.port) as c:
            assert "default/live" in c.status()["status"]["jobs"]
        # failover runs at most once per backend
        assert fleet.failover("b1")["resubmitted"] == []


# ---------------------------------------------------------------------------
# rebalancer policy (deterministic: injected clock + burn probe)
# ---------------------------------------------------------------------------


def test_rebalancer_streak_cooldown_and_target_choice(monkeypatch):
    fleet = Fleet(FleetConfig(backends=_specs(3)))
    moves = []
    monkeypatch.setattr(
        fleet, "rebalance",
        lambda tenant, src, dst: (
            moves.append((tenant, src, dst))
            or {"tenant": tenant, "moved": [], "failed": []}
        ),
    )
    burning = {"b1": {"t1": True}}
    rb = FleetRebalancer(
        fleet,
        policy=RebalancePolicy(page_streak=3, cooldown_s=60.0),
        burn_probe=lambda spec: burning.get(spec.name, {}),
    )
    assert rb.evaluate_once(0.0) == []  # streak 1
    assert rb.evaluate_once(1.0) == []  # streak 2
    rb.evaluate_once(2.0)  # streak 3: actuates
    # target = coldest (fewest pins), name-tiebroken: b2
    assert moves == [("t1", "b1", "b2")]
    # cooldown holds the pair even under sustained burn
    for t in (3.0, 4.0, 5.0):
        rb.evaluate_once(t)
    assert len(moves) == 1
    # a burn-free evaluation resets the streak
    burning.clear()
    rb.evaluate_once(6.0)
    burning["b1"] = {"t1": True}
    rb.evaluate_once(70.0)  # cooled + streak 1 (was reset): no move
    assert len(moves) == 1
    rb.evaluate_once(71.0)
    rb.evaluate_once(72.0)  # streak 3 again, past cooldown: moves
    assert len(moves) == 2


def test_rebalancer_pick_target_skips_dead_and_taken_over():
    fleet = Fleet(FleetConfig(backends=_specs(3, standby=True)))
    rb = FleetRebalancer(fleet, burn_probe=lambda spec: {})
    assert rb._pick_target("b1") == "b2"
    fleet.registry.report_failure("b2")
    fleet.registry.report_failure("b2")  # threshold: down
    assert rb._pick_target("b1") == "b3"
    with fleet._lock:
        fleet._takeover["b3"] = "sb"
    assert rb._pick_target("b1") is None


# ---------------------------------------------------------------------------
# CHAOS: SIGKILL a backend mid-stream, standby takeover, exact counts
# ---------------------------------------------------------------------------


def _spawn_backend(tmp_path, name, conf):
    bdir = tmp_path / name
    bdir.mkdir(exist_ok=True)
    conf_path = bdir / "conf.json"
    conf_path.write_text(json.dumps(conf))
    env = dict(
        os.environ,
        XLA_FLAGS="--xla_force_host_platform_device_count=1",
        JAX_PLATFORMS="cpu",
        PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
    )
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "gelly_streaming_tpu.runtime.serve",
            "--listen", "127.0.0.1:0",
            "--config", str(conf_path),
            "--checkpoint-prefix", str(bdir / "ck"),
            "--events-path", str(bdir / "journal.jsonl"),
            "--status-interval", "0",
        ],
        env=env,
        stderr=subprocess.PIPE,
        stdout=subprocess.PIPE,
    )
    return proc, bdir


def _await_port(proc):
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        line = proc.stderr.readline().decode()
        if "listening on" in line:
            return int(line.rsplit(":", 1)[1])
        if not line and proc.poll() is not None:
            break
    raise AssertionError("backend child never reported its port")


def test_chaos_sigkill_backend_standby_takeover_exact_counts(tmp_path):
    """The tentpole's acceptance pin.  2 tenants x 2 checkpointed jobs
    spread over 2 backends + 1 warm standby behind a live router; half
    the stream in, SIGKILL the backend hosting jobs; the resilient
    clients finish through the SAME router address.  Every job must show
    exact non-idempotent counts (``second[-1] == N``), overlap-only
    emissions (no gaps), and a ``job_history`` chain that spans both
    incarnations when the replica + standby journals are replayed."""
    from gelly_streaming_tpu.runtime.router import GLYRouter, RouterConfig

    conf = {
        "jobs": [],
        "tenants": [
            {"tenant": "t1", "token": "tok1"},
            {"tenant": "t2", "token": "tok2"},
        ],
    }
    procs = {}
    for name in ("b1", "b2", "sb"):
        procs[name] = _spawn_backend(tmp_path, name, conf)
    try:
        ports = {name: _await_port(proc) for name, (proc, _d) in procs.items()}
        specs = tuple(
            BackendSpec(
                name,
                "127.0.0.1",
                ports[name],
                journal_path=str(tmp_path / name / "journal.jsonl"),
                checkpoint_prefix=str(tmp_path / name / "ck"),
                standby=(name == "sb"),
            )
            for name in ("b1", "b2", "sb")
        )
        fleet = Fleet(
            FleetConfig(
                backends=specs,
                replica_dir=str(tmp_path / "replica"),
                tenant_tokens={"t1": "tok1", "t2": "tok2"},
                probe_interval_s=0.1,
                probe_timeout_s=1.0,
                fail_threshold=2,
                replicate_interval_s=0.2,
            )
        )
        jobs = [
            ("t1", "tok1", "jx", 51), ("t1", "tok1", "jy", 52),
            ("t2", "tok2", "jx", 53), ("t2", "tok2", "jy", 54),
        ]
        serial = [(i + 1) * W for i in range(N // W)]
        half = N // 2
        datasets = {}
        first = {}
        with GLYRouter(fleet, RouterConfig()) as router:
            clients = {}
            try:
                for tenant, token, job, seed in jobs:
                    rng = np.random.default_rng(seed)
                    src = rng.integers(0, CAP, N).astype(np.int32)
                    dst = rng.integers(0, CAP, N).astype(np.int32)
                    datasets[(tenant, job)] = (src, dst)
                    c = GellyClient("127.0.0.1", router.port, token=token)
                    clients[(tenant, job)] = c
                    c.submit(
                        name=job, query="edges", capacity=CAP,
                        window_edges=W, batch=B, checkpoint=True,
                    )
                    c.push_edges(
                        job, src[:half], dst[:half], batch=B, capacity=CAP,
                        close=False,
                    )
                # fetch EVERY closed window's record so a checkpointed-
                # but-unfetched window can't read as a gap (the final
                # pushed window only closes when the NEXT edge crosses
                # its boundary, so half/W edges close half/W - 1 windows)
                closed = half // W - 1
                for (tenant, job), c in clients.items():
                    got = []
                    deadline = time.monotonic() + 120
                    while len(got) < closed and time.monotonic() < deadline:
                        recs, _state, _eos = c.results(job, timeout_ms=2000)
                        got.extend(int(r[0]) for r in recs)
                    first[(tenant, job)] = got
                    assert got == serial[:closed], (tenant, job, got)
                # durable state shipped BEFORE the kill (deterministic:
                # drive the replication tick directly)
                fleet.replicate_once()

                placement = {
                    (tenant, job): fleet.place(tenant, job).name
                    for tenant, _tok, job, _s in jobs
                }
                victim = max(
                    ("b1", "b2"),
                    key=lambda n: sum(
                        1 for v in placement.values() if v == n
                    ),
                )
                victim_jobs = [
                    k for k, v in placement.items() if v == victim
                ]
                assert victim_jobs, placement
                vproc, _vdir = procs[victim]
                vproc.kill()  # SIGKILL: no drain, no cleanup, no atexit
                vproc.wait(timeout=30)

                # finish every stream through the SAME router address;
                # resilient pushes ride rerouted -> reconnect ->
                # out-of-sync resync onto the standby
                second = {}
                for tenant, token, job, _seed in jobs:
                    c = clients[(tenant, job)]
                    src, dst = datasets[(tenant, job)]
                    c.push_edges_resilient(
                        job, src, dst, batch=B, capacity=CAP, start=half,
                        deadline_s=120.0, backoff_s=0.1,
                    )
                    second[(tenant, job)] = [
                        int(r[0])
                        for r in c.iter_results(job, deadline_s=240)
                    ]
            finally:
                for c in clients.values():
                    c.close()

            # takeover installed: the dead backend's keys now resolve to
            # the standby (and the survivor's keys did NOT move)
            assert fleet.takeover_map() == {victim: "sb"}
            for key, backend in placement.items():
                want = "sb" if backend == victim else backend
                assert fleet.place(*key).name == want

        for key in placement:
            a, b = first[key], second[key]
            # exact non-idempotent count: state folded exactly once
            assert b[-1] == N, (key, b)
            overlap = len(a) + len(b) - len(serial)
            assert overlap >= 0, (key, "kill dropped emissions (a gap)", a, b)
            assert a[: len(a) - overlap] + b == serial, (key, a, b)
            if key in victim_jobs:
                # the standby REPLAYED from its replicated cursor: the
                # at-least-once overlap is exactly the server-directed
                # re-push past the resume point
                assert overlap >= 0

        # the lifecycle chain spans both incarnations: replica journal
        # (dead backend's sync) + the standby's own journal
        evs = events.replay(fleet.replica_journal_path(victim))
        evs += events.replay(str(tmp_path / "sb" / "journal.jsonl"))
        for tenant, job in victim_jobs:
            history = events.job_history(evs, f"{tenant}/{job}")
            assert len(history) >= 2, (tenant, job, history)
            assert history[-1][0] == "PENDING"
    finally:
        for proc, _d in procs.values():
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=30)

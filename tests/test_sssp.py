"""Windowed SSSP (beyond the reference library): scatter-min Bellman–Ford
per pane matches a host Dijkstra, hop counts on valueless streams, sliding
windows compose, negative weights rejected."""

import heapq

import numpy as np
import pytest

from gelly_streaming_tpu.core.config import StreamConfig
from gelly_streaming_tpu.core.stream import EdgeStream
from gelly_streaming_tpu.library.sssp import sssp_windows, windowed_sssp

CFG = StreamConfig(vertex_capacity=32, max_degree=16, batch_size=8)


def _host_dijkstra(edges, source):
    adj = {}
    for s, d, w in edges:
        adj.setdefault(s, []).append((d, w))
    dist = {source: 0.0}
    pq = [(0.0, source)]
    while pq:
        du, u = heapq.heappop(pq)
        if du > dist.get(u, np.inf):
            continue
        for v, w in adj.get(u, []):
            nd = du + w
            if nd < dist.get(v, np.inf):
                dist[v] = nd
                heapq.heappush(pq, (nd, v))
    return dist


def _records(out):
    return {int(v): float(d) for v, d in out.collect()}


def test_weighted_matches_host_dijkstra():
    edges = [(0, 1, 4.0), (0, 2, 1.0), (2, 1, 2.0), (1, 3, 1.0), (2, 3, 5.0)]
    stream = EdgeStream.from_collection(edges, CFG)
    got = _records(windowed_sssp(stream, 0, 1000))
    want = _host_dijkstra(edges, 0)
    assert got == pytest.approx(want)  # 1 via 2 (3.0), 3 via 2->1 (4.0)
    assert got[1] == 3.0 and got[3] == 4.0


def test_valueless_stream_counts_hops():
    edges = [(0, 1), (1, 2), (2, 3), (0, 3)]
    stream = EdgeStream.from_collection(edges, CFG)
    got = _records(windowed_sssp(stream, 0, 1000))
    assert got == {0: 0.0, 1: 1.0, 2: 2.0, 3: 1.0}


def test_unreached_vertices_emit_nothing():
    edges = [(0, 1, 1.0), (5, 6, 1.0)]
    stream = EdgeStream.from_collection(edges, CFG)
    got = _records(windowed_sssp(stream, 0, 1000))
    assert set(got) == {0, 1}


def test_sliding_windows_compose():
    timed = [
        (0, 1, 1.0, 100),
        (1, 2, 1.0, 1100),
        (2, 3, 1.0, 2100),
    ]
    stream = EdgeStream.from_collection(timed, CFG, batch_size=1, with_time=True)
    wins = list(sssp_windows(stream, 0, 2000, slide_ms=1000))
    # windows: 0:{e0} 1:{e0,e1} 2:{e1,e2} 3:{e2}; 0 reaches into w0/w1 only
    dists = [dict(zip(v.tolist(), d.tolist())) for v, d in wins]
    assert dists[0] == {0: 0.0, 1: 1.0}
    assert dists[1] == {0: 0.0, 1: 1.0, 2: 2.0}
    assert dists[2] == {0: 0.0}  # source isolated from the e1,e2 chain
    assert dists[3] == {0: 0.0}


def test_negative_weights_rejected():
    edges = [(0, 1, -1.0)]
    stream = EdgeStream.from_collection(edges, CFG)
    with pytest.raises(ValueError, match="non-negative"):
        list(sssp_windows(stream, 0, 1000))


@pytest.mark.parametrize("seed", [0, 1])
def test_random_graph_matches_host(seed):
    rng = np.random.default_rng(seed)
    edges = [
        (
            int(rng.integers(0, 20)),
            int(rng.integers(0, 20)),
            float(rng.integers(1, 10)),
        )
        for _ in range(50)
    ]
    stream = EdgeStream.from_collection(edges, CFG)
    got = _records(windowed_sssp(stream, 0, 1000))
    want = _host_dijkstra(edges, 0)
    assert got == pytest.approx(want)


def test_out_of_range_source_rejected():
    stream = EdgeStream.from_collection([(0, 1)], CFG)
    with pytest.raises(ValueError, match="outside"):
        list(sssp_windows(stream, 40, 1000))


def test_multi_leaf_values_rejected():
    edges = [(0, 1, 2.0)]
    stream = EdgeStream.from_collection(edges, CFG).map_edges(
        lambda s, d, v: {"a": v, "b": v}
    )
    with pytest.raises(ValueError, match="single scalar"):
        list(sssp_windows(stream, 0, 1000))


def test_bounded_hop_semantics():
    # chain 0->1->2->3; max_iters=2 reaches exactly 2 hops
    edges = [(0, 1, 1.0), (1, 2, 1.0), (2, 3, 1.0)]
    stream = EdgeStream.from_collection(edges, CFG)
    got = _records(windowed_sssp(stream, 0, 1000, max_iters=2))
    assert got == {0: 0.0, 1: 1.0, 2: 2.0}  # vertex 3 beyond the bound

"""Whole-program example tests (the ITCase tier, SURVEY.md §4.3): each example
main() runs on temp input/output files; WindowTriangles and DegreeDistribution
assert the reference ITCase goldens."""

import os

import pytest

from gelly_streaming_tpu.examples import (
    bipartiteness_check,
    broadcast_triangle_count,
    centralized_weighted_matching,
    connected_components,
    degree_distribution,
    exact_triangle_count,
    incidence_sampling_triangle_count,
    iterative_connected_components,
    spanner,
    window_triangles,
)

TRIANGLES_DATA = (
    "1 2 100\n1 3 150\n3 2 200\n2 4 250\n3 4 300\n3 5 350\n4 5 400\n"
    "4 6 450\n6 5 500\n5 7 550\n6 7 600\n8 6 650\n7 8 700\n7 9 750\n"
    "8 9 800\n10 8 850\n9 10 900\n9 11 950\n10 11 1000\n"
)

DEGREES_DATA = "1 2 +\n2 3 +\n1 4 +\n2 3 -\n3 4 +\n1 2 -\n"


def _run(module, tmp_path, data, extra_args=()):
    inp = os.path.join(str(tmp_path), "in.txt")
    out = os.path.join(str(tmp_path), "out.txt")
    with open(inp, "w") as f:
        f.write(data)
    module.main([inp, out, *extra_args])
    with open(out) as f:
        return [l.rstrip("\n") for l in f if l.strip()]


def test_window_triangles_itcase(tmp_path):
    # WindowTrianglesITCase golden: (2,399) (3,799) (2,1199)
    lines = _run(window_triangles, tmp_path, TRIANGLES_DATA, ["400"])
    assert sorted(lines) == sorted(["2,399", "3,799", "2,1199"])


def test_degree_distribution_itcase(tmp_path):
    # DegreeDistributionITCase golden (ExamplesTestData.DEGREES_RESULT)
    lines = _run(degree_distribution, tmp_path, DEGREES_DATA)
    expected = [
        "1,1", "1,2",
        "2,1", "1,1", "1,2",
        "2,2", "1,1", "1,2",
        "1,3", "2,1", "1,2",
        "1,3", "2,2", "1,2",
        "1,3", "2,1", "1,2",
    ]
    assert lines == expected


def test_connected_components_example(tmp_path):
    lines = _run(
        connected_components, tmp_path, "1 2\n2 3\n5 6\n", ["1000"]
    )
    assert lines == ["1,1 2 3", "5,5 6"]


def test_connected_components_tree_example(tmp_path):
    lines = _run(
        connected_components, tmp_path, "1 2\n2 3\n5 6\n", ["1000", "--tree"]
    )
    assert lines == ["1,1 2 3", "5,5 6"]


def test_bipartiteness_example(tmp_path):
    lines = _run(bipartiteness_check, tmp_path, "1 2\n2 3\n3 1\n")
    assert lines == ["(false,{})"]


def test_spanner_example(tmp_path):
    lines = _run(spanner, tmp_path, "1 2\n2 3\n1 3\n", ["1000", "2"])
    assert lines == ["1,2", "2,3"]


def test_exact_triangle_count_example(tmp_path):
    lines = _run(exact_triangle_count, tmp_path, "1 2\n2 3\n1 3\n")
    assert lines[-1] == "-1,1"  # global count reaches 1


def test_iterative_cc_example(tmp_path):
    lines = _run(iterative_connected_components, tmp_path, "1 2\n2 3\n")
    assert "3,1" in lines


def test_sampling_examples_run(tmp_path):
    data = "".join(f"{i} {j}\n" for i in range(6) for j in range(i + 1, 6))
    lines = _run(broadcast_triangle_count, tmp_path, data, ["64"])
    assert len(lines) >= 1
    lines = _run(incidence_sampling_triangle_count, tmp_path, data, ["64"])
    assert len(lines) >= 1


def test_matching_example(tmp_path):
    lines = _run(centralized_weighted_matching, tmp_path, "1 2 10\n3 4 20\n")
    assert lines == ["ADD,1,2,10.0", "ADD,3,4,20.0"]


def test_example_usage_error():
    with pytest.raises(SystemExit):
        exact_triangle_count.main(["a", "b", "c", "d", "e"])


def test_pagerank_example(tmp_path):
    from gelly_streaming_tpu.examples import pagerank as ex

    inp = tmp_path / "edges.txt"
    inp.write_text("1 2\n2 3\n3 1\n3 4\n4 1\n5 1\n")
    out = tmp_path / "out.csv"
    ex.main([str(inp), str(out), "1000"])
    lines = out.read_text().strip().split("\n")
    recs = {int(l.split(",")[0]): float(l.split(",")[1]) for l in lines}
    assert len(recs) == 5
    assert abs(sum(recs.values()) - 1.0) < 1e-4
    assert recs[1] == max(recs.values())


def test_sssp_example(tmp_path):
    from gelly_streaming_tpu.examples import sssp as ex

    inp = tmp_path / "edges.txt"
    inp.write_text("0 1 4\n0 2 1\n2 1 2\n1 3 1\n2 3 5\n")
    out = tmp_path / "out.csv"
    ex.main(["--source=0", str(inp), str(out), "1000"])
    recs = {
        int(l.split(",")[0]): float(l.split(",")[1])
        for l in out.read_text().strip().split("\n")
    }
    assert recs == {0: 0.0, 1: 3.0, 2: 1.0, 3: 4.0}

"""Union-find kernel tests: fixed-point equivalence with a sequential reference.

Mirrors the reference's DisjointSetTest (util/DisjointSetTest.java) and adds
randomized equivalence checks of the batched kernel against a plain sequential
union-find.
"""

import jax.numpy as jnp
import numpy as np

from gelly_streaming_tpu.ops import unionfind as uf
from gelly_streaming_tpu.summaries.disjoint_set import DisjointSet


class _SeqUF:
    """Plain sequential union-find used as ground truth."""

    def __init__(self, n):
        self.p = list(range(n))

    def find(self, v):
        while self.p[v] != v:
            self.p[v] = self.p[self.p[v]]
            v = self.p[v]
        return v

    def union(self, a, b):
        ra, rb = self.find(a), self.find(b)
        if ra != rb:
            self.p[max(ra, rb)] = min(ra, rb)


def _labels(parent):
    p = np.asarray(uf.compress(jnp.asarray(parent)))
    return p


def test_union_edges_simple_chain():
    parent = uf.init_parent(8)
    src = jnp.array([0, 1, 2], jnp.int32)
    dst = jnp.array([1, 2, 3], jnp.int32)
    p = _labels(uf.union_edges(parent, src, dst))
    assert p[0] == p[1] == p[2] == p[3] == 0
    assert p[4] == 4 and p[7] == 7


def test_union_edges_masked_rows_do_nothing():
    parent = uf.init_parent(8)
    src = jnp.array([0, 5], jnp.int32)
    dst = jnp.array([1, 6], jnp.int32)
    mask = jnp.array([True, False])
    p = _labels(uf.union_edges(parent, src, dst, mask))
    assert p[0] == p[1] == 0
    assert p[5] == 5 and p[6] == 6


def test_union_edges_random_matches_sequential():
    rng = np.random.default_rng(42)
    n = 128
    for trial in range(5):
        m = int(rng.integers(1, 200))
        src = rng.integers(0, n, size=m).astype(np.int32)
        dst = rng.integers(0, n, size=m).astype(np.int32)
        seq = _SeqUF(n)
        for a, b in zip(src, dst):
            seq.union(int(a), int(b))
        want = np.array([seq.find(v) for v in range(n)])
        got = _labels(uf.union_edges(uf.init_parent(n), jnp.asarray(src), jnp.asarray(dst)))
        np.testing.assert_array_equal(got, want)


def test_incremental_batches_match_one_shot():
    rng = np.random.default_rng(7)
    n = 64
    src = rng.integers(0, n, size=60).astype(np.int32)
    dst = rng.integers(0, n, size=60).astype(np.int32)
    p_inc = uf.init_parent(n)
    for i in range(0, 60, 10):
        p_inc = uf.union_edges(p_inc, jnp.asarray(src[i : i + 10]), jnp.asarray(dst[i : i + 10]))
    p_one = uf.union_edges(uf.init_parent(n), jnp.asarray(src), jnp.asarray(dst))
    np.testing.assert_array_equal(_labels(p_inc), _labels(p_one))


def test_merge_parents():
    n = 32
    pa = uf.union_edges(uf.init_parent(n), jnp.array([1], jnp.int32), jnp.array([2], jnp.int32))
    pb = uf.union_edges(uf.init_parent(n), jnp.array([2], jnp.int32), jnp.array([3], jnp.int32))
    merged = _labels(uf.merge_parents(pa, pb))
    assert merged[1] == merged[2] == merged[3] == 1


# ---- DisjointSet API parity (mirrors util/DisjointSetTest.java) -------------


def _setup_ds():
    ds = DisjointSet(capacity=128)
    for i in range(8):
        ds.union(i, i + 2)  # DisjointSetTest.java:36-41
    return ds


def test_get_matches_size():
    ds = _setup_ds()
    assert len(ds.get_matches()) == 10  # DisjointSetTest.java:43-46


def test_find_parity():
    ds = _setup_ds()
    root1 = ds.find(0)
    root2 = ds.find(1)
    assert root1 != root2
    for i in range(10):
        assert ds.find(i) == (root1 if i % 2 == 0 else root2)


def test_merge_parity():
    ds = _setup_ds()
    ds2 = DisjointSet(capacity=128)
    for i in range(8):
        ds2.union(i, i + 100)
    ds2.merge(ds)
    assert len(ds2.get_matches()) == 18
    roots = {ds2.find(v) for v in ds2.get_matches()}
    assert len(roots) == 2  # DisjointSetTest.java:58-77


def test_tostring_format():
    ds = DisjointSet(capacity=16)
    for a, b in [(1, 2), (1, 3), (2, 3), (1, 5), (6, 7), (8, 9)]:
        ds.union(a, b)
    assert str(ds) == "{1=[1, 2, 3, 5], 6=[6, 7], 8=[8, 9]}"


# ---- parity (signed) union-find ---------------------------------------------


def test_parity_bipartite_path():
    c = 16
    p2 = uf.init_parity_parent(c)
    src = jnp.array([1, 2, 3], jnp.int32)
    dst = jnp.array([2, 3, 4], jnp.int32)
    p2 = uf.parity_union_edges(p2, src, dst)
    seen = jnp.zeros((c,), bool).at[jnp.array([1, 2, 3, 4])].set(True)
    assert bool(uf.is_bipartite(p2, seen))


def test_parity_odd_cycle_fails():
    c = 16
    p2 = uf.init_parity_parent(c)
    src = jnp.array([1, 2, 3], jnp.int32)
    dst = jnp.array([2, 3, 1], jnp.int32)
    p2 = uf.parity_union_edges(p2, src, dst)
    seen = jnp.zeros((c,), bool).at[jnp.array([1, 2, 3])].set(True)
    assert not bool(uf.is_bipartite(p2, seen))
    conflicts = np.asarray(uf.parity_conflicts(p2, seen))
    assert conflicts[[1, 2, 3]].all()


def test_parity_even_cycle_ok():
    c = 16
    p2 = uf.init_parity_parent(c)
    src = jnp.array([1, 2, 3, 4], jnp.int32)
    dst = jnp.array([2, 3, 4, 1], jnp.int32)
    p2 = uf.parity_union_edges(p2, src, dst)
    seen = jnp.zeros((c,), bool).at[jnp.array([1, 2, 3, 4])].set(True)
    assert bool(uf.is_bipartite(p2, seen))

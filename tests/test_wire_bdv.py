"""BDV (binned delta/varint) wire format: round-trip fuzz vs the oracle.

The compressed ingest path (ISSUE 6) ships (dst, src)-sorted batches as
interleaved varint streams with a vectorized device decode
(ops/wire_decode.py).  These tests pin:

  * encode -> host decode round trip == numpy lexsort oracle, across
    uniform / skewed / clustered / empty / single / max-degree batches;
  * device decode == host decode bit-exactly (one implementation contract);
  * native encoder output == numpy fallback bytes;
  * padding tolerance (trailing zeros decode as dropped groups);
  * valued (zigzag) round trip;
  * bucket sizing (bounded shape set, bounded padding overhead).
"""

import numpy as np
import pytest

from gelly_streaming_tpu.io import wire

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402


def _sorted_oracle(src, dst, val=None):
    order = np.lexsort((src, dst))
    if val is None:
        return src[order], dst[order]
    return src[order], dst[order], val[order]


def _gen(kind, n, cap, rng):
    if kind == "uniform":
        return (
            rng.integers(0, cap, n).astype(np.int32),
            rng.integers(0, cap, n).astype(np.int32),
        )
    if kind == "skewed":
        # hub-heavy destinations + clustered sources (the propagation-
        # blocking target workload)
        d = (cap * rng.random(n) ** 4).astype(np.int64).astype(np.int32) % cap
        s = (cap * rng.random(n) ** 2).astype(np.int64).astype(np.int32) % cap
        return s, d
    if kind == "max-degree":
        # every edge lands on one destination: the worst-case single bin
        return (
            np.sort(rng.integers(0, cap, n)).astype(np.int32),
            np.full(n, cap - 1, np.int32),
        )
    if kind == "clustered":
        block = max(cap // 64, 1)
        base = rng.integers(0, max(cap - block, 1), n).astype(np.int64)
        return (
            (base + rng.integers(0, block, n)).astype(np.int32) % cap,
            (base + rng.integers(0, block, n)).astype(np.int32) % cap,
        )
    raise AssertionError(kind)


@pytest.mark.parametrize("kind", ["uniform", "skewed", "max-degree", "clustered"])
@pytest.mark.parametrize("cap", [1 << 10, 1 << 20, 1 << 28])
def test_roundtrip_host_and_device(kind, cap):
    rng = np.random.default_rng(hash((kind, cap)) % (1 << 32))
    n = 2048
    src, dst = _gen(kind, n, cap, rng)
    buf = wire.pack_edges_bdv(src, dst, cap)
    # bucketed size: a {4..7} * 2^k byte count
    nb = buf.nbytes
    k = max(nb.bit_length() - 3, 0)
    assert nb % (1 << k) == 0 and nb >> k in (4, 5, 6, 7, 8), nb
    s_h, d_h = wire.unpack_edges_bdv_host(buf, n)
    s_o, d_o = _sorted_oracle(src, dst)
    assert np.array_equal(s_h, s_o)
    assert np.array_equal(d_h, d_o)
    s_d, d_d = wire.unpack_edges(jnp.asarray(buf), n, (wire.BDV, cap))
    assert np.array_equal(np.asarray(s_d), s_h)
    assert np.array_equal(np.asarray(d_d), d_h)


def test_roundtrip_fuzz_many_seeds():
    for seed in range(20):
        rng = np.random.default_rng(seed)
        cap = int(rng.integers(2, 1 << 20))
        n = int(rng.integers(1, 600))
        src = rng.integers(0, cap, n).astype(np.int32)
        dst = rng.integers(0, cap, n).astype(np.int32)
        buf = wire.pack_edges_bdv(src, dst, cap)
        s_h, d_h = wire.unpack_edges_bdv_host(buf, n)
        s_o, d_o = _sorted_oracle(src, dst)
        assert np.array_equal(s_h, s_o) and np.array_equal(d_h, d_o), seed


def test_empty_and_single_edge():
    buf = wire.pack_edges_bdv(
        np.empty(0, np.int32), np.empty(0, np.int32), 16
    )
    s, d = wire.unpack_edges_bdv_host(buf, 0)
    assert len(s) == 0 and len(d) == 0
    buf = wire.pack_edges_bdv(
        np.array([3], np.int32), np.array([9], np.int32), 16
    )
    s, d = wire.unpack_edges_bdv_host(buf, 1)
    assert s.tolist() == [3] and d.tolist() == [9]


def test_valued_zigzag_roundtrip():
    rng = np.random.default_rng(7)
    n, cap = 1500, 1 << 16
    src = rng.integers(0, cap, n).astype(np.int32)
    dst = rng.integers(0, cap, n).astype(np.int32)
    val = rng.integers(-(1 << 27), 1 << 27, n).astype(np.int32)
    buf = wire.pack_edges_bdv(src, dst, cap, val_i32=val)
    s_h, d_h, v_h = wire.unpack_edges_bdv_host(buf, n, valued=True)
    s_o, d_o, v_o = _sorted_oracle(src, dst, val)
    assert np.array_equal(s_h, s_o)
    assert np.array_equal(d_h, d_o)
    assert np.array_equal(v_h, v_o)
    from gelly_streaming_tpu.ops import wire_decode

    s_d, d_d, v_d = wire_decode.decode_bdv(jnp.asarray(buf), n, valued=True)
    assert np.array_equal(np.asarray(s_d), s_h)
    assert np.array_equal(np.asarray(d_d), d_h)
    assert np.array_equal(np.asarray(v_d), v_h)


def test_native_and_numpy_encoders_agree():
    from gelly_streaming_tpu.utils.native import load_ingest_lib

    lib = load_ingest_lib()
    if lib is None or not hasattr(lib, "encode_edges_bdv"):
        pytest.skip("native library unavailable")
    rng = np.random.default_rng(3)
    n, cap = 3000, 1 << 20
    src = rng.integers(0, cap, n).astype(np.int32)
    dst = rng.integers(0, cap, n).astype(np.int32)
    s_s, d_s, _ = wire._sort_edges_bdv(src, dst, cap)
    numpy_payload = wire._encode_bdv_np(s_s, d_s)
    buf = wire.pack_edges_bdv(src, dst, cap)  # native encoder path
    assert np.array_equal(buf[: len(numpy_payload)], numpy_payload)
    assert not buf[len(numpy_payload) :].any()


def test_native_sort_matches_lexsort():
    from gelly_streaming_tpu.utils.native import load_ingest_lib

    lib = load_ingest_lib()
    if lib is None or not hasattr(lib, "sort_edges_dst_src"):
        pytest.skip("native library unavailable")
    rng = np.random.default_rng(4)
    for cap in (2, 1 << 8, 1 << 20):
        n = 4000
        src = rng.integers(0, cap, n).astype(np.int32)
        dst = rng.integers(0, cap, n).astype(np.int32)
        s, d, _ = wire._sort_edges_bdv(src, dst, cap)
        order = np.lexsort((src, dst))
        assert np.array_equal(s, src[order])
        assert np.array_equal(d, dst[order])


def test_padding_tolerance():
    """Trailing zero bytes (bucket padding, superbatch group max-padding)
    decode as dropped empty varint groups — same edges out."""
    rng = np.random.default_rng(5)
    n, cap = 513, 1 << 14
    src = rng.integers(0, cap, n).astype(np.int32)
    dst = rng.integers(0, cap, n).astype(np.int32)
    buf = wire.pack_edges_bdv(src, dst, cap)
    padded = np.zeros(buf.nbytes + 4096, np.uint8)
    padded[: buf.nbytes] = buf
    s1, d1 = wire.unpack_edges_bdv_host(buf, n)
    s2, d2 = wire.unpack_edges_bdv_host(padded, n)
    assert np.array_equal(s1, s2) and np.array_equal(d1, d2)
    s3, d3 = wire.unpack_edges(jnp.asarray(padded), n, (wire.BDV, cap))
    assert np.array_equal(np.asarray(s3), s1)
    assert np.array_equal(np.asarray(d3), d1)


def test_capacity_bound_refused():
    with pytest.raises(ValueError, match="BDV"):
        wire.pack_edges_bdv(
            np.array([0], np.int32), np.array([0], np.int32), 1 << 29
        )


def test_varint_boundaries():
    vals = np.array(
        [0, 1, 127, 128, 16383, 16384, (1 << 21) - 1, 1 << 21, (1 << 28) - 1],
        np.uint64,
    )
    enc = wire._varint_encode_np(vals)
    dec = wire._varint_decode_np(enc, len(vals))
    assert np.array_equal(dec, vals.astype(np.int64))
    from gelly_streaming_tpu.ops import wire_decode

    dev = wire_decode.decode_varints(jnp.asarray(enc), len(vals))
    assert np.array_equal(np.asarray(dev).astype(np.int64), vals.astype(np.int64))


def test_wire_nbytes_and_pack_edges_dispatch():
    rng = np.random.default_rng(6)
    n, cap = 256, 1 << 12
    src = rng.integers(0, cap, n).astype(np.int32)
    dst = rng.integers(0, cap, n).astype(np.int32)
    width = (wire.BDV, cap)
    buf = wire.pack_edges(src, dst, width)
    assert buf.nbytes <= wire.wire_nbytes(n, width)
    s, d = wire.unpack_edges_host(buf, n, width)
    s_o, d_o = _sorted_oracle(src, dst)
    assert np.array_equal(s, s_o) and np.array_equal(d, d_o)
    # fixed-slice arena packing has no contract for variable-size buffers
    with pytest.raises(ValueError, match="variable-size"):
        wire.pack_edges_into(src, dst, width, np.zeros(64, np.uint8))


def test_from_wire_bdv_replay():
    """BDV replay buffers stream through EdgeStream.from_wire: the fast
    path consumes them transfer-only and the host decode serves every
    other consumer; out-of-range ids are smoke-checked up front."""
    from gelly_streaming_tpu.core.config import StreamConfig
    from gelly_streaming_tpu.core.stream import EdgeStream
    from gelly_streaming_tpu.library.connected_components import (
        ConnectedComponents,
    )

    rng = np.random.default_rng(8)
    cap = 1 << 12
    n, batch = 2048, 512
    src = rng.integers(0, cap, n).astype(np.int32)
    dst = rng.integers(0, cap, n).astype(np.int32)
    cfg = StreamConfig(vertex_capacity=cap, batch_size=batch)
    width = (wire.BDV, cap)
    bufs, tail = wire.pack_stream(src, dst, batch, width)
    stream = EdgeStream.from_wire(bufs, batch, width, cfg, tail=tail)
    got = list(ConnectedComponents().run(stream))
    ref = list(
        ConnectedComponents().run(EdgeStream.from_arrays(src, dst, cfg))
    )
    assert len(got) == len(ref) == 1
    assert np.array_equal(np.asarray(got[0][0].parent), np.asarray(ref[0][0].parent))
    assert np.array_equal(np.asarray(got[0][0].seen), np.asarray(ref[0][0].seen))
    # capacity beyond the config is refused outright
    with pytest.raises(ValueError, match="BDV width capacity"):
        EdgeStream.from_wire([], batch, (wire.BDV, 1 << 20), cfg)
    # ids beyond vertex_capacity are smoke-checked on the first buffer
    small = StreamConfig(vertex_capacity=8, batch_size=4)
    bad = wire.pack_edges_bdv(
        np.array([9] * 4, np.int32), np.array([1] * 4, np.int32), 1 << 8
    )
    with pytest.raises(ValueError, match="decodes vertex ids"):
        EdgeStream.from_wire([bad], 4, (wire.BDV, 8), small)


def test_worst_case_payload_clamps_at_wire_nbytes():
    """A near-worst-case batch (huge dst deltas, alternating-sign src
    deltas) must never bucket-pad PAST the documented ``wire_nbytes``
    ceiling: from_wire and the mesh replay arenas size buffers by it."""
    n, cap = 16, 1 << 28
    dst = (np.arange(n, dtype=np.int64) * (1 << 24)).astype(np.int32)
    src = np.where(np.arange(n) % 2, 1 << 27, 0).astype(np.int32)
    width = (wire.BDV, cap)
    buf = wire.pack_edges_bdv(src, dst, cap)
    assert buf.nbytes <= wire.wire_nbytes(n, width), buf.nbytes
    s, d = wire.unpack_edges_bdv_host(buf, n)
    s_o, d_o = _sorted_oracle(src, dst)
    assert np.array_equal(s, s_o) and np.array_equal(d, d_o)
    # and the producer's own buffer passes from_wire's validation
    from gelly_streaming_tpu.core.config import StreamConfig
    from gelly_streaming_tpu.core.stream import EdgeStream

    cfg = StreamConfig(vertex_capacity=cap, batch_size=n)
    stream = EdgeStream.from_wire([buf], n, width, cfg)
    assert stream is not None


def test_truncated_buffer_refused():
    """The host decode is the validation front door: a buffer shorter than
    its control block (or the payload the control block declares) raises a
    clean ValueError instead of an IndexError — including through
    from_wire's smoke guard."""
    with pytest.raises(ValueError, match="truncated"):
        wire.unpack_edges_bdv_host(np.zeros(8, np.uint8), 1024)
    # control block present but declaring more payload than the buffer holds
    with pytest.raises(ValueError, match="truncated"):
        wire.unpack_edges_bdv_host(np.full(3, 0xFF, np.uint8), 4)
    from gelly_streaming_tpu.core.config import StreamConfig
    from gelly_streaming_tpu.core.stream import EdgeStream

    cfg = StreamConfig(vertex_capacity=1 << 20, batch_size=1024)
    with pytest.raises(ValueError, match="truncated"):
        EdgeStream.from_wire(
            [np.zeros(8, np.uint8)], 1024, (wire.BDV, 1 << 20), cfg
        )


def test_negative_decoded_ids_refused():
    """BDV is the one wire format whose signed zigzag src deltas can decode
    NEGATIVE ids; a negative scatter index wraps to the end of the summary
    arrays, so from_wire's smoke guard must refuse both range ends."""
    from gelly_streaming_tpu.core.config import StreamConfig
    from gelly_streaming_tpu.core.stream import EdgeStream

    # stream = [dst_delta=0, zigzag(src_delta=-1)=1] -> decodes (src=-1, dst=0)
    payload = wire._varint_encode_np(np.array([0, 1], np.uint64))
    buf = np.zeros(wire.bdv_bucket_nbytes(len(payload)), np.uint8)
    buf[: len(payload)] = payload
    s, d = wire.unpack_edges_bdv_host(buf, 1)
    assert s.tolist() == [-1] and d.tolist() == [0]
    cfg = StreamConfig(vertex_capacity=1 << 12, batch_size=1)
    with pytest.raises(ValueError, match="outside"):
        EdgeStream.from_wire([buf], 1, (wire.BDV, 1 << 12), cfg)

"""graftcheck (tier-1): the static-analysis suite holds the shipped tree to
zero unsuppressed findings, and each pass provably catches its seeded
defect.

Three layers, mirroring the framework's contract:

* the PACKAGE GATE — running every pass over core/io/library/parallel/utils
  (plus the shipped baseline) must come back clean, so a new raw jit, an
  unguarded counter, or a use-after-donate fails tier-1 at the line that
  introduced it;
* the FIXTURE CORPUS — one good + one seeded-bad snippet per pass under
  tests/analysis_corpus/, asserting exact finding codes (a checker that
  finds nothing anywhere must fail here, not pass vacuously);
* the FRAMEWORK — suppression grammar, baseline round-trip (grandfathered
  counts, new-finding overflow), finding format, CLI driver exit codes.
"""

import json
import os
import subprocess
import sys
import textwrap

import pytest

from gelly_streaming_tpu import analysis

CORPUS = os.path.join(os.path.dirname(__file__), "analysis_corpus")
REPO_ROOT = os.path.dirname(analysis.package_root())


def _analyze(path):
    return analysis.analyze_file(os.path.join(CORPUS, path))


def _codes(findings):
    return sorted(f.code for f in findings)


def _src(snippet, filename="probe.py"):
    return analysis.analyze_source(textwrap.dedent(snippet), filename)


# ---------------------------------------------------------------------------
# package gate


def _package_paths():
    root = analysis.package_root()
    return [
        os.path.join(root, d)
        for d in ("core", "io", "library", "ops", "parallel", "runtime", "utils")
    ]


@pytest.mark.timeout_cap(120)
def test_package_tree_is_clean():
    findings = analysis.analyze_paths(_package_paths(), root=REPO_ROOT)
    baseline = analysis.load_baseline(analysis.default_baseline_path())
    new, _old = analysis.apply_baseline(findings, baseline)
    assert new == [], "\n".join(f.format() for f in new)


def test_baseline_is_small_and_rawjit_only():
    """The baseline exists to grandfather the module-scope @jax.jit
    decorators, not to absorb new debt: pin its size and composition so
    quietly re-baselining a regression shows up as a diff here."""
    baseline = analysis.load_baseline(analysis.default_baseline_path())
    assert sum(baseline.values()) <= 6
    assert all(code == "RAWJIT" for (_p, code, _m) in baseline)


@pytest.mark.timeout_cap(120)
def test_cli_package_scan_exits_zero():
    proc = subprocess.run(
        [
            sys.executable,
            "-m",
            "gelly_streaming_tpu.analysis",
            "--paths",
            "core",
            "io",
            "library",
            "ops",
            "parallel",
            "runtime",
            "utils",
        ],
        capture_output=True,
        text=True,
        cwd=REPO_ROOT,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "0 finding(s)" in proc.stderr


def test_cli_list_passes_names_all_five():
    proc = subprocess.run(
        [
            sys.executable,
            "-m",
            "gelly_streaming_tpu.analysis",
            "--list-passes",
        ],
        capture_output=True,
        text=True,
        cwd=REPO_ROOT,
    )
    assert proc.returncode == 0
    for name in (
        "hot-loop",
        "jit-discipline",
        "donation-safety",
        "lock-discipline",
        "trace-safety",
        "collective-discipline",
    ):
        assert name in proc.stdout


# ---------------------------------------------------------------------------
# fixture corpus: each pass catches exactly its seeded defect


def test_corpus_rawjit():
    assert _codes(_analyze("bad_rawjit.py")) == ["RAWJIT", "RAWJIT"]
    assert _analyze("good_rawjit.py") == []


def test_corpus_donate():
    findings = _analyze("bad_donate.py")
    assert _codes(findings) == ["DONATE", "DONATE"]
    # one per seeded bug: the donated-carry read and the arena write
    assert "state" in findings[0].message and "src" in findings[1].message
    assert _analyze("good_donate.py") == []


def test_corpus_unguarded():
    findings = _analyze("bad_unguarded.py")
    assert _codes(findings) == ["UNGUARDED", "UNGUARDED"]
    assert "_COUNT" in findings[0].message
    assert "self.total" in findings[1].message
    assert _analyze("good_unguarded.py") == []


def test_corpus_jobstate():
    """The runtime fixtures (ISSUE 5): job lifecycle state is
    '# guarded-by:' the manager lock; a transition outside it is exactly
    the lost-transition race the JobManager's discipline forbids."""
    findings = _analyze("bad_jobstate.py")
    assert _codes(findings) == ["UNGUARDED", "UNGUARDED"]
    assert all("self._state" in f.message for f in findings)
    assert all("_lock" in f.message for f in findings)
    assert _analyze("good_jobstate.py") == []


def test_nested_with_collects_inner_lock():
    """A `with self._lock:` nested directly inside another with-block must
    still collect its lock for the body (the serving plane's admission
    section hit this: check() used to recurse INTO the inner With without
    dispatching it, losing the lock and false-positive-flagging guarded
    registry writes)."""
    findings = _src(
        """
        import threading


        class S:
            def __init__(self):
                self._admission = threading.Lock()
                self._lock = threading.Lock()
                self._reg = {}  # guarded-by: _lock

            def f(self, key, value):
                with self._admission:
                    with self._lock:
                        self._reg[key] = value
        """
    )
    assert findings == []


def test_corpus_server():
    """The serving-plane fixtures (ISSUE 8): the connection registry every
    accept/teardown/shutdown path touches is '# guarded-by:' the server
    lock; an unlocked check-then-add races two accepts past the
    connection cap."""
    findings = _analyze("bad_server.py")
    assert _codes(findings) == ["UNGUARDED", "UNGUARDED"]
    assert all("self._conns" in f.message for f in findings)
    assert all("_lock" in f.message for f in findings)
    assert _analyze("good_server.py") == []


def test_corpus_traceif():
    assert _codes(_analyze("bad_traceif.py")) == [
        "TRACECAST",
        "TRACECAST",
        "TRACEIF",
    ]
    assert _analyze("good_traceif.py") == []


def test_corpus_hotsync():
    assert _codes(_analyze("bad_hotsync.py")) == ["HOTSYNC"]
    # the good twin hangs '# hot-loop-ok' on the CLOSING line of a
    # multi-line call — the satellite regression for hot_loop_lint's
    # original single-line marker scan
    assert _analyze("good_hotsync.py") == []


def test_corpus_wirebin():
    """The binned-ingest fixtures (ISSUE 6): the compressed decode+fold
    dispatch is a '# hot-loop' region, and the wire-counter registry the
    pack threads bump is '# guarded-by:' its lock."""
    findings = _analyze("bad_wirebin.py")
    assert _codes(findings) == ["HOTSYNC", "UNGUARDED"]
    assert any("_WIRE_BYTES" in f.message for f in findings)
    assert _analyze("good_wirebin.py") == []


def test_corpus_tracing():
    """The observability fixtures (ISSUE 9): the flight recorder's span
    ring is '# guarded-by:' its lock (drain threads of many jobs write
    while status/server threads read), and the traced dispatch loop stays
    a '# hot-loop' region — span marks are clock reads, never host syncs."""
    findings = _analyze("bad_tracing.py")
    assert _codes(findings) == [
        "HOTSYNC",
        "UNGUARDED",
        "UNGUARDED",
        "UNGUARDED",
    ]
    assert any("self._ring" in f.message for f in findings)
    assert any("self._next" in f.message for f in findings)
    assert _analyze("good_tracing.py") == []


def test_corpus_events():
    """The health-plane fixtures (ISSUE 10): the event journal's
    ring/cursor/file mirror are '# guarded-by:' its lock (scheduler,
    connection, and monitor threads emit while the events verb tails),
    and the SLO monitor's evaluation sweep is a '# hot-loop' region —
    gauge reads and burn math only, never a device sync."""
    findings = _analyze("bad_events.py")
    assert _codes(findings) == [
        "HOTSYNC",
        "UNGUARDED",
        "UNGUARDED",
        "UNGUARDED",
    ]
    assert any("self._ring" in f.message for f in findings)
    assert any("self._seq" in f.message for f in findings)
    assert any("self._file" in f.message for f in findings)
    assert _analyze("good_events.py") == []


def test_corpus_autoscale():
    """The elastic-control-plane fixtures (ISSUE 11): the autoscaler's
    handle/streak decision registry is '# guarded-by:' its lock
    (connection threads register while the policy thread sweeps), and the
    decision sweep is a '# hot-loop' region — alert/gauge reads and
    streak math only, never a device sync that would stall a pending
    rescale behind one fold."""
    findings = _analyze("bad_autoscale.py")
    assert _codes(findings) == [
        "HOTSYNC",
        "UNGUARDED",
        "UNGUARDED",
        "UNGUARDED",
        "UNGUARDED",
        "UNGUARDED",
    ]
    assert any("self._handles" in f.message for f in findings)
    assert any("self._streaks" in f.message for f in findings)
    assert _analyze("good_autoscale.py") == []


def test_corpus_collgather():
    findings = _analyze("bad_collgather.py")
    assert _codes(findings) == ["COLLGATHER", "COLLGATHER", "COLLGATHER"]
    assert any("all_gather" in f.message for f in findings)
    assert any("gather_blocks" in f.message for f in findings)
    # the good twin sanctions each gather with `# gather-ok: <why>`
    # (including one marker hung on the attribute line of a wrapped call)
    assert _analyze("good_collgather.py") == []


def test_collgather_requires_a_reason():
    # a bare `# gather-ok` without a why does NOT sanction the site
    findings = _src(
        """
        from jax import lax

        def f(x, axis):
            return lax.all_gather(x, axis)  # gather-ok
        """
    )
    assert _codes(findings) == ["COLLGATHER"]


# ---------------------------------------------------------------------------
# suppressions


def test_trailing_suppression_silences_one_code():
    findings = _src(
        """
        import jax

        step = jax.jit(lambda x: x)  # graft: disable=RAWJIT — probe justification
        """
    )
    assert findings == []


def test_standalone_suppression_on_line_above():
    findings = _src(
        """
        import jax

        # graft: disable=RAWJIT — decorator form cannot carry a trailing comment here
        @jax.jit
        def f(x):
            return x
        """
    )
    assert findings == []


def test_suppression_is_code_specific():
    findings = _src(
        """
        import jax

        step = jax.jit(lambda x: x)  # graft: disable=DONATE — wrong code
        """
    )
    assert _codes(findings) == ["RAWJIT"]


def test_suppression_above_a_code_line_does_not_leak_down():
    findings = _src(
        """
        import jax

        a = jax.jit(lambda x: x)  # graft: disable=RAWJIT — this line only
        b = jax.jit(lambda x: x)
        """
    )
    assert _codes(findings) == ["RAWJIT"]
    assert findings[0].line == 5


# ---------------------------------------------------------------------------
# baseline


def test_baseline_round_trip_and_overflow(tmp_path):
    src = textwrap.dedent(
        """
        import jax

        a = jax.jit(lambda x: x)
        b = jax.jit(lambda x: x)
        """
    )
    findings = analysis.analyze_source(src, "probe.py")
    assert len(findings) == 2
    path = str(tmp_path / "baseline.json")
    analysis.write_baseline(findings, path)
    baseline = analysis.load_baseline(path)
    new, old = analysis.apply_baseline(findings, baseline)
    assert new == [] and len(old) == 2
    # a THIRD identical finding exceeds the grandfathered count: reported
    src3 = src + "c = jax.jit(lambda x: x)\n"
    findings3 = analysis.analyze_source(src3, "probe.py")
    new3, old3 = analysis.apply_baseline(findings3, baseline)
    assert len(new3) == 1 and len(old3) == 2


def test_baseline_file_shape(tmp_path):
    f = analysis.Finding("p.py", 3, "jit-discipline", "RAWJIT", "msg")
    path = str(tmp_path / "b.json")
    analysis.write_baseline([f, f], path)
    data = json.load(open(path))
    assert data["findings"] == [
        {"path": "p.py", "code": "RAWJIT", "message": "msg", "count": 2}
    ]


# ---------------------------------------------------------------------------
# framework details


def test_finding_format_is_machine_readable():
    f = analysis.Finding("a/b.py", 7, "lock-discipline", "UNGUARDED", "boom")
    assert f.format() == "a/b.py:7: [lock-discipline/UNGUARDED] boom"


def test_syntax_error_is_a_parse_finding():
    findings = _src("def broken(:\n")
    assert _codes(findings) == ["PARSE"]


def test_registry_has_six_passes_in_order():
    passes = list(analysis.load_passes())
    assert passes == [
        "hot-loop",
        "jit-discipline",
        "donation-safety",
        "lock-discipline",
        "trace-safety",
        "collective-discipline",
    ]


def test_lock_pass_respects_with_and_single_thread():
    findings = _src(
        """
        import threading

        _L = threading.Lock()
        _N = 0  # guarded-by: _L

        def ok():
            global _N
            with _L:
                _N += 1

        def also_ok():  # single-thread: driver loop
            return _N

        def bad():
            return _N
        """
    )
    assert _codes(findings) == ["UNGUARDED"]
    assert findings[0].line == 16


def test_donation_pass_drain_marker_ends_liveness():
    findings = _src(
        """
        from gelly_streaming_tpu.core import compile_cache

        fold = compile_cache.cached_jit(("k",), lambda: None, donate_argnums=0)

        def f(state, buf):
            out = fold(state, buf)
            # arena-live-until: drain
            return state, out
        """
    )
    assert findings == []


def test_trace_pass_sees_shard_map_wrapped_defs():
    findings = _src(
        """
        import jax
        from gelly_streaming_tpu.parallel.mesh import shard_map

        def step(x):
            if x > 0:
                return x
            return -x

        fn = jax.jit(shard_map(step, mesh=None, in_specs=(), out_specs=()))  # graft: disable=RAWJIT — probe
        """
    )
    assert _codes(findings) == ["TRACEIF"]

"""graftcheck (tier-1): the static-analysis suite holds the shipped tree to
zero unsuppressed findings, and each pass provably catches its seeded
defect.

Three layers, mirroring the framework's contract:

* the PACKAGE GATE — running every pass over core/io/library/parallel/utils
  (plus the shipped baseline) must come back clean, so a new raw jit, an
  unguarded counter, or a use-after-donate fails tier-1 at the line that
  introduced it;
* the FIXTURE CORPUS — one good + one seeded-bad snippet per pass under
  tests/analysis_corpus/, asserting exact finding codes (a checker that
  finds nothing anywhere must fail here, not pass vacuously);
* the FRAMEWORK — suppression grammar, baseline round-trip (grandfathered
  counts, new-finding overflow), finding format, CLI driver exit codes.
"""

import json
import os
import subprocess
import sys
import textwrap
import time

import pytest

from gelly_streaming_tpu import analysis

CORPUS = os.path.join(os.path.dirname(__file__), "analysis_corpus")
REPO_ROOT = os.path.dirname(analysis.package_root())


def _analyze(path):
    return analysis.analyze_file(os.path.join(CORPUS, path))


def _codes(findings):
    return sorted(f.code for f in findings)


def _src(snippet, filename="probe.py"):
    return analysis.analyze_source(textwrap.dedent(snippet), filename)


# ---------------------------------------------------------------------------
# package gate


def _package_paths():
    root = analysis.package_root()
    return [
        os.path.join(root, d)
        for d in (
            "core",
            "io",
            "library",
            "native_src",
            "ops",
            "parallel",
            "runtime",
            "utils",
        )
    ]


@pytest.mark.timeout_cap(120)
def test_package_tree_is_clean():
    findings = analysis.analyze_paths(_package_paths(), root=REPO_ROOT)
    baseline = analysis.load_baseline(analysis.default_baseline_path())
    new, _old = analysis.apply_baseline(findings, baseline)
    assert new == [], "\n".join(f.format() for f in new)


def test_baseline_is_empty():
    """The grandfathered debt is paid down: the last module-scope @jax.jit
    decorators are routed through cached_jit, so the shipped baseline
    holds ZERO findings.  Pin that — any future entry means someone
    re-baselined a regression instead of fixing it (regenerate with
    ``python -m gelly_streaming_tpu.analysis --write-baseline``)."""
    baseline = analysis.load_baseline(analysis.default_baseline_path())
    assert sum(baseline.values()) == 0, dict(baseline)


@pytest.mark.timeout_cap(120)
def test_cli_package_scan_exits_zero():
    proc = subprocess.run(
        [
            sys.executable,
            "-m",
            "gelly_streaming_tpu.analysis",
            "--paths",
            "core",
            "io",
            "library",
            "native_src",
            "ops",
            "parallel",
            "runtime",
            "utils",
        ],
        capture_output=True,
        text=True,
        cwd=REPO_ROOT,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "0 finding(s)" in proc.stderr


@pytest.mark.timeout_cap(120)
def test_cli_list_passes_names_all_sixteen():
    proc = subprocess.run(
        [
            sys.executable,
            "-m",
            "gelly_streaming_tpu.analysis",
            "--list-passes",
        ],
        capture_output=True,
        text=True,
        cwd=REPO_ROOT,
    )
    assert proc.returncode == 0
    for name in (
        "hot-loop",
        "jit-discipline",
        "donation-safety",
        "lock-discipline",
        "trace-safety",
        "collective-discipline",
        "holds-lock",
        "lock-order",
        "check-then-act",
        "test-discipline",
        "native-leak",
        "native-bound",
        "native-ovfl",
        "native-abi",
        "shapeflow",
        "stale-disable",
    ):
        assert name in proc.stdout


# ---------------------------------------------------------------------------
# fixture corpus: each pass catches exactly its seeded defect


def test_corpus_rawjit():
    # decorator, call form, `import jax as _jax` alias, partial(jax.jit,...)
    assert _codes(_analyze("bad_rawjit.py")) == ["RAWJIT"] * 4
    assert _analyze("good_rawjit.py") == []


def test_corpus_donate():
    findings = _analyze("bad_donate.py")
    assert _codes(findings) == ["DONATE", "DONATE"]
    # one per seeded bug: the donated-carry read and the arena write
    assert "state" in findings[0].message and "src" in findings[1].message
    assert _analyze("good_donate.py") == []


def test_corpus_unguarded():
    findings = _analyze("bad_unguarded.py")
    assert _codes(findings) == ["UNGUARDED", "UNGUARDED"]
    assert "_COUNT" in findings[0].message
    assert "self.total" in findings[1].message
    assert _analyze("good_unguarded.py") == []


def test_corpus_sketch():
    # the ISSUE 19 sketch contract registry's lock discipline: byte totals
    # and the per-job contract table mutate only under the registry lock
    # (submit-thread registrations race metrics/bench-thread scrapes)
    findings = _analyze("bad_sketch.py")
    assert _codes(findings) == ["UNGUARDED", "UNGUARDED"]
    assert "_SKETCH" in findings[0].message
    assert "_SKETCH_JOBS" in findings[1].message
    assert _analyze("good_sketch.py") == []


def test_corpus_jobstate():
    """The runtime fixtures (ISSUE 5): job lifecycle state is
    '# guarded-by:' the manager lock; a transition outside it is exactly
    the lost-transition race the JobManager's discipline forbids."""
    findings = _analyze("bad_jobstate.py")
    assert _codes(findings) == ["UNGUARDED", "UNGUARDED"]
    assert all("self._state" in f.message for f in findings)
    assert all("_lock" in f.message for f in findings)
    assert _analyze("good_jobstate.py") == []


def test_nested_with_collects_inner_lock():
    """A `with self._lock:` nested directly inside another with-block must
    still collect its lock for the body (the serving plane's admission
    section hit this: check() used to recurse INTO the inner With without
    dispatching it, losing the lock and false-positive-flagging guarded
    registry writes)."""
    findings = _src(
        """
        import threading


        class S:
            def __init__(self):
                self._admission = threading.Lock()
                self._lock = threading.Lock()
                self._reg = {}  # guarded-by: _lock

            def f(self, key, value):
                with self._admission:
                    with self._lock:
                        self._reg[key] = value
        """
    )
    assert findings == []


def test_corpus_server():
    """The serving-plane fixtures (ISSUE 8): the connection registry every
    accept/teardown/shutdown path touches is '# guarded-by:' the server
    lock; an unlocked check-then-add races two accepts past the
    connection cap."""
    findings = _analyze("bad_server.py")
    assert _codes(findings) == ["UNGUARDED", "UNGUARDED"]
    assert all("self._conns" in f.message for f in findings)
    assert all("_lock" in f.message for f in findings)
    assert _analyze("good_server.py") == []


def test_corpus_traceif():
    assert _codes(_analyze("bad_traceif.py")) == [
        "TRACECAST",
        "TRACECAST",
        "TRACEIF",
    ]
    assert _analyze("good_traceif.py") == []


def test_corpus_hotsync():
    assert _codes(_analyze("bad_hotsync.py")) == ["HOTSYNC"]
    # the good twin hangs '# hot-loop-ok' on the CLOSING line of a
    # multi-line call — the satellite regression for hot_loop_lint's
    # original single-line marker scan
    assert _analyze("good_hotsync.py") == []


def test_corpus_wirebin():
    """The binned-ingest fixtures (ISSUE 6): the compressed decode+fold
    dispatch is a '# hot-loop' region, and the wire-counter registry the
    pack threads bump is '# guarded-by:' its lock."""
    findings = _analyze("bad_wirebin.py")
    assert _codes(findings) == ["HOTSYNC", "UNGUARDED"]
    assert any("_WIRE_BYTES" in f.message for f in findings)
    assert _analyze("good_wirebin.py") == []


def test_corpus_tracing():
    """The observability fixtures (ISSUE 9): the flight recorder's span
    ring is '# guarded-by:' its lock (drain threads of many jobs write
    while status/server threads read), and the traced dispatch loop stays
    a '# hot-loop' region — span marks are clock reads, never host syncs."""
    findings = _analyze("bad_tracing.py")
    assert _codes(findings) == [
        "HOTSYNC",
        "UNGUARDED",
        "UNGUARDED",
        "UNGUARDED",
    ]
    assert any("self._ring" in f.message for f in findings)
    assert any("self._next" in f.message for f in findings)
    assert _analyze("good_tracing.py") == []


def test_corpus_events():
    """The health-plane fixtures (ISSUE 10): the event journal's
    ring/cursor/file mirror are '# guarded-by:' its lock (scheduler,
    connection, and monitor threads emit while the events verb tails),
    and the SLO monitor's evaluation sweep is a '# hot-loop' region —
    gauge reads and burn math only, never a device sync."""
    findings = _analyze("bad_events.py")
    assert _codes(findings) == [
        "HOTSYNC",
        "UNGUARDED",
        "UNGUARDED",
        "UNGUARDED",
    ]
    assert any("self._ring" in f.message for f in findings)
    assert any("self._seq" in f.message for f in findings)
    assert any("self._file" in f.message for f in findings)
    assert _analyze("good_events.py") == []


def test_corpus_autoscale():
    """The elastic-control-plane fixtures (ISSUE 11): the autoscaler's
    handle/streak decision registry is '# guarded-by:' its lock
    (connection threads register while the policy thread sweeps), and the
    decision sweep is a '# hot-loop' region — alert/gauge reads and
    streak math only, never a device sync that would stall a pending
    rescale behind one fold."""
    findings = _analyze("bad_autoscale.py")
    assert _codes(findings) == [
        "HOTSYNC",
        "UNGUARDED",
        "UNGUARDED",
        "UNGUARDED",
        "UNGUARDED",
        "UNGUARDED",
    ]
    assert any("self._handles" in f.message for f in findings)
    assert any("self._streaks" in f.message for f in findings)
    assert _analyze("good_autoscale.py") == []


def test_corpus_collgather():
    findings = _analyze("bad_collgather.py")
    assert _codes(findings) == ["COLLGATHER", "COLLGATHER", "COLLGATHER"]
    assert any("all_gather" in f.message for f in findings)
    assert any("gather_blocks" in f.message for f in findings)
    # the good twin sanctions each gather with `# gather-ok: <why>`
    # (including one marker hung on the attribute line of a wrapped call)
    assert _analyze("good_collgather.py") == []


def test_corpus_holdslock():
    """The interprocedural contracts (ISSUE 12): a helper mutating under
    its CALLER's lock declares '# holds-lock:'; pass #6 checks every call
    site for the lock and the helper's guarded accesses against the
    declared held set."""
    findings = _analyze("bad_holdslock.py")
    assert _codes(findings) == ["HELDLOCK", "NOHOLD"]
    assert any("_evict" in f.message for f in findings)
    assert any("self._stats" in f.message for f in findings)
    assert _analyze("good_holdslock.py") == []


def test_corpus_decodepool():
    """The serving data plane's decode pool discipline (ISSUE 14): the
    arena free-list and completion queue stay under their declared locks
    and the worker's hot region stays free of device syncs — the good
    twin also carries the pool's lock-order declaration under the server
    hierarchy, and must scan clean with it."""
    findings = _analyze("bad_decodepool.py")
    assert _codes(findings) == [
        "HOTSYNC",
        "UNGUARDED",
        "UNGUARDED",
        "UNGUARDED",
    ]
    assert any("self._done" in f.message for f in findings)
    assert any("self._free" in f.message for f in findings)
    assert any("np.asarray" in f.message for f in findings)
    assert _analyze("good_decodepool.py") == []


def test_corpus_fuseddispatch():
    """The cross-tenant fused-dispatch fixtures (ISSUE 16): the cohort
    registry the scheduler bumps while status/metrics threads snapshot is
    '# guarded-by:' its lock (the high-water check-then-act flags both
    its unlocked read and store), and the cohort COLLECT pass is a
    '# hot-loop' region — rows stack and the mega-fold dispatches async,
    so one host sync there re-serializes the N tenants the fusion exists
    to batch."""
    findings = _analyze("bad_fuseddispatch.py")
    assert _codes(findings) == [
        "HOTSYNC",
        "UNGUARDED",
        "UNGUARDED",
        "UNGUARDED",
    ]
    assert any("self._parked" in f.message for f in findings)
    assert any("self._hwm" in f.message for f in findings)
    assert any("np.asarray" in f.message for f in findings)
    assert _analyze("good_fuseddispatch.py") == []


def test_corpus_native():
    """The C++ decode-plane fixtures (ISSUE 15): all four nativecheck rule
    families fire on their seeded defects — ctypes signature drift (arity,
    width, unlisted export), an untrusted read before any bounds
    comparison, narrow size arithmetic into malloc/memcpy, and a refusal
    path that leaks — while the contract-honoring twin (with a justified
    ``// graft: disable=`` suppression) scans clean."""
    findings = _analyze("bad_native.cpp")
    assert _codes(findings) == [
        "NATIVEABI",
        "NATIVEABI",
        "NATIVEABI",
        "NATIVEBOUND",
        "NATIVELEAK",
        "NATIVEOVFL",
        "NATIVEOVFL",
    ]
    msgs = "\n".join(f.format() for f in findings)
    assert "count_rows takes 2 parameter(s)" in msgs
    assert "cc_baseline parameter 4" in msgs
    assert "decode_probe has no declared ctypes signature" in msgs
    assert "before any bounds comparison against nbytes" in msgs
    assert "without free(tmp)" in msgs
    assert "(size_t)n" in msgs
    assert _analyze("good_native.cpp") == []


def test_corpus_spmv():
    """The direction-optimized SpMV fixtures (ISSUE 17): picking the
    push/pull lowering with a Python ``if`` on the traced frontier density
    is a TRACEIF (the density is a value, not a shape), and syncing every
    window's result inside the dispatch hot-loop is a HOTSYNC; the twin
    that branches via ``lax.cond`` and drains once after the region scans
    clean."""
    findings = _analyze("bad_spmv.py")
    assert _codes(findings) == ["HOTSYNC", "TRACEIF"]
    msgs = "\n".join(f.message for f in findings)
    assert "thr" in msgs or "fm" in msgs
    assert "np.asarray" in msgs
    assert _analyze("good_spmv.py") == []


def test_native_passes_only_see_cpp_and_vice_versa():
    """Language routing: the Python passes must not choke on (or scan) a
    .cpp file, and the native passes stay silent on .py sources — the same
    seeded text produces PARSE/RAWJIT only under its own language."""
    cpp_text = 'extern "C" int64_t mystery(const char* p) { return 0; }\n'
    findings = analysis.analyze_source(cpp_text, "probe.cpp")
    assert _codes(findings) == ["NATIVEABI"]  # and no PARSE from ast
    py_text = "import jax\n\nstep = jax.jit(lambda x: x)\n"
    findings = analysis.analyze_source(py_text, "probe.py")
    assert _codes(findings) == ["RAWJIT"]  # and no NATIVE* from the lexer


def test_cpp_suppression_grammar():
    """``// graft: disable=CODE`` works trailing and standalone-above, is
    code-specific, and does not leak to the next line — the Python
    grammar's contract, ported."""
    base = (
        "int64_t probe_fn(int64_t n) {{\n"
        "{}"
        "  char* p = static_cast<char*>(malloc(n * 2));{}\n"
        "  free(p);\n"
        "  return n;\n"
        "}}\n"
    )
    trailing = base.format(
        "", "  // graft: disable=NATIVEOVFL — probe justification"
    )
    assert analysis.analyze_source(trailing, "probe.cpp") == []
    above = base.format(
        "  // graft: disable=NATIVEOVFL — standalone form\n", ""
    )
    assert analysis.analyze_source(above, "probe.cpp") == []
    bare = base.format("", "")
    assert _codes(analysis.analyze_source(bare, "probe.cpp")) == ["NATIVEOVFL"]
    # a wrong-code disable both fails to suppress AND is itself stale
    wrong = base.format("", "  // graft: disable=NATIVELEAK — wrong code")
    assert _codes(analysis.analyze_source(wrong, "probe.cpp")) == [
        "NATIVEOVFL",
        "STALEDISABLE",
    ]


def test_native_leak_null_guard_is_name_exact():
    """Regression: a failure guard for pointer ``ab`` must not exempt a
    leak of pointer ``a`` (``!a`` is a substring of ``!ab``) — guard
    matching is identifier-boundary-exact."""
    leaky = """
int64_t two_allocs(int64_t n) {
  char* a = static_cast<char*>(malloc((size_t)n));
  if (!a) return -1;
  char* ab = static_cast<char*>(malloc((size_t)n));
  if (!ab) return -2;
  free(ab);
  free(a);
  return n;
}
"""
    findings = analysis.analyze_source(leaky, "probe.cpp")
    assert _codes(findings) == ["NATIVELEAK"]
    assert "free(a)" in findings[0].message
    fixed = leaky.replace(
        "if (!ab) return -2;",
        "if (!ab) {\n    free(a);\n    return -2;\n  }",
    )
    assert analysis.analyze_source(fixed, "probe.cpp") == []


def test_native_leak_compound_guard_does_not_exempt():
    """Regression: ``if (!p || other) return`` returns with p LIVE on the
    other-branch — only a condition that pins p null in every disjunct
    (e.g. ``!p`` alone, or ``!p && logging``) exempts the return."""
    compound = """
int64_t guard_probe(int64_t n, int32_t flag) {
  char* p = static_cast<char*>(malloc((size_t)n));
  if (!p || n > 100) return -1;
  free(p);
  return n;
}
"""
    findings = analysis.analyze_source(compound, "probe.cpp")
    assert _codes(findings) == ["NATIVELEAK"]
    conjunct = compound.replace("if (!p || n > 100)", "if (!p && flag)")
    assert analysis.analyze_source(conjunct, "probe.cpp") == []


def test_suppression_grammars_do_not_cross_languages():
    """Regression: a Python '#' comment that merely MENTIONS the C++
    grammar (`// graft: disable=...`) must not suppress a Python finding,
    and a C++ `//` comment mentioning the Python grammar must not
    suppress a C++ one."""
    py = (
        "import jax\n\n"
        "step = jax.jit(lambda x: x)  # C++ twin uses // graft: disable=RAWJIT\n"
    )
    assert _codes(_src(py)) == ["RAWJIT"]
    cpp = (
        "int64_t f(int64_t n) {\n"
        "  char* p = static_cast<char*>(malloc(n * 2));  // py uses # graft: disable=NATIVEOVFL\n"
        "  free(p);\n"
        "  return n;\n"
        "}\n"
    )
    assert _codes(analysis.analyze_source(cpp, "probe.cpp")) == ["NATIVEOVFL"]


def test_native_bound_deref_compare_is_still_a_read():
    """Regression: '*buf != 71' reads attacker memory just like buf[0];
    only the exact NULL-test shapes (!buf, buf == nullptr) are exempt."""
    deref = """
// untrusted: buf[nbytes]
int64_t probe(const uint8_t* buf, int64_t nbytes) {
  if (*buf != 71) return -1;
  return nbytes;
}
"""
    assert _codes(analysis.analyze_source(deref, "probe.cpp")) == [
        "NATIVEBOUND"
    ]
    nulltest = deref.replace("if (*buf != 71)", "if (buf == nullptr)")
    assert analysis.analyze_source(nulltest, "probe.cpp") == []


def test_native_ovfl_const_runtime_product_still_flags():
    """Regression: 'const' on a narrow runtime product is not a constant —
    only literal/known-constant initializers exempt a name, and a size_t
    PARAMETER is already full-width (no cast demanded)."""
    hidden = """
int64_t probe(int32_t a, int32_t b) {
  const int32_t total = a * b;
  char* p = static_cast<char*>(malloc(total * 2));
  free(p);
  return total;
}
"""
    assert _codes(analysis.analyze_source(hidden, "probe.cpp")) == [
        "NATIVEOVFL"
    ]
    sizet_param = """
int64_t grow(size_t n) {
  char* p = static_cast<char*>(malloc(n * 2));
  free(p);
  return (int64_t)n;
}
"""
    assert analysis.analyze_source(sizet_param, "probe.cpp") == []


def test_native_abi_table_is_a_parseable_literal():
    """NATIVEABI single-sources utils/native.py's NATIVE_SIGNATURES: the
    table must parse as a pure literal (the analyzer never imports the
    module) and carry every export of the canonical C++ source."""
    from gelly_streaming_tpu.analysis import nativecheck

    table = nativecheck.load_signature_table()
    assert len(table) >= 15
    canonical = os.path.join(
        analysis.package_root(), "native_src", "edge_parser.cpp"
    )
    with open(canonical) as f:
        funcs = nativecheck.parse_functions(nativecheck.lex(f.read()))
    exports = {fn.name for fn in funcs if fn.extern_c}
    assert exports  # the parser actually saw the extern "C" surface
    assert exports <= set(table), exports - set(table)


@pytest.mark.timeout_cap(120)
def test_cli_json_carries_native_codes():
    """--format json over the seeded C++ fixture: the machine schema rows
    carry the C++ codes with correct file and integer line numbers."""
    proc = subprocess.run(
        [
            sys.executable,
            "-m",
            "gelly_streaming_tpu.analysis",
            "--format",
            "json",
            "--paths",
            os.path.join(CORPUS, "bad_native.cpp"),
            "--no-baseline",
        ],
        capture_output=True,
        text=True,
        cwd=REPO_ROOT,
    )
    assert proc.returncode == 1
    data = json.loads(proc.stdout)
    codes = sorted(r["code"] for r in data["findings"])
    assert codes == [
        "NATIVEABI",
        "NATIVEABI",
        "NATIVEABI",
        "NATIVEBOUND",
        "NATIVELEAK",
        "NATIVEOVFL",
        "NATIVEOVFL",
    ]
    for row in data["findings"]:
        assert row["file"].endswith("bad_native.cpp")
        assert isinstance(row["line"], int) and row["line"] > 0
        assert row["pass"].startswith("native-")


@pytest.mark.timeout_cap(180)
def test_cli_parallel_jobs_handle_cpp():
    """--jobs 2 agrees with the serial scan on a path set that mixes .py
    and .cpp — the worker processes must route the C++ file through the
    native passes exactly like the in-process scan."""
    argv = [
        sys.executable,
        "-m",
        "gelly_streaming_tpu.analysis",
        "--paths",
        os.path.join(CORPUS, "bad_native.cpp"),
        os.path.join(CORPUS, "bad_rawjit.py"),
        "--no-baseline",
    ]
    serial = subprocess.run(argv, capture_output=True, text=True, cwd=REPO_ROOT)
    parallel = subprocess.run(
        argv + ["--jobs", "2"], capture_output=True, text=True, cwd=REPO_ROOT
    )
    assert serial.returncode == parallel.returncode == 1
    assert serial.stdout == parallel.stdout
    assert "NATIVEABI" in serial.stdout and "RAWJIT" in serial.stdout


def test_native_src_in_default_scan_paths():
    """native_src/ must sit inside the default --paths set, so the package
    gate (and the CLI default scan) covers the C++ byte path without
    anyone remembering to add it."""
    from gelly_streaming_tpu.analysis.__main__ import main as _cli_main  # noqa: F401
    import gelly_streaming_tpu.analysis.__main__ as cli

    src = open(cli.__file__).read()
    assert '"native_src"' in src
    canonical = os.path.join(
        analysis.package_root(), "native_src", "edge_parser.cpp"
    )
    assert os.path.exists(canonical)
    files = list(analysis.iter_source_files(
        [os.path.join(analysis.package_root(), "native_src")]
    ))
    assert canonical in files


def test_decode_pool_module_in_default_scan_paths():
    """runtime/decode_pool.py must sit inside the default --paths set, so
    the package gate (and the CLI default scan) covers the new module's
    lock discipline without anyone remembering to add it."""
    root = analysis.package_root()
    mod = os.path.join(root, "runtime", "decode_pool.py")
    assert os.path.exists(mod)
    findings = analysis.analyze_paths([mod], root=REPO_ROOT)
    baseline = analysis.load_baseline(analysis.default_baseline_path())
    new, _old = analysis.apply_baseline(findings, baseline)
    assert new == [], "\n".join(f.format() for f in new)


def test_corpus_lockorder():
    """The two-function deadlock (ISSUE 12): no single function acquires
    both locks, so only the call-graph propagation can see the A->B->A
    cycle; the report carries both acquisition chains as file:line."""
    findings = _analyze("bad_lockorder.py")
    assert _codes(findings) == ["LOCKORDER"]
    msg = findings[0].message
    assert "_ADMIT" in msg and "_STATE" in msg
    assert "bad_lockorder.py:" in msg  # the file:line acquisition chains
    assert _analyze("good_lockorder.py") == []


def test_corpus_router():
    """The fleet-tier fixtures (ISSUE 20): the placement pin table and the
    relay set are '# guarded-by:' state, and the failover path's
    registry->placement nesting against the placement path's
    placement->registry nesting is a two-function-pair inversion only the
    interprocedural propagation can see."""
    findings = _analyze("bad_router.py")
    assert _codes(findings) == ["LOCKORDER", "UNGUARDED", "UNGUARDED"]
    unguarded = [f for f in findings if f.code == "UNGUARDED"]
    assert any("_PINS" in f.message for f in unguarded)
    assert any("self._relays" in f.message for f in unguarded)
    (order,) = [f for f in findings if f.code == "LOCKORDER"]
    assert "_REGISTRY" in order.message and "_PLACEMENT" in order.message
    assert "bad_router.py:" in order.message  # the acquisition chains
    assert _analyze("good_router.py") == []


def test_corpus_toctou():
    """The split-lock check-then-act (ISSUE 12, the PR 7 tenant-cap steal
    shape): both accesses correctly locked, but in two acquisitions."""
    findings = _analyze("bad_toctou.py")
    assert _codes(findings) == ["TOCTOU", "TOCTOU"]
    assert all("self._jobs" in f.message for f in findings)
    assert all("different" in f.message for f in findings)
    assert _analyze("good_toctou.py") == []


def test_interprocedural_cases_invisible_to_pass_three():
    """The acceptance proof: each seeded interprocedural defect is INVISIBLE
    to the intraprocedural lock pass (#3) — the new layer is the only
    thing standing between these shapes and production."""
    p3 = [analysis.load_passes()["lock-discipline"]]
    for fixture in ("bad_holdslock.py", "bad_lockorder.py", "bad_toctou.py"):
        findings = analysis.analyze_file(os.path.join(CORPUS, fixture), p3)
        assert findings == [], (
            f"{fixture} should be invisible to pass #3:\n"
            + "\n".join(f.format() for f in findings)
        )


def test_holds_lock_across_modules_with_alias(tmp_path):
    """The runtime/job.py shape: a class whose lock IS another module's
    lock by reference ('# lock-alias:') — a call site holding the ALIASED
    lock satisfies the callee's holds-lock contract, and the re-entrant
    edge does not cycle."""
    (tmp_path / "mgr.py").write_text(
        textwrap.dedent(
            """
            import threading
            from wkr import Worker


            class Boss:
                def __init__(self):
                    self._lock = threading.RLock()

                def run(self, w: Worker):
                    with self._lock:
                        w._step()
            """
        )
    )
    (tmp_path / "wkr.py").write_text(
        textwrap.dedent(
            """
            import threading


            class Worker:
                def __init__(self, boss_lock: threading.RLock):
                    self._lock = boss_lock  # lock-alias: mgr._lock
                    self._n = 0  # guarded-by: _lock

                # holds-lock: _lock
                def _step(self):
                    self._n += 1
            """
        )
    )
    findings = analysis.analyze_paths([str(tmp_path)])
    assert findings == [], "\n".join(f.format() for f in findings)


def test_lockorder_cycle_across_two_modules(tmp_path):
    """A cross-MODULE inversion: modules a and b each take their own lock
    then call into the other — only the project-wide graph sees it."""
    (tmp_path / "moda.py").write_text(
        textwrap.dedent(
            """
            import threading
            import modb

            _A = threading.Lock()


            def into_b():
                with _A:
                    modb.locked_work()


            def locked_work():
                with _A:
                    pass
            """
        )
    )
    (tmp_path / "modb.py").write_text(
        textwrap.dedent(
            """
            import threading
            import moda

            _B = threading.Lock()


            def locked_work():
                with _B:
                    pass


            def into_a():
                with _B:
                    moda.locked_work()
            """
        )
    )
    findings = analysis.analyze_paths([str(tmp_path)])
    assert _codes(findings) == ["LOCKORDER"]
    assert "moda._A" in findings[0].message
    assert "modb._B" in findings[0].message


def test_declared_order_inversion_needs_no_reverse_path():
    """'# lock-order: A < B' is a virtual edge: ONE real B-held-then-A
    acquisition closes the cycle, so an inversion is caught before anyone
    writes the forward path."""
    findings = _src(
        """
        import threading

        # lock-order: _A < _B

        _A = threading.Lock()
        _B = threading.Lock()

        def backwards():
            with _B:
                with _A:
                    pass
        """
    )
    assert _codes(findings) == ["LOCKORDER"]
    assert "declared" in findings[0].message


def test_non_reentrant_self_reacquisition_is_a_cycle():
    """A plain Lock re-acquired while held deadlocks immediately; the
    known re-entrant RLock shape (the server's _admission) is exempt —
    good_lockorder.py pins the exemption."""
    findings = _src(
        """
        import threading

        _L = threading.Lock()

        def outer():
            with _L:
                inner()

        def inner():
            with _L:
                pass
        """
    )
    assert _codes(findings) == ["LOCKORDER"]
    assert "re-acquired" in findings[0].message


def test_nohold_respects_with_nesting_and_chained_contracts():
    findings = _src(
        """
        import threading


        class R:
            def __init__(self):
                self._lock = threading.Lock()
                self._d = {}  # guarded-by: _lock

            # holds-lock: _lock
            def _a(self):
                self._b()  # ok: entry contract covers the callee's

            # holds-lock: _lock
            def _b(self):
                self._d.clear()

            def go(self):
                with self._lock:
                    self._a()

            def bad(self):
                self._b()
        """
    )
    assert _codes(findings) == ["NOHOLD"]


def test_lockorder_sees_through_recursion_regardless_of_order():
    """Regression: acquisition sets are a worklist FIXPOINT, not a DFS
    memo — with mutually recursive f<->g, an unrelated entry point
    traversed first must not freeze g's set without f's lock (the DFS
    memo missed the _L->_A inversion whenever h1 came before h2)."""
    findings = _src(
        """
        import threading

        # lock-order: _A < _L

        _A = threading.Lock()
        _L = threading.Lock()
        _U = threading.Lock()

        def h1():
            with _U:
                g()

        def f():
            with _A:
                pass
            g()

        def g():
            f()

        def h2():
            with _L:
                g()
        """
    )
    assert _codes(findings) == ["LOCKORDER"]


def test_toctou_sees_mutator_calls_with_result_used():
    """Regression: `val = self._d.pop(k)` / `if self._d.pop(k):` are the
    same act as the bare statement — write detection must not require the
    mutator call to be an expression statement."""
    findings = _src(
        """
        import threading

        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._d = {}  # guarded-by: _lock

            def take(self, k):
                with self._lock:
                    present = k in self._d
                if present:
                    with self._lock:
                        val = self._d.pop(k)
                    return val
        """
    )
    assert _codes(findings) == ["TOCTOU"]


def test_toctou_recheck_under_write_lock_sanctions():
    # the double-checked shape: good_toctou.py pins the full fixture; this
    # probes the minimal form inline
    findings = _src(
        """
        import threading


        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._d = {}  # guarded-by: _lock

            def put(self, k, v):
                with self._lock:
                    seen = k in self._d
                if not seen:
                    with self._lock:
                        if k not in self._d:
                            self._d[k] = v
        """
    )
    assert findings == []


def test_collgather_requires_a_reason():
    # a bare `# gather-ok` without a why does NOT sanction the site
    findings = _src(
        """
        from jax import lax

        def f(x, axis):
            return lax.all_gather(x, axis)  # gather-ok
        """
    )
    assert _codes(findings) == ["COLLGATHER"]


# ---------------------------------------------------------------------------
# suppressions


def test_trailing_suppression_silences_one_code():
    findings = _src(
        """
        import jax

        step = jax.jit(lambda x: x)  # graft: disable=RAWJIT — probe justification
        """
    )
    assert findings == []


def test_standalone_suppression_on_line_above():
    findings = _src(
        """
        import jax

        # graft: disable=RAWJIT — decorator form cannot carry a trailing comment here
        @jax.jit
        def f(x):
            return x
        """
    )
    assert findings == []


def test_suppression_is_code_specific():
    # the wrong-code disable fails to silence the RAWJIT — and, since it
    # suppresses nothing, the stale-disable post-check flags it too
    findings = _src(
        """
        import jax

        step = jax.jit(lambda x: x)  # graft: disable=DONATE — wrong code
        """
    )
    assert _codes(findings) == ["RAWJIT", "STALEDISABLE"]


def test_suppression_above_a_code_line_does_not_leak_down():
    findings = _src(
        """
        import jax

        a = jax.jit(lambda x: x)  # graft: disable=RAWJIT — this line only
        b = jax.jit(lambda x: x)
        """
    )
    assert _codes(findings) == ["RAWJIT"]
    assert findings[0].line == 5


# ---------------------------------------------------------------------------
# baseline


def test_baseline_round_trip_and_overflow(tmp_path):
    src = textwrap.dedent(
        """
        import jax

        a = jax.jit(lambda x: x)
        b = jax.jit(lambda x: x)
        """
    )
    findings = analysis.analyze_source(src, "probe.py")
    assert len(findings) == 2
    path = str(tmp_path / "baseline.json")
    analysis.write_baseline(findings, path)
    baseline = analysis.load_baseline(path)
    new, old = analysis.apply_baseline(findings, baseline)
    assert new == [] and len(old) == 2
    # a THIRD identical finding exceeds the grandfathered count: reported
    src3 = src + "c = jax.jit(lambda x: x)\n"
    findings3 = analysis.analyze_source(src3, "probe.py")
    new3, old3 = analysis.apply_baseline(findings3, baseline)
    assert len(new3) == 1 and len(old3) == 2


def test_baseline_file_shape(tmp_path):
    f = analysis.Finding("p.py", 3, "jit-discipline", "RAWJIT", "msg")
    path = str(tmp_path / "b.json")
    analysis.write_baseline([f, f], path)
    data = json.load(open(path))
    assert data["findings"] == [
        {"path": "p.py", "code": "RAWJIT", "message": "msg", "count": 2}
    ]


# ---------------------------------------------------------------------------
# machine-readable output + parallel scanning (ISSUE 12 satellites)


@pytest.mark.timeout_cap(120)
def test_cli_json_format_schema():
    """--format json: the stable schema an external gate consumes —
    file/line/pass/code/message/suppressed per finding — with suppressed
    and grandfathered findings carried (suppressed=true) but not failing
    the run."""
    proc = subprocess.run(
        [
            sys.executable,
            "-m",
            "gelly_streaming_tpu.analysis",
            "--format",
            "json",
            "--paths",
            os.path.join(CORPUS, "bad_toctou.py"),
            "--no-baseline",
        ],
        capture_output=True,
        text=True,
        cwd=REPO_ROOT,
    )
    assert proc.returncode == 1
    data = json.loads(proc.stdout)
    assert set(data) == {"findings", "summary"}
    assert data["summary"]["new"] == 2
    for row in data["findings"]:
        assert set(row) == {
            "file", "line", "pass", "code", "message", "suppressed",
        }
        assert row["code"] == "TOCTOU" and row["suppressed"] is False
        assert row["file"].endswith("bad_toctou.py")
        assert isinstance(row["line"], int)


@pytest.mark.timeout_cap(120)
def test_cli_json_marks_suppressed_and_exits_zero(tmp_path):
    probe = tmp_path / "probe.py"
    probe.write_text(
        "import jax\n\n"
        "step = jax.jit(lambda x: x)  # graft: disable=RAWJIT — probe\n"
    )
    proc = subprocess.run(
        [
            sys.executable,
            "-m",
            "gelly_streaming_tpu.analysis",
            "--format",
            "json",
            "--paths",
            str(probe),
            "--no-baseline",
        ],
        capture_output=True,
        text=True,
        cwd=REPO_ROOT,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    data = json.loads(proc.stdout)
    assert data["summary"]["new"] == 0
    assert data["summary"]["suppressed"] == 1
    assert [r["suppressed"] for r in data["findings"]] == [True]


@pytest.mark.timeout_cap(120)
def test_cli_sarif_format_schema():
    """--format sarif: a SARIF 2.1.0 document CI viewers ingest directly —
    one run, graftcheck as the driver with one rule per finding code, one
    result per finding with a physical location."""
    proc = subprocess.run(
        [
            sys.executable,
            "-m",
            "gelly_streaming_tpu.analysis",
            "--format",
            "sarif",
            "--paths",
            os.path.join(CORPUS, "bad_toctou.py"),
            "--no-baseline",
        ],
        capture_output=True,
        text=True,
        cwd=REPO_ROOT,
    )
    assert proc.returncode == 1
    data = json.loads(proc.stdout)
    assert data["version"] == "2.1.0"
    assert "sarif-schema-2.1.0" in data["$schema"]
    assert len(data["runs"]) == 1
    driver = data["runs"][0]["tool"]["driver"]
    assert driver["name"] == "graftcheck"
    rule_ids = {r["id"] for r in driver["rules"]}
    # every registered code ships a rule, including the new prover's
    assert {
        "RAWJIT",
        "TOCTOU",
        "UNBUCKETED",
        "KEYLEAK",
        "DTYPEDRIFT",
        "STALEDISABLE",
    } <= rule_ids
    results = data["runs"][0]["results"]
    assert len(results) == 2
    for r in results:
        assert r["ruleId"] == "TOCTOU"
        assert r["ruleId"] in rule_ids
        loc = r["locations"][0]["physicalLocation"]
        assert loc["artifactLocation"]["uri"].endswith("bad_toctou.py")
        assert isinstance(loc["region"]["startLine"], int)
        assert "suppressions" not in r  # live findings are unmuted


@pytest.mark.timeout_cap(120)
def test_cli_sarif_suppression_kinds(tmp_path):
    """Comment-suppressed findings surface as inSource suppressions,
    baseline-grandfathered ones as external — both exit 0."""
    probe = tmp_path / "probe.py"
    probe.write_text(
        "import jax\n\n"
        "a = jax.jit(lambda x: x)  # graft: disable=RAWJIT — probe\n"
        "b = jax.jit(lambda x: x)\n"
    )
    baseline = tmp_path / "baseline.json"
    argv = [
        sys.executable,
        "-m",
        "gelly_streaming_tpu.analysis",
        "--paths",
        str(probe),
        "--baseline",
        str(baseline),
    ]
    wrote = subprocess.run(
        argv + ["--write-baseline"], capture_output=True, text=True,
        cwd=REPO_ROOT,
    )
    assert wrote.returncode == 0, wrote.stdout + wrote.stderr
    proc = subprocess.run(
        argv + ["--format", "sarif"], capture_output=True, text=True,
        cwd=REPO_ROOT,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    results = json.loads(proc.stdout)["runs"][0]["results"]
    kinds = sorted(r["suppressions"][0]["kind"] for r in results)
    assert kinds == ["external", "inSource"]


@pytest.mark.timeout_cap(120)
def test_full_suite_wall_time_stays_fast():
    """The whole-tree scan — all sixteen passes, the interprocedural
    prover included, --jobs 2 on the 2-core gate host — must stay cheap
    enough to run UNMARKED in tier-1 (no @pytest.mark.slow escape hatch):
    pin the wall-time so a quadratic fixpoint regression in shapeflow or
    the lock-order graph fails here, not in CI latency graphs.  A fresh
    interpreter, not in-process: the worker pool forks, and this pytest
    process may already have JAX's threads running."""
    start = time.monotonic()
    proc = subprocess.run(
        [
            sys.executable,
            "-m",
            "gelly_streaming_tpu.analysis",
            "--jobs",
            "2",
        ],
        capture_output=True,
        text=True,
        cwd=REPO_ROOT,
    )
    elapsed = time.monotonic() - start
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert elapsed < 60.0, f"full graftcheck scan took {elapsed:.1f}s"


@pytest.mark.timeout_cap(180)
def test_cli_parallel_jobs_match_serial():
    """--jobs 2 (the 2-core host's gate speedup) must agree with the
    serial scan bit-for-bit on the corpus findings."""
    argv = [
        sys.executable,
        "-m",
        "gelly_streaming_tpu.analysis",
        "--paths",
        CORPUS,
        "--no-baseline",
    ]
    serial = subprocess.run(
        argv, capture_output=True, text=True, cwd=REPO_ROOT
    )
    parallel = subprocess.run(
        argv + ["--jobs", "2"], capture_output=True, text=True, cwd=REPO_ROOT
    )
    assert serial.returncode == parallel.returncode == 1
    assert serial.stdout == parallel.stdout
    assert len(serial.stdout.splitlines()) > 10  # the bad fixtures fired


# ---------------------------------------------------------------------------
# test-discipline (pass #9): the tests/ tree itself is gated


def test_notimeout_pass_semantics():
    with_threads = """
        import threading

        def test_spawns():
            t = threading.Thread(target=lambda: None)
            t.start()
            t.join()
        """
    assert _codes(_src(with_threads)) == ["NOTIMEOUT"]
    capped = """
        import threading
        import pytest

        @pytest.mark.timeout_cap(30)
        def test_spawns():
            t = threading.Thread(target=lambda: None)
            t.start()
            t.join()
        """
    assert _src(capped) == []
    marked_module = """
        import threading
        import pytest

        pytestmark = pytest.mark.timeout_cap(300)

        def test_spawns():
            threading.Event().wait(0)
        """
    assert _src(marked_module) == []
    pure = """
        def test_pure_math():
            assert 1 + 1 == 2
        """
    assert _src(pure) == []


@pytest.mark.timeout_cap(120)
def test_tests_tree_has_no_uncapped_concurrency_tests():
    """The gate the satellite demands: every test_* under tests/ that
    drives threads/sockets/subprocesses carries timeout_cap."""
    pass_obj = [analysis.load_passes()["test-discipline"]]
    findings = analysis.analyze_paths(
        [os.path.dirname(__file__)], pass_obj, root=REPO_ROOT
    )
    assert findings == [], "\n".join(f.format() for f in findings)


# ---------------------------------------------------------------------------
# framework details


def test_finding_format_is_machine_readable():
    f = analysis.Finding("a/b.py", 7, "lock-discipline", "UNGUARDED", "boom")
    assert f.format() == "a/b.py:7: [lock-discipline/UNGUARDED] boom"


def test_syntax_error_is_a_parse_finding():
    findings = _src("def broken(:\n")
    assert _codes(findings) == ["PARSE"]


def test_registry_has_sixteen_passes_in_order():
    passes = list(analysis.load_passes())
    assert passes == [
        "hot-loop",
        "jit-discipline",
        "donation-safety",
        "lock-discipline",
        "trace-safety",
        "collective-discipline",
        "holds-lock",
        "lock-order",
        "check-then-act",
        "test-discipline",
        "native-leak",
        "native-bound",
        "native-ovfl",
        "native-abi",
        "shapeflow",
        "stale-disable",
    ]


def test_lock_pass_respects_with_and_single_thread():
    findings = _src(
        """
        import threading

        _L = threading.Lock()
        _N = 0  # guarded-by: _L

        def ok():
            global _N
            with _L:
                _N += 1

        def also_ok():  # single-thread: driver loop
            return _N

        def bad():
            return _N
        """
    )
    assert _codes(findings) == ["UNGUARDED"]
    assert findings[0].line == 16


def test_donation_pass_drain_marker_ends_liveness():
    findings = _src(
        """
        from gelly_streaming_tpu.core import compile_cache

        fold = compile_cache.cached_jit(("k",), lambda: None, donate_argnums=0)

        def f(state, buf):
            out = fold(state, buf)
            # arena-live-until: drain
            return state, out
        """
    )
    assert findings == []


def test_trace_pass_sees_shard_map_wrapped_defs():
    findings = _src(
        """
        import jax
        from gelly_streaming_tpu.parallel.mesh import shard_map

        def step(x):
            if x > 0:
                return x
            return -x

        fn = jax.jit(shard_map(step, mesh=None, in_specs=(), out_specs=()))  # graft: disable=RAWJIT — probe
        """
    )
    assert _codes(findings) == ["TRACEIF"]

"""Continuous property-stream tests: degrees, vertex/edge counts, getVertices.

Goldens from test/operations/TestGetDegrees.java, TestGetVertices.java,
TestNumberOfEntities.java — these are *running-update traces* (one record per
per-key update), which the batched kernels reproduce exactly via in-batch
occurrence ranking.
"""

import pytest

from fixtures import assert_lines, long_long_stream

DEGREES_GOLDEN = (
    "1,1\n1,2\n1,3\n2,1\n2,2\n3,1\n3,2\n3,3\n3,4\n4,1\n4,2\n5,1\n5,2\n5,3"
)
IN_DEGREES_GOLDEN = "1,1\n2,1\n3,1\n3,2\n4,1\n5,1\n5,2"
OUT_DEGREES_GOLDEN = "1,1\n1,2\n2,1\n3,1\n3,2\n4,1\n5,1"


@pytest.mark.parametrize("bs", [1, 3, 7])
def test_get_degrees(bs):
    # TestGetDegrees.testGetDegrees (:33-60)
    assert_lines(long_long_stream(batch_size=bs).get_degrees().lines(), DEGREES_GOLDEN)


@pytest.mark.parametrize("bs", [1, 7])
def test_get_in_degrees(bs):
    # TestGetDegrees.testGetInDegrees (:62-84)
    assert_lines(
        long_long_stream(batch_size=bs).get_in_degrees().lines(), IN_DEGREES_GOLDEN
    )


@pytest.mark.parametrize("bs", [1, 7])
def test_get_out_degrees(bs):
    # TestGetDegrees.testGetOutDegrees (:86-109)
    assert_lines(
        long_long_stream(batch_size=bs).get_out_degrees().lines(), OUT_DEGREES_GOLDEN
    )


def test_get_vertices():
    # TestGetVertices.java:38-42
    assert_lines(
        long_long_stream().get_vertices().lines(),
        "1,(null)\n2,(null)\n3,(null)\n4,(null)\n5,(null)",
    )


def test_number_of_vertices():
    # TestNumberOfEntities.testNumberOfVertices (:40-44)
    assert_lines(
        long_long_stream().number_of_vertices().lines(), "1\n2\n3\n4\n5"
    )


def test_number_of_edges():
    # TestNumberOfEntities.testNumberOfEdges (:65-71)
    assert_lines(
        long_long_stream().number_of_edges().lines(), "1\n2\n3\n4\n5\n6\n7"
    )


def test_degree_trace_order_within_key():
    # The per-key degree trace must be monotonically increasing in arrival
    # order (running updates, not final values).
    recs = long_long_stream(batch_size=2).get_degrees().collect()
    per_key = {}
    for v, d in recs:
        per_key.setdefault(v, []).append(d)
    for v, seq in per_key.items():
        assert seq == list(range(1, len(seq) + 1))

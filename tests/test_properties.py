"""Continuous property-stream tests: degrees, vertex/edge counts, getVertices.

Goldens from test/operations/TestGetDegrees.java, TestGetVertices.java,
TestNumberOfEntities.java — these are *running-update traces* (one record per
per-key update), which the batched kernels reproduce exactly via in-batch
occurrence ranking.
"""

import pytest

from fixtures import assert_lines, long_long_stream

DEGREES_GOLDEN = (
    "1,1\n1,2\n1,3\n2,1\n2,2\n3,1\n3,2\n3,3\n3,4\n4,1\n4,2\n5,1\n5,2\n5,3"
)
IN_DEGREES_GOLDEN = "1,1\n2,1\n3,1\n3,2\n4,1\n5,1\n5,2"
OUT_DEGREES_GOLDEN = "1,1\n1,2\n2,1\n3,1\n3,2\n4,1\n5,1"


@pytest.mark.parametrize("bs", [1, 3, 7])
def test_get_degrees(bs):
    # TestGetDegrees.testGetDegrees (:33-60)
    assert_lines(long_long_stream(batch_size=bs).get_degrees().lines(), DEGREES_GOLDEN)


@pytest.mark.parametrize("bs", [1, 7])
def test_get_in_degrees(bs):
    # TestGetDegrees.testGetInDegrees (:62-84)
    assert_lines(
        long_long_stream(batch_size=bs).get_in_degrees().lines(), IN_DEGREES_GOLDEN
    )


@pytest.mark.parametrize("bs", [1, 7])
def test_get_out_degrees(bs):
    # TestGetDegrees.testGetOutDegrees (:86-109)
    assert_lines(
        long_long_stream(batch_size=bs).get_out_degrees().lines(), OUT_DEGREES_GOLDEN
    )


def test_get_vertices():
    # TestGetVertices.java:38-42
    assert_lines(
        long_long_stream().get_vertices().lines(),
        "1,(null)\n2,(null)\n3,(null)\n4,(null)\n5,(null)",
    )


def test_number_of_vertices():
    # TestNumberOfEntities.testNumberOfVertices (:40-44)
    assert_lines(
        long_long_stream().number_of_vertices().lines(), "1\n2\n3\n4\n5"
    )


def test_number_of_edges():
    # TestNumberOfEntities.testNumberOfEdges (:65-71)
    assert_lines(
        long_long_stream().number_of_edges().lines(), "1\n2\n3\n4\n5\n6\n7"
    )


def test_degree_trace_order_within_key():
    # The per-key degree trace must be monotonically increasing in arrival
    # order (running updates, not final values).
    recs = long_long_stream(batch_size=2).get_degrees().collect()
    per_key = {}
    for v, d in recs:
        per_key.setdefault(v, []).append(d)
    for v, seq in per_key.items():
        assert seq == list(range(1, len(seq) + 1))


def test_degree_blocks_match_records():
    """Block mode (production sink) and per-record trace mode must agree."""
    import numpy as np

    from gelly_streaming_tpu.core.config import StreamConfig
    from gelly_streaming_tpu.core.stream import EdgeStream

    rng = np.random.default_rng(3)
    src = rng.integers(0, 32, 500).astype(np.int32)
    dst = rng.integers(0, 32, 500).astype(np.int32)
    cfg = StreamConfig(vertex_capacity=32, batch_size=64)
    out = EdgeStream.from_arrays(src, dst, cfg).get_degrees()
    from_blocks = []
    for blk in out.blocks():
        v, d = blk.columns
        from_blocks.extend(zip(v.tolist(), d.tolist()))
    assert from_blocks == out.collect()
    # wire-backed and collection-backed sources produce the same trace
    coll = EdgeStream.from_collection(
        list(zip(src.tolist(), dst.tolist())), cfg, 64
    ).get_degrees()
    assert out.collect() == coll.collect()


def test_record_stream_block_adapter():
    from gelly_streaming_tpu.core.output import OutputStream

    out = OutputStream(lambda: iter([(1, 2), (3, 4)]))
    blks = list(out.blocks())
    assert [tuple(b.columns[0]) for b in blks] == [(1, 3)]
    assert list(blks[0].tuples()) == [(1, 2), (3, 4)]


def test_degree_stream_wide_vertex_space_uses_raw_columns():
    """Capacities beyond 2^20 can't use the 48-bit packed emission; the raw
    column fallback must stay trace-exact."""
    from gelly_streaming_tpu.core.config import StreamConfig
    from gelly_streaming_tpu.core.stream import EdgeStream

    big = (1 << 20) + 4  # > 2^20 forces the raw path
    cfg = StreamConfig(vertex_capacity=big, batch_size=4)
    hub = big - 1
    recs = (
        EdgeStream.from_collection([(hub, 1), (hub, 2)], cfg)
        .get_out_degrees()
        .collect()
    )
    assert recs == [(hub, 1), (hub, 2)]


def test_write_csv_vectorized_matches_lines(tmp_path):
    """The fast integer-block CSV path must render byte-identically to the
    per-record golden renderer."""
    from gelly_streaming_tpu.core.config import StreamConfig
    from gelly_streaming_tpu.core.stream import EdgeStream

    cfg = StreamConfig(vertex_capacity=64, batch_size=4)
    edges = [(1, 2), (1, 3), (2, 3), (3, 4), (3, 5)]

    def stream():
        return EdgeStream.from_collection(edges, cfg)

    out = stream().get_degrees()
    p = tmp_path / "deg.csv"
    out.write_csv(str(p))
    assert p.read_text().splitlines() == stream().get_degrees().lines()
